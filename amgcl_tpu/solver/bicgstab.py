"""Preconditioned BiCGStab for non-symmetric systems with selectable
preconditioning side (reference: amgcl/solver/bicgstab.hpp, default
side::right; the convergence criterion uses the UNPRECONDITIONED rhs norm
for both sides, bicgstab.hpp:168-186, and with side=left the tracked
residual is the preconditioned one). Whole iteration is one
``lax.while_loop``."""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
from jax import lax

from amgcl_tpu.ops import device as dev
from amgcl_tpu.ops import fused_vec as fv
from amgcl_tpu.telemetry.history import HistoryMixin


@dataclass
class BiCGStab(HistoryMixin):
    maxiter: int = 100
    tol: float = 1e-8
    abstol: float = 0.0
    precond_side: str = "right"
    record_history: bool = False  # per-iteration relative residuals
    guard: bool = True      # in-loop health guards (telemetry/health.py)

    def solve(self, A, precond, rhs, x0=None, inner_product=dev.inner_product):
        if self.precond_side not in ("left", "right"):
            raise ValueError("precond_side must be 'left' or 'right', got %r"
                             % self.precond_side)
        if rhs.ndim == 2:
            # stacked multi-RHS entry (serve/batched.py)
            from amgcl_tpu.serve.batched import vmap_solve
            return vmap_solve(self, A, precond, rhs, x0, inner_product)
        left = self.precond_side == "left"
        dot = inner_product
        x = jnp.zeros_like(rhs) if x0 is None else x0

        # criterion on the unpreconditioned rhs norm for BOTH sides
        norm_rhs = jnp.sqrt(jnp.abs(dot(rhs, rhs)))
        scale = jnp.where(norm_rhs > 0, norm_rhs, 1.0)
        eps = jnp.maximum(self.tol * scale,
                          jnp.asarray(self.abstol, rhs.dtype).real)

        if left:
            r = precond(dev.residual(rhs, A, x))
            rr0 = dot(r, r)
        else:
            # fused residual + <r,r> in one operator pass
            r, rr0 = fv.residual_dot(rhs, A, x, ip=dot)
        rhat = r

        def apply_op(p):
            """(v, z): v enters the recurrence, z accumulates into x."""
            if left:
                return precond(dev.spmv(A, p)), p
            z = precond(p)
            return dev.spmv(A, z), z

        one = jnp.ones((), rhs.dtype)

        from amgcl_tpu.telemetry import health as H

        def cond(st):
            (x, r, p, v, rho, rho_c, alpha, omega, it, res, hist,
             hs) = st
            return (it < self.maxiter) & (res > eps) & self._guard_go(hs)

        def body(st):
            # ``rho_c`` = <rhat, r> of the CURRENT r, computed by the
            # previous iteration's fused tail (same value the historical
            # ``dot(rhat, r)`` opened the body with — one reduction pass
            # per iteration cheaper)
            (x, r, p, v, rho, rho_c, alpha, omega, it, res, hist,
             hs) = st
            rho_new = rho_c
            beta = (rho_new / jnp.where(rho == 0, 1, rho)) \
                * (alpha / jnp.where(omega == 0, 1, omega))
            p_n = r + beta * (p - omega * v)
            if left:
                v_n, phat = apply_op(p_n)
                denom = dot(rhat, v_n)
            else:
                # fused spmv + <rhat, v> on the DIA path (one HBM pass);
                # spmv_dots returns <v, rhat> — conjugate for the
                # complex fallback (identity for real)
                phat = precond(p_n)
                v_n, _, _, vr = dev.spmv_dots(A, phat, rhat, dot)
                denom = jnp.conj(vr)
            alpha_n = rho_new / jnp.where(denom == 0, 1, denom)
            s = r - alpha_n * v_n
            if left:
                t, shat = apply_op(s)
                # one read of t for both reductions (ops/fused_vec.py)
                tt, ts = fv.multi_dot(t, (t, s), ip=dot)
            else:
                shat = precond(s)
                t, tt, _, ts = dev.spmv_dots(A, shat, s, dot)
            omega_n = ts / jnp.where(tt == 0, 1, tt)
            # fused tail (ops/fused_vec.py): the x/r double-axpby from
            # ONE read of {phat, shat, s, t, x}, with <r,r> AND the next
            # iteration's rho = <rhat, r> reduced in the same pass
            x_n, r_n, rr, rho_next = fv.bicgstab_tail(
                alpha_n, phat, omega_n, shat, s, t, x, rhat, ip=dot)
            res_n = jnp.sqrt(jnp.abs(rr))
            # the three breakdown modes of the reference (bicgstab.hpp
            # throws on each): rho-, alpha(denom)- and omega-breakdown
            ok, hs = self._guard_step(
                hs, it, res_n / scale,
                ((H.BREAKDOWN_RHO, H.bad_denom(rho_new)),
                 (H.BREAKDOWN_ALPHA, H.bad_denom(denom)),
                 (H.BREAKDOWN_OMEGA, H.bad_denom(omega_n))))
            x, r, p, v, rho, rho_c, alpha, omega, res = \
                self._guard_commit(
                    ok, (x_n, r_n, p_n, v_n, rho_new, rho_next, alpha_n,
                         omega_n, res_n),
                    (x, r, p, v, rho, rho_c, alpha, omega, res))
            hist = self._hist_put(hist, it, res_n / scale, keep=ok)
            return (x, r, p, v, rho, rho_c, alpha, omega,
                    it + ok.astype(jnp.int32), res, hist, hs)

        res0 = jnp.sqrt(jnp.abs(rr0))
        # rhat = r, so the first iteration's rho = <rhat, r> = <r, r>
        st = (x, r, jnp.zeros_like(r), jnp.zeros_like(r),
              one, jnp.asarray(rr0, rhs.dtype), one, one,
              jnp.zeros((), jnp.int32), res0,
              self._hist_init(rhs.real.dtype),
              self._guard_init(res0 / scale))
        (x, r, p, v, rho, rho_c, alpha, omega, it, res, hist, hs) = \
            lax.while_loop(cond, body, st)
        x = jnp.where(norm_rhs > 0, x, jnp.zeros_like(x))
        return self._hist_result(x, it, res / scale, hist, health=hs)
