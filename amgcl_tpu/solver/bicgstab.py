"""Preconditioned BiCGStab for non-symmetric systems with selectable
preconditioning side (reference: amgcl/solver/bicgstab.hpp, default
side::right; the convergence criterion uses the UNPRECONDITIONED rhs norm
for both sides, bicgstab.hpp:168-186, and with side=left the tracked
residual is the preconditioned one). Whole iteration is one
``lax.while_loop``."""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
from jax import lax

from amgcl_tpu.ops import device as dev
from amgcl_tpu.telemetry.history import HistoryMixin


@dataclass
class BiCGStab(HistoryMixin):
    maxiter: int = 100
    tol: float = 1e-8
    abstol: float = 0.0
    precond_side: str = "right"
    record_history: bool = False  # per-iteration relative residuals

    def solve(self, A, precond, rhs, x0=None, inner_product=dev.inner_product):
        if self.precond_side not in ("left", "right"):
            raise ValueError("precond_side must be 'left' or 'right', got %r"
                             % self.precond_side)
        left = self.precond_side == "left"
        dot = inner_product
        x = jnp.zeros_like(rhs) if x0 is None else x0

        # criterion on the unpreconditioned rhs norm for BOTH sides
        norm_rhs = jnp.sqrt(jnp.abs(dot(rhs, rhs)))
        scale = jnp.where(norm_rhs > 0, norm_rhs, 1.0)
        eps = jnp.maximum(self.tol * scale,
                          jnp.asarray(self.abstol, rhs.dtype).real)

        if left:
            r = precond(dev.residual(rhs, A, x))
        else:
            r = dev.residual(rhs, A, x)
        rhat = r

        def apply_op(p):
            """(v, z): v enters the recurrence, z accumulates into x."""
            if left:
                return precond(dev.spmv(A, p)), p
            z = precond(p)
            return dev.spmv(A, z), z

        one = jnp.ones((), rhs.dtype)

        def cond(st):
            (x, r, p, v, rho, alpha, omega, it, res, hist) = st
            return (it < self.maxiter) & (res > eps)

        def body(st):
            (x, r, p, v, rho, alpha, omega, it, res, hist) = st
            rho_new = dot(rhat, r)
            beta = (rho_new / jnp.where(rho == 0, 1, rho)) \
                * (alpha / jnp.where(omega == 0, 1, omega))
            p = r + beta * (p - omega * v)
            if left:
                v, phat = apply_op(p)
                denom = dot(rhat, v)
            else:
                # fused spmv + <rhat, v> on the DIA path (one HBM pass);
                # spmv_dots returns <v, rhat> — conjugate for the
                # complex fallback (identity for real)
                phat = precond(p)
                v, _, _, vr = dev.spmv_dots(A, phat, rhat, dot)
                denom = jnp.conj(vr)
            alpha = rho_new / jnp.where(denom == 0, 1, denom)
            s = r - alpha * v
            if left:
                t, shat = apply_op(s)
                tt = dot(t, t)
                ts = dot(t, s)
            else:
                shat = precond(s)
                t, tt, _, ts = dev.spmv_dots(A, shat, s, dot)
            omega = ts / jnp.where(tt == 0, 1, tt)
            x = x + alpha * phat + omega * shat
            r = s - omega * t
            res = jnp.sqrt(jnp.abs(dot(r, r)))
            hist = self._hist_put(hist, it, res / scale)
            return (x, r, p, v, rho_new, alpha, omega, it + 1, res, hist)

        res0 = jnp.sqrt(jnp.abs(dot(r, r)))
        st = (x, r, jnp.zeros_like(r), jnp.zeros_like(r),
              one, one, one, 0, res0, self._hist_init(rhs.real.dtype))
        (x, r, p, v, rho, alpha, omega, it, res, hist) = \
            lax.while_loop(cond, body, st)
        x = jnp.where(norm_rhs > 0, x, jnp.zeros_like(x))
        return self._hist_result(x, it, res / scale, hist)
