"""Preconditioned BiCGStab for non-symmetric systems with selectable
preconditioning side (reference: amgcl/solver/bicgstab.hpp, default
side::right; the convergence criterion uses the UNPRECONDITIONED rhs norm
for both sides, bicgstab.hpp:168-186, and with side=left the tracked
residual is the preconditioned one). Whole iteration is one
``lax.while_loop``."""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
from jax import lax

from amgcl_tpu.ops import device as dev
from amgcl_tpu.telemetry.history import HistoryMixin


@dataclass
class BiCGStab(HistoryMixin):
    maxiter: int = 100
    tol: float = 1e-8
    abstol: float = 0.0
    precond_side: str = "right"
    record_history: bool = False  # per-iteration relative residuals
    guard: bool = True      # in-loop health guards (telemetry/health.py)

    def solve(self, A, precond, rhs, x0=None, inner_product=dev.inner_product):
        if self.precond_side not in ("left", "right"):
            raise ValueError("precond_side must be 'left' or 'right', got %r"
                             % self.precond_side)
        left = self.precond_side == "left"
        dot = inner_product
        x = jnp.zeros_like(rhs) if x0 is None else x0

        # criterion on the unpreconditioned rhs norm for BOTH sides
        norm_rhs = jnp.sqrt(jnp.abs(dot(rhs, rhs)))
        scale = jnp.where(norm_rhs > 0, norm_rhs, 1.0)
        eps = jnp.maximum(self.tol * scale,
                          jnp.asarray(self.abstol, rhs.dtype).real)

        if left:
            r = precond(dev.residual(rhs, A, x))
        else:
            r = dev.residual(rhs, A, x)
        rhat = r

        def apply_op(p):
            """(v, z): v enters the recurrence, z accumulates into x."""
            if left:
                return precond(dev.spmv(A, p)), p
            z = precond(p)
            return dev.spmv(A, z), z

        one = jnp.ones((), rhs.dtype)

        from amgcl_tpu.telemetry import health as H

        def cond(st):
            (x, r, p, v, rho, alpha, omega, it, res, hist, hs) = st
            return (it < self.maxiter) & (res > eps) & self._guard_go(hs)

        def body(st):
            (x, r, p, v, rho, alpha, omega, it, res, hist, hs) = st
            rho_new = dot(rhat, r)
            beta = (rho_new / jnp.where(rho == 0, 1, rho)) \
                * (alpha / jnp.where(omega == 0, 1, omega))
            p_n = r + beta * (p - omega * v)
            if left:
                v_n, phat = apply_op(p_n)
                denom = dot(rhat, v_n)
            else:
                # fused spmv + <rhat, v> on the DIA path (one HBM pass);
                # spmv_dots returns <v, rhat> — conjugate for the
                # complex fallback (identity for real)
                phat = precond(p_n)
                v_n, _, _, vr = dev.spmv_dots(A, phat, rhat, dot)
                denom = jnp.conj(vr)
            alpha_n = rho_new / jnp.where(denom == 0, 1, denom)
            s = r - alpha_n * v_n
            if left:
                t, shat = apply_op(s)
                tt = dot(t, t)
                ts = dot(t, s)
            else:
                shat = precond(s)
                t, tt, _, ts = dev.spmv_dots(A, shat, s, dot)
            omega_n = ts / jnp.where(tt == 0, 1, tt)
            x_n = x + alpha_n * phat + omega_n * shat
            r_n = s - omega_n * t
            res_n = jnp.sqrt(jnp.abs(dot(r_n, r_n)))
            # the three breakdown modes of the reference (bicgstab.hpp
            # throws on each): rho-, alpha(denom)- and omega-breakdown
            ok, hs = self._guard_step(
                hs, it, res_n / scale,
                ((H.BREAKDOWN_RHO, H.bad_denom(rho_new)),
                 (H.BREAKDOWN_ALPHA, H.bad_denom(denom)),
                 (H.BREAKDOWN_OMEGA, H.bad_denom(omega_n))))
            x, r, p, v, rho, alpha, omega, res = self._guard_commit(
                ok, (x_n, r_n, p_n, v_n, rho_new, alpha_n, omega_n, res_n),
                (x, r, p, v, rho, alpha, omega, res))
            hist = self._hist_put(hist, it, res_n / scale, keep=ok)
            return (x, r, p, v, rho, alpha, omega,
                    it + ok.astype(jnp.int32), res, hist, hs)

        res0 = jnp.sqrt(jnp.abs(dot(r, r)))
        st = (x, r, jnp.zeros_like(r), jnp.zeros_like(r),
              one, one, one, jnp.zeros((), jnp.int32), res0,
              self._hist_init(rhs.real.dtype),
              self._guard_init(res0 / scale))
        (x, r, p, v, rho, alpha, omega, it, res, hist, hs) = \
            lax.while_loop(cond, body, st)
        x = jnp.where(norm_rhs > 0, x, jnp.zeros_like(x))
        return self._hist_result(x, it, res / scale, hist, health=hs)
