"""Preconditioned BiCGStab for non-symmetric systems, right-preconditioned
(the reference defaults to side=right, amgcl/solver/bicgstab.hpp with
precond_side option). Whole iteration is one ``lax.while_loop``."""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
from jax import lax

from amgcl_tpu.ops import device as dev


@dataclass
class BiCGStab:
    maxiter: int = 100
    tol: float = 1e-8
    abstol: float = 0.0

    def solve(self, A, precond, rhs, x0=None, inner_product=dev.inner_product):
        dot = inner_product
        x = jnp.zeros_like(rhs) if x0 is None else x0
        r = dev.residual(rhs, A, x)
        rhat = r
        norm_rhs = jnp.sqrt(jnp.abs(dot(rhs, rhs)))
        scale = jnp.where(norm_rhs > 0, norm_rhs, 1.0)
        eps = jnp.maximum(self.tol * scale,
                          jnp.asarray(self.abstol, rhs.dtype).real)
        one = jnp.ones((), rhs.dtype)

        def cond(st):
            (x, r, p, v, rho, alpha, omega, it, res) = st
            return (it < self.maxiter) & (res > eps)

        def body(st):
            (x, r, p, v, rho, alpha, omega, it, res) = st
            rho_new = dot(rhat, r)
            beta = (rho_new / jnp.where(rho == 0, 1, rho)) \
                * (alpha / jnp.where(omega == 0, 1, omega))
            p = r + beta * (p - omega * v)
            phat = precond(p)
            v = dev.spmv(A, phat)
            denom = dot(rhat, v)
            alpha = rho_new / jnp.where(denom == 0, 1, denom)
            s = r - alpha * v
            shat = precond(s)
            t = dev.spmv(A, shat)
            tt = dot(t, t)
            omega = dot(t, s) / jnp.where(tt == 0, 1, tt)
            x = x + alpha * phat + omega * shat
            r = s - omega * t
            res = jnp.sqrt(jnp.abs(dot(r, r)))
            return (x, r, p, v, rho_new, alpha, omega, it + 1, res)

        res0 = jnp.sqrt(jnp.abs(dot(r, r)))
        st = (x, r, jnp.zeros_like(r), jnp.zeros_like(r),
              one, one, one, 0, res0)
        (x, r, p, v, rho, alpha, omega, it, res) = \
            lax.while_loop(cond, body, st)
        x = jnp.where(norm_rhs > 0, x, jnp.zeros_like(x))
        return x, it, res / scale
