"""Apply the preconditioner exactly once — used for nesting preconditioners
inside other solvers (reference: amgcl/solver/preonly.hpp)."""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from amgcl_tpu.ops import device as dev


@dataclass
class PreOnly:
    maxiter: int = 1   # unused; kept for interface parity
    tol: float = 0.0

    def solve(self, A, precond, rhs, x0=None, inner_product=dev.inner_product):
        x = precond(rhs)
        r = dev.residual(rhs, A, x)
        nr = jnp.sqrt(jnp.abs(inner_product(r, r)))
        nb = jnp.sqrt(jnp.abs(inner_product(rhs, rhs)))
        return x, 1, nr / jnp.where(nb > 0, nb, 1.0)
