"""Apply the preconditioner exactly once — used for nesting preconditioners
inside other solvers (reference: amgcl/solver/preonly.hpp)."""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from amgcl_tpu.ops import device as dev
from amgcl_tpu.telemetry.history import HistoryMixin


@dataclass
class PreOnly(HistoryMixin):
    maxiter: int = 1   # unused; kept for interface parity
    tol: float = 0.0
    record_history: bool = False
    guard: bool = True      # NaN detection only (no loop to guard)

    def solve(self, A, precond, rhs, x0=None, inner_product=dev.inner_product):
        if rhs.ndim == 2:
            # stacked multi-RHS entry (serve/batched.py)
            from amgcl_tpu.serve.batched import vmap_solve
            return vmap_solve(self, A, precond, rhs, x0, inner_product)
        from amgcl_tpu.telemetry import health as H
        x = precond(rhs)
        r = dev.residual(rhs, A, x)
        nr = jnp.sqrt(jnp.abs(inner_product(r, r)))
        nb = jnp.sqrt(jnp.abs(inner_product(rhs, rhs)))
        rel = nr / jnp.where(nb > 0, nb, 1.0)
        hist = self._hist_put(self._hist_init(rhs.real.dtype), 0, rel)
        hs = H.trip(self._guard_init(rel), 0, H.NAN, ~jnp.isfinite(rel))
        return self._hist_result(x, 1, rel, hist, health=hs)
