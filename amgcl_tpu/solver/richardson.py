"""Damped Richardson iteration: x += ω M(f − A x)
(reference: amgcl/solver/richardson.hpp, default damping 1.0)."""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
from jax import lax

from amgcl_tpu.ops import device as dev
from amgcl_tpu.telemetry.history import HistoryMixin


@dataclass
class Richardson(HistoryMixin):
    maxiter: int = 100
    tol: float = 1e-8
    damping: float = 1.0
    record_history: bool = False  # per-iteration relative residuals

    def solve(self, A, precond, rhs, x0=None, inner_product=dev.inner_product):
        dot = inner_product
        x = jnp.zeros_like(rhs) if x0 is None else x0
        norm_rhs = jnp.sqrt(jnp.abs(dot(rhs, rhs)))
        scale = jnp.where(norm_rhs > 0, norm_rhs, 1.0)
        eps = self.tol * scale

        def cond(st):
            x, r, it, res, hist = st
            return (it < self.maxiter) & (res > eps)

        def body(st):
            x, r, it, _, hist = st
            x = x + self.damping * precond(r)
            r = dev.residual(rhs, A, x)
            res = jnp.sqrt(jnp.abs(dot(r, r)))
            hist = self._hist_put(hist, it, res / scale)
            return (x, r, it + 1, res, hist)

        r0 = dev.residual(rhs, A, x)
        st = (x, r0, 0, jnp.sqrt(jnp.abs(dot(r0, r0))),
              self._hist_init(rhs.real.dtype))
        x, r, it, res, hist = lax.while_loop(cond, body, st)
        return self._hist_result(x, it, res / scale, hist)
