"""Damped Richardson iteration: x += ω M(f − A x)
(reference: amgcl/solver/richardson.hpp, default damping 1.0)."""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
from jax import lax

from amgcl_tpu.ops import device as dev
from amgcl_tpu.ops import fused_vec as fv
from amgcl_tpu.telemetry.history import HistoryMixin


@dataclass
class Richardson(HistoryMixin):
    maxiter: int = 100
    tol: float = 1e-8
    damping: float = 1.0
    record_history: bool = False  # per-iteration relative residuals
    guard: bool = True      # in-loop health guards (telemetry/health.py)

    def solve(self, A, precond, rhs, x0=None, inner_product=dev.inner_product):
        if rhs.ndim == 2:
            # stacked multi-RHS entry (serve/batched.py)
            from amgcl_tpu.serve.batched import vmap_solve
            return vmap_solve(self, A, precond, rhs, x0, inner_product)
        dot = inner_product
        x = jnp.zeros_like(rhs) if x0 is None else x0
        norm_rhs = jnp.sqrt(jnp.abs(dot(rhs, rhs)))
        scale = jnp.where(norm_rhs > 0, norm_rhs, 1.0)
        eps = self.tol * scale

        def cond(st):
            x, r, it, res, hist, hs = st
            return (it < self.maxiter) & (res > eps) & self._guard_go(hs)

        def body(st):
            x, r, it, res, hist, hs = st
            x_n = x + self.damping * precond(r)
            # fused residual + <r,r> (ops/fused_vec.py): the whole body's
            # vector work after the preconditioner is ONE operator pass
            r_n, rr = fv.residual_dot(rhs, A, x_n, ip=dot)
            res_n = jnp.sqrt(jnp.abs(rr))
            # no breakdown denominators in a stationary iteration — the
            # guards watch for NaN, stagnation and divergence only
            ok, hs = self._guard_step(hs, it, res_n / scale)
            x, r, res = self._guard_commit(ok, (x_n, r_n, res_n),
                                           (x, r, res))
            hist = self._hist_put(hist, it, res_n / scale, keep=ok)
            return (x, r, it + ok.astype(jnp.int32), res, hist, hs)

        r0, rr0 = fv.residual_dot(rhs, A, x, ip=dot)
        res0 = jnp.sqrt(jnp.abs(rr0))
        st = (x, r0, jnp.zeros((), jnp.int32), res0,
              self._hist_init(rhs.real.dtype),
              self._guard_init(res0 / scale))
        x, r, it, res, hist, hs = lax.while_loop(cond, body, st)
        return self._hist_result(x, it, res / scale, hist, health=hs)
