/* C API for amgcl_tpu — the TPU-native rendition of the reference's
 * C shared library (/root/reference/lib/amgcl.h:47-157): opaque handles
 * over the runtime registry, so C / Fortran callers can configure, build,
 * and run solvers. The implementation (csrc/c_api.cpp) embeds CPython and
 * drives the ordinary JAX-backed runtime compositions; arrays cross the
 * boundary zero-copy.
 *
 * All indices are 0-based ints (CSR). The *_f variants accept 1-based
 * (Fortran) ptr/col arrays. Values are double; solves run f64 end-to-end.
 */
#ifndef AMGCL_TPU_H
#define AMGCL_TPU_H

#ifdef __cplusplus
extern "C" {
#endif

typedef void* amgclHandle;

struct amgcl_tpu_conv_info {
    int    iterations;
    double residual;
};

/* Must be called once before anything else; returns 0 on success.
 * Initializes the embedded Python runtime (no-op when already inside a
 * Python process). */
int amgcl_tpu_init(void);

/* -- parameter lists (dotted keys, e.g. "solver.type" = "cg") ----------- */
amgclHandle amgcl_tpu_params_create(void);
void amgcl_tpu_params_seti(amgclHandle prm, const char* name, int value);
void amgcl_tpu_params_setf(amgclHandle prm, const char* name, double value);
void amgcl_tpu_params_sets(amgclHandle prm, const char* name,
                           const char* value);
void amgcl_tpu_params_read_json(amgclHandle prm, const char* fname);
void amgcl_tpu_params_destroy(amgclHandle prm);

/* -- preconditioner ----------------------------------------------------- */
amgclHandle amgcl_tpu_precond_create(int n, const int* ptr, const int* col,
                                     const double* val, amgclHandle prm);
amgclHandle amgcl_tpu_precond_create_f(int n, const int* ptr, const int* col,
                                       const double* val, amgclHandle prm);
void amgcl_tpu_precond_apply(amgclHandle p, const double* rhs, double* x);
void amgcl_tpu_precond_report(amgclHandle p);
void amgcl_tpu_precond_destroy(amgclHandle p);

/* -- solver (preconditioner + Krylov) ----------------------------------- */
amgclHandle amgcl_tpu_solver_create(int n, const int* ptr, const int* col,
                                    const double* val, amgclHandle prm);
amgclHandle amgcl_tpu_solver_create_f(int n, const int* ptr, const int* col,
                                      const double* val, amgclHandle prm);
/* x holds the initial guess on entry (zeros = cold start) and the solution
 * on exit. */
struct amgcl_tpu_conv_info amgcl_tpu_solver_solve(amgclHandle s,
                                                  const double* rhs,
                                                  double* x);
/* Fortran-friendly variant: conv_info returned via an out parameter. */
void amgcl_tpu_solver_solve_f(amgclHandle s, const double* rhs, double* x,
                              struct amgcl_tpu_conv_info* cnv);
void amgcl_tpu_solver_report(amgclHandle s);
void amgcl_tpu_solver_destroy(amgclHandle s);

#ifdef __cplusplus
}
#endif

#endif /* AMGCL_TPU_H */
