// Native setup-phase kernels for amgcl_tpu.
//
// The AMG hierarchy is constructed on the host (SURVEY.md: the reference
// builds on CPU and moves the hierarchy to the backend); these kernels are
// the hot host-side passes, exposed over a plain C ABI and loaded with
// ctypes. Everything here is a fresh implementation of standard algorithms
// (Vanek-style greedy aggregation, strength filtering, CSR transpose) — not
// a translation of the reference sources.
//
// Build: g++ -O3 -march=native -fopenmp -shared -fPIC \
//            -o libamgcl_tpu_native.so setup_kernels.cpp

#include <cstdint>
#include <cstring>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

extern "C" {

// Greedy distance-2 aggregation over a strength mask.
//
// ptr/col: CSR pattern of A (n rows); strong: per-entry 0/1 strength flag
// (diagonal entries must be 0). agg (out, size n): aggregate id per node or
// -1 for nodes with no strong connections. Returns the number of
// aggregates.
//
// Sweep: visiting nodes in index order, a node that was never claimed
// becomes the root of a new aggregate, finalizes all its unclaimed or
// tentatively-claimed strong neighbors, and tentatively claims their
// neighbors (a later root may steal tentative nodes as its own distance-1
// members; leftover tentative nodes keep the aggregate that claimed them).
int64_t aggregate_d2(int64_t n, const int64_t* ptr, const int32_t* col,
                     const uint8_t* strong, int64_t* agg) {
  const int64_t kUnset = -3, kTentative = -2, kIsolated = -1;
  std::vector<int64_t> owner(n, kUnset);  // tentative owner id
  for (int64_t i = 0; i < n; ++i) {
    bool has_strong = false;
    for (int64_t j = ptr[i]; j < ptr[i + 1]; ++j)
      if (strong[j]) { has_strong = true; break; }
    agg[i] = has_strong ? kUnset : kIsolated;
  }

  int64_t count = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (agg[i] != kUnset) continue;
    if (owner[i] != kUnset) continue;  // tentatively claimed: not a root
    const int64_t id = count++;
    agg[i] = id;
    // finalize strong neighbors
    for (int64_t j = ptr[i]; j < ptr[i + 1]; ++j) {
      if (!strong[j]) continue;
      const int32_t c = col[j];
      if (agg[c] == kUnset) {
        agg[c] = id;
        // tentatively claim the neighbors' neighbors
        for (int64_t k = ptr[c]; k < ptr[c + 1]; ++k) {
          if (!strong[k]) continue;
          const int32_t cc = col[k];
          if (agg[cc] == kUnset && owner[cc] == kUnset) owner[cc] = id;
        }
      }
    }
  }
  // leftover tentatives keep their claiming aggregate
  for (int64_t i = 0; i < n; ++i)
    if (agg[i] == kUnset) agg[i] = owner[i] != kUnset ? owner[i] : kIsolated;

  // aggregates can lose every finalized member only if they never had one;
  // compress ids to be safe (cheap single pass)
  std::vector<int64_t> seen(count, 0);
  for (int64_t i = 0; i < n; ++i)
    if (agg[i] >= 0) seen[agg[i]] = 1;
  std::vector<int64_t> remap(count, -1);
  int64_t live = 0;
  for (int64_t a = 0; a < count; ++a)
    if (seen[a]) remap[a] = live++;
  if (live != count)
    for (int64_t i = 0; i < n; ++i)
      if (agg[i] >= 0) agg[i] = remap[agg[i]];
  return live;
}

// Per-entry strength flag: |a_ij|^2 > eps^2 * |a_ii * a_jj| (off-diagonal).
void strength_mask(int64_t n, const int64_t* ptr, const int32_t* col,
                   const double* val, double eps, uint8_t* strong) {
  std::vector<double> dia(n, 0.0);
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i)
    for (int64_t j = ptr[i]; j < ptr[i + 1]; ++j)
      if (col[j] == i) dia[i] = val[j] < 0 ? -val[j] : val[j];
  const double e2 = eps * eps;
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i)
    for (int64_t j = ptr[i]; j < ptr[i + 1]; ++j) {
      const int32_t c = col[j];
      strong[j] =
          (c != i) && (val[j] * val[j] > e2 * dia[i] * dia[c]) ? 1 : 0;
    }
}

// Symmetrize a 0/1 strength mask in place: strong[i->j] |= strong[j->i].
// Requires sorted column indices per row (binary search on the reverse
// entry).
void symmetrize_mask(int64_t n, const int64_t* ptr, const int32_t* col,
                     uint8_t* strong) {
#pragma omp parallel for schedule(dynamic, 1024)
  for (int64_t i = 0; i < n; ++i) {
    for (int64_t j = ptr[i]; j < ptr[i + 1]; ++j) {
      if (strong[j]) continue;
      const int32_t c = col[j];
      // find (c, i)
      int64_t lo = ptr[c], hi = ptr[c + 1];
      while (lo < hi) {
        const int64_t mid = (lo + hi) / 2;
        if (col[mid] < i) lo = mid + 1; else hi = mid;
      }
      if (lo < ptr[c + 1] && col[lo] == (int32_t)i && strong[lo])
        strong[j] = 1;
    }
  }
}

int omp_max_threads() {
#ifdef _OPENMP
  return omp_get_max_threads();
#else
  return 1;
#endif
}

}  // extern "C"
