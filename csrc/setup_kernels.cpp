// Native setup-phase kernels for amgcl_tpu.
//
// The AMG hierarchy is constructed on the host (SURVEY.md: the reference
// builds on CPU and moves the hierarchy to the backend); these kernels are
// the hot host-side passes, exposed over a plain C ABI and loaded with
// ctypes. Everything here is a fresh implementation of standard algorithms
// (Vanek-style greedy aggregation, strength filtering, CSR transpose) — not
// a translation of the reference sources.
//
// Build: g++ -O3 -march=native -fopenmp -shared -fPIC \
//            -o libamgcl_tpu_native.so setup_kernels.cpp

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

extern "C" {

// Greedy distance-2 aggregation over a strength mask.
//
// ptr/col: CSR pattern of A (n rows); strong: per-entry 0/1 strength flag
// (diagonal entries must be 0). agg (out, size n): aggregate id per node or
// -1 for nodes with no strong connections. Returns the number of
// aggregates.
//
// Sweep: visiting nodes in index order, a node that was never claimed
// becomes the root of a new aggregate, finalizes all its unclaimed or
// tentatively-claimed strong neighbors, and tentatively claims their
// neighbors (a later root may steal tentative nodes as its own distance-1
// members; leftover tentative nodes keep the aggregate that claimed them).
int64_t aggregate_d2(int64_t n, const int64_t* ptr, const int32_t* col,
                     const uint8_t* strong, int64_t* agg) {
  const int64_t kUnset = -3, kTentative = -2, kIsolated = -1;
  std::vector<int64_t> owner(n, kUnset);  // tentative owner id
  for (int64_t i = 0; i < n; ++i) {
    bool has_strong = false;
    for (int64_t j = ptr[i]; j < ptr[i + 1]; ++j)
      if (strong[j]) { has_strong = true; break; }
    agg[i] = has_strong ? kUnset : kIsolated;
  }

  int64_t count = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (agg[i] != kUnset) continue;
    if (owner[i] != kUnset) continue;  // tentatively claimed: not a root
    const int64_t id = count++;
    agg[i] = id;
    // finalize strong neighbors
    for (int64_t j = ptr[i]; j < ptr[i + 1]; ++j) {
      if (!strong[j]) continue;
      const int32_t c = col[j];
      if (agg[c] == kUnset) {
        agg[c] = id;
        // tentatively claim the neighbors' neighbors
        for (int64_t k = ptr[c]; k < ptr[c + 1]; ++k) {
          if (!strong[k]) continue;
          const int32_t cc = col[k];
          if (agg[cc] == kUnset && owner[cc] == kUnset) owner[cc] = id;
        }
      }
    }
  }
  // leftover tentatives keep their claiming aggregate
  for (int64_t i = 0; i < n; ++i)
    if (agg[i] == kUnset) agg[i] = owner[i] != kUnset ? owner[i] : kIsolated;

  // aggregates can lose every finalized member only if they never had one;
  // compress ids to be safe (cheap single pass)
  std::vector<int64_t> seen(count, 0);
  for (int64_t i = 0; i < n; ++i)
    if (agg[i] >= 0) seen[agg[i]] = 1;
  std::vector<int64_t> remap(count, -1);
  int64_t live = 0;
  for (int64_t a = 0; a < count; ++a)
    if (seen[a]) remap[a] = live++;
  if (live != count)
    for (int64_t i = 0; i < n; ++i)
      if (agg[i] >= 0) agg[i] = remap[agg[i]];
  return live;
}

// Per-entry strength flag: |a_ij|^2 > eps^2 * |a_ii * a_jj| (off-diagonal).
void strength_mask(int64_t n, const int64_t* ptr, const int32_t* col,
                   const double* val, double eps, uint8_t* strong) {
  std::vector<double> dia(n, 0.0);
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i)
    for (int64_t j = ptr[i]; j < ptr[i + 1]; ++j)
      if (col[j] == i) dia[i] = val[j] < 0 ? -val[j] : val[j];
  const double e2 = eps * eps;
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i)
    for (int64_t j = ptr[i]; j < ptr[i + 1]; ++j) {
      const int32_t c = col[j];
      strong[j] =
          (c != i) && (val[j] * val[j] > e2 * dia[i] * dia[c]) ? 1 : 0;
    }
}

// Symmetrize a 0/1 strength mask in place: strong[i->j] |= strong[j->i].
// Requires sorted column indices per row (binary search on the reverse
// entry).
void symmetrize_mask(int64_t n, const int64_t* ptr, const int32_t* col,
                     uint8_t* strong) {
#pragma omp parallel for schedule(dynamic, 1024)
  for (int64_t i = 0; i < n; ++i) {
    for (int64_t j = ptr[i]; j < ptr[i + 1]; ++j) {
      if (strong[j]) continue;
      const int32_t c = col[j];
      // find (c, i)
      int64_t lo = ptr[c], hi = ptr[c + 1];
      while (lo < hi) {
        const int64_t mid = (lo + hi) / 2;
        if (col[mid] < i) lo = mid + 1; else hi = mid;
      }
      if (lo < ptr[c + 1] && col[lo] == (int32_t)i && strong[lo])
        strong[j] = 1;
    }
  }
}

int omp_max_threads() {
#ifdef _OPENMP
  return omp_get_max_threads();
#else
  return 1;
#endif
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Sparse general matrix-matrix multiply (CSR x CSR), two-phase, hash-based
// per-row accumulators (open addressing, power-of-2 capacity) — no large
// per-thread scratch, rows parallelized dynamically.

namespace {

struct HashAcc {
  std::vector<int32_t> keys;
  std::vector<double> vals;
  int64_t mask = 0;

  void reset_keys(int64_t cap_hint) {
    int64_t cap = 16;
    while (cap < cap_hint * 2) cap <<= 1;
    keys.assign(cap, -1);
    mask = cap - 1;
  }

  void reset(int64_t cap_hint) {
    reset_keys(cap_hint);
    vals.assign(mask + 1, 0.0);
  }

  // insert without value accumulation; returns true when the key is new
  inline bool insert_key(int32_t key) {
    int64_t h = (static_cast<uint32_t>(key) * 2654435761u) & mask;
    while (true) {
      if (keys[h] == key) return false;
      if (keys[h] == -1) { keys[h] = key; return true; }
      h = (h + 1) & mask;
    }
  }

  inline void add(int32_t key, double v) {
    int64_t h = (static_cast<uint32_t>(key) * 2654435761u) & mask;
    while (true) {
      if (keys[h] == key) { vals[h] += v; return; }
      if (keys[h] == -1) { keys[h] = key; vals[h] = v; return; }
      h = (h + 1) & mask;
    }
  }

  // accumulate only when the key is already present (masked products)
  inline void add_if_present(int32_t key, double v) {
    int64_t h = (static_cast<uint32_t>(key) * 2654435761u) & mask;
    while (true) {
      if (keys[h] == key) { vals[h] += v; return; }
      if (keys[h] == -1) return;
      h = (h + 1) & mask;
    }
  }

  inline double get(int32_t key) const {
    int64_t h = (static_cast<uint32_t>(key) * 2654435761u) & mask;
    while (true) {
      if (keys[h] == key) return vals[h];
      if (keys[h] == -1) return 0.0;
      h = (h + 1) & mask;
    }
  }
};

// Block-value accumulator: each slot owns a bs-element dense block.
struct BlockHashAcc {
  std::vector<int32_t> keys;
  std::vector<double> vals;  // (mask+1) * bs
  std::vector<int64_t> used;
  int64_t mask = 0;
  int64_t bs = 1;

  void reset(int64_t cap_hint, int64_t bs_) {
    int64_t cap = 16;
    while (cap < cap_hint * 2) cap <<= 1;
    keys.assign(cap, -1);
    mask = cap - 1;
    bs = bs_;
    vals.assign(cap * bs, 0.0);
    used.clear();
  }

  inline double* slot(int32_t key) {
    int64_t h = (static_cast<uint32_t>(key) * 2654435761u) & mask;
    while (true) {
      if (keys[h] == key) return &vals[h * bs];
      if (keys[h] == -1) {
        keys[h] = key;
        used.push_back(h);
        return &vals[h * bs];
      }
      h = (h + 1) & mask;
    }
  }
};

// Shared numeric pass over the value type (f64 / f32 front-ends below).
template <class T>
void spgemm_numeric_t(int64_t n, const int64_t* aptr, const int32_t* acol,
                      const T* aval, const int64_t* bptr,
                      const int32_t* bcol, const T* bval,
                      const int64_t* cptr, int32_t* ccol, T* cval) {
#pragma omp parallel
  {
    HashAcc acc;
    std::vector<int64_t> tmp;
#pragma omp for schedule(dynamic, 256)
    for (int64_t i = 0; i < n; ++i) {
      acc.reset(cptr[i + 1] - cptr[i] + 8);
      for (int64_t j = aptr[i]; j < aptr[i + 1]; ++j) {
        const int32_t a = acol[j];
        const double av = static_cast<double>(aval[j]);
        for (int64_t t = bptr[a]; t < bptr[a + 1]; ++t)
          acc.add(bcol[t], av * static_cast<double>(bval[t]));
      }
      tmp.clear();
      for (int64_t h = 0; h <= acc.mask; ++h)
        if (acc.keys[h] != -1) tmp.push_back(h);
      std::sort(tmp.begin(), tmp.end(),
                [&](int64_t x, int64_t y) { return acc.keys[x] < acc.keys[y]; });
      int64_t o = cptr[i];
      for (int64_t h : tmp) {
        ccol[o] = acc.keys[h];
        cval[o] = static_cast<T>(acc.vals[h]);
        ++o;
      }
    }
  }
}

}  // namespace

extern "C" {

// Pass 1: per-row nnz of C = A (n x k) * B (k x m).
void spgemm_symbolic(int64_t n, const int64_t* aptr, const int32_t* acol,
                     const int64_t* bptr, const int32_t* bcol,
                     int64_t* c_row_nnz) {
#pragma omp parallel
  {
    HashAcc acc;
#pragma omp for schedule(dynamic, 256)
    for (int64_t i = 0; i < n; ++i) {
      int64_t hint = 8;
      for (int64_t j = aptr[i]; j < aptr[i + 1]; ++j)
        hint += bptr[acol[j] + 1] - bptr[acol[j]];
      acc.reset_keys(hint);
      int64_t cnt = 0;
      for (int64_t j = aptr[i]; j < aptr[i + 1]; ++j) {
        const int32_t a = acol[j];
        for (int64_t t = bptr[a]; t < bptr[a + 1]; ++t)
          if (acc.insert_key(bcol[t])) ++cnt;
      }
      c_row_nnz[i] = cnt;
    }
  }
}

// Pass 2: fill col/val given precomputed cptr (exclusive scan of row nnz).
// Column indices are emitted sorted per row.
void spgemm_numeric(int64_t n, const int64_t* aptr, const int32_t* acol,
                    const double* aval, const int64_t* bptr,
                    const int32_t* bcol, const double* bval,
                    const int64_t* cptr, int32_t* ccol, double* cval) {
  spgemm_numeric_t<double>(n, aptr, acol, aval, bptr, bcol, bval, cptr,
                           ccol, cval);
}

void spgemm_numeric_f32(int64_t n, const int64_t* aptr, const int32_t* acol,
                        const float* aval, const int64_t* bptr,
                        const int32_t* bcol, const float* bval,
                        const int64_t* cptr, int32_t* ccol, float* cval) {
  spgemm_numeric_t<float>(n, aptr, acol, aval, bptr, bcol, bval, cptr,
                          ccol, cval);
}

// Block-valued numeric pass: aval blocks are (br x bk) row-major, bval
// (bk x bc), accumulating (br x bc) product blocks. Same symbolic pass as
// the scalar kernel (the pattern is value-type-free).
void spgemm_numeric_block(int64_t n, const int64_t* aptr,
                          const int32_t* acol, const double* aval,
                          const int64_t* bptr, const int32_t* bcol,
                          const double* bval, const int64_t* cptr,
                          int32_t* ccol, double* cval, int64_t br,
                          int64_t bk, int64_t bc) {
  const int64_t as = br * bk, bs = bk * bc, cs = br * bc;
#pragma omp parallel
  {
    BlockHashAcc acc;
    std::vector<int64_t> tmp;
#pragma omp for schedule(dynamic, 128)
    for (int64_t i = 0; i < n; ++i) {
      acc.reset(cptr[i + 1] - cptr[i] + 8, cs);
      for (int64_t j = aptr[i]; j < aptr[i + 1]; ++j) {
        const int32_t a = acol[j];
        const double* Ab = aval + j * as;
        for (int64_t t = bptr[a]; t < bptr[a + 1]; ++t) {
          const double* Bb = bval + t * bs;
          double* Cb = acc.slot(bcol[t]);
          for (int64_t r = 0; r < br; ++r)
            for (int64_t k = 0; k < bk; ++k) {
              const double av = Ab[r * bk + k];
              if (av == 0.0) continue;
              const double* Brow = Bb + k * bc;
              double* Crow = Cb + r * bc;
              for (int64_t c = 0; c < bc; ++c) Crow[c] += av * Brow[c];
            }
        }
      }
      tmp = acc.used;
      std::sort(tmp.begin(), tmp.end(), [&](int64_t x, int64_t y) {
        return acc.keys[x] < acc.keys[y];
      });
      int64_t o = cptr[i];
      for (int64_t h : tmp) {
        ccol[o] = acc.keys[h];
        std::memcpy(cval + o * cs, &acc.vals[h * cs], cs * sizeof(double));
        ++o;
      }
    }
  }
}

// ELL packing: scatter CSR rows into dense (n, K) column/value planes
// (the host->device format conversion — the hot part of to_device).
// The value cast (f64 input -> f32/f64 output) is fused into the pack;
// both output planes must arrive zeroed. bs = elements per value (1 for
// scalar, br*bc for block values).
void ell_pack(int64_t n, const int64_t* ptr, const int32_t* col,
              const double* val, int64_t K, int64_t bs, int32_t* ocols,
              double* ovals) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    int64_t o = i * K;
    for (int64_t j = ptr[i]; j < ptr[i + 1]; ++j, ++o) {
      ocols[o] = col[j];
      std::memcpy(ovals + o * bs, val + j * bs, bs * sizeof(double));
    }
  }
}

void ell_pack_f32(int64_t n, const int64_t* ptr, const int32_t* col,
                  const double* val, int64_t K, int64_t bs, int32_t* ocols,
                  float* ovals) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    int64_t o = i * K;
    for (int64_t j = ptr[i]; j < ptr[i + 1]; ++j, ++o) {
      ocols[o] = col[j];
      const double* src = val + j * bs;
      float* dst = ovals + o * bs;
      for (int64_t b = 0; b < bs; ++b) dst[b] = static_cast<float>(src[b]);
    }
  }
}

// SPAI-0 diagonal: m_i = a_ii / sum_j a_ij^2 (one fused pass; the
// reference's spai0.hpp row loop, here the hot part of the default
// smoother's host build).
void spai0_diag(int64_t n, const int64_t* ptr, const int32_t* col,
                const double* val, double* m) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    double dia = 0.0, ss = 0.0;
    for (int64_t j = ptr[i]; j < ptr[i + 1]; ++j) {
      const double v = val[j];
      ss += v * v;
      if (col[j] == i) dia = v;
    }
    m[i] = ss != 0.0 ? dia / ss : 0.0;
  }
}

// Pattern-restricted product: tval[q] = sum_k A[i,k] B[k, tcol[q]] for each
// target entry q of row i — one pass, no symbolic phase, no allocation of
// the full product. This is the Chow-Patel sweep kernel: (L+I)U evaluated
// on the factor pattern (reference role: the per-entry inner products of
// amgcl/relaxation/ilu0_chow_patel.hpp's sweeps).
void spgemm_masked(int64_t n, const int64_t* aptr, const int32_t* acol,
                   const double* aval, const int64_t* bptr,
                   const int32_t* bcol, const double* bval,
                   const int64_t* tptr, const int32_t* tcol, double* tval) {
#pragma omp parallel
  {
    HashAcc acc;
#pragma omp for schedule(dynamic, 256)
    for (int64_t i = 0; i < n; ++i) {
      const int64_t t0 = tptr[i], t1 = tptr[i + 1];
      if (t0 == t1) continue;
      acc.reset(t1 - t0 + 8);
      for (int64_t q = t0; q < t1; ++q) acc.add(tcol[q], 0.0);
      for (int64_t j = aptr[i]; j < aptr[i + 1]; ++j) {
        const int32_t a = acol[j];
        const double av = aval[j];
        if (av == 0.0) continue;
        for (int64_t t = bptr[a]; t < bptr[a + 1]; ++t)
          acc.add_if_present(bcol[t], av * bval[t]);
      }
      for (int64_t q = t0; q < t1; ++q) tval[q] = acc.get(tcol[q]);
    }
  }
}

// Strength-filtered matrix with weak-entry lumping (the SA "filtered"
// operator): strong entries (|a_ij|^2 > eps^2 |a_ii a_jj|) and diagonals
// are kept, weak off-diagonals removed and added to the diagonal.
// Pass 1 counts kept entries per row; pass 2 fills. f64 and f32 value
// variants share the templates below (templates cannot carry C linkage,
// so the block is closed around them).
}  // extern "C"

template <typename V>
static void filter_count_impl(int64_t n, const int64_t* ptr,
                              const int32_t* col, const V* val, double eps,
                              int64_t* row_nnz) {
  std::vector<double> dia(n, 0.0);
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i)
    for (int64_t j = ptr[i]; j < ptr[i + 1]; ++j)
      if (col[j] == i) dia[i] = val[j] < 0 ? -double(val[j]) : val[j];
  const double e2 = eps * eps;
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    int64_t cnt = 0;
    for (int64_t j = ptr[i]; j < ptr[i + 1]; ++j) {
      const int32_t c = col[j];
      if (c == i || double(val[j]) * val[j] > e2 * dia[i] * dia[c]) ++cnt;
    }
    row_nnz[i] = cnt;
  }
}

template <typename V>
static void filter_fill_impl(int64_t n, const int64_t* ptr,
                             const int32_t* col, const V* val, double eps,
                             const int64_t* optr, int32_t* ocol, V* oval,
                             V* dinv) {
  std::vector<double> dia(n, 0.0);
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i)
    for (int64_t j = ptr[i]; j < ptr[i + 1]; ++j)
      if (col[j] == i) dia[i] = val[j] < 0 ? -double(val[j]) : val[j];
  const double e2 = eps * eps;
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    int64_t o = optr[i];
    int64_t dpos = -1;
    V lump = 0;
    for (int64_t j = ptr[i]; j < ptr[i + 1]; ++j) {
      const int32_t c = col[j];
      if (c == i) {
        dpos = o;
        ocol[o] = c;
        oval[o] = val[j];
        ++o;
      } else if (double(val[j]) * val[j] > e2 * dia[i] * dia[c]) {
        ocol[o] = c;
        oval[o] = val[j];
        ++o;
      } else {
        lump += val[j];
      }
    }
    V d = 0;
    if (dpos >= 0) {
      oval[dpos] += lump;
      d = oval[dpos];
    }
    dinv[i] = d != 0 ? V(1) / d : V(1);
  }
}

extern "C" {

void filter_count(int64_t n, const int64_t* ptr, const int32_t* col,
                  const double* val, double eps, int64_t* row_nnz) {
  filter_count_impl(n, ptr, col, val, eps, row_nnz);
}

void filter_count_f32(int64_t n, const int64_t* ptr, const int32_t* col,
                      const float* val, double eps, int64_t* row_nnz) {
  filter_count_impl(n, ptr, col, val, eps, row_nnz);
}

void filter_fill(int64_t n, const int64_t* ptr, const int32_t* col,
                 const double* val, double eps, const int64_t* optr,
                 int32_t* ocol, double* oval, double* dinv) {
  filter_fill_impl(n, ptr, col, val, eps, optr, ocol, oval, dinv);
}

void filter_fill_f32(int64_t n, const int64_t* ptr, const int32_t* col,
                     const float* val, double eps, const int64_t* optr,
                     int32_t* ocol, float* oval, float* dinv) {
  filter_fill_impl(n, ptr, col, val, eps, optr, ocol, oval, dinv);
}

}  // extern "C"

// ---------------------------------------------------------------------------
// ILU(k) symbolic factorization: classic row-merge fill-level computation
// (IKJ ordering). For each row i, start from A's pattern at level 0; for
// each candidate column j < i (in ascending order), merge row j of the
// symbolic factor with propagated level lev(i,j) + lev(j,t) + 1; keep
// entries with level <= k. Sequential over rows (the dependency is real),
// linear-ish work for small k.

extern "C" {

// Pass 1+2 in one call with caller-provided output budget. Returns the
// total output nnz, or -1 if the budget was too small (caller doubles and
// retries). Output rows are sorted.
int64_t iluk_symbolic(int64_t n, const int64_t* ptr, const int32_t* col,
                      int64_t k, int64_t budget, int64_t* optr,
                      int32_t* ocol) {
  std::vector<int32_t> levels(budget, 0);
  // per-row workspace: linked-list row merge (Saad's style, re-derived)
  std::vector<int32_t> lev_w(n, -1);   // working levels per column
  std::vector<int32_t> next(n, -1);    // sorted linked list of columns
  optr[0] = 0;
  for (int64_t i = 0; i < n; ++i) {
    // init working row from A's pattern
    int32_t head = -2;
    {
      int32_t prev = -1;
      for (int64_t j = ptr[i]; j < ptr[i + 1]; ++j) {
        const int32_t c = col[j];
        lev_w[c] = 0;
        if (prev < 0) head = c; else next[prev] = c;
        prev = c;
      }
      if (prev >= 0) next[prev] = -2;  // terminator
      else head = -2;
    }
    // eliminate: walk columns j < i in ascending order
    for (int32_t j = head; j != -2 && j < (int32_t)i; j = next[j]) {
      const int32_t lev_ij = lev_w[j];
      if (lev_ij > k) continue;
      // merge factor row j (strictly upper part), propagated level
      int32_t p = j;  // insertion cursor in the linked list
      for (int64_t t = optr[j]; t < optr[j + 1]; ++t) {
        const int32_t c = ocol[t];
        if (c <= j) continue;
        const int32_t lv = lev_ij + levels[t] + 1;
        if (lv > k) continue;
        if (lev_w[c] >= 0) {
          if (lv < lev_w[c]) lev_w[c] = lv;
        } else {
          // insert c into the sorted list after cursor p
          while (next[p] != -2 && next[p] < c) p = next[p];
          next[c] = next[p];
          next[p] = c;
          lev_w[c] = lv;
        }
      }
    }
    // emit row i
    int64_t o = optr[i];
    for (int32_t c = head; c != -2; c = next[c]) {
      if (lev_w[c] <= k) {
        if (o >= budget) {  // out of space: clean up and signal retry
          for (int32_t cc = head; cc != -2; cc = next[cc]) lev_w[cc] = -1;
          return -1;
        }
        ocol[o] = c;
        levels[o] = lev_w[c];
        ++o;
      }
    }
    optr[i + 1] = o;
    // reset workspace
    for (int32_t c = head; c != -2; ) {
      const int32_t nx = next[c];
      lev_w[c] = -1;
      next[c] = -1;
      c = nx;
    }
  }
  return optr[n];
}

}  // extern "C"

// -- DIA packing -----------------------------------------------------------
// Device DIA conversion is setup's hottest host pass at large N (the numpy
// path spends seconds in int64 diagonal arithmetic at 14.6M nnz). These
// kernels mark the distinct diagonals and scatter values into the (ndiag, n)
// diagonal-major array with the dtype cast fused, OpenMP-parallel over rows.

extern "C" {

// hits: (nrows + ncols - 1) bytes, pre-zeroed; diagonal d = col - row marked
// at hits[d + nrows - 1].
void dia_mark(int64_t n, const int64_t* ptr, const int32_t* col,
              uint8_t* hits) {
  const int64_t base = n - 1;
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i)
    for (int64_t j = ptr[i]; j < ptr[i + 1]; ++j) {
      // rows sharing a diagonal write the same flag byte concurrently;
      // an atomic relaxed store keeps it defined under the memory model
#pragma omp atomic write
      hits[col[j] - i + base] = 1;
    }
}

// slot: (nrows + ncols - 1) int32 diagonal->row lookup; out: (ndiag * n),
// pre-zeroed, diagonal-major. Cast variants cover the f64-valued host CSR
// going to an f32 or f64 device hierarchy.
void dia_pack_f64_f32(int64_t n, const int64_t* ptr, const int32_t* col,
                      const double* val, const int32_t* slot, float* out) {
  const int64_t base = n - 1;
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i)
    for (int64_t j = ptr[i]; j < ptr[i + 1]; ++j)
      out[(int64_t)slot[col[j] - i + base] * n + i] =
          static_cast<float>(val[j]);
}

void dia_pack_f64_f64(int64_t n, const int64_t* ptr, const int32_t* col,
                      const double* val, const int32_t* slot, double* out) {
  const int64_t base = n - 1;
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i)
    for (int64_t j = ptr[i]; j < ptr[i + 1]; ++j)
      out[(int64_t)slot[col[j] - i + base] * n + i] = val[j];
}

void dia_pack_f32_f32(int64_t n, const int64_t* ptr, const int32_t* col,
                      const float* val, const int32_t* slot, float* out) {
  const int64_t base = n - 1;
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i)
    for (int64_t j = ptr[i]; j < ptr[i + 1]; ++j)
      out[(int64_t)slot[col[j] - i + base] * n + i] = val[j];
}

// -- stencil Galerkin inner kernel ------------------------------------------
// All pair products of one diagonal-space Galerkin stage in a single
// call: out[i] -= a[i] * b[i + s] over the valid index range, fused into
// one memory pass (ops/stencil.py stencil_galerkin).
// Batched variant: all pair products of one Galerkin stage in a single
// call (no per-pair ctypes overhead), parallel over output diagonals so
// no two threads touch the same output row. a_idx/b_idx/out_idx select
// rows of the (ndiag, n) diagonal-major arrays; pairs sharing out_idx
// must be contiguous and the out rows pre-initialized.

void dia_fnma_batch_f64(int64_t n, int64_t npairs, const double* abase,
                        const int64_t* a_idx, const double* bbase,
                        const int64_t* b_idx, const int64_t* shifts,
                        double* obase, const int64_t* out_idx) {
#pragma omp parallel
  {
#pragma omp for schedule(dynamic, 1)
    for (int64_t p0 = 0; p0 < npairs; ++p0) {
      if (p0 > 0 && out_idx[p0 - 1] == out_idx[p0]) continue;
      for (int64_t p = p0; p < npairs && out_idx[p] == out_idx[p0]; ++p) {
        const double* a = abase + a_idx[p] * n;
        const double* b = bbase + b_idx[p] * n;
        double* out = obase + out_idx[p] * n;
        const int64_t s = shifts[p];
        const int64_t lo = s < 0 ? -s : 0;
        const int64_t hi = s > 0 ? n - s : n;
        for (int64_t i = lo; i < hi; ++i) out[i] -= a[i] * b[i + s];
      }
    }
  }
}

void dia_fnma_batch_f32(int64_t n, int64_t npairs, const float* abase,
                        const int64_t* a_idx, const float* bbase,
                        const int64_t* b_idx, const int64_t* shifts,
                        float* obase, const int64_t* out_idx) {
#pragma omp parallel
  {
#pragma omp for schedule(dynamic, 1)
    for (int64_t p0 = 0; p0 < npairs; ++p0) {
      if (p0 > 0 && out_idx[p0 - 1] == out_idx[p0]) continue;
      for (int64_t p = p0; p < npairs && out_idx[p] == out_idx[p0]; ++p) {
        const float* a = abase + a_idx[p] * n;
        const float* b = bbase + b_idx[p] * n;
        float* out = obase + out_idx[p] * n;
        const int64_t s = shifts[p];
        const int64_t lo = s < 0 ? -s : 0;
        const int64_t hi = s > 0 ? n - s : n;
        for (int64_t i = lo; i < hi; ++i) out[i] -= a[i] * b[i + s];
      }
    }
  }
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Classic Ruge-Stuben C/F splitting (sequential dynamic measures).
//
// Reference role: amgcl/coarsening/ruge_stuben.hpp cfsplit. Independent
// implementation: a lazy max-heap (stale entries skipped by comparing the
// stored lambda against the current one) instead of the reference's bucket
// arrays; tie-break on the smaller index so the result matches the Python
// fallback in coarsening/ruge_stuben.py exactly.
//
// cf: in/out, one byte per point — 0 undecided, 1 coarse, 2 fine (rows
// without strong connections arrive pre-marked 2).
// ---------------------------------------------------------------------------

#include <queue>
#include <utility>

extern "C" {

void rs_cfsplit(int64_t n, const int64_t* ptr, const int32_t* col,
                const uint8_t* strong, const int64_t* stp,
                const int32_t* stc, int8_t* cf) {
  std::vector<int64_t> lam(n);
  for (int64_t i = 0; i < n; ++i) {
    int64_t t = 0;
    for (int64_t j = stp[i]; j < stp[i + 1]; ++j)
      t += (cf[stc[j]] == 0) ? 1 : 2;
    lam[i] = t;
  }
  // (lambda, -index): max lambda first, smaller index on ties
  std::priority_queue<std::pair<int64_t, int64_t>> pq;
  for (int64_t i = 0; i < n; ++i)
    if (cf[i] == 0) pq.push({lam[i], -i});
  while (!pq.empty()) {
    const int64_t l = pq.top().first;
    const int64_t i = -pq.top().second;
    pq.pop();
    if (cf[i] != 0 || l != lam[i]) continue;  // decided or stale
    if (l == 0) {
      for (int64_t k = 0; k < n; ++k)
        if (cf[k] == 0) cf[k] = 1;
      break;
    }
    cf[i] = 1;
    for (int64_t j = stp[i]; j < stp[i + 1]; ++j) {
      const int64_t c = stc[j];
      if (cf[c] != 0) continue;
      cf[c] = 2;
      // the new F point raises its strong neighbours' lambdas
      for (int64_t aj = ptr[c]; aj < ptr[c + 1]; ++aj) {
        if (!strong[aj]) continue;
        const int64_t ac = col[aj];
        if (cf[ac] == 0 && lam[ac] + 1 < n) pq.push({++lam[ac], -ac});
      }
    }
    // the new C point lowers its strong neighbours' lambdas
    for (int64_t j = ptr[i]; j < ptr[i + 1]; ++j) {
      if (!strong[j]) continue;
      const int64_t c = col[j];
      if (cf[c] == 0 && lam[c] > 0) pq.push({--lam[c], -c});
    }
  }
}

}  // extern "C"
