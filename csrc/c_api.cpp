// C ABI over the amgcl_tpu runtime registry (reference parity:
// /root/reference/lib/amgcl.cpp — opaque handles over the runtime
// interface). The implementation embeds CPython: handles are integer ids
// into a table owned by amgcl_tpu.capi, arrays cross zero-copy as raw
// addresses, and the solves are the ordinary JAX-backed compositions.
//
// Build (see tests/test_c_api.py for the exact line):
//   g++ -O2 -shared -fPIC -std=c++17 -o libamgcl_tpu_c.so c_api.cpp \
//       $(python3-config --includes --ldflags --embed)

#include <Python.h>

#include <cstdint>
#include <cstdio>

#include "../include/amgcl_tpu.h"

namespace {

PyObject* g_mod = nullptr;   // amgcl_tpu.capi

struct Gil {
  PyGILState_STATE st;
  Gil() : st(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(st); }
};

// call capi.<name>(fmt args...) and return the result (new ref, or null
// with the Python error printed)
PyObject* vcall(const char* name, const char* fmt, va_list va) {
  if (!g_mod) return nullptr;
  PyObject* fn = PyObject_GetAttrString(g_mod, name);
  if (!fn) {
    PyErr_Print();
    return nullptr;
  }
  PyObject* args = Py_VaBuildValue(fmt, va);
  PyObject* out = args ? PyObject_CallObject(fn, args) : nullptr;
  Py_XDECREF(args);
  Py_DECREF(fn);
  if (!out) PyErr_Print();
  return out;
}

PyObject* call(const char* name, const char* fmt, ...) {
  va_list va;
  va_start(va, fmt);
  PyObject* out = vcall(name, fmt, va);
  va_end(va);
  return out;
}

int64_t call_i64(const char* name, const char* fmt, ...) {
  va_list va;
  va_start(va, fmt);
  PyObject* out = vcall(name, fmt, va);
  va_end(va);
  if (!out) return 0;
  int64_t v = PyLong_AsLongLong(out);
  Py_DECREF(out);
  return v;
}

intptr_t handle_id(amgclHandle h) { return reinterpret_cast<intptr_t>(h); }

amgclHandle as_handle(int64_t id) {
  return reinterpret_cast<amgclHandle>(static_cast<intptr_t>(id));
}

// The C surface takes int32 CSR arrays; capi.py views them by address.
amgclHandle create(const char* fn_name, int n, const int* ptr,
                   const int* col, const double* val, amgclHandle prm,
                   int one_based) {
  Gil g;
  int64_t id = call_i64(
      fn_name, "(iLLLLi)", n, (long long)(intptr_t)ptr,
      (long long)(intptr_t)col, (long long)(intptr_t)val,
      (long long)handle_id(prm), one_based);
  return as_handle(id);
}

}  // namespace

extern "C" {

int amgcl_tpu_init(void) {
  if (g_mod) return 0;
  const bool we_initialized = !Py_IsInitialized();
  if (we_initialized) Py_InitializeEx(0);
  {
    Gil g;
    // the C surface is f64; enable x64 before any JAX program compiles
    PyRun_SimpleString(
        "import jax; jax.config.update('jax_enable_x64', True)");
    g_mod = PyImport_ImportModule("amgcl_tpu.capi");
    if (!g_mod) {
      PyErr_Print();
      std::fprintf(stderr,
                   "amgcl_tpu_init: cannot import amgcl_tpu.capi "
                   "(set PYTHONPATH to the amgcl_tpu checkout)\n");
      return 1;
    }
  }
  // Py_InitializeEx leaves the GIL held by this thread; release it so C API
  // calls from ANY thread (each using PyGILState_Ensure) don't deadlock.
  if (we_initialized) PyEval_SaveThread();
  return 0;
}

amgclHandle amgcl_tpu_params_create(void) {
  Gil g;
  return as_handle(call_i64("params_create", "()"));
}

void amgcl_tpu_params_seti(amgclHandle prm, const char* name, int value) {
  Gil g;
  Py_XDECREF(call("params_set", "(Lsi)", (long long)handle_id(prm), name,
                  value));
}

void amgcl_tpu_params_setf(amgclHandle prm, const char* name, double value) {
  Gil g;
  Py_XDECREF(call("params_set", "(Lsd)", (long long)handle_id(prm), name,
                  value));
}

void amgcl_tpu_params_sets(amgclHandle prm, const char* name,
                           const char* value) {
  Gil g;
  Py_XDECREF(call("params_set", "(Lss)", (long long)handle_id(prm), name,
                  value));
}

void amgcl_tpu_params_read_json(amgclHandle prm, const char* fname) {
  Gil g;
  Py_XDECREF(call("params_read_json", "(Ls)", (long long)handle_id(prm),
                  fname));
}

void amgcl_tpu_params_destroy(amgclHandle prm) {
  Gil g;
  Py_XDECREF(call("handle_destroy", "(L)", (long long)handle_id(prm)));
}

amgclHandle amgcl_tpu_precond_create(int n, const int* ptr, const int* col,
                                     const double* val, amgclHandle prm) {
  return create("precond_create", n, ptr, col, val, prm, 0);
}

amgclHandle amgcl_tpu_precond_create_f(int n, const int* ptr, const int* col,
                                       const double* val, amgclHandle prm) {
  return create("precond_create", n, ptr, col, val, prm, 1);
}

void amgcl_tpu_precond_apply(amgclHandle p, const double* rhs, double* x) {
  Gil g;
  PyObject* n_obj = call("handle_n", "(L)", (long long)handle_id(p));
  if (!n_obj) return;
  long long n = PyLong_AsLongLong(n_obj);
  Py_DECREF(n_obj);
  Py_XDECREF(call("precond_apply", "(LLLL)", (long long)handle_id(p),
                  (long long)(intptr_t)rhs, (long long)(intptr_t)x, n));
}

void amgcl_tpu_precond_report(amgclHandle p) {
  Gil g;
  PyObject* s = call("report", "(L)", (long long)handle_id(p));
  if (s) {
    std::printf("%s\n", PyUnicode_AsUTF8(s));
    Py_DECREF(s);
  }
}

void amgcl_tpu_precond_destroy(amgclHandle p) {
  Gil g;
  Py_XDECREF(call("handle_destroy", "(L)", (long long)handle_id(p)));
}

amgclHandle amgcl_tpu_solver_create(int n, const int* ptr, const int* col,
                                    const double* val, amgclHandle prm) {
  return create("solver_create", n, ptr, col, val, prm, 0);
}

amgclHandle amgcl_tpu_solver_create_f(int n, const int* ptr, const int* col,
                                      const double* val, amgclHandle prm) {
  return create("solver_create", n, ptr, col, val, prm, 1);
}

struct amgcl_tpu_conv_info amgcl_tpu_solver_solve(amgclHandle s,
                                                  const double* rhs,
                                                  double* x) {
  struct amgcl_tpu_conv_info out = {0, -1.0};
  Gil g;
  PyObject* n_obj = call("handle_n", "(L)", (long long)handle_id(s));
  if (!n_obj) return out;
  long long n = PyLong_AsLongLong(n_obj);
  Py_DECREF(n_obj);
  PyObject* res = call("solver_solve", "(LLLL)", (long long)handle_id(s),
                       (long long)(intptr_t)rhs, (long long)(intptr_t)x, n);
  if (res && PyTuple_Check(res) && PyTuple_Size(res) == 2) {
    out.iterations = (int)PyLong_AsLong(PyTuple_GetItem(res, 0));
    out.residual = PyFloat_AsDouble(PyTuple_GetItem(res, 1));
  }
  Py_XDECREF(res);
  return out;
}

void amgcl_tpu_solver_solve_f(amgclHandle s, const double* rhs, double* x,
                              struct amgcl_tpu_conv_info* cnv) {
  *cnv = amgcl_tpu_solver_solve(s, rhs, x);
}

void amgcl_tpu_solver_report(amgclHandle s) { amgcl_tpu_precond_report(s); }

void amgcl_tpu_solver_destroy(amgclHandle s) {
  amgcl_tpu_precond_destroy(s);
}

}  // extern "C"
