/* C smoke test for the amgcl_tpu C API: assemble a 2-D Poisson problem in
 * plain C (mirrors the reference's examples/call_lib pattern), configure a
 * CG+AMG solver through dotted params, solve, and check the residual. */
#include <math.h>
#include <stdio.h>
#include <stdlib.h>

#include "../include/amgcl_tpu.h"

int main(void) {
    const int m = 24;           /* 24x24 grid -> n = 576 */
    const int n = m * m;
    int* ptr = (int*)malloc((n + 1) * sizeof(int));
    int* col = (int*)malloc(5 * n * sizeof(int));
    double* val = (double*)malloc(5 * n * sizeof(double));
    double* rhs = (double*)malloc(n * sizeof(double));
    double* x = (double*)calloc(n, sizeof(double));

    int idx = 0;
    ptr[0] = 0;
    for (int j = 0; j < m; ++j) {
        for (int i = 0; i < m; ++i) {
            int r = j * m + i;
            if (j > 0) { col[idx] = r - m; val[idx] = -1.0; ++idx; }
            if (i > 0) { col[idx] = r - 1; val[idx] = -1.0; ++idx; }
            col[idx] = r; val[idx] = 4.0; ++idx;
            if (i + 1 < m) { col[idx] = r + 1; val[idx] = -1.0; ++idx; }
            if (j + 1 < m) { col[idx] = r + m; val[idx] = -1.0; ++idx; }
            ptr[r + 1] = idx;
            rhs[r] = 1.0;
        }
    }

    if (amgcl_tpu_init() != 0) {
        fprintf(stderr, "init failed\n");
        return 1;
    }

    amgclHandle prm = amgcl_tpu_params_create();
    amgcl_tpu_params_sets(prm, "solver.type", "cg");
    amgcl_tpu_params_setf(prm, "solver.tol", 1e-8);
    amgcl_tpu_params_seti(prm, "solver.maxiter", 100);
    amgcl_tpu_params_sets(prm, "precond.dtype", "float64");
    amgcl_tpu_params_seti(prm, "precond.coarse_enough", 100);

    amgclHandle slv = amgcl_tpu_solver_create(n, ptr, col, val, prm);
    if (!slv) {
        fprintf(stderr, "solver_create failed\n");
        return 1;
    }
    struct amgcl_tpu_conv_info cnv = amgcl_tpu_solver_solve(slv, rhs, x);
    printf("iters=%d resid=%g\n", cnv.iterations, cnv.residual);

    /* true residual check in C */
    double rn = 0.0, bn = 0.0;
    for (int r = 0; r < n; ++r) {
        double ax = 0.0;
        for (int q = ptr[r]; q < ptr[r + 1]; ++q) ax += val[q] * x[col[q]];
        rn += (rhs[r] - ax) * (rhs[r] - ax);
        bn += rhs[r] * rhs[r];
    }
    double rel = sqrt(rn / bn);
    printf("true relative residual = %g\n", rel);

    amgcl_tpu_solver_destroy(slv);
    amgcl_tpu_params_destroy(prm);
    free(ptr); free(col); free(val); free(rhs); free(x);

    if (!(rel < 1e-7)) {
        fprintf(stderr, "FAIL: residual too large\n");
        return 1;
    }
    printf("C API smoke test OK\n");
    return 0;
}
