"""Local benchmark suite across the problem classes the reference tracks
(BASELINE.md): scalar Poisson, block system, saddle point (Schur),
non-symmetric convection, and the distributed mesh path. Prints a table and
writes benchmarks/RESULTS_<device>.md.

The driver-facing headline benchmark stays in /bench.py (one JSON line);
this suite is for humans comparing configurations.
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

import numpy as np


def timed(fn):
    t0 = time.perf_counter()
    out = fn()
    return time.perf_counter() - t0, out


def main():
    # CPU-only suite: drop the axon plugin's forced registration (its
    # wedged tunnel otherwise hangs backend init even with
    # JAX_PLATFORMS=cpu)
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        from amgcl_tpu.utils.axon_guard import force_cpu_backend
        force_cpu_backend()
    import jax
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    import scipy.sparse as sp

    from amgcl_tpu import make_solver, AMGParams, CSR
    from amgcl_tpu.solver.cg import CG
    from amgcl_tpu.solver.bicgstab import BiCGStab
    from amgcl_tpu.solver.gmres import FGMRES
    from amgcl_tpu.models.schur import SchurPressureCorrection
    from amgcl_tpu.models.cpr import CPR
    from amgcl_tpu.relaxation.ilu0 import ILU0
    from amgcl_tpu.utils.sample_problem import (poisson3d,
                                                convection_diffusion_2d)

    rows = []

    def bench(name, build, solve_args=None):
        t_setup, solver = timed(build)
        rhs = solve_args
        x, info = solver(rhs)                       # compile + solve
        jax.block_until_ready(x)
        t_solve, (x, info) = timed(lambda: solver(rhs))
        jax.block_until_ready(x)
        rows.append((name, t_setup, t_solve, info.iters, float(info.resid)))
        print("%-38s setup %6.2fs solve %6.3fs iters %3d resid %.1e"
              % rows[-1])

    # 1. scalar 3D Poisson, SA + CG + spai0 (the headline config)
    A, rhs = poisson3d(64)
    bench("poisson3d_64 sa+cg+spai0 f32+refine",
          lambda: make_solver(A, AMGParams(dtype=jnp.float32),
                              CG(tol=1e-6), refine=3), rhs)

    # 2. block system (Serena-style value types), spai0
    b = 3
    Ap, _ = poisson3d(16)
    K = sp.kron(Ap.to_scipy(), np.eye(b)).tocsr()
    Ab = CSR.from_scipy(K).to_block(b)
    rb = np.ones(Ab.nrows * b)
    bench("block3x3 sa+cg+spai0 f64",
          lambda: make_solver(Ab, AMGParams(dtype=jnp.float64,
                                            coarse_enough=600),
                              CG(tol=1e-8)), rb)

    # 3. Stokes-type saddle point, Schur pressure correction
    n = 24
    T = sp.diags([-np.ones(n - 1), 2 * np.ones(n), -np.ones(n - 1)],
                 [-1, 0, 1])
    L = (sp.kron(sp.identity(n), T) + sp.kron(T, sp.identity(n))).tocsr()
    nu = L.shape[0]
    Avv = sp.block_diag([L, L]).tocsr()
    D = sp.diags([-np.ones(nu - 1), np.ones(nu)], [-1, 0], shape=(nu, nu))
    B = sp.hstack([D, 0.5 * D]).tocsr()
    Ks = sp.bmat([[Avv, B.T], [B, -1e-2 * sp.identity(nu)]]).tocsr()
    pmask = np.zeros(Ks.shape[0], dtype=bool)
    pmask[2 * nu:] = True
    rs = np.ones(Ks.shape[0])
    bench("stokes schur_pc + fgmres f64",
          lambda: make_solver(
              Ks, SchurPressureCorrection(
                  Ks, pmask, AMGParams(dtype=jnp.float64),
                  AMGParams(dtype=jnp.float64), dtype=jnp.float64),
              FGMRES(maxiter=300, tol=1e-8)), rs)

    # 4. non-symmetric convection-diffusion, ILU0 + BiCGStab
    Ac, rc = convection_diffusion_2d(96, eps=0.02)
    bench("convection96 ilu0+bicgstab f64",
          lambda: make_solver(Ac, AMGParams(relax=ILU0(),
                                            dtype=jnp.float64),
                              BiCGStab(maxiter=200, tol=1e-8)), rc)

    # 5. distributed AMG over the local mesh
    from amgcl_tpu.parallel.mesh import make_mesh
    from amgcl_tpu.parallel.dist_amg import DistAMGSolver
    mesh = make_mesh()
    Am, rm = poisson3d(32)
    bench("dist poisson3d_32 over %d devices" % len(jax.devices()),
          lambda: DistAMGSolver(Am, mesh, AMGParams(dtype=jnp.float64),
                                CG(tol=1e-8)), rm)

    dev = jax.devices()[0].platform
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "RESULTS_%s.md" % dev)
    with open(path, "w") as f:
        f.write("# Benchmark results (%s)\n\n" % jax.devices()[0])
        f.write("| case | setup (s) | solve (s) | iters | resid |\n")
        f.write("|---|---|---|---|---|\n")
        for r in rows:
            f.write("| %s | %.2f | %.3f | %d | %.1e |\n" % r)
    print("\nwrote", path)


if __name__ == "__main__":
    main()
