"""Targeted chip-session decomposition of the headline solve.

Runs ONE arm per invocation (env knobs are read at import/probe time, so
each arm needs a fresh process):

    python benchmarks/chip_probe.py baseline      # default config
    python benchmarks/chip_probe.py noplas        # AMGCL_TPU_PALLAS=0
    python benchmarks/chip_probe.py nofuse        # AMGCL_TPU_FUSED_VCYCLE=0
    python benchmarks/chip_probe.py diadb         # AMGCL_TPU_DIA_DB=1
    python benchmarks/chip_probe.py norefine      # refine=0 (no f64 pass)

Each arm builds the 128^3 Poisson SA+CG+SPAI0 solver, reports which fused
tiers engaged (+ the probe-decline log), and times the solve PER CALL
(median of 5, minus a jitted-scalar dispatch floor) — the dispatch-free
chained scan 413s on the tunnel's remote_compile, so per-call result
fetch and residual RTT jitter remain in solve_s: read arm DELTAS at the
10 ms+ scale, not absolute device time (benchmarks/chained_solve.py has
the honest chained number for the default config). Appends one JSON line
to /tmp/chip_probe_results.jsonl.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_ARMS = {
    "baseline": {},
    "noplas": {"AMGCL_TPU_PALLAS": "0"},
    "nofuse": {"AMGCL_TPU_FUSED_VCYCLE": "0"},
    "diadb": {"AMGCL_TPU_DIA_DB": "1"},
    # refine=0: drop the f64 outer residual (emulated f64 on TPU streams
    # the fine operator at software speed even when zero restarts fire)
    "norefine": {},
}


def main():
    arm = sys.argv[1] if len(sys.argv) > 1 else "baseline"
    os.environ.update(_ARMS[arm])
    os.environ.setdefault("AMGCL_TPU_PROBE_VERBOSE", "1")
    n = int(os.environ.get("AMGCL_TPU_BENCH_N", "128"))

    import numpy as np
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(os.path.dirname(os.path.dirname(
                          os.path.abspath(__file__))), ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from amgcl_tpu.utils.sample_problem import poisson3d
    from amgcl_tpu.models.make_solver import make_solver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.cg import CG
    from amgcl_tpu.ops.pallas_spmv import PROBE_DECLINES

    rec = {"arm": arm, "n": n,
           "platform": jax.devices()[0].platform}
    A, rhs = poisson3d(n)
    t0 = time.perf_counter()
    solver = make_solver(A, AMGParams(dtype=jnp.float32),
                         CG(maxiter=100, tol=1e-6),
                         refine=0 if arm == "norefine" else 3)
    rec["setup_s"] = round(time.perf_counter() - t0, 3)
    rec["fused_levels"] = " ".join(
        "%d%s%s" % (i, "d" if lv.down is not None else "",
                    "u" if lv.up is not None else "")
        for i, lv in enumerate(solver.precond.hierarchy.levels)
        if lv.down is not None or lv.up is not None)
    rec["declines"] = [list(d) for d in PROBE_DECLINES[:10]]

    rhs_dev = jnp.asarray(rhs, jnp.float32)
    x, info = solver(rhs_dev)
    jax.block_until_ready(x)
    rec["iters"] = int(info.iters)

    # dispatch-overhead floor (the tunneled per-call sync), subtracted
    # from plain per-call timing. Chained-scan timing would be cleaner
    # but the tunnel's remote_compile endpoint 413s on the large fresh
    # chain HLO; at the 100ms+ scale under study the per-call floor is
    # a small correction.
    g = jax.jit(lambda s: s * 2.0)
    float(g(jnp.float32(1.0)))
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        float(g(jnp.float32(1.0)))
        ts.append(time.perf_counter() - t0)
    overhead = float(np.median(ts))
    rec["dispatch_overhead_s"] = round(overhead, 4)

    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        x, info = solver(rhs_dev)
        jax.block_until_ready(x)
        ts.append(time.perf_counter() - t0)
    rec["solve_s"] = round(max(float(np.median(ts)) - overhead, 0.0), 4)
    rec["ms_per_iter"] = round(rec["solve_s"] / max(rec["iters"], 1)
                               * 1e3, 2)
    line = json.dumps(rec)
    print(line)
    with open("/tmp/chip_probe_results.jsonl", "a") as f:
        f.write(line + "\n")


if __name__ == "__main__":
    main()
