"""On-chip decomposition of ONE V-cycle at the headline problem: times
hierarchy.apply and each level-0/1 building block with two-length
difference chains (small programs — the tunnel's remote_compile size
limit only bites on whole-solve chains).

Usage: python benchmarks/cycle_parts.py [n]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 128

    import numpy as np
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(os.path.dirname(os.path.dirname(
                          os.path.abspath(__file__))), ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    import jax.numpy as jnp
    from jax import lax

    from amgcl_tpu.utils.sample_problem import poisson3d
    from amgcl_tpu.models.amg import AMG, AMGParams
    from amgcl_tpu.ops import device as dev

    m = AMG(poisson3d(n)[0], AMGParams(dtype=jnp.float32))
    hier = m.hierarchy

    def diff_time(fn, x0, aux=None, reps=(5, 20)):
        """fn(aux, v) -> v'; ``aux`` (a pytree, e.g. the hierarchy or a
        level) rides through jit as an ARGUMENT — closing over it would
        embed the operator data as MLIR constants (~60 MB/diagonal set)
        and overflow the tunnel's remote_compile upload limit."""
        def chain(r):
            def many(a, x):
                def body(c, _):
                    return fn(a, c) * 0.5 + x, None
                out, _ = lax.scan(body, x, None, length=r)
                return out.sum()
            f = jax.jit(many)
            float(f(aux, x0))
            ts = []
            for _ in range(5):
                t0 = time.perf_counter()
                float(f(aux, x0))
                ts.append(time.perf_counter() - t0)
            return float(np.median(ts))
        return max(chain(reps[1]) - chain(reps[0]), 0.0) / (reps[1]
                                                            - reps[0])

    out = {"n": n, "platform": jax.devices()[0].platform}
    nf = hier.levels[0].A.shape[0]
    rng = np.random.RandomState(0)
    r0 = jnp.asarray(rng.rand(nf), jnp.float32)

    out["vcycle_ms"] = round(diff_time(
        lambda h, v: h.apply(v), r0, aux=hier) * 1e3, 3)

    for li in range(min(2, len(hier.levels) - 1)):
        lv = hier.levels[li]
        nl = lv.A.shape[0]
        nc = lv.R.shape[0]
        f = jnp.asarray(rng.rand(nl), jnp.float32)
        u = jnp.asarray(rng.rand(nl), jnp.float32)
        L = {}
        L["presmooth_us"] = round(diff_time(
            lambda a, v: a.relax.apply_pre(a.A, f, v), u, aux=lv) * 1e6, 1)
        L["resid_us"] = round(diff_time(
            lambda a, v: dev.residual(f, a.A, v), u, aux=lv) * 1e6, 1)
        L["restrict_us"] = round(diff_time(
            lambda a, v: jnp.pad(a.R.mv(v), (0, nl - nc)), u,
            aux=lv) * 1e6, 1)
        L["prolong_us"] = round(diff_time(
            lambda a, v: a.P.mv(v[:nc]), u, aux=lv) * 1e6, 1)
        L["spmv_us"] = round(diff_time(
            lambda a, v: a.A.mv(v), u, aux=lv) * 1e6, 1)
        if hasattr(dev, "spmv_dots"):
            L["spmv_dots_us"] = round(diff_time(
                lambda a, v: dev.spmv_dots(a.A, v, None)[0], u,
                aux=lv) * 1e6, 1)
        if lv.down is not None:
            L["fused_down_us"] = round(diff_time(
                lambda a, v: jnp.pad(a.down(f, v).reshape(-1),
                                     (0, nl - nc)), u, aux=lv) * 1e6, 1)
        if lv.up is not None:
            L["fused_up_us"] = round(diff_time(
                lambda a, v: a.up(f, v, v[:nc]), u, aux=lv) * 1e6, 1)
        out["level%d" % li] = L

    line = json.dumps(out)
    print(line)
    with open("/tmp/cycle_parts.jsonl", "a") as f:
        f.write(line + "\n")


if __name__ == "__main__":
    main()
