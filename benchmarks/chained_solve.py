"""Honest device-time measurement of the headline solve: chains N
data-dependent solves inside one jitted scan (operators as jit args, so
the upload stays small) and reports the two-length difference — no
dispatch, no fetch, no RTT in the number.

Usage: python benchmarks/chained_solve.py [n]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 128

    import numpy as np
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(os.path.dirname(os.path.dirname(
                          os.path.abspath(__file__))), ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    from jax import lax

    from amgcl_tpu.utils.sample_problem import poisson3d
    from amgcl_tpu.models.make_solver import make_solver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.cg import CG

    A, rhs = poisson3d(n)
    solver = make_solver(A, AMGParams(dtype=jnp.float32),
                         CG(maxiter=100, tol=1e-6), refine=3)
    rhs_dev = jnp.asarray(rhs, jnp.float32)
    x0 = jnp.zeros_like(rhs_dev)
    x, info = solver(rhs_dev)
    jax.block_until_ready(x)

    ops = (solver.A_dev, solver.A_dev64, solver.precond.hierarchy)

    def chain(r):
        def many(args):
            A_dev, A_dev64, hier = args

            def one(c):
                got = solver._solve_fn(A_dev, A_dev64, hier,
                                       rhs_dev + 0 * c, x0)
                return got[0].astype(jnp.float32)

            def body(c, _):
                return one(c), None
            out, _ = lax.scan(body, one(x0 * 0), None, length=r - 1)
            return out.sum()
        f = jax.jit(many)
        float(f(ops))
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            float(f(ops))
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts))

    t = max(chain(4) - chain(1), 0.0) / 3
    rec = {"n": n, "platform": jax.devices()[0].platform,
           "iters": int(info.iters), "solve_s": round(t, 4),
           "ms_per_iter": round(t / max(int(info.iters), 1) * 1e3, 2),
           "fused_levels": " ".join(
               "%d%s%s" % (i, "d" if lv.down is not None else "",
                           "u" if lv.up is not None else "")
               for i, lv in enumerate(solver.precond.hierarchy.levels)
               if lv.down is not None or lv.up is not None)}
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
