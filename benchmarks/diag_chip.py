import os, sys, time
os.environ["AMGCL_TPU_PROBE_VERBOSE"] = "1"
os.environ["AMGCL_TPU_PROFILE_SETUP"] = "1"
sys.path.insert(0, "/root/repo")
if os.environ.get("DIAG_CPU") == "1":
    from amgcl_tpu.utils import axon_guard
    axon_guard.force_cpu_backend()
import jax
jax.config.update("jax_enable_x64", True)
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
import jax.numpy as jnp
import numpy as np
print("backend:", jax.default_backend(), flush=True)

step = sys.argv[1]

def timed_chain(solver, rhs_dev, x0, reps=4, repeats=3):
    from jax import lax
    def one(c):
        r = rhs_dev if c is None else rhs_dev + 0 * c
        got = solver._solve_fn(solver.A_dev, solver.A_dev64,
                               solver.precond.hierarchy, r, x0)
        return got[0].astype(jnp.float32)
    def many():
        def body(c, _):
            return one(c), None
        out, _ = lax.scan(body, one(None), None, length=reps - 1)
        return out.sum()
    f = jax.jit(many)
    float(f())
    ts = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        float(f())
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts)) / reps

if step == "fused":
    from amgcl_tpu.utils.sample_problem import poisson3d
    from amgcl_tpu.models.make_solver import make_solver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.cg import CG
    A, rhs = poisson3d(128)
    rhs_dev = jnp.asarray(rhs, jnp.float32)
    x0 = jnp.zeros_like(rhs_dev)
    t0 = time.time()
    s3 = make_solver(A, AMGParams(dtype=jnp.float32),
                     CG(maxiter=100, tol=1e-6), refine=3)
    print("setup(refine=3) %.1fs" % (time.time() - t0), flush=True)
    for i, lv in enumerate(s3.precond.hierarchy.levels):
        print("level", i, "down:", getattr(lv, "down", None) is not None,
              "up:", getattr(lv, "up", None) is not None, flush=True)
    x, info = s3(rhs_dev)
    jax.block_until_ready(x)
    print("refine=3 iters=%d resid=%.2e" % (info.iters, info.resid),
          flush=True)
    t3 = timed_chain(s3, rhs_dev, x0)
    print("refine=3 chained %.4f s/solve" % t3, flush=True)
    t0 = time.time()
    s0 = make_solver(A, AMGParams(dtype=jnp.float32),
                     CG(maxiter=100, tol=1e-6), refine=0)
    print("setup(refine=0) %.1fs" % (time.time() - t0), flush=True)
    x, info = s0(rhs_dev)
    jax.block_until_ready(x)
    tr = float(np.linalg.norm(rhs - A.spmv(np.asarray(x, np.float64)))
               / np.linalg.norm(rhs))
    print("refine=0 iters=%d resid=%.2e true=%.2e" % (
        info.iters, info.resid, tr), flush=True)
    t0v = timed_chain(s0, rhs_dev, x0)
    print("refine=0 chained %.4f s/solve" % t0v, flush=True)
elif step == "well":
    from amgcl_tpu.ops.unstructured import kernel_supported
    for k in ("spmv", "fused", "dots"):
        t0 = time.time()
        ok = kernel_supported(win=1 << 14, K=4, kernel=k)
        print("well[%s] supported=%s (%.1fs)" % (k, ok, time.time() - t0),
              flush=True)
    # block variant too (the bench's block3 stage wedged the r5 worker)
    t0 = time.time()
    ok = kernel_supported(win=1 << 13, K=4, block=(3, 3), kernel="spmv")
    print("well[block3 spmv] supported=%s (%.1fs)" % (ok, time.time() - t0),
          flush=True)
elif step == "stall":
    from amgcl_tpu.ops.csr import CSR
    z = np.load("/root/repo/.bench_fe_cache.npz")
    A = CSR(z["ptr"], z["col"], z["val"], int(z["n"]))
    from amgcl_tpu.models.make_solver import make_solver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.bicgstab import BiCGStab
    t0 = time.time()
    try:
        s = make_solver(A, AMGParams(dtype=jnp.float32),
                        BiCGStab(maxiter=300, tol=1e-8), refine=2)
        print("setup ok %.1fs; levels=%d" % (
            time.time() - t0,
            len(s.precond.hierarchy.levels)), flush=True)
        for i, lv in enumerate(s.precond.hierarchy.levels):
            print("  level", i, "n=%d" % lv.A.shape[0], flush=True)
    except Exception as e:
        print("SETUP FAILED after %.1fs: %r" % (time.time() - t0, e),
              flush=True)
