"""Tile-size sweep for the DIA Pallas kernels at the headline shapes.

The r5 chip artifact measured dia_spmv at 271 us on the 128^3 fine level
— almost exactly the window-redundancy model's prediction for tile=2048:
each tile DMAs a (tile + 2*16384)-element x window, 17.5x the tile's own
rows, so adjacent tiles refetch the z-halo over and over. Larger tiles
amortize the halo (32768 -> 2x, 131072 -> 1.25x) at the cost of a bigger
VMEM footprint (win*4B + ndiag*tile*4B per grid step; cap ~12 MB).

Runs on whatever backend answers; only TPU numbers matter. One JSON line
per (level, tile, db) to stdout and /tmp/dia_tile_sweep.jsonl.

Usage: python benchmarks/dia_tile_sweep.py [n]
"""

import itertools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 128

    import numpy as np
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(os.path.dirname(os.path.dirname(
                          os.path.abspath(__file__))), ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    import jax.numpy as jnp
    from jax import lax

    from amgcl_tpu.utils.sample_problem import poisson3d
    from amgcl_tpu.models.amg import AMG, AMGParams
    from amgcl_tpu.ops.device import DiaMatrix
    from amgcl_tpu.ops.pallas_spmv import dia_spmv, dia_residual

    platform = jax.devices()[0].platform
    interpret = platform != "tpu"

    m = AMG(poisson3d(n)[0], AMGParams(dtype=jnp.float32))
    levels = [lv.A for lv in m.hierarchy.levels
              if isinstance(lv.A, DiaMatrix)]

    def diff_time(fn, x0, aux, reps=(10, 60)):
        """fn(aux, v) -> v'; aux (operator data pytree) rides through jit
        as an ARGUMENT — closed-over operator arrays become MLIR
        constants and blow the tunnel's remote_compile upload limit."""
        def chain(r):
            def many(a, x):
                def body(c, _):
                    return fn(a, c) * 0.5 + x, None
                out, _ = lax.scan(body, x, None, length=r)
                return out.sum()
            f = jax.jit(many)
            float(f(aux, x0))
            ts = []
            for _ in range(5):
                t0 = time.perf_counter()
                float(f(aux, x0))
                ts.append(time.perf_counter() - t0)
            return float(np.median(ts))
        return max(chain(reps[1]) - chain(reps[0]), 0.0) / (reps[1]
                                                            - reps[0])

    out_path = "/tmp/dia_tile_sweep.jsonl"
    for li, A in enumerate(levels):
        nrows = A.shape[0]
        x = jnp.asarray(np.random.RandomState(li).rand(nrows), jnp.float32)
        f = jnp.asarray(np.random.RandomState(99).rand(nrows), jnp.float32)
        H = max(abs(o) for o in A.offsets)
        for tile, db in itertools.product(
                (2048, 8192, 32768, 131072), (False, True)):
            if tile > max(2048, nrows):
                continue
            win_b = (tile + 2 * H + 2048) * 4 * (2 if db else 1)
            dia_b = len(A.offsets) * tile * 4
            if win_b + dia_b > 12 << 20:     # VMEM cap, mirrors the kernel
                continue
            try:
                offs = A.offsets
                spmv_us = diff_time(
                    lambda a, v: dia_spmv(offs, a[0], v, tile=tile,
                                          interpret=interpret, db=db),
                    x, (A.data,)) * 1e6
                resid_us = diff_time(
                    lambda a, v: dia_residual(offs, a[0], a[1], v,
                                              tile=tile,
                                              interpret=interpret,
                                              db=db), x, (A.data, f)) * 1e6
                rec = {"level": li, "rows": nrows,
                       "ndiag": len(A.offsets), "halo": H, "tile": tile,
                       "db": db, "spmv_us": round(spmv_us, 1),
                       "resid_us": round(resid_us, 1),
                       "platform": platform}
            except Exception as e:
                rec = {"level": li, "tile": tile, "db": db,
                       "error": repr(e)[:200]}
            line = json.dumps(rec)
            print(line, flush=True)
            with open(out_path, "a") as fh:
                fh.write(line + "\n")


if __name__ == "__main__":
    main()
