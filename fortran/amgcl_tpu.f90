! Fortran bindings for the amgcl_tpu C API (include/amgcl_tpu.h), in the
! iso_c_binding style of the reference's fortran module
! (/root/reference/fortran/amgcl.f90 — independent declaration set for our
! own C surface). Use the *_f creators: they take 1-based ptr/col arrays.
module amgcl_tpu
    use iso_c_binding
    implicit none

    type, bind(c) :: conv_info
        integer(c_int)  :: iterations
        real(c_double)  :: residual
    end type

    interface
        integer(c_int) function amgcl_tpu_init() bind(c)
            use iso_c_binding
        end function

        type(c_ptr) function amgcl_tpu_params_create() bind(c)
            use iso_c_binding
        end function

        subroutine amgcl_tpu_params_seti(prm, name, val) bind(c)
            use iso_c_binding
            type(c_ptr), value :: prm
            character(c_char), intent(in) :: name(*)
            integer(c_int), value :: val
        end subroutine

        subroutine amgcl_tpu_params_setf(prm, name, val) bind(c)
            use iso_c_binding
            type(c_ptr), value :: prm
            character(c_char), intent(in) :: name(*)
            real(c_double), value :: val
        end subroutine

        subroutine amgcl_tpu_params_sets(prm, name, val) bind(c)
            use iso_c_binding
            type(c_ptr), value :: prm
            character(c_char), intent(in) :: name(*)
            character(c_char), intent(in) :: val(*)
        end subroutine

        subroutine amgcl_tpu_params_destroy(prm) bind(c)
            use iso_c_binding
            type(c_ptr), value :: prm
        end subroutine

        type(c_ptr) function amgcl_tpu_solver_create_f(n, ptr, col, val, &
                prm) bind(c)
            use iso_c_binding
            integer(c_int), value :: n
            integer(c_int), intent(in) :: ptr(*)
            integer(c_int), intent(in) :: col(*)
            real(c_double), intent(in) :: val(*)
            type(c_ptr), value :: prm
        end function

        subroutine amgcl_tpu_solver_solve_f(solver, rhs, x, cnv) bind(c)
            use iso_c_binding
            import :: conv_info
            type(c_ptr), value :: solver
            real(c_double), intent(in) :: rhs(*)
            real(c_double), intent(inout) :: x(*)
            type(conv_info), intent(out) :: cnv
        end subroutine

        subroutine amgcl_tpu_solver_destroy(solver) bind(c)
            use iso_c_binding
            type(c_ptr), value :: solver
        end subroutine
    end interface
end module amgcl_tpu
