"""Headline benchmark: 3D Poisson 128^3 (2,097,152 unknowns, ~14.6M nnz),
smoothed aggregation + CG + spai0 — the reference's shared-memory benchmark
configuration (docs/benchmarks.rst:60-79, BASELINE.json configs[0]).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Baseline: the reference's CUDA backend on a Tesla K80 solves the 150^3
problem in 0.55 s (BASELINE.md; docs/smem_data/poisson/amgcl-cuda.txt:1).
Scaled to 128^3 by problem size that is 0.55*(128/150)^3 = 0.342 s, the
number a single TPU chip must beat. vs_baseline = baseline_time / our_time
(>1 means faster than the K80 reference).
"""

import json
import os
import threading
import time

import numpy as np

# AMGCL_TPU_BENCH_N overrides the problem size (default 128; 150 compares
# against the K80 baseline at its native size instead of volume-scaled)
_N = int(os.environ.get("AMGCL_TPU_BENCH_N", "128"))
_METRIC = "poisson3d_%d_sa_cg_spai0_solve_time" % _N

_T0 = time.time()
_STAGES = []           # (name, start_ts) — progress stamps for the watchdog
_PARTIAL = {}          # results already secured; emitted even on a wedge


def _stage(name):
    _STAGES.append((name, time.time()))


def _watchdog(init_timeout_s: float = 240.0, total_timeout_s: float = None):
    """The axon TPU tunnel can wedge at ANY point — backend init, a
    compile, or an execute can block forever (both failure modes observed
    in this image). Two deadlines, both emitting a diagnostic JSON line
    and hard-exiting instead of hanging the driver:

    - init: jax.devices() must return within ``init_timeout_s``;
    - total: the whole bench must finish within ``total_timeout_s``
      (env AMGCL_TPU_BENCH_DEADLINE, default 1500s), with the error
      naming the last stage reached so a wedge mid-compile is
      distinguishable from a wedge at init."""
    if total_timeout_s is None:
        total_timeout_s = float(os.environ.get(
            "AMGCL_TPU_BENCH_DEADLINE", "1500"))
    done = threading.Event()

    def bail(err):
        import sys
        stamps = {n: round(t - _T0, 1) for n, t in _STAGES}
        out = {
            "metric": _METRIC,
            "value": None, "unit": "s", "vs_baseline": None,
            "error": err, "stages_reached": stamps,
        }
        # a wedge after the headline solve still reports the real number
        out.update(_PARTIAL)
        print(json.dumps(out))
        sys.stdout.flush()
        os._exit(2)

    def probe():
        import jax
        jax.devices()
        done.set()

    t = threading.Thread(target=probe, daemon=True)
    t.start()

    def total_guard():
        left = total_timeout_s - (time.time() - _T0)
        if left > 0:
            time.sleep(left)
        last = _STAGES[-1][0] if _STAGES else "start"
        bail("bench wedged during '%s' (%.0fs deadline; TPU tunnel "
             "stalled mid-run)" % (last, total_timeout_s))

    threading.Thread(target=total_guard, daemon=True).start()
    if not done.wait(init_timeout_s):
        bail("device backend init timed out after %.0fs "
             "(TPU tunnel unreachable)" % init_timeout_s)


def _bench_levels(solver):
    """Per-level SpMV timings: XLA lowering vs the Pallas DIA kernel where
    the level is DIA-formatted (VERDICT round-1 ask: per-level
    kernel-vs-XLA numbers so format/kernel choices are measured, not
    guessed). Each measurement chains 50 SpMVs inside ONE jitted scan and
    fetches a scalar, because per-dispatch sync overhead through the axon
    tunnel (~70ms) swamps a single op and block_until_ready does not
    actually block there. Returns a list of dicts."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from amgcl_tpu.ops.device import DiaMatrix
    from amgcl_tpu.ops.pallas_spmv import dia_spmv

    reps = 50

    def timeit(fn, x):
        def many(x):
            def body(c, _):
                return fn(c) * 0.5, None
            out, _ = lax.scan(body, x, None, length=reps)
            return out.sum()

        f = jax.jit(many)
        v = float(f(x))                       # compile + warm
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            v = float(f(x))
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts))

    tiny = jnp.zeros(8, jnp.float32)
    overhead = timeit(lambda c: c, tiny)

    out = []
    for li, lv in enumerate(solver.precond.hierarchy.levels):
        M = lv.A
        if M.shape[0] != M.shape[1]:
            continue
        n_cols = M.shape[1] * getattr(M, "block", (1, 1))[1]
        x = jnp.asarray(np.random.RandomState(li).rand(n_cols),
                        dtype=jnp.float32)
        saved = os.environ.get("AMGCL_TPU_PALLAS")
        os.environ["AMGCL_TPU_PALLAS"] = "0"   # mv() gates on this at trace
        try:
            t_x = timeit(M.mv, x)
        finally:
            if saved is None:
                del os.environ["AMGCL_TPU_PALLAS"]
            else:
                os.environ["AMGCL_TPU_PALLAS"] = saved
        row = {"level": li, "format": type(M).__name__,
               "rows": int(M.shape[0]),
               "xla_us": round(max(t_x - overhead, 0.0) / reps * 1e6, 1)}
        if isinstance(M, DiaMatrix):
            offs = tuple(M.offsets)
            # interpret mode off-TPU keeps the CPU smoke path alive; its
            # timings are meaningless and marked as such
            interp = jax.default_backend() != "tpu"
            row["ndiag"] = len(offs)
            row["pallas_us"] = round(max(timeit(
                lambda v: dia_spmv(offs, M.data, v, interpret=interp), x)
                - overhead, 0.0) / reps * 1e6, 1)
            if interp:
                row["pallas_interpret_mode"] = True
            else:
                row["winner"] = "pallas" \
                    if row["pallas_us"] < row["xla_us"] else "xla"
        out.append(row)
    return out


def main():
    _stage("device init")
    _watchdog()
    import jax
    # x64 so the refinement's outer residual really is float64 (the
    # correction solves stay float32)
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    from amgcl_tpu.utils.sample_problem import poisson3d
    from amgcl_tpu.models.make_solver import make_solver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.cg import CG

    n = _N
    _stage("problem gen")
    t0 = time.perf_counter()
    A, rhs = poisson3d(n)
    t_gen = time.perf_counter() - t0

    _stage("hierarchy setup")
    t0 = time.perf_counter()
    solver = make_solver(A, AMGParams(dtype=jnp.float32),
                         CG(maxiter=100, tol=1e-6), refine=3)
    t_setup = time.perf_counter() - t0

    rhs_dev = jnp.asarray(rhs, dtype=jnp.float32)

    def timed(tag):
        x, info = solver(rhs_dev)           # warmup/compile
        jax.block_until_ready(x)
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            x, info = solver(rhs_dev)
            jax.block_until_ready(x)
            times.append(time.perf_counter() - t0)
        return float(np.median(times)), x, info

    import os
    from amgcl_tpu.ops.pallas_spmv import pallas_enabled
    # Pallas DIA kernel is the default on TPU (AMGCL_TPU_PALLAS=0 opts
    # out); also time the pure-XLA lowering for the record and keep
    # whichever is faster
    on_tpu = jax.default_backend() == "tpu"
    primary_path = "pallas" if on_tpu and pallas_enabled() else "xla"
    _stage("solve compile+run (%s)" % primary_path)
    t_solve, x, info = timed(primary_path)
    spmv_path = primary_path
    baseline = 0.55 * (n / 150.0) ** 3   # K80 CUDA solve, size-scaled
    _PARTIAL.update({
        "value": round(t_solve, 4),
        "vs_baseline": round(baseline / t_solve, 3),
        "iters": int(info.iters), "resid": float(info.resid),
        "setup_s": round(t_setup, 3), "gen_s": round(t_gen, 3),
        "spmv_path": spmv_path, "device": str(jax.devices()[0])})
    t_xla = None
    if on_tpu and primary_path == "pallas":
        _stage("solve compile+run (xla compare)")
        saved = os.environ.get("AMGCL_TPU_PALLAS")
        os.environ["AMGCL_TPU_PALLAS"] = "0"
        solver._compiled = None
        try:
            t_xla, x2, info2 = timed("xla")
            if t_xla < t_solve:
                t_solve, x, info, spmv_path = t_xla, x2, info2, "xla"
        except Exception:
            pass
        finally:
            if saved is None:
                del os.environ["AMGCL_TPU_PALLAS"]
            else:
                os.environ["AMGCL_TPU_PALLAS"] = saved
            solver._compiled = None

    true_res = float(np.linalg.norm(rhs - A.spmv(np.asarray(x, np.float64)))
                     / np.linalg.norm(rhs))
    _PARTIAL.update({
        "value": round(t_solve, 4),
        "vs_baseline": round(baseline / t_solve, 3),
        "iters": int(info.iters), "resid": float(info.resid),
        "true_resid": true_res, "spmv_path": spmv_path,
        "xla_solve_s": round(t_xla, 4) if t_xla else None})

    levels = None
    if jax.default_backend() == "tpu" or os.environ.get(
            "AMGCL_TPU_BENCH_LEVELS") == "1":
        _stage("per-level timings")
        try:
            levels = _bench_levels(solver)
        except Exception as e:       # per-level timing must never kill the
            levels = [{"error": repr(e)}]   # headline number
    out = {"metric": _METRIC, "unit": "s"}
    out.update(_PARTIAL)
    out["levels"] = levels
    print(json.dumps(out))


if __name__ == "__main__":
    main()
