"""Headline benchmark: 3D Poisson 128^3 (2,097,152 unknowns, ~14.6M nnz),
smoothed aggregation + CG + spai0 — the reference's shared-memory benchmark
configuration (docs/benchmarks.rst:60-79, BASELINE.json configs[0]).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Baselines (BASELINE.md; docs/smem_data/poisson/amgcl-cuda.txt:1): the
reference's CUDA backend on a Tesla K80 solves the 150^3 problem in 0.55 s
and sets it up in 1.33 s. Volume-scaled to N^3: solve 0.55*(N/150)^3,
setup 1.33*(N/150)^3. vs_baseline = baseline_time / our_time (>1 = faster
than the K80 reference).

Architecture (round-3 rework): the axon TPU tunnel comes and goes — backend
init, a compile, or an execute can block forever. So this file is a
SUPERVISOR that never imports jax itself: it probes device init in a
subprocess, retries for the WHOLE deadline, runs the measurement in a
killable WORKER subprocess, persists every good TPU run to
BENCH_LAST_GOOD.json, and embeds the last-good result in any failure JSON.

    python bench.py                 # supervisor (what the driver runs)
    python bench.py --worker        # one measurement pass (internal)
    python bench.py --opportunistic # background loop: bench whenever the
                                    # tunnel is alive, refresh last-good
    python bench.py --check [paths] # run the tier-1 pytest line and emit
                                    # a JSONL record with DOTS_PASSED
                                    # (also runs the regression gate and
                                    # attaches the cross-round trend +
                                    # roofline/compile summaries)
    python bench.py --gate [cand]   # regression gate: compare a candidate
                                    # record (default: the last-good run
                                    # itself) against BENCH_LAST_GOOD.json
                                    # under AMGCL_TPU_GATE_* tolerances;
                                    # exit nonzero on regression
    python bench.py --trend [sink.jsonl]
                                    # cross-round trajectory: the headline
                                    # fields of BENCH_r*.json as a table +
                                    # percentile rollups (p50/p90/p99),
                                    # optionally rolling up a JSONL sink
                                    # file too; --prom PATH additionally
                                    # writes Prometheus exposition text.
                                    # Rounds that regressed beyond the
                                    # gate's time tolerance gain a 'why'
                                    # column — the top attributed stage
                                    # from telemetry/diff.py ('-' when
                                    # the older round predates per-stage
                                    # data)
    python bench.py --why A.json B.json
                                    # cross-run regression attribution:
                                    # compare two records of the same
                                    # kind (bench worker records, solve
                                    # reports, or multichip records)
                                    # stage by stage and decompose the
                                    # wall/iters/bytes delta into ranked
                                    # per-stage contributions
                                    # (telemetry/diff.py); emits ONE
                                    # bench_why JSONL record
    python bench.py --vecbench [n ...]
                                    # microbenchmark: fused vector kernels
                                    # (ops/fused_vec.py) vs the composed
                                    # axpby+dot per vector size (including
                                    # the stacked (n, B) tier), emitted
                                    # as a bench_vecbench JSONL record
    python bench.py --scaling       # distributed scaling harness: weak +
                                    # strong sweeps over the mesh (8
                                    # virtual CPU devices forced where no
                                    # TPU is attached) for dist CG /
                                    # pipelined CG / dist AMG, with
                                    # measured comm attribution, per-shard
                                    # imbalance and the collective-census
                                    # cross-check; emits ONE structured
                                    # multichip_scaling record and writes
                                    # MULTICHIP_LATEST.json — the --gate /
                                    # --check candidate scored against the
                                    # previous round's MULTICHIP_r*.json
                                    # (AMGCL_TPU_GATE_MULTICHIP)
    python bench.py --throughput [B ...]
                                    # serving throughput: solves/sec of the
                                    # stacked multi-RHS path at B in
                                    # {1, 8, 32} (or the given list) vs the
                                    # honest un-chained single-solve rate;
                                    # emitted as a bench_throughput JSONL
                                    # record and gated round-over-round via
                                    # AMGCL_TPU_GATE_THROUGHPUT
    python bench.py --farm [T [R]]  # multi-tenant farm throughput: T
                                    # tenants (default 3) with distinct
                                    # operators round-robined R rounds
                                    # (default 6) through one SolverFarm
                                    # under an eviction-forcing byte
                                    # budget; aggregate solves/sec +
                                    # per-tenant p99 + eviction counts,
                                    # emitted as a bench_farm JSONL record
                                    # and gated round-over-round via
                                    # AMGCL_TPU_GATE_FARM
    python bench.py --storm [--smoke] [--trace PATH]
                                    # OPEN-LOOP load harness
                                    # (serve/storm.py): a seeded Poisson
                                    # offered-load ladder + a mixed
                                    # poisson/burst/ramp profile storm
                                    # through a multi-tenant SolverFarm,
                                    # latency measured from SCHEDULED
                                    # arrival (no coordinated omission);
                                    # emits ONE bench_storm record with
                                    # the latency-vs-load curve, the
                                    # saturation knee, goodput accounting
                                    # and per-phase span attribution,
                                    # writes STORM_LATEST.json, gated
                                    # round-over-round via
                                    # AMGCL_TPU_GATE_STORM. --smoke is
                                    # the seeded ~10 s CI variant;
                                    # --trace PATH writes the Perfetto
                                    # storm timeline

All JSON emission routes through the telemetry sink
(amgcl_tpu/telemetry/sink.py) — loaded by FILE PATH below because the sink
is stdlib-only while the package __init__ pulls in jax, which this
supervisor must never import (a wedged tunnel can hang backend init).
"""

import importlib.util
import json
import os
import re
import subprocess
import sys
import threading
import time

_REPO = os.path.dirname(os.path.abspath(__file__))
_LAST_GOOD_PATH = os.path.join(_REPO, "BENCH_LAST_GOOD.json")
_N = int(os.environ.get("AMGCL_TPU_BENCH_N", "128"))
_METRIC = "poisson3d_%d_sa_cg_spai0_solve_time" % _N


def _load_by_path(name, relpath):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, *relpath))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_sink():
    return _load_by_path("_amgcl_tpu_sink",
                         ("amgcl_tpu", "telemetry", "sink.py"))


def _load_metrics():
    # stdlib-only, like the sink: the supervisor aggregates without jax
    return _load_by_path("_amgcl_tpu_metrics",
                         ("amgcl_tpu", "telemetry", "metrics.py"))


def _load_diff():
    # stdlib-only structured report diffing (telemetry/diff.py) — the
    # --why / --trend / gate-failure attribution engine, loaded by file
    # path for the same no-jax reason as the sink
    return _load_by_path("_amgcl_tpu_diff",
                         ("amgcl_tpu", "telemetry", "diff.py"))


_sink = _load_sink()
#: one JSON line to stdout — the contract the driver parses; no stamping
#: or NaN-cleaning so the line matches the historical print(json.dumps())
_stdout_sink = _sink.JsonlSink(stream=sys.stdout, stamp_records=False,
                               clean_records=False)

# HBM peak bandwidth per chip by device_kind substring (GB/s) — public
# figures; used only for the hbm_frac observability field.
_HBM_PEAK_GBPS = [
    ("v6", 1640.0), ("v5p", 2765.0), ("v5 lite", 819.0), ("v5e", 819.0),
    ("v5", 2765.0), ("v4", 1228.0), ("v3", 900.0), ("v2", 700.0),
]


def _git_head():
    return _sink.git_commit(_REPO)


def _load_last_good():
    try:
        with open(_LAST_GOOD_PATH) as f:
            return json.load(f)
    except Exception:
        return None


def _save_last_good(out):
    # stamp() + write_json_atomic() reproduce the historical record
    # byte-for-byte: same key order (ts, ts_iso, commit appended), same
    # json.dump defaults, same tmp+rename
    rec = _sink.stamp(dict(out))
    rec["commit"] = _git_head()
    _sink.write_json_atomic(_LAST_GOOD_PATH, rec)
    return rec


def _last_good_fields():
    lg = _load_last_good()
    if not lg:
        return {}
    return {"last_good": {
        "value": lg.get("value"), "vs_baseline": lg.get("vs_baseline"),
        "setup_s": lg.get("setup_s"),
        "setup_vs_baseline": lg.get("setup_vs_baseline"),
        "iters": lg.get("iters"), "device": lg.get("device"),
        "achieved_gbps": lg.get("achieved_gbps"),
        "hbm_frac": lg.get("hbm_frac"),
        "ts": lg.get("ts"), "ts_iso": lg.get("ts_iso"),
        "commit": lg.get("commit"),
    }}


# ===========================================================================
# supervisor
# ===========================================================================

def probe_platform(timeout_s):
    """Initialize jax in a throwaway subprocess. Returns 'tpu'/'cpu'/... or
    None if init wedged or crashed — the tunnel hang never touches us."""
    code = ("import jax\n"
            "d = jax.devices()[0]\n"
            "print('PLATFORM=' + d.platform)\n")
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=timeout_s)
    except (subprocess.TimeoutExpired, OSError):
        return None
    if r.returncode != 0:
        return None
    for line in r.stdout.splitlines():
        if line.startswith("PLATFORM="):
            return line.split("=", 1)[1].strip()
    return None


def run_worker(budget_s, extra_env=None):
    """Run one measurement pass in a killable subprocess.

    Returns (result_dict_or_None, stages, error_str_or_None). The worker
    streams '@@stage <t> <name>' lines; its final line is the JSON."""
    env = dict(os.environ)
    env.update(extra_env or {})
    # the worker's own internal watchdog fires just before we would kill it,
    # so a mid-run wedge still yields a JSON line with stage stamps
    env["AMGCL_TPU_BENCH_DEADLINE"] = str(max(int(budget_s) - 15, 60))
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--worker"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env)
    lines = []
    done = threading.Event()

    def reader():
        for line in proc.stdout:
            lines.append(line.rstrip("\n"))
        done.set()

    th = threading.Thread(target=reader, daemon=True)
    th.start()
    done.wait(budget_s)
    if proc.poll() is None:
        proc.kill()
        proc.wait()
        done.wait(5)
    stages, result = {}, None
    for line in lines:
        if line.startswith("@@stage "):
            _, t, name = line.split(" ", 2)
            stages[name] = float(t)
        elif line.startswith("{"):
            try:
                result = json.loads(line)
            except ValueError:
                pass
    if result is None:
        last = max(stages, key=stages.get) if stages else "start"
        return None, stages, ("worker wedged during '%s' (killed at %.0fs)"
                              % (last, budget_s))
    return result, stages, result.get("error")


def main_supervisor():
    t0 = time.time()
    deadline = float(os.environ.get("AMGCL_TPU_BENCH_DEADLINE", "1500"))
    attempts = []
    # time reserved at the tail for a CPU-forced fallback measurement if
    # the tunnel never comes up (clearly labeled device=cpu — NOT the
    # headline claim, but proof the harness measures end to end; the
    # last-good TPU fields ride along either way)
    cpu_reserve = min(600.0, deadline * 0.4)

    def remaining():
        return deadline - (time.time() - t0)

    def emit(out):
        # stdout line for the driver + a copy through the process-global
        # sink (AMGCL_TPU_TELEMETRY) for anyone collecting metrics
        _stdout_sink.emit(out)
        _sink.emit(dict(out), event="bench")

    def finish(result):
        if result.get("device_platform") == "tpu" \
                and result.get("value") is not None:
            _save_last_good(result)
        result.update(_last_good_fields())
        result["init_retries"] = len(attempts)
        emit(result)

    tpu_fails = 0
    while remaining() > cpu_reserve + 90:
        plat = probe_platform(min(90, remaining() - 30))
        if plat == "tpu":
            budget = remaining() - (cpu_reserve if remaining()
                                    > cpu_reserve + 400 else 30)
            result, stages, err = run_worker(budget)
            if result is not None and result.get("value") is not None:
                finish(result)
                return
            attempts.append("t+%ds: %s"
                            % (time.time() - t0, err or "worker failed"))
            # a fast deterministic worker crash (not a tunnel wedge) would
            # otherwise spin subprocess churn for the whole deadline
            tpu_fails += 1
            if tpu_fails >= 3:
                break
            time.sleep(min(30, max(remaining() - cpu_reserve - 60, 0)))
        else:
            attempts.append("t+%ds: %s" % (
                time.time() - t0,
                "init wedged" if plat is None else "platform=" + plat))
            time.sleep(min(20, max(remaining() - cpu_reserve - 60, 0)))

    # tail: the tunnel never produced a number — run the same measurement
    # CPU-forced so the emitted line still carries a real, labeled value
    budget = remaining() - 20
    if budget > 120:
        result, stages, err = run_worker(budget, {
            "AMGCL_TPU_FORCE_CPU": "1",
            "AMGCL_TPU_BENCH_N": os.environ.get(
                "AMGCL_TPU_BENCH_CPU_N",
                os.environ.get("AMGCL_TPU_BENCH_N", "96"))})
        if result is not None and result.get("value") is not None:
            result["fallback"] = "cpu (TPU tunnel unreachable all deadline)"
            finish(result)
            return
        attempts.append("cpu fallback: %s" % (err or "worker failed"))

    out = {"metric": _METRIC, "value": None, "unit": "s",
           "vs_baseline": None,
           "error": "no successful measurement within the %.0fs deadline"
                    % deadline,
           "init_retry_log": attempts[-12:]}
    out.update(_last_good_fields())
    emit(out)


# ===========================================================================
# opportunistic background mode
# ===========================================================================

def main_opportunistic():
    """Loop forever: whenever the tunnel answers, run one measurement and
    refresh BENCH_LAST_GOOD.json; append every outcome to a jsonl log.
    Run with nohup/background during a build round so any alive-window of
    the tunnel produces a stored artifact."""
    log_path = os.path.join(_REPO, "BENCH_OPPORTUNISTIC.jsonl")
    log = _sink.JsonlSink(log_path)
    period = float(os.environ.get("AMGCL_TPU_OPP_PERIOD", "900"))
    while True:
        t0 = time.time()
        plat = probe_platform(90)
        rec = {"ts": time.time(), "platform": plat}
        if plat == "tpu":
            # 1800s: the first chip session additionally pays the fused-
            # kernel probe compiles (cached persistently afterwards)
            result, stages, err = run_worker(1800)
            if result is not None and result.get("value") is not None \
                    and result.get("device_platform") == "tpu":
                _save_last_good(result)
                rec["result"] = result
            else:
                rec["error"] = err or "worker failed"
                rec["stages"] = stages
        log.emit(rec)
        time.sleep(max(period - (time.time() - t0), 30))


# ===========================================================================
# worker: one measurement pass (runs under the supervisor's knife)
# ===========================================================================

_T0 = time.time()
_STAGES = []
_PARTIAL = {}


def _stage(name):
    _STAGES.append((name, time.time()))
    print("@@stage %.1f %s" % (time.time() - _T0, name))
    sys.stdout.flush()


def _worker_watchdog():
    """In-process total deadline: emit a diagnostic JSON naming the last
    stage reached, then hard-exit. The supervisor kills us slightly later
    regardless; this path preserves partial results."""
    total = float(os.environ.get("AMGCL_TPU_BENCH_DEADLINE", "1500"))

    def guard():
        left = total - (time.time() - _T0)
        if left > 0:
            time.sleep(left)
        last = _STAGES[-1][0] if _STAGES else "start"
        out = {"metric": _METRIC, "value": None, "unit": "s",
               "vs_baseline": None,
               "error": "bench wedged during '%s' (%.0fs worker deadline)"
                        % (last, total),
               "stages_reached": {n: round(t - _T0, 1) for n, t in _STAGES}}
        out.update(_PARTIAL)
        _stdout_sink.emit(out)
        os._exit(2)

    threading.Thread(target=guard, daemon=True).start()


def _deadline_left():
    """Seconds until the worker watchdog fires (AMGCL_TPU_BENCH_DEADLINE
    is set by the supervisor from its own budget)."""
    total = float(os.environ.get("AMGCL_TPU_BENCH_DEADLINE", "1500"))
    return total - (time.time() - _T0)


def _dispatch_overhead(reps=5):
    """Median wall time of an already-compiled trivial dispatch + scalar
    fetch — the per-call cost floor imposed by the (possibly tunneled)
    runtime, subtracted from chained measurements."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    f = jax.jit(lambda s: s * 2.0)
    x = jnp.float32(1.0)
    float(f(x))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        float(f(x))
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def _timed_chain(fn_ops, reps, repeats, overhead):
    """Time ``reps`` data-dependent applications of fn inside ONE jitted
    scan, fetching a single scalar — so per-dispatch tunnel sync (which a
    locally-attached device would not pay) amortizes away. ``fn_ops`` is
    ``(fn, ops)``: fn(ops, carry_or_None) with the operator pytree as an
    explicit jit argument (closure constants would balloon the uploaded
    MLIR past the tunnel's remote_compile limit). Returns median
    per-application seconds."""
    import jax
    import numpy as np
    from jax import lax

    fn, ops = fn_ops

    def many(args):
        def body(c, _):
            return fn(args, c), None
        out, _ = lax.scan(body, fn(args, None), None, length=reps - 1)
        return out.sum()

    f = jax.jit(many)
    float(f(ops))                   # compile + warm
    ts = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        float(f(ops))
        ts.append(time.perf_counter() - t0)
    return (float(np.median(ts)) - overhead) / reps


def _diff_timeit(fn, x0, reps=(50, 250), carry_plus_x0=False, aux=None):
    """Per-op seconds for a shape-preserving ``fn`` by timing ONE jitted
    scan at two lengths and dividing the difference by the length delta.
    The per-dispatch tunnel round trip (~66 ms on the axon link, ms-scale
    jitter) swamps a short chain of µs-scale ops, and subtracting a
    separately-measured overhead leaves the signal inside the RTT noise —
    the r5 chip session measured a physically impossible 2.2 TB/s "XLA
    win" that way. The two-length difference cancels dispatch, fetch and
    warm-cache effects exactly. Can return ~0 (even slightly clamped-up
    negative) under extreme jitter; callers guard ratios with _floor."""
    import jax
    import numpy as np
    from jax import lax

    r1, r2 = reps

    def chain(r):
        # ``aux`` (operator arrays) rides through jit as an ARGUMENT —
        # closure constants embed the data in the uploaded MLIR and the
        # tunnel's remote_compile rejects multi-GB programs
        def many(a, x):
            def body(c, _):
                out = (fn(a, c) if aux is not None else fn(c)) * 0.5
                return (out + x if carry_plus_x0 else out), None
            out, _ = lax.scan(body, x, None, length=r)
            return out.sum()

        f = jax.jit(many)
        float(f(aux, x0))               # compile + warm
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            float(f(aux, x0))
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts))

    return max(chain(r2) - chain(r1), 0.0) / (r2 - r1)


def _floor(us, lo=0.05):
    """Ratio-denominator guard for _diff_timeit results (µs)."""
    return max(us, lo)


def _traffic_model(solver, npre, npost, pre_cycles):
    """Approximate HBM bytes moved per CG iteration (documented model, not
    a measurement): per level, each smoother application and the residual
    stream the operator once plus a few vector passes; transfers stream
    once per cycle; the fine level adds the CG body's SpMV and ~14 vector
    passes (dots/axpbys). Used for achieved_gbps / hbm_frac."""
    def mat_bytes(m):
        try:
            return int(m.bytes())
        except Exception:
            return 0

    levels = solver.precond.hierarchy.levels
    itemsize = 4
    per_cycle = 0
    for i, lv in enumerate(levels):
        n = lv.A.shape[0] if lv.A is not None else 0
        a = mat_bytes(lv.A)
        vec = n * itemsize
        if i < len(levels) - 1:
            per_cycle += (npre + npost) * (a + 4 * vec)   # smoother sweeps
            per_cycle += a + 2 * vec                       # residual
            per_cycle += mat_bytes(lv.R) + mat_bytes(lv.P) + 4 * vec
        else:
            per_cycle += 2 * a + 4 * vec                   # coarse solve-ish
    n0 = levels[0].A.shape[0]
    per_iter = pre_cycles * per_cycle + mat_bytes(levels[0].A) \
        + 14 * n0 * itemsize
    return per_iter


def _bench_levels(solver):
    """Per-level SpMV timings: XLA lowering vs the Pallas DIA kernel where
    the level is DIA-formatted. Chains 50 SpMVs inside ONE jitted scan and
    fetches a scalar (per-dispatch sync through the axon tunnel swamps a
    single op). Returns a list of dicts."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from amgcl_tpu.ops.device import DiaMatrix
    from amgcl_tpu.ops.pallas_spmv import dia_spmv

    timeit = _diff_timeit               # two-length difference (see above)

    out = []
    for li, lv in enumerate(solver.precond.hierarchy.levels):
        M = lv.A
        if M.shape[0] != M.shape[1]:
            continue
        n_cols = M.shape[1] * getattr(M, "block", (1, 1))[1]
        x = jnp.asarray(np.random.RandomState(li).rand(n_cols),
                        dtype=jnp.float32)
        saved = os.environ.get("AMGCL_TPU_PALLAS")
        os.environ["AMGCL_TPU_PALLAS"] = "0"   # mv() gates on this at trace
        try:
            t_x = timeit(M.mv, x)
        finally:
            if saved is None:
                del os.environ["AMGCL_TPU_PALLAS"]
            else:
                os.environ["AMGCL_TPU_PALLAS"] = saved
        row = {"level": li, "format": type(M).__name__,
               "rows": int(M.shape[0]),
               "xla_us": round(t_x * 1e6, 1)}
        if isinstance(M, DiaMatrix):
            offs = tuple(M.offsets)
            interp = jax.default_backend() != "tpu"
            row["ndiag"] = len(offs)
            row["pallas_us"] = round(timeit(
                lambda v: dia_spmv(offs, M.data, v, interpret=interp), x)
                * 1e6, 1)
            if interp:
                row["pallas_interpret_mode"] = True
            elif row["pallas_us"] == 0.0 or row["xla_us"] == 0.0:
                # an exact 0.0 is _diff_timeit's negative-difference
                # clamp, i.e. jitter won — no verdict from that arm
                row["winner"] = "noise"
            else:
                row["winner"] = "pallas" \
                    if row["pallas_us"] < row["xla_us"] else "xla"
            # fused residual (one-pass f - A x) vs composed (spmv kernel +
            # XLA subtract, with the HBM round-trip of A x in between) —
            # decides whether the fused kernels stay default-on
            from amgcl_tpu.ops.pallas_spmv import dia_residual
            f = jnp.asarray(np.random.RandomState(li + 1).rand(M.shape[0]),
                            dtype=jnp.float32)
            row["fused_resid_us"] = round(timeit(
                lambda v: dia_residual(offs, M.data, f, v,
                                       interpret=interp), x) * 1e6, 1)
            row["composed_resid_us"] = round(timeit(
                lambda v: f - dia_spmv(offs, M.data, v, interpret=interp),
                x) * 1e6, 1)
        if getattr(lv, "down", None) is not None:
            # one-pass down-sweep tail vs the composed 3-op chain (the
            # timeit scan needs shape-preserving fns, so wrap both to
            # return a fine-grid vector via the prolongation broadcast)
            f = jnp.asarray(np.random.RandomState(li + 2).rand(M.shape[0]),
                            dtype=jnp.float32)
            from amgcl_tpu.ops import device as _dv
            T = lv.R.T
            row["fused_down_us"] = round(timeit(
                lambda v: T.mv(lv.down(f, v)), x) * 1e6, 1)
            # honest baseline: the ACTUAL fallback path (which already
            # rides the fused dia_residual kernel), not spmv + subtract
            row["composed_down_us"] = round(timeit(
                lambda v: T.mv(lv.R.mv(_dv.residual(f, lv.A, v))), x)
                * 1e6, 1)
        if getattr(lv, "up", None) is not None:
            from amgcl_tpu.ops import device as _d
            f = jnp.asarray(np.random.RandomState(li + 3).rand(M.shape[0]),
                            dtype=jnp.float32)
            uc = jnp.asarray(np.random.RandomState(li + 4).rand(
                lv.R.shape[0]), dtype=jnp.float32)
            row["fused_up_us"] = round(timeit(
                lambda v: lv.up(f, v, uc), x) * 1e6, 1)
            row["composed_up_us"] = round(timeit(
                lambda v: lv.relax.apply_post(
                    lv.A, f, v + _d.spmv(lv.P, uc)), x) * 1e6, 1)
        out.append(row)
    return out


def _bench_unstructured(on_tpu):
    """Unstructured SpMV comparison (VERDICT r2 item 3): FE-style matrix at
    poisson3Db's profile (BASELINE config 2), RCM-reordered; times the
    plain-ELL jnp.take path vs the windowed-ELL paths (ops/unstructured.py)
    with 50 chained SpMVs per measurement."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax import lax
    from amgcl_tpu.ops.csr import CSR
    from amgcl_tpu.ops import device as dev
    from amgcl_tpu.ops.unstructured import (
        csr_to_windowed_ell, fe_like_problem, kernel_supported)
    from amgcl_tpu.utils.adapters import cuthill_mckee, permute

    cache = os.path.join(_REPO, ".bench_fe_cache.npz")
    n_target = int(os.environ.get("AMGCL_TPU_BENCH_UNSTRUCT_N", "85623"))
    fe_version = 2      # v2: 1/h² edge weights (v1 was SA-degenerate)
    A = None
    if os.path.exists(cache):
        try:
            z = np.load(cache)
            if int(z["n"]) == n_target and "version" in z.files \
                    and int(z["version"]) == fe_version:
                A = CSR(z["ptr"], z["col"], z["val"], int(z["n"]))
        except Exception:
            A = None
    if A is None:
        A, _ = fe_like_problem(n=n_target)
        A = permute(A, cuthill_mckee(A))
        np.savez(cache, ptr=A.ptr, col=A.col, val=A.val, n=A.nrows,
                 version=fe_version)

    x = jnp.asarray(np.random.RandomState(0).rand(A.nrows), jnp.float32)

    def timeit(fn):
        # shorter chains than _bench_levels: the take-ELL arm is ~30 ms
        # per op on this fixture, so the work dominates and long chains
        # would cost minutes; the difference still cancels dispatch
        return _diff_timeit(fn, x, reps=(10, 30),
                            carry_plus_x0=True) * 1e6  # us per spmv

    out = {"n": A.nrows, "nnz": A.nnz}
    E = dev.csr_to_ell(A, jnp.float32)
    out["ell_take_us"] = round(timeit(E.mv), 1)
    W = csr_to_windowed_ell(A, jnp.float32)
    if W is not None:
        out["win"] = W.win
        out["well_xla_us"] = round(timeit(W._mv_xla), 1)
        if on_tpu and kernel_supported(W.win, W.cols_local.shape[2],
                                       W.vals.dtype):
            from amgcl_tpu.ops.unstructured import (
                windowed_ell_spmv, windowed_ell_residual,
                windowed_ell_scaled_correction)
            out["well_pallas_us"] = round(timeit(
                lambda v: windowed_ell_spmv(
                    W.window_starts, W.cols_local, W.vals, v,
                    W.win, W.shape[0])), 1)
            out["speedup_vs_take"] = round(
                out["ell_take_us"] / _floor(out["well_pallas_us"]), 2)
            # fused tiers on the unstructured path (VERDICT r4 item 2):
            # fused single-pass vs composed kernel + XLA elementwise
            f = jnp.asarray(np.random.RandomState(1).rand(A.nrows),
                            jnp.float32)
            wgt = jnp.asarray(np.random.RandomState(2).rand(A.nrows),
                              jnp.float32)
            out["fused_resid_us"] = round(timeit(
                lambda v: windowed_ell_residual(
                    W.window_starts, W.cols_local, W.vals, f, v,
                    W.win, W.shape[0])), 1)
            out["composed_resid_us"] = round(timeit(
                lambda v: f - windowed_ell_spmv(
                    W.window_starts, W.cols_local, W.vals, v,
                    W.win, W.shape[0])), 1)
            out["fused_sweep_us"] = round(timeit(
                lambda v: windowed_ell_scaled_correction(
                    W.window_starts, W.cols_local, W.vals, wgt, f, v,
                    W.win, W.shape[0])), 1)
            out["composed_sweep_us"] = round(timeit(
                lambda v: v + wgt * (f - windowed_ell_spmv(
                    W.window_starts, W.cols_local, W.vals, v,
                    W.win, W.shape[0]))), 1)
        elif on_tpu:
            out["well_pallas_us"] = None
            out["note"] = "in-kernel gather not legalized on this backend"

    # gather-free dense-window format (ops/densewin.py): storage-for-
    # bandwidth trade; on TPU this is the production unstructured path
    # (auto-selected), so its SpMV row is the one the solve runs on
    try:
        from amgcl_tpu.ops.densewin import (csr_to_dense_window,
                                            dense_window_spmv)
        # TPU-only: the build materializes multi-GB dense blocks and
        # nothing times them off-chip
        D = csr_to_dense_window(A, jnp.float32, require_kernel=True) \
            if on_tpu else None
        if D is not None:
            out["dwin_win"] = D.win
            out["dwin_gb"] = round(D.bytes() / 1e9, 2)
            if on_tpu:
                out["dwin_spmv_us"] = round(_diff_timeit(
                    lambda a, v: dense_window_spmv(
                        a[0], a[1], v, D.win, D.shape[0]),
                    x, reps=(10, 30), carry_plus_x0=True,
                    aux=(D.window_starts, D.blocks)) * 1e6, 1)
                out["dwin_speedup_vs_take"] = round(
                    out["ell_take_us"] / _floor(out["dwin_spmv_us"]), 2)
        else:
            out["dwin_win"] = None
    except Exception as e:
        out["dwin_error"] = repr(e)[:200]

    # EXECUTED reorder (ISSUE 20 tentpole attribution): the permuted-
    # banded fixture through the production seams — reorder_plan()
    # computes the RCM permutation, to_device('auto') re-prices the
    # candidate table on each ordering, and the format-decision records
    # carry the model bytes that explain the wall-time gain. 'rcm' is
    # forced (not 'auto') so the row is deterministic across hosts even
    # when the advisor's gain floor would sit right at the threshold.
    try:
        from amgcl_tpu.telemetry import structure as _st
        from amgcl_tpu.utils.adapters import permute as _permute
        Ax, _A0, _pm = _st.permuted_banded(4096, bw=16, seed=7, local=32)
        rx = {"n": Ax.nrows, "nnz": Ax.nnz}
        plan = _st.reorder_plan(Ax, on_tpu=on_tpu, mode="rcm")
        if plan is None:
            rx["note"] = "reorder_plan declined"
        else:
            rx["variant"] = plan["variant"]
            rx["predicted_gain"] = plan["predicted_gain"]
            Bx = _permute(Ax, plan["perm"])
            xr = jnp.asarray(np.random.RandomState(3).rand(Ax.nrows),
                             jnp.float32)
            for tag, mat in (("identity", Ax), ("reordered", Bx)):
                M = dev.to_device(mat, "auto", jnp.float32)
                d = getattr(M, "_format_decision", None) or {}
                rx[tag] = {
                    "format": d.get("fmt"),
                    "model_bytes": (d.get("predicted") or {}).get("bytes"),
                    "stored_bytes": d.get("stored_bytes"),
                    "spmv_us": round(_diff_timeit(
                        lambda v, _M=M: dev.spmv(_M, v), xr,
                        reps=(10, 30), carry_plus_x0=True) * 1e6, 1)}
            ti = rx["identity"]["spmv_us"]
            tr = rx["reordered"]["spmv_us"]
            if ti and tr:
                rx["measured_gain"] = round(ti / _floor(tr), 3)
            bi = rx["identity"]["model_bytes"]
            br = rx["reordered"]["model_bytes"]
            if bi and br:
                rx["model_bytes_gain"] = round(bi / br, 3)
        out["reorder_exec"] = rx
    except Exception as e:
        out["reorder_exec"] = {"error": repr(e)[:200]}

    # end-to-end SOLVE at the poisson3Db profile (BASELINE tutorial rows:
    # builtin 0.592 s / GTX 1050 Ti CUDA 0.171 s, AMG(SA)+BiCGStab) — a
    # synthetic same-class matrix, so the comparison is indicative of the
    # problem CLASS, not the exact SuiteSparse instance. TPU-gated (or
    # AMGCL_TPU_BENCH_UNSTRUCT_SOLVE=1): the f32 solve on the hard kNN
    # fixture is minutes on a contended CPU host
    if not (on_tpu or os.environ.get(
            "AMGCL_TPU_BENCH_UNSTRUCT_SOLVE") == "1"):
        return out
    left = _deadline_left()
    if left < 150:
        out["solve"] = {"skipped": "%.0fs left < ~150s solve cost" % left}
        return out
    try:
        from amgcl_tpu.models.make_solver import make_solver
        from amgcl_tpu.models.amg import AMGParams
        from amgcl_tpu.solver.bicgstab import BiCGStab
        s = make_solver(A, AMGParams(dtype=jnp.float32),
                        BiCGStab(maxiter=300, tol=1e-8), refine=2)
        rhs = jnp.asarray(np.ones(A.nrows), jnp.float32)
        t0 = time.perf_counter()
        xs, info = s(rhs)
        jax.block_until_ready(xs)
        t_setup_solve = time.perf_counter() - t0       # includes compile
        t0 = time.perf_counter()
        xs, info = s(rhs)
        jax.block_until_ready(xs)
        t_solve = time.perf_counter() - t0
        out["solve"] = {
            "solve_s": round(t_solve, 4), "iters": int(info.iters),
            "resid": float(info.resid),
            "first_call_s": round(t_setup_solve, 3),
            "vs_poisson3Db_cpu": round(0.592 / t_solve, 3),
            "vs_poisson3Db_cuda": round(0.171 / t_solve, 3)}
    except Exception as e:
        out["solve"] = {"error": repr(e)}
    return out


def _bench_extra_configs(on_tpu):
    """Compact analogues of BASELINE configs 3 (Serena-class: block value
    type) and 4 (Stokes-class: schur_pressure_correction). The real
    SuiteSparse matrices are not redistributable in this image, so these
    are generated systems of the same class; timings are absolute (no
    vs_baseline), chained like the headline measurement."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import scipy.sparse as sp
    from amgcl_tpu.ops.csr import CSR
    from amgcl_tpu.models.make_solver import make_solver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.bicgstab import BiCGStab
    from amgcl_tpu.solver.gmres import FGMRES
    from amgcl_tpu.models.schur import SchurPressureCorrection
    from amgcl_tpu.utils.sample_problem import poisson3d_block

    out = {}

    def timed_solve(solver, rhs):
        x, info = solver(rhs)            # compile + warm
        jax.block_until_ready(x)
        t0 = time.perf_counter()
        x, info = solver(rhs)
        jax.block_until_ready(x)
        return time.perf_counter() - t0, info

    # config-3 analogue: block 3x3 system, SA + spai0 + BiCGStab
    try:
        n = int(os.environ.get("AMGCL_TPU_BENCH_BLOCK_N", "48"))
        A, rhs = poisson3d_block(n, 3)
        s = make_solver(A, AMGParams(dtype=jnp.float32),
                        BiCGStab(maxiter=200, tol=1e-6))
        t, info = timed_solve(s, jnp.asarray(rhs, jnp.float32))
        out["block3_n%d" % n] = {
            "rows": A.nrows * 3, "solve_s": round(t, 4),
            "iters": int(info.iters), "resid": float(info.resid)}
        # block SpMV format decision (VERDICT r4 item 3): windowed
        # block-ELL Pallas kernel vs the einsum block-ELL XLA path on the
        # fine-level operator
        from jax import lax
        from amgcl_tpu.ops import device as devops
        from amgcl_tpu.ops.unstructured import (
            csr_to_windowed_ell, kernel_supported,
            windowed_ell_block_spmv)
        xv = jnp.asarray(np.random.RandomState(0).rand(A.nrows * 3),
                         jnp.float32)

        def timeit(fn):
            return round(_diff_timeit(fn, xv, carry_plus_x0=True)
                         * 1e6, 1)

        E = devops.csr_to_ell(A, jnp.float32)
        out["block3_ell_einsum_us"] = timeit(E.mv)
        Wb = csr_to_windowed_ell(A, jnp.float32)
        if Wb is not None:
            out["block3_well_xla_us"] = timeit(Wb._mv_xla)
            if on_tpu and kernel_supported(
                    Wb.win, Wb.cols_local.shape[2], Wb.dtype, Wb.block):
                out["block3_well_pallas_us"] = timeit(
                    lambda v: windowed_ell_block_spmv(
                        Wb.window_starts, Wb.cols_local, Wb.vals, v,
                        Wb.win, Wb.shape[0]))
                out["block3_speedup_vs_einsum"] = round(
                    out["block3_ell_einsum_us"]
                    / _floor(out["block3_well_pallas_us"]), 2)
    except Exception as e:
        out["block3"] = {"error": repr(e)}

    # config-4 analogue: stabilized Stokes saddle point + Schur PC + FGMRES
    left = _deadline_left()
    if left < 150:
        out["stokes_schur"] = {"skipped": "%.0fs left < ~150s config cost"
                                          % left}
        return out
    try:
        n = int(os.environ.get("AMGCL_TPU_BENCH_STOKES_N", "48"))
        T = sp.diags([-np.ones(n - 1), 2 * np.ones(n), -np.ones(n - 1)],
                     [-1, 0, 1])
        L = (sp.kron(sp.identity(n), T)
             + sp.kron(T, sp.identity(n))).tocsr()
        nu = L.shape[0]
        Av = sp.block_diag([L, L]).tocsr()
        D = sp.diags([-np.ones(nu - 1), np.ones(nu)], [-1, 0],
                     shape=(nu, nu))
        B = sp.hstack([D, 0.5 * D]).tocsr()
        K = sp.bmat([[Av, B.T], [B, -sp.identity(nu) * 1e-2]]).tocsr()
        pmask = np.zeros(K.shape[0], dtype=bool)
        pmask[2 * nu:] = True
        Ks = CSR.from_scipy(K)
        pre = SchurPressureCorrection(
            Ks, pmask, usolver_prm=AMGParams(dtype=jnp.float32),
            psolver_prm=AMGParams(dtype=jnp.float32),
            approx_schur=True, dtype=jnp.float32)
        s = make_solver(Ks, pre, FGMRES(maxiter=300, tol=1e-6))
        t, info = timed_solve(s, np.ones(Ks.nrows))
        out["stokes_schur_n%d" % n] = {
            "rows": Ks.nrows, "solve_s": round(t, 4),
            "iters": int(info.iters), "resid": float(info.resid)}
    except Exception as e:
        out["stokes_schur"] = {"error": repr(e)}
    return out


def _setup_attr_summary(report, top=12):
    """Compact form of AMG.setup_report() for the bench record: the
    named-stage coverage fraction plus the top (non-nested) stages."""
    rows = [[r["stage"], r["seconds"]] for r in report.get("rows", [])
            if not r.get("nested")][:top]
    return {"coverage": report.get("coverage"),
            "total_s": report.get("total_s"),
            "named_s": report.get("named_s"), "stages": rows}


def main_worker():
    _stage("device init")
    _worker_watchdog()
    import numpy as np
    if os.environ.get("AMGCL_TPU_FORCE_CPU") == "1":
        # supervisor's tail fallback: never touch the (wedged) tunnel
        from amgcl_tpu.utils.axon_guard import force_cpu_backend
        force_cpu_backend()
    else:
        # an explicit JAX_PLATFORMS=cpu must win over the axon plugin's
        # registration-time override here too — the worker inits the
        # backend before the package __init__ hook would run
        from amgcl_tpu.utils.axon_guard import apply_if_cpu_requested
        apply_if_cpu_requested()
    import jax
    # persistent compilation cache: opportunistic runs during the round
    # pre-warm every per-level setup program and the solve program, so a
    # later driver-invoked run at the same shapes skips compilation
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(_REPO, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:
        pass
    # x64 so the refinement's outer residual really is float64 (the
    # correction solves stay float32)
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    dev0 = jax.devices()[0]
    on_tpu = jax.default_backend() == "tpu"
    from amgcl_tpu.utils.sample_problem import poisson3d
    from amgcl_tpu.models.make_solver import make_solver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.cg import CG

    n = _N
    solve_base = 0.55 * (n / 150.0) ** 3    # K80 CUDA, volume-scaled
    setup_base = 1.33 * (n / 150.0) ** 3

    # environment telemetry: host contention invalidated the r03→r04
    # cross-round comparison (same code, 4× slower generation); record the
    # load so future readers can tell a regression from a noisy host
    ncpu = os.cpu_count() or 1
    load0 = os.getloadavg()
    _PARTIAL["telemetry"] = {
        "ncpu": ncpu,
        "loadavg_start": [round(v, 2) for v in load0],
        "contended": load0[0] / ncpu > 0.5,
        "timing": "median-of-k chained (see _timed_chain)"}

    _stage("problem gen")
    t0 = time.perf_counter()
    A, rhs = poisson3d(n)
    t_gen = time.perf_counter() - t0

    _stage("hierarchy setup")
    # ONE definition of the headline configuration — the setup-profile
    # stage re-runs exactly this so its warm-cache premise holds
    headline_config = dict(solver=lambda: CG(maxiter=100, tol=1e-6),
                           refine=3)
    t0 = time.perf_counter()
    prm = AMGParams(dtype=jnp.float32)
    solver = make_solver(A, prm, headline_config["solver"](),
                         refine=headline_config["refine"])
    t_setup = time.perf_counter() - t0
    _PARTIAL.update({
        "setup_s": round(t_setup, 3),
        "setup_vs_baseline": round(setup_base / t_setup, 3),
        "gen_s": round(t_gen, 3),
        "device": str(dev0), "device_platform": dev0.platform,
        "device_kind": getattr(dev0, "device_kind", None)})
    # uniform hardware-provenance stamp (telemetry/comm.py): device
    # kind, topology, and the ICI vs CPU-fallback tag every gate's
    # platform-mismatch skip reads through _record_platform
    try:
        from amgcl_tpu.telemetry.comm import hw_provenance
        _PARTIAL["provenance"] = hw_provenance()
    except Exception:
        pass
    # stage-by-stage setup attribution (telemetry/ledger.
    # setup_attribution): named-stage coverage + the top stages, captured
    # NOW — the rebuild stage below replaces the profiler
    try:
        _PARTIAL["setup_attribution"] = _setup_attr_summary(
            solver.precond.setup_report())
    except Exception as e:
        _PARTIAL["setup_attribution"] = {"error": repr(e)[:200]}
    # which levels carry the fused sweep kernels (empty on CPU fallback
    # where pallas_mode gates them off — documents engagement per run)
    _PARTIAL["fused_levels"] = " ".join(
        "%d%s%s" % (i, "d" if lv.down is not None else "",
                    "u" if lv.up is not None else "")
        for i, lv in enumerate(solver.precond.hierarchy.levels)
        if lv.down is not None or lv.up is not None)
    # why any fused tier is missing: the probe/value-check decline log
    # (worker stderr never reaches the committed artifact)
    from amgcl_tpu.ops.pallas_spmv import PROBE_DECLINES
    if PROBE_DECLINES:
        _PARTIAL["fused_declines"] = [
            [n_, r] for n_, r in PROBE_DECLINES[:20]]

    rhs_dev = jnp.asarray(rhs, dtype=jnp.float32)
    x0 = jnp.zeros_like(rhs_dev)

    _stage("dispatch overhead probe")
    overhead = _dispatch_overhead()
    _PARTIAL["dispatch_overhead_s"] = round(overhead, 4)

    # one plain call for convergence info + per-call wall time (includes
    # dispatch/sync and the single-round-trip info fetch)
    _stage("solve compile+run")
    x, info = solver(rhs_dev)               # compile + warm
    jax.block_until_ready(x)
    t0 = time.perf_counter()
    x, info = solver(rhs_dev)
    jax.block_until_ready(x)
    wall_per_call = time.perf_counter() - t0

    true_res = float(np.linalg.norm(rhs - A.spmv(np.asarray(x, np.float64)))
                     / np.linalg.norm(rhs))
    _PARTIAL.update({
        "value": round(wall_per_call, 4),
        "vs_baseline": round(solve_base / wall_per_call, 3),
        "wall_per_call_s": round(wall_per_call, 4),
        "iters": int(info.iters), "resid": float(info.resid),
        "true_resid": true_res})
    # numerical-health guard decode (telemetry/health.py): the gate's
    # health check compares this against the last-good record — a
    # previously-clean problem that now trips any guard is a regression
    if getattr(info, "health", None) is not None:
        _PARTIAL["health"] = info.health

    # amortized timing: chain solves inside one scan so per-dispatch tunnel
    # latency (absent on a locally-attached device) does not pollute the
    # device-time measurement — this is the headline number
    _stage("solve chained timing")
    reps = 4 if on_tpu else 2
    repeats = 3 if on_tpu else 2
    _PARTIAL["telemetry"]["chain_reps"] = reps
    _PARTIAL["telemetry"]["timing_repeats"] = repeats

    def chained_step(slv):
        # the 0*c term makes each solve data-depend on the previous one,
        # so chained repetitions cannot be reordered or elided. The
        # operators ride as explicit args (_timed_chain passes them back
        # through jit): closing over them would embed every level's data
        # as MLIR constants — with the fused-kernel frames that is
        # ~300 MB of text and the tunnel's remote_compile 413s on it
        ops = (slv.A_dev, slv.A_dev64, slv.precond.hierarchy)

        def one(args, c):
            A_dev, A_dev64, hier = args
            r = rhs_dev if c is None else rhs_dev + 0 * c
            got = slv._solve_fn(A_dev, A_dev64, hier, r, x0)
            return got[0].astype(jnp.float32)
        return one, ops

    try:
        t_solve = _timed_chain(chained_step(solver), reps,
                               repeats, overhead)
        t_solve = max(t_solve, 1e-9)
    except Exception:
        t_solve = wall_per_call
    _PARTIAL.update({
        "value": round(t_solve, 4),
        "vs_baseline": round(solve_base / t_solve, 3)})

    # resource ledger (telemetry/ledger.py): hierarchy bytes by format,
    # analytic cycle FLOP/byte, dense-window budget use — the gate's
    # 'peak ledger bytes' source and the roofline x-coordinate
    try:
        from amgcl_tpu.telemetry.ledger import summarize_ledger
        _PARTIAL["ledger"] = summarize_ledger(
            solver.precond.resource_ledger())
    except Exception as e:
        _PARTIAL["ledger"] = {"error": repr(e)[:200]}

    # operator X-ray summary (telemetry/structure.py): per-level format
    # decisions (winner + reason) and waste metrics on EVERY record, so
    # --why / --trend can attribute format-decision changes across
    # rounds (AMGCL_TPU_XRAY=0 opts out). Metrics + decision ledger
    # only — the advisor's RCM pass stays out of the headline worker
    # (bench --xray is the advisor's measured validation arm)
    if os.environ.get("AMGCL_TPU_XRAY", "1") != "0":
        try:
            from amgcl_tpu.telemetry.structure import xray_summary
            _PARTIAL["structure"] = xray_summary(
                solver.precond.structure_report(advise=False))
        except Exception as e:
            _PARTIAL["structure"] = {"error": repr(e)[:200]}

    # bandwidth observability: documented traffic model / measured time.
    # The ledger's per-iteration model is the primary source — it prices
    # the fused tiers (single-pass V-cycle legs, fused vector algebra)
    # at their actual single-stream cost instead of double counting the
    # composed stages; the legacy composed formula stays as the fallback
    per_iter_bytes = ((info.resources or {}).get("per_iteration")
                      or {}).get("bytes")
    if not per_iter_bytes:
        per_iter_bytes = _traffic_model(solver, prm.npre, prm.npost,
                                        prm.pre_cycles)
    iters = max(int(info.iters), 1)
    achieved = per_iter_bytes * iters / t_solve / 1e9
    _PARTIAL["model_bytes_per_iter"] = int(per_iter_bytes)
    _PARTIAL["achieved_gbps"] = round(achieved, 1)
    kind = (getattr(dev0, "device_kind", "") or "").lower()
    for key, peak in _HBM_PEAK_GBPS:
        if key in kind:
            _PARTIAL["hbm_peak_gbps"] = peak
            _PARTIAL["hbm_frac"] = round(achieved / peak, 3)
            break

    # roofline summary (telemetry/roofline.py): the ledger's per-
    # iteration model over the CHAINED solve time vs auto-detected peaks
    # — the trend's roofline_frac column
    try:
        from amgcl_tpu.telemetry import roofline as _roofline
        pi = (info.resources or {}).get("per_iteration")
        if pi:
            rf = _roofline.solve_roofline(pi, iters, t_solve)
            if rf is not None:
                _PARTIAL["roofline"] = rf
    except Exception as e:
        _PARTIAL["roofline"] = {"error": repr(e)[:200]}

    # compile accounting (telemetry/compile_watch.py): per-function
    # traces/compiles/compile-seconds + retrace events for this run —
    # a retrace regression shows up in the committed record
    try:
        from amgcl_tpu.telemetry import compile_watch as _cwatch
        if _cwatch.enabled():
            snap = _cwatch.snapshot()
            _PARTIAL["compile"] = {
                "totals": snap["totals"],
                "functions": {name: {"traces": rec["traces"],
                                     "compile_s": rec["compile_s"],
                                     "retraces": rec["retraces"]}
                              for name, rec in snap["functions"].items()
                              if rec["traces"] or rec["compile_s"]},
                "retrace_events": snap["retrace_events"][-10:]}
    except Exception as e:
        _PARTIAL["compile"] = {"error": repr(e)[:200]}

    # same-sparsity numeric rebuild (ROADMAP item 2, time-stepping
    # workloads): identical values, so every later stage still measures
    # the same operator. Warm median-of-2 — the first rebuild pays the
    # one-time plan construction/compiles, which a time-stepping loop
    # amortizes away; that cost is recorded separately.
    _stage("hierarchy rebuild")
    try:
        pre = solver.precond
        if hasattr(pre, "rebuild"):
            vals = A.val.copy()
            t0 = time.perf_counter()
            pre.rebuild(vals)
            _PARTIAL["rebuild_first_s"] = round(
                time.perf_counter() - t0, 3)
            ts = []
            for _ in range(2):
                t0 = time.perf_counter()
                pre.rebuild(vals)
                ts.append(time.perf_counter() - t0)
            rebuild_s = float(np.median(ts))
            _PARTIAL["rebuild_s"] = round(rebuild_s, 4)
            _PARTIAL["rebuild_vs_setup"] = round(
                rebuild_s / max(t_setup, 1e-9), 4)
    except Exception as e:
        _PARTIAL["rebuild_error"] = repr(e)[:200]

    # Optional deep-dive stages, highest decision-leverage first, each
    # gated on the time left before the watchdog (the r5 chip run burned
    # half its budget in 'block + stokes configs' and got killed mid-
    # stage; a skipped stage with a recorded reason beats a wedge). Cost
    # estimates are the observed r5 stage durations + compile margin.
    def _enough(key, est):
        left = _deadline_left()
        if left > est:
            return True
        _PARTIAL[key] = {"skipped": "%.0fs left < ~%.0fs stage cost"
                                    % (left, est)}
        return False

    if (on_tpu or os.environ.get("AMGCL_TPU_BENCH_STAGES") == "1") \
            and _enough("roofline_stages", 150):
        # measured per-(level, stage) cycle times (telemetry/roofline.
        # measure_stages) in the compact form telemetry/diff.py joins —
        # the rows that let a LATER round's gate failure name the stage
        # that regressed instead of just the ratio (--why / --trend why)
        _stage("roofline stages")
        try:
            roof = solver.precond.roofline()
            _PARTIAL["roofline_stages"] = [
                {"level": r["level"], "stage": r["stage"],
                 "visits": r.get("visits", 1), "t_s": r["t_s"],
                 "model_bytes": r.get("model_bytes"),
                 "model_flops": r.get("model_flops")}
                for r in roof.get("stages", [])]
        except Exception as e:
            _PARTIAL["roofline_stages"] = {"error": repr(e)[:200]}

    levels = None
    if (on_tpu or os.environ.get("AMGCL_TPU_BENCH_LEVELS") == "1") \
            and _enough("levels", 180):
        _stage("per-level timings")
        try:
            levels = _bench_levels(solver)
        except Exception as e:       # per-level timing must never kill the
            levels = [{"error": repr(e)}]   # headline number
        _PARTIAL["levels"] = levels
    if (on_tpu or os.environ.get("AMGCL_TPU_BENCH_SETUP_PROF") == "1") \
            and _enough("setup_profile", 120):
        # warm-cache setup re-run with per-phase blocking profile: all
        # programs are already compiled, so this decomposes the REBUILD
        # cost (device programs vs fetch round trips vs fused probe/value
        # checks) — the r5 chip session's 15.7s setup was opaque
        _stage("setup profile")
        try:
            from amgcl_tpu.ops import stencil_device as _sdev
            os.environ["AMGCL_TPU_PROFILE_SETUP"] = "1"
            t0 = time.perf_counter()
            s_rep = make_solver(A, prm, headline_config["solver"](),
                                refine=headline_config["refine"])
            _PARTIAL["setup_repeat_s"] = round(time.perf_counter() - t0, 3)
            _PARTIAL["setup_profile"] = [
                [tag, dt] for tag, dt in _sdev.LAST_SETUP_PROFILE]
            # per-stage attribution of the warm re-run (device-setup
            # stages included), same shape as setup_attribution above
            _PARTIAL["setup_repeat_attribution"] = _setup_attr_summary(
                s_rep.precond.setup_report())
        except Exception as e:
            _PARTIAL["setup_profile"] = {"error": repr(e)}
        finally:
            os.environ.pop("AMGCL_TPU_PROFILE_SETUP", None)
    if (on_tpu or os.environ.get("AMGCL_TPU_BENCH_BF16") == "1") \
            and _enough("bf16", 200):
        # the ROADMAP's f32-vs-bf16 hierarchy decision, measured: same
        # problem, bf16 level operators (half the HBM bytes per
        # iteration) + f64-residual refinement; more iterations vs
        # cheaper iterations is exactly the hardware question
        _stage("bf16 hierarchy probe")
        try:
            t0 = time.perf_counter()
            prm16 = AMGParams(dtype=jnp.bfloat16)
            solver16 = make_solver(A, prm16, CG(maxiter=200, tol=1e-6),
                                   refine=3)
            t_setup16 = time.perf_counter() - t0
            x16, info16 = solver16(rhs_dev)
            jax.block_until_ready(x16)
            t16 = max(_timed_chain(chained_step(solver16), reps,
                                   repeats, overhead), 1e-9)
            tr16 = float(np.linalg.norm(
                rhs - A.spmv(np.asarray(x16, np.float64)))
                / np.linalg.norm(rhs))
            _PARTIAL["bf16"] = {
                "solve_s": round(t16, 4), "setup_s": round(t_setup16, 3),
                "iters": int(info16.iters), "true_resid": tr16,
                "speedup_vs_f32": round(t_solve / t16, 3)}
        except Exception as e:
            _PARTIAL["bf16"] = {"error": repr(e)}
    if (on_tpu or os.environ.get("AMGCL_TPU_BENCH_THROUGHPUT") == "1") \
            and _enough("throughput", 200):
        # serving throughput (serve/): stacked multi-RHS solves/sec at
        # B in {1, 8, 32} vs the honest un-chained single rate — the
        # gate's AMGCL_TPU_GATE_THROUGHPUT metric (ROADMAP item 1's
        # acceptance: b32 >= 4x the un-chained single-solve rate)
        _stage("throughput")
        try:
            _PARTIAL["throughput"] = _bench_throughput(solver, rhs_dev,
                                                       on_tpu)
        except Exception as e:
            _PARTIAL["throughput"] = {"error": repr(e)[:200]}
    if (on_tpu or os.environ.get("AMGCL_TPU_BENCH_FARM") == "1") \
            and _enough("farm", 240):
        # multi-tenant farm throughput under eviction pressure — the
        # AMGCL_TPU_GATE_FARM metric (agg_sps) rides the record
        _stage("farm")
        try:
            _PARTIAL["farm"] = _bench_farm(on_tpu)
        except Exception as e:
            _PARTIAL["farm"] = {"error": repr(e)[:200]}
    if (on_tpu or os.environ.get("AMGCL_TPU_BENCH_UNSTRUCT") == "1") \
            and _enough("unstructured", 320):
        _stage("unstructured spmv")
        try:
            _PARTIAL["unstructured"] = _bench_unstructured(on_tpu)
        except Exception as e:
            _PARTIAL["unstructured"] = {"error": repr(e)}
    if (on_tpu or os.environ.get("AMGCL_TPU_BENCH_XRAY") == "1") \
            and _enough("xray", 150):
        # the advisor-validation join (--xray) rides the worker record
        # so the gate's AMGCL_TPU_GATE_XRAY check scores it per round:
        # predicted reorder gain vs measured, same experiment the CLI
        # prints, just stored under 'xray' instead of its own record
        _stage("xray join")
        try:
            xrec = _xray_record(
                n=int(os.environ.get("AMGCL_TPU_XRAY_N", "4096")),
                bw=int(os.environ.get("AMGCL_TPU_XRAY_BW", "16")),
                local=int(os.environ.get("AMGCL_TPU_XRAY_LOCAL", "32")),
                seed=7)
            _PARTIAL["xray"] = {
                "value": xrec["value"], "n": xrec["n"], "bw": xrec["bw"],
                "advisor": xrec["advisor"], "join": xrec["join"],
                "end_to_end": xrec["end_to_end"],
                "formats": xrec["formats"]}
        except Exception as e:
            _PARTIAL["xray"] = {"error": repr(e)[:200]}
    if (on_tpu or os.environ.get("AMGCL_TPU_BENCH_EXTRA") == "1") \
            and _enough("extra_configs", 300):
        _stage("block + stokes configs")
        try:
            _PARTIAL["extra_configs"] = _bench_extra_configs(on_tpu)
        except Exception as e:
            _PARTIAL["extra_configs"] = {"error": repr(e)}
    loadN = os.getloadavg()
    _PARTIAL["telemetry"]["loadavg_end"] = [round(v, 2) for v in loadN]
    _PARTIAL["telemetry"]["contended"] = (
        _PARTIAL["telemetry"]["contended"] or loadN[0] / ncpu > 0.5)
    out = {"metric": _METRIC, "unit": "s"}
    out.update(_PARTIAL)
    if levels is not None:
        out["levels"] = levels
    _stdout_sink.emit(out)
    _sink.emit(dict(out), event="bench_worker")


def _bench_throughput(solver, rhs_dev, on_tpu, bs=(1, 8, 32)):
    """Solves/sec of the stacked multi-RHS path at each batch size in
    ``bs``, against the honest UN-CHAINED single-solve rate (every
    per-call overhead included — that is the number batching amortizes).
    ``solver`` is the headline bundle; the measurement builds a
    refine-free CG bundle SHARING its hierarchy (stacked solves gate
    out refinement), so no second setup cost is paid.

    Each row also carries SERVICE-measured per-request latency
    percentiles (``latency_ms`` p50/p99 + ``service_sps``): 2B requests
    pushed through a real ``SolverService`` at that bucket, so the
    BENCH_r* trend tracks serving latency — queue, padding and sync
    included — not just raw stacked solves/sec."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from amgcl_tpu.models.make_solver import make_solver
    from amgcl_tpu.solver.cg import CG
    slv = make_solver(solver.A_host, solver.precond,
                      CG(maxiter=100, tol=1e-6))
    rhs1 = jnp.asarray(rhs_dev, jnp.float32)

    def timed(call, warm=1, reps=3):
        for _ in range(warm):
            x, _ = call()
            jax.block_until_ready(x)
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            x, info = call()
            jax.block_until_ready(x)
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts)), info

    t1, info1 = timed(lambda: slv(rhs1))
    out = {"single_unchained_s": round(t1, 4),
           "single_unchained_sps": round(1.0 / t1, 3),
           "iters_b1": int(info1.iters), "rows": []}
    for B in bs:
        cols = np.stack([np.asarray(rhs_dev) * (1.0 + 0.1 * k)
                         for k in range(B)], axis=1)
        Rh = jnp.asarray(cols, jnp.float32)
        reps = 2 if B >= 8 and not on_tpu else 3
        tB, infoB = timed(lambda: slv(Rh), reps=reps)
        sps = B / tB
        row = {"B": int(B), "batch_s": round(tB, 4),
               "solves_per_sec": round(sps, 3),
               "iters_max": int(infoB.iters),
               "speedup_vs_single": round(sps * t1, 3)}
        row.update(_serve_latency(slv, rhs_dev, B))
        out["rows"].append(row)
        out["b%d_sps" % B] = row["solves_per_sec"]
        if row.get("latency_ms"):
            out["b%d_p99_ms" % B] = row["latency_ms"]["p99"]
    if "b32_sps" in out:
        out["speedup_b32_vs_single"] = round(out["b32_sps"] * t1, 3)
    return out


def _serve_latency(slv, rhs_dev, B, factor=2):
    """Per-request latency p50/p99 through a resident SolverService at
    bucket ``B`` — the serving numbers (queue wait + padding + solve +
    sync), not the bare stacked-dispatch rate. ``factor * B`` requests
    give the bucket at least two full batches. Never fails the bench:
    errors come back as ``latency_error``.

    This harness is CLOSED-LOOP (submit blocks when the queue fills, so
    the arrival process slows down with the server — coordinated
    omission), and its rows say so: ``closed_loop``/``latency_basis``
    label the service-measured ``latency_ms`` percentiles, and
    ``open_loop_latency_ms`` carries the honest companion derived from
    INTENDED arrivals — every request here is intended at t0 (a burst
    the loop would fire instantly if never blocked), so its open-loop
    latency is completion minus t0, queueing included. The open-loop
    storm harness (``bench --storm``) measures the same quantity under
    a real arrival process."""
    import numpy as np
    try:
        from amgcl_tpu.serve import SolverService
        reqs = max(factor * B, 4)
        # ONE device_get; per-submit np.asarray(rhs_dev) would pay a
        # full device->host transfer per request and compete with the
        # service worker for the device mid-measurement
        rhs_host = np.asarray(rhs_dev)
        import time as _time
        from amgcl_tpu.telemetry import metrics as _metrics
        with SolverService(slv, batch=B, flush_ms=5.0) as svc:
            # warm the (shape, B) bucket OUTSIDE the measured window:
            # the service's jitted entry has its own compile cache, so
            # without this the percentiles track cold XLA compiles
            # (and early partial-bucket compiles), not serving latency
            warm = [svc.submit(rhs_host, block=True)
                    for _ in range(max(B, 1))]
            for f in warm:
                f.result(timeout=600)
            done_t = []          # completion stamps (done callbacks —
            #                      list.append is atomic under the GIL)
            t0 = _time.perf_counter()
            futs = []
            for k in range(reqs):
                fut = svc.submit(
                    rhs_host * (1.0 + 0.1 * (k % max(B, 1))),
                    block=True)
                fut.add_done_callback(
                    lambda f: done_t.append(_time.perf_counter()))
                futs.append(fut)
            lats = [f.result(timeout=600)[1].serve["latency_ms"]
                    for f in futs]
            wall = _time.perf_counter() - t0
        out = {"closed_loop": True, "latency_basis": "submit"}
        if lats:
            out["latency_ms"] = {
                "p50": round(_metrics.percentile(lats, 50), 3),
                "p99": round(_metrics.percentile(lats, 99), 3),
                "max": round(max(lats), 3)}
        open_lats = [(t - t0) * 1e3 for t in done_t]
        if open_lats:
            out["open_loop_latency_ms"] = {
                "basis": "intended_arrival_t0",
                "p50": round(_metrics.percentile(open_lats, 50), 3),
                "p99": round(_metrics.percentile(open_lats, 99), 3),
                "max": round(max(open_lats), 3)}
        if wall > 0:
            out["service_sps"] = round(reqs / wall, 3)
        return out
    except Exception as e:            # noqa: BLE001 — latency detail is
        return {"latency_error": repr(e)[:120]}   # optional, the gate
        #                                           metric is b32_sps


def _bench_farm(on_tpu, tenants=3, rounds=6):
    """Multi-tenant farm throughput (serve/farm.py): ``tenants``
    distinct graded-Poisson operators round-robined through one
    SolverFarm under a byte budget capped at 75% of the resident set —
    every round pays real eviction/readmission traffic, which is the
    number the farm gate protects. Reports aggregate solves/sec across
    tenants, per-tenant p99 latency, the eviction/readmission counts
    and the registry hit/miss/rebuild counters (readmission must stay
    on the rebuild path: misses == tenants)."""
    import numpy as np
    import jax.numpy as jnp
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.serve.farm import SolverFarm
    from amgcl_tpu.solver.cg import CG
    from amgcl_tpu.utils.sample_problem import poisson3d
    base = int(os.environ.get("AMGCL_TPU_BENCH_FARM_N", "0")) \
        or (24 if on_tpu else 8)
    tenants = max(int(tenants), 2)
    rounds = max(int(rounds), 2)
    with SolverFarm(metrics_port=-9) as farm:
        rhs_by = {}
        for k in range(tenants):
            A, rhs = poisson3d(base + 2 * k)
            name = "t%d" % k
            farm.register(name, A, solver=CG(maxiter=100, tol=1e-6),
                          precond=AMGParams(dtype=jnp.float32,
                                            coarse_enough=200))
            rhs_by[name] = np.asarray(rhs)
        total = farm.stats()["pool"]["used_bytes"]
        farm.set_max_bytes(int(total * 0.75))
        # warm one round outside the measured window (cold compiles)
        for name, rhs in rhs_by.items():
            farm.solve(name, rhs)
        t0 = time.perf_counter()
        futs = []
        for _ in range(rounds):
            futs += [(name, farm.submit(name, rhs, block=True))
                     for name, rhs in rhs_by.items()]
        iters_max = 0
        for name, fut in futs:
            _x, rep = fut.result(timeout=farm.timeout_s + 600)
            iters_max = max(iters_max, int(rep.iters))
        wall = time.perf_counter() - t0
        stats = farm.stats()
    nreq = rounds * tenants
    out = {
        "tenants": tenants, "rounds": rounds, "n_base": base,
        "requests": nreq, "wall_s": round(wall, 4),
        "agg_sps": round(nreq / wall, 3) if wall > 0 else None,
        "evictions": stats["evictions"],
        "readmissions": stats["readmissions"],
        "registry": {k: stats["registry"][k]
                     for k in ("hits", "misses", "rebuilds")},
        "iters_max": iters_max,
        "pool_bytes": stats["pool"]["total_bytes"],
        "per_tenant": [
            {"tenant": r["tenant"], "requests": r["requests"],
             "p99_ms": (r.get("latency_ms") or {}).get("p99"),
             "slo_trips": r["slo_trips"],
             "unhealthy": r["unhealthy"]}
            for r in stats["tenants"]],
    }
    # the acceptance invariant, recorded where the gate can see it:
    # readmissions never paid a fresh setup
    out["rebuild_only_readmission"] = \
        stats["registry"]["misses"] <= tenants
    return out


def main_farm(args=None):
    """``bench.py --farm [T ...]``: measure the multi-tenant farm
    throughput (T tenants round-robin under an eviction-forcing byte
    budget) and emit ONE ``bench_farm`` JSONL record — the
    AMGCL_TPU_GATE_FARM metric is ``agg_sps``."""
    from amgcl_tpu.utils.axon_guard import apply_if_cpu_requested
    apply_if_cpu_requested()
    import jax
    nums = [int(a) for a in (args or []) if a.isdigit()]
    tenants = nums[0] if nums else 3
    rounds = nums[1] if len(nums) > 1 else 6
    on_tpu = jax.default_backend() == "tpu"
    rec = _bench_farm(on_tpu, tenants=tenants, rounds=rounds)
    dev0 = jax.devices()[0]
    print("farm (%d tenant(s) x %d round(s), base n=%d^3, %s): "
          "%.2f solves/s aggregate, %d eviction(s), %d readmission(s)"
          % (rec["tenants"], rec["rounds"], rec["n_base"],
             dev0.platform, rec["agg_sps"] or 0.0, rec["evictions"],
             rec["readmissions"]))
    for row in rec["per_tenant"]:
        print("  %-6s %3d request(s)  p99 %sms  slo_trips %d"
              % (row["tenant"], row["requests"], row["p99_ms"],
                 row["slo_trips"]))
    reg = rec["registry"]
    print("  registry: %d hit / %d miss / %d rebuild  "
          "(rebuild-only readmission: %s)"
          % (reg["hits"], reg["misses"], reg["rebuilds"],
             rec["rebuild_only_readmission"]))
    from amgcl_tpu.telemetry.comm import hw_provenance
    out = {"event": "bench_farm", **rec,
           "device": str(dev0), "device_platform": dev0.platform,
           "device_kind": getattr(dev0, "device_kind", None),
           "provenance": hw_provenance(),
           "commit": _git_head()}
    _stdout_sink.emit(out)
    _sink.emit(dict(out))
    return 0


def main_throughput(args=None):
    """``bench.py --throughput [B ...]``: measure the serving throughput
    curve (stacked multi-RHS solves/sec per batch size vs the un-chained
    single-solve rate) and emit ONE ``bench_throughput`` JSONL record.
    Problem size: AMGCL_TPU_THROUGHPUT_N, defaulting to the headline
    bench size on TPU and a small CPU-friendly size elsewhere."""
    from amgcl_tpu.utils.axon_guard import apply_if_cpu_requested
    apply_if_cpu_requested()
    import jax
    import jax.numpy as jnp
    from amgcl_tpu.utils.sample_problem import poisson3d
    from amgcl_tpu.models.make_solver import make_solver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.cg import CG

    bs = tuple(int(a) for a in (args or []) if a.isdigit()) or (1, 8, 32)
    on_tpu = jax.default_backend() == "tpu"
    n = int(os.environ.get("AMGCL_TPU_THROUGHPUT_N", "0")) \
        or (_N if on_tpu else 24)
    A, rhs = poisson3d(n)
    solver = make_solver(A, AMGParams(dtype=jnp.float32),
                         CG(maxiter=100, tol=1e-6))
    rec = _bench_throughput(solver, jnp.asarray(rhs, jnp.float32),
                            on_tpu, bs)
    dev0 = jax.devices()[0]
    print("throughput (n=%d^3, %s): single un-chained %.2f solves/s"
          % (n, dev0.platform, rec["single_unchained_sps"]))
    for row in rec["rows"]:
        lat = row.get("latency_ms") or {}
        print("  B=%-3d  %8.4f s/batch  %8.2f solves/s  (%.2fx single)%s"
              % (row["B"], row["batch_s"], row["solves_per_sec"],
                 row["speedup_vs_single"],
                 "  serve p50 %.1fms p99 %.1fms"
                 % (lat["p50"], lat["p99"]) if lat else ""))
    from amgcl_tpu.telemetry.comm import hw_provenance
    out = {"event": "bench_throughput", "n": n, **rec,
           "device": str(dev0), "device_platform": dev0.platform,
           "device_kind": getattr(dev0, "device_kind", None),
           "provenance": hw_provenance(),
           "commit": _git_head()}
    _stdout_sink.emit(out)
    _sink.emit(dict(out))
    return 0


# ===========================================================================
# scaling harness: weak+strong sweeps over the mesh, gated round-over-round
# ===========================================================================

_MULTICHIP_LATEST = os.path.join(_REPO, "MULTICHIP_LATEST.json")


def _scaling_problem(n, scale):
    """3D Poisson on an (n*scale, n, n) grid, slow dim stretched: rows
    scale linearly with ``scale`` while the strip-partition halo (the
    +-n^2 band reach) stays constant — the weak-scaling ladder, built by
    the SAME fixture the tests and audits use (poisson3d's ``nx``
    parameter). Rows divide every mesh size that divides n^3."""
    from amgcl_tpu.utils.sample_problem import poisson3d
    return poisson3d(n, nx=n * scale)


def _scaling_measure(solver_key, A, rhs, mesh, maxiter, tol, reps):
    """One (solver, mesh, problem) cell: warm once, then median-of-reps
    timed solves. Returns rows/iters/solve seconds/per-iteration
    seconds (the efficiency metric — iteration counts move with problem
    size, per-iteration time is the comparable quantity)."""
    import numpy as np
    import jax.numpy as jnp
    t_setup = 0.0
    if solver_key in ("dist_cg", "dist_cg_pipelined"):
        from amgcl_tpu.parallel.dist_matrix import DistDiaMatrix
        from amgcl_tpu.parallel.dist_solver import dist_cg
        Ad = DistDiaMatrix.from_csr(A, mesh, jnp.float64)
        dinv = jnp.asarray(A.diagonal(invert=True))
        rhs_d = jnp.asarray(rhs)
        pip = solver_key == "dist_cg_pipelined"

        def run():
            return dist_cg(Ad, mesh, rhs_d, dinv=dinv, maxiter=maxiter,
                           tol=tol, pipelined=pip)
    else:
        from amgcl_tpu.parallel.dist_amg import DistAMGSolver
        from amgcl_tpu.models.amg import AMGParams
        from amgcl_tpu.solver.cg import CG
        t0 = time.perf_counter()
        s = DistAMGSolver(A, mesh, AMGParams(),
                          CG(maxiter=maxiter, tol=tol))
        t_setup = time.perf_counter() - t0

        def run():
            x, info = s(rhs)
            return x, info.iters, info.resid
    out = run()                                  # compile + warm
    ts = []
    for _ in range(max(int(reps), 1)):
        t0 = time.perf_counter()
        out = run()
        ts.append(time.perf_counter() - t0)
    iters = max(int(out[1]), 1)
    solve_s = float(np.median(ts))
    row = {"rows": int(A.nrows), "iters": iters,
           "solve_s": round(solve_s, 5),
           "t_iter_s": round(solve_s / iters, 6)}
    if t_setup:
        row["setup_s"] = round(t_setup, 3)
    return row


def scaling_record(devices=None, base_n=None, solvers=None, maxiter=None,
                   tol=1e-6, reps=None):
    """The structured multichip record: weak + strong sweeps per
    distributed solver over the device ladder, measured comm
    attribution + per-shard imbalance at the largest mesh, and the
    collective census cross-checked against the declared
    ``DIST_CG_COLLECTIVES`` contract. Callable with small parameters
    from tests; ``bench.py --scaling`` drives it with the env defaults
    and emits/persists the result."""
    import jax
    import jax.numpy as jnp
    from amgcl_tpu.parallel.mesh import make_mesh
    from amgcl_tpu.telemetry import comm as C
    from amgcl_tpu.telemetry.ledger import DIST_CG_COLLECTIVES

    def _env_int(name, default):
        try:
            return int(os.environ.get(name, default))
        except ValueError:
            return int(default)

    base_n = base_n or _env_int("AMGCL_TPU_SCALING_N", 12)
    maxiter = maxiter or _env_int("AMGCL_TPU_SCALING_MAXITER", 50)
    reps = reps or _env_int("AMGCL_TPU_SCALING_REPS", 3)
    nd_avail = len(jax.devices())
    if devices is None:
        raw = os.environ.get("AMGCL_TPU_SCALING_DEVICES", "1,2,4,8")
        devices = [int(v) for v in raw.split(",") if v.strip()]
    devices = sorted(d for d in set(int(d) for d in devices)
                     if 1 <= d <= nd_avail
                     and (base_n ** 3) % d == 0)
    if not devices:
        devices = [1]
    if solvers is None:
        raw = os.environ.get("AMGCL_TPU_SCALING_SOLVERS",
                             "dist_cg,dist_cg_pipelined,dist_amg")
        solvers = [s.strip() for s in raw.split(",") if s.strip()]
    nd_max = devices[-1]
    prov = C.hw_provenance(make_mesh(nd_max))
    rec = {"event": "multichip_scaling", "schema": 2,
           "metric": "multichip_scaling",
           "base_n": base_n, "devices": devices,
           "maxiter": maxiter, "tol": tol, "reps": reps,
           "device_platform": prov.get("device_platform"),
           "device_kind": prov.get("device_kind"),
           "provenance": prov, "solvers": {}}

    # strong problem = the base grid; weak ladder scales x with nd
    A_strong, rhs_strong = _scaling_problem(base_n, 1)
    weak_cache = {1: (A_strong, rhs_strong)}

    def weak_problem(nd):
        if nd not in weak_cache:
            weak_cache[nd] = _scaling_problem(base_n, nd)
        return weak_cache[nd]

    for key in solvers:
        srec = {"weak": {"devices": devices, "cells": []},
                "strong": {"devices": devices, "cells": []}}
        if key in DIST_CG_COLLECTIVES:
            srec["collectives"] = dict(DIST_CG_COLLECTIVES[key])
        for nd in devices:
            mesh = make_mesh(nd)
            Aw, fw = weak_problem(nd)
            srec["weak"]["cells"].append(
                {"devices": nd, **_scaling_measure(
                    key, Aw, fw, mesh, maxiter, tol, reps)})
            srec["strong"]["cells"].append(
                {"devices": nd, **_scaling_measure(
                    key, A_strong, rhs_strong, mesh, maxiter, tol,
                    reps)})
        for mode in ("weak", "strong"):
            cells = srec[mode]["cells"]
            t0_, tN = cells[0]["t_iter_s"], cells[-1]["t_iter_s"]
            if t0_ and tN:
                eff = t0_ / tN
                if mode == "strong":
                    eff /= max(devices[-1] / devices[0], 1)
                srec[mode]["efficiency"] = round(eff, 4)
        rec["solvers"][key] = srec

    # comm attribution + per-shard imbalance at the largest mesh on the
    # weak (headline) problem — DIA strip operator, the dist_cg path
    mesh_max = make_mesh(nd_max)
    try:
        from amgcl_tpu.parallel.dist_matrix import DistDiaMatrix
        Aw, _fw = weak_problem(nd_max)
        Ad = DistDiaMatrix.from_csr(Aw, mesh_max, jnp.float64)
        attr = C.comm_attribution(Ad, mesh_max, solver="dist_cg")
        rec["comm"] = {k: v for k, v in attr.items()
                       if not k.startswith("_")}
        rec["imbalance"] = C.dist_resources(Ad, nd_max)
        spread = C.measure_shard_spread(Ad, mesh_max)
        if spread:
            rec["imbalance"]["measured"] = {
                "per_shard_us": spread["per_shard_us"],
                "spread": spread["spread"]}
    except Exception as e:
        rec["comm"] = {"error": repr(e)[:200]}

    # collective-census cross-check: the traced dist bodies vs the SAME
    # DIST_CG_COLLECTIVES table the comm model prices from
    if nd_max >= 2:
        try:
            from amgcl_tpu.analysis import jaxpr_audit as _ja
            census = {}
            ok = True
            for pip in (False, True):
                arec = _ja.audit_dist_cg(pipelined=pip, mesh=mesh_max)
                errs = [f for f in _ja.check_dist(arec)
                        if f["severity"] == "error"]
                census[arec["entry"].rsplit(".", 1)[1]] = {
                    "census": arec.get("collectives"),
                    "match": not errs}
                ok = ok and not errs
            rec["collectives_census"] = {"ok": ok, "bodies": census}
        except Exception as e:
            rec["collectives_census"] = {"ok": None,
                                         "error": repr(e)[:200]}

    # headline: the gate's round-over-round quantities (dist_cg at the
    # largest mesh; the first configured solver when dist_cg is absent)
    head_key = "dist_cg" if "dist_cg" in rec["solvers"] \
        else (solvers[0] if solvers else None)
    head = {"devices": nd_max}
    if head_key:
        srec = rec["solvers"][head_key]
        head["solver"] = head_key
        head["weak_efficiency"] = srec["weak"].get("efficiency")
        head["strong_efficiency"] = srec["strong"].get("efficiency")
        head["iters"] = srec["weak"]["cells"][-1]["iters"]
    pi = (rec.get("comm") or {}).get("per_iteration") or {}
    head["comm_fraction"] = pi.get("comm_fraction")
    head["wire_gbps"] = pi.get("wire_gbps")
    imb = (rec.get("imbalance") or {}).get("imbalance") or {}
    head["imbalance"] = imb.get("factor")
    rec["headline"] = head
    return rec


def main_scaling(args=None):
    """``bench.py --scaling``: run the weak+strong scaling sweep on the
    available mesh (8 virtual CPU devices are forced when the host
    platform is in play — the flag is a no-op on TPU), print the
    ladder, emit ONE structured ``multichip_scaling`` JSONL record and
    persist it to ``MULTICHIP_LATEST.json`` — the candidate
    ``--gate``/``--check`` score against the previous round's committed
    ``MULTICHIP_r*.json`` under ``AMGCL_TPU_GATE_MULTICHIP``."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    from amgcl_tpu.utils.axon_guard import apply_if_cpu_requested
    apply_if_cpu_requested()
    import jax
    jax.config.update("jax_enable_x64", True)

    rec = scaling_record()
    for key, srec in rec["solvers"].items():
        for mode in ("weak", "strong"):
            cells = srec[mode]["cells"]
            print("%s %s scaling: %s" % (key, mode, "  ".join(
                "nd=%d %.0f rows %.1fus/it" % (
                    c["devices"], c["rows"], c["t_iter_s"] * 1e6)
                for c in cells)))
            if srec[mode].get("efficiency") is not None:
                print("  %s efficiency (per-iteration): %.3f"
                      % (mode, srec[mode]["efficiency"]))
    head = rec["headline"]
    print("headline (nd=%d): weak eff %s, comm fraction %s, "
          "imbalance %s" % (head["devices"], head.get("weak_efficiency"),
                            head.get("comm_fraction"),
                            head.get("imbalance")))
    rec["commit"] = _git_head()
    _stdout_sink.emit(rec)
    _sink.emit(dict(rec))
    _sink.write_json_atomic(_MULTICHIP_LATEST, _sink.stamp(dict(rec)))
    base = _multichip_baseline()
    if base is not None:
        ok, checks = run_multichip_gate(rec, base)
        print("multichip gate vs %s: %s" % (
            base.get("path", "baseline"), "ok" if ok else "REGRESSION"))
        for c in checks:
            if c.get("status") != "ok":
                # the measured pair rides the failure line — a status
                # name alone sends the reader back to the JSON
                print("  %s: %s (candidate %s vs baseline %s, limit %s)"
                      % (c["check"], c["status"], c.get("candidate"),
                         c.get("last_good"), c.get("limit")))
    return 0


def multichip_tolerances():
    """Multichip gate tolerances:

      AMGCL_TPU_GATE_MULTICHIP — minimum allowed fraction of the
                              baseline's scaling efficiency (default
                              0.8: the candidate regresses when its
                              weak/strong per-iteration efficiency
                              drops below 80% of the previous round's);
                              0 disables every multichip check
      AMGCL_TPU_GATE_COMM_FRAC — maximum allowed ratio of the
                              baseline's measured comm fraction
                              (default 1.3, plus a 0.05 absolute slack
                              so near-zero fractions don't gate on
                              noise)
    """
    def _f(name, default):
        try:
            return float(os.environ.get(name, default))
        except ValueError:
            return float(default)

    return {"efficiency": _f("AMGCL_TPU_GATE_MULTICHIP", 0.8),
            "comm_frac": _f("AMGCL_TPU_GATE_COMM_FRAC", 1.3)}


def run_multichip_gate(candidate, baseline, tol=None):
    """Compare two structured multichip records round-over-round:
    scaling efficiency (higher is better, min-fraction floor) and
    measured comm fraction (lower is better, max-ratio ceiling +
    absolute slack). Platform-mismatched pairs skip every ratio — the
    provenance tag makes a CPU-fallback candidate vs a TPU baseline a
    platform change, not a regression (the same rule the bench gate
    applies to solve time)."""
    tol = tol or multichip_tolerances()
    checks = []
    if tol["efficiency"] <= 0:
        return True, [{"check": "multichip", "status": "skipped",
                       "reason": "disabled (AMGCL_TPU_GATE_MULTICHIP=0)"}]
    plat_c = _record_platform(candidate)
    plat_b = _record_platform(baseline)
    plat_skip = None
    if plat_c is not None and plat_b is not None and plat_c != plat_b:
        plat_skip = "platform_mismatch: candidate=%s baseline=%s" \
            % (plat_c, plat_b)
    hc = candidate.get("headline") or {}
    hb = baseline.get("headline") or {}

    def higher_better(name, cv, bv):
        if plat_skip is not None:
            checks.append({"check": name, "status": "skipped",
                           "reason": plat_skip, "candidate": cv,
                           "last_good": bv})
        elif cv is None or bv is None:
            checks.append({"check": name, "status": "skipped",
                           "candidate": cv, "last_good": bv})
        else:
            floor = bv * tol["efficiency"]
            checks.append({"check": name, "candidate": cv,
                           "last_good": bv, "limit": round(floor, 6),
                           "status": "ok" if cv >= floor
                           else "regression"})

    higher_better("weak_efficiency", hc.get("weak_efficiency"),
                  hb.get("weak_efficiency"))
    higher_better("strong_efficiency", hc.get("strong_efficiency"),
                  hb.get("strong_efficiency"))
    cf_c, cf_b = hc.get("comm_fraction"), hb.get("comm_fraction")
    if plat_skip is not None:
        checks.append({"check": "comm_fraction", "status": "skipped",
                       "reason": plat_skip, "candidate": cf_c,
                       "last_good": cf_b})
    elif cf_c is None or cf_b is None:
        checks.append({"check": "comm_fraction", "status": "skipped",
                       "candidate": cf_c, "last_good": cf_b})
    else:
        limit = cf_b * tol["comm_frac"] + 0.05
        checks.append({"check": "comm_fraction", "candidate": cf_c,
                       "last_good": cf_b, "limit": round(limit, 6),
                       "status": "ok" if cf_c <= limit
                       else "regression"})
    ok = not any(c["status"] == "regression" for c in checks)
    return ok, checks


def _multichip_candidate():
    """This round's scaling record (``--scaling`` writes it):
    ``AMGCL_TPU_GATE_MULTICHIP_CANDIDATE`` path override, else
    ``MULTICHIP_LATEST.json``. (None, src) when unreadable/absent."""
    path = os.environ.get("AMGCL_TPU_GATE_MULTICHIP_CANDIDATE",
                          _MULTICHIP_LATEST)
    try:
        with open(path) as f:
            return json.load(f), path
    except Exception:
        return None, path


def _multichip_baseline():
    """The previous round's committed structured multichip record —
    the newest schema-carrying ``MULTICHIP_r*.json`` (legacy dryrun
    logs carry no metrics to gate on)."""
    m = _load_metrics()
    rows = [r for r in m.multichip_history(_REPO)
            if not r.get("legacy_dryrun")]
    return rows[-1] if rows else None


def multichip_gate_record():
    """The multichip arm of ``--gate``/``--check``: None when the
    feature is unused (no candidate AND no structured baseline), a
    gate sub-record otherwise."""
    tol = multichip_tolerances()
    cand, src = _multichip_candidate()
    base = _multichip_baseline()
    if cand is None and base is None:
        return None
    if cand is None:
        return {"ok": True, "status": "no_candidate",
                "candidate_src": src, "tolerances": tol}
    if base is None:
        return {"ok": True, "status": "no_baseline",
                "candidate_src": src, "tolerances": tol}
    ok, checks = run_multichip_gate(cand, base, tol)
    out = {"ok": ok, "candidate_src": src,
           "baseline": base.get("path"), "tolerances": tol,
           "checks": checks}
    if not ok:
        # same contract as the bench gate: the failure record carries
        # the measured pairs + the cross-run attribution
        out["failed"] = gate_failures(checks)
        out["attribution"] = gate_attribution(cand, base)
    return out


# ===========================================================================
# storm: open-loop load harness + saturation record, gated round-over-round
# ===========================================================================

_STORM_LATEST = os.path.join(_REPO, "STORM_LATEST.json")


def _storm_env_f(name, default):
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return float(default)


def main_storm(args=None):
    """``bench.py --storm [--smoke] [--trace PATH]``: the OPEN-LOOP
    load harness. Builds a small multi-tenant SolverFarm, runs a seeded
    Poisson offered-load ladder (rates from ``AMGCL_TPU_STORM_RATES``
    or auto-calibrated from a quick closed-loop warm burst), then one
    mixed poisson/burst/ramp profile storm near the sustainable rate —
    every request timestamped at its SCHEDULED arrival so latency
    includes the queueing a closed-loop harness hides. Emits ONE
    schema-versioned ``bench_storm`` record (latency-vs-offered-load
    curve, saturation knee, goodput accounting, per-phase span
    attribution, scraped gauge series) and writes ``STORM_LATEST.json``
    — the ``AMGCL_TPU_GATE_STORM`` candidate. ``--smoke`` is the seeded
    ~10 s CI variant ``--check`` runs."""
    from amgcl_tpu.utils.axon_guard import apply_if_cpu_requested
    apply_if_cpu_requested()
    import numpy as np
    import jax
    import jax.numpy as jnp
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.serve import storm as S
    from amgcl_tpu.serve.farm import SolverFarm
    from amgcl_tpu.solver.cg import CG
    from amgcl_tpu.telemetry import load as L
    from amgcl_tpu.telemetry.comm import hw_provenance
    from amgcl_tpu.utils.sample_problem import poisson3d

    args = list(args or [])
    smoke = "--smoke" in args
    trace_path = os.environ.get("AMGCL_TPU_STORM_TRACE")
    if "--trace" in args:
        i = args.index("--trace")
        trace_path = args[i + 1] if i + 1 < len(args) else trace_path
    on_tpu = jax.default_backend() == "tpu"
    seed = int(os.environ.get("AMGCL_TPU_STORM_SEED", "0"))
    base = int(os.environ.get("AMGCL_TPU_STORM_N", "0")) \
        or (24 if on_tpu else 8)
    dur = _storm_env_f("AMGCL_TPU_STORM_DURATION_S", 0) \
        or (1.5 if smoke else 6.0)
    drain = _storm_env_f("AMGCL_TPU_STORM_DRAIN_S", 30.0)
    slo_ms = _storm_env_f("AMGCL_TPU_STORM_SLO_MS", 0) or None
    fault_plan = os.environ.get("AMGCL_TPU_STORM_FAULT_PLAN")
    n_tenants = 2

    with SolverFarm(metrics_port=0, flush_ms=5.0) as farm:
        rhs_by = {}
        for k in range(n_tenants):
            A, rhs = poisson3d(base + 2 * k)
            name = "t%d" % k
            farm.register(name, A, solver=CG(maxiter=100, tol=1e-6),
                          precond=AMGParams(dtype=jnp.float32,
                                            coarse_enough=200))
            rhs_by[name] = np.asarray(rhs)
        tenants = tuple(sorted(rhs_by))

        def rhs_for(tenant, rid):
            # mixed-content requests without a per-submit device trip
            return rhs_by[tenant] * (1.0 + 0.01 * (rid % 17))

        # warm EVERY tenant and every power-of-two bucket width the
        # storm can pack (1..batch) outside the measured window — an
        # open-loop storm against cold XLA compiles measures the
        # compiler, and ONE mid-rung bucket compile stalls the queue
        # long enough to poison the whole rung's percentiles
        for name, rhs in rhs_by.items():
            b = 1
            while b <= farm.batch:
                futs = [farm.submit(name, rhs, block=True)
                        for _ in range(b)]
                for f in futs:
                    f.result(timeout=600)
                b *= 2
        rates_env = os.environ.get("AMGCL_TPU_STORM_RATES")
        if rates_env:
            rates = [float(x) for x in rates_env.split(",")
                     if x.strip()]
        else:
            # auto-calibrate: the warm closed-loop service rate of a
            # short burst anchors the ladder so the top rung sits past
            # saturation on any hardware. TWO bursts: the first pays
            # the partial-bucket compiles its batch widths trigger,
            # only the second (warm) one is the measurement
            t0 = time.perf_counter()
            for _ in range(2):
                t0 = time.perf_counter()
                futs = [farm.submit(name, rhs, block=True)
                        for name, rhs in rhs_by.items()
                        for _ in range(3)]
                for f in futs:
                    f.result(timeout=600)
            closed_sps = (3 * n_tenants) \
                / max(time.perf_counter() - t0, 1e-6)
            anchor = max(closed_sps, 0.5)
            mult = (0.5, 1.0, 2.0) if smoke \
                else (0.4, 0.8, 1.2, 1.8, 2.5)
            rates = [round(anchor * m, 3) for m in mult]
        rungs = S.run_ladder(farm, rates, dur, rhs_for,
                             tenants=tenants, seed=seed,
                             drain_timeout_s=drain,
                             scrape_every_s=0.2,
                             fault_plan=fault_plan)
        # the mixed-phase profile storm near the sustainable rate:
        # per-phase span attribution + the Perfetto timeline source
        curve = L.ladder_curve(rungs)
        knee = L.detect_knee(curve, slo_p99_ms=slo_ms)
        ms_rate = knee.get("max_sustainable_rps") \
            or (rates[len(rates) // 2] if rates else 1.0)
        pdur = dur * (0.7 if smoke else 1.0)
        phases = [S.poisson_phase(0.8 * ms_rate, pdur),
                  S.burst_phase(0.5 * ms_rate, pdur,
                                burst_every_s=max(pdur / 3, 0.4),
                                burst_len=4),
                  S.ramp_phase(0.5 * ms_rate, 1.5 * ms_rate, pdur)]
        sched = S.build_schedule(phases, tenants=tenants, seed=seed)
        prof = S.run_storm(farm, sched, rhs_for,
                           drain_timeout_s=drain, scrape_every_s=0.2,
                           label="profile", fault_plan=fault_plan)
    by_phase = {}
    for s in prof["samples"]:
        by_phase.setdefault(s["phase"], []).append(s)
    prof_summary = {
        "phases": [{"kind": p["kind"], "rate_rps": p["rate_rps"],
                    "duration_s": p["duration_s"]} for p in phases],
        "summary": prof["summary"],
        "per_phase": {ph: L.summarize_samples(rows)
                      for ph, rows in sorted(by_phase.items())},
    }
    record = L.build_record(rungs, slo_p99_ms=slo_ms,
                            profile=prof_summary)
    # the concurrently scraped /metrics gauge time-series rides the
    # record (bounded), not just its rollup — queue-depth divergence is
    # visible in the raw series
    record["gauge_series"] = prof["gauges"][:400]
    if trace_path:
        trace = L.storm_timeline_trace(prof["samples"], prof["gauges"])
        with open(trace_path, "w") as f:
            json.dump(trace, f)
        print("storm timeline written to %s" % trace_path)
    dev0 = jax.devices()[0]
    kn = record["knee"]
    print("storm (%d tenant(s), base n=%d^3, %s, seed %d): "
          "%d request(s) over %d rung(s) + profile"
          % (len(tenants), base, dev0.platform, seed,
             record["goodput"]["requests"], len(rates)))
    for row in record["curve"]:
        print("  offered %8.2f rps  goodput %8s rps  p99 %8s ms  "
              "shed %s" % (row["offered_rps"],
                           row.get("goodput_rps"), row.get("p99_ms"),
                           row.get("shed_rate")))
    print("  knee: %s (max sustainable %s rps%s)"
          % (kn.get("reason") or "not reached",
             kn.get("max_sustainable_rps"),
             ", knee at %s rps" % kn["knee_offered_rps"]
             if kn.get("knee_offered_rps") else ""))
    out = {"event": "bench_storm", "record": record,
           "rates": rates, "duration_s": dur, "seed": seed,
           "smoke": smoke, "tenants": list(tenants), "n_base": base,
           "fault_plan": fault_plan,
           "device": str(dev0), "device_platform": dev0.platform,
           "device_kind": getattr(dev0, "device_kind", None),
           "provenance": hw_provenance(), "commit": _git_head()}
    _stdout_sink.emit(out)
    _sink.emit(dict(out))
    with open(_STORM_LATEST, "w") as f:
        json.dump(out, f, indent=1)
    return 0


def storm_tolerances():
    """Storm gate tolerances:

      AMGCL_TPU_GATE_STORM — minimum allowed fraction of the baseline's
                          max sustainable rate (default 0.7: the
                          candidate regresses when the rate its goodput
                          sustains below the knee drops under 70% of
                          the previous round's); 0 disables every storm
                          check
      AMGCL_TPU_GATE_STORM_P99 — maximum allowed ratio of the
                          baseline's p99 latency at the REFERENCE
                          offered load (the lowest ladder rung; default
                          1.5). Skipped when the two rounds' reference
                          rates differ by more than 25% — a ladder
                          recalibration changes the question, not the
                          answer.
    """
    return {"rate": _storm_env_f("AMGCL_TPU_GATE_STORM", 0.7),
            "p99": _storm_env_f("AMGCL_TPU_GATE_STORM_P99", 1.5)}


def run_storm_gate(candidate, baseline, tol=None):
    """Compare two ``bench_storm`` records round-over-round: max
    sustainable rate (higher is better, min-fraction floor) and p99 at
    the reference offered load (lower is better, max-ratio ceiling,
    comparability-gated on the reference rate). Platform-mismatched
    pairs skip every ratio via ``hw_provenance``/``device_platform`` —
    the multichip-gate rule."""
    tol = tol or storm_tolerances()
    if tol["rate"] <= 0:
        return True, [{"check": "storm", "status": "skipped",
                       "reason": "disabled (AMGCL_TPU_GATE_STORM=0)"}]
    checks = []
    plat_c = _record_platform(candidate)
    plat_b = _record_platform(baseline)
    plat_skip = None
    if plat_c is not None and plat_b is not None and plat_c != plat_b:
        plat_skip = "platform_mismatch: candidate=%s baseline=%s" \
            % (plat_c, plat_b)
    rc = candidate.get("record") or {}
    rb = baseline.get("record") or {}
    mc = (rc.get("knee") or {}).get("max_sustainable_rps")
    mb = (rb.get("knee") or {}).get("max_sustainable_rps")
    if plat_skip is not None:
        checks.append({"check": "storm_max_rps", "status": "skipped",
                       "reason": plat_skip, "candidate": mc,
                       "last_good": mb})
    elif mc is None or mb is None:
        checks.append({"check": "storm_max_rps", "status": "skipped",
                       "candidate": mc, "last_good": mb})
    else:
        floor = mb * tol["rate"]
        checks.append({"check": "storm_max_rps", "candidate": mc,
                       "last_good": mb, "limit": round(floor, 6),
                       "status": "ok" if mc >= floor
                       else "regression"})
    refc = rc.get("reference") or {}
    refb = rb.get("reference") or {}
    pc, pb = refc.get("p99_ms"), refb.get("p99_ms")
    ratec, rateb = refc.get("offered_rps"), refb.get("offered_rps")
    if plat_skip is not None:
        checks.append({"check": "storm_ref_p99", "status": "skipped",
                       "reason": plat_skip, "candidate": pc,
                       "last_good": pb})
    elif pc is None or pb is None or not ratec or not rateb:
        checks.append({"check": "storm_ref_p99", "status": "skipped",
                       "candidate": pc, "last_good": pb})
    elif abs(ratec - rateb) > 0.25 * max(ratec, rateb):
        checks.append({"check": "storm_ref_p99", "status": "skipped",
                       "reason": "reference_rate_mismatch: "
                                 "candidate=%s baseline=%s rps"
                                 % (ratec, rateb),
                       "candidate": pc, "last_good": pb})
    else:
        limit = pb * tol["p99"]
        checks.append({"check": "storm_ref_p99", "candidate": pc,
                       "last_good": pb, "limit": round(limit, 6),
                       "status": "ok" if pc <= limit
                       else "regression"})
    ok = not any(c["status"] == "regression" for c in checks)
    return ok, checks


def _storm_candidate():
    """This round's storm record (``--storm`` writes it):
    ``AMGCL_TPU_GATE_STORM_CANDIDATE`` path override, else
    ``STORM_LATEST.json``. (None, src) when unreadable/absent."""
    path = os.environ.get("AMGCL_TPU_GATE_STORM_CANDIDATE",
                          _STORM_LATEST)
    try:
        with open(path) as f:
            return json.load(f), path
    except Exception:
        return None, path


def _storm_baseline():
    """The previous round's committed storm record — the newest
    ``STORM_r*.json``."""
    m = _load_metrics()
    rows = m.storm_history(_REPO)
    return rows[-1] if rows else None


def storm_gate_record():
    """The storm arm of ``--gate``/``--check``: None when the feature
    is unused (no candidate AND no baseline), a gate sub-record
    otherwise — the multichip-arm contract."""
    tol = storm_tolerances()
    cand, src = _storm_candidate()
    base = _storm_baseline()
    if cand is None and base is None:
        return None
    if cand is None:
        return {"ok": True, "status": "no_candidate",
                "candidate_src": src, "tolerances": tol}
    if base is None:
        return {"ok": True, "status": "no_baseline",
                "candidate_src": src, "tolerances": tol}
    ok, checks = run_storm_gate(cand, base, tol)
    out = {"ok": ok, "candidate_src": src,
           "baseline": base.get("path"), "tolerances": tol,
           "checks": checks}
    if not ok:
        out["failed"] = gate_failures(checks)
    return out


# ===========================================================================
# regression gate: compare a candidate bench record against the last-good
# ===========================================================================

def gate_tolerances():
    """Gate tolerances, env-tunable so the supervisor can tighten them as
    the bench trajectory stabilizes:

      AMGCL_TPU_GATE_ITERS  — allowed ABSOLUTE iteration increase (def 2)
      AMGCL_TPU_GATE_TIME   — allowed solve-time ratio (default 1.25:
                              chained timings still jitter ~10-15% across
                              chip sessions, see BENCH_r0*.json)
      AMGCL_TPU_GATE_BYTES  — allowed peak-ledger-bytes ratio (def 1.10)
      AMGCL_TPU_GATE_THROUGHPUT — minimum allowed fraction of the
                              baseline's B=32 serving throughput
                              (default 0.75: the candidate regresses
                              when its b32 solves/sec drop below 75% of
                              last-good); skipped across
                              device_platform mismatches like the time
                              ratio
      AMGCL_TPU_GATE_HEALTH — 1 (default): fail when a previously-clean
                              record's candidate trips any health guard
                              (breakdown/NaN/stagnation/divergence);
                              0 disables the health check
      AMGCL_TPU_GATE_SETUP  — minimum allowed fraction of the baseline's
                              setup_vs_baseline (default 0.7: higher is
                              better, the candidate regresses when its
                              setup speed ratio drops below 70% of
                              last-good); rebuild_s is gated alongside
                              at the AMGCL_TPU_GATE_TIME ratio (lower
                              is better). 0 disables both setup checks;
                              both skip across device_platform
                              mismatches like the time ratio.
      AMGCL_TPU_GATE_FARM   — minimum allowed fraction of the baseline's
                              multi-tenant farm throughput (bench_farm
                              agg_sps; default 0.7 — eviction traffic
                              jitters more than the single-operator
                              path); platform-mismatch-skipped like the
                              other time gates. The same check also
                              fails a candidate whose readmissions left
                              the rebuild path (rebuild_only_readmission
                              false) regardless of speed.
      AMGCL_TPU_GATE_MEMDRIFT — allowed measured-vs-ledger drift-ratio
                              growth for the memwatch record (default
                              1.25: the candidate's |drift−1| may be at
                              most 1.25× the baseline's, floored at the
                              declared join tolerance so a clean
                              baseline does not gate noise); the leak
                              check itself is absolute — any leaked
                              owner bytes fail the round regardless.
      AMGCL_TPU_GATE_XRAY   — allowed predicted-vs-measured divergence
                              of the executed-reorder gain (the
                              ``bench --xray`` join the worker's xray
                              stage records; default 0.25: the
                              measured/predicted ratio must stay within
                              25% of 1). Skipped across device_platform
                              mismatches like the time ratio, and for
                              CPU-fallback joins that could only match
                              end-to-end (informational). 0 disables.
    """
    def _f(name, default):
        try:
            return float(os.environ.get(name, default))
        except ValueError:
            return float(default)

    return {"iters": _f("AMGCL_TPU_GATE_ITERS", 2),
            "time": _f("AMGCL_TPU_GATE_TIME", 1.25),
            "bytes": _f("AMGCL_TPU_GATE_BYTES", 1.10),
            "throughput": _f("AMGCL_TPU_GATE_THROUGHPUT", 0.75),
            "setup": _f("AMGCL_TPU_GATE_SETUP", 0.7),
            "farm": _f("AMGCL_TPU_GATE_FARM", 0.7),
            "memdrift": _f("AMGCL_TPU_GATE_MEMDRIFT", 1.25),
            "xray": _f("AMGCL_TPU_GATE_XRAY", 0.25)}


def _record_health_flags(rec):
    """Tripped health-guard names of a bench record (sorted list), or
    None when the record predates health telemetry (comparison
    skipped)."""
    h = rec.get("health")
    if not isinstance(h, dict):
        return None
    flags = h.get("flags")
    if flags is None:
        ok = h.get("ok")
        return None if ok is None else ([] if ok else ["unhealthy"])
    return sorted(str(f) for f in flags)


def _record_ledger_bytes(rec):
    """Peak hierarchy bytes of a bench record: the ledger summary when the
    record carries one, else the hierarchy stats' total (older records),
    else None (comparison skipped)."""
    led = rec.get("ledger") or {}
    v = led.get("hierarchy_bytes")
    if v is None:
        v = (rec.get("hierarchy") or {}).get("bytes")
    return v


def _record_platform(rec):
    """Device platform of a bench/scaling record — the ONE place every
    gate's platform-mismatch skip reads. Resolution order: the
    top-level field, the hardware-provenance stamp (newer records carry
    ``provenance.device_platform`` uniformly), then the CPU-fallback
    marker for records predating the split."""
    p = rec.get("device_platform")
    if p is None:
        p = (rec.get("provenance") or {}).get("device_platform")
    if p is None and rec.get("fallback"):
        return "cpu"
    return p


def run_gate(candidate, last_good, tol=None):
    """Compare ``candidate`` against ``last_good`` under the tolerances.

    Returns (ok, checks): one check row per metric — iterations (absolute
    slack), solve time and peak ledger bytes (ratios), plus the health
    check (tripped-guard count must not exceed the baseline's; env
    AMGCL_TPU_GATE_HEALTH=0 opts out). A metric missing on either side
    is 'skipped', not a regression (pre-ledger records carry no byte
    accounting, pre-health records no guard decode).

    The time/bytes ratios only compare records from the SAME
    ``device_platform``: a CPU-fallback candidate scored against a TPU
    last-good (or vice versa) is a platform change, not a perf
    regression — those checks report 'skipped' with the mismatch
    (BENCH_r05 compared a CPU 2.10 s run against a TPU 0.069 s baseline
    and the ratio meant nothing). Iteration count and health flags stay
    compared — the math is platform-independent."""
    tol = tol or gate_tolerances()
    checks = []

    def check(name, cand, base, limit, skip_reason=None):
        if skip_reason is not None:
            checks.append({"check": name, "status": "skipped",
                           "reason": skip_reason,
                           "candidate": cand, "last_good": base})
            return
        if cand is None or base is None:
            checks.append({"check": name, "status": "skipped",
                           "candidate": cand, "last_good": base})
            return
        checks.append({"check": name, "candidate": cand,
                       "last_good": base, "limit": round(limit, 6),
                       "status": "ok" if cand <= limit else "regression"})

    plat_c, plat_b = _record_platform(candidate), _record_platform(last_good)
    plat_skip = None
    if plat_c is not None and plat_b is not None and plat_c != plat_b:
        plat_skip = "platform_mismatch: candidate=%s last_good=%s" \
            % (plat_c, plat_b)
    it0 = last_good.get("iters")
    check("iters", candidate.get("iters"), it0,
          it0 + tol["iters"] if it0 is not None else 0)
    t0 = last_good.get("value")
    check("solve_time", candidate.get("value"), t0,
          t0 * tol["time"] if t0 is not None else 0,
          skip_reason=plat_skip)
    b0 = _record_ledger_bytes(last_good)
    check("ledger_bytes", _record_ledger_bytes(candidate), b0,
          b0 * tol["bytes"] if b0 is not None else 0,
          skip_reason=plat_skip)
    # serving throughput (bench_throughput / the worker's throughput
    # stage): HIGHER is better, so the check inverts — regression when
    # the candidate's B=32 solves/sec fall below the tolerance fraction
    # of the baseline's. Skipped across platforms and for records that
    # predate the metric.
    tp_c = (candidate.get("throughput") or {}).get("b32_sps")
    tp_b = (last_good.get("throughput") or {}).get("b32_sps")
    if tp_c is None and tp_b is None:
        pass          # neither record carries the metric: no check row
    elif plat_skip is not None:
        checks.append({"check": "throughput_b32", "status": "skipped",
                       "reason": plat_skip,
                       "candidate": tp_c, "last_good": tp_b})
    elif tp_c is None or tp_b is None:
        checks.append({"check": "throughput_b32", "status": "skipped",
                       "candidate": tp_c, "last_good": tp_b})
    else:
        floor = tp_b * tol["throughput"]
        checks.append({"check": "throughput_b32", "candidate": tp_c,
                       "last_good": tp_b, "limit": round(floor, 6),
                       "status": "ok" if tp_c >= floor
                       else "regression"})
    # multi-tenant farm throughput (bench_farm / the worker's farm
    # stage): higher-is-better like throughput_b32, same platform and
    # pre-metric skips. A candidate whose readmissions left the rebuild
    # path regresses outright — speed cannot buy back a broken registry.
    fm_c = (candidate.get("farm") or {}).get("agg_sps")
    fm_b = (last_good.get("farm") or {}).get("agg_sps")
    if fm_c is None and fm_b is None:
        pass          # neither record carries the metric: no check row
    elif plat_skip is not None:
        checks.append({"check": "farm_sps", "status": "skipped",
                       "reason": plat_skip,
                       "candidate": fm_c, "last_good": fm_b})
    elif fm_c is None or fm_b is None:
        checks.append({"check": "farm_sps", "status": "skipped",
                       "candidate": fm_c, "last_good": fm_b})
    else:
        floor = fm_b * tol.get("farm", 0.7)
        rebuild_ok = (candidate.get("farm") or {}).get(
            "rebuild_only_readmission", True)
        row = {"check": "farm_sps", "candidate": fm_c,
               "last_good": fm_b, "limit": round(floor, 6),
               "status": "ok" if (fm_c >= floor and rebuild_ok)
               else "regression"}
        if not rebuild_ok:
            row["reason"] = "readmission paid a fresh setup " \
                "(rebuild_only_readmission false)"
        checks.append(row)
    # setup speed + same-sparsity rebuild (ROADMAP item 2): both skip on
    # platform mismatch and on records predating the metrics.
    # setup_vs_baseline is higher-is-better (like throughput), the
    # rebuild time lower-is-better (like solve time).
    if tol.get("setup", 0) > 0:
        sv_c, sv_b = candidate.get("setup_vs_baseline"), \
            last_good.get("setup_vs_baseline")
        if sv_c is not None or sv_b is not None:
            if plat_skip is not None or sv_c is None or sv_b is None:
                checks.append({"check": "setup_vs_baseline",
                               "status": "skipped",
                               "reason": plat_skip,
                               "candidate": sv_c, "last_good": sv_b})
            else:
                floor = sv_b * tol["setup"]
                checks.append({
                    "check": "setup_vs_baseline", "candidate": sv_c,
                    "last_good": sv_b, "limit": round(floor, 6),
                    "status": "ok" if sv_c >= floor else "regression"})
        rb_c, rb_b = candidate.get("rebuild_s"), last_good.get("rebuild_s")
        if rb_c is not None or rb_b is not None:
            check("rebuild_s", rb_c, rb_b,
                  rb_b * max(tol["time"], 1.0) if rb_b is not None else 0,
                  skip_reason=plat_skip)
    # measured-vs-ledger drift (the memwatch record, ISSUE 18):
    # |drift_ratio − 1| may grow at most tol["memdrift"]× over the
    # baseline's, floored at the declared join tolerance so a clean
    # baseline (drift 1.0) does not gate measurement noise. Platform-
    # skipped: TPU padding/layout legitimately moves measured away
    # from the analytic model.
    md_c = (candidate.get("memwatch") or {}).get("drift_ratio")
    md_b = (last_good.get("memwatch") or {}).get("drift_ratio")
    if md_c is None and md_b is None:
        pass          # neither record carries the metric: no check row
    elif plat_skip is not None:
        checks.append({"check": "memwatch_drift", "status": "skipped",
                       "reason": plat_skip,
                       "candidate": md_c, "last_good": md_b})
    elif md_c is None or md_b is None:
        checks.append({"check": "memwatch_drift", "status": "skipped",
                       "candidate": md_c, "last_good": md_b})
    else:
        try:
            from amgcl_tpu.telemetry.memwatch import declared_tolerance
            floor_tol = declared_tolerance()
        except Exception:
            floor_tol = 0.25
        limit = max(abs(md_b - 1.0) * tol.get("memdrift", 1.25),
                    floor_tol)
        checks.append({"check": "memwatch_drift",
                       "candidate": round(abs(md_c - 1.0), 6),
                       "last_good": round(abs(md_b - 1.0), 6),
                       "limit": round(limit, 6),
                       "status": "ok" if abs(md_c - 1.0) <= limit
                       else "regression"})
    # predicted-vs-measured reorder gain (the bench --xray join, ISSUE
    # 20): the candidate's measured gain must stay within tol["xray"]
    # of its OWN prediction — a drifting join means the executed
    # reorder no longer delivers what the advisor priced, i.e. either
    # the cost model or the execution seam regressed. Checked against
    # the candidate alone (the ratio is self-relative); the last_good
    # side only decides whether the metric exists for this trajectory.
    xtol = tol.get("xray", 0.25)
    xj_c = (candidate.get("xray") or {}).get("join") or {}
    xj_b = (last_good.get("xray") or {}).get("join") or {}
    xr_c, xr_b = xj_c.get("ratio"), xj_b.get("ratio")
    if (xr_c is None and xr_b is None) or xtol <= 0:
        pass          # neither record carries the join: no check row
    elif plat_skip is not None:
        checks.append({"check": "xray_join", "status": "skipped",
                       "reason": plat_skip,
                       "candidate": xr_c, "last_good": xr_b})
    elif xr_c is None:
        checks.append({"check": "xray_join", "status": "skipped",
                       "candidate": xr_c, "last_good": xr_b})
    elif xj_c.get("informational") and xj_c.get("fallback"):
        checks.append({"check": "xray_join", "status": "skipped",
                       "reason": "cpu-fallback end-to-end join is "
                       "informational (format winners differ between "
                       "the orderings, so time does not track the "
                       "byte model off-TPU)",
                       "candidate": xr_c, "last_good": xr_b})
    else:
        checks.append({"check": "xray_join",
                       "candidate": round(abs(xr_c - 1.0), 6),
                       "last_good": round(abs(xr_b - 1.0), 6)
                       if xr_b is not None else None,
                       "limit": round(xtol, 6),
                       "status": "ok" if abs(xr_c - 1.0) <= xtol
                       else "regression"})
    if os.environ.get("AMGCL_TPU_GATE_HEALTH", "1") != "0":
        # flag IDENTITIES, not counts: any guard the baseline did not
        # trip is a regression (a candidate swapping a warning-level
        # stagnation for a fatal breakdown must not pass on 1 <= 1)
        h0 = _record_health_flags(last_good)
        hc = _record_health_flags(candidate)
        if h0 is None or hc is None:
            checks.append({"check": "health_flags", "status": "skipped",
                           "candidate": hc, "last_good": h0})
        else:
            new = sorted(set(hc) - set(h0))
            checks.append({"check": "health_flags", "candidate": hc,
                           "last_good": h0, "new_flags": new,
                           "status": "ok" if not new else "regression"})
    ok = not any(c["status"] == "regression" for c in checks)
    return ok, checks


def _gate_last_good():
    """Gate baseline record: AMGCL_TPU_GATE_LAST_GOOD overrides the repo
    BENCH_LAST_GOOD.json (tests and ad-hoc comparisons)."""
    path = os.environ.get("AMGCL_TPU_GATE_LAST_GOOD", _LAST_GOOD_PATH)
    try:
        with open(path) as f:
            return json.load(f)
    except Exception:
        return None


def main_gate(args=None):
    """``bench.py --gate [candidate.json]``: exit 0 when the candidate
    (default: the last-good record itself — the self-consistency run CI
    gets) stays within tolerances of the last-good record, 1 on a
    regression, 2 on an unreadable candidate. Emits ONE JSONL record
    either way."""
    tol = gate_tolerances()
    lg = _gate_last_good()
    cand_src = "last_good"
    cand = lg
    if args:
        cand_src = args[0]
        try:
            with open(cand_src) as f:
                cand = json.load(f)
        except Exception as e:
            rec = {"event": "bench_gate", "ok": False,
                   "error": "unreadable candidate %r: %r" % (cand_src, e)}
            _stdout_sink.emit(rec)
            _sink.emit(dict(rec))
            return 2
    if lg is None or cand is None:
        rec = {"event": "bench_gate", "ok": True, "status": "no_baseline",
               "tolerances": tol}
        _stdout_sink.emit(rec)
        _sink.emit(dict(rec))
        return 0
    ok, checks = run_gate(cand, lg, tol)
    rec = {"event": "bench_gate", "ok": ok, "candidate_src": cand_src,
           "tolerances": tol, "checks": checks, "commit": _git_head()}
    if not ok:
        # failed checks with their measured candidate/baseline pairs in
        # one place, plus the automatic cross-run attribution — the
        # post-hoc `--why` answer rides the failure record itself
        rec["failed"] = gate_failures(checks)
        rec["attribution"] = gate_attribution(cand, lg)
    # multichip arm: this round's --scaling record vs the previous
    # round's committed MULTICHIP_r*.json (AMGCL_TPU_GATE_MULTICHIP)
    mc = multichip_gate_record()
    if mc is not None:
        rec["multichip"] = mc
        ok = ok and mc["ok"]
        rec["ok"] = ok
    # storm arm: this round's --storm record vs the previous round's
    # committed STORM_r*.json (AMGCL_TPU_GATE_STORM)
    st = storm_gate_record()
    if st is not None:
        rec["storm"] = st
        ok = ok and st["ok"]
        rec["ok"] = ok
    _stdout_sink.emit(rec)
    _sink.emit(dict(rec))
    return 0 if ok else 1


def gate_failures(checks):
    """The regression rows of a gate run, with the measured
    candidate/baseline pair each (so post-hoc tooling never re-derives
    them from the tolerance and the limit)."""
    return [{"check": c["check"], "candidate": c.get("candidate"),
             "baseline": c.get("last_good"), "limit": c.get("limit"),
             **({"reason": c["reason"]} if c.get("reason") else {})}
            for c in checks if c.get("status") == "regression"]


def gate_attribution(cand, base):
    """Automatic cross-run attribution of a gate failure: the
    ``telemetry/diff.py`` record of candidate-vs-baseline, stage rows
    bounded for the JSONL event. Never raises — a broken diff must not
    mask the gate verdict."""
    try:
        dm = _load_diff()
        d = dm.compact(dm.diff(base, cand))
        print(dm.format_diff(d), file=sys.stderr)
        return d
    except Exception as e:     # noqa: BLE001
        return {"error": repr(e)[:200]}


# ===========================================================================
# why: cross-run regression attribution (stdlib-only, telemetry/diff.py)
# ===========================================================================

def main_why(args=None):
    """``bench.py --why A.json B.json``: structured attribution of the
    delta between two records of the same kind — A is the baseline /
    older run, B the candidate / newer one. Wraps ``telemetry/diff.py``
    (stage join over the ledger stage keys + roofline rows, exact
    iterations-vs-per-iteration wall split, compile/comm call-outs).
    Exit 2 on unreadable/mismatched inputs; exit 0 otherwise — the
    attribution is a report, the GATE is the verdict."""
    args = [a for a in (args or []) if not a.startswith("-")]
    if len(args) < 2:
        print("usage: bench.py --why A.json B.json", file=sys.stderr)
        return 2
    recs = []
    for path in args[:2]:
        try:
            with open(path) as f:
                recs.append(json.load(f))
        except Exception as e:
            print("unreadable record %r: %r" % (path, e),
                  file=sys.stderr)
            return 2
    dm = _load_diff()
    d = dm.diff(recs[0], recs[1])
    print(dm.format_diff(d))
    rec = {"event": "bench_why", "a": args[0], "b": args[1],
           "diff": dm.compact(d), "commit": _git_head()}
    _stdout_sink.emit(rec)
    _sink.emit(dict(rec))
    return 2 if d.get("error") else 0


# ===========================================================================
# trend: cross-round trajectory + percentile rollups (stdlib-only)
# ===========================================================================

def trend_summary(metrics_mod=None):
    """The cross-PR trend over the committed ``BENCH_r*.json`` rounds:
    {"rows": per-round headline fields, "rollups": p50/p90/p99 per
    column}. Pre-ledger/pre-roofline rounds contribute gaps, never
    errors."""
    m = metrics_mod or _load_metrics()
    history = m.bench_history(_REPO)
    rows = m.trend(history)
    # the raw records ride along (underscored: not for the JSONL
    # record) so --trend's why-attribution reuses them instead of
    # re-reading every BENCH_r*.json from disk
    return {"rows": rows, "rollups": m.trend_rollups(rows),
            "_history": history}


def _annotate_trend_why(rows, history):
    """Attach the ``why`` column to trend rows IN PLACE: for each round
    whose solve time regressed beyond the gate's time tolerance against
    the previous round (same platform), the top attributed contributor
    of ``telemetry/diff.py``; None (rendered '-') everywhere else,
    including rounds whose predecessor predates per-stage data (the
    label then degrades to the coarse iterations/per-iteration bucket
    the wall split can still name)."""
    dm = _load_diff()
    limit = gate_tolerances()["time"]
    prev_row = prev_rec = None
    for rec, row in zip(history, rows):
        row.setdefault("why", None)
        if prev_row is not None:
            t0, t1 = prev_row.get("solve_s"), row.get("solve_s")
            if t0 and t1 and t1 > t0 * limit:
                row["why"] = dm.why(prev_rec, rec)
        prev_row, prev_rec = row, rec


def main_trend(args=None):
    """``bench.py --trend [sink.jsonl]``: print the cross-round table
    (BENCH_r01.. on disk) + rollups, optionally aggregate a telemetry
    JSONL file's solve/bench events too; ``--prom PATH`` writes the
    rollups as Prometheus exposition text. Emits ONE JSONL record."""
    m = _load_metrics()
    args = list(args or [])
    prom_path = None
    if "--prom" in args:
        i = args.index("--prom")
        prom_path = args[i + 1] if i + 1 < len(args) else None
        del args[i:i + 2]
    summ = trend_summary(m)
    # the why column: each round-over-round regression beyond the
    # gate's time tolerance gets the top attributed stage from
    # telemetry/diff.py ('-' gap when the older record predates
    # per-stage data or the platforms differ)
    try:
        _annotate_trend_why(summ["rows"], summ["_history"])
    except Exception:       # noqa: BLE001 — attribution is a bonus
        pass                # column; the table must still render
    print(m.format_trend(summ["rows"],
                         m.TREND_FIELDS + [("why", "why")]))
    rollups = dict(summ["rollups"])
    rec = {"event": "bench_trend", "rows": summ["rows"],
           "rollups": summ["rollups"], "commit": _git_head()}
    # multichip trajectory alongside the BENCH_r* table: structured
    # rounds carry efficiency/comm-fraction/imbalance, legacy dryrun
    # rounds degrade to device-count-only rows with gaps
    mc_hist = m.multichip_history(_REPO)
    if mc_hist:
        mc_rows = m.trend(mc_hist, m.MULTICHIP_TREND_FIELDS)
        print("\nmultichip trajectory (MULTICHIP_r*.json):")
        print(m.format_trend(mc_rows, m.MULTICHIP_TREND_FIELDS))
        rec["multichip_rows"] = mc_rows
        mc_roll = m.trend_rollups(mc_rows, m.MULTICHIP_TREND_FIELDS)
        for name, r in mc_roll.items():
            rollups["multichip_" + name] = r
    # storm trajectory: max sustainable rate + reference-load p99 per
    # committed STORM_r*.json round
    st_hist = m.storm_history(_REPO)
    if st_hist:
        st_rows = m.trend(st_hist, m.STORM_TREND_FIELDS)
        print("\nstorm trajectory (STORM_r*.json):")
        print(m.format_trend(st_rows, m.STORM_TREND_FIELDS))
        rec["storm_rows"] = st_rows
        st_roll = m.trend_rollups(st_rows, m.STORM_TREND_FIELDS)
        for name, r in st_roll.items():
            rollups["storm_" + name] = r
    if args:
        sink_records = m.iter_jsonl(args[0])
        ev_roll = m.rollup_events(sink_records)
        rec["sink"] = {"path": args[0], "records": len(sink_records),
                       "rollups": ev_roll}
        rollups.update(ev_roll)
        if ev_roll:
            print("\nsink rollups (%s, %d records):"
                  % (args[0], len(sink_records)))
            for name in sorted(ev_roll):
                r = ev_roll[name]
                print("  %-28s n=%-4d p50=%-10.4g p90=%-10.4g "
                      "p99=%.4g" % (name, r["count"], r["p50"],
                                    r["p90"], r["p99"]))
    if prom_path:
        with open(prom_path, "w") as f:
            f.write(m.prometheus_text(rollups))
        print("\nprometheus text written to %s" % prom_path)
    _stdout_sink.emit(rec)
    _sink.emit(dict(rec))
    return 0


# ===========================================================================
# vecbench: fused vector kernels vs their composed counterparts
# ===========================================================================

def main_vecbench(args=None):
    """``bench.py --vecbench [n ...]``: time the fused vector-algebra
    primitives (ops/fused_vec.py) against the composed axpby+dot
    reference per vector size and emit ONE ``bench_vecbench`` JSONL
    record — so the fusion win is tracked round-over-round like the
    solve metric. Each arm chains ``reps`` data-dependent applications
    inside one jitted scan (both carries thread every output, so
    neither arm can dead-code its updates) and reports median
    per-application microseconds."""
    from amgcl_tpu.utils.axon_guard import apply_if_cpu_requested
    apply_if_cpu_requested()
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax import lax
    from amgcl_tpu.ops import fused_vec as fv

    sizes = [int(a) for a in (args or []) if a.isdigit()]
    on_tpu = jax.default_backend() == "tpu"
    if not sizes:
        sizes = [1 << k for k in ((16, 18, 20, 22) if on_tpu
                                  else (14, 16, 18))]
    reps = 32 if on_tpu else 8
    repeats = 5

    def timeit(step, init, ops):
        # the carry AND the operand vectors ride as jit ARGUMENTS: a
        # closed-over init would let XLA constant-fold the whole chain
        # (measuring nothing), and closure operands embed megabytes of
        # MLIR constants (see _timed_chain's tunnel note)
        def many(st, ops):
            out, _ = lax.scan(lambda c, _: (step(c, ops), None),
                              step(st, ops), None, length=reps - 1)
            return out[-1]
        f = jax.jit(many)
        jax.block_until_ready(f(init, ops))     # compile + warm
        ts = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            jax.block_until_ready(f(init, ops))
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts)) / reps

    rows = []
    for n in sizes:
        rng = np.random.RandomState(7)
        p, q, x, r = (jnp.asarray(rng.standard_normal(n), jnp.float32)
                      for _ in range(4))
        alpha = jnp.float32(0.37)
        mode = fv._pallas_mode(x)
        path = "xla" if mode is None else (
            "pallas-interpret" if mode else "pallas")

        # -- xr_update: the CG tail -------------------------------------
        def xr_fused(st, ops):
            xc, rc, rr = st
            pp, qq = ops
            a = alpha * (1 + 0 * rr)    # data-depend on the prior dot
            return fv.xr_update(a, pp, qq, xc, rc)

        def xr_composed(st, ops):
            xc, rc, rr = st
            pp, qq = ops
            a = alpha * (1 + 0 * rr)
            xn = xc + a * pp
            rn = rc - a * qq
            return xn, rn, jnp.vdot(rn, rn)

        init_xr = (x, r, jnp.float32(0))
        t_f = timeit(xr_fused, init_xr, (p, q))
        t_c = timeit(xr_composed, init_xr, (p, q))

        # -- axpby_dot --------------------------------------------------
        def ax_fused(st, ops):
            z, zz = st
            (pp,) = ops
            a = alpha * (1 + 0 * zz)
            return fv.axpby_dot(a, pp, 0.5, z)

        def ax_composed(st, ops):
            z, zz = st
            (pp,) = ops
            a = alpha * (1 + 0 * zz)
            zn = a * pp + 0.5 * z
            return zn, jnp.vdot(zn, zn)

        init_ax = (x, jnp.float32(0))
        a_f = timeit(ax_fused, init_ax, (p,))
        a_c = timeit(ax_composed, init_ax, (p,))

        # -- stacked (n, B) tier: one fused pass retires B columns ------
        Bb = 8
        Pb, Qb, Xb, Rb = (jnp.asarray(
            rng.standard_normal((n, Bb)), jnp.float32) for _ in range(4))

        def xr_batched(st, ops):
            xc, rc, rr = st
            pp, qq = ops
            a = alpha * (1 + 0 * rr)    # (Bb,) per-column scalars
            return fv.xr_update(a, pp, qq, xc, rc)

        init_b = (Xb, Rb, jnp.zeros(Bb, jnp.float32))
        t_b = timeit(xr_batched, init_b, (Pb, Qb))
        rows.append({
            "n": n, "path": path,
            "xr_b8_us": round(t_b * 1e6, 3),
            "xr_b8_per_rhs_us": round(t_b / Bb * 1e6, 3),
            # per-rhs win of the stacked pass vs B single fused passes
            "xr_b8_vs_single": round(t_f / max(t_b / Bb, 1e-12), 3),
            "xr_update_us": round(t_f * 1e6, 3),
            "xr_composed_us": round(t_c * 1e6, 3),
            "xr_speedup": round(t_c / max(t_f, 1e-12), 3),
            "axpby_dot_us": round(a_f * 1e6, 3),
            "axpby_composed_us": round(a_c * 1e6, 3),
            "axpby_speedup": round(a_c / max(a_f, 1e-12), 3)})
        print("n=%-9d %-17s xr %8.2f vs %8.2f us (%.2fx)   axpby_dot "
              "%8.2f vs %8.2f us (%.2fx)"
              % (n, path, rows[-1]["xr_update_us"],
                 rows[-1]["xr_composed_us"], rows[-1]["xr_speedup"],
                 rows[-1]["axpby_dot_us"], rows[-1]["axpby_composed_us"],
                 rows[-1]["axpby_speedup"]))
    dev0 = jax.devices()[0]
    from amgcl_tpu.telemetry.comm import hw_provenance
    rec = {"event": "bench_vecbench", "rows": rows,
           "fused_enabled": fv.fused_vec_enabled(),
           "device": str(dev0), "device_platform": dev0.platform,
           "device_kind": getattr(dev0, "device_kind", None),
           "provenance": hw_provenance(),
           "commit": _git_head()}
    _stdout_sink.emit(rec)
    _sink.emit(dict(rec))
    return 0


# ===========================================================================
# tier-1 check: run the ROADMAP pytest line, emit DOTS_PASSED as JSONL
# ===========================================================================

_DOTS_RE = re.compile(r"^[.FEsx]+( *\[ *[0-9]+%\])?$")

# the ROADMAP tier-1 invocation, minus the shell plumbing
_TIER1_ARGS = ["-m", "pytest", "-q", "-m", "not slow",
               "--continue-on-collection-errors", "-p", "no:cacheprovider",
               "-p", "no:xdist", "-p", "no:randomly"]


def count_dots(text: str) -> int:
    """DOTS_PASSED: '.' characters on pytest -q progress lines — the same
    grep the ROADMAP tier-1 line applies to its log (char class kept
    identical on purpose, quirks included, so the two metrics never
    disagree)."""
    return sum(line.count(".") for line in text.splitlines()
               if _DOTS_RE.match(line.strip()))


def _xray_record(n, bw, local, seed):
    """Build the ``bench_xray`` record for one permuted-banded operator
    (the measurement body shared by ``--xray`` and the bench worker's
    xray stage — one copy of the chained-SpMV protocol, so the gate's
    ``xray_join`` check always scores the same experiment the CLI
    prints)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from amgcl_tpu.telemetry import structure as _structure
    from amgcl_tpu.telemetry.comm import hw_provenance
    from amgcl_tpu.ops import device as dev
    from amgcl_tpu.utils.adapters import cuthill_mckee, permute

    A, _A0, _perm = _structure.permuted_banded(n, bw=bw, seed=seed,
                                               local=local or None)
    rcm = cuthill_mckee(A)
    B = permute(A, rcm)
    on_tpu = jax.default_backend() == "tpu"
    # the prediction: exactly the advisor row cli --xray would print
    # for this operator (candidate tables identity vs RCM)
    adv = _structure.advise(A, variants=("rcm",), on_tpu=on_tpu)
    best = adv.get("best") or {}
    best_fmt = best.get("format")
    predicted = (best.get("per_format") or {}).get(best_fmt)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(n).astype(np.float32))

    def build(mat, fmt):
        """Device matrix for one candidate format, None when the
        format declines this structure (exactly what the X-ray table
        records as ineligible)."""
        try:
            if fmt == "ell":
                return dev.csr_to_ell(mat)
            if fmt == "dia":
                # the DIA SpMV unrolls one fused multiply-add per
                # diagonal — thousands of diagonals (the scrambled
                # identity ordering) would build an absurd XLA graph,
                # the same reason auto rejects it
                if len(dev._dia_offsets(mat)) > 512:
                    return None
                return dev.csr_to_dia(mat)
            if fmt == "well":
                from amgcl_tpu.ops.unstructured import \
                    csr_to_windowed_ell
                return csr_to_windowed_ell(mat, max_win_bytes=4 << 20)
            if fmt == "dwin":
                from amgcl_tpu.ops.densewin import csr_to_dense_window
                return csr_to_dense_window(mat)
        except Exception:
            return None

    chain = 16

    def time_spmv(M, reps=7):
        """Per-SpMV seconds, measured as a CHAIN of data-dependent
        applications inside one dispatch — a single spmv at these
        sizes is µs-scale and would drown in per-call dispatch
        overhead (the bench _timed_chain lesson). Min-of-reps: the
        joined quantity is a RATIO of two such measurements, and on a
        shared host the best case is the one uncontaminated by
        interference (median would fold ambient load into whichever
        side ran during a busy window)."""
        if M is None:
            return None

        def chained(v):
            for _ in range(chain):       # square operator: y feeds x
                v = dev.spmv(M, v)
            return v

        f = jax.jit(chained)
        jax.block_until_ready(f(x))          # compile + warm
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(f(x))
            ts.append(time.perf_counter() - t0)
        return float(min(ts)) / chain

    rows = []
    best_id = best_rcm = None
    matched = {}
    per_format_pred = best.get("per_format") or {}
    for fmt in ("ell", "dia", "well", "dwin"):
        t_id = time_spmv(build(A, fmt))
        t_rcm = time_spmv(build(B, fmt))
        row = {"format": fmt,
               "t_identity_s": round(t_id, 7) if t_id else None,
               "t_rcm_s": round(t_rcm, 7) if t_rcm else None}
        if t_id and t_rcm:
            row["gain"] = round(t_id / t_rcm, 4)
            matched[fmt] = row["gain"]
            if per_format_pred.get(fmt):
                row["predicted_gain"] = per_format_pred[fmt]
        rows.append(row)
        if t_id is not None and (best_id is None or t_id < best_id):
            best_id = t_id
        if t_rcm is not None and (best_rcm is None or t_rcm < best_rcm):
            best_rcm = t_rcm
    measured = matched.get(best_fmt)
    e2e = round(best_id / best_rcm, 4) if best_id and best_rcm else None
    prov = hw_provenance()
    join = {"format": best_fmt, "predicted_gain": predicted,
            "measured_gain": measured,
            "informational": prov.get("platform_tag") != "ici"}
    if measured is None and e2e is not None:
        # the matched pair could not be built on one side — fall back
        # to the cross-format end-to-end gain, flagged as such
        join["fallback"] = "end_to_end"
        measured = e2e
        join["measured_gain"] = measured
        predicted = best.get("gain")
        join["predicted_gain"] = predicted
    if predicted and measured:
        join["ratio"] = round(measured / predicted, 4)
        join["within_25pct"] = bool(abs(join["ratio"] - 1.0) <= 0.25)
    rec = {"event": "bench_xray", "metric": "xray_reorder_gain",
           "value": measured, "unit": "x", "n": n, "bw": bw,
           "local": local, "seed": seed, "provenance": prov,
           "device_platform": prov.get("device_platform"),
           "advisor": {"predicted_gain": best.get("gain"),
                       "predicted_format_gain": predicted,
                       "best_format": best_fmt,
                       "densify": best.get("densify")},
           "end_to_end": {"measured_gain": e2e,
                          "predicted_gain": best.get("gain")},
           "formats": rows, "join": join, "commit": _git_head()}
    return rec


def main_xray(args=None):
    """``bench.py --xray``: the advisor-validation microbenchmark
    (ISSUE 14 satellite) — ONE unstructured operator (the
    permuted-banded fixture from telemetry/structure.py: a band
    scrambled by a block-local symmetric permutation, the matrix class
    the reorder advisor exists for), SpMV measured per candidate
    device format under the identity ordering and under RCM, joined
    against the X-ray's PREDICTED reorder gain. The headline join is
    MECHANISM-MATCHED: the advisor's winning format measured on both
    orderings (same packing, so time tracks the byte model on any
    platform — DIA's shifted multiply-adds scale with ndiags whether
    the bottleneck is HBM or cache); the cross-format end-to-end gain
    (best identity format vs best reordered format) rides along as
    ``end_to_end``. Emits ONE ``bench_xray`` record (platform-stamped
    via hw_provenance; informational on the CPU fallback — the
    cross-format mapping is only roofline-faithful where the SpMV is
    HBM-bound). Exit 1 only when nothing could be measured."""
    n = int(os.environ.get("AMGCL_TPU_XRAY_N", "4096"))
    # bw 16 keeps the RCM-recovered band at ~33 diagonals — still
    # inside auto's CPU max_diags=40 so the advisor genuinely picks
    # DIA, and in the same XLA lowering regime as the scrambled
    # identity's ~160 (below ~16 diagonals the whole DIA chain fuses
    # into one pass and the per-diagonal cost drops ~40%, which would
    # bias the matched join)
    bw = int(os.environ.get("AMGCL_TPU_XRAY_BW", "16"))
    local = int(os.environ.get("AMGCL_TPU_XRAY_LOCAL", "32"))
    rec = _xray_record(n, bw, local, seed=7)
    _stdout_sink.emit(rec)
    _sink.emit(dict(rec))
    return 0 if rec["value"] is not None else 1


def main_check(targets=None):
    """Run the tier-1 pytest line in a subprocess (CPU-forced, like the
    driver) and emit ONE JSONL record carrying DOTS_PASSED, the return
    code and the duration — to stdout and the process-global sink. The
    bench regression gate rides along (AMGCL_TPU_GATE_IN_CHECK=0 opts
    out): the record gains a ``gate`` field and a gate regression fails
    the check, so CI inherits the gate for free. The gate candidate
    defaults to the last-good record itself (a self-consistency pass);
    point AMGCL_TPU_GATE_CANDIDATE at a fresh bench record to score a
    new run.

    ``targets``: optional pytest paths/flags replacing the default
    ``tests/`` target (lets callers check a subset quickly)."""
    timeout = float(os.environ.get("AMGCL_TPU_CHECK_TIMEOUT", "870"))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable] + _TIER1_ARGS \
        + (list(targets) if targets else ["tests/"])
    t0 = time.time()
    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=timeout, cwd=_REPO, env=env)
        rc, text = r.returncode, r.stdout + "\n" + r.stderr
        err = None
    except subprocess.TimeoutExpired as e:
        rc = -1
        text = (e.stdout or b"").decode("utf-8", "replace") if isinstance(
            e.stdout, bytes) else (e.stdout or "")
        err = "pytest timed out after %.0fs" % timeout
    rec = {"event": "tier1_check", "metric": "tier1_dots_passed",
           "value": count_dots(text), "unit": "tests",
           "rc": rc, "duration_s": round(time.time() - t0, 1),
           "commit": _git_head()}
    if err:
        rec["error"] = err
    gate_ok = True
    if os.environ.get("AMGCL_TPU_GATE_IN_CHECK", "1") != "0":
        lg = _gate_last_good()
        cand = lg
        cand_src = "last_good"
        cpath = os.environ.get("AMGCL_TPU_GATE_CANDIDATE")
        if cpath:
            cand_src = cpath
            try:
                with open(cpath) as f:
                    cand = json.load(f)
            except Exception:
                cand = None
        if cpath and cand is None:
            # an unreadable EXPLICIT candidate is a failure regardless of
            # the baseline — the caller asked to score it (same contract
            # as `--gate <path>`'s exit 2)
            gate_ok = False
            rec["gate"] = {"ok": False, "status": "unreadable_candidate",
                           "candidate_src": cand_src}
        elif lg is None:
            gate_ok = True
            rec["gate"] = {"ok": True, "status": "no_baseline"}
        else:
            gate_ok, checks = run_gate(cand, lg)
            rec["gate"] = {"ok": gate_ok, "candidate_src": cand_src,
                           "checks": checks}
            if not gate_ok:
                # failed checks carry their measured pairs, and the
                # cross-run attribution section is appended to every
                # gate failure — CI names the culprit stage itself
                rec["gate"]["failed"] = gate_failures(checks)
                rec["gate"]["attribution"] = gate_attribution(cand, lg)
        # the CI record carries the efficiency summaries of the record it
        # gated (roofline frac + compile totals travel with the gate
        # verdict), plus the cross-round trend rollups — pre-roofline
        # records simply lack the fields
        for key in ("roofline", "compile"):
            if isinstance(cand, dict) and isinstance(cand.get(key), dict):
                src = cand[key]
                rec[key] = src.get("totals", src) \
                    if key == "compile" else {
                        k: src.get(k) for k in
                        ("gbps", "gflops", "frac_hbm_peak", "bound")
                        if src.get(k) is not None}
        # multichip arm rides --check exactly like --gate: a scaling
        # efficiency / comm-fraction regression fails CI
        mc = multichip_gate_record()
        if mc is not None:
            rec["multichip"] = mc
            gate_ok = gate_ok and mc["ok"]
        # storm arm rides --check the same way: a max-sustainable-rate
        # or reference-p99 regression (AMGCL_TPU_GATE_STORM) fails CI
        st = storm_gate_record()
        if st is not None:
            rec["storm_gate"] = st
            gate_ok = gate_ok and st["ok"]
    replay_ok = True
    if os.environ.get("AMGCL_TPU_FLIGHT", "1") != "0":
        # determinism self-check (telemetry/flight.py): dump a replay
        # bundle of a small headline-config solve, replay it, and
        # require report parity — so "a bundle replays identically on
        # the same platform" is gated every round, not asserted once.
        # A gate failure additionally persists the bundle into
        # AMGCL_TPU_FLIGHT_DIR (when set): the failing round leaves a
        # replayable artifact behind, not just ratios.
        r_timeout = float(os.environ.get("AMGCL_TPU_CHECK_TIMEOUT",
                                         "870")) / 2
        cmd2 = [sys.executable, "-m", "amgcl_tpu.telemetry.flight",
                "--selftest"]
        keep_dir = os.environ.get("AMGCL_TPU_FLIGHT_DIR")
        if not gate_ok and keep_dir:
            # a `check/` SUBdirectory: the persisted bundle must not
            # consume one of the incident dir's bounded dump slots
            cmd2 += ["--dir", os.path.join(keep_dir, "check")]
        try:
            rr = subprocess.run(cmd2, capture_output=True, text=True,
                                timeout=r_timeout, cwd=_REPO,
                                env=dict(os.environ,
                                         JAX_PLATFORMS="cpu"))
            rrec = json.loads(rr.stdout.strip().splitlines()[-1])
            replay_ok = bool(rrec.get("ok")) and rr.returncode == 0
            rec["selfreplay"] = {
                "ok": replay_ok, "n": rrec.get("n"),
                "reason": rrec.get("reason"),
                "parity": rrec.get("parity"),
                "bundle": rrec.get("bundle")}
            if not replay_ok and rrec.get("error"):
                rec["selfreplay"]["error"] = rrec["error"]
        except Exception as e:
            replay_ok = False
            rec["selfreplay"] = {"ok": False, "error": repr(e)[:300]}
    recovery_ok = True
    if os.environ.get("AMGCL_TPU_GATE_RECOVERY", "1") != "0":
        # chaos-matrix gate (amgcl_tpu/faults/chaos.py): every injected
        # fault scenario (numeric x allocation x device x serve) must
        # either recover with solution parity or fail cleanly (typed
        # error + flight bundle) under the global deadline — a hang or
        # an unclean failure fails the round, the flight-selftest
        # pattern applied to the whole fault-tolerance layer.
        try:
            c_timeout = float(os.environ.get("AMGCL_TPU_CHAOS_TIMEOUT",
                                             "900"))
        except ValueError:
            c_timeout = 900.0
        try:
            cr = subprocess.run(
                [sys.executable, "-m", "amgcl_tpu.faults",
                 "--selftest"],
                capture_output=True, text=True, timeout=c_timeout + 60,
                cwd=_REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"))
            crec = json.loads(cr.stdout.strip().splitlines()[-1])
            recovery_ok = bool(crec.get("ok")) and cr.returncode == 0
            rec["recovery"] = {
                "ok": recovery_ok,
                "scenarios": crec.get("total"),
                "recovered": crec.get("recovered"),
                "clean_fail": crec.get("clean_fail"),
                "hangs": crec.get("hangs"),
                "failures": crec.get("failures"),
                "wall_s": crec.get("wall_s")}
            if not recovery_ok:
                # the actionable payload: the failing scenario rows
                rec["recovery"]["failed_scenarios"] = [
                    s for s in crec.get("scenarios", [])
                    if not s.get("ok")]
        except Exception as e:
            recovery_ok = False
            rec["recovery"] = {"ok": False, "error": repr(e)[:300]}
    storm_ok = True
    if os.environ.get("AMGCL_TPU_STORM_IN_CHECK", "1") != "0":
        # seeded storm smoke (serve/storm.py): a ~10 s open-loop load
        # pass on the CPU mesh, so every round carries a measured
        # load-under-traffic datapoint (curve + knee + goodput). The
        # subprocess's last stdout line is the bench_storm record; it
        # also refreshes STORM_LATEST.json for the storm gate arm.
        s_timeout = _storm_env_f("AMGCL_TPU_STORM_TIMEOUT", 600.0)
        try:
            sr = subprocess.run(
                [sys.executable, os.path.join(_REPO, "bench.py"),
                 "--storm", "--smoke"],
                capture_output=True, text=True, timeout=s_timeout,
                cwd=_REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"))
            srec = json.loads(sr.stdout.strip().splitlines()[-1])
            body = srec.get("record") or {}
            knee = body.get("knee") or {}
            storm_ok = sr.returncode == 0 and bool(body.get("curve"))
            rec["storm"] = {
                "ok": storm_ok,
                "requests": (body.get("goodput") or {}).get("requests"),
                "good_frac": (body.get("goodput") or {}).get(
                    "good_frac"),
                "max_sustainable_rps": knee.get("max_sustainable_rps"),
                "saturated": knee.get("saturated"),
                "knee_reason": knee.get("reason"),
                "ref_p99_ms": (body.get("reference") or {}).get(
                    "p99_ms"),
            }
        except Exception as e:
            storm_ok = False
            rec["storm"] = {"ok": False, "error": repr(e)[:300]}
    memwatch_ok = True
    if os.environ.get("AMGCL_TPU_MEMWATCH_IN_CHECK", "1") != "0":
        # seeded memory-observatory selftest (telemetry/memwatch.py):
        # builds a small farm tenant on the CPU mesh, joins measured
        # live-array bytes against the ledger model per level, then
        # runs register->evict->register cycles and fails on bytes
        # that do not return to baseline (the leak gate). The record's
        # drift_ratio also feeds the AMGCL_TPU_GATE_MEMDRIFT gate arm.
        try:
            m_timeout = float(os.environ.get(
                "AMGCL_TPU_MEMWATCH_TIMEOUT", "600"))
        except ValueError:
            m_timeout = 600.0
        try:
            mr = subprocess.run(
                [sys.executable, "-m", "amgcl_tpu.telemetry.memwatch",
                 "--selftest"],
                capture_output=True, text=True, timeout=m_timeout,
                cwd=_REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"))
            mrec = json.loads(mr.stdout.strip().splitlines()[-1])
            memwatch_ok = bool(mrec.get("ok")) and mr.returncode == 0
            rec["memwatch"] = {
                "ok": memwatch_ok,
                "drift_ratio": mrec.get("drift_ratio"),
                "baseline_bytes": mrec.get("baseline_bytes"),
                "leaked_bytes": mrec.get("leaked_bytes"),
                "checks": mrec.get("checks"),
                "wall_s": mrec.get("wall_s")}
            if not memwatch_ok:
                # the actionable payload: findings + per-owner rows
                rec["memwatch"]["findings"] = mrec.get("findings")
                rec["memwatch"]["owners"] = mrec.get("owners")
        except Exception as e:
            memwatch_ok = False
            rec["memwatch"] = {"ok": False, "error": repr(e)[:300]}
    analysis_ok = True
    if os.environ.get("AMGCL_TPU_ANALYSIS_IN_CHECK", "1") != "0":
        # static-analysis gate (amgcl_tpu/analysis): AST lint vs the
        # committed ANALYSIS_BASELINE.json findings budget + the jaxpr
        # contract audit (collective census, fused-tier engagement,
        # dtype/donation discipline). A subprocess, like the pytest
        # run: the audit forces its own 8-virtual-device CPU topology.
        a_timeout = float(os.environ.get("AMGCL_TPU_ANALYSIS_TIMEOUT",
                                         "600"))
        try:
            ar = subprocess.run(
                [sys.executable, "-m", "amgcl_tpu.analysis", "--json"],
                capture_output=True, text=True, timeout=a_timeout,
                cwd=_REPO, env=dict(os.environ))
            arec = json.loads(ar.stdout.strip().splitlines()[-1])
            audit = arec.get("audit", {})
            analysis_ok = bool(arec.get("ok")) and ar.returncode == 0
            rec["analysis"] = {
                "ok": analysis_ok,
                "lint_total": arec["lint"]["total"],
                "lint_new": len(arec["lint"]["new"]),
                "lint_suppressed": arec["lint"]["suppressed"],
                "stale_suppressions":
                    len(arec["lint"]["stale_suppressions"]),
                "rules": arec["lint"]["rules"],
                "audit_records": len(audit.get("records", [])),
                "audit_errors": audit.get("errors", 0),
            }
            conc = arec.get("concurrency") or {}
            if conc:
                # concurrency contract analyzer counts (lock-order /
                # guarded-by / cv- / handoff-discipline) — new findings
                # fail the round through the shared arec["ok"] gate
                rec["analysis"]["concurrency"] = {
                    "total": conc.get("total", 0),
                    "new": len(conc.get("new", [])),
                    "suppressed": conc.get("suppressed", 0),
                    "modules": len(conc.get("modules", [])),
                    "rules": conc.get("rules", []),
                }
            if not analysis_ok:
                # the actionable payload rides the CI record
                rec["analysis"]["new_findings"] = (
                    arec["lint"]["new"] + list(conc.get("new", [])))
                rec["analysis"]["audit_findings"] = [
                    f for f in audit.get("findings", [])
                    if f.get("severity") == "error"]
        except Exception as e:
            analysis_ok = False
            rec["analysis"] = {"ok": False, "error": repr(e)[:300]}
    try:
        rec["trend"] = trend_summary()["rollups"]
    except Exception as e:
        rec["trend"] = {"error": repr(e)[:200]}
    _stdout_sink.emit(rec)
    _sink.emit(dict(rec))
    return 0 if (rc == 0 and gate_ok and analysis_ok
                 and replay_ok and recovery_ok and storm_ok
                 and memwatch_ok) else 1


if __name__ == "__main__":
    if "--worker" in sys.argv:
        main_worker()
    elif "--opportunistic" in sys.argv:
        main_opportunistic()
    elif "--check" in sys.argv:
        extra = sys.argv[sys.argv.index("--check") + 1:]
        sys.exit(main_check(extra))
    elif "--gate" in sys.argv:
        extra = sys.argv[sys.argv.index("--gate") + 1:]
        sys.exit(main_gate(extra))
    elif "--why" in sys.argv:
        extra = sys.argv[sys.argv.index("--why") + 1:]
        sys.exit(main_why(extra))
    elif "--xray" in sys.argv:
        extra = sys.argv[sys.argv.index("--xray") + 1:]
        sys.exit(main_xray(extra))
    elif "--trend" in sys.argv:
        extra = sys.argv[sys.argv.index("--trend") + 1:]
        sys.exit(main_trend(extra))
    elif "--vecbench" in sys.argv:
        extra = sys.argv[sys.argv.index("--vecbench") + 1:]
        sys.exit(main_vecbench(extra))
    elif "--throughput" in sys.argv:
        extra = sys.argv[sys.argv.index("--throughput") + 1:]
        sys.exit(main_throughput(extra))
    elif "--farm" in sys.argv:
        extra = sys.argv[sys.argv.index("--farm") + 1:]
        sys.exit(main_farm(extra))
    elif "--storm" in sys.argv:
        extra = sys.argv[sys.argv.index("--storm") + 1:]
        sys.exit(main_storm(extra))
    elif "--scaling" in sys.argv:
        extra = sys.argv[sys.argv.index("--scaling") + 1:]
        sys.exit(main_scaling(extra))
    else:
        main_supervisor()
