"""Headline benchmark: 3D Poisson 128^3 (2,097,152 unknowns, ~14.6M nnz),
smoothed aggregation + CG + spai0 — the reference's shared-memory benchmark
configuration (docs/benchmarks.rst:60-79, BASELINE.json configs[0]).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Baseline: the reference's CUDA backend on a Tesla K80 solves the 150^3
problem in 0.55 s (BASELINE.md; docs/smem_data/poisson/amgcl-cuda.txt:1).
Scaled to 128^3 by problem size that is 0.55*(128/150)^3 = 0.342 s, the
number a single TPU chip must beat. vs_baseline = baseline_time / our_time
(>1 means faster than the K80 reference).
"""

import json
import os
import threading
import time

import numpy as np


def _device_watchdog(timeout_s: float = 240.0):
    """The axon TPU tunnel can wedge so that backend init blocks forever
    (observed in this image). Probe device init in a thread; on timeout,
    emit a diagnostic JSON line and hard-exit instead of hanging the
    driver."""
    done = threading.Event()

    def probe():
        import jax
        jax.devices()
        done.set()

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    if not done.wait(timeout_s):
        import sys
        print(json.dumps({
            "metric": "poisson3d_128_sa_cg_spai0_solve_time",
            "value": None, "unit": "s", "vs_baseline": None,
            "error": "device backend init timed out after %.0fs "
                     "(TPU tunnel unreachable)" % timeout_s,
        }))
        sys.stdout.flush()
        os._exit(2)


def _bench_levels(solver):
    """Per-level SpMV timings: XLA lowering vs the Pallas DIA kernel where
    the level is DIA-formatted (VERDICT round-1 ask: per-level
    kernel-vs-XLA numbers so format/kernel choices are measured, not
    guessed). Returns a list of dicts."""
    import jax
    import jax.numpy as jnp
    from amgcl_tpu.ops.device import DiaMatrix
    from amgcl_tpu.ops.pallas_spmv import dia_spmv

    out = []
    for li, lv in enumerate(solver.precond.hierarchy.levels):
        M = lv.A
        n_cols = M.shape[1] * getattr(M, "block", (1, 1))[1] \
            if hasattr(M, "block") else M.shape[1]
        x = jnp.asarray(np.random.RandomState(li).rand(n_cols),
                        dtype=jnp.float32)

        def timeit(fn):
            y = fn(x)
            jax.block_until_ready(y)
            ts = []
            for _ in range(20):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(x))
                ts.append(time.perf_counter() - t0)
            return float(np.median(ts))

        row = {"level": li, "format": type(M).__name__,
               "rows": int(M.shape[0]),
               "xla_us": round(timeit(jax.jit(M.mv)) * 1e6, 1)}
        if isinstance(M, DiaMatrix):
            offs = tuple(M.offsets)
            # interpret mode off-TPU keeps the CPU smoke path alive; its
            # timings are meaningless and marked as such
            interp = jax.default_backend() != "tpu"
            row["pallas_us"] = round(timeit(
                lambda v: dia_spmv(offs, M.data, v, interpret=interp))
                * 1e6, 1)
            if interp:
                row["pallas_interpret_mode"] = True
            else:
                row["winner"] = "pallas" \
                    if row["pallas_us"] < row["xla_us"] else "xla"
        out.append(row)
    return out


def main():
    _device_watchdog()
    import jax
    # x64 so the refinement's outer residual really is float64 (the
    # correction solves stay float32)
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    from amgcl_tpu.utils.sample_problem import poisson3d
    from amgcl_tpu.models.make_solver import make_solver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.cg import CG

    n = 128
    t0 = time.perf_counter()
    A, rhs = poisson3d(n)
    t_gen = time.perf_counter() - t0

    t0 = time.perf_counter()
    solver = make_solver(A, AMGParams(dtype=jnp.float32),
                         CG(maxiter=100, tol=1e-6), refine=3)
    t_setup = time.perf_counter() - t0

    rhs_dev = jnp.asarray(rhs, dtype=jnp.float32)

    def timed(tag):
        x, info = solver(rhs_dev)           # warmup/compile
        jax.block_until_ready(x)
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            x, info = solver(rhs_dev)
            jax.block_until_ready(x)
            times.append(time.perf_counter() - t0)
        return float(np.median(times)), x, info

    import os
    t_solve, x, info = timed("xla")
    spmv_path = "xla"
    if jax.default_backend() == "tpu":
        # try the Pallas DIA kernel; keep whichever is faster
        os.environ["AMGCL_TPU_PALLAS"] = "1"
        solver._compiled = None
        try:
            t_pallas, xp_, infop = timed("pallas")
            if t_pallas < t_solve:
                t_solve, x, info, spmv_path = t_pallas, xp_, infop, "pallas"
        except Exception:
            pass
        finally:
            os.environ["AMGCL_TPU_PALLAS"] = "0"

    true_res = float(np.linalg.norm(rhs - A.spmv(np.asarray(x, np.float64)))
                     / np.linalg.norm(rhs))

    levels = None
    if jax.default_backend() == "tpu" or os.environ.get(
            "AMGCL_TPU_BENCH_LEVELS") == "1":
        try:
            levels = _bench_levels(solver)
        except Exception as e:       # per-level timing must never kill the
            levels = [{"error": repr(e)}]   # headline number
    baseline = 0.55 * (n / 150.0) ** 3   # K80 CUDA solve, size-scaled
    print(json.dumps({
        "metric": "poisson3d_128_sa_cg_spai0_solve_time",
        "value": round(t_solve, 4),
        "unit": "s",
        "vs_baseline": round(baseline / t_solve, 3),
        "iters": int(info.iters),
        "resid": float(info.resid),
        "true_resid": true_res,
        "setup_s": round(t_setup, 3),
        "gen_s": round(t_gen, 3),
        "spmv_path": spmv_path,
        "levels": levels,
        "device": str(jax.devices()[0]),
    }))


if __name__ == "__main__":
    main()
