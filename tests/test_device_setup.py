"""Device-side hierarchy setup: plan-based Galerkin/smoothing parity,
default device MIS quality bounds, same-sparsity numeric rebuilds, setup
attribution, and the setup gate/audit contracts (ISSUE 9 / ROADMAP 2)."""

import os

import numpy as np
import scipy.sparse as sp
import jax.numpy as jnp
import pytest

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.ops import segment_spgemm as seg
from amgcl_tpu.coarsening.galerkin import galerkin, scaled_galerkin
from amgcl_tpu.coarsening.aggregation import Aggregation
from amgcl_tpu.coarsening.smoothed_aggregation import SmoothedAggregation
from amgcl_tpu.coarsening.smoothed_aggr_emin import SmoothedAggrEMin
from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.models.make_solver import make_solver
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.utils.sample_problem import poisson3d, poisson3d_block


@pytest.fixture
def env(monkeypatch):
    """Knob setter that restores after the test."""
    def set_(name, val):
        if val is None:
            monkeypatch.delenv(name, raising=False)
        else:
            monkeypatch.setenv(name, str(val))
    return set_


def _unstructured(n=500, density=0.015, seed=3, dtype=np.float64):
    rng = np.random.RandomState(seed)
    M = sp.random(n, n, density=density, random_state=rng).tocsr()
    M = M + M.T + 10.0 * sp.identity(n)
    A = CSR.from_scipy(sp.csr_matrix(M))
    A.val = A.val.astype(dtype)
    return A


def _csr_transfer_policy(policy):
    """Force the generic CSR route (no stencil/structured shortcuts)."""
    for attr, val in (("stencil_setup", False), ("structured", False),
                      ("implicit_transfers", False)):
        if hasattr(policy, attr):
            setattr(policy, attr, val)
    return policy


def _host_rap(A, P, R, scale=1.0):
    ref = (R @ (A @ P)).to_scipy()
    ref.sort_indices()
    return ref * scale


# ---------------------------------------------------------------------------
# device-Galerkin parity: device plan numerics == host R @ (A @ P)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("policy_factory,scale", [
    (lambda: _csr_transfer_policy(SmoothedAggregation()), 1.0),
    (lambda: _csr_transfer_policy(Aggregation()), 1.0 / 1.5),
    (lambda: _csr_transfer_policy(SmoothedAggrEMin()), 1.0),
])
def test_device_galerkin_parity_all_coarsenings(env, policy_factory,
                                                scale):
    """Plan-based (forced-device) Galerkin == host two-SpGEMM to f64
    tolerance for all three aggregation coarsening types."""
    A = _unstructured()
    env("AMGCL_TPU_DEVICE_SETUP", 1)     # device numeric on CPU backend
    P, R = policy_factory().transfer_operators(A)
    plan = seg.ensure_plan(A, P, R, force=True)
    assert plan is not None
    got = plan.coarse(A, scale).to_scipy()
    got.sort_indices()
    ref = _host_rap(A, P, R, scale)
    assert np.array_equal(ref.indptr, got.indptr)
    assert np.array_equal(ref.indices, got.indices)
    assert abs(ref - got).max() < 1e-11 * max(abs(ref).max(), 1.0)


def test_selection_triple_product_one_pass(env):
    """Tentative P (a selection matrix) takes the single segment-sum
    route — plan flops equal nnz(A) kept entries, not a multiply list."""
    A = _unstructured()
    P, R = _csr_transfer_policy(Aggregation()).transfer_operators(A)
    plan = seg.ensure_plan(A, P, R, force=True)
    assert plan.kind == "selection"
    assert plan.flops <= A.nnz
    # host-numeric and device-numeric backends agree exactly in f64
    host = plan.triple.coarse_values(A.val, device=False)
    dev = plan.triple.coarse_values(A.val, device=True)
    np.testing.assert_allclose(host, dev, rtol=0, atol=1e-13)


def test_device_galerkin_f32_values(env):
    """Scalar f32 values ride the same plans (the bench hierarchy dtype)."""
    A = _unstructured(dtype=np.float32)
    P, R = _csr_transfer_policy(SmoothedAggregation()).transfer_operators(A)
    plan = seg.ensure_plan(A, P, R, force=True)
    got = plan.coarse(A).to_scipy()
    ref = _host_rap(A.copy(), P, R)
    assert abs(ref - got).max() < 1e-4 * abs(ref).max()


def test_block_values_keep_host_route_and_fresh_scale():
    """Block (BCSR) values: plans opt out, the host SpGEMM route runs,
    and scaled_galerkin no longer mutates a possibly-shared value
    array."""
    A, _ = poisson3d_block(6, 2)
    P, R = Aggregation(block_size=2).transfer_operators(A)
    assert seg.ensure_plan(A, P, R, force=True) is None
    Ac = galerkin(A, P, R)
    v0 = Ac.val.copy()
    Acs = scaled_galerkin(A, P, R, 1.0 / 1.5)
    assert np.array_equal(Ac.val, v0)          # unscaled product intact
    assert Acs.val is not Ac.val
    np.testing.assert_allclose(
        np.asarray(Acs.to_scipy().todense()),
        np.asarray(Ac.to_scipy().todense()) / 1.5, atol=1e-12)


def test_smooth_plan_matches_host_p_smooth(env):
    """Device prolongation smoothing (SmoothPlan) == host
    P_tent + (-omega DA) @ P_tent, pattern and values."""
    from amgcl_tpu.coarsening.smoothed_aggregation import (_filtered,
                                                           _p_smooth)
    from amgcl_tpu.coarsening.aggregates import plain_aggregates
    from amgcl_tpu.coarsening.tentative import tentative_prolongation
    A = _unstructured()
    agg, n_agg = plain_aggregates(A, 0.08)
    Pt, _ = tentative_prolongation(A.nrows, agg, n_agg)
    Af, Dfi = _filtered(A, 0.08)
    omega = 0.61
    ref = _p_smooth(Pt, Af.scale_rows(Dfi), omega).to_scipy()
    ref.sort_indices()
    for device in (False, True):
        got = seg.SmoothPlan(Af, agg, n_agg).prolongation(
            Af, Dfi, omega, device=device).to_scipy()
        got.sort_indices()
        assert np.array_equal(ref.indices, got.indices)
        assert abs(ref - got).max() < 1e-12


def test_sa_transfer_operators_use_smooth_plan(env):
    """With device numerics forced, SmoothedAggregation's CSR route
    produces the SAME P as the host path (f64 tolerance). The
    aggregation is pinned through the aggregator hook so both runs
    smooth the identical tentative operator."""
    from amgcl_tpu.coarsening.aggregates import mis_aggregates, \
        strength_graph
    A = _unstructured()

    def agg_hook(M, eps):
        return mis_aggregates(strength_graph(M, eps))

    def pol():
        p = _csr_transfer_policy(SmoothedAggregation())
        p.aggregator = agg_hook
        return p

    env("AMGCL_TPU_DEVICE_SETUP", 1)
    P_dev, _ = pol().transfer_operators(A)
    env("AMGCL_TPU_DEVICE_SETUP", 0)
    P_host, _ = pol().transfer_operators(A)
    assert P_dev.shape == P_host.shape
    d = abs(P_dev.to_scipy() - P_host.to_scipy())
    assert (d.max() if d.nnz else 0.0) < 1e-12


# ---------------------------------------------------------------------------
# device MIS as the default aggregation path
# ---------------------------------------------------------------------------

def test_device_mis_default_gates():
    from amgcl_tpu.coarsening.device_mis import device_mis_default
    # CPU backend: host default, device under the force knob, host wins
    # under AMGCL_TPU_HOST_SETUP
    saved = {k: os.environ.get(k) for k in
             ("AMGCL_TPU_DEVICE_SETUP", "AMGCL_TPU_HOST_SETUP")}
    try:
        os.environ.pop("AMGCL_TPU_DEVICE_SETUP", None)
        os.environ.pop("AMGCL_TPU_HOST_SETUP", None)
        assert device_mis_default() is False      # CPU test backend
        os.environ["AMGCL_TPU_DEVICE_SETUP"] = "1"
        assert device_mis_default() is True
        os.environ["AMGCL_TPU_HOST_SETUP"] = "1"
        assert device_mis_default() is False
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def test_device_mis_quality_within_10pct(env, monkeypatch):
    """Device-MIS-default aggregates vs the host greedy path: operator
    complexity within 10% (PDE-graph fixture — the paths run different
    distance-2 heuristics), and the device-MIS hierarchy converges."""
    from amgcl_tpu.ops import stencil_device as sdev
    monkeypatch.setattr(sdev, "enabled", lambda: False)
    A, rhs = poisson3d(12)

    def complexity(force_host):
        env("AMGCL_TPU_DEVICE_SETUP", None if force_host else 1)
        env("AMGCL_TPU_HOST_SETUP", 1 if force_host else None)
        amg = AMG(A, AMGParams(
            coarsening=_csr_transfer_policy(SmoothedAggregation()),
            dtype=jnp.float64, coarse_enough=80))
        st = amg.hierarchy_stats()
        return st["operator_complexity"], amg

    oc_dev, amg_dev = complexity(force_host=False)
    oc_host, _ = complexity(force_host=True)
    assert abs(oc_dev - oc_host) / oc_host < 0.10
    env("AMGCL_TPU_DEVICE_SETUP", 1)
    env("AMGCL_TPU_HOST_SETUP", None)
    solve = make_solver(A, AMGParams(
        coarsening=_csr_transfer_policy(SmoothedAggregation()),
        dtype=jnp.float64, coarse_enough=80), CG(maxiter=100, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8
    assert info.iters < 40


def test_device_mis_bucketing_invisible(env):
    """Padding to shape buckets must not change the aggregation: the
    real nodes keep the host priorities."""
    from amgcl_tpu.coarsening.device_mis import aggregates_on_device
    A, _ = poisson3d(9)                  # n = 729, pads to 1024
    a1, n1 = aggregates_on_device(A)
    a2, n2 = aggregates_on_device(A)
    assert n1 == n2 and np.array_equal(a1, a2)
    assert (a1 >= 0).all() and n1 == a1.max() + 1


# ---------------------------------------------------------------------------
# same-sparsity numeric rebuilds
# ---------------------------------------------------------------------------

def _dev_arrays(amg):
    import jax
    return [np.asarray(leaf) for leaf in jax.tree.leaves(amg.hierarchy)
            if hasattr(leaf, "dtype")]


@pytest.mark.parametrize("policy_factory", [
    lambda: None,                                        # stencil path
    lambda: _csr_transfer_policy(SmoothedAggregation()),  # CSR path
])
def test_rebuild_bit_identical_to_fresh(policy_factory):
    """rebuild(2A) == fresh AMG(2A), bit for bit, host AND device
    arrays — both builds run the identical numeric route."""
    pol = policy_factory()
    prm = dict(dtype=jnp.float64, coarse_enough=80)
    if pol is not None:
        prm["coarsening"] = pol
        A = _unstructured(n=900, density=0.01, seed=5)
    else:
        A, _ = poisson3d(12)
    amg = AMG(A, AMGParams(**prm))
    A2 = CSR(A.ptr, A.col, 2.0 * A.val, A.ncols)
    amg.rebuild(A2)
    if pol is not None:
        prm["coarsening"] = policy_factory()
    fresh = AMG(A2, AMGParams(**prm))
    for (Ai, _, _), (Bi, _, _) in zip(amg.host_levels,
                                      fresh.host_levels):
        assert np.array_equal(Ai.val, Bi.val)
        assert np.array_equal(Ai.col, Bi.col)
    for a, b in zip(_dev_arrays(amg), _dev_arrays(fresh)):
        assert a.shape == b.shape
        np.testing.assert_array_equal(a, b)


def test_rebuild_values_only_api():
    """rebuild(new_vals) takes a bare value array and skips the pattern
    comparison."""
    A, rhs = poisson3d(10)
    amg = AMG(A, AMGParams(dtype=jnp.float64, coarse_enough=80))
    amg.rebuild(2.0 * A.val)
    ref = AMG(CSR(A.ptr, A.col, 2.0 * A.val, A.ncols),
              AMGParams(dtype=jnp.float64, coarse_enough=80))
    assert np.array_equal(amg.host_levels[1][0].val,
                          ref.host_levels[1][0].val)
    with pytest.raises(ValueError, match="value array shape"):
        amg.rebuild(np.ones(3))


def test_rebuild_asserts_same_sparsity():
    A, _ = poisson3d(10)
    amg = AMG(A, AMGParams(dtype=jnp.float64, coarse_enough=80))
    B = A.to_scipy().tolil()
    B[0, A.nrows - 1] = 1e-3             # new structural entry
    B = CSR.from_scipy(B.tocsr())
    with pytest.raises(ValueError, match="same sparsity"):
        amg.rebuild(B)


def test_rebuild_reuses_transfer_devices_and_plans():
    """The rebuild keeps the device transfer operators (frozen) and the
    cached Galerkin plans — no re-pack, no re-plan."""
    A = _unstructured(n=900, density=0.01, seed=5)
    amg = AMG(A, AMGParams(
        coarsening=_csr_transfer_policy(SmoothedAggregation()),
        dtype=jnp.float64, coarse_enough=80))
    lv0 = amg.hierarchy.levels[0]
    P_dev0, R_dev0 = lv0.P, lv0.R
    amg.rebuild(2.0 * A.val)
    plans1 = [getattr(P, "_seg_plan", None)
              for (_, P, _) in amg.host_levels[:-1]]
    assert amg.hierarchy.levels[0].P is P_dev0
    assert amg.hierarchy.levels[0].R is R_dev0
    amg.rebuild(3.0 * A.val)
    plans2 = [getattr(P, "_seg_plan", None)
              for (_, P, _) in amg.host_levels[:-1]]
    for p1, p2 in zip(plans1, plans2):
        assert p1 is p2                   # plan objects survive rebuilds


def test_windowed_ell_value_refresh():
    from amgcl_tpu.ops import device as dev
    from amgcl_tpu.ops.unstructured import csr_to_windowed_ell
    A = _unstructured(n=700, density=0.02, seed=9, dtype=np.float32)
    W = csr_to_windowed_ell(A, jnp.float32)
    if W is None:
        pytest.skip("fixture has no banded locality")
    A2 = CSR(A.ptr, A.col, 2.0 * A.val, A.ncols)
    W2 = dev.refresh_values(W, A2, jnp.float32)
    assert W2 is not None
    assert W2.window_starts is W.window_starts
    np.testing.assert_array_equal(np.asarray(W2.vals),
                                  2.0 * np.asarray(W.vals))


def test_stencil_csr_cache_drift_guard():
    """The cached DIA→CSR rebuild map serves same-value-pattern
    rebuilds and REFUSES (returns None → caller re-derives) when a
    value that was exactly zero at the first build comes alive."""
    from amgcl_tpu.ops.stencil import (HostDia, _build_dia_csr_cache,
                                       _csr_from_dia_cache)
    dims = (1, 1, 8)
    offs = [(0, 0, -1), (0, 0, 0), (0, 0, 1)]
    data = np.zeros((3, 8))
    data[1] = 2.0
    data[2, :7] = -1.0
    data[2, 3] = 0.0                    # value-zero inside the window
    kept = [1, 2]                       # lower band all-zero at build 1
    Acd = HostDia([offs[k] for k in kept], data[kept], dims)
    out = Acd.to_csr()
    cache = _build_dia_csr_cache(kept, Acd, out)
    got = _csr_from_dia_cache(HostDia(offs, 2.0 * data, dims), cache)
    assert got is not None
    np.testing.assert_array_equal(got.val, 2.0 * out.val)
    # a dropped diagonal turns on
    d2 = data.copy()
    d2[0, 1:] = -1.0
    assert _csr_from_dia_cache(HostDia(offs, d2, dims), cache) is None
    # an eliminated in-window entry turns on
    d3 = data.copy()
    d3[2, 3] = -1.0
    assert _csr_from_dia_cache(HostDia(offs, d3, dims), cache) is None


def test_stencil_galerkin_device_kernel_parity():
    """The generated jitted stencil-Galerkin program == the native/host
    pair-fnma route on the same plan (pre-drop output, f64)."""
    from amgcl_tpu.ops.stencil import (StencilGalerkinPlan,
                                       host_dia_from_csr, filtered_dia,
                                       scale_rows)
    m = 8
    A, _ = poisson3d(m)
    Ad = host_dia_from_csr(A, (m, m, m), np.float64)
    Af, Dinv = filtered_dia(Ad, 0.08)
    M = scale_rows(Af, Dinv)
    M.data = M.data * 0.57
    M = M.drop_empty()
    coarse = tuple(-(-d // 2) for d in (m, m, m))
    plan = StencilGalerkinPlan(Ad.offsets3, M.offsets3, Ad.dims,
                               (2, 2, 2), coarse, np.float64)
    host = plan.apply(Ad.data, M.data, device=False)
    dev = plan.apply(Ad.data, M.data, device=True)
    assert host.offsets3 == dev.offsets3
    np.testing.assert_allclose(dev.data, host.data, rtol=0, atol=1e-12)
    # plain-aggregation degenerate case (M=None): parity collapse only
    plan0 = StencilGalerkinPlan(Ad.offsets3, None, Ad.dims, (2, 2, 2),
                                coarse, np.float64)
    h0 = plan0.apply(Ad.data, None, device=False)
    d0 = plan0.apply(Ad.data, None, device=True)
    np.testing.assert_allclose(d0.data, h0.data, rtol=0, atol=1e-12)


# ---------------------------------------------------------------------------
# telemetry: setup attribution + substage threading
# ---------------------------------------------------------------------------

def test_setup_attribution_coverage():
    A, _ = poisson3d(24)
    amg = AMG(A, AMGParams(dtype=jnp.float32))
    rep = amg.setup_report()
    assert rep["total_s"] > 0
    stages = {r["stage"] for r in rep["rows"]}
    assert any(s.endswith("/galerkin") for s in stages)
    # the acceptance criterion: named stages own (nearly) all setup time
    assert rep["coverage"] > 0.8
    g = [r for r in rep["rows"] if r["stage"] == "level0/galerkin"][0]
    assert g.get("bytes", 0) > 0 and "frac" in g


def test_setup_substage_nested_in_profile(env, monkeypatch):
    """Plan construction/numeric substages appear nested under the
    level's galerkin scope when the device path engages, and the
    attribution marks them nested (no double counting)."""
    from amgcl_tpu.ops import stencil_device as sdev
    monkeypatch.setattr(sdev, "enabled", lambda: False)
    env("AMGCL_TPU_DEVICE_SETUP", 1)
    A = _unstructured(n=900, density=0.01, seed=5)
    amg = AMG(A, AMGParams(
        coarsening=_csr_transfer_policy(SmoothedAggregation()),
        dtype=jnp.float64, coarse_enough=80))
    scopes = amg.setup_profile.to_dict()["scopes"]
    kids = scopes.get("level0/galerkin", {}).get("children", {})
    assert "galerkin_numeric" in kids or "galerkin_plan" in kids, scopes
    rep = amg.setup_report()
    nested = [r for r in rep["rows"] if r["nested"]]
    assert any(r["stage"].endswith("galerkin_numeric") or
               r["stage"].endswith("galerkin_plan") for r in nested)
    top_sum = sum(r["seconds"] for r in rep["rows"] if not r["nested"])
    assert abs(rep["named_s"] - top_sum) < 1e-9


# ---------------------------------------------------------------------------
# bench gate: setup_vs_baseline + rebuild_s round-over-round
# ---------------------------------------------------------------------------

def _gate(candidate, last_good):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_bench_for_setup_gate",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench.run_gate(candidate, last_good)


def test_gate_setup_and_rebuild_checks():
    base = {"iters": 10, "value": 1.0, "device_platform": "cpu",
            "setup_vs_baseline": 0.2, "rebuild_s": 1.0}
    ok, checks = _gate({**base}, base)
    names = {c["check"]: c for c in checks}
    assert names["setup_vs_baseline"]["status"] == "ok"
    assert names["rebuild_s"]["status"] == "ok"
    # setup speed collapse → regression
    ok, checks = _gate({**base, "setup_vs_baseline": 0.05}, base)
    assert not ok
    assert {c["check"]: c for c in checks}[
        "setup_vs_baseline"]["status"] == "regression"
    # rebuild blow-up → regression
    ok, checks = _gate({**base, "rebuild_s": 2.0}, base)
    assert not ok
    # platform mismatch → skipped, not compared
    ok, checks = _gate({**base, "device_platform": "tpu",
                        "setup_vs_baseline": 0.01}, base)
    st = {c["check"]: c for c in checks}
    assert st["setup_vs_baseline"]["status"] == "skipped"
    assert ok


# ---------------------------------------------------------------------------
# static audit: setup contract
# ---------------------------------------------------------------------------

def test_audit_setup_contract_clean():
    from amgcl_tpu.analysis import jaxpr_audit as ja
    recs = ja.audit_setup(m=6)
    entries = {r["entry"] for r in recs}
    from amgcl_tpu.telemetry.ledger import SETUP_CONTRACTS
    assert entries == set(SETUP_CONTRACTS)
    for rec in recs:
        assert ja.check_setup(rec) == [], rec["entry"]


def test_audit_setup_catches_violations():
    from amgcl_tpu.analysis import jaxpr_audit as ja
    bad = {"entry": "ops.segment_galerkin",
           "collectives": {"psum": 1, "ppermute": 0, "all_gather": 0,
                           "all_to_all": 0, "psum_elems": [1]},
           "casts": [{"kind": "downcast", "from": "float64",
                      "to": "float32", "elements": 4096, "path": ""}],
           "host_callbacks": [{"primitive": "pure_callback", "path": ""}]}
    findings = ja.check_setup(bad)
    passes = {f["pass"] for f in findings}
    assert passes == {"host-sync", "collectives", "dtype"}
    assert all(f["severity"] == "error" for f in findings)
