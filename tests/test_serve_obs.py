"""Serving observability (ISSUE 8): per-request span partition, live
registry + occupancy math, the /metrics scrape endpoint, the SLO
watchdog + serve-side findings, the serve/slo JSONL event schemas, the
lowering tag, the padding-waste ledger, the JsonlSink write-path
thread-safety fix, and the metric-name-literal lint rule.

The acceptance invariant: every completed request's report carries a
serve-phase breakdown whose phase sum is within 10% of its measured
end-to-end latency (by construction the phases PARTITION the
submit->result interval, so the slack only absorbs rounding).
"""

import json
import os
import textwrap
import threading
import time
import urllib.request

import numpy as np
import pytest
import jax.numpy as jnp

from amgcl_tpu import telemetry
from amgcl_tpu.models.amg import AMGParams
from amgcl_tpu.models.make_solver import make_solver
from amgcl_tpu.serve import STACKED_LOWERING, SolverService, lowering_kind
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.telemetry import live as tlive
from amgcl_tpu.telemetry.health import diagnose, serve_findings
from amgcl_tpu.utils.sample_problem import poisson3d

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bundle(m=8):
    A, rhs = poisson3d(m)
    ms = make_solver(A, AMGParams(dtype=jnp.float32, coarse_enough=50),
                     solver=CG(maxiter=50, tol=1e-6))
    return A, rhs, ms


# ===========================================================================
# per-request spans
# ===========================================================================

def test_request_spans_partition_latency():
    """report.serve carries the full phase breakdown; the phase sum is
    within 10% of the end-to-end latency; request ids are unique and
    the lowering tag marks the stacked trace."""
    _, rhs, ms = _bundle()
    with SolverService(ms, batch=4, flush_ms=25) as svc:
        futs = [svc.submit(rhs * (1.0 + k)) for k in range(6)]
        results = [f.result(timeout=120) for f in futs]
    rids = set()
    for _, rep in results:
        s = rep.serve
        assert s is not None
        rids.add(s["request_id"])
        total = (s["queue_ms"] + s["pad_ms"] + s["compile_ms"]
                 + s["solve_ms"] + s["sync_ms"])
        assert abs(total - s["latency_ms"]) \
            <= 0.1 * s["latency_ms"] + 0.5, (total, s["latency_ms"])
        assert s["bucket_B"] in (1, 2, 4)
        assert 0 < s["batch_fill"] <= 1.0
        assert s["lowering"] == STACKED_LOWERING == "xla-batched"
        assert "serve" in rep.to_dict()
    assert len(rids) == 6
    # the span recorder kept a queue/solve span per request
    paths = {p.split("/", 1)[1] for p, _, _ in svc.spans.events}
    assert {"queue", "pad", "solve", "sync"} <= paths
    trace = svc.to_chrome_trace(tid=3, tid_name="serve requests")
    names = {e["name"] for e in trace["traceEvents"]
             if e.get("ph") == "X"}
    assert {"queue", "solve"} <= names


def test_occupancy_math_on_partial_batch():
    """3 requests land in a power-of-two bucket of 4: batch_fill = 0.75
    everywhere (the reports, the live histogram, stats) and the
    padding-waste ledger books the dead column. (The power-of-two
    bucketing bounds per-dispatch fill to (0.5, 1] — 2 requests would
    ride a bucket of 2 at fill 1.0.)"""
    _, rhs, ms = _bundle()
    # long flush: all three submits must join ONE batch
    with SolverService(ms, batch=4, flush_ms=2000) as svc:
        futs = [svc.submit(rhs * (1.0 + k)) for k in range(3)]
        results = [f.result(timeout=120) for f in futs]
        stats = svc.stats()
    for _, rep in results:
        assert rep.serve["bucket_B"] == 4
        assert rep.serve["batch_fill"] == 0.75
    assert svc.live.get("serve_batch_fill") == 0.75
    assert svc.live.get("serve_padded_slots_total") == 1
    assert svc.live.get("serve_requests_total") == 3
    assert svc.live.get("serve_bucket_solves_total", bucket="4") == 3
    assert stats["batch_fill"] == 0.75
    waste = stats["padding_waste"]
    assert waste["flops"] > 0 and waste["bytes"] > 0
    iters_max = max(r[1].iters for r in results)
    assert waste["padded_col_iters"] == 1 * iters_max


def test_padding_waste_ledger_model():
    """krylov_iteration_model(effective_batch=k): fill math, the
    effective/waste split, and the amortization asymmetry (FLOPs scale
    with padding, stored-operator bytes do not)."""
    from amgcl_tpu.ops import device as dev
    from amgcl_tpu.telemetry.ledger import krylov_iteration_model
    A, _ = poisson3d(8)
    Ad = dev.to_device(A, "dia", jnp.float32)
    m = krylov_iteration_model("CG", Ad, batch=8, effective_batch=2)
    assert m["batch"] == 8 and m["effective_batch"] == 2
    assert m["batch_fill"] == 0.25
    assert m["padding_waste_flops"] + m["effective_flops"] == m["flops"]
    assert m["padding_waste_bytes"] + m["effective_bytes"] == m["bytes"]
    assert m["padding_waste_flops"] == int(round(0.75 * m["flops"]))
    # bytes waste only covers the per-column traffic, so its fraction
    # sits strictly below the FLOP fraction
    assert 0 < m["padding_waste_bytes"] < 0.75 * m["bytes"]
    full = krylov_iteration_model("CG", Ad, batch=8, effective_batch=8)
    assert full["padding_waste_flops"] == 0
    assert full["padding_waste_bytes"] == 0


# ===========================================================================
# /metrics endpoint
# ===========================================================================

def test_metrics_endpoint_scrape_smoke():
    """Port 0 = ephemeral; /metrics serves live gauges (queue depth,
    batch_fill, latency p99) that change between scrapes; /healthz
    reports liveness."""
    _, rhs, ms = _bundle()
    with SolverService(ms, batch=2, flush_ms=10, metrics_port=0) as svc:
        port = svc.metrics_server.port
        assert port > 0 and svc.metrics_url.endswith("/metrics")

        def scrape():
            return urllib.request.urlopen(
                "http://127.0.0.1:%d/metrics" % port,
                timeout=30).read().decode()

        first = scrape()
        assert "amgcl_tpu_serve_queue_depth" in first
        futs = [svc.submit(rhs * (1.0 + k), block=True)
                for k in range(4)]
        [f.result(timeout=120) for f in futs]
        second = scrape()
        assert second != first
        assert "amgcl_tpu_serve_batch_fill" in second
        assert 'amgcl_tpu_serve_latency_ms{quantile="0.99"}' in second
        assert "amgcl_tpu_serve_requests_total 4" in second
        h = json.loads(urllib.request.urlopen(
            "http://127.0.0.1:%d/healthz" % port, timeout=30).read())
        assert h["ok"] and h["requests"] == 4
        assert svc.stats()["metrics_port"] == port
    # close() tore the server down
    assert svc.metrics_server is None


def test_registry_rejects_undeclared_names():
    reg = tlive.LiveRegistry()
    with pytest.raises(KeyError):
        reg.inc("not_a_declared_metric")
    with pytest.raises(TypeError):
        reg.inc("serve_queue_depth")      # declared, but a gauge


def test_histogram_window_semantics_exposed():
    """Histogram deques keep only the last ``hist_cap`` observations —
    a deliberate bounded-memory choice that SILENTLY truncated until
    now. Pin the exposed semantics: snapshot rollups carry ``window``,
    the count tops out at the cap (oldest samples dropped), and the
    Prometheus exposition says 'rolling window' in the histogram HELP
    line so a scraper can never mistake the quantiles for lifetime
    ones."""
    reg = tlive.LiveRegistry(hist_cap=16)
    for i in range(50):
        reg.observe("serve_latency_ms", float(i))
    roll = reg.snapshot()["histograms"]["serve_latency_ms"]
    assert roll["window"] == 16
    assert roll["count"] == 16            # 34 oldest samples are GONE
    assert roll["min"] == 34.0            # the survivors are the tail
    assert roll["max"] == 49.0
    text = reg.prometheus()
    assert "rolling window: last 16 observations" in text


# ===========================================================================
# SLO watchdog
# ===========================================================================

def test_slo_trip_emits_event_with_findings(tmp_path):
    """An absurdly tight p99 target trips on the first batch: ONE slo
    event lands in the sink with the serve-side findings (the watchdog
    is edge-triggered — the second batch stays over threshold but emits
    nothing new), the trip counter counts the incident once, and stats
    carries the watchdog state."""
    out = tmp_path / "slo.jsonl"
    telemetry.set_default_sink(telemetry.JsonlSink(str(out)))
    try:
        _, rhs, ms = _bundle()
        with SolverService(ms, batch=2, flush_ms=10,
                           slo_p99_ms=1e-6) as svc:
            futs = [svc.submit(rhs * (1.0 + k), block=True)
                    for k in range(4)]
            [f.result(timeout=120) for f in futs]
            stats = svc.stats()
    finally:
        telemetry.set_default_sink(telemetry.NullSink())
    assert stats["slo_trips"] == 1   # one incident, not one per batch
    assert "p99" in stats["slo"]["trips"]
    assert stats["slo"]["targets"]["p99_ms"] == 1e-6
    recs = [json.loads(ln) for ln in open(out)]
    slo = [r for r in recs if r.get("event") == "slo"]
    assert slo, "no slo events emitted"
    ev = slo[-1]
    assert ev["trips"] == ["p99"]
    finds = ev["findings"]
    assert finds and finds[0]["code"] == "slo_p99"
    assert "p99 latency" in finds[0]["message"]
    assert "dominated by" in finds[0]["message"]


def test_slo_watchdog_sustained_breach_trips_once_then_rearms(tmp_path):
    """Edge-trigger under SUSTAINED breach: every batch of the run
    stays over the p99 target, yet exactly ONE incident is counted and
    ONE slo event emitted. Clearing the window re-arms the watchdog, so
    a fresh breach counts a SECOND incident — trips count incidents,
    not batches-while-tripped."""
    out = tmp_path / "slo_sustained.jsonl"
    telemetry.set_default_sink(telemetry.JsonlSink(str(out)))
    try:
        _, rhs, ms = _bundle()
        with SolverService(ms, batch=2, flush_ms=10,
                           slo_p99_ms=1e-6) as svc:
            # sustained breach: several batches, all over the target
            for k in range(6):
                svc.submit(rhs * (1.0 + k),
                           block=True).result(timeout=120)
            mid = svc.stats()
            # loosen the target until the window CLEARS (one clean
            # check re-arms the edge trigger) ...
            svc.slo["p99_ms"] = 1e9
            svc.submit(rhs * 7.0, block=True).result(timeout=120)
            # ... then tighten again: the next batch is a NEW incident
            svc.slo["p99_ms"] = 1e-6
            svc.submit(rhs * 8.0, block=True).result(timeout=120)
            stats = svc.stats()
    finally:
        telemetry.set_default_sink(telemetry.NullSink())
    assert mid["slo_trips"] == 1, mid["slo_trips"]
    assert stats["slo_trips"] == 2, stats["slo_trips"]
    # the satellite-2 stats surface rides along: the rolling-window
    # size behind the latency percentiles is part of the contract
    assert stats["histogram_window"] == svc.live.hist_cap
    recs = [json.loads(ln) for ln in open(out)]
    slo = [r for r in recs if r.get("event") == "slo"]
    assert len(slo) == 2, [r["new_trips"] for r in slo]
    assert all(r["new_trips"] == ["p99"] for r in slo)


def test_serve_findings_attribution_and_padding():
    """The p99 finding names the dominant phase with the matching
    suggestion; batch_fill < 0.5 yields the padding-waste warning; the
    findings ride telemetry.diagnose(serve=...)."""
    base = {"window": 100, "p50_ms": 5.0, "p99_ms": 50.0,
            "timeout_rate": 0.0, "unhealthy_rate": 0.0,
            "batch_fill": 0.9, "bucket": 8,
            "slo": {"p99_ms": 10.0, "timeout_rate": 0.01,
                    "unhealthy_rate": 0.05, "window": 256},
            "trips": ["p99"]}
    queue_bound = dict(base, spans_ms={"queue": 40.0, "pad": 1.0,
                                       "compile": 0.0, "solve": 8.0,
                                       "sync": 1.0})
    f = serve_findings(queue_bound)
    assert f[0]["code"] == "slo_p99"
    assert "dominated by queue_ms" in f[0]["message"]
    assert "flush deadline" in f[0]["suggestion"]
    solve_bound = dict(base, spans_ms={"queue": 1.0, "pad": 1.0,
                                       "compile": 0.0, "solve": 45.0,
                                       "sync": 1.0})
    f = serve_findings(solve_bound)
    assert "dominated by solve_ms" in f[0]["message"]
    assert "batching cannot help" in f[0]["suggestion"]
    # padding waste is a standing warning, trip or no trip
    sparse = dict(base, trips=[], batch_fill=0.3,
                  spans_ms=queue_bound["spans_ms"])
    f = serve_findings(sparse)
    assert [x["code"] for x in f] == ["serve_padding_waste"]
    assert "batch_fill 0.30" in f[0]["message"]
    assert "shrink the bucket" in f[0]["suggestion"]
    # rate trips
    rates = dict(base, trips=["timeout_rate", "unhealthy_rate"],
                 timeout_rate=0.5, unhealthy_rate=0.25,
                 spans_ms=queue_bound["spans_ms"])
    codes = [x["code"] for x in serve_findings(rates)]
    assert "slo_timeout_rate" in codes and "slo_unhealthy_rate" in codes
    # diagnose folds them in next to the solve-side findings
    finds = diagnose(None, serve=queue_bound)
    assert any(x["code"] == "slo_p99" for x in finds)


# ===========================================================================
# event schemas
# ===========================================================================

SERVE_FIELDS = {"event", "requests", "bucket", "batch_fill", "wall_s",
                "solves_per_sec", "iters_max", "resid_max", "lowering",
                "spans_ms", "totals", "ts", "ts_iso"}
SERVE_REQUEST_FIELDS = {"event", "request_id", "iters", "resid",
                        "healthy", "queue_ms", "pad_ms", "compile_ms",
                        "solve_ms", "sync_ms", "bucket_B", "batch_fill",
                        "latency_ms", "lowering", "ts", "ts_iso"}
SLO_FIELDS = {"event", "window", "p50_ms", "p99_ms", "timeout_rate",
              "unhealthy_rate", "batch_fill", "bucket", "spans_ms",
              "slo", "trips", "new_trips", "findings", "ts", "ts_iso"}


def test_serve_event_schemas(tmp_path):
    """Pin the serve / serve_request / slo JSONL event fields — sink
    consumers (dashboards, the fleet rollups) parse these by name."""
    out = tmp_path / "schema.jsonl"
    telemetry.set_default_sink(telemetry.JsonlSink(str(out)))
    try:
        _, rhs, ms = _bundle()
        with SolverService(ms, batch=2, flush_ms=10,
                           slo_p99_ms=1e-6) as svc:
            futs = [svc.submit(rhs * (1.0 + k), block=True)
                    for k in range(4)]
            [f.result(timeout=120) for f in futs]
    finally:
        telemetry.set_default_sink(telemetry.NullSink())
    recs = [json.loads(ln) for ln in open(out)]
    per_batch = [r for r in recs if r.get("event") == "serve"
                 and not r.get("final")]
    assert per_batch
    for r in per_batch:
        assert set(r) == SERVE_FIELDS, set(r) ^ SERVE_FIELDS
        assert set(r["spans_ms"]) == {"queue", "pad", "compile",
                                      "solve", "sync"}
    reqs = [r for r in recs if r.get("event") == "serve_request"]
    assert len(reqs) == 4
    for r in reqs:
        assert set(r) == SERVE_REQUEST_FIELDS, \
            set(r) ^ SERVE_REQUEST_FIELDS
    slo = [r for r in recs if r.get("event") == "slo"]
    assert slo
    for r in slo:
        assert set(r) == SLO_FIELDS, set(r) ^ SLO_FIELDS
    # the final serve summary still rides the same sink
    assert any(r.get("final") for r in recs if r.get("event") == "serve")
    # and the fleet rollups aggregate the new events by name
    from amgcl_tpu.telemetry import metrics as tmetrics
    roll = tmetrics.rollup_events(recs)
    assert roll["serve_request.latency_ms"]["count"] == 4
    assert "serve.solves_per_sec" in roll
    # the final=True lifetime summary must NOT ride the per-batch
    # rollup: its top-level requests is the lifetime total (4), the
    # per-batch rows carry at most the bucket size (2)
    assert roll["serve.requests"]["max"] <= 2
    assert roll["serve.requests"]["count"] == len(per_batch)


# ===========================================================================
# lowering tag (satellite: make the Pallas gate visible)
# ===========================================================================

def test_sink_failure_does_not_fail_futures(tmp_path):
    """serve_request emission is deferred until after futures resolve.
    The module-level telemetry.emit already swallows SINK errors, so to
    pin the ordering itself this patches emit() to raise at the worker's
    serve_request call site: if emission ever moves back before
    ``set_result``, the raise propagates to _loop's handler and fails
    the batch's futures — exactly what must not happen."""
    _, rhs, ms = _bundle()
    svc = SolverService(ms, batch=2, flush_ms=20)
    orig = telemetry.emit

    def boom(record=None, **fields):
        if fields.get("event") == "serve_request":
            raise OSError("disk full")
        return orig(record, **fields)

    telemetry.set_default_sink(
        telemetry.JsonlSink(str(tmp_path / "boom.jsonl")))
    telemetry.emit = boom
    try:
        futs = [svc.submit(rhs * (1.0 + k), block=True)
                for k in range(2)]
        for f in futs:
            x, rep = f.result(timeout=120)   # must NOT raise
            assert rep.serve["request_id"] > 0
    finally:
        telemetry.emit = orig
        telemetry.set_default_sink(telemetry.NullSink())
        svc.close()


def test_metrics_port_bind_failure_leaks_nothing():
    """A taken metrics port fails the first start() loudly, BEFORE the
    worker thread launches — nothing to clean up, and the error names
    the bind, not a half-started service."""
    import socket
    _, rhs, ms = _bundle()
    blocker = socket.socket()
    blocker.bind(("127.0.0.1", 0))
    try:
        port = blocker.getsockname()[1]
        svc = SolverService(ms, batch=2, metrics_port=port)
        with pytest.raises(OSError):
            svc.start()
        assert svc._thread is None and svc.metrics_server is None
    finally:
        blocker.close()


def test_negative_metrics_port_disables_env_knob(monkeypatch):
    """metrics_port=-1 means OFF even when AMGCL_TPU_SERVE_METRICS_PORT
    is set fleet-wide — a second service on a host must be able to opt
    out of the taken port."""
    monkeypatch.setenv("AMGCL_TPU_SERVE_METRICS_PORT", "39999")
    _, rhs, ms = _bundle()
    with SolverService(ms, batch=2, metrics_port=-1) as svc:
        svc.submit(rhs, block=True).result(timeout=120)
        assert svc.metrics_port is None
        assert svc.metrics_server is None and svc.metrics_url is None


def test_submit_after_close_raises():
    """close() is terminal: a submit() landing after (or racing) it
    raises instead of silently resurrecting a worker thread and a
    metrics port that nothing would ever stop."""
    _, rhs, ms = _bundle()
    svc = SolverService(ms, batch=2, flush_ms=10)
    f = svc.submit(rhs, block=True)
    f.result(timeout=120)
    svc.close()
    with pytest.raises(RuntimeError, match="closed"):
        svc.submit(rhs)
    assert svc._thread is None and svc.metrics_server is None


def test_failed_batch_stays_observable():
    """A batch whose dispatch raises fails its futures AND stays
    visible: in-flight gauge back to 0, the failed requests counted
    unhealthy in the lifetime stats and the SLO rolling window."""
    _, rhs, ms = _bundle()
    with SolverService(ms, batch=2, flush_ms=50) as svc:
        boom = RuntimeError("injected dispatch failure")

        def _dispatch_fail(*a, **k):
            raise boom

        svc._dispatch = _dispatch_fail
        futs = [svc.submit(rhs * (1.0 + k), block=True)
                for k in range(2)]
        for f in futs:
            with pytest.raises(RuntimeError, match="injected"):
                f.result(timeout=120)
        # worker is asynchronous past future resolution: wait for the
        # stats commit the failure path performs
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if svc.stats()["unhealthy"] >= 2:
                break
            time.sleep(0.01)
        stats = svc.stats()
    assert stats["unhealthy"] == 2
    assert svc.live.get("serve_inflight") == 0.0
    assert svc.live.get("serve_unhealthy_total") == 2
    assert stats["slo"]["unhealthy_rate"] == 1.0


def test_lowering_tag_in_reports():
    """Batched dispatches tag xla-batched; single-rhs dispatches tag
    the live gate state (pallas/xla) in SolveReport.compile."""
    A, rhs, _ = _bundle()
    ms = make_solver(A, AMGParams(dtype=jnp.float32, coarse_enough=50),
                     solver=CG(maxiter=50, tol=1e-6), batch=2)
    _, info1 = ms(rhs)
    assert info1.compile["lowering"] == lowering_kind(
        False, jnp.float32)
    assert info1.compile["lowering"] in ("pallas", "xla")
    R = np.stack([rhs, 2 * rhs], axis=1)
    _, infob = ms(R)
    assert infob.compile["lowering"] == "xla-batched"
    # the tag is stickied at trace time: a warm repeat reuses jit's
    # cached executable, so a gate-state change between calls must NOT
    # relabel it (the tag describes the executable that ran) — but a
    # fresh trace (new stacked shape) re-reads the gates
    import amgcl_tpu.serve.batched as batched_mod
    tag1 = info1.compile["lowering"]
    orig = batched_mod.lowering_kind
    batched_mod.lowering_kind = lambda *a, **k: "sentinel"
    try:
        _, info2 = ms(rhs)
        assert info2.compile["new_traces"] == 0     # warm repeat
        assert info2.compile["lowering"] == tag1    # sticky
        R4 = np.stack([rhs, 2 * rhs, 3 * rhs, 4 * rhs], axis=1)
        _, info4 = ms(R4)                           # fresh (n, 4) trace
        assert info4.compile["new_traces"] >= 1
        assert info4.compile["lowering"] == "sentinel"   # refreshed
    finally:
        batched_mod.lowering_kind = orig


# ===========================================================================
# JsonlSink write-path thread-safety (satellite)
# ===========================================================================

def test_jsonl_sink_two_writer_threads(tmp_path):
    """Two threads hammering one size-capped (rotating) file sink: no
    exceptions, every surviving line is intact JSON, and the live file
    plus its .1 sibling stay within the rotation budget."""
    path = tmp_path / "rot.jsonl"
    sink = telemetry.JsonlSink(str(path), max_bytes=4096)
    errors = []

    def writer(tag, n=300):
        try:
            for i in range(n):
                sink.emit(event="stress", tag=tag, i=i,
                          pad="x" * 40)
        except Exception as e:    # noqa: BLE001 — the assertion target
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(t,))
               for t in ("a", "b")]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert not errors, errors
    total = 0
    for p in (str(path), str(path) + ".1"):
        if not os.path.exists(p):
            continue
        with open(p) as f:
            for line in f:
                rec = json.loads(line)      # torn line would raise
                assert rec["event"] == "stress"
                total += 1
    assert total > 0
    # rotation kept the on-disk footprint bounded (~2x the cap + one
    # record of slack per file)
    for p in (str(path), str(path) + ".1"):
        if os.path.exists(p):
            assert os.path.getsize(p) < 4096 + 4096


# ===========================================================================
# metric-name-literal lint rule (satellite)
# ===========================================================================

def test_lint_metric_name_literal(tmp_path):
    """Fixture package: a declared table in telemetry/live.py, one
    clean call, one undeclared literal, one dynamic name — the rule
    flags exactly the last two."""
    from amgcl_tpu.analysis import lint
    pkg = tmp_path / "pkg"
    (pkg / "telemetry").mkdir(parents=True)
    (pkg / "telemetry" / "live.py").write_text(textwrap.dedent("""
        METRICS = {
            "declared_total": ("counter", "x"),
        }
        class LiveRegistry:
            def inc(self, name, by=1):
                self._c[name] = by       # dynamic by design: exempt
    """))
    (pkg / "mod.py").write_text(textwrap.dedent("""
        def work(reg, name):
            reg.inc("declared_total")
            reg.inc(name="declared_total")
            reg.inc("rogue_total")
            reg.inc(name="kw_rogue_total")
            reg.observe(name, 1.0)
    """))
    (tmp_path / "README.md").write_text("")
    fs = lint.run_lint(root=str(pkg), readme=str(tmp_path / "README.md"),
                       rules=["metric-name-literal"])
    assert [f["symbol"] for f in fs] == ["rogue_total", "kw_rogue_total",
                                        "work"]
    assert all(f["rule"] == "metric-name-literal" for f in fs)
    assert "not declared" in fs[0]["message"]
    assert "not declared" in fs[1]["message"]
    assert "string literal" in fs[2]["message"]


def test_lint_table_matches_runtime_registry():
    """The statically parsed table IS the registry the /metrics
    endpoint serves — the lint rule and the runtime can never disagree
    about what is declared."""
    from amgcl_tpu.analysis import lint
    assert lint.declared_metric_names() == set(tlive.METRICS)
    # and the repo itself is clean under the rule
    fs = lint.run_lint(rules=["metric-name-literal"])
    assert fs == [], fs


# ===========================================================================
# bench --throughput latency rows (satellite)
# ===========================================================================

def test_bench_throughput_service_latency():
    """_bench_throughput rows carry service-measured latency_ms
    p50/p99 and the b<N>_p99_ms rollup key the trend reads — and
    (ISSUE 16 satellite) the rows now CONFESS their protocol: the
    harness is closed-loop, so its latency_ms hides queueing a real
    arrival process would pay (coordinated omission), and the
    open_loop_latency_ms companion measured from the intended arrival
    at t0 bounds it from above."""
    import sys
    sys.path.insert(0, _REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    A, rhs = poisson3d(6)
    solver = make_solver(A, AMGParams(dtype=jnp.float32,
                                      coarse_enough=50),
                         CG(maxiter=50, tol=1e-6))
    rec = bench._bench_throughput(solver, jnp.asarray(rhs, jnp.float32),
                                  on_tpu=False, bs=(2,))
    row = rec["rows"][0]
    assert row["B"] == 2
    lat = row["latency_ms"]
    assert 0 < lat["p50"] <= lat["p99"] <= lat["max"]
    assert rec["b2_p99_ms"] == lat["p99"]
    assert row["service_sps"] > 0
    # the coordinated-omission labels (satellite of the storm harness)
    assert row["closed_loop"] is True
    assert row["latency_basis"] == "submit"
    ol = row["open_loop_latency_ms"]
    assert ol["basis"] == "intended_arrival_t0"
    assert 0 < ol["p50"] <= ol["p99"] <= ol["max"]
    # every request is intended at t0 and submitted at or after it, so
    # completion-minus-t0 dominates completion-minus-submit order
    # statistic by order statistic (0.01 ms of rounding slack)
    assert ol["p99"] >= lat["p99"] - 0.01
