"""Device algebra: sparse formats and backend primitives vs host reference."""

import numpy as np
import jax.numpy as jnp
import pytest

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.ops import device as dev
from amgcl_tpu.utils.sample_problem import poisson3d
from tests.test_csr import random_csr


@pytest.fixture(scope="module")
def problem():
    A, rhs = poisson3d(8)
    x = np.random.RandomState(0).rand(A.nrows)
    return A, rhs, x


def test_ell_spmv(problem):
    A, _, x = problem
    M = dev.csr_to_ell(A, jnp.float64)
    y = dev.spmv(M, jnp.asarray(x))
    assert np.allclose(np.asarray(y), A.spmv(x))


def test_dia_spmv(problem):
    A, _, x = problem
    M = dev.csr_to_dia(A, jnp.float64)
    y = dev.spmv(M, jnp.asarray(x))
    assert np.allclose(np.asarray(y), A.spmv(x))


def test_dense_mv(problem):
    A, _, x = problem
    M = dev.DenseMatrix(jnp.asarray(A.to_dense()))
    assert np.allclose(np.asarray(dev.spmv(M, jnp.asarray(x))), A.spmv(x))


def test_auto_format_selection(problem):
    A, _, _ = problem
    M = dev.to_device(A, "auto", jnp.float64, dense_cutoff=10)
    assert isinstance(M, dev.DiaMatrix)  # Poisson is banded: 7 diagonals
    small = random_csr(20, 20, density=0.5)
    assert isinstance(dev.to_device(small, "auto", jnp.float64),
                      dev.DenseMatrix)


def test_rectangular_ell():
    P = random_csr(30, 10, density=0.2, seed=3)
    # remove the square setdiag effect: P is rectangular with diag on top rows
    M = dev.csr_to_ell(P, jnp.float64)
    x = np.random.RandomState(1).rand(10)
    assert np.allclose(np.asarray(dev.spmv(M, jnp.asarray(x))), P.spmv(x))


def test_block_ell_spmv():
    A = random_csr(24, 24, seed=4).to_block(4)
    M = dev.csr_to_ell(A, jnp.float64)
    x = np.random.RandomState(2).rand(24)
    assert np.allclose(np.asarray(dev.spmv(M, jnp.asarray(x))), A.spmv(x))


def test_residual(problem):
    A, rhs, x = problem
    M = dev.csr_to_dia(A, jnp.float64)
    r = dev.residual(jnp.asarray(rhs), M, jnp.asarray(x))
    assert np.allclose(np.asarray(r), rhs - A.spmv(x))


def test_vector_primitives():
    x = jnp.arange(5.0)
    y = jnp.ones(5)
    assert np.allclose(dev.axpby(2.0, x, 3.0, y), 2 * np.arange(5.0) + 3)
    z = dev.axpbypcz(1.0, x, 2.0, y, 0.5, x)
    assert np.allclose(z, np.arange(5.0) * 1.5 + 2)
    w = dev.vmul(2.0, x, y, 1.0, x)
    assert np.allclose(w, 3 * np.arange(5.0))
    assert np.isclose(float(dev.inner_product(x, x)), 30.0)
    assert np.isclose(float(dev.norm(x)), np.sqrt(30.0))
    assert np.allclose(dev.gather(x, jnp.asarray([4, 0])), [4.0, 0.0])
    assert np.allclose(dev.scatter(y, jnp.asarray([0]), jnp.asarray([7.0])),
                       [7, 1, 1, 1, 1])


def test_complex_ell_and_dia_spmv():
    """Complex values must survive the host->device packing (regression:
    the scratch buffers used to be hard-coded float64)."""
    from amgcl_tpu.utils.sample_problem import poisson3d_complex
    A, _ = poisson3d_complex(6)
    x = (np.random.RandomState(3).rand(A.nrows)
         + 1j * np.random.RandomState(4).rand(A.nrows))
    ref = A.spmv(x)
    for conv in (dev.csr_to_ell, dev.csr_to_dia):
        M = conv(A, jnp.complex128)
        assert np.allclose(np.asarray(dev.spmv(M, jnp.asarray(x))), ref)


def test_tall_rectangular_dia():
    """nrows > ncols DIA used to read clamped garbage via dynamic_slice."""
    R = random_csr(30, 10, density=0.3, seed=7)
    M = dev.csr_to_dia(R, jnp.float64)
    x = np.random.RandomState(5).rand(10)
    assert np.allclose(np.asarray(dev.spmv(M, jnp.asarray(x))), R.spmv(x))


def test_pallas_dia_spmv_interpret():
    """Pallas DIA kernel in interpret mode vs the XLA path."""
    from amgcl_tpu.ops.pallas_spmv import dia_spmv
    from amgcl_tpu.utils.sample_problem import poisson3d
    A, _ = poisson3d(10)
    M = dev.csr_to_dia(A, jnp.float64)
    x = jnp.asarray(np.random.RandomState(0).rand(A.nrows))
    y_ref = M.mv(x)
    y = dia_spmv(M.offsets, M.data, x, tile=256, interpret=True)
    assert np.allclose(np.asarray(y), np.asarray(y_ref))


@pytest.mark.parametrize("db", [False, True])
def test_pallas_dia_kernels_db_modes(db):
    """The window double-buffering flag (AMGCL_TPU_DIA_DB / the ``db``
    static arg) must not change numerics in any DIA kernel — the db=True
    prefetch path is otherwise only exercised in chip sessions."""
    from amgcl_tpu.ops.pallas_spmv import (dia_spmv, dia_residual,
                                           dia_scaled_correction,
                                           dia_spmv_dots)
    from amgcl_tpu.utils.sample_problem import poisson3d
    A, _ = poisson3d(10)
    M = dev.csr_to_dia(A, jnp.float64)
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.rand(A.nrows))
    f = jnp.asarray(rng.rand(A.nrows))
    w = jnp.asarray(rng.rand(A.nrows))
    y_ref = np.asarray(M.mv(x))
    y = dia_spmv(M.offsets, M.data, x, tile=256, interpret=True, db=db)
    assert np.allclose(np.asarray(y), y_ref)
    r = dia_residual(M.offsets, M.data, f, x, tile=256, interpret=True,
                     db=db)
    assert np.allclose(np.asarray(r), np.asarray(f) - y_ref)
    c = dia_scaled_correction(M.offsets, M.data, w, f, x, tile=256,
                              interpret=True, db=db)
    assert np.allclose(np.asarray(c),
                       np.asarray(x) + np.asarray(w)
                       * (np.asarray(f) - y_ref))
    y2, yy, yx, yw = dia_spmv_dots(M.offsets, M.data, x, w, tile=256,
                                   interpret=True, db=db)
    assert np.allclose(np.asarray(y2), y_ref)
    assert np.allclose(float(yy), y_ref @ y_ref)
    assert np.allclose(float(yx), y_ref @ np.asarray(x))
    assert np.allclose(float(yw), y_ref @ np.asarray(w))


def test_pallas_dia_spmv_rect_interpret():
    from amgcl_tpu.ops.pallas_spmv import dia_spmv
    R = random_csr(300, 100, density=0.1, seed=9)
    M = dev.csr_to_dia(R, jnp.float64)
    x = jnp.asarray(np.random.RandomState(1).rand(100))
    y = dia_spmv(M.offsets, M.data, x, tile=128, interpret=True)
    assert np.allclose(np.asarray(y), R.spmv(np.asarray(x)))


def test_pallas_dia_spmv_wide_banded_interpret():
    """Wide operator with a NARROW band: x is longer than the tile window
    span, which used to fail at trace time (round-1 advisor finding) —
    xp must be sized for max(window span, len(x))."""
    import scipy.sparse as sp
    from amgcl_tpu.ops.csr import CSR
    from amgcl_tpu.ops.pallas_spmv import dia_spmv
    n, m = 100, 300
    R = CSR.from_scipy(sp.diags(
        [np.ones(n), 0.5 * np.ones(n), 0.25 * np.ones(n)],
        [0, 5, 20], shape=(n, m), format="csr"))
    M = dev.csr_to_dia(R, jnp.float64)
    assert max(M.offsets) + (-(-n // 128) * 128) < m   # the failing regime
    x = jnp.asarray(np.random.RandomState(2).rand(m))
    y = dia_spmv(M.offsets, M.data, x, tile=128, interpret=True)
    assert np.allclose(np.asarray(y), R.spmv(np.asarray(x)))


def test_pallas_dia_spmv_wide_interpret():
    """Wide (ncols > nrows) matrices read beyond the tile — regression for
    the undersized VMEM window."""
    from amgcl_tpu.ops.pallas_spmv import dia_spmv
    R = random_csr(100, 300, density=0.05, seed=11)
    M = dev.csr_to_dia(R, jnp.float64)
    x = jnp.asarray(np.random.RandomState(2).rand(300))
    y = dia_spmv(M.offsets, M.data, x, tile=64, interpret=True)
    assert np.allclose(np.asarray(y), R.spmv(np.asarray(x)))

def test_pallas_dia_residual_interpret():
    """Fused r = f - A x kernel vs the composed ops."""
    from amgcl_tpu.ops.pallas_spmv import dia_residual
    from amgcl_tpu.utils.sample_problem import poisson3d
    A, _ = poisson3d(10)
    M = dev.csr_to_dia(A, jnp.float64)
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.rand(A.nrows))
    f = jnp.asarray(rng.rand(A.nrows))
    r = dia_residual(M.offsets, M.data, f, x, tile=256, interpret=True)
    assert np.allclose(np.asarray(r), np.asarray(f - M.mv(x)))


def test_pallas_dia_residual_rect_interpret():
    """Rectangular operator: f has nrows entries, x has ncols."""
    from amgcl_tpu.ops.pallas_spmv import dia_residual
    R = random_csr(100, 300, density=0.05, seed=13)
    M = dev.csr_to_dia(R, jnp.float64)
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.rand(300))
    f = jnp.asarray(rng.rand(100))
    r = dia_residual(M.offsets, M.data, f, x, tile=64, interpret=True)
    assert np.allclose(np.asarray(r), np.asarray(f) - R.spmv(np.asarray(x)))


def test_pallas_dia_scaled_correction_interpret():
    """Fused x + w*(f - A x) sweep vs the composed smoother step."""
    from amgcl_tpu.ops.pallas_spmv import dia_scaled_correction
    from amgcl_tpu.utils.sample_problem import poisson3d
    A, _ = poisson3d(9)
    M = dev.csr_to_dia(A, jnp.float64)
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.rand(A.nrows))
    f = jnp.asarray(rng.rand(A.nrows))
    w = jnp.asarray(rng.rand(A.nrows))
    got = dia_scaled_correction(M.offsets, M.data, w, f, x,
                                tile=256, interpret=True)
    want = x + w * (f - M.mv(x))
    assert np.allclose(np.asarray(got), np.asarray(want))


def test_pallas_fused_f32_interpret():
    """The production dtype (f32 hierarchy) through both fused kernels."""
    from amgcl_tpu.ops.pallas_spmv import (dia_residual,
                                           dia_scaled_correction)
    from amgcl_tpu.utils.sample_problem import poisson3d
    A, _ = poisson3d(8)
    M = dev.csr_to_dia(A, jnp.float32)
    rng = np.random.RandomState(6)
    x = jnp.asarray(rng.rand(A.nrows), dtype=jnp.float32)
    f = jnp.asarray(rng.rand(A.nrows), dtype=jnp.float32)
    w = jnp.asarray(rng.rand(A.nrows), dtype=jnp.float32)
    r = dia_residual(M.offsets, M.data, f, x, tile=256, interpret=True)
    assert r.dtype == jnp.float32
    assert np.allclose(np.asarray(r), np.asarray(f - M.mv(x)), atol=1e-5)
    c = dia_scaled_correction(M.offsets, M.data, w, f, x,
                              tile=256, interpret=True)
    assert np.allclose(np.asarray(c), np.asarray(x + w * (f - M.mv(x))),
                       atol=1e-5)

def test_pallas_wiring_end_to_end(monkeypatch):
    """Full AMG-CG solve with the DIA dispatch seams forced through the
    Pallas kernels (interpret mode) — exercises the production wiring
    (hierarchy residual, smoother sweeps, Krylov spmv) rather than the
    kernels in isolation. Must match the XLA path bit-for-bit in count
    and closely in value."""
    from amgcl_tpu.utils.sample_problem import poisson3d
    from amgcl_tpu.models.make_solver import make_solver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.cg import CG

    A, rhs = poisson3d(10)
    prm = AMGParams(dtype=jnp.float32, coarse_enough=200)
    x_ref, i_ref = make_solver(A, prm, CG(tol=1e-6, maxiter=40))(rhs)

    monkeypatch.setenv("AMGCL_TPU_PALLAS_INTERPRET", "1")
    x_pal, i_pal = make_solver(A, prm, CG(tol=1e-6, maxiter=40))(rhs)

    assert i_pal.iters == i_ref.iters
    r = rhs - A.spmv(np.asarray(x_pal, dtype=np.float64))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-5


def test_pallas_dia_spmv_dot_interpret():
    """Fused (A p, <Ap, p>) vs composed."""
    from amgcl_tpu.ops.pallas_spmv import dia_spmv_dot
    from amgcl_tpu.utils.sample_problem import poisson3d
    A, _ = poisson3d(10)
    M = dev.csr_to_dia(A, jnp.float32)
    p = jnp.asarray(np.random.RandomState(8).rand(A.nrows),
                    dtype=jnp.float32)
    q, qp = dia_spmv_dot(M.offsets, M.data, p, tile=256, interpret=True)
    q_ref = M.mv(p)
    assert np.allclose(np.asarray(q), np.asarray(q_ref), atol=1e-5)
    assert np.allclose(float(qp), float(jnp.vdot(q_ref, p)), rtol=1e-5)


def test_pallas_wiring_bicgstab(monkeypatch):
    """BiCGStab's fused spmv+dots path (interpret hook): iteration
    parity with the composed path."""
    from amgcl_tpu.utils.sample_problem import poisson3d
    from amgcl_tpu.models.make_solver import make_solver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.bicgstab import BiCGStab

    A, rhs = poisson3d(10)
    prm = AMGParams(dtype=jnp.float32, coarse_enough=200)
    x_ref, i_ref = make_solver(A, prm, BiCGStab(tol=1e-6, maxiter=40))(rhs)

    monkeypatch.setenv("AMGCL_TPU_PALLAS_INTERPRET", "1")
    x_pal, i_pal = make_solver(A, prm, BiCGStab(tol=1e-6, maxiter=40))(rhs)

    assert i_pal.iters == i_ref.iters
    r = rhs - A.spmv(np.asarray(x_pal, dtype=np.float64))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-5


@pytest.mark.parametrize("name", ["gmres", "fgmres", "lgmres", "idrs",
                                  "bicgstabl", "richardson"])
def test_pallas_wiring_solver_sweep(monkeypatch, name):
    """Remaining Krylov bodies through the interpret hook: iteration
    parity with the composed path (wiring-level check)."""
    from amgcl_tpu.utils.sample_problem import poisson3d
    from amgcl_tpu.models.make_solver import make_solver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.models.runtime import SOLVERS

    A, rhs = poisson3d(8)
    prm = AMGParams(dtype=jnp.float32, coarse_enough=200)
    mk = lambda: SOLVERS[name](maxiter=60, tol=1e-6)
    x_ref, i_ref = make_solver(A, prm, mk())(rhs)

    monkeypatch.setenv("AMGCL_TPU_PALLAS_INTERPRET", "1")
    x_pal, i_pal = make_solver(A, prm, mk())(rhs)
    assert i_pal.iters == i_ref.iters
    r = rhs - A.spmv(np.asarray(x_pal, dtype=np.float64))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-4


def test_pallas_min_ndiag_routes_to_xla(monkeypatch):
    """AMGCL_TPU_PALLAS_MIN_NDIAG gates the DIA Pallas kernels by
    diagonal count (the per-level XLA-vs-Pallas default knob for chip
    sessions); below the threshold the XLA path must serve mv/residual
    with identical results."""
    import numpy as np
    import jax.numpy as jnp
    from amgcl_tpu.ops.device import DiaMatrix, residual

    n = 64
    offsets = (-1, 0, 1)
    data = jnp.asarray(np.random.RandomState(0).rand(3, n), jnp.float32)
    M = DiaMatrix(offsets, data, (n, n))
    x = jnp.asarray(np.random.RandomState(1).rand(n), jnp.float32)
    f = jnp.asarray(np.random.RandomState(2).rand(n), jnp.float32)
    y_ref = np.asarray(M.mv(x))
    r_ref = np.asarray(residual(f, M, x))
    monkeypatch.setenv("AMGCL_TPU_PALLAS_MIN_NDIAG", "5")
    assert M._pallas_mode(x) is None          # 3 diagonals < 5 -> XLA
    np.testing.assert_allclose(np.asarray(M.mv(x)), y_ref, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(residual(f, M, x)), r_ref,
                               rtol=1e-6)
