"""Coarsening policy tests: RS splitting, as_scalar block wrapper,
nullspace-augmented SA."""

import numpy as np
import jax.numpy as jnp
import pytest

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.models.make_solver import make_solver
from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.coarsening.ruge_stuben import RugeStuben
from amgcl_tpu.coarsening.as_scalar import AsScalar
from amgcl_tpu.coarsening.smoothed_aggregation import SmoothedAggregation
from amgcl_tpu.utils.sample_problem import poisson3d, poisson3d_block


def test_ruge_stuben_cg():
    A, rhs = poisson3d(16)
    solve = make_solver(
        A, AMGParams(coarsening=RugeStuben(), dtype=jnp.float64,
                     coarse_enough=500),
        CG(maxiter=100, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8
    assert info.iters < 60
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-7


def test_ruge_stuben_rejects_block():
    A, _ = poisson3d_block(6, 2)
    with pytest.raises(NotImplementedError):
        RugeStuben().transfer_operators(A)


def test_as_scalar_block_hierarchy():
    A, rhs = poisson3d_block(8, 2)
    solve = make_solver(
        A, AMGParams(coarsening=AsScalar(SmoothedAggregation()),
                     dtype=jnp.float64, coarse_enough=300),
        CG(maxiter=100, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8


def test_block_hierarchy_direct():
    """Block matrix through the default (pointwise-aggregation) path."""
    A, rhs = poisson3d_block(8, 3)
    solve = make_solver(
        A, AMGParams(dtype=jnp.float64, coarse_enough=300),
        CG(maxiter=200, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8


def test_nullspace_sa():
    """Near-nullspace vectors: constant + linear functions on the grid."""
    n = 12
    A, rhs = poisson3d(n)
    g = np.arange(n)
    X, Y, Z = np.meshgrid(g, g, g, indexing="ij")
    B = np.stack([np.ones(n**3), X.ravel() / n], axis=1)
    solve = make_solver(
        A, AMGParams(coarsening=SmoothedAggregation(nullspace=B),
                     dtype=jnp.float64, coarse_enough=200),
        CG(maxiter=100, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8


@pytest.mark.parametrize("coarsening_factory", [
    lambda: RugeStuben(), lambda: SmoothedAggregation()])
def test_setup_does_not_mutate_input(coarsening_factory):
    """Regression: scipy views over A's buffers used to be compacted in
    place by eliminate_zeros, corrupting A mid-setup."""
    A, _ = poisson3d(10)
    ptr, col, val = A.ptr.copy(), A.col.copy(), A.val.copy()
    c = coarsening_factory()
    P, R = c.transfer_operators(A)
    c.coarse_operator(A, P, R)
    assert np.array_equal(A.ptr, ptr)
    assert np.array_equal(A.col, col)
    assert np.array_equal(A.val, val)


def test_smoothed_aggr_emin():
    from amgcl_tpu.coarsening.smoothed_aggr_emin import SmoothedAggrEMin
    A, rhs = poisson3d(14)
    solve = make_solver(
        A, AMGParams(coarsening=SmoothedAggrEMin(), dtype=jnp.float64,
                     coarse_enough=400),
        CG(maxiter=100, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8
    assert info.iters < 40


def test_rigid_body_modes_nullspace():
    """2D elasticity-style: vector Laplacian with rigid-body nullspace."""
    import scipy.sparse as sp
    from amgcl_tpu.coarsening.rigid_body_modes import rigid_body_modes
    n = 14
    T = sp.diags([-np.ones(n - 1), 2 * np.ones(n), -np.ones(n - 1)],
                 [-1, 0, 1])
    L = (sp.kron(sp.identity(n), T) + sp.kron(T, sp.identity(n))).tocsr()
    K = sp.kron(L, np.eye(2)).tocsr()      # interleaved 2D displacement
    g = np.arange(n, dtype=float)
    X, Y = np.meshgrid(g, g, indexing="ij")
    coords = np.stack([X.ravel(), Y.ravel()], axis=1)
    B = rigid_body_modes(coords)
    assert B.shape == (2 * n * n, 3)
    solve = make_solver(
        CSR.from_scipy(K),
        AMGParams(coarsening=SmoothedAggregation(nullspace=B),
                  dtype=jnp.float64, coarse_enough=300),
        CG(maxiter=200, tol=1e-8))
    rhs = np.ones(2 * n * n)
    x, info = solve(rhs)
    assert info.resid < 1e-8


def test_device_mis_aggregates():
    """Device (jittable) MIS must produce a valid aggregation: every
    connected node assigned, aggregates connected through the strength
    graph, and the resulting AMG converges like the host path."""
    from amgcl_tpu.coarsening.device_mis import aggregates_on_device
    A, rhs = poisson3d(12)
    agg, n_agg = aggregates_on_device(A)
    assert (agg >= 0).all()           # no isolated rows in this fixture
    assert n_agg == agg.max() + 1
    sizes = np.bincount(agg)
    assert sizes.min() >= 1 and 4 <= A.nrows / n_agg <= 40
    # spot-check hierarchy quality through a real solve

    class DeviceAggSA(SmoothedAggregation):
        def transfer_operators(self, A, ctx=None):
            # route aggregation through the device path, keep SA smoothing
            import amgcl_tpu.coarsening.smoothed_aggregation as sa
            orig = sa.plain_aggregates
            sa.plain_aggregates = lambda M, e: aggregates_on_device(M, e)
            try:
                return super().transfer_operators(A, ctx)
            finally:
                sa.plain_aggregates = orig

    solve = make_solver(
        A, AMGParams(coarsening=DeviceAggSA(), dtype=jnp.float64,
                     coarse_enough=200),
        CG(maxiter=100, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8
    assert info.iters < 40
