"""Coarsening policy tests: RS splitting, as_scalar block wrapper,
nullspace-augmented SA."""

import numpy as np
import jax.numpy as jnp
import pytest

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.models.make_solver import make_solver
from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.coarsening.ruge_stuben import RugeStuben
from amgcl_tpu.coarsening.as_scalar import AsScalar
from amgcl_tpu.coarsening.smoothed_aggregation import SmoothedAggregation
from amgcl_tpu.utils.sample_problem import poisson3d, poisson3d_block


def test_ruge_stuben_cg():
    A, rhs = poisson3d(16)
    solve = make_solver(
        A, AMGParams(coarsening=RugeStuben(), dtype=jnp.float64,
                     coarse_enough=500),
        CG(maxiter=100, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8
    assert info.iters < 60
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-7


def test_ruge_stuben_rejects_block():
    A, _ = poisson3d_block(6, 2)
    with pytest.raises(NotImplementedError):
        RugeStuben().transfer_operators(A)


def test_as_scalar_block_hierarchy():
    A, rhs = poisson3d_block(8, 2)
    solve = make_solver(
        A, AMGParams(coarsening=AsScalar(SmoothedAggregation()),
                     dtype=jnp.float64, coarse_enough=300),
        CG(maxiter=100, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8


def test_block_hierarchy_direct():
    """Block matrix through the default (pointwise-aggregation) path."""
    A, rhs = poisson3d_block(8, 3)
    solve = make_solver(
        A, AMGParams(dtype=jnp.float64, coarse_enough=300),
        CG(maxiter=200, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8


def test_nullspace_sa():
    """Near-nullspace vectors: constant + linear functions on the grid."""
    n = 12
    A, rhs = poisson3d(n)
    g = np.arange(n)
    X, Y, Z = np.meshgrid(g, g, g, indexing="ij")
    B = np.stack([np.ones(n**3), X.ravel() / n], axis=1)
    solve = make_solver(
        A, AMGParams(coarsening=SmoothedAggregation(nullspace=B),
                     dtype=jnp.float64, coarse_enough=200),
        CG(maxiter=100, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8


@pytest.mark.parametrize("coarsening_factory", [
    lambda: RugeStuben(), lambda: SmoothedAggregation()])
def test_setup_does_not_mutate_input(coarsening_factory):
    """Regression: scipy views over A's buffers used to be compacted in
    place by eliminate_zeros, corrupting A mid-setup."""
    A, _ = poisson3d(10)
    ptr, col, val = A.ptr.copy(), A.col.copy(), A.val.copy()
    c = coarsening_factory()
    P, R = c.transfer_operators(A)
    c.coarse_operator(A, P, R)
    assert np.array_equal(A.ptr, ptr)
    assert np.array_equal(A.col, col)
    assert np.array_equal(A.val, val)


def test_smoothed_aggr_emin():
    from amgcl_tpu.coarsening.smoothed_aggr_emin import SmoothedAggrEMin
    A, rhs = poisson3d(14)
    solve = make_solver(
        A, AMGParams(coarsening=SmoothedAggrEMin(), dtype=jnp.float64,
                     coarse_enough=400),
        CG(maxiter=100, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8
    assert info.iters < 40


def test_rigid_body_modes_nullspace():
    """2D elasticity-style: vector Laplacian with rigid-body nullspace."""
    import scipy.sparse as sp
    from amgcl_tpu.coarsening.rigid_body_modes import rigid_body_modes
    n = 14
    T = sp.diags([-np.ones(n - 1), 2 * np.ones(n), -np.ones(n - 1)],
                 [-1, 0, 1])
    L = (sp.kron(sp.identity(n), T) + sp.kron(T, sp.identity(n))).tocsr()
    K = sp.kron(L, np.eye(2)).tocsr()      # interleaved 2D displacement
    g = np.arange(n, dtype=float)
    X, Y = np.meshgrid(g, g, indexing="ij")
    coords = np.stack([X.ravel(), Y.ravel()], axis=1)
    B = rigid_body_modes(coords)
    assert B.shape == (2 * n * n, 3)
    solve = make_solver(
        CSR.from_scipy(K),
        AMGParams(coarsening=SmoothedAggregation(nullspace=B),
                  dtype=jnp.float64, coarse_enough=300),
        CG(maxiter=200, tol=1e-8))
    rhs = np.ones(2 * n * n)
    x, info = solve(rhs)
    assert info.resid < 1e-8


def test_device_mis_aggregates():
    """Device (jittable) MIS must produce a valid aggregation: every
    connected node assigned, aggregates connected through the strength
    graph, and the resulting AMG converges like the host path."""
    from amgcl_tpu.coarsening.device_mis import aggregates_on_device
    A, rhs = poisson3d(12)
    agg, n_agg = aggregates_on_device(A)
    assert (agg >= 0).all()           # no isolated rows in this fixture
    assert n_agg == agg.max() + 1
    sizes = np.bincount(agg)
    assert sizes.min() >= 1 and 4 <= A.nrows / n_agg <= 40
    # spot-check hierarchy quality through a real solve

    class DeviceAggSA(SmoothedAggregation):
        def transfer_operators(self, A, ctx=None):
            # route aggregation through the device path, keep SA smoothing
            import amgcl_tpu.coarsening.smoothed_aggregation as sa
            orig = sa.plain_aggregates
            sa.plain_aggregates = lambda M, e: aggregates_on_device(M, e)
            try:
                return super().transfer_operators(A, ctx)
            finally:
                sa.plain_aggregates = orig

    solve = make_solver(
        A, AMGParams(coarsening=DeviceAggSA(), dtype=jnp.float64,
                     coarse_enough=200),
        CG(maxiter=100, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8
    assert info.iters < 40


@pytest.mark.parametrize("aniso", [1.0, 0.1])
def test_rs_classic_vs_pmis_fidelity(aniso):
    """Classic-RS fidelity check (VERDICT r3 item 7). Measured table
    (CG + damped-Jacobi defaults, tol 1e-8, f64):

        fixture              classic   pmis
        24^3 Poisson              11     15
        32^3 Poisson              11     16
        24^3 aniso 10:1           10     14
        32^3 aniso 10:1           10     17

    PMIS needs >1.3x the reference heuristic's iterations, so 'classic'
    (the reference's sequential dynamic-measure cfsplit + exact direct
    interpolation, ruge_stuben.hpp:120-446) is the default. This test
    pins the 24^3 rows of the table (+/-2 iterations of slack)."""
    from amgcl_tpu.coarsening.ruge_stuben import RugeStuben
    A, rhs = poisson3d(24, anisotropy=aniso)
    iters = {}
    for split in ("classic", "pmis"):
        prm = AMGParams(dtype=jnp.float64,
                        coarsening=RugeStuben(splitting=split))
        solve = make_solver(A, prm, CG(maxiter=200, tol=1e-8))
        x, info = solve(rhs)
        r = np.linalg.norm(rhs - A.spmv(np.asarray(x))) / \
            np.linalg.norm(rhs)
        assert r < 1e-7, (split, r)
        iters[split] = info.iters
    assert iters["classic"] <= iters["pmis"]
    assert iters["classic"] <= 13
    assert iters["pmis"] <= 17


def test_rs_splitting_validation():
    from amgcl_tpu.coarsening.ruge_stuben import RugeStuben
    A, _ = poisson3d(6)
    with pytest.raises(ValueError, match="splitting"):
        RugeStuben(splitting="nope").transfer_operators(A)


def test_rs_classic_native_python_parity(monkeypatch):
    """The native rs_cfsplit and the Python heap fallback must produce
    the IDENTICAL C/F split (same tie-break, same lambda cap) — drift
    would change hierarchies depending on compiler availability."""
    import scipy.sparse as sp
    from amgcl_tpu.coarsening.ruge_stuben import (_strength_rs,
                                                  cf_splitting_classic)
    import amgcl_tpu.native as nat
    if nat.lib() is None:
        pytest.skip("native library unavailable")
    rng = np.random.RandomState(5)
    cases = [poisson3d(12)[0], poisson3d(10, anisotropy=0.1)[0]]
    M = sp.random(300, 300, density=0.03, random_state=rng).tocsr()
    M = M + M.T + 4.0 * sp.identity(300)   # random pattern, spd-ish
    cases.append(CSR.from_scipy(sp.csr_matrix(M)))
    for A in cases:
        strong, rows = _strength_rs(A, 0.25)
        got_native = cf_splitting_classic(A, strong, rows)
        # the fallback import happens at call time, so patching the
        # module attribute forces the Python path
        monkeypatch.setattr("amgcl_tpu.native.native_rs_cfsplit",
                            lambda *a: None)
        got_python = cf_splitting_classic(A, strong, rows)
        monkeypatch.undo()
        np.testing.assert_array_equal(got_native, got_python)


def test_tentative_qr_contract():
    """Unit test of the batched-QR tentative prolongation (the
    reference's tests/test_qr.cpp role, amgcl/detail/qr.hpp consumer):
    P has per-aggregate orthonormal columns (P^T P = I), reproduces the
    nullspace exactly (P @ Bc = B), uses the deterministic sign
    convention (diag(R) >= 0), and fails loudly on aggregates smaller
    than the nullspace dimension."""
    from amgcl_tpu.coarsening.tentative import tentative_prolongation
    rng = np.random.RandomState(3)
    n, n_agg, nvec = 60, 12, 3
    agg = np.repeat(np.arange(n_agg), n // n_agg)
    B = rng.randn(n, nvec)
    P, Bc = tentative_prolongation(n, agg, n_agg, nullspace=B)
    Ps = P.to_scipy()
    # orthonormal aggregate blocks
    G = (Ps.T @ Ps).toarray()
    np.testing.assert_allclose(G, np.eye(n_agg * nvec), atol=1e-12)
    # exact nullspace reproduction
    np.testing.assert_allclose(Ps @ Bc, B, atol=1e-12)
    # deterministic sign: the R factors have nonnegative diagonals
    R = Bc.reshape(n_agg, nvec, nvec)
    assert (np.einsum("aii->ai", R) >= 0).all()
    # rank-deficiency guard: a singleton aggregate with nvec=3
    agg_bad = agg.copy()
    agg_bad[agg_bad == 0] = 1
    agg_bad[0] = 0                      # aggregate 0 has one member
    with pytest.raises(ValueError, match="smaller than the nullspace"):
        tentative_prolongation(n, agg_bad, n_agg, nullspace=B)
