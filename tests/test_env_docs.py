"""Env-knob documentation lint, asserted through the ONE implementation
(ISSUE 6 satellite): ``analysis.lint``'s ``undocumented-knob`` rule owns
the scan — every ``AMGCL_TPU_*`` variable referenced under ``amgcl_tpu/``
must have a row in README's environment-variable table. A knob nobody
can discover is a knob that does not exist."""

from amgcl_tpu.analysis import lint


def test_every_env_var_documented():
    refs = lint.referenced_env_vars()
    assert refs, "lint is broken: no AMGCL_TPU_* references found"
    missing = lint.undocumented_knobs()
    assert not missing, (
        "env vars referenced under amgcl_tpu/ but missing from README's "
        "environment-variable table: %s" % ", ".join(missing))


def test_rule_rides_run_lint():
    """The same check fires as an `undocumented-knob` finding through
    run_lint, so `python -m amgcl_tpu.analysis` and this test can never
    disagree about what counts as documented."""
    findings = lint.run_lint(rules=["undocumented-knob"])
    assert [f["symbol"] for f in findings] == lint.undocumented_knobs()


def test_table_covers_new_knobs():
    """Knobs recent PRs added are in the table (guards against the table
    regressing while the lint above is green only by accident)."""
    documented = lint.documented_env_vars()
    for var in ("AMGCL_TPU_TELEMETRY_MAX_BYTES", "AMGCL_TPU_PEAK_GBPS",
                "AMGCL_TPU_PEAK_FLOPS", "AMGCL_TPU_COMPILE_WATCH",
                "AMGCL_TPU_ROOFLINE_REPS", "AMGCL_TPU_FUSED_VEC",
                "AMGCL_TPU_PIPELINED_CG", "AMGCL_TPU_ANALYSIS_IN_CHECK",
                "AMGCL_TPU_ANALYSIS_TIMEOUT",
                "AMGCL_TPU_SERVE_METRICS_PORT", "AMGCL_TPU_SLO_P99_MS",
                "AMGCL_TPU_SLO_TIMEOUT_RATE",
                "AMGCL_TPU_SLO_UNHEALTHY_RATE", "AMGCL_TPU_SLO_WINDOW",
                "AMGCL_TPU_COMM_REPS", "AMGCL_TPU_PEAK_ICI_GBPS",
                "AMGCL_TPU_SCALING_N", "AMGCL_TPU_SCALING_DEVICES",
                "AMGCL_TPU_SCALING_SOLVERS",
                "AMGCL_TPU_GATE_MULTICHIP",
                "AMGCL_TPU_GATE_COMM_FRAC",
                "AMGCL_TPU_FARM_MAX_BYTES", "AMGCL_TPU_FARM_QUEUE_MAX",
                "AMGCL_TPU_FARM_METRICS_PORT", "AMGCL_TPU_GATE_FARM",
                "AMGCL_TPU_FLIGHT", "AMGCL_TPU_FLIGHT_DIR",
                "AMGCL_TPU_FLIGHT_MAX_DUMPS", "AMGCL_TPU_XRAY",
                "AMGCL_TPU_XRAY_VARIANTS",
                "AMGCL_TPU_XRAY_MAX_ADVISE_NNZ",
                "AMGCL_TPU_STORM_SEED", "AMGCL_TPU_STORM_N",
                "AMGCL_TPU_STORM_DURATION_S", "AMGCL_TPU_STORM_DRAIN_S",
                "AMGCL_TPU_STORM_SLO_MS", "AMGCL_TPU_STORM_RATES",
                "AMGCL_TPU_STORM_FAULT_PLAN", "AMGCL_TPU_STORM_TRACE",
                "AMGCL_TPU_STORM_IN_CHECK", "AMGCL_TPU_STORM_TIMEOUT",
                "AMGCL_TPU_GATE_STORM", "AMGCL_TPU_GATE_STORM_P99",
                "AMGCL_TPU_GATE_STORM_CANDIDATE",
                "AMGCL_TPU_MEMWATCH", "AMGCL_TPU_MEMWATCH_INTERVAL_MS",
                "AMGCL_TPU_MEMWATCH_TIMELINE", "AMGCL_TPU_MEMWATCH_TOL",
                "AMGCL_TPU_MEMWATCH_CENSUS_MS",
                "AMGCL_TPU_MEMWATCH_IN_CHECK",
                "AMGCL_TPU_MEMWATCH_LEAK_BYTES",
                "AMGCL_TPU_MEMWATCH_TIMEOUT",
                "AMGCL_TPU_GATE_MEMDRIFT", "AMGCL_TPU_FARM_HEADROOM",
                "AMGCL_TPU_REORDER", "AMGCL_TPU_GATE_XRAY",
                "AMGCL_TPU_GATHER_KERNEL"):
        assert var in documented, var
