"""Env-knob lint (ISSUE 4 satellite): every ``AMGCL_TPU_*`` variable
referenced under ``amgcl_tpu/`` must have a row in README's environment
variable table — a knob nobody can discover is a knob that does not
exist. Fails listing the missing names."""

import os
import re

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_VAR = re.compile(r"AMGCL_TPU_[A-Z0-9_]+")
#: a documented row looks like ``| `AMGCL_TPU_X` | meaning |``
_ROW = re.compile(r"\|\s*`(AMGCL_TPU_[A-Z0-9_]+)`")


def _referenced_vars():
    refs = set()
    for root, dirs, files in os.walk(os.path.join(_REPO, "amgcl_tpu")):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for fn in files:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(root, fn)) as f:
                for match in _VAR.findall(f.read()):
                    # prose like "AMGCL_TPU_PEAK_{GBPS,FLOPS}" leaves a
                    # trailing-underscore stem — not a variable
                    refs.add(match.rstrip("_"))
    return refs


def test_every_env_var_documented():
    refs = _referenced_vars()
    assert refs, "lint is broken: no AMGCL_TPU_* references found"
    with open(os.path.join(_REPO, "README.md")) as f:
        documented = set(_ROW.findall(f.read()))
    # a stem like AMGCL_TPU_PEAK (from "AMGCL_TPU_PEAK_{GBPS,FLOPS}"
    # prose) is covered when longer documented names extend it
    missing = sorted(v for v in refs - documented
                     if not any(d.startswith(v + "_")
                                for d in documented))
    assert not missing, (
        "env vars referenced under amgcl_tpu/ but missing from README's "
        "environment-variable table: %s" % ", ".join(missing))


def test_table_covers_new_knobs():
    """The knobs this PR added are in the table (guards against the
    table regressing while the lint above is green only by accident)."""
    with open(os.path.join(_REPO, "README.md")) as f:
        documented = set(_ROW.findall(f.read()))
    for var in ("AMGCL_TPU_TELEMETRY_MAX_BYTES", "AMGCL_TPU_PEAK_GBPS",
                "AMGCL_TPU_PEAK_FLOPS", "AMGCL_TPU_COMPILE_WATCH",
                "AMGCL_TPU_ROOFLINE_REPS", "AMGCL_TPU_FUSED_VEC",
                "AMGCL_TPU_PIPELINED_CG"):
        assert var in documented, var
