"""Distributed layer on the 8-virtual-device CPU mesh (SURVEY.md §4 lesson:
multi-chip behavior is tested in CI, unlike the reference's untested MPI)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from amgcl_tpu.utils.sample_problem import poisson3d
from amgcl_tpu.parallel.mesh import make_mesh
from amgcl_tpu.parallel.dist_matrix import DistDiaMatrix
from amgcl_tpu.parallel.dist_solver import dist_cg


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) >= 8, "conftest must force 8 CPU devices"
    return make_mesh(8)


def test_dist_spmv_matches_host(mesh8):
    A, _ = poisson3d(16)  # 4096 rows, divides 8
    M = DistDiaMatrix.from_csr(A, mesh8, jnp.float64)
    x = np.random.RandomState(0).rand(A.nrows)
    from jax.sharding import NamedSharding, PartitionSpec as P
    from amgcl_tpu.parallel.compat import shard_map
    fn = shard_map(M.shard_mv, mesh=mesh8,
                   in_specs=(P(None, "rows"), P("rows")),
                   out_specs=P("rows"), check_vma=False)
    y = jax.jit(fn)(M.data, jax.device_put(
        jnp.asarray(x), NamedSharding(mesh8, P("rows"))))
    assert np.allclose(np.asarray(y), A.spmv(x))


def test_dist_cg_solves_poisson(mesh8):
    A, rhs = poisson3d(16)
    M = DistDiaMatrix.from_csr(A, mesh8, jnp.float64)
    dinv = jnp.asarray(A.diagonal(invert=True))
    x, iters, resid = dist_cg(M, mesh8, jnp.asarray(rhs), dinv=dinv,
                              maxiter=500, tol=1e-8)
    assert resid < 1e-8
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-7


def test_dist_cg_matches_serial_iteration_count(mesh8):
    """Sharding must not change the math: same iters as a 1-device mesh."""
    A, rhs = poisson3d(8)
    dinv = jnp.asarray(A.diagonal(invert=True))
    M8 = DistDiaMatrix.from_csr(A, mesh8, jnp.float64)
    _, it8, _ = dist_cg(M8, mesh8, jnp.asarray(rhs), dinv=dinv, tol=1e-8,
                        maxiter=500)
    mesh1 = make_mesh(1)
    M1 = DistDiaMatrix.from_csr(A, mesh1, jnp.float64)
    _, it1, _ = dist_cg(M1, mesh1, jnp.asarray(rhs), dinv=dinv, tol=1e-8,
                        maxiter=500)
    assert it8 == it1


def test_dist_cg_pipelined_matches_classical(mesh8):
    """ISSUE 5: the merged-reduction (Ghysels–Vanroose) CG converges to
    the same residual as the classical body on the 8-device mesh, with
    exactly ONE psum per iteration (asserted via the comm model in
    resources['comm'] — dots=1, carrying the stacked 3-vector), at a
    third of the collective count."""
    from amgcl_tpu.parallel.dist_solver import dist_cg_pipelined
    A, rhs = poisson3d(16)
    M = DistDiaMatrix.from_csr(A, mesh8, jnp.float64)
    dinv = jnp.asarray(A.diagonal(invert=True))
    ref = dist_cg(M, mesh8, jnp.asarray(rhs), dinv=dinv, maxiter=500,
                  tol=1e-8)
    out = dist_cg_pipelined(M, mesh8, jnp.asarray(rhs), dinv=dinv,
                            maxiter=500, tol=1e-8)
    assert out[2] < 1e-8
    # exact-arithmetic-equivalent recurrence: same trajectory in f64
    assert abs(out[1] - ref[1]) <= 1
    assert np.isclose(out[2], ref[2], rtol=1e-6)
    r = rhs - A.spmv(np.asarray(out[0]))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-7
    comm = out.report.resources["comm"]["per_iteration"]
    assert comm["dots"] == 1
    assert comm["elems_per_dot"] == 3
    ref_comm = ref.report.resources["comm"]["per_iteration"]
    assert ref_comm["dots"] == 3
    # one collective instead of three: a third of the allreduce msgs
    assert comm["msgs"] < ref_comm["msgs"]
    assert out.report.solver == "dist_cg_pipelined"


def test_dist_cg_pipelined_env_dispatch(mesh8, monkeypatch):
    """AMGCL_TPU_PIPELINED_CG=1 routes dist_cg through the pipelined
    body by default."""
    monkeypatch.setenv("AMGCL_TPU_PIPELINED_CG", "1")
    A, rhs = poisson3d(8)
    M = DistDiaMatrix.from_csr(A, mesh8, jnp.float64)
    out = dist_cg(M, mesh8, jnp.asarray(rhs),
                  dinv=jnp.asarray(A.diagonal(invert=True)),
                  maxiter=500, tol=1e-8)
    assert out.report.solver == "dist_cg_pipelined"
    assert out[2] < 1e-8


def test_dist_ell_spmv_matches_host(mesh8):
    from amgcl_tpu.parallel.dist_ell import build_dist_ell
    from amgcl_tpu.parallel.compat import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P
    A, _ = poisson3d(11)   # 1331 rows: not divisible by 8 -> padding path
    M = build_dist_ell(A, mesh8, jnp.float64)
    x = np.random.RandomState(1).rand(A.nrows)
    xp = np.zeros(M.shape[1])
    xp[:A.nrows] = x
    fn = shard_map(lambda m, v: m.shard_mv(v), mesh=mesh8,
                   in_specs=(M.specs(), P("rows")), out_specs=P("rows"),
                   check_vma=False)
    y = jax.jit(fn)(M, jax.device_put(
        jnp.asarray(xp), NamedSharding(mesh8, P("rows"))))
    assert np.allclose(np.asarray(y)[:A.nrows], A.spmv(x))


def test_dist_amg_solver(mesh8):
    from amgcl_tpu.parallel.dist_amg import DistAMGSolver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.cg import CG
    A, rhs = poisson3d(12)
    s = DistAMGSolver(A, mesh8, AMGParams(dtype=jnp.float64,
                                          coarse_enough=300),
                      CG(maxiter=100, tol=1e-8))
    x, info = s(rhs)
    assert info.resid < 1e-8
    r = rhs - A.spmv(x)
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-7


def test_dist_amg_matches_serial_quality(mesh8):
    """Distribution must not degrade the hierarchy: iteration counts stay
    in the serial ballpark (same host-side construction)."""
    from amgcl_tpu.parallel.dist_amg import DistAMGSolver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.models.make_solver import make_solver
    from amgcl_tpu.solver.cg import CG
    A, rhs = poisson3d(10)
    _, si = make_solver(A, AMGParams(dtype=jnp.float64, coarse_enough=200),
                        CG(maxiter=100, tol=1e-8))(rhs)
    _, di = DistAMGSolver(A, mesh8,
                          AMGParams(dtype=jnp.float64, coarse_enough=200),
                          CG(maxiter=100, tol=1e-8))(rhs)
    assert di.resid < 1e-8
    assert abs(di.iters - si.iters) <= 3


def test_subdomain_deflation(mesh8):
    from amgcl_tpu.parallel.deflation import DistDeflatedSolver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.cg import CG
    A, rhs = poisson3d(12)
    s = DistDeflatedSolver(A, mesh8,
                           AMGParams(dtype=jnp.float64, coarse_enough=300),
                           CG(maxiter=100, tol=1e-8))
    x, info = s(rhs)
    assert info.resid < 1e-8
    r = rhs - A.spmv(x)
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-7


def test_linear_deflation_vectors(mesh8):
    from amgcl_tpu.parallel.deflation import (DistDeflatedSolver,
                                              linear_deflation)
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.cg import CG
    n = 12
    A, rhs = poisson3d(n)
    g = np.arange(n, dtype=float)
    X, Y, Z = np.meshgrid(g, g, g, indexing="ij")
    coords = np.stack([X.ravel(), Y.ravel(), Z.ravel()], axis=1)
    Zd = linear_deflation(coords, 8)
    s = DistDeflatedSolver(A, mesh8,
                           AMGParams(dtype=jnp.float64, coarse_enough=300),
                           CG(maxiter=100, tol=1e-8), deflation=Zd)
    x, info = s(rhs)
    assert info.resid < 1e-8


def test_block_preconditioner_ras(mesh8):
    from amgcl_tpu.parallel.block_precond import DistBlockPreconditioner
    from amgcl_tpu.solver.cg import CG
    A, rhs = poisson3d(12)
    s = DistBlockPreconditioner(A, mesh8, CG(maxiter=500, tol=1e-8),
                                dtype=jnp.float64)
    x, info = s(rhs)
    assert info.resid < 1e-8
    r = rhs - A.spmv(x)
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-7


def test_dist_chebyshev_smoother(mesh8):
    from amgcl_tpu.parallel.dist_amg import DistAMGSolver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.relaxation.chebyshev import Chebyshev
    from amgcl_tpu.solver.cg import CG
    A, rhs = poisson3d(12)
    s = DistAMGSolver(A, mesh8,
                      AMGParams(relax=Chebyshev(), dtype=jnp.float64,
                                coarse_enough=300),
                      CG(maxiter=100, tol=1e-8))
    x, info = s(rhs)
    assert info.resid < 1e-8
    r = rhs - A.spmv(x)
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-7


def test_dist_runtime_config(mesh8):
    from amgcl_tpu.models.runtime import make_dist_solver_from_config
    A, rhs = poisson3d(12)
    for pclass in ("amg", "deflated_amg", "block"):
        s = make_dist_solver_from_config(
            A, mesh8, {"precond.class": pclass, "precond.dtype": "float64",
                       "solver.type": "cg", "solver.maxiter": 500,
                       "solver.tol": 1e-8})
        x, info = s(rhs)
        assert info.resid < 1e-8, pclass


def test_cli_mesh_flag(capsys):
    from amgcl_tpu.cli import main
    rc = main(["-n", "10", "--mesh", "4", "-p", "precond.dtype=float64",
               "-p", "solver.type=cg", "-p", "solver.tol=1e-8"])
    assert rc == 0
    cap = capsys.readouterr().out
    assert "Iterations:" in cap


def test_replicated_tail_split(mesh8):
    """Small levels run replicated (merge analogue): deep hierarchy splits,
    convergence matches the serial path."""
    from amgcl_tpu.parallel.dist_amg import DistAMGSolver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.cg import CG
    A, rhs = poisson3d(16)
    s = DistAMGSolver(A, mesh8,
                      AMGParams(dtype=jnp.float64, coarse_enough=100),
                      CG(maxiter=100, tol=1e-8), replicate_below=2000)
    assert s._split >= 1 and len(s.hier.levels) == s._split
    assert s.hier.rep.levels       # non-empty replicated tail
    x, info = s(rhs)
    assert info.resid < 1e-8
    r = rhs - A.spmv(x)
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-7


def test_fully_replicated_small_problem(mesh8):
    """Single-level hierarchy: the whole preconditioner replicates."""
    from amgcl_tpu.parallel.dist_amg import DistAMGSolver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.cg import CG
    A, rhs = poisson3d(8)   # 512 rows < coarse_enough
    s = DistAMGSolver(A, mesh8, AMGParams(dtype=jnp.float64),
                      CG(maxiter=50, tol=1e-10))
    assert s._split == 0 and not s.hier.levels
    x, info = s(rhs)
    r = rhs - A.spmv(x)
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-9


def test_fully_replicated_block_matrix(mesh8):
    """Regression: block-unit shapes truncated the gathered residual in the
    fully-replicated path."""
    from amgcl_tpu.parallel.dist_amg import DistAMGSolver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.cg import CG
    from amgcl_tpu.utils.sample_problem import poisson3d_block
    A, rhs = poisson3d_block(6, 2)   # 432 scalar rows, single level
    s = DistAMGSolver(A, mesh8, AMGParams(dtype=jnp.float64),
                      CG(maxiter=50, tol=1e-10))
    x, info = s(rhs)
    r = rhs - A.spmv(x)
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-9


def test_dist_cpr(mesh8):
    from amgcl_tpu.parallel.dist_cpr import DistCPRSolver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.bicgstab import BiCGStab
    from tests.test_coupled import reservoir_like
    A, rhs = reservoir_like(8, 3)
    s = DistCPRSolver(A, mesh8,
                      pressure_prm=AMGParams(dtype=jnp.float64,
                                             coarse_enough=100),
                      solver=BiCGStab(maxiter=200, tol=1e-8),
                      dtype=jnp.float64)
    x, info = s(rhs)
    assert info.resid < 1e-8
    r = rhs - A.spmv(x)
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-6


def test_dist_schur(mesh8):
    from amgcl_tpu.parallel.dist_schur import DistSchurSolver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.gmres import FGMRES
    from tests.test_coupled import stokes_like
    A, pmask = stokes_like(10)
    rhs = np.ones(A.nrows)
    s = DistSchurSolver(A, mesh8, pmask,
                        AMGParams(dtype=jnp.float64, coarse_enough=100),
                        AMGParams(dtype=jnp.float64, coarse_enough=100),
                        solver=FGMRES(maxiter=300, tol=1e-8),
                        dtype=jnp.float64)
    x, info = s(rhs)
    assert info.resid < 1e-8
    r = rhs - A.spmv(x)
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-6


def test_dist_lgmres(mesh8):
    """LGMRES's own Arnoldi body must also reduce basis dots globally."""
    from amgcl_tpu.parallel.dist_amg import DistAMGSolver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.lgmres import LGMRES
    A, rhs = poisson3d(12)
    s = DistAMGSolver(A, mesh8,
                      AMGParams(dtype=jnp.float64, coarse_enough=300),
                      LGMRES(M=10, K=2, maxiter=200, tol=1e-9))
    x, info = s(rhs)
    r = rhs - A.spmv(x)
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-7


_SOLVER_PARITY = [
    ("cg", dict(maxiter=200, tol=1e-8)),
    ("bicgstab", dict(maxiter=200, tol=1e-8)),
    ("bicgstabl", dict(L=2, maxiter=200, tol=1e-8)),
    ("gmres", dict(M=20, maxiter=200, tol=1e-8)),
    ("fgmres", dict(M=20, maxiter=200, tol=1e-8)),
    ("lgmres", dict(M=10, K=2, maxiter=200, tol=1e-8)),
    ("idrs", dict(s=4, maxiter=200, tol=1e-8)),
    ("richardson", dict(maxiter=300, tol=1e-8)),
    ("preonly", dict()),
]


@pytest.mark.parametrize("name,kw", _SOLVER_PARITY,
                         ids=[n for n, _ in _SOLVER_PARITY])
def test_all_solvers_distributed_parity(mesh8, name, kw):
    """Every registry solver must be seam-correct under sharding: same
    iteration count as a 1-device mesh AND a small TRUE residual (catches
    shard-local reductions that under-report the residual — the round-1
    BiCGStab(L)/IDR(s) bug class)."""
    from amgcl_tpu.parallel.dist_amg import DistAMGSolver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.models.runtime import SOLVERS
    A, rhs = poisson3d(12)
    prm = AMGParams(dtype=jnp.float64, coarse_enough=300)
    s8 = DistAMGSolver(A, mesh8, prm, SOLVERS[name](**kw))
    x8, info8 = s8(rhs)
    r8 = np.linalg.norm(rhs - A.spmv(x8)) / np.linalg.norm(rhs)
    if name == "preonly":
        # single preconditioner application: parity = identical output
        mesh1 = make_mesh(1)
        s1 = DistAMGSolver(A, mesh1, prm, SOLVERS[name](**kw))
        x1, _ = s1(rhs)
        assert np.allclose(x8, x1, rtol=1e-10, atol=1e-12)
        return
    assert r8 < 1e-6, "true residual %g (reported %g)" % (r8, info8.resid)
    mesh1 = make_mesh(1)
    s1 = DistAMGSolver(A, mesh1, prm, SOLVERS[name](**kw))
    x1, info1 = s1(rhs)
    assert info8.iters == info1.iters, (
        "distributed iteration count %d != serial %d"
        % (info8.iters, info1.iters))


@pytest.mark.parametrize("relax_name", ["ilu0", "gauss_seidel", "spai1",
                                        "ilut", "iluk"])
def test_dist_smoother_parity(mesh8, relax_name):
    """ILU/GS/SPAI1 smoother states are sharded with halo plans (not
    degraded to damped Jacobi as in round 1): distributed convergence must
    exactly match the 1-device mesh, with no fallback warning."""
    import warnings
    from amgcl_tpu.parallel.dist_amg import DistAMGSolver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.models.runtime import RELAXATION
    from amgcl_tpu.solver.cg import CG
    A, rhs = poisson3d(12)
    mk = lambda: AMGParams(dtype=jnp.float64, coarse_enough=300,
                           relax=RELAXATION[relax_name]())
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        s8 = DistAMGSolver(A, mesh8, mk(), CG(maxiter=100, tol=1e-8))
    x8, info8 = s8(rhs)
    r8 = np.linalg.norm(rhs - A.spmv(x8)) / np.linalg.norm(rhs)
    assert r8 < 1e-7
    s1 = DistAMGSolver(A, make_mesh(1), mk(), CG(maxiter=100, tol=1e-8))
    _, info1 = s1(rhs)
    assert info8.iters == info1.iters


def test_dist_unsupported_smoother_raises(mesh8):
    """No silent quality degradation: anything without a distributed form
    fails loudly (round-1 ADVICE: fallback warnings hide regressions)."""
    from amgcl_tpu.parallel.dist_amg import DistAMGSolver
    from amgcl_tpu.models.amg import AMGParams

    class OpaqueRelax:
        def build(self, A, dtype):
            return object()   # state without a shardable form

    A, _ = poisson3d(8)
    with pytest.raises(ValueError, match="no distributed form"):
        DistAMGSolver(A, mesh8,
                      AMGParams(dtype=jnp.float64, coarse_enough=100,
                                relax=OpaqueRelax()))


def test_sharded_mis_aggregates(mesh8):
    """Mesh-sharded MIS must produce the same PARTITION QUALITY contract as
    the host pass: every non-isolated row assigned, aggregates connected
    within distance 2, count in a sane band — and identical keys on a
    1-device mesh vs the 8-device mesh (sharding must not change the
    math)."""
    from amgcl_tpu.parallel.dist_mis import sharded_aggregates
    A, _ = poisson3d(12)
    agg8, n8 = sharded_aggregates(A, 0.08, mesh8)
    agg1, n1 = sharded_aggregates(A, 0.08, make_mesh(1))
    assert n8 == n1 and np.array_equal(agg8, agg1)
    assert (agg8 >= 0).all()                   # 7-pt stencil: none isolated
    assert n8 <= A.nrows // 3                  # meaningful coarsening
    sizes = np.bincount(agg8)
    assert sizes.max() <= 60                   # no runaway aggregate


def test_dist_amg_device_mis(mesh8):
    """DistAMGSolver(device_mis=True): aggregation runs sharded on the
    mesh; convergence matches the usual quality bar."""
    from amgcl_tpu.parallel.dist_amg import DistAMGSolver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.cg import CG
    A, rhs = poisson3d(12)
    s = DistAMGSolver(A, mesh8,
                      AMGParams(dtype=jnp.float64, coarse_enough=300),
                      CG(maxiter=100, tol=1e-8), device_mis=True)
    x, info = s(rhs)
    r = np.linalg.norm(rhs - A.spmv(x)) / np.linalg.norm(rhs)
    assert r < 1e-7
    assert info.iters <= 30


def test_dist_amg_device_mis_rejects_block(mesh8):
    """Block (pointwise) aggregation bypasses the aggregator hook — must
    fail loudly, not silently run the host pass."""
    from amgcl_tpu.parallel.dist_amg import DistAMGSolver
    from amgcl_tpu.models.amg import AMGParams
    from tests.test_coupled import reservoir_like
    A, _ = reservoir_like(6, 3)
    with pytest.raises(ValueError, match="device_mis does not support"):
        DistAMGSolver(A, mesh8, AMGParams(dtype=jnp.float64),
                      device_mis=True)


def test_dist_amg_min_per_shard(mesh8):
    """Mid-size level shrink (the repartition-merge analogue): identical
    math to the full spread — same iterations, same quality."""
    from amgcl_tpu.parallel.dist_amg import DistAMGSolver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.cg import CG
    A, rhs = poisson3d(16)     # 4096 rows: level 1 ~ 500 rows over 8 shards
    # replicate_below=300 keeps level 1 SHARDED (it would otherwise fall
    # into the replicated tail and the shrink would never engage)
    mk = lambda **kw: DistAMGSolver(
        A, mesh8, AMGParams(dtype=jnp.float64, coarse_enough=100),
        CG(maxiter=100, tol=1e-8), replicate_below=300, **kw)
    s_spread = mk()
    s_shrink = mk(min_per_shard=256)   # level 1 concentrates on 2 shards
    assert len(s_shrink.hier.levels) >= 2, "level 1 must stay sharded"
    lvl1_spread = s_spread.hier.levels[1].A
    lvl1_shrink = s_shrink.hier.levels[1].A
    assert lvl1_spread.nloc < 256      # even spread really is finer
    assert lvl1_shrink.nloc == 256     # ... and the shrink really engaged
    x1, i1 = s_spread(rhs)
    x2, i2 = s_shrink(rhs)
    assert i1.iters == i2.iters
    r2 = np.linalg.norm(rhs - A.spmv(x2)) / np.linalg.norm(rhs)
    assert r2 < 1e-7


def test_rep_rowshard_parity(mesh8):
    """rep_rowshard=True row-shards the finest replicated-tail level —
    identical math (scaled-residual sweeps are permutation/association
    free up to f32 drift): same iterations, same quality (VERDICT r4
    item 8 / ROADMAP 'coarse levels underutilize large meshes')."""
    from amgcl_tpu.parallel.dist_amg import DistAMGSolver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.cg import CG
    A, rhs = poisson3d(16)
    mk = lambda **kw: DistAMGSolver(
        A, mesh8, AMGParams(dtype=jnp.float64, coarse_enough=100),
        CG(maxiter=100, tol=1e-8), replicate_below=5000, **kw)
    s0 = mk()
    s1 = mk(rep_rowshard=True)
    # the tail (whole hierarchy below the finest) must actually qualify
    assert s1.hier.rep_rowshard and s1.hier._rowshard_ok()
    x0, i0 = s0(rhs)
    x1, i1 = s1(rhs)
    assert i0.iters == i1.iters
    r1 = np.linalg.norm(rhs - A.spmv(x1)) / np.linalg.norm(rhs)
    assert r1 < 1e-7
    np.testing.assert_allclose(np.asarray(x0), np.asarray(x1),
                               rtol=1e-8, atol=1e-10)


def test_dist_cpr_drs(mesh8):
    """Distributed CPR with dynamic row-sum weights (cpr_drs.hpp role):
    same weight policy as serial CPRDRS, iteration parity vs 1 device."""
    from amgcl_tpu.parallel.dist_cpr import DistCPRSolver
    from amgcl_tpu.solver.bicgstab import BiCGStab
    from tests.test_coupled import reservoir_like
    A, rhs = reservoir_like(8, 3)
    s8 = DistCPRSolver(A, mesh8, solver=BiCGStab(maxiter=200, tol=1e-8),
                       dtype=jnp.float64, weighting="drs")
    x8, i8 = s8(rhs)
    r8 = np.linalg.norm(rhs - A.spmv(x8)) / np.linalg.norm(rhs)
    assert r8 < 1e-6
    s1 = DistCPRSolver(A, make_mesh(1), solver=BiCGStab(maxiter=200,
                                                        tol=1e-8),
                       dtype=jnp.float64, weighting="drs")
    _, i1 = s1(rhs)
    assert i8.iters == i1.iters


def test_dist_amg_ruge_stuben(mesh8):
    """Classic RS coarsening through the distributed hierarchy (host
    setup, sharded solve) — coarsening policy and distribution compose."""
    from amgcl_tpu.parallel.dist_amg import DistAMGSolver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.coarsening.ruge_stuben import RugeStuben
    from amgcl_tpu.solver.cg import CG
    A, rhs = poisson3d(12)
    s = DistAMGSolver(A, mesh8,
                      AMGParams(dtype=jnp.float64, coarse_enough=300,
                                coarsening=RugeStuben()),
                      CG(maxiter=100, tol=1e-8))
    x, info = s(rhs)
    r = np.linalg.norm(rhs - A.spmv(x)) / np.linalg.norm(rhs)
    assert r < 1e-7


def test_dist_amg_complex(mesh8):
    """Complex value type through the whole distributed stack: halo ELL
    SpMVs, conjugated psum dots, replicated complex coarse solve
    (SURVEY L0 complex support x L10 distribution)."""
    from amgcl_tpu.utils.sample_problem import poisson3d_complex
    from amgcl_tpu.parallel.dist_amg import DistAMGSolver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.bicgstab import BiCGStab
    A, rhs = poisson3d_complex(10)
    # genuinely complex rhs: a real rhs would mask imaginary-discarding
    # casts in the vector padding path (round-2 bug found exactly there)
    rhs = rhs * (1.0 + 0.5j)
    s8 = DistAMGSolver(A, mesh8,
                       AMGParams(dtype=jnp.complex128, coarse_enough=200),
                       BiCGStab(maxiter=200, tol=1e-8))
    x8, info8 = s8(rhs)
    r8 = np.linalg.norm(rhs - A.spmv(x8)) / np.linalg.norm(rhs)
    assert r8 < 1e-6
    s1 = DistAMGSolver(A, make_mesh(1),
                       AMGParams(dtype=jnp.complex128, coarse_enough=200),
                       BiCGStab(maxiter=200, tol=1e-8))
    _, info1 = s1(rhs)
    assert info8.iters == info1.iters


def test_dist_cpr_runtime_config(mesh8):
    from amgcl_tpu.models.runtime import make_dist_solver_from_config
    from tests.test_coupled import reservoir_like
    A, rhs = reservoir_like(8, 3)
    s = make_dist_solver_from_config(
        A, mesh8, {"precond.class": "cpr", "precond.dtype": "float64",
                   "precond.pressure.coarse_enough": 100,
                   "precond.pressure.dtype": "float64",
                   "solver.type": "bicgstab", "solver.tol": 1e-8,
                   "solver.maxiter": 200})
    x, info = s(rhs)
    assert info.resid < 1e-8


def test_precond_dtype_mixed_precision(mesh8):
    """Distributed mixing.hpp seam: bfloat16 hierarchy internals, f32
    Krylov loop against a solver-precision system matrix — accuracy must
    reach the f32 level, not the bf16 matrix floor."""
    from amgcl_tpu.parallel.dist_amg import DistAMGSolver
    from amgcl_tpu.parallel.dist_setup import StripAMGSolver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.cg import CG
    A, rhs = poisson3d(16)
    for cls in (DistAMGSolver, StripAMGSolver):
        s = cls(A, mesh8, AMGParams(dtype=jnp.float32),
                CG(maxiter=200, tol=1e-6), precond_dtype=jnp.bfloat16)
        x, info = s(rhs)
        r = np.linalg.norm(rhs - A.spmv(np.asarray(x, np.float64))) \
            / np.linalg.norm(rhs)
        assert r < 1e-4, (cls.__name__, r)
        # the narrowed copy must not replace the Krylov operator
        import jax.numpy as _jnp
        assert _jnp.dtype(s.hier.system_A().loc_vals.dtype) == \
            _jnp.dtype(_jnp.float32)


def test_dist_pallas_wiring_parity(mesh8, monkeypatch):
    """The halo SpMV's interior product through the Pallas kernel
    (interpret hook) must match the XLA shift loop — same iterations,
    same quality — proving the overlapped-SpMV substitution is exact."""
    from amgcl_tpu.parallel.dist_amg import DistAMGSolver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.cg import CG

    A, rhs = poisson3d(16)
    prm = AMGParams(dtype=jnp.float32, coarse_enough=200)
    x0, i0 = DistAMGSolver(A, mesh8, prm, CG(maxiter=30, tol=1e-5))(rhs)

    monkeypatch.setenv("AMGCL_TPU_PALLAS_INTERPRET", "1")
    x1, i1 = DistAMGSolver(A, mesh8, prm, CG(maxiter=30, tol=1e-5))(rhs)

    assert i1.iters == i0.iters
    r = rhs - A.spmv(np.asarray(x1, dtype=np.float64))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-4
