"""Distributed observability (ISSUE 10): measured comm attribution via
comm-ablated stand-ins, per-shard imbalance, the structured multichip
scaling record, and the AMGCL_TPU_GATE_MULTICHIP gate."""

import json
import os
import subprocess
import sys
import types

import numpy as np
import pytest
import jax.numpy as jnp

from amgcl_tpu.utils.sample_problem import poisson3d
from amgcl_tpu.parallel.mesh import make_mesh
from amgcl_tpu.parallel.dist_matrix import DistDiaMatrix
from amgcl_tpu.parallel.dist_ell import build_dist_ell
from amgcl_tpu.telemetry import comm as C
from amgcl_tpu.telemetry.ledger import (DIST_CG_COLLECTIVES,
                                        COMM_STAGE_CONTRACTS)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
import bench  # noqa: E402  (repo-root module)


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(8)


@pytest.fixture(scope="module")
def dia16(mesh8):
    A, _ = poisson3d(16)                 # 4096 rows, divides 8
    return A, DistDiaMatrix.from_csr(A, mesh8, jnp.float64)


# ---------------------------------------------------------------------------
# imbalance: structural tables
# ---------------------------------------------------------------------------

def test_imbalance_unit():
    assert C.imbalance([3, 3, 3, 3])["factor"] == 1.0
    r = C.imbalance([4, 1, 1, 2])
    assert r["factor"] == pytest.approx(2.0)
    assert r["max"] == 4.0
    assert C.imbalance([])["factor"] == 1.0


def test_shard_costs_skewed_strip_partition():
    """A deliberately skewed strip partition reports its real load
    factor; the even partition of the same matrix is near-balanced, and
    both conserve total nnz."""
    A, _ = poisson3d(8)                  # 512 rows
    n = A.nrows
    even = C.shard_costs(A.ptr, C.even_bounds(n, 8))
    assert sum(r["nnz"] for r in even) == A.nnz
    assert C.imbalance([r["nnz"] for r in even])["factor"] < 1.1
    # skew: shard 0 takes half the rows, the rest split the remainder
    bounds = [0, n // 2] + [n // 2 + (n // 2) * k // 7
                            for k in range(1, 8)]
    skewed = C.shard_costs(A.ptr, bounds)
    assert sum(r["nnz"] for r in skewed) == A.nnz
    assert C.imbalance([r["nnz"] for r in skewed])["factor"] > 1.5


def test_dia_shard_table(dia16):
    A, Ad = dia16
    dist = C.dist_resources(Ad, 8)
    assert dist["format"] == "DistDiaMatrix"
    assert dist["pattern"] == "ring"
    assert dist["halo_width"] == 256     # the +-n^2 band of 16^3
    rows = dist["per_shard"]
    assert len(rows) == 8
    assert all(r["rows"] == 512 for r in rows)
    # per-shard in-range counts must sum to the whole-matrix in-range
    # count (each diagonal stores n - |offset| values inside the matrix)
    total = sum(A.nrows - abs(off) for off in Ad.offsets)
    assert sum(r["nnz"] for r in rows) == total
    # edge shards exchange one side only
    assert rows[0]["halo_elems"] == 256
    assert rows[3]["halo_elems"] == 512
    f = dist["imbalance"]["factor"]
    assert 1.0 <= f < 1.1


def test_ell_dist_resources(mesh8):
    A, _ = poisson3d(8)
    Ae = build_dist_ell(A, mesh8, jnp.float64)
    dist = C.dist_resources(Ae, 8)
    assert dist["pattern"] == "all_to_all"
    assert dist["padding_uniform"] is True
    assert dist["imbalance"]["factor"] == 1.0
    assert len(dist["per_shard"]) == 8


def test_dist_amg_ledger_skewed_partition(mesh8):
    """min_per_shard concentrates a level on fewer shards — the ledger's
    useful-work shard table must report the resulting imbalance (the
    device buffers stay padding-uniform, the nnz table does not)."""
    from amgcl_tpu.parallel.dist_amg import DistAMGSolver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.cg import CG
    A, rhs = poisson3d(12)               # 1728 rows
    s = DistAMGSolver(A, mesh8, AMGParams(coarse_enough=50),
                      CG(maxiter=5),
                      replicate_below=256, min_per_shard=432)
    led = s.resource_ledger()
    dist = led["dist"]
    lvl0 = dist["levels"][0]
    nz = [r["nnz"] for r in lvl0["per_shard"]]
    assert len(nz) == 8
    assert sum(1 for v in nz if v == 0) == 4     # concentrated on 4
    assert lvl0["imbalance"]["factor"] > 1.5
    assert dist["imbalance_factor"] >= lvl0["imbalance"]["factor"]
    assert dist["provenance"]["device_platform"] == "cpu"


# ---------------------------------------------------------------------------
# measured comm attribution
# ---------------------------------------------------------------------------

def test_measure_comm_join_invariants(dia16, mesh8):
    """The ablation pair partitions each stage by construction:
    comm_us == max(measured − ablated, 0), fraction in [0, 1], every
    measured time positive."""
    _, Ad = dia16
    rec = C.measure_comm(Ad, mesh8, reps=2)
    keys = {r["stage"] for r in rec["rows"]}
    assert keys == {"halo", "psum", "iteration"}
    for r in rec["rows"]:
        assert r["t_us"] > 0 and r["ablated_us"] > 0
        assert r["comm_us"] >= 0
        # the three fields are independently rounded to 1e-3 us
        assert r["comm_us"] == pytest.approx(
            max(r["t_us"] - r["ablated_us"], 0.0), abs=2e-3)
        assert 0.0 <= r["comm_fraction"] <= 1.0
        assert r["contract"] in COMM_STAGE_CONTRACTS


def test_comm_attribution_model_join(dia16, mesh8):
    _, Ad = dia16
    rec = C.comm_attribution(Ad, mesh8, solver="dist_cg", reps=2)
    pi = rec["per_iteration"]
    assert pi["collectives"] == DIST_CG_COLLECTIVES["dist_cg"]
    assert pi["model"]["msgs"] > 0 and pi["model"]["bytes"] > 0
    assert pi["comm_fraction"] is not None
    prov = rec["provenance"]
    assert prov["device_platform"] == "cpu"
    assert prov["platform_tag"] == "cpu-fallback"
    # the host-virtual-mesh caveat is always a finding on CPU meshes
    codes = {f["code"] for f in rec["findings"]}
    assert "comm_platform" in codes
    # formatter renders without raising
    assert "Comm attribution" in C.format_comm(rec)


def test_comm_attribution_ell_pipelined(mesh8):
    A, _ = poisson3d(8)
    Ae = build_dist_ell(A, mesh8, jnp.float64)
    rec = C.comm_attribution(Ae, mesh8, solver="dist_cg_pipelined",
                             reps=2)
    assert rec["per_iteration"]["collectives"] == \
        DIST_CG_COLLECTIVES["dist_cg_pipelined"]
    assert {r["stage"] for r in rec["stages"]} == \
        {"halo", "psum", "iteration"}


def test_measured_shard_spread(dia16, mesh8):
    _, Ad = dia16
    spread = C.measure_shard_spread(Ad, mesh8, reps=2)
    assert len(spread["per_shard_us"]) == 8
    assert all(t > 0 for t in spread["per_shard_us"])
    assert spread["spread"]["factor"] >= 1.0
    # ELL buffers are padding-uniform: no per-shard split to measure
    A, _ = poisson3d(8)
    Ae = build_dist_ell(A, mesh8, jnp.float64)
    assert C.measure_shard_spread(Ae, mesh8, reps=1) is None


def test_dist_cg_report_carries_dist(dia16, mesh8):
    from amgcl_tpu.parallel.dist_solver import dist_cg
    A, Ad = dia16
    dinv = jnp.asarray(A.diagonal(invert=True))
    out = dist_cg(Ad, mesh8, jnp.asarray(np.ones(A.nrows)), dinv=dinv,
                  maxiter=5, tol=1e-12)
    res = out.report.resources
    assert res["dist"]["imbalance"]["factor"] >= 1.0
    assert len(res["dist"]["per_shard"]) == 8
    prov = out.report.extra["provenance"]
    assert prov["device_count"] == 8
    assert prov["platform_tag"] == "cpu-fallback"


def test_diagnose_folds_comm_findings():
    from amgcl_tpu.telemetry.health import diagnose
    report = types.SimpleNamespace(health=None, resid=1e-8, iters=7,
                                   convergence_rate=0.1, extra={})
    comm_rec = {"solver": "dist_cg", "devices": 8,
                "per_iteration": {"comm_fraction": 0.9},
                "provenance": {"platform_tag": "cpu-fallback"}}
    codes = {f["code"] for f in diagnose(report, comm=comm_rec)}
    assert "comm_bound" in codes
    assert "comm_platform" in codes


# ---------------------------------------------------------------------------
# audit: measured census == contract, ablated census == 0
# ---------------------------------------------------------------------------

def test_audit_comm_stage_census(mesh8):
    from amgcl_tpu.analysis import jaxpr_audit as ja
    recs = ja.audit_comm_stages(mesh8)
    assert len(recs) == 14               # 7 contracts x (measured, ablated)
    findings = [f for r in recs for f in ja.check_comm_stages(r)]
    assert findings == []
    for r in recs:
        if r["ablated"]:
            cen = r["collectives"]
            assert all(cen[k] == 0 for k in
                       ("psum", "ppermute", "all_gather", "all_to_all"))


def test_audit_comm_negative_injection(mesh8):
    """A collective surviving in an 'ablated' stand-in must fail the
    check — both on a fabricated record and on a really-traced body."""
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P
    from amgcl_tpu.parallel.compat import shard_map
    from amgcl_tpu.parallel.mesh import ROWS_AXIS
    from amgcl_tpu.analysis import jaxpr_audit as ja

    fake = {"entry": "telemetry.comm_psum_ablated", "stage": "psum",
            "ablated": True, "devices": 8,
            "collectives": {"psum": 1, "ppermute": 0, "all_gather": 0,
                            "all_to_all": 0, "psum_elems": [1]}}
    errs = ja.check_comm_stages(fake)
    assert len(errs) == 1 and errs[0]["severity"] == "error"

    # trace an injected bad stand-in for real and run the same check
    def bad_ablated(a, b):
        return lax.psum(jnp.vdot(a, b), ROWS_AXIS)   # the poison

    fn = shard_map(bad_ablated, mesh=mesh8,
                   in_specs=(P(ROWS_AXIS), P(ROWS_AXIS)),
                   out_specs=P(), check_vma=False)
    x = jnp.ones(4096)
    jx = jax.make_jaxpr(fn)(x, x)
    rec = {"entry": "telemetry.comm_psum_ablated", "stage": "psum",
           "ablated": True, "devices": 8,
           "collectives": ja.collective_census(jx.jaxpr)}
    errs = ja.check_comm_stages(rec)
    assert len(errs) == 1
    # a measured stage whose census drifted from the contract fails too
    drifted = {"entry": "telemetry.comm_psum", "stage": "psum",
               "ablated": False, "devices": 8,
               "collectives": {"psum": 2, "ppermute": 0,
                               "all_gather": 0, "all_to_all": 0,
                               "psum_elems": [1, 1]}}
    assert len(ja.check_comm_stages(drifted)) == 1


# ---------------------------------------------------------------------------
# scaling record + multichip gate
# ---------------------------------------------------------------------------

def test_scaling_record_schema(monkeypatch):
    monkeypatch.setenv("AMGCL_TPU_COMM_REPS", "2")
    rec = bench.scaling_record(devices=[1, 2], base_n=8,
                               solvers=["dist_cg"], maxiter=10, reps=1)
    assert rec["event"] == "multichip_scaling"
    assert rec["schema"] == 2
    assert rec["provenance"]["device_platform"] == "cpu"
    assert rec["device_platform"] == "cpu"
    srec = rec["solvers"]["dist_cg"]
    assert srec["collectives"] == DIST_CG_COLLECTIVES["dist_cg"]
    assert [c["devices"] for c in srec["weak"]["cells"]] == [1, 2]
    assert srec["weak"]["cells"][1]["rows"] == \
        2 * srec["weak"]["cells"][0]["rows"]
    assert [c["rows"] for c in srec["strong"]["cells"]] == [512, 512]
    assert srec["weak"]["efficiency"] is not None
    head = rec["headline"]
    for key in ("weak_efficiency", "strong_efficiency",
                "comm_fraction", "imbalance", "devices"):
        assert key in head
    assert head["comm_fraction"] is not None
    assert rec["imbalance"]["imbalance"]["factor"] >= 1.0
    assert rec["collectives_census"]["ok"] is True


def _mk_record(weak=0.8, strong=0.5, comm=0.2, platform="cpu"):
    return {"schema": 2, "headline": {
        "weak_efficiency": weak, "strong_efficiency": strong,
        "comm_fraction": comm, "imbalance": 1.05, "devices": 8},
        "provenance": {"device_platform": platform},
        "path": "MULTICHIP_r01.json"}


def test_multichip_gate_unit(monkeypatch):
    monkeypatch.delenv("AMGCL_TPU_GATE_MULTICHIP", raising=False)
    monkeypatch.delenv("AMGCL_TPU_GATE_COMM_FRAC", raising=False)
    base = _mk_record()
    ok, checks = bench.run_multichip_gate(_mk_record(weak=0.85), base)
    assert ok
    # injected efficiency regression fails
    ok, checks = bench.run_multichip_gate(_mk_record(weak=0.4), base)
    assert not ok
    assert [c for c in checks if c["check"] == "weak_efficiency"][0][
        "status"] == "regression"
    # comm-fraction blowup fails (beyond ratio + abs slack)
    ok, checks = bench.run_multichip_gate(_mk_record(comm=0.6), base)
    assert not ok
    # platform mismatch skips every ratio instead of comparing
    ok, checks = bench.run_multichip_gate(
        _mk_record(weak=0.1, platform="tpu"), base)
    assert ok
    assert all(c["status"] == "skipped" for c in checks)
    # kill switch
    monkeypatch.setenv("AMGCL_TPU_GATE_MULTICHIP", "0")
    ok, checks = bench.run_multichip_gate(_mk_record(weak=0.01), base)
    assert ok and checks[0]["status"] == "skipped"


def test_multichip_gate_wiring(tmp_path, monkeypatch):
    """--gate/--check read the candidate from MULTICHIP_LATEST.json (or
    the env override) and the baseline from the newest structured
    MULTICHIP_r*.json; a regressed candidate flips ok to False."""
    cand = _mk_record(weak=0.3)
    p = tmp_path / "cand.json"
    p.write_text(json.dumps(cand))
    monkeypatch.setenv("AMGCL_TPU_GATE_MULTICHIP_CANDIDATE", str(p))
    monkeypatch.delenv("AMGCL_TPU_GATE_MULTICHIP", raising=False)
    monkeypatch.setattr(bench, "_multichip_baseline",
                        lambda: _mk_record(weak=0.8))
    rec = bench.multichip_gate_record()
    assert rec["ok"] is False
    assert any(c["status"] == "regression" for c in rec["checks"])
    # no candidate + no structured baseline = feature unused, no arm
    monkeypatch.setenv("AMGCL_TPU_GATE_MULTICHIP_CANDIDATE",
                       str(tmp_path / "missing.json"))
    monkeypatch.setattr(bench, "_multichip_baseline", lambda: None)
    assert bench.multichip_gate_record() is None


def test_multichip_history_mixed(tmp_path):
    from amgcl_tpu.telemetry import metrics as m
    legacy = {"n_devices": 8, "rc": 0, "ok": True, "tail": "dryrun..."}
    (tmp_path / "MULTICHIP_r01.json").write_text(json.dumps(legacy))
    (tmp_path / "MULTICHIP_r02.json").write_text(
        json.dumps(_mk_record()))
    rows = m.multichip_history(str(tmp_path))
    assert [r["round"] for r in rows] == [1, 2]
    assert rows[0]["legacy_dryrun"] is True
    trend = m.trend(rows, m.MULTICHIP_TREND_FIELDS)
    assert trend[0]["devices"] == 8          # legacy keeps the count
    assert trend[0]["weak_eff"] is None      # ... and gaps elsewhere
    assert trend[1]["weak_eff"] == 0.8
    assert "multichip" not in m.format_trend([], m.MULTICHIP_TREND_FIELDS)


def test_record_platform_reads_provenance():
    assert bench._record_platform(
        {"provenance": {"device_platform": "tpu"}}) == "tpu"
    assert bench._record_platform(
        {"device_platform": "cpu",
         "provenance": {"device_platform": "tpu"}}) == "cpu"
    assert bench._record_platform({"fallback": "cpu (...)"}) == "cpu"


def test_live_dist_gauges():
    from amgcl_tpu.telemetry.live import (LiveRegistry,
                                          publish_dist_gauges)
    reg = LiveRegistry()
    publish_dist_gauges(reg, devices=8, comm_fraction=0.25)
    assert reg.get("dist_mesh_devices") == 8.0
    assert reg.get("dist_comm_fraction") == 0.25
    text = reg.prometheus()
    assert "amgcl_tpu_dist_mesh_devices 8.0" in text
    assert "amgcl_tpu_dist_comm_fraction 0.25" in text


@pytest.mark.serial
def test_cli_dist_report_smoke(tmp_path):
    """`cli --mesh 8 --dist-report` end to end on the 8-virtual-device
    mesh: per-shard + comm tables printed, dist_report event emitted."""
    out = tmp_path / "dist.jsonl"
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               AMGCL_TPU_COMM_REPS="2")
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8"
                        ).strip()
    r = subprocess.run(
        [sys.executable, "-m", "amgcl_tpu.cli", "-n", "10",
         "--mesh", "8", "--dist-report", "--telemetry", str(out)],
        capture_output=True, text=True, timeout=900, cwd=REPO, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "Comm attribution" in r.stdout
    assert "Per-shard ledger" in r.stdout
    events = [json.loads(line) for line in out.read_text().splitlines()]
    by = {e.get("event") for e in events}
    assert "dist_report" in by
    dr = [e for e in events if e.get("event") == "dist_report"][0]
    assert dr["comm"]["per_iteration"]["collectives"] in (
        DIST_CG_COLLECTIVES["dist_cg"],
        DIST_CG_COLLECTIVES["dist_cg_pipelined"])
