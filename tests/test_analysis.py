"""Static analysis (ISSUE 6): the AST linter's rules on synthetic
fixtures, the jaxpr auditor's contracts over all nine Krylov solvers and
both distributed CG bodies, the negative-injection paths (an extra psum
and an f64->f32 downcast must each be caught), the compile-watch
entry-point drift check, and the repo's own clean bill against the
committed ANALYSIS_BASELINE.json."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

import jax
import jax.numpy as jnp
from jax import lax

from amgcl_tpu import analysis
from amgcl_tpu.analysis import jaxpr_audit as ja
from amgcl_tpu.analysis import lint
from amgcl_tpu.telemetry.ledger import (DIST_CG_COLLECTIVES,
                                        KRYLOV_FUSED_PASSES,
                                        KRYLOV_VEC_STREAMS_FUSED)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ===========================================================================
# linter: one fixture per rule
# ===========================================================================

def _lint_src(tmp_path, src, readme="| `AMGCL_TPU_DOCUMENTED` | x |\n"):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(textwrap.dedent(src))
    rd = tmp_path / "README.md"
    rd.write_text(readme)
    return lint.run_lint(root=str(pkg), readme=str(rd))


def _rules(findings):
    return sorted({f["rule"] for f in findings})


def test_lint_bare_jit_call_and_decorator(tmp_path):
    fs = _lint_src(tmp_path, """
        import jax

        @jax.jit
        def deco(x):
            return x

        def build(fn):
            return jax.jit(fn)
    """)
    hits = [f for f in fs if f["rule"] == "bare-jit"]
    assert {f["symbol"] for f in hits} == {"deco", "build"}


def test_lint_host_sync_and_np_in_loop_body(tmp_path):
    fs = _lint_src(tmp_path, """
        import numpy as np
        from jax import lax

        def solve(rhs):
            def body(st):
                x, it = st
                v = float(x)            # host sync on a tracer
                y = np.linalg.norm(x)   # numpy on a tracer
                z = x.item()            # host sync
                d = np.float32(0.5)     # allowlisted constant helper
                g = bool(self_like)     # not a self attr: flagged
                return (x + v + y + z + d + g, it + 1)

            def cond(st):
                return st[1] < 3

            return lax.while_loop(cond, body, (rhs, 0))
    """)
    assert _rules(fs) == ["host-sync-in-loop", "np-in-jit"]
    assert sum(f["rule"] == "host-sync-in-loop" for f in fs) == 3
    assert sum(f["rule"] == "np-in-jit" for f in fs) == 1
    assert all(f["symbol"] == "solve.body" for f in fs)


def test_lint_loop_hazard_ignores_trace_time_config(tmp_path):
    """float(self.tol) and np.dtype in a loop body are trace-time
    constants, not hazards."""
    fs = _lint_src(tmp_path, """
        import numpy as np
        from jax import lax

        class S:
            def solve(self, rhs):
                def body(st):
                    eps = float(self.tol)
                    dt = np.dtype(np.float32)
                    return st * eps

                def cond(st):
                    return True

                return lax.while_loop(cond, body, rhs)
    """)
    assert fs == []


def test_lint_mutable_default(tmp_path):
    fs = _lint_src(tmp_path, """
        def f(x, cache={}, names=[], opts=dict()):
            return x
    """)
    assert _rules(fs) == ["mutable-default"]
    assert len(fs) == 3


def test_lint_pallas_interpret_seam(tmp_path):
    fs = _lint_src(tmp_path, """
        from jax.experimental import pallas as pl

        def good(kernel, interpret):
            return pl.pallas_call(kernel, interpret=interpret)

        def bad(kernel):
            return pl.pallas_call(kernel)
    """)
    assert _rules(fs) == ["pallas-no-interpret"]
    assert [f["symbol"] for f in fs] == ["bad"]


def test_lint_undocumented_knob(tmp_path):
    fs = _lint_src(tmp_path, """
        import os
        A = os.environ.get("AMGCL_TPU_DOCUMENTED", "1")
        B = os.environ.get("AMGCL_TPU_MYSTERY_KNOB")
    """)
    assert _rules(fs) == ["undocumented-knob"]
    assert fs[0]["symbol"] == "AMGCL_TPU_MYSTERY_KNOB"


def test_lint_baseline_split():
    findings = [lint.finding("bare-jit", "a.py", 3, "f", "m"),
                lint.finding("bare-jit", "b.py", 9, "g", "m")]
    baseline = {"suppressions": [
        {"rule": "bare-jit", "file": "a.py", "symbol": "f",
         "reason": "probe"},
        {"rule": "bare-jit", "file": "gone.py", "symbol": "h",
         "reason": "stale"}]}
    split = lint.apply_baseline(findings, baseline)
    assert [f["file"] for f in split["new"]] == ["b.py"]
    assert [f["file"] for f in split["suppressed"]] == ["a.py"]
    assert [s["file"] for s in split["stale"]] == ["gone.py"]


def test_repo_lint_is_clean_against_committed_baseline():
    """The tree as committed has zero NEW findings and zero stale
    suppressions — the acceptance criterion `python -m amgcl_tpu.analysis
    runs clean against the committed baseline`, lint half. The
    baseline is SHARED with the concurrency analyzer (ISSUE 15), so
    the stale check runs over the union of both findings streams."""
    from amgcl_tpu.analysis import run_concurrency
    split = lint.apply_baseline(lint.run_lint() + run_concurrency(),
                                analysis.load_baseline())
    assert split["new"] == [], lint.format_findings(split["new"])
    assert split["stale"] == [], split["stale"]


def test_lint_blocking_call_under_lock(tmp_path):
    """Rule 9: the cheap lexical blocking-under-lock check for modules
    outside the declared concurrent set."""
    fs = _lint_src(tmp_path, """
        import queue
        import threading
        import time

        _LOCK = threading.Lock()
        work_queue = queue.Queue()

        def bad_sleep():
            with _LOCK:
                time.sleep(0.1)

        def bad_get(self):
            with self._state_lock:
                return self.queue.get()

        def good(self):
            with self._state_lock:
                v = self.queue.get_nowait()
            time.sleep(0.1)
            return v

        def good_wait(cond):
            with cond._lock:
                cond.wait(timeout=1.0)
    """)
    hits = [f for f in fs if f["rule"] == "blocking-call-under-lock"]
    assert {f["symbol"] for f in hits} == {"bad_sleep", "bad_get"}, fs


# ===========================================================================
# jaxpr auditor: solver contracts
# ===========================================================================

@pytest.mark.parametrize("name", sorted(KRYLOV_FUSED_PASSES))
def test_audit_solver_contracts(name):
    """Every Krylov solver's iteration body satisfies its declared
    fused-engagement contract with the tier on AND off."""
    for fused in (True, False):
        rec = ja.audit_solver(name, fused=fused)
        findings = ja.check_solver(rec)
        errors = [f for f in findings if f["severity"] == "error"]
        assert not errors, (rec, errors)
        if fused:
            assert rec["fused_passes"] == KRYLOV_FUSED_PASSES[name][0]
        else:
            assert rec["fused_passes"] == 0


def test_audit_cg_streams_match_fused_model():
    """The acceptance pin: fused CG's statically recounted per-iteration
    vector streams equal KRYLOV_VEC_STREAMS_FUSED['CG'] exactly."""
    rec = ja.audit_solver("CG", fused=True)
    assert rec["streams"] == KRYLOV_VEC_STREAMS_FUSED["CG"] == 11
    assert rec["fused_passes"] == 1
    assert rec["collectives"]["psum"] == 0
    assert rec["host_callbacks"] == []
    assert rec["casts"] == []


def test_audit_bicgstab_streams_match_fused_model():
    rec_on = ja.audit_solver("BiCGStab", fused=True)
    rec_off = ja.audit_solver("BiCGStab", fused=False)
    assert rec_on["streams"] == KRYLOV_VEC_STREAMS_FUSED["BiCGStab"] == 15
    # the composed body pays more vector traffic than the fused one
    assert rec_off["streams"] > rec_on["streams"]


def test_audit_detects_dead_fused_path():
    """AMGCL_TPU_FUSED_VEC on but kernels not engaged (Pallas gated off,
    no interpret seam) — exactly the silently-dead-fused-path scenario:
    the audit must fail the fusion contract."""
    with ja._env(AMGCL_TPU_FUSED_VEC="1", AMGCL_TPU_PALLAS="0",
                 AMGCL_TPU_PALLAS_INTERPRET=None):
        import jax as _jax
        Ad, rhs, dinv = ja._probe_problem()
        from amgcl_tpu.solver.cg import CG
        jx = _jax.make_jaxpr(
            lambda b: CG(maxiter=10).solve(Ad, ja._audit_precond(dinv),
                                           b))(rhs)
    body = ja.find_while_bodies(jx.jaxpr)[0]
    vs = ja.vector_streams(body, int(rhs.shape[0]))
    rec = {"entry": "solver.CG", "fused_env": True,
           "streams": vs["streams"], "fused_passes": vs["fused_passes"],
           "collectives": ja.collective_census(body),
           "casts": [], "host_callbacks": []}
    errors = [f for f in ja.check_solver(rec)
              if f["severity"] == "error"]
    assert vs["fused_passes"] == 0
    assert errors and any("not engaged" in f["message"] for f in errors)


def test_audit_detects_injected_downcast():
    """Negative injection: a preconditioner that round-trips the
    residual through f64 plants a vector f64->f32 downcast in the
    iteration body; the dtype pass must catch it."""
    _, _, dinv = ja._probe_problem()

    def audit_precond(r):
        return (dinv * r.astype(jnp.float64)).astype(jnp.float32)

    rec = ja.audit_solver("CG", fused=True,
                          precond=jax.jit(audit_precond))
    kinds = {c["kind"] for c in rec["casts"]}
    assert "downcast" in kinds, rec["casts"]
    errors = [f for f in ja.check_solver(rec)
              if f["severity"] == "error" and f["pass"] == "dtype"]
    assert errors, rec["casts"]


def test_audit_detects_host_callback_in_loop():
    """CG(verbose=True) debug-prints inside the loop — the host-sync
    pass must flag it (and quiet CG stays clean, asserted above)."""
    from amgcl_tpu.solver.cg import CG
    rec = ja.audit_solver("CG", fused=True,
                          solver=CG(maxiter=10, verbose=True))
    assert rec["host_callbacks"], "debug callback not detected"
    errors = [f for f in ja.check_solver(rec)
              if f["severity"] == "error" and f["pass"] == "host-sync"]
    assert errors


# ===========================================================================
# jaxpr auditor: gather-SpMV census (ops/pallas_gather.py)
# ===========================================================================

def test_audit_gather_records_clean():
    """Both gather-SpMV entries trace clean on CPU (interpret seam) and
    pass the GATHER_CONTRACTS census: no host callbacks, no
    collectives, no narrowing casts on matrix-sized values."""
    recs = ja.audit_gather()
    assert {r["entry"] for r in recs} == {"ops.gather_spmv",
                                          "ops.gather_spmv_xla"}
    for rec in recs:
        assert "skipped" not in rec, rec
        assert [f for f in ja.check_gather(rec)
                if f["severity"] == "error"] == [], rec


def test_audit_gather_detects_injected_downcast():
    """Negative injection: a gather-SpMV-shaped program that round-trips
    the values through f64 and narrows back plants a matrix-sized
    downcast; check_gather must fail the dtype pass."""
    from amgcl_tpu.ops import pallas_gather as pg
    n_tiles, tile, K = 2, 1024, 4
    n = n_tiles * tile
    starts = jnp.zeros(n_tiles, jnp.int32)
    cols = jnp.zeros((n_tiles, tile, K), jnp.int32)
    vals = jnp.ones((n_tiles, tile, K), jnp.float32)
    x = jnp.ones(n, jnp.float32)

    def poisoned(s, c, v, xv):
        y = pg.gather_spmv_xla(s, c, v.astype(jnp.float64), xv,
                               n_out=n)
        return y.astype(jnp.float32)          # the injected narrowing

    jx = jax.make_jaxpr(poisoned)(starts, cols, vals, x)
    rec = {"entry": "ops.gather_spmv_xla", "n": n,
           "collectives": ja.collective_census(jx.jaxpr),
           "casts": [c for c in ja.dtype_casts(jx.jaxpr, 1)
                     if c["elements"] >= n],
           "host_callbacks": ja.host_callbacks(jx.jaxpr)}
    errors = [f for f in ja.check_gather(rec)
              if f["severity"] == "error" and f["pass"] == "dtype"]
    assert errors, rec["casts"]


# ===========================================================================
# jaxpr auditor: distributed collective census
# ===========================================================================

def test_audit_dist_cg_collective_census():
    """Classical dist CG: exactly 3 scalar psums + one fwd/bwd halo
    ppermute pair per iteration, as DIST_CG_COLLECTIVES declares."""
    rec = ja.audit_dist_cg(pipelined=False)
    assert "skipped" not in rec, rec
    assert rec["collectives"]["psum"] == 3
    assert max(rec["collectives"]["psum_elems"]) == 1
    assert rec["collectives"]["ppermute"] == 2
    assert [f for f in ja.check_dist(rec)
            if f["severity"] == "error"] == []


def test_audit_dist_cg_pipelined_single_stacked_psum():
    """The acceptance pin: dist_cg_pipelined issues exactly ONE psum per
    iteration and it carries the stacked 3-vector."""
    rec = ja.audit_dist_cg(pipelined=True)
    assert "skipped" not in rec, rec
    assert rec["collectives"]["psum"] == 1
    assert rec["collectives"]["psum_elems"] == [3]
    assert [f for f in ja.check_dist(rec)
            if f["severity"] == "error"] == []


def test_audit_detects_extra_psum():
    """Negative injection: a pipelined-CG-shaped body with a second
    psum (the regression the contract exists for) must fail the
    census."""
    from amgcl_tpu.parallel.compat import shard_map
    from amgcl_tpu.parallel.mesh import make_mesh, ROWS_AXIS
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(len(jax.devices()))
    nd = int(mesh.shape[ROWS_AXIS])
    n = 64 * nd

    def body_shard(f):
        def cond(st):
            return st[1] < 10

        def body(st):
            x, it = st
            g = lax.psum(jnp.stack([jnp.vdot(x, x), jnp.vdot(x, f),
                                    jnp.vdot(f, f)]), ROWS_AXIS)
            extra = lax.psum(jnp.vdot(x, x), ROWS_AXIS)   # the bug
            return (x * (g[0] + extra), it + 1)

        return lax.while_loop(cond, body, (f, 0))[0]

    fn = shard_map(body_shard, mesh=mesh, in_specs=(P(ROWS_AXIS),),
                   out_specs=P(ROWS_AXIS), check_vma=False)
    jx = jax.make_jaxpr(fn)(jnp.ones(n))
    census = ja.collective_census(ja.find_while_bodies(jx.jaxpr)[0])
    assert census["psum"] == 2
    rec = {"entry": "parallel.dist_cg_pipelined", "devices": nd,
           "halo_width": 0, "collectives": census, "host_callbacks": []}
    errors = [f for f in ja.check_dist(rec) if f["severity"] == "error"]
    assert errors and any("psum" in f["message"] for f in errors)


# ===========================================================================
# make_solver program audit + entry-point drift
# ===========================================================================

def test_audit_make_solver_uniform_and_mixed():
    uni = ja.audit_make_solver(mixed=False)
    assert uni["downcasts"] == 0 and uni["upcasts"] == 0
    assert uni["host_callbacks"] == []
    mixed = ja.audit_make_solver(mixed=True)
    assert "skipped" not in mixed, mixed
    # the declared mixing seam: exactly one down + one up per apply
    assert mixed["downcasts"] == 1 and mixed["upcasts"] == 1
    for rec in (uni, mixed):
        errors = [f for f in ja.check_make_solver(rec)
                  if f["severity"] == "error"]
        assert errors == [], errors
    # donation groundwork (ROADMAP 1): contract says none today, and
    # the audit keeps the reminder finding alive
    assert uni["donation"]["donated_args"] == 0
    infos = [f for f in ja.check_make_solver(uni)
             if f["pass"] == "donation"]
    assert infos and infos[0]["severity"] == "info"


def test_watched_entry_points_match_declared():
    """ISSUE 6 small fix: compile_watch.DECLARED_ENTRY_POINTS is exactly
    the set of watched_jit(name=...) registrations in the source — the
    PR-4 docstring list can no longer drift from reality."""
    assert ja.check_entry_points() == []
    found = lint.watched_entry_points()
    assert "<dynamic>" not in found, (
        "watched_jit call sites must pass a static name= so the "
        "entry-point contract stays auditable: %r" % found["<dynamic>"])


def test_dist_comm_model_priced_from_contract():
    """dist_solver prices its SolveReport comm model from
    DIST_CG_COLLECTIVES — one declaration for model and audit."""
    assert DIST_CG_COLLECTIVES["dist_cg_pipelined"]["psums"] == 1
    assert DIST_CG_COLLECTIVES["dist_cg_pipelined"]["elems_per_psum"] == 3
    assert DIST_CG_COLLECTIVES["dist_cg"]["psums"] == 3
    import inspect
    from amgcl_tpu.parallel import dist_solver
    src = inspect.getsource(dist_solver.dist_cg)
    assert "DIST_CG_COLLECTIVES" in src


# ===========================================================================
# the gate itself
# ===========================================================================

def test_run_all_lint_only_ok():
    rec = analysis.run_all(with_audit=False)
    assert rec["ok"], rec["lint"]["new"]


def test_full_audit_ok():
    """run_audit end to end on the 8-virtual-device mesh: zero errors
    (infos — the donation reminder — are allowed)."""
    res = ja.run_audit()
    assert res["ok"], ja.format_report(res)
    assert res["errors"] == 0
    entries = {r["entry"] for r in res["records"]}
    assert "parallel.dist_cg_pipelined" in entries
    assert "make_solver._solve_fn" in entries


def test_analysis_cli_lint_only(tmp_path):
    """`python -m amgcl_tpu.analysis --no-audit` exits 0 against the
    committed baseline and FAILs (exit 1) against an empty one."""
    r = subprocess.run(
        [sys.executable, "-m", "amgcl_tpu.analysis", "--no-audit"],
        capture_output=True, text=True, timeout=300, cwd=_REPO,
        env=dict(os.environ))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ANALYSIS OK" in r.stdout
    empty = tmp_path / "empty_baseline.json"
    empty.write_text(json.dumps({"version": 1, "suppressions": []}))
    r2 = subprocess.run(
        [sys.executable, "-m", "amgcl_tpu.analysis", "--no-audit",
         "--json", "--baseline", str(empty)],
        capture_output=True, text=True, timeout=300, cwd=_REPO,
        env=dict(os.environ))
    assert r2.returncode == 1
    rec = json.loads(r2.stdout.strip().splitlines()[-1])
    assert not rec["ok"] and len(rec["lint"]["new"]) > 0
