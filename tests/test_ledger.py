"""Resource ledger (ISSUE 2): hierarchy-wide HBM accounting, the shared
dense-window budget, FLOP/byte and comm-volume models, setup-phase
profiling, the bench regression gate, and the satellite fixes
(forced TPU setup path, dense-window mixed-dtype promotion, df32
runtime residual validation)."""

import json
import os
import subprocess
import sys

import numpy as np
import scipy.sparse as sp
import jax
import jax.numpy as jnp
import pytest

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.models.make_solver import make_solver
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.telemetry.ledger import (DeviceMemoryBudget, mv_cost,
                                        cycle_cost_model,
                                        krylov_iteration_model,
                                        comm_model, allreduce_model,
                                        format_ledger, summarize_ledger,
                                        xla_cost_analysis)
from amgcl_tpu.utils.sample_problem import poisson3d, poisson3d_block

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tridiag(n=256):
    T = sp.diags([-np.ones(n - 1), 2 * np.ones(n), -np.ones(n - 1)],
                 [-1, 0, 1]).tocsr()
    return CSR.from_scipy(T)


# ---------------------------------------------------------------------------
# shared dense-window budget
# ---------------------------------------------------------------------------

def test_budget_object_semantics():
    b = DeviceMemoryBudget(100, name="t")
    assert b.try_charge(60, "a") and b.used == 60 and b.remaining() == 40
    assert not b.try_charge(41, "too big")      # refuse, never overdraw
    assert b.used == 60
    assert b.try_charge(40, "b") and b.remaining() == 0
    assert not b.try_charge(1)
    d = b.to_dict()
    assert d["used_bytes"] == 100 and d["total_bytes"] == 100
    assert [c["tag"] for c in d["charges"]] == ["a", "b"]
    json.dumps(d)


def test_dense_window_draws_from_shared_budget():
    """Two conversions against one budget: the second declines once the
    pool cannot cover it — the per-matrix env cap no longer stacks."""
    from amgcl_tpu.ops.densewin import csr_to_dense_window
    A = _tridiag()
    D0 = csr_to_dense_window(A, jnp.float32)
    assert D0 is not None
    need = int(D0.blocks.size) * 4
    b = DeviceMemoryBudget(need + need // 2)
    D1 = csr_to_dense_window(A, jnp.float32, budget=b)
    assert D1 is not None and b.used == need
    # pool cannot cover a second full conversion
    assert csr_to_dense_window(A, jnp.float32, budget=b) is None
    assert b.used == need                        # no partial charge


def test_to_device_dwin_respects_budget():
    from amgcl_tpu.ops import device as dev
    A = _tridiag()
    D = dev.to_device(A, "dwin", jnp.float32)
    b = DeviceMemoryBudget(int(D.blocks.size) * 4)
    assert dev.to_device(A, "dwin", jnp.float32, budget=b) is not None
    with pytest.raises(ValueError, match="budget"):
        dev.to_device(A, "dwin", jnp.float32, budget=b)


def test_hierarchy_build_shares_one_budget(monkeypatch):
    """Every to_device call of one AMG build receives the SAME budget
    object (the hierarchy-wide pool), including the coarse level."""
    from amgcl_tpu.ops import device as dev
    seen = []
    orig = dev.to_device

    def spy(A, fmt="auto", dtype=jnp.float32, **kw):
        seen.append(kw.get("budget"))
        return orig(A, fmt, dtype, **kw)

    monkeypatch.setattr(dev, "to_device", spy)
    A, _ = poisson3d(10)
    amg = AMG(A, AMGParams(dtype=jnp.float64, coarse_enough=200))
    budgets = [b for b in seen if b is not None]
    assert len(budgets) >= 2
    assert all(b is budgets[0] for b in budgets)
    assert budgets[0] is amg._dwin_budget
    # the Krylov-side copy and a rebuild() draw from the same pool too
    solve = make_solver(A, AMGParams(dtype=jnp.float64, coarse_enough=200),
                        CG(), matrix_format="dia")
    seen.clear()
    solve.rebuild(A)
    budgets = [b for b in seen if b is not None]
    assert budgets and all(b is solve.precond._dwin_budget
                           for b in budgets)


# ---------------------------------------------------------------------------
# hierarchy ledger invariants
# ---------------------------------------------------------------------------

def test_ledger_totals_match_live_bytes_scalar():
    """Ledger totals are DEFINED as the leaf-byte sum of the hierarchy
    pytree — they must equal AMG.bytes() exactly."""
    A, _ = poisson3d(12)
    amg = AMG(A, AMGParams(dtype=jnp.float64, coarse_enough=200))
    led = amg.resource_ledger()
    assert led["totals"]["bytes"] == amg.bytes()
    per_level = sum(lv["bytes"]["total"] for lv in led["levels"])
    assert per_level + led["coarse_solver_bytes"] == amg.bytes()
    # by-format operator classification covers the operator total
    ops = sum(v for k, v in led["totals"]["by_format"].items()
              if not k.startswith("transfer/"))
    assert ops == led["totals"]["operator"]
    json.dumps(led)                         # JSONL-sink clean
    assert "Resource ledger" in format_ledger(led)
    s = summarize_ledger(led)
    assert s["hierarchy_bytes"] == amg.bytes()
    assert s["cycle_flops"] > 0 and s["cycle_bytes"] > 0


def test_ledger_totals_match_live_bytes_block():
    A, _ = poisson3d_block(6, 3)
    amg = AMG(A, AMGParams(dtype=jnp.float64, coarse_enough=100))
    led = amg.resource_ledger()
    assert led["totals"]["bytes"] == amg.bytes()
    assert led["levels"][0]["format"] in ("EllMatrix", "WindowedEllMatrix")
    assert led["levels"][0]["spmv"]["flops"] > 0


def test_hierarchy_stats_carries_ledger_fields():
    A, _ = poisson3d(12)
    amg = AMG(A, AMGParams(dtype=jnp.float64, coarse_enough=200))
    st = amg.hierarchy_stats()
    lv0 = st["levels"][0]
    assert lv0["bytes"]["operator"] > 0
    assert lv0["spmv"]["flops"] > 0 and lv0["spmv"]["bytes"] > 0
    assert st["cycle"]["flops"] > 0 and st["cycle"]["bytes"] > 0
    assert 0 < st["cycle"]["flop_per_byte"] < 10
    json.dumps(st)


def test_setup_profile_covers_build_phases():
    """ISSUE 2 tentpole (d): the setup phase is profiled — coarsening,
    galerkin, device transfer, smoother setup, coarse solver."""
    A, _ = poisson3d(12)
    amg = AMG(A, AMGParams(dtype=jnp.float64, coarse_enough=200))
    scopes = amg.setup_profile.to_dict()["scopes"]
    names = set(scopes)
    assert "level0/coarsening" in names
    assert "level0/galerkin" in names
    assert "level0/transfer" in names
    assert "level0/relax_setup" in names
    assert "coarse_solver" in names
    assert all(v["total_s"] >= 0 for v in scopes.values())
    led = amg.resource_ledger()
    assert "level0/coarsening" in led["setup"]["scopes"]


def test_cycle_model_against_xla_cost_analysis():
    """The analytic cycle FLOPs cross-check against XLA's own compiled
    cost analysis (where exposed): same order of magnitude."""
    A, _ = poisson3d(12)
    amg = AMG(A, AMGParams(dtype=jnp.float64, coarse_enough=200))
    hier = amg.hierarchy
    r0 = jnp.zeros(hier.system_matrix.shape[0], jnp.float64)
    xc = xla_cost_analysis(lambda r: hier.apply(r), r0)
    if xc is None or not xc.get("flops"):
        pytest.skip("backend exposes no cost analysis")
    model = cycle_cost_model(hier)["total"]["flops"]
    assert 0.2 < model / xc["flops"] < 5.0


def test_solve_report_resources():
    A, rhs = poisson3d(12)
    solve = make_solver(A, AMGParams(dtype=jnp.float64, coarse_enough=200),
                        CG(maxiter=100, tol=1e-8))
    x, info = solve(rhs)
    res = info.resources
    assert res["memory"]["bytes"] == solve.precond.bytes()
    assert res["per_iteration"]["flops"] > 0
    assert res["per_iteration"]["solver"] == "CG"
    assert res["cycle"]["total"]["bytes"] > 0
    rec = json.loads(info.to_json())
    assert rec["resources"]["memory"]["bytes"] == res["memory"]["bytes"]
    # second call reuses the cached ledger (same object)
    x, info2 = solve(rhs)
    assert info2.resources is res


def test_mv_cost_formats():
    from amgcl_tpu.ops import device as dev
    A = _tridiag()
    dia = dev.csr_to_dia(A, jnp.float32)
    c = mv_cost(dia)
    assert c["flops"] == 2 * 3 * 256
    ell = dev.csr_to_ell(A, jnp.float32)
    assert mv_cost(ell)["flops"] == 2 * ell.vals.size
    dense = dev.DenseMatrix(jnp.zeros((8, 8), jnp.float32))
    assert mv_cost(dense) == {"flops": 128, "bytes": 256 + 64}
    assert mv_cost(None) == {"flops": 0, "bytes": 0}


def test_krylov_iteration_model_includes_precond():
    from amgcl_tpu.ops import device as dev
    dia = dev.csr_to_dia(_tridiag(), jnp.float32)
    base = krylov_iteration_model("CG", dia)
    with_pc = krylov_iteration_model("CG", dia,
                                     {"flops": 1000, "bytes": 5000})
    assert with_pc["flops"] == base["flops"] + 1000
    assert with_pc["bytes"] == base["bytes"] + 5000


# ---------------------------------------------------------------------------
# distributed comm accounting
# ---------------------------------------------------------------------------

def test_dist_dia_comm_scales_with_partitions():
    """Halo wire bytes grow with the shard count: 2(nd-1) edge messages
    of halo_width values."""
    from amgcl_tpu.parallel.mesh import make_mesh
    from amgcl_tpu.parallel.dist_matrix import DistDiaMatrix
    from amgcl_tpu.parallel.dist_solver import dist_cg
    A, rhs = poisson3d(8)
    per_iter = {}
    for nd in (2, 4):
        mesh = make_mesh(nd)
        M = DistDiaMatrix.from_csr(A, mesh, jnp.float64)
        c = comm_model(M, nd)
        assert c["pattern"] == "ring"
        assert c["msgs"] == 2 * (nd - 1)
        assert c["bytes"] == 2 * (nd - 1) * M.halo * 8
        out = dist_cg(M, mesh, jnp.asarray(rhs), maxiter=50, tol=1e-8)
        res = out.report.resources["comm"]
        assert res["per_spmv"] == c
        per_iter[nd] = res["per_iteration"]["bytes"]
    assert per_iter[4] > per_iter[2]


def test_dist_amg_resources():
    from amgcl_tpu.parallel.mesh import make_mesh
    from amgcl_tpu.parallel.dist_amg import DistAMGSolver
    A, rhs = poisson3d(8)
    s = DistAMGSolver(A, make_mesh(4),
                      AMGParams(dtype=jnp.float64, coarse_enough=200))
    x, info = s(rhs)
    comm = info.resources["comm"]
    assert comm["devices"] == 4
    assert comm["per_cycle"]["bytes"] > 0
    assert comm["per_iteration"]["bytes"] >= comm["per_cycle"]["bytes"]
    assert info.resources["memory"]["sharded_bytes"] > 0
    assert info.resources["memory"]["replicated_bytes"] > 0
    json.loads(info.to_json())


def test_dist_ell_comm_model():
    from amgcl_tpu.parallel.mesh import make_mesh
    from amgcl_tpu.parallel.dist_ell import build_dist_ell
    A, _ = poisson3d(8)
    nd = 4
    M = build_dist_ell(A, make_mesh(nd), jnp.float64)
    c = comm_model(M, nd)
    assert c["pattern"] == "all_to_all"
    assert c["msgs"] == nd * (nd - 1)
    assert c["bytes"] == nd * (nd - 1) * M.send_idx.shape[-1] * 8
    assert allreduce_model(1, 10, 8) == {"msgs": 0, "bytes": 0}
    assert allreduce_model(4, 4, 8)["msgs"] == 6


# ---------------------------------------------------------------------------
# bench regression gate
# ---------------------------------------------------------------------------

def _bench():
    sys.path.insert(0, _REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    return bench


def test_gate_pass_fail_unit(monkeypatch):
    bench = _bench()
    lg = {"value": 1.0, "iters": 10, "ledger": {"hierarchy_bytes": 1000},
          "health": {"ok": True, "flags": []}}
    ok, checks = bench.run_gate(dict(lg), lg)
    assert ok and all(c["status"] == "ok" for c in checks)
    for key, bad in [("value", 2.0), ("iters", 20),
                     ("ledger", {"hierarchy_bytes": 2000})]:
        cand = dict(lg, **{key: bad})
        ok, checks = bench.run_gate(cand, lg)
        assert not ok, key
        assert sum(c["status"] == "regression" for c in checks) == 1
    # tolerances are env-tunable (AMGCL_TPU_GATE_*)
    monkeypatch.setenv("AMGCL_TPU_GATE_TIME", "3.0")
    ok, _ = bench.run_gate(dict(lg, value=2.0), lg)
    assert ok
    # a pre-ledger baseline skips the byte check instead of failing
    old = {"value": 1.0, "iters": 10}
    ok, checks = bench.run_gate(dict(lg), old)
    assert ok
    assert [c for c in checks if c["check"] == "ledger_bytes"][0][
        "status"] == "skipped"
    # hierarchy-stats bytes serve as the fallback source
    assert bench._record_ledger_bytes(
        {"hierarchy": {"bytes": 7}}) == 7


def test_gate_subprocess_roundtrip(tmp_path):
    """bench.py --gate exits 0 on the last-good run and nonzero on an
    injected time regression (acceptance criterion)."""
    lg = {"metric": "m", "value": 1.0, "iters": 10, "unit": "s",
          "ledger": {"hierarchy_bytes": 1000}}
    lg_path = tmp_path / "last_good.json"
    lg_path.write_text(json.dumps(lg))
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(dict(lg, value=5.0)))
    env = dict(os.environ, AMGCL_TPU_GATE_LAST_GOOD=str(lg_path))

    def run(*args):
        return subprocess.run(
            [sys.executable, "bench.py", "--gate", *args],
            capture_output=True, text=True, timeout=120, cwd=_REPO,
            env=env)

    r = run()                                   # self vs self
    assert r.returncode == 0, r.stdout + r.stderr
    rec = json.loads(r.stdout.splitlines()[-1])
    assert rec["event"] == "bench_gate" and rec["ok"]
    r = run(str(bad))
    assert r.returncode == 1
    rec = json.loads(r.stdout.splitlines()[-1])
    assert not rec["ok"]
    assert any(c["status"] == "regression" for c in rec["checks"])
    r = run(str(tmp_path / "missing.json"))
    assert r.returncode == 2


def test_gate_rides_check_record(monkeypatch, tmp_path):
    """--check embeds the gate outcome and fails on a gate regression
    (CI gets the gate for free)."""
    bench = _bench()
    lg = {"value": 1.0, "iters": 10}
    lg_path = tmp_path / "lg.json"
    lg_path.write_text(json.dumps(lg))
    cand = tmp_path / "cand.json"
    cand.write_text(json.dumps(dict(lg, iters=50)))
    monkeypatch.setenv("AMGCL_TPU_GATE_LAST_GOOD", str(lg_path))
    monkeypatch.setenv("AMGCL_TPU_GATE_CANDIDATE", str(cand))
    # this test fakes subprocess.run for the pytest leg, which would
    # also feed garbage to the static-analysis subprocess (ISSUE 6),
    # the flight self-replay subprocess (ISSUE 12), the chaos-matrix
    # subprocess (ISSUE 13) and the storm smoke (ISSUE 16) — opt those
    # gates out here; test_telemetry's bench-check test covers the
    # analysis record, test_flight the replay roundtrip, test_faults
    # the chaos contract, test_storm the storm smoke and
    # test_memwatch the leak-cycle selftest end to end
    monkeypatch.setenv("AMGCL_TPU_ANALYSIS_IN_CHECK", "0")
    monkeypatch.setenv("AMGCL_TPU_FLIGHT", "0")
    monkeypatch.setenv("AMGCL_TPU_GATE_RECOVERY", "0")
    monkeypatch.setenv("AMGCL_TPU_STORM_IN_CHECK", "0")
    monkeypatch.setenv("AMGCL_TPU_MEMWATCH_IN_CHECK", "0")
    recs = []
    monkeypatch.setattr(bench._stdout_sink, "emit",
                        lambda rec=None, **kw: recs.append(dict(rec or {})))
    monkeypatch.setattr(bench, "_TIER1_ARGS", ["-c", "pass"])

    class _R:
        returncode, stdout, stderr = 0, ". [100%]\n", ""

    monkeypatch.setattr(bench.subprocess, "run",
                        lambda *a, **k: _R())
    rc = bench.main_check(["ignored"])
    assert rc == 1                       # pytest passed, gate regressed
    assert recs[-1]["gate"]["ok"] is False
    cand.write_text(json.dumps(lg))      # clean candidate
    rc = bench.main_check(["ignored"])
    assert rc == 0 and recs[-1]["gate"]["ok"] is True
    # an unreadable EXPLICIT candidate fails even with no baseline
    monkeypatch.setenv("AMGCL_TPU_GATE_LAST_GOOD",
                       str(tmp_path / "missing.json"))
    monkeypatch.setenv("AMGCL_TPU_GATE_CANDIDATE",
                       str(tmp_path / "typo.json"))
    rc = bench.main_check(["ignored"])
    assert rc == 1
    assert recs[-1]["gate"]["status"] == "unreadable_candidate"
    # ... while a plain missing baseline is a vacuous pass
    monkeypatch.delenv("AMGCL_TPU_GATE_CANDIDATE")
    rc = bench.main_check(["ignored"])
    assert rc == 0 and recs[-1]["gate"]["status"] == "no_baseline"


# ---------------------------------------------------------------------------
# satellites
# ---------------------------------------------------------------------------

def test_forced_tpu_setup_path_matches_scan(monkeypatch):
    """AMGCL_TPU_FORCE_TPU_SETUP_PATH=1 exercises the TPU-only unrolled
    _fnma_scan / static-collapse branches on CPU and reproduces the scan
    branch bit-for-bit."""
    from amgcl_tpu.ops import stencil_device as sdev
    monkeypatch.setenv("AMGCL_TPU_DEVICE_SETUP", "1")
    A, _ = poisson3d(8)
    prm = lambda: AMGParams(dtype=jnp.float32, coarse_enough=200)  # noqa
    amg1 = AMG(A, prm())
    assert amg1._device_built
    ref = [np.asarray(lv.A.data) for lv in amg1.hierarchy.levels]
    # the branch choice is baked in at trace time: clear the jit cache
    # so the forced build really re-traces (see tpu_setup_path docstring)
    sdev._level_setup.clear_cache()
    monkeypatch.setenv("AMGCL_TPU_FORCE_TPU_SETUP_PATH", "1")
    assert sdev.tpu_setup_path()
    amg2 = AMG(A, prm())
    assert amg2._device_built
    got = [np.asarray(lv.A.data) for lv in amg2.hierarchy.levels]
    monkeypatch.delenv("AMGCL_TPU_FORCE_TPU_SETUP_PATH")
    sdev._level_setup.clear_cache()
    assert len(ref) == len(got) >= 2
    for a, b in zip(ref, got):
        np.testing.assert_allclose(a, b, rtol=0, atol=1e-6)


def test_densewin_mixed_dtype_promotes():
    """f64 x against f32 blocks computes at f64 (previously silently
    demoted to the block dtype), in the XLA fallback and the
    interpret-mode kernel alike."""
    from amgcl_tpu.ops.densewin import csr_to_dense_window, \
        dense_window_spmv, dense_window_residual
    A = _tridiag()
    D = csr_to_dense_window(A, jnp.float32)
    x = np.random.RandomState(0).rand(256)
    y = D.mv(jnp.asarray(x, jnp.float64))
    assert y.dtype == jnp.float64
    dense = np.zeros((256, 256))
    rows = A.expanded_rows()
    dense[rows, A.col] = A.val
    ref = dense.astype(np.float32).astype(np.float64) @ x
    np.testing.assert_allclose(np.asarray(y), ref, atol=1e-12)
    # interpret-mode kernels: bf16 blocks x f32 vectors -> f32 compute
    Db = csr_to_dense_window(A, jnp.bfloat16)
    x32 = jnp.asarray(x, jnp.float32)
    y2 = dense_window_spmv(Db.window_starts, Db.blocks, x32,
                           Db.win, Db.shape[0], interpret=True)
    assert y2.dtype == jnp.float32
    ref16 = np.asarray(Db.blocks, np.float64).reshape(4, 64, Db.win)
    f = jnp.asarray(np.random.RandomState(1).rand(256), jnp.float32)
    r = dense_window_residual(Db.window_starts, Db.blocks, f, x32,
                              Db.win, Db.shape[0], interpret=True)
    assert r.dtype == jnp.float32
    # promoted accumulate: within f32 roundoff of the exact bf16-valued
    # product (a bf16 accumulate would be ~1e-2 off)
    xpad = np.zeros(max(int(Db.window_starts[t]) + Db.win
                        for t in range(4)) + 1)
    xpad[:256] = x
    exact = np.stack([
        ref16[t] @ xpad[int(Db.window_starts[t]):
                        int(Db.window_starts[t]) + Db.win]
        for t in range(4)]).reshape(-1)[:256]
    np.testing.assert_allclose(np.asarray(y2), exact, atol=1e-4)


def test_df32_runtime_residual_validation():
    """The first compiled df32 solve validates its reported residual
    against a host f64 residual; harmful drift (reported converged,
    true residual above target) warns."""
    import warnings as _w
    A, rhs = poisson3d(10)
    s = make_solver(A, AMGParams(dtype=jnp.float32, coarse_enough=200),
                    CG(maxiter=100, tol=1e-6), refine=2,
                    refine_dtype="df32")
    with _w.catch_warnings():
        _w.simplefilter("error")
        x, info = s(rhs)                 # healthy solve: no warning
    rhs32 = jnp.asarray(rhs, jnp.float32)
    actual = s._check_df32_runtime(rhs32, x, float(info.resid))
    assert actual == pytest.approx(float(info.resid), rel=1e-2)
    # harmful drift: claimed 1e-15 while the true residual misses a
    # 1e-12 target by orders of magnitude
    s.solver.tol = 1e-12
    with pytest.warns(UserWarning, match="df32 refinement drift"):
        s._check_df32_runtime(rhs32, x, 1e-15)
