"""Fused vector-algebra tier (ISSUE 5): agreement of the compound
primitives (Pallas kernels and XLA fallback) with the plain composition
across dtypes and awkward lengths, seam behavior (plain / psum-marked /
opaque inner products), health-guard parity with the tier on and off,
the fused spmv_dots psum acceptance, and the pipelined-CG comm model."""

import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from amgcl_tpu.ops import device as dev
from amgcl_tpu.ops import fused_vec as fv
from amgcl_tpu.ops.csr import CSR

_LENS = [0, 1, 5, 1000, 8195]      # incl. odd / non-tile-aligned / empty


def _vecs(n, dtype, k, seed=0):
    rng = np.random.RandomState(seed)
    return tuple(jnp.asarray(rng.standard_normal(n), dtype)
                 for _ in range(k))


def _tol(dtype):
    return dict(rtol=2e-5, atol=1e-5) if jnp.dtype(dtype) == jnp.float32 \
        else dict(rtol=1e-12, atol=1e-12)


# -- agreement: fused (kernel where it applies) vs plain composition --------

@pytest.mark.parametrize("dtype", [jnp.float32, jnp.float64],
                         ids=["f32", "f64"])
@pytest.mark.parametrize("n", _LENS)
@pytest.mark.parametrize("kernels", [False, True],
                         ids=["xla", "pallas-interpret"])
def test_axpby_dot_agrees(monkeypatch, dtype, n, kernels):
    if kernels:
        monkeypatch.setenv("AMGCL_TPU_PALLAS_INTERPRET", "1")
    x, y = _vecs(n, dtype, 2)
    z, zz = fv.axpby_dot(0.3, x, -1.2, y)
    ref = 0.3 * x - 1.2 * y
    np.testing.assert_allclose(np.asarray(z), np.asarray(ref), **_tol(dtype))
    np.testing.assert_allclose(float(zz), float(jnp.vdot(ref, ref)),
                               **_tol(dtype))


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.float64],
                         ids=["f32", "f64"])
@pytest.mark.parametrize("n", _LENS)
@pytest.mark.parametrize("kernels", [False, True],
                         ids=["xla", "pallas-interpret"])
def test_xr_update_agrees(monkeypatch, dtype, n, kernels):
    if kernels:
        monkeypatch.setenv("AMGCL_TPU_PALLAS_INTERPRET", "1")
    p, q, x, r = _vecs(n, dtype, 4)
    xn, rn, rr = fv.xr_update(0.7, p, q, x, r)
    xr, rr_ref = x + 0.7 * p, r - 0.7 * q
    np.testing.assert_allclose(np.asarray(xn), np.asarray(xr),
                               **_tol(dtype))
    np.testing.assert_allclose(np.asarray(rn), np.asarray(rr_ref),
                               **_tol(dtype))
    np.testing.assert_allclose(float(rr), float(jnp.vdot(rr_ref, rr_ref)),
                               **_tol(dtype))


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.float64],
                         ids=["f32", "f64"])
@pytest.mark.parametrize("n", _LENS)
@pytest.mark.parametrize("kernels", [False, True],
                         ids=["xla", "pallas-interpret"])
def test_bicgstab_tail_agrees(monkeypatch, dtype, n, kernels):
    if kernels:
        monkeypatch.setenv("AMGCL_TPU_PALLAS_INTERPRET", "1")
    ph, sh, s, t, x, rhat = _vecs(n, dtype, 6)
    xn, rn, rr, rhr = fv.bicgstab_tail(0.4, ph, 0.2, sh, s, t, x, rhat)
    x_ref = x + 0.4 * ph + 0.2 * sh
    r_ref = s - 0.2 * t
    np.testing.assert_allclose(np.asarray(xn), np.asarray(x_ref),
                               **_tol(dtype))
    np.testing.assert_allclose(np.asarray(rn), np.asarray(r_ref),
                               **_tol(dtype))
    np.testing.assert_allclose(float(rr), float(jnp.vdot(r_ref, r_ref)),
                               **_tol(dtype))
    np.testing.assert_allclose(float(rhr), float(jnp.vdot(rhat, r_ref)),
                               **_tol(dtype))


@pytest.mark.parametrize("n", [0, 5, 1000])
def test_multi_stack_block_dots_agree(n):
    x, y, z = _vecs(n, jnp.float64, 3)
    d1, d2 = fv.multi_dot(x, (x, y))
    assert np.allclose(float(d1), float(jnp.vdot(x, x)))
    assert np.allclose(float(d2), float(jnp.vdot(x, y)))
    V = jnp.stack([x, y, z]) if n else jnp.zeros((3, 0))
    sd = fv.stack_dots(V, y)
    ref = np.array([float(jnp.vdot(v, y)) for v in V])
    np.testing.assert_allclose(np.asarray(sd), ref, rtol=1e-12, atol=1e-12)
    B = fv.block_dots(V, V)
    refB = np.array([[float(jnp.vdot(a, b)) for b in V] for a in V])
    np.testing.assert_allclose(np.asarray(B), refB, rtol=1e-12,
                               atol=1e-12)


@pytest.mark.parametrize("kernels", [False, True],
                         ids=["xla", "pallas-interpret"])
def test_residual_dot_agrees(monkeypatch, kernels):
    import scipy.sparse as sp
    if kernels:
        monkeypatch.setenv("AMGCL_TPU_PALLAS_INTERPRET", "1")
    n = 100
    L = sp.diags([-np.ones(n - 1), 2.05 * np.ones(n), -np.ones(n - 1)],
                 [-1, 0, 1]).tocsr()
    f, x = _vecs(n, jnp.float32, 2)
    for fmt in ("dia", "ell"):
        A = dev.to_device(CSR.from_scipy(L), fmt, jnp.float32)
        r, rr = fv.residual_dot(f, A, x)
        r_ref = dev.residual(f, A, x)
        np.testing.assert_allclose(np.asarray(r), np.asarray(r_ref),
                                   rtol=2e-5, atol=1e-5)
        np.testing.assert_allclose(
            float(rr), float(jnp.vdot(r_ref, r_ref)), rtol=2e-5,
            atol=1e-5)


def test_opt_out_restores_composition(monkeypatch):
    """AMGCL_TPU_FUSED_VEC=0: no kernel runs even under the interpret
    hook, and the results are the plain composition's bit-for-bit."""
    monkeypatch.setenv("AMGCL_TPU_PALLAS_INTERPRET", "1")
    monkeypatch.setenv("AMGCL_TPU_FUSED_VEC", "0")
    assert not fv.fused_vec_enabled()
    assert fv._pallas_mode(jnp.zeros(8, jnp.float32)) is None
    p, q, x, r = _vecs(1000, jnp.float32, 4)
    xn, rn, rr = fv.xr_update(0.7, p, q, x, r)
    assert np.array_equal(np.asarray(xn),
                          np.asarray(dev.axpby(0.7, p, 1.0, x)))
    assert np.array_equal(np.asarray(rn),
                          np.asarray(dev.axpby(-0.7, q, 1.0, r)))
    assert float(rr) == float(jnp.vdot(rn, rn))


# -- df32 pairs: the primitives stay usable on the refinement's hi/lo legs --

def test_df32_pair_through_fused_ops():
    """Applying the (linear) fused update to the hi and lo legs of a
    df32 pair recombines to the f64 result at f32-grade accuracy — and
    strictly better than dropping the lo leg — so the fused tier
    composes with the double-float refinement (ops/dfloat.py)."""
    from amgcl_tpu.ops.dfloat import df_decompose
    rng = np.random.RandomState(3)
    a64 = rng.standard_normal(4097) * (1 + rng.rand(4097) * 1e-3)
    b64 = rng.standard_normal(4097)
    xhi, xlo = df_decompose(a64)
    yhi, ylo = df_decompose(b64)
    zhi, _ = fv.axpby_dot(0.3, jnp.asarray(xhi), -1.2, jnp.asarray(yhi))
    zlo, _ = fv.axpby_dot(0.3, jnp.asarray(xlo), -1.2, jnp.asarray(ylo))
    z64 = 0.3 * a64 - 1.2 * b64
    got = np.asarray(zhi, np.float64) + np.asarray(zlo, np.float64)
    err_pair = np.linalg.norm(got - z64) / np.linalg.norm(z64)
    err_hi = np.linalg.norm(np.asarray(zhi, np.float64) - z64) \
        / np.linalg.norm(z64)
    assert err_pair < 1e-6
    assert err_pair <= err_hi
    # the pair dot: <x, y> from the cross terms of one multi_dot read
    d_hh, d_hl = fv.multi_dot(jnp.asarray(xhi, jnp.float64),
                              (jnp.asarray(yhi, jnp.float64),
                               jnp.asarray(ylo, jnp.float64)))
    (d_lh,) = fv.multi_dot(jnp.asarray(xlo, jnp.float64),
                           (jnp.asarray(yhi, jnp.float64),))
    ref = float(np.vdot(a64, b64))
    assert abs(float(d_hh + d_hl + d_lh) - ref) < 1e-6 * abs(ref) + 1e-9


# -- inner-product seams ----------------------------------------------------

def test_opaque_seam_composes_through_ip():
    """A custom (unmarked) inner product must be called — never bypassed
    by a kernel — so custom seams keep custom semantics."""
    calls = []

    def weird_ip(a, b):
        calls.append(1)
        return 2.0 * jnp.vdot(a, b)

    p, q, x, r = _vecs(1000, jnp.float64, 4)
    _, rn, rr = fv.xr_update(0.7, p, q, x, r, ip=weird_ip)
    assert calls, "opaque seam was bypassed"
    assert np.allclose(float(rr), 2.0 * float(jnp.vdot(rn, rn)))
    sd = fv.stack_dots(jnp.stack([p, q]), x, ip=weird_ip)
    assert np.allclose(np.asarray(sd),
                       [2 * float(jnp.vdot(p, x)),
                        2 * float(jnp.vdot(q, x))])


def test_psum_seam_merges_reductions():
    """Under shard_map with the psum-marked distributed dot, the fused
    primitives return globally-reduced values (matching the serial
    math), via ONE stacked psum."""
    from amgcl_tpu.parallel.compat import shard_map
    from amgcl_tpu.parallel.mesh import make_mesh
    from amgcl_tpu.parallel.dist_matrix import dist_inner_product
    from jax.sharding import PartitionSpec as P
    mesh = make_mesh(8)
    n = 8 * 32
    p, q, x, r = _vecs(n, jnp.float64, 4)
    V = jnp.stack([p, q, r])

    def body(pl_, ql_, xl_, rl_, Vl_):
        xn, rn, rr = fv.xr_update(0.7, pl_, ql_, xl_, rl_,
                                  ip=dist_inner_product)
        dots = fv.multi_dot(rl_, (rl_, xl_), ip=dist_inner_product)
        sd = fv.stack_dots(Vl_, xl_, ip=dist_inner_product)
        B = fv.block_dots(Vl_, Vl_, ip=dist_inner_product)
        return xn, rn, rr, dots[0], dots[1], sd, B

    fn = shard_map(body, mesh=mesh,
                   in_specs=(P("rows"), P("rows"), P("rows"), P("rows"),
                             P(None, "rows")),
                   out_specs=(P("rows"), P("rows"), P(), P(), P(), P(),
                              P()),
                   check_vma=False)
    xn, rn, rr, d0, d1, sd, B = jax.jit(fn)(p, q, x, r, V)
    rn_ref = r - 0.7 * q
    np.testing.assert_allclose(np.asarray(xn), np.asarray(x + 0.7 * p))
    np.testing.assert_allclose(float(rr),
                               float(jnp.vdot(rn_ref, rn_ref)))
    np.testing.assert_allclose(float(d0), float(jnp.vdot(r, r)))
    np.testing.assert_allclose(float(d1), float(jnp.vdot(r, x)))
    np.testing.assert_allclose(np.asarray(sd),
                               [float(jnp.vdot(v, x)) for v in V])
    np.testing.assert_allclose(
        np.asarray(B),
        [[float(jnp.vdot(a, b)) for b in V] for a in V])


def test_spmv_dots_accepts_psum_seam():
    """ISSUE 5 satellite: spmv_dots with the psum-marked distributed dot
    returns globally-reduced dots (local-shard fusion + one collective)
    instead of falling back to the unfused per-dot seam calls."""
    from amgcl_tpu.parallel.compat import shard_map
    from amgcl_tpu.parallel.mesh import make_mesh
    from amgcl_tpu.parallel.dist_matrix import dist_inner_product
    from jax.sharding import PartitionSpec as P
    mesh = make_mesh(8)
    nloc, nd = 32, 8
    n = nloc * nd
    x, w = _vecs(n, jnp.float64, 2)
    d = jnp.asarray(np.random.RandomState(5).rand(n) + 1.0)

    def body(dl, xl, wl):
        A_loc = dev.DiaMatrix((0,), dl[None, :], (nloc, nloc))
        y, yy, yx, yw = dev.spmv_dots(A_loc, xl, wl,
                                      ip=dist_inner_product)
        return y, yy, yx, yw

    fn = shard_map(body, mesh=mesh,
                   in_specs=(P("rows"), P("rows"), P("rows")),
                   out_specs=(P("rows"), P(), P(), P()),
                   check_vma=False)
    y, yy, yx, yw = jax.jit(fn)(d, x, w)
    y_ref = d * x            # block-diagonal: the diagonal operator
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref))
    np.testing.assert_allclose(float(yy), float(jnp.vdot(y_ref, y_ref)))
    np.testing.assert_allclose(float(yx), float(jnp.vdot(y_ref, x)))
    np.testing.assert_allclose(float(yw), float(jnp.vdot(y_ref, w)))


# -- health-guard parity with the tier on/off -------------------------------

def _neumann(n):
    import scipy.sparse as sp
    main = 2.0 * np.ones(n)
    main[0] = main[-1] = 1.0
    L = sp.diags([-np.ones(n - 1), main, -np.ones(n - 1)],
                 [-1, 0, 1]).tocsr()
    return dev.to_device(CSR.from_scipy(L), "ell", jnp.float64)


def _poisson1d(n):
    import scipy.sparse as sp
    L = sp.diags([-np.ones(n - 1), 2.0 * np.ones(n), -np.ones(n - 1)],
                 [-1, 0, 1]).tocsr()
    return dev.to_device(CSR.from_scipy(L), "dia", jnp.float64)


@pytest.mark.parametrize("fused", ["0", "1"])
def test_guard_parity_recorded(monkeypatch, fused):
    """Breakdown (singular system), NaN propagation (guards off) and
    divergence-trip behavior must be IDENTICAL with the fused tier on
    and off — same flags, same trip iteration, same early exit. The
    parametrization records both arms; the cross-arm equality is
    asserted in test_guard_parity_cross below with explicit env
    control."""
    monkeypatch.setenv("AMGCL_TPU_FUSED_VEC", fused)
    got = _guard_scenarios()
    assert got["cg_breakdown"]["breakdown"] is not None
    assert got["bicgstab_breakdown"]["breakdown"] is not None
    assert got["richardson_divergence"]["diverged"]
    assert not np.isfinite(got["cg_nan_guard_off"])


def _guard_scenarios():
    """Run the guard-relevant scenarios under the CURRENT env; returns
    decoded health per scenario."""
    from amgcl_tpu.solver import CG, BiCGStab, Richardson
    from amgcl_tpu.telemetry import health as H
    out = {}
    A = _neumann(8)
    b = jnp.ones(8, jnp.float64)
    x, it, res, hs = CG(maxiter=50, tol=1e-8).solve(A, lambda r: r, b)
    out["cg_breakdown"] = H.decode(hs.flags, hs.first_it)
    out["cg_breakdown"]["iters"] = int(it)
    x, it, res, hs = BiCGStab(maxiter=50, tol=1e-8).solve(
        A, lambda r: r, b)
    out["bicgstab_breakdown"] = H.decode(hs.flags, hs.first_it)
    out["bicgstab_breakdown"]["iters"] = int(it)
    # guards off: the historical NaN-exit failure signal must survive
    x, it, res = CG(maxiter=50, tol=1e-8, guard=False).solve(
        A, lambda r: r, b)
    out["cg_nan_guard_off"] = float(res)
    # divergence: over-relaxed Richardson on an SPD system grows the
    # residual monotonically — the divergence guard must trip and exit
    Ap = _poisson1d(64)
    bp = jnp.ones(64, jnp.float64)
    x, it, res, hs = Richardson(maxiter=200, tol=1e-10, damping=1.3).solve(
        Ap, lambda r: r, bp)
    out["richardson_divergence"] = H.decode(hs.flags, hs.first_it)
    out["richardson_divergence"]["iters"] = int(it)
    return out


def test_guard_parity_cross(monkeypatch):
    """The decisive check: the same scenarios, run back to back with
    AMGCL_TPU_FUSED_VEC=0 and =1 — flags, trip iterations and iteration
    counts must agree exactly; residuals to solver tolerance."""
    monkeypatch.setenv("AMGCL_TPU_FUSED_VEC", "1")
    on = _guard_scenarios()
    monkeypatch.setenv("AMGCL_TPU_FUSED_VEC", "0")
    off = _guard_scenarios()
    for key in ("cg_breakdown", "bicgstab_breakdown",
                "richardson_divergence"):
        assert on[key]["flags"] == off[key]["flags"], key
        assert on[key]["iters"] == off[key]["iters"], key
        assert on[key].get("breakdown") == off[key].get("breakdown"), key
    assert np.isnan(on["cg_nan_guard_off"]) \
        == np.isnan(off["cg_nan_guard_off"])


@pytest.mark.parametrize("fused", ["0", "1"])
def test_solver_residual_parity(monkeypatch, fused):
    """Fused and unfused paths agree on the final residual to solver
    tolerance (acceptance criterion), across CG / BiCGStab / IDRs."""
    import scipy.sparse as sp
    from amgcl_tpu.solver import CG, BiCGStab, IDRs
    monkeypatch.setenv("AMGCL_TPU_FUSED_VEC", fused)
    n = 128
    L = sp.diags([-np.ones(n - 1), 2.1 * np.ones(n), -np.ones(n - 1)],
                 [-1, 0, 1]).tocsr()
    A = dev.to_device(CSR.from_scipy(L), "dia", jnp.float64)
    b = jnp.asarray(np.random.RandomState(0).rand(n))
    host = L.toarray()
    for slv in (CG(maxiter=200, tol=1e-8), BiCGStab(maxiter=200, tol=1e-8),
                IDRs(s=2, maxiter=200, tol=1e-8)):
        x, it, res = slv.solve(A, lambda r: r, b)[:3]
        true = np.linalg.norm(np.asarray(b) - host @ np.asarray(x)) \
            / np.linalg.norm(np.asarray(b))
        assert true < 5e-8, (type(slv).__name__, fused, true)


# -- models / CLI -----------------------------------------------------------

def test_iteration_model_fused_bytes_drop():
    """The fused iteration model charges strictly fewer vector bytes
    than the composed one, with identical FLOPs (fusion moves bytes,
    not arithmetic)."""
    from amgcl_tpu.telemetry.ledger import krylov_iteration_model
    d = dev.DiaMatrix((0,), jnp.ones((1, 4096), jnp.float32),
                      (4096, 4096))
    for name in ("CG", "BiCGStab", "Richardson", "IDRs"):
        f = krylov_iteration_model(name, d, fused=True)
        u = krylov_iteration_model(name, d, fused=False)
        assert f["bytes"] < u["bytes"], name
        assert f["flops"] == u["flops"], name
        assert f["fused_vec"] and not u["fused_vec"]


def test_vecbench_cli():
    """bench.py --vecbench runs end to end and emits the record."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_bench_vec", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    assert bench.main_vecbench(["1024"]) == 0
