"""End-to-end: AMG-preconditioned CG on the Poisson fixture.

The acceptance criterion follows the reference's convergence-sweep tests:
final relative residual below tolerance within a bounded iteration count
(reference: tests/test_solver.hpp:120-248, assertion at :71)."""

import numpy as np
import jax.numpy as jnp
import pytest

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.models.make_solver import make_solver
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.relaxation.jacobi import DampedJacobi
from amgcl_tpu.relaxation.spai0 import Spai0
from amgcl_tpu.coarsening.smoothed_aggregation import SmoothedAggregation
from amgcl_tpu.coarsening.aggregation import Aggregation
from amgcl_tpu.utils.sample_problem import poisson3d


def check_solution(A, rhs, x, tol=1e-6):
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < tol


def test_hierarchy_shape():
    A, _ = poisson3d(16)
    amg = AMG(A, AMGParams(dtype=jnp.float64, coarse_enough=500))
    assert len(amg.host_levels) >= 2
    # coarse levels shrink fast (aggregation ratio ~> 4x in 3D)
    sizes = [l[0].nrows for l in amg.host_levels]
    assert all(sizes[i + 1] < sizes[i] for i in range(len(sizes) - 1))
    assert sizes[-1] <= 500
    r = repr(amg)
    assert "Number of levels" in r and "unknowns" in r


def test_amg_apply_reduces_residual():
    A, rhs = poisson3d(12)
    amg = AMG(A, AMGParams(dtype=jnp.float64))
    f = jnp.asarray(rhs)
    x = amg.hierarchy.apply(f)
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) < 0.5 * np.linalg.norm(rhs)


@pytest.mark.parametrize("relax", [Spai0(), DampedJacobi()])
@pytest.mark.parametrize("coarsening_cls", [SmoothedAggregation, Aggregation])
def test_cg_amg_poisson(relax, coarsening_cls):
    A, rhs = poisson3d(16)
    solve = make_solver(
        A,
        AMGParams(coarsening=coarsening_cls(), relax=relax,
                  dtype=jnp.float64),
        CG(maxiter=100, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8
    assert info.iters < 60
    check_solution(A, rhs, x, 1e-7)


def test_sa_cg_iteration_count_matches_reference_ballpark():
    """Reference hits 24 iters on Poisson with SA+CG+spai0
    (BASELINE.md shared-memory table); on the same setup we must be in the
    same range — the hierarchy quality check."""
    A, rhs = poisson3d(32)
    solve = make_solver(
        A, AMGParams(dtype=jnp.float64), CG(maxiter=100, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8
    assert info.iters <= 40
    check_solution(A, rhs, x, 1e-7)


def test_w_cycle_and_sweeps():
    A, rhs = poisson3d(12)
    solve = make_solver(
        A, AMGParams(dtype=jnp.float64, ncycle=2, npre=2, npost=2),
        CG(maxiter=50, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8
    check_solution(A, rhs, x, 1e-7)


def test_mixed_precision_precond():
    """float32 hierarchy inside a float64 CG loop
    (reference: examples/mixed_precision.cpp:32-44)."""
    A, rhs = poisson3d(16)
    solve = make_solver(
        A, AMGParams(dtype=jnp.float32), CG(maxiter=200, tol=1e-8),
        solver_dtype=jnp.float64)
    x, info = solve(rhs)
    assert info.resid < 1e-8
    check_solution(A, rhs, x, 1e-7)


def test_x0_initial_guess():
    A, rhs = poisson3d(12)
    solve = make_solver(A, AMGParams(dtype=jnp.float64), CG(tol=1e-8))
    x1, info1 = solve(rhs)
    # resolving from the solution should converge (nearly) immediately
    x2, info2 = solve(rhs, x0=x1)
    assert info2.iters <= 1


def test_npre_zero_is_honored():
    A, rhs = poisson3d(12)
    solve = make_solver(A, AMGParams(dtype=jnp.float64, npre=0, npost=2),
                        CG(maxiter=100, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8


def test_block_nullspace_unsupported():
    from amgcl_tpu.utils.sample_problem import poisson3d_block
    A, rhs = poisson3d_block(6, 2)
    ns = np.ones((A.nrows * 2, 3))
    with pytest.raises(NotImplementedError):
        SmoothedAggregation(nullspace=ns).transfer_operators(A)


def test_rhs_shape_check():
    A, rhs = poisson3d(8)
    solve = make_solver(A, AMGParams(dtype=jnp.float64), CG())
    with pytest.raises(ValueError, match="unknowns"):
        solve(np.ones(len(rhs) + 1))


def test_refine_reaches_true_tolerance():
    """f32 hierarchy + f32 CG drifts from the true residual; refinement
    restarts must recover it."""
    A, rhs = poisson3d(20)
    s_plain = make_solver(A, AMGParams(dtype=jnp.float32),
                          CG(maxiter=100, tol=1e-6))
    s_ref = make_solver(A, AMGParams(dtype=jnp.float32),
                        CG(maxiter=100, tol=1e-6), refine=3)
    x0, _ = s_plain(rhs)
    x1, info = s_ref(rhs)
    t0 = np.linalg.norm(rhs - A.spmv(np.asarray(x0, np.float64)))
    t1 = np.linalg.norm(rhs - A.spmv(np.asarray(x1, np.float64)))
    nb = np.linalg.norm(rhs)
    assert t1 / nb <= 2e-6
    assert t1 <= t0


def test_rebuild_fast_path():
    """allow_rebuild equivalent: same structure, new values."""
    A, rhs = poisson3d(14)
    solve = make_solver(A, AMGParams(dtype=jnp.float64, coarse_enough=300),
                        CG(maxiter=100, tol=1e-8))
    x1, i1 = solve(rhs)
    # scale the operator: structure identical, values changed
    A2 = CSR(A.ptr.copy(), A.col.copy(), 2.0 * A.val, A.ncols)
    solve.rebuild(A2)
    x2, i2 = solve(rhs)
    assert i2.resid < 1e-8
    r = rhs - A2.spmv(np.asarray(x2))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-7
    assert np.allclose(np.asarray(x2), np.asarray(x1) / 2.0, atol=1e-6)


def test_bfloat16_hierarchy_smoke():
    """bf16 preconditioner inside an f32 Krylov loop — the TPU-lean mixed
    precision configuration."""
    A, rhs = poisson3d(12)
    solve = make_solver(A, AMGParams(dtype=jnp.bfloat16),
                        CG(maxiter=200, tol=1e-5), solver_dtype=jnp.float32)
    x, info = solve(rhs)
    r = rhs - A.spmv(np.asarray(x, dtype=np.float64))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-4


def test_memory_report():
    A, _ = poisson3d(12)
    amg = AMG(A, AMGParams(dtype=jnp.float64))
    assert amg.bytes() > 0
    assert "Memory footprint:" in repr(amg)


def _level_payload(lv):
    """Comparable numeric payload of a host level operator (CSR or HostDia)."""
    A = lv[0]
    if hasattr(A, "val"):
        return np.asarray(A.val)
    return np.asarray(A.data)     # HostDia (stencil setup)


def test_build_twice_from_one_params_is_identical():
    """Per-build coarsening state lives in a build context, not on the
    policy object — two builds from ONE params object must be bitwise
    identical, and the policy's own fields must stay untouched
    (round-2 review item: transfer_operators used to mutate self)."""
    A, _ = poisson3d(16)
    for coarsening_cls in (SmoothedAggregation, Aggregation):
        coarsening = coarsening_cls()
        prm = AMGParams(coarsening=coarsening, dtype=jnp.float64,
                        coarse_enough=100)
        amg1 = AMG(A, prm)
        amg2 = AMG(A, prm)
        assert coarsening.eps_strong == coarsening_cls().eps_strong
        assert coarsening.nullspace is None
        assert len(amg1.host_levels) == len(amg2.host_levels)
        for l1, l2 in zip(amg1.host_levels, amg2.host_levels):
            np.testing.assert_array_equal(_level_payload(l1),
                                          _level_payload(l2))


def test_direct_transfer_operators_call_is_pure():
    """Calling transfer_operators without a ctx twice gives identical
    results — no hidden eps_strong decay on the object."""
    A, _ = poisson3d(12)
    sa = SmoothedAggregation(stencil_setup=False, structured=False,
                             implicit_transfers=False)
    P1, _ = sa.transfer_operators(A)
    P2, _ = sa.transfer_operators(A)
    assert sa.eps_strong == SmoothedAggregation().eps_strong
    np.testing.assert_array_equal(np.asarray(P1.val), np.asarray(P2.val))


def test_device_coarse_inverse(monkeypatch):
    """AMGCL_TPU_DEVICE_INV=1: the coarse inverse runs on device in f32
    with Newton-Schulz polish — convergence must match the host f64
    inverse (it is cast to f32 anyway)."""
    monkeypatch.setenv("AMGCL_TPU_DEVICE_INV", "1")
    A, rhs = poisson3d(20)
    solve = make_solver(A, AMGParams(dtype=jnp.float32),
                        CG(maxiter=100, tol=1e-6))
    x, info = solve(jnp.asarray(rhs, jnp.float32))
    monkeypatch.setenv("AMGCL_TPU_DEVICE_INV", "0")
    solve0 = make_solver(A, AMGParams(dtype=jnp.float32),
                         CG(maxiter=100, tol=1e-6))
    x0, info0 = solve0(jnp.asarray(rhs, jnp.float32))
    assert abs(info.iters - info0.iters) <= 1
    r = np.linalg.norm(rhs - A.spmv(np.asarray(x, np.float64))) \
        / np.linalg.norm(rhs)
    assert r < 1e-4


def test_singular_coarse_pinv_fallback():
    """A singular coarse operator (pure Neumann: nullspace = constants)
    must announce the pseudo-inverse fallback and still produce a valid
    least-squares coarse solve (solver/direct.py pinv branch)."""
    import warnings
    import scipy.sparse as sp
    from amgcl_tpu.solver.direct import DenseDirectSolver
    from amgcl_tpu.ops.csr import CSR
    n = 24
    e = np.ones(n)
    # 1D Neumann Laplacian: rows sum to zero -> exactly singular
    L = sp.diags([-e[:-1], 2 * e, -e[:-1]], [-1, 0, 1]).tolil()
    L[0, 0] = 1.0
    L[-1, -1] = 1.0
    A = CSR.from_scipy(L.tocsr())
    with warnings.catch_warnings(record=True) as got:
        warnings.simplefilter("always")
        ds = DenseDirectSolver.build(A, jnp.float64)
    assert any("pseudo-inverse" in str(w.message) for w in got)
    # least-squares solve: for rhs in range(A), A (A+ f) == f
    f = np.asarray(A.to_dense() @ np.linspace(0, 1, n))
    y = np.asarray(ds.solve(jnp.asarray(f)))
    np.testing.assert_allclose(A.to_dense() @ y, f, atol=1e-8)


def test_stall_closes_hierarchy_but_real_errors_propagate():
    """CoarseningStall from a policy closes the hierarchy at the current
    level (the reference's empty_level terminal state); any OTHER
    ValueError is a real bug and must propagate — a bare except once
    mislabeled a degenerate benchmark fixture as 'coarsening stalled'
    (see coarsening/stall.py)."""
    from amgcl_tpu.coarsening.stall import CoarseningStall

    A, _ = poisson3d(8)

    class Stalling(SmoothedAggregation):
        def transfer_operators(self, Acur, ctx):
            raise CoarseningStall("no coarse points")

    amg = AMG(A, AMGParams(dtype=jnp.float64, coarsening=Stalling(),
                           coarse_enough=100))
    assert len(amg.host_levels) == 1      # closed at the fine level

    class Broken(SmoothedAggregation):
        def transfer_operators(self, Acur, ctx):
            raise ValueError("actual bug in the policy")

    with pytest.raises(ValueError, match="actual bug"):
        AMG(A, AMGParams(dtype=jnp.float64, coarsening=Broken(),
                         coarse_enough=100))
