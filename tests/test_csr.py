"""Unit tests for the host CSR build format (setup-phase algebra)."""

import numpy as np
import scipy.sparse as sp
import pytest

from amgcl_tpu.ops.csr import CSR, spectral_radius, pointwise_matrix
from amgcl_tpu.utils.sample_problem import poisson3d


def random_csr(n, m, density=0.1, seed=0):
    rng = np.random.RandomState(seed)
    M = sp.random(n, m, density=density, random_state=rng, format="csr")
    M.setdiag(rng.rand(min(n, m)) + 1.0)
    M = sp.csr_matrix(M)
    M.sort_indices()
    return CSR.from_scipy(M)


def test_roundtrip_scipy():
    A = random_csr(40, 40)
    B = CSR.from_scipy(A.to_scipy())
    assert np.array_equal(A.ptr, B.ptr)
    assert np.array_equal(A.col, B.col)
    assert np.allclose(A.val, B.val)


def test_transpose_matches_scipy():
    A = random_csr(30, 50)
    T = A.transpose()
    assert np.allclose(T.to_dense(), A.to_dense().T)


def test_spgemm_matches_scipy():
    A = random_csr(30, 40, seed=1)
    B = random_csr(40, 20, seed=2)
    C = A @ B
    assert np.allclose(C.to_dense(), A.to_dense() @ B.to_dense())


def test_sum():
    A = random_csr(25, 25, seed=3)
    B = random_csr(25, 25, seed=4)
    assert np.allclose((A + B).to_dense(), A.to_dense() + B.to_dense())


def test_diagonal_and_inverse():
    A = random_csr(20, 20, seed=5)
    d = A.diagonal()
    assert np.allclose(d, A.to_dense().diagonal())
    di = A.diagonal(invert=True)
    assert np.allclose(di[d != 0], 1.0 / d[d != 0])


def test_block_roundtrip():
    A = random_csr(24, 24, seed=6)
    B = A.to_block(4)
    assert B.is_block and B.block_size == (4, 4)
    assert np.allclose(B.unblock().to_dense(), A.to_dense())


def test_block_transpose():
    A = random_csr(12, 12, seed=7).to_block(3)
    T = A.transpose()
    assert np.allclose(T.unblock().to_dense(), A.unblock().to_dense().T)


def test_block_spgemm():
    A = random_csr(12, 12, seed=8).to_block(2)
    B = random_csr(12, 12, seed=9).to_block(2)
    C = A @ B
    assert C.is_block
    assert np.allclose(C.unblock().to_dense(),
                       A.unblock().to_dense() @ B.unblock().to_dense())


def test_block_diagonal_inverse():
    A = random_csr(12, 12, seed=10).to_block(3)
    D = A.diagonal()
    Di = A.diagonal(invert=True)
    for k in range(4):
        assert np.allclose(Di[k] @ D[k], np.eye(3), atol=1e-10)


def test_spmv_block_matches_scalar():
    A = random_csr(12, 12, seed=11)
    x = np.random.RandomState(0).rand(12)
    yb = A.to_block(3).spmv(x)
    assert np.allclose(yb, A.to_scipy() @ x)


def test_spectral_radius_poisson():
    A, _ = poisson3d(8)
    # D^-1 A of the Laplacian has spectral radius < 2 (and close to 2)
    g = spectral_radius(A, power_iters=0)
    p = spectral_radius(A, power_iters=30)
    assert 1.0 < p <= g <= 2.5
    assert abs(p - 2.0) < 0.2


def test_pointwise_matrix():
    A = random_csr(12, 12, seed=12)
    Ap = pointwise_matrix(A, 3)
    assert Ap.shape == (4, 4)
    d = Ap.diagonal()
    assert np.all(d >= 0)  # diagonal blocks keep + sign


def test_scale_and_filter_rows():
    A = random_csr(15, 15, seed=13)
    d = np.arange(1, 16).astype(float)
    S = A.scale_rows(d)
    assert np.allclose(S.to_dense(), np.diag(d) @ A.to_dense())
    keep = A.val > 0.5
    F = A.filter_rows(keep)
    assert F.nnz == int(keep.sum())


def test_from_row_generator():
    from amgcl_tpu.ops.csr import from_row_generator

    def row(i):  # 1D Laplacian, matrix-free
        cols, vals = [i], [2.0]
        if i > 0:
            cols.append(i - 1); vals.append(-1.0)
        if i < 19:
            cols.append(i + 1); vals.append(-1.0)
        return cols, vals

    A = from_row_generator(20, 20, row)
    import scipy.sparse as sp
    ref = sp.diags([-np.ones(19), 2 * np.ones(20), -np.ones(19)],
                   [-1, 0, 1]).toarray()
    assert np.allclose(A.to_dense(), ref)


def test_native_spgemm_parity(monkeypatch):
    """Exercise the native hash-SpGEMM even on single-core hosts (the
    normal gate defers to scipy there) and check exact parity."""
    from amgcl_tpu import native
    if native.lib() is None:
        pytest.skip("native kernels unavailable")
    monkeypatch.setenv("AMGCL_TPU_FORCE_NATIVE_SPGEMM", "1")
    A = random_csr(80, 60, density=0.08, seed=21)
    B = random_csr(60, 70, density=0.08, seed=22)
    got = native.native_spgemm(A, B)
    assert got is not None
    C = CSR(got[0], got[1], got[2], 70)
    assert np.allclose(C.to_dense(), A.to_dense() @ B.to_dense())
    # dimension mismatch raises instead of reading out of bounds
    with pytest.raises(ValueError, match="dimension mismatch"):
        native.native_spgemm(A, random_csr(10, 10, seed=23))
