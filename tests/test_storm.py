"""Storm open-loop load harness + saturation analytics (ISSUE 16): the
seeded arrival schedules (Poisson/burst/ramp determinism), the open-loop
sample accounting in ``telemetry/load.py`` (goodput excludes
sheds/timeouts/unhealthy, latency measured from the SCHEDULED arrival),
knee detection on ladder curves, the Perfetto storm timeline, the
/metrics scraper, the ``bench_storm`` round-over-round gate — and the
headline theorem: open-loop and closed-loop p99 DIVERGE under overload
(coordinated omission is real and the storm harness refuses to commit
it).

Everything here drives a pure-python stub queueing target (one worker,
deterministic service time, bounded queue) — no jax, no device — so the
protocol properties are tested exactly, not statistically.
"""

import concurrent.futures as _cf
import json
import os
import queue
import sys
import threading
import time
import types

import pytest

from amgcl_tpu import telemetry
from amgcl_tpu.faults import LoadShedError
from amgcl_tpu.serve import storm as S
from amgcl_tpu.telemetry import load as L

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bench():
    sys.path.insert(0, _REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    return bench


# ===========================================================================
# arrival schedules: seeded determinism + shape
# ===========================================================================

PHASES = [S.poisson_phase(40.0, 1.0),
          S.burst_phase(5.0, 1.0, burst_every_s=0.25, burst_len=6),
          S.ramp_phase(10.0, 80.0, 1.0)]


def test_schedule_deterministic_and_ordered():
    """Same (phases, tenants, seed) -> byte-identical schedule; a
    different seed moves the arrivals; rows are time-sorted with dense
    rids."""
    a = S.build_schedule(PHASES, tenants=("t0", "t1"), seed=7)
    b = S.build_schedule(PHASES, tenants=("t0", "t1"), seed=7)
    assert a == b
    assert a != S.build_schedule(PHASES, tenants=("t0", "t1"), seed=8)
    ts = [r["t_s"] for r in a]
    assert ts == sorted(ts)
    assert [r["rid"] for r in a] == list(range(len(a)))
    assert {r["tenant"] for r in a} == {"t0", "t1"}
    assert {r["phase"] for r in a} == {"poisson", "burst", "ramp"}
    # phases lie back-to-back: every arrival inside the 3 s span
    assert 0.0 <= ts[0] and ts[-1] < S.schedule_duration_s(PHASES) == 3.0


def test_poisson_phase_mean_rate():
    """Seeded homogeneous Poisson arrivals land near rate*duration
    (deterministic given the seed, so the bound never flakes)."""
    rows = S.build_schedule([S.poisson_phase(200.0, 2.0)], seed=3)
    # E[N] = 400, sd = 20 — a 5-sigma band
    assert 300 <= len(rows) <= 500
    assert all(0.0 <= r["t_s"] < 2.0 for r in rows)
    assert all(r["rate_rps"] == 200.0 for r in rows)


def test_ramp_phase_density_and_rate_annotation():
    """An increasing ramp puts more arrivals in the second half
    (Lambda(2)-Lambda(1) = 77.5 vs Lambda(1) = 32.5 for 10->100 over
    2 s); the per-row rate annotation ramps monotonically with t; a
    DECREASING ramp terminates (finite total intensity)."""
    rows = S.build_schedule([S.ramp_phase(10.0, 100.0, 2.0)], seed=11)
    lo = [r for r in rows if r["t_s"] < 1.0]
    hi = [r for r in rows if r["t_s"] >= 1.0]
    assert len(hi) > 1.5 * len(lo)
    rates = [r["rate_rps"] for r in rows]
    assert rates == sorted(rates)
    assert rates[0] < 50.0 < rates[-1] <= 100.0
    down = S.build_schedule([S.ramp_phase(100.0, 10.0, 2.0)], seed=11)
    assert down and all(0.0 <= r["t_s"] < 2.0 for r in down)


def test_burst_phase_trains_are_deterministic():
    """The flash-crowd trains ride the Poisson background verbatim:
    burst_len arrivals 1 ms apart at every multiple of burst_every_s,
    independent of the seed."""
    phase = S.burst_phase(5.0, 2.0, burst_every_s=0.5, burst_len=6)
    rows = S.build_schedule([phase], seed=1)
    ts = {r["t_s"] for r in rows}
    for k in (1, 2, 3):          # trains at 0.5, 1.0, 1.5
        for j in range(6):
            assert round(k * 0.5 + j * 1e-3, 6) in ts
    assert len(rows) >= 18        # 3 trains + background


# ===========================================================================
# the open-loop sample accounting (telemetry/load.py)
# ===========================================================================

def _sample(rid, t, outcome, lat=None, tenant="t0", phase="poisson",
            spans=None):
    s = {"rid": rid, "tenant": tenant, "phase": phase, "rate_rps": 10.0,
         "t_sched_s": t, "t_submit_s": t, "lag_ms": 0.1,
         "outcome": outcome}
    if lat is not None:
        s["latency_ms"] = lat
        s["t_done_s"] = t + lat / 1e3
    if spans is not None:
        s["spans_ms"] = spans
    return s


def test_summarize_goodput_excludes_bad_outcomes():
    """goodput counts ONLY ok completions; sheds/timeouts/unhealthy/
    errors appear in their rate fields and in bad_frac; latency
    percentiles cover ok rows alone."""
    spans = {"queue": 2.0, "pad": 0.5, "compile": 0.0, "solve": 6.0,
             "sync": 1.5}
    samples = (
        [_sample(i, i * 0.1, "ok", lat=10.0 + i, spans=spans)
         for i in range(6)]
        + [_sample(6, 0.6, "shed", lat=0.2),
           _sample(7, 0.7, "timeout", lat=500.0),
           _sample(8, 0.8, "unhealthy", lat=20.0),
           _sample(9, 0.9, "error", lat=20.0)])
    out = L.summarize_samples(samples, duration_s=1.0)
    assert out["requests"] == 10
    assert out["outcomes"]["ok"] == 6
    assert out["offered_rps"] == 10.0
    assert out["shed_rate"] == 0.1 and out["timeout_rate"] == 0.1
    assert out["unhealthy_rate"] == 0.1 and out["error_rate"] == 0.1
    assert out["bad_frac"] == 0.4
    # goodput_rps / offered_rps: 6 good of 10 offered over the same
    # clock would be 0.6; the wall stretches past the schedule end so
    # the fraction sits at or under it
    assert 0 < out["goodput_frac"] <= 0.6
    assert out["latency_ms"]["count"] == 6
    assert out["latency_ms"]["max"] == 15.0   # the 500 ms timeout row
    #                                           never enters the ok set
    assert out["spans_ms"]["solve"] == 6.0
    assert abs(sum(out["span_share"].values()) - 1.0) < 1e-6
    assert out["span_share"]["solve"] == 0.6


def test_detect_knee_all_three_reasons_and_clean():
    """Each saturation criterion fires on the FIRST offending rung in
    offered-rate order, and max_sustainable_rps is the best goodput
    strictly below the knee."""
    def row(i, rate, p99, gf, qd=None):
        return {"rung": i, "offered_rps": rate, "p99_ms": p99,
                "goodput_frac": gf, "goodput_rps": rate * gf,
                "queue_depth_max": qd}
    clean = [row(0, 10, 5.0, 1.0), row(1, 20, 6.0, 0.99),
             row(2, 40, 8.0, 0.97)]
    k = L.detect_knee(clean, slo_p99_ms=50.0)
    assert not k["saturated"] and k["reason"] is None
    assert k["knee_offered_rps"] is None
    assert k["max_sustainable_rps"] == 40 * 0.97

    slo = clean[:2] + [row(2, 40, 80.0, 0.97)]
    k = L.detect_knee(slo, slo_p99_ms=50.0)
    assert k["saturated"] and k["reason"] == "p99_slo_breach"
    assert k["knee_offered_rps"] == 40 and k["knee_p99_ms"] == 80.0
    assert k["max_sustainable_rps"] == 20 * 0.99

    gp = clean[:2] + [row(2, 40, 8.0, 0.5)]
    k = L.detect_knee(gp)                      # no SLO set
    assert k["reason"] == "goodput_collapse"
    assert k["knee_rung"] == 2

    qd = [row(0, 10, 5.0, 1.0, qd=2), row(1, 20, 6.0, 0.99, qd=900)]
    k = L.detect_knee(qd, queue_depth_limit=100.0)
    assert k["reason"] == "queue_divergence"
    assert k["knee_offered_rps"] == 20
    assert k["max_sustainable_rps"] == 10.0


def test_build_record_schema_and_reference():
    """The bench_storm record body: schema pin, curve rows per rung,
    aggregate goodput accounting, and the reference row = LOWEST
    offered rate (the gate's p99 comparison point)."""
    spans = {"queue": 1.0, "pad": 0.2, "compile": 0.0, "solve": 4.0,
             "sync": 0.8}
    def rung(rate, n_ok, n_shed):
        samples = [_sample(i, i / rate, "ok", lat=8.0, spans=spans)
                   for i in range(n_ok)]
        samples += [_sample(n_ok + j, (n_ok + j) / rate, "shed",
                            lat=0.1) for j in range(n_shed)]
        return {"offered_rps": rate,
                "summary": L.summarize_samples(
                    samples, duration_s=(n_ok + n_shed) / rate),
                "gauges": [{"t_s": 0.1, "queue_depth": 3.0}]}
    rungs = [rung(40.0, 8, 8), rung(10.0, 10, 0)]   # unsorted on purpose
    rec = L.build_record(rungs, slo_p99_ms=100.0)
    assert rec["schema"] == L.STORM_SCHEMA == 1
    assert len(rec["curve"]) == 2
    assert rec["reference"]["offered_rps"] == 10.0
    assert rec["reference"]["p99_ms"] == 8.0
    assert rec["goodput"]["requests"] == 26
    assert rec["goodput"]["ok"] == 18
    assert rec["goodput"]["outcomes"]["shed"] == 8
    assert rec["knee"]["saturated"]            # rate-40 rung shed half
    assert rec["knee"]["reason"] == "goodput_collapse"
    assert rec["attribution"] and \
        rec["attribution"][0]["shares"]["solve"] > 0
    assert rec["gauges"]["rows"] == 2
    json.dumps(rec)                            # JSONL-clean


def test_storm_timeline_trace_shape():
    """Perfetto export: per-tenant thread tracks, complete events
    spanning scheduled arrival -> completion, instant markers for bad
    outcomes, counter tracks from the gauge series."""
    samples = [_sample(0, 0.1, "ok", lat=12.0, tenant="a"),
               _sample(1, 0.2, "shed", lat=0.1, tenant="b")]
    gauges = [{"t_s": 0.15, "queue_depth": 4.0}]
    tr = L.storm_timeline_trace(samples, gauges)
    evs = tr["traceEvents"]
    names = {e["ph"] for e in evs}
    assert {"M", "X", "i", "C"} <= names
    x = [e for e in evs if e["ph"] == "X"][0]
    assert x["ts"] == pytest.approx(0.1 * 1e6)
    assert x["dur"] == pytest.approx(12.0 * 1e3)
    meta = {e["args"]["name"] for e in evs if e["ph"] == "M"}
    assert meta == {"storm/a", "storm/b"}
    c = [e for e in evs if e["ph"] == "C"][0]
    assert c["args"] == {"queue_depth": 4.0}


# ===========================================================================
# /metrics scraping
# ===========================================================================

PROM_PAGE = """\
# HELP amgcl_tpu_farm_queue_depth per-tenant backlog
# TYPE amgcl_tpu_farm_queue_depth gauge
amgcl_tpu_farm_queue_depth{tenant="a"} 3
amgcl_tpu_farm_queue_depth{tenant="b"} 4.5
amgcl_tpu_serve_inflight 2
amgcl_tpu_serve_requests_total 120
amgcl_tpu_serve_batch_fill 0.75
not a metric line
"""


def test_parse_prometheus_gauges_sums_label_variants():
    out = S.parse_prometheus_gauges(PROM_PAGE)
    assert out["queue_depth"] == 7.5      # tenants summed
    assert out["inflight"] == 2.0
    assert out["requests_total"] == 120.0
    assert set(out) == {"queue_depth", "inflight", "requests_total"}


def test_scraper_counts_errors_instead_of_swallowing():
    """An unreachable /metrics endpoint never fails the storm, but the
    failures are COUNTED on the scraper (the swallowed-worker-exception
    lint contract: broad handlers in thread targets must do real
    work)."""
    lock = threading.Lock()
    rows = []
    sc = S._Scraper("http://127.0.0.1:9/metrics", 0.02,
                    time.perf_counter(), lock, rows).start()
    time.sleep(0.15)
    sc.stop()
    assert sc.errors > 0
    assert sc.last_error
    assert rows == []


# ===========================================================================
# the open-loop run against a stub queueing target
# ===========================================================================

class _StubTarget:
    """One worker, deterministic service time, bounded queue — an exact
    M/D/1/K system the storm protocol properties are provable on."""

    def __init__(self, service_s=0.008, qmax=16, healthy=True):
        self.service_s = service_s
        self.healthy = healthy
        self._q = queue.Queue(maxsize=qmax)
        self._t = threading.Thread(target=self._worker, daemon=True)
        self._t.start()

    def submit(self, tenant, rhs):
        fut = _cf.Future()
        self._q.put_nowait((fut, rhs))     # queue.Full -> shed
        return fut

    def _worker(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            fut, rhs = item
            time.sleep(self.service_s)
            rep = types.SimpleNamespace(
                health={"ok": self.healthy, "flags": []
                        if self.healthy else ["stub"]},
                serve={"queue_ms": 1.0, "pad_ms": 0.1,
                       "compile_ms": 0.0,
                       "solve_ms": self.service_s * 1e3,
                       "sync_ms": 0.2,
                       "latency_ms": self.service_s * 1e3 + 1.3})
            fut.set_result((rhs, rep))

    def close(self):
        self._q.put(None)
        self._t.join(timeout=5.0)


def test_open_loop_vs_closed_loop_p99_diverge_under_overload():
    """THE theorem this harness exists for: drive the same overloaded
    target (capacity ~125 rps) both ways. The closed-loop protocol
    submits-waits-submits, so its per-request latency stays ~= the
    service time no matter how overloaded the system is — coordinated
    omission. The open-loop storm charges queueing from the SCHEDULED
    arrival and its p99 explodes. They must diverge by >= 3x."""
    tgt = _StubTarget(service_s=0.008, qmax=16)
    try:
        # closed loop: one at a time, latency measured submit->done
        closed = []
        for _ in range(30):
            t0 = time.perf_counter()
            tgt.submit("t0", b"x").result(timeout=10)
            closed.append((time.perf_counter() - t0) * 1e3)
        closed.sort()
        closed_p99 = closed[int(0.99 * (len(closed) - 1))]

        # open loop: offered 300 rps >> capacity, same target
        sched = S.build_schedule([S.poisson_phase(300.0, 1.0)], seed=5)
        res = S.run_storm(tgt, sched, lambda tenant, rid: b"x",
                          drain_timeout_s=10.0, scrape_every_s=0.0,
                          emit_event=False)
    finally:
        tgt.close()
    summ = res["summary"]
    assert summ["outcomes"].get("pending", 0) == 0
    assert summ["outcomes"]["ok"] > 20
    assert summ["shed_rate"] > 0.2        # the bounded queue shed load
    open_p99 = summ["latency_ms"]["p99"]
    assert open_p99 > 3 * closed_p99, (open_p99, closed_p99)
    # and goodput saturates near capacity, far under the offered rate
    assert summ["goodput_rps"] < 0.75 * summ["offered_rps"]


def test_run_storm_outcomes_spans_and_event(tmp_path):
    """A gentle storm on a healthy stub: all ok, spans copied off the
    reports, latency from the scheduled arrival, one `storm` JSONL
    event with the headline numbers."""
    out = tmp_path / "storm.jsonl"
    telemetry.set_default_sink(telemetry.JsonlSink(str(out)))
    tgt = _StubTarget(service_s=0.002, qmax=64)
    try:
        sched = S.build_schedule([S.poisson_phase(50.0, 0.5)],
                                 tenants=("a", "b"), seed=2)
        res = S.run_storm(tgt, sched, lambda tenant, rid: b"x",
                          drain_timeout_s=10.0, scrape_every_s=0.0,
                          label="gentle")
    finally:
        tgt.close()
        telemetry.set_default_sink(telemetry.NullSink())
    summ = res["summary"]
    assert summ["outcomes"] == {"ok": summ["requests"]}
    assert summ["goodput_frac"] > 0.5
    ok_rows = [s for s in res["samples"] if s["outcome"] == "ok"]
    assert all(s["spans_ms"]["solve"] == 2.0 for s in ok_rows)
    assert all(s["latency_ms"] >= 0 for s in ok_rows)
    recs = [json.loads(ln) for ln in open(out)]
    ev = [r for r in recs if r.get("event") == "storm"]
    assert len(ev) == 1 and ev[0]["label"] == "gentle"
    assert ev[0]["requests"] == summ["requests"]
    assert ev[0]["p99_ms"] == summ["latency_ms"]["p99"]
    assert ev[0]["shed_rate"] == 0.0


def test_unhealthy_solves_excluded_from_goodput():
    tgt = _StubTarget(service_s=0.001, qmax=64, healthy=False)
    try:
        sched = S.build_schedule([S.poisson_phase(40.0, 0.4)], seed=4)
        res = S.run_storm(tgt, sched, lambda tenant, rid: b"x",
                          drain_timeout_s=10.0, scrape_every_s=0.0,
                          emit_event=False)
    finally:
        tgt.close()
    summ = res["summary"]
    assert summ["outcomes"] == {"unhealthy": summ["requests"]}
    assert summ["unhealthy_rate"] == 1.0
    assert summ["goodput_rps"] == 0.0
    assert "latency_ms" not in summ       # no ok rows, no percentiles


def test_classify_exc_taxonomy():
    class RequestTimeout(Exception):
        pass
    assert S._classify_exc(queue.Full()) == "shed"
    assert S._classify_exc(LoadShedError("t0", 1, 2)) == "shed"
    assert S._classify_exc(TimeoutError()) == "timeout"
    assert S._classify_exc(RequestTimeout()) == "timeout"
    assert S._classify_exc(ValueError("boom")) == "error"


def test_ladder_to_knee_on_stub():
    """End-to-end analytics on the stub: a 3-rung ladder crossing the
    stub's ~125 rps capacity produces a curve whose knee lands at an
    overloaded rung, with max_sustainable_rps below capacity."""
    tgt = _StubTarget(service_s=0.008, qmax=16)
    try:
        rungs = S.run_ladder(tgt, (20.0, 60.0, 400.0), 0.8,
                             lambda tenant, rid: b"x", seed=9,
                             drain_timeout_s=10.0, scrape_every_s=0.0,
                             emit_events=False)
    finally:
        tgt.close()
    rec = L.build_record(rungs)
    assert [r["offered_rps"] for r in rec["curve"]] == [20.0, 60.0,
                                                        400.0]
    assert rec["knee"]["saturated"]
    assert rec["knee"]["knee_offered_rps"] == 400.0
    assert rec["knee"]["max_sustainable_rps"] is not None
    assert rec["knee"]["max_sustainable_rps"] < 130.0
    assert rec["reference"]["offered_rps"] == 20.0


def test_armed_fault_plan_swaps_and_restores_env():
    key = "AMGCL_TPU_FAULT_PLAN"
    prev = os.environ.pop(key, None)
    try:
        with S.armed_fault_plan("serve_timeout_storm:p=1"):
            assert os.environ[key] == "serve_timeout_storm:p=1"
        assert key not in os.environ
        os.environ[key] = "outer"
        with S.armed_fault_plan("inner"):
            assert os.environ[key] == "inner"
        assert os.environ[key] == "outer"
        with S.armed_fault_plan(None):
            assert os.environ[key] == "outer"   # no-op when unset
    finally:
        os.environ.pop(key, None)
        if prev is not None:
            os.environ[key] = prev


# ===========================================================================
# the storm gate (bench.py)
# ===========================================================================

def _storm_rec(max_rps=100.0, ref_p99=20.0, ref_rps=10.0,
               platform="cpu"):
    return {"event": "bench_storm", "device_platform": platform,
            "record": {"schema": 1,
                       "knee": {"max_sustainable_rps": max_rps},
                       "reference": {"offered_rps": ref_rps,
                                     "p99_ms": ref_p99}}}


TOL = {"rate": 0.7, "p99": 1.5}


def test_storm_gate_clean_pass():
    bench = _bench()
    ok, checks = bench.run_storm_gate(_storm_rec(), _storm_rec(),
                                      tol=TOL)
    assert ok
    assert [c["status"] for c in checks] == ["ok", "ok"]
    assert [c["check"] for c in checks] == ["storm_max_rps",
                                            "storm_ref_p99"]


def test_storm_gate_fails_on_rate_and_p99_regressions():
    bench = _bench()
    base = _storm_rec(max_rps=100.0, ref_p99=20.0)
    ok, checks = bench.run_storm_gate(_storm_rec(max_rps=50.0), base,
                                      tol=TOL)
    assert not ok
    by = {c["check"]: c for c in checks}
    assert by["storm_max_rps"]["status"] == "regression"
    assert by["storm_max_rps"]["limit"] == 70.0
    ok, checks = bench.run_storm_gate(_storm_rec(ref_p99=45.0), base,
                                      tol=TOL)
    assert not ok
    by = {c["check"]: c for c in checks}
    assert by["storm_ref_p99"]["status"] == "regression"
    assert by["storm_ref_p99"]["limit"] == 30.0
    # riding the edge is still a pass (>= floor, <= ceiling)
    ok, _ = bench.run_storm_gate(
        _storm_rec(max_rps=70.0, ref_p99=30.0), base, tol=TOL)
    assert ok


def test_storm_gate_skips():
    """Platform mismatch skips every ratio; a recalibrated reference
    rate skips the p99 check only; AMGCL_TPU_GATE_STORM=0 disables."""
    bench = _bench()
    ok, checks = bench.run_storm_gate(
        _storm_rec(max_rps=1.0, ref_p99=9999.0, platform="cpu"),
        _storm_rec(platform="tpu"), tol=TOL)
    assert ok
    assert all(c["status"] == "skipped" for c in checks)
    assert all("platform_mismatch" in c["reason"] for c in checks)
    ok, checks = bench.run_storm_gate(
        _storm_rec(ref_p99=9999.0, ref_rps=40.0), _storm_rec(),
        tol=TOL)
    assert ok                      # p99 blew up, but at a different rate
    by = {c["check"]: c for c in checks}
    assert by["storm_max_rps"]["status"] == "ok"
    assert by["storm_ref_p99"]["status"] == "skipped"
    assert "reference_rate_mismatch" in by["storm_ref_p99"]["reason"]
    ok, checks = bench.run_storm_gate(
        _storm_rec(max_rps=0.001), _storm_rec(),
        tol={"rate": 0.0, "p99": 1.5})
    assert ok and checks[0]["status"] == "skipped"
    assert "disabled" in checks[0]["reason"]


def test_storm_gate_record_statuses(tmp_path, monkeypatch):
    """The --gate/--check sub-record contract: None when unused,
    no_candidate / no_baseline markers, ok=False + failed rows on a
    real regression."""
    bench = _bench()
    cand_path = tmp_path / "cand.json"
    monkeypatch.setenv("AMGCL_TPU_GATE_STORM_CANDIDATE", str(cand_path))
    monkeypatch.setattr(bench, "_storm_baseline", lambda: None)
    assert bench.storm_gate_record() is None        # unused: no files
    base = dict(_storm_rec(), path="STORM_r1.json")
    monkeypatch.setattr(bench, "_storm_baseline", lambda: base)
    rec = bench.storm_gate_record()
    assert rec["status"] == "no_candidate" and rec["ok"]
    cand_path.write_text(json.dumps(_storm_rec(max_rps=10.0)))
    monkeypatch.setattr(bench, "_storm_baseline", lambda: None)
    rec = bench.storm_gate_record()
    assert rec["status"] == "no_baseline" and rec["ok"]
    monkeypatch.setattr(bench, "_storm_baseline", lambda: base)
    rec = bench.storm_gate_record()
    assert not rec["ok"]
    assert rec["baseline"] == "STORM_r1.json"
    assert rec["failed"][0]["check"] == "storm_max_rps"
    assert rec["failed"][0]["candidate"] == 10.0
    assert rec["failed"][0]["baseline"] == 100.0


def test_storm_history_and_trend_fields(tmp_path):
    """STORM_r*.json round files join bench --trend through
    metrics.storm_history + STORM_TREND_FIELDS."""
    from amgcl_tpu.telemetry import metrics as m
    for i, rps in ((1, 80.0), (2, 120.0)):
        (tmp_path / ("STORM_r%d.json" % i)).write_text(json.dumps(
            dict(_storm_rec(max_rps=rps),
                 record=dict(_storm_rec(max_rps=rps)["record"],
                             goodput={"good_frac": 0.9,
                                      "requests": 100}))))
    (tmp_path / "STORM_LATEST.json").write_text("{}")   # not a round
    hist = m.storm_history(str(tmp_path))
    assert [h["round"] for h in hist] == [1, 2]
    rows = m.trend(hist, m.STORM_TREND_FIELDS)
    assert [r["max_rps"] for r in rows] == [80.0, 120.0]
    assert all(r["good_frac"] == 0.9 for r in rows)
