"""Structured-grid detection and gather-free (implicit) transfer operators.

Covers ops/structured.py: grid detection from diagonal offsets, grid-aligned
strength-aware aggregation (semicoarsening), and the exactness of the
matrix-free smoothed transfers against the explicit host CSR P/R they
replace (the device path the TPU solve actually runs)."""

import numpy as np
import pytest
import scipy.sparse as sp

import jax
import jax.numpy as jnp

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.ops.structured import (
    detect_grid, detect_grid_csr, grid_aggregates, strength_blocks,
    GridTentative, AggTentative, build_implicit_transfers)
from amgcl_tpu.utils.sample_problem import poisson3d
from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.models.make_solver import make_solver
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.coarsening.smoothed_aggregation import SmoothedAggregation


def laplace2d(n, aniso=1.0):
    T = sp.diags([-np.ones(n - 1), 2 * np.ones(n), -np.ones(n - 1)],
                 [-1, 0, 1])
    A = (sp.kron(sp.identity(n), T)
         + aniso * sp.kron(T, sp.identity(n))).tocsr()
    return CSR.from_scipy(A)


class TestDetectGrid:
    def test_3d_7pt(self):
        A, _ = poisson3d(16)
        assert detect_grid_csr(A) == (16, 16, 16)

    def test_2d_5pt(self):
        assert detect_grid_csr(laplace2d(32)) == (1, 32, 32)

    def test_1d(self):
        assert detect_grid([-1, 0, 1], 100) == (1, 1, 100)

    def test_27pt(self):
        # 27-point stencil: offsets dx + 8*dy + 64*dz, |d*| <= 1
        offs = [dx + 8 * dy + 64 * dz
                for dx in (-1, 0, 1) for dy in (-1, 0, 1)
                for dz in (-1, 0, 1)]
        assert detect_grid(offs, 8 * 8 * 8) == (8, 8, 8)

    def test_one_sided(self):
        # upwind-style stencil: one-sided y and z couplings must not crash
        assert detect_grid([-400, -20, -1, 0, 1, 20], 8000) == (20, 20, 20)

    def test_unstructured_returns_none(self):
        rng = np.random.RandomState(0)
        offs = np.unique(rng.randint(-900, 900, 60))
        assert detect_grid(offs, 1000) is None

    def test_non_divisible_returns_none(self):
        # prime n: no stride candidate divides it
        assert detect_grid([-7, -1, 0, 1, 7], 53) is None


class TestGridAggregates:
    def test_ids_match_explicit(self):
        agg, n_agg, coarse, blocks = grid_aggregates((4, 6, 6))
        assert blocks == (2, 2, 2) and coarse == (2, 3, 3)
        assert n_agg == 18
        # spot-check: fine point (z,y,x) -> (z//2)*9 + (y//2)*3 + x//2
        idx = lambda z, y, x: z * 36 + y * 6 + x
        a = np.asarray(agg)
        assert a[idx(3, 5, 4)] == 1 * 9 + 2 * 3 + 2
        assert a[idx(0, 0, 0)] == 0

    def test_ragged_boundary(self):
        agg, n_agg, coarse, _ = grid_aggregates((1, 1, 5))
        assert coarse == (1, 1, 3) and n_agg == 3
        assert np.array_equal(np.asarray(agg), [0, 0, 1, 1, 2])

    def test_strength_semicoarsening(self):
        # y-coupling 1e-3: strength filter removes it; blocks must
        # semicoarsen (x only)
        A = laplace2d(16, aniso=1e-3)
        from amgcl_tpu.coarsening.smoothed_aggregation import _filtered
        Af, _ = _filtered(A, 0.08)
        assert strength_blocks(Af, (1, 16, 16)) == (1, 1, 2)

    def test_strength_blocks_isotropic(self):
        A, _ = poisson3d(12)
        from amgcl_tpu.coarsening.smoothed_aggregation import _filtered
        Af, _ = _filtered(A, 0.08)
        assert strength_blocks(Af, (12, 12, 12)) == (2, 2, 2)

    def test_no_strong_axis_falls_back(self):
        # pure diagonal matrix: nothing strong -> None (caller uses MIS)
        D = CSR.from_scipy(sp.identity(64, format="csr"))
        assert strength_blocks(D, (1, 8, 8)) is None


class TestTentativeOps:
    def test_grid_tentative_matches_csr(self):
        dims, blocks = (5, 7, 6), (2, 2, 2)
        agg, n_agg, coarse, _ = grid_aggregates(dims, blocks)
        T = GridTentative(dims, blocks, coarse)
        # explicit tentative P: all-ones entry (row, agg[row])
        n = int(np.prod(dims))
        P = sp.csr_matrix((np.ones(n), (np.arange(n), np.asarray(agg))),
                          shape=(n, n_agg))
        xc = np.random.RandomState(0).rand(n_agg)
        yf = np.random.RandomState(1).rand(n)
        np.testing.assert_allclose(np.asarray(T.mv(jnp.asarray(xc))),
                                   P @ xc, rtol=1e-12)
        np.testing.assert_allclose(np.asarray(T.rmv(jnp.asarray(yf))),
                                   P.T @ yf, rtol=1e-12)

    def test_grid_tentative_mxu_formulation_matches(self):
        """The TPU matmul route (0/1 pair-sum operators on the MXU)
        must agree with the explicit tentative P — including
        non-multiple extents and odd blocks. (Compared against the CSR
        ground truth, NOT against T.mv/rmv, which dispatch to this very
        route on TPU backends.)"""
        for dims, blocks in (((5, 7, 6), (2, 2, 2)),
                             ((8, 8, 8), (2, 2, 2)),
                             ((4, 9, 5), (1, 3, 2))):
            agg, n_agg, coarse, _ = grid_aggregates(dims, blocks)
            T = GridTentative(dims, blocks, coarse)
            n = int(np.prod(dims))
            P = sp.csr_matrix(
                (np.ones(n), (np.arange(n), np.asarray(agg))),
                shape=(n, n_agg))
            xc = np.random.RandomState(3).rand(n_agg)
            yf = np.random.RandomState(4).rand(n)
            np.testing.assert_allclose(
                np.asarray(T._mv_mxu(jnp.asarray(xc, jnp.float32))),
                P @ xc, rtol=1e-6)
            np.testing.assert_allclose(
                np.asarray(T._rmv_mxu(jnp.asarray(yf, jnp.float32))),
                P.T @ yf, rtol=1e-6)

    def test_agg_tentative_matches_csr(self):
        rng = np.random.RandomState(2)
        n, n_agg = 200, 37
        agg = rng.randint(0, n_agg, n)
        agg[rng.choice(n, 10, replace=False)] = -1   # excluded points
        # ensure every aggregate is nonempty
        agg[:n_agg] = np.arange(n_agg)
        T = AggTentative.build(agg, n_agg)
        rows = np.flatnonzero(agg >= 0)
        P = sp.csr_matrix((np.ones(len(rows)), (rows, agg[rows])),
                          shape=(n, n_agg))
        xc = rng.rand(n_agg)
        yf = rng.rand(n)
        np.testing.assert_allclose(np.asarray(T.mv(jnp.asarray(xc))),
                                   P @ xc, rtol=1e-12)
        np.testing.assert_allclose(np.asarray(T.rmv(jnp.asarray(yf))),
                                   P.T @ yf, rtol=1e-12)


class TestAggRmvAccuracy:
    def test_large_one_signed_prefix(self):
        """f32 prefix-sum differencing loses segment sums inside the global
        prefix magnitude at large n (tail segments exactly 0 at n~3e7);
        rmv must stay segment-local-accurate on one-signed input."""
        n, size = 2_000_000, 8
        n_agg = n // size
        agg = np.arange(n) // size
        T = AggTentative.build(agg, n_agg)
        y = np.full(n, 0.1, dtype=np.float32)
        out = np.asarray(T.rmv(jnp.asarray(y)))
        ref = np.full(n_agg, 0.1 * size)
        rel = np.abs(out - ref) / ref
        assert rel.max() < 1e-5

    def test_segment_sum_branch_matches(self):
        # exercise the no-x64 scatter-add branch explicitly
        import jax as _jax
        agg = np.arange(4000) // 7
        T = AggTentative.build(agg, -(-4000 // 7))
        y = np.random.RandomState(3).rand(4000).astype(np.float32)
        ref = np.asarray(T.rmv(jnp.asarray(y)))
        old = _jax.config.jax_enable_x64
        try:
            _jax.config.update("jax_enable_x64", False)
            out = np.asarray(T.rmv(jnp.asarray(y)))
        finally:
            _jax.config.update("jax_enable_x64", old)
        np.testing.assert_allclose(out, ref, rtol=1e-5)


class TestImplicitTransfers:
    @pytest.mark.parametrize("structured", [True, False])
    def test_matches_explicit_host_pr(self, structured):
        """Device P/R (implicit, matrix-free) must reproduce the host CSR
        P/R the Galerkin product was built from — exactly (same math,
        different composition)."""
        A, _ = poisson3d(16)
        prm = AMGParams(dtype=jnp.float64,
                        coarsening=SmoothedAggregation(structured=structured))
        amg = AMG(A, prm)
        hostP, hostR = amg.host_levels[0][1], amg.host_levels[0][2]
        if not hasattr(hostP, "spmv"):
            # stencil-setup path: the host transfers are implicit proxies;
            # the explicit CSR P/R to compare against come from the
            # SpGEMM route of the same configuration
            ref = AMG(poisson3d(16)[0], AMGParams(
                dtype=jnp.float64,
                coarsening=SmoothedAggregation(structured=structured,
                                               stencil_setup=False)))
            hostP, hostR = ref.host_levels[0][1], ref.host_levels[0][2]
        Pd = amg.hierarchy.levels[0].P
        Rd = amg.hierarchy.levels[0].R
        assert type(Pd).__name__ == "ImplicitSmoothedP"
        xc = np.random.RandomState(0).rand(hostP.ncols)
        yf = np.random.RandomState(1).rand(hostP.nrows)
        np.testing.assert_allclose(np.asarray(Pd.mv(jnp.asarray(xc))),
                                   hostP.spmv(xc), atol=1e-12)
        np.testing.assert_allclose(np.asarray(Rd.mv(jnp.asarray(yf))),
                                   hostR.spmv(yf), atol=1e-12)

    def test_under_jit_and_grad_free_pytree(self):
        A, _ = poisson3d(16)
        amg = AMG(A, AMGParams(dtype=jnp.float64))
        lv = amg.hierarchy.levels[0]
        f = jax.jit(lambda P, x: P.mv(x))
        xc = jnp.asarray(np.random.RandomState(0).rand(lv.P.shape[1]))
        np.testing.assert_allclose(np.asarray(f(lv.P, xc)),
                                   np.asarray(lv.P.mv(xc)), rtol=1e-12)


class TestEndToEnd:
    def test_isotropic_convergence(self):
        A, rhs = poisson3d(24)
        s = make_solver(A, AMGParams(dtype=jnp.float64), CG(tol=1e-8))
        x, info = s(rhs)
        tr = np.linalg.norm(rhs - A.spmv(np.asarray(x))) \
            / np.linalg.norm(rhs)
        assert tr < 1e-8 and info.iters <= 15

    def test_anisotropic_semicoarsening_beats_maxiter(self):
        # pre-fix this took 105 iterations (blind 2x2 boxing across the
        # weak axis); semicoarsening restores normal SA behavior
        A = laplace2d(48, aniso=1e-3)
        rhs = np.ones(A.nrows)
        s = make_solver(A, AMGParams(dtype=jnp.float64),
                        CG(tol=1e-8, maxiter=40))
        x, info = s(rhs)
        assert info.iters <= 20
        tr = np.linalg.norm(rhs - A.spmv(np.asarray(x))) \
            / np.linalg.norm(rhs)
        assert tr < 1e-8

    def test_structured_false_unchanged(self):
        A, rhs = poisson3d(16)
        s = make_solver(
            A, AMGParams(dtype=jnp.float64,
                         coarsening=SmoothedAggregation(
                             structured=False, implicit_transfers=False)),
            CG(tol=1e-8))
        x, info = s(rhs)
        assert info.resid < 1e-8

    def test_grid_hint_propagates(self):
        A, _ = poisson3d(16)
        amg = AMG(A, AMGParams(dtype=jnp.float64))
        # level-1 operator carries the coarse grid hint -> level-1
        # aggregation also went grid-aligned (its P is implicit + grid)
        A1 = amg.host_levels[1][0]
        assert getattr(A1, "_grid_dims", None) == (8, 8, 8)
