"""Smoother suite tests: each smoother inside the AMG-CG sweep + standalone
(as-preconditioner-style) behavior."""

import numpy as np
import jax.numpy as jnp
import pytest

from amgcl_tpu.models.make_solver import make_solver
from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.solver.bicgstab import BiCGStab
from amgcl_tpu.relaxation.jacobi import DampedJacobi
from amgcl_tpu.relaxation.spai0 import Spai0
from amgcl_tpu.relaxation.chebyshev import Chebyshev
from amgcl_tpu.relaxation.ilu0 import ILU0
from amgcl_tpu.utils.sample_problem import poisson3d, convection_diffusion_2d
from amgcl_tpu.ops import device as dev


@pytest.mark.parametrize("relax", [
    DampedJacobi(), Spai0(), Chebyshev(), ILU0(),
])
def test_amg_cg_with_each_smoother(relax):
    A, rhs = poisson3d(16)
    solve = make_solver(
        A, AMGParams(relax=relax, dtype=jnp.float64, coarse_enough=500),
        CG(maxiter=100, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8, type(relax).__name__
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-7


def test_chebyshev_damps_rough_error():
    """A smoother's job: strongly damp rough (random) error components."""
    A, _ = poisson3d(12)
    st = Chebyshev(degree=5).build(A, jnp.float64)
    Ad = dev.to_device(A, "auto", jnp.float64)
    e = np.random.RandomState(0).rand(A.nrows) - 0.5
    r = A.spmv(e)
    z = st.apply(Ad, jnp.asarray(r))
    assert np.linalg.norm(e - np.asarray(z)) < 0.35 * np.linalg.norm(e)


def test_ilu0_damps_rough_error():
    A, _ = poisson3d(8)
    st = ILU0(sweeps=8, jacobi_iters=4).build(A, jnp.float64)
    Ad = dev.to_device(A, "auto", jnp.float64)
    e = np.random.RandomState(1).rand(A.nrows) - 0.5
    r = A.spmv(e)
    z = st.apply(Ad, jnp.asarray(r))
    assert np.linalg.norm(e - np.asarray(z)) < 0.5 * np.linalg.norm(e)


def test_ilu0_bicgstab_convection():
    A, rhs = convection_diffusion_2d(24, eps=0.05)
    solve = make_solver(
        A, AMGParams(relax=ILU0(), dtype=jnp.float64),
        BiCGStab(maxiter=200, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8
