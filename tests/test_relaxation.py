"""Smoother suite tests: each smoother inside the AMG-CG sweep + standalone
(as-preconditioner-style) behavior."""

import numpy as np
import jax.numpy as jnp
import pytest

from amgcl_tpu.models.make_solver import make_solver
from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.solver.bicgstab import BiCGStab
from amgcl_tpu.relaxation.jacobi import DampedJacobi
from amgcl_tpu.relaxation.spai0 import Spai0
from amgcl_tpu.relaxation.chebyshev import Chebyshev
from amgcl_tpu.relaxation.ilu0 import ILU0
from amgcl_tpu.utils.sample_problem import poisson3d, convection_diffusion_2d
from amgcl_tpu.ops import device as dev


@pytest.mark.parametrize("relax", [
    DampedJacobi(), Spai0(), Chebyshev(), ILU0(),
])
def test_amg_cg_with_each_smoother(relax):
    A, rhs = poisson3d(16)
    solve = make_solver(
        A, AMGParams(relax=relax, dtype=jnp.float64, coarse_enough=500),
        CG(maxiter=100, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8, type(relax).__name__
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-7


def test_chebyshev_damps_rough_error():
    """A smoother's job: strongly damp rough (random) error components."""
    A, _ = poisson3d(12)
    st = Chebyshev(degree=5).build(A, jnp.float64)
    Ad = dev.to_device(A, "auto", jnp.float64)
    e = np.random.RandomState(0).rand(A.nrows) - 0.5
    r = A.spmv(e)
    z = st.apply(Ad, jnp.asarray(r))
    assert np.linalg.norm(e - np.asarray(z)) < 0.35 * np.linalg.norm(e)


def test_ilu0_damps_rough_error():
    A, _ = poisson3d(8)
    st = ILU0(sweeps=8, jacobi_iters=4).build(A, jnp.float64)
    Ad = dev.to_device(A, "auto", jnp.float64)
    e = np.random.RandomState(1).rand(A.nrows) - 0.5
    r = A.spmv(e)
    z = st.apply(Ad, jnp.asarray(r))
    assert np.linalg.norm(e - np.asarray(z)) < 0.5 * np.linalg.norm(e)


def test_ilu0_bicgstab_convection():
    A, rhs = convection_diffusion_2d(24, eps=0.05)
    solve = make_solver(
        A, AMGParams(relax=ILU0(), dtype=jnp.float64),
        BiCGStab(maxiter=200, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8


def test_gauss_seidel_multicolor():
    from amgcl_tpu.relaxation.gauss_seidel import GaussSeidel, greedy_coloring
    A, rhs = poisson3d(12)
    # iterated-MIS coloring stays within maxdegree+1 classes
    color = greedy_coloring(A.to_scipy())
    assert color.max() + 1 <= 8
    solve = make_solver(
        A, AMGParams(relax=GaussSeidel(), dtype=jnp.float64),
        CG(maxiter=100, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8


def test_spai1():
    from amgcl_tpu.relaxation.spai1 import Spai1
    A, rhs = poisson3d(12)
    solve = make_solver(
        A, AMGParams(relax=Spai1(), dtype=jnp.float64),
        CG(maxiter=100, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8
    # spai1 should smooth at least as well as spai0 (fewer or equal iters)
    solve0 = make_solver(
        A, AMGParams(relax=Spai0(), dtype=jnp.float64),
        CG(maxiter=100, tol=1e-8))
    _, info0 = solve0(rhs)
    assert info.iters <= info0.iters + 2


def test_ilup_widened_pattern():
    from amgcl_tpu.relaxation.ilu0 import ILUP
    A, rhs = convection_diffusion_2d(20, eps=0.05)
    solve = make_solver(
        A, AMGParams(relax=ILUP(p=1), dtype=jnp.float64),
        BiCGStab(maxiter=200, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8


def test_ilup_pattern_actually_widens():
    """Regression: scipy zero-pruning used to collapse ILUP's pattern back
    to A's, making ILUP == ILU0 silently."""
    from amgcl_tpu.relaxation.ilu0 import ILU0, ILUP
    A, _ = poisson3d(6)
    s0 = ILU0(sweeps=5).build(A, jnp.float64)
    s1 = ILUP(p=1, sweeps=5).build(A, jnp.float64)
    nnz0 = s0.Ls.bytes() + s0.Us.bytes()
    nnz1 = s1.Ls.bytes() + s1.Us.bytes()
    assert nnz1 > nnz0


def test_ilu0_block_matrix():
    """Regression: explicit zeros from unblock() used to crash the sweep."""
    from amgcl_tpu.utils.sample_problem import poisson3d_block
    A, rhs = poisson3d_block(6, 2)
    st = ILU0().build(A, jnp.float64)
    Ad = dev.to_device(A, "ell", jnp.float64)
    e = np.random.RandomState(2).rand(A.nrows * 2) - 0.5
    r = A.spmv(e)
    z = st.apply(Ad, jnp.asarray(r))
    assert np.linalg.norm(e - np.asarray(z)) < 0.9 * np.linalg.norm(e)


def test_ilut():
    from amgcl_tpu.relaxation.ilu0 import ILUT
    A, rhs = convection_diffusion_2d(20, eps=0.05)
    solve = make_solver(
        A, AMGParams(relax=ILUT(p=2, tau=1e-2), dtype=jnp.float64),
        BiCGStab(maxiter=200, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8


def test_as_block_wrapper():
    from amgcl_tpu.relaxation.as_block import AsBlock
    from amgcl_tpu.relaxation.spai1 import Spai1
    from amgcl_tpu.utils.sample_problem import poisson3d_block
    A, rhs = poisson3d_block(6, 2)
    solve = make_solver(
        A, AMGParams(relax=AsBlock(Spai1()), dtype=jnp.float64,
                     coarse_enough=100),
        CG(maxiter=200, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8


def test_iluk_level_of_fill():
    from amgcl_tpu.native import native_iluk_pattern, lib
    from amgcl_tpu.relaxation.ilu0 import ILUK
    from amgcl_tpu.utils.sample_problem import poisson3d
    A, rhs = poisson3d(8)
    if lib() is not None:
        # k=0 pattern must equal A's own pattern
        optr, ocol = native_iluk_pattern(A, 0)
        assert np.array_equal(optr, A.ptr)
        assert np.array_equal(ocol, A.col)
        # k=1 strictly widens it
        optr1, ocol1 = native_iluk_pattern(A, 1)
        assert optr1[-1] > optr[-1]
    solve = make_solver(
        A, AMGParams(relax=ILUK(k=1), dtype=jnp.float64, coarse_enough=200),
        CG(maxiter=100, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8
