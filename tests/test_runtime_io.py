"""Runtime config, IO round-trips, adapters, compositions, profiler, CLI."""

import json
import os

import numpy as np
import jax.numpy as jnp
import pytest

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.utils.sample_problem import poisson3d, poisson3d_complex
from amgcl_tpu.utils import io as aio
from amgcl_tpu.models.runtime import make_solver_from_config
from amgcl_tpu.models.block_solver import make_block_solver
from amgcl_tpu.models.deflated import deflated_solver
from amgcl_tpu.models.preconditioner import AsPreconditioner, \
    DummyPreconditioner
from amgcl_tpu.models.make_solver import make_solver
from amgcl_tpu.models.amg import AMGParams
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.relaxation.chebyshev import Chebyshev


def test_runtime_dotted_config():
    A, rhs = poisson3d(12)
    solve = make_solver_from_config(A, {
        "precond.coarsening.type": "smoothed_aggregation",
        "precond.relax.type": "chebyshev",
        "precond.dtype": "float64",
        "solver.type": "cg",
        "solver.tol": "1e-8",
        "solver.maxiter": "100",
    })
    x, info = solve(rhs)
    assert info.resid < 1e-8


def test_runtime_json_file(tmp_path):
    A, rhs = poisson3d(10)
    cfg = {"precond": {"relax": {"type": "damped_jacobi", "damping": 0.8},
                       "dtype": "float64"},
           "solver": {"type": "bicgstab", "tol": 1e-8}}
    p = tmp_path / "cfg.json"
    p.write_text(json.dumps(cfg))
    solve = make_solver_from_config(A, str(p))
    x, info = solve(rhs)
    assert info.resid < 1e-8


def test_runtime_relaxation_class():
    A, rhs = poisson3d(10)
    solve = make_solver_from_config(A, {
        "precond.class": "relaxation",
        "precond.relax.type": "ilu0",
        "precond.dtype": "float64",
        "solver.type": "cg", "solver.maxiter": 500, "solver.tol": 1e-8})
    x, info = solve(rhs)
    assert info.resid < 1e-8


def test_runtime_dummy_class():
    A, rhs = poisson3d(8)
    solve = make_solver_from_config(A, {
        "precond.class": "dummy", "precond.dtype": "float64",
        "solver.type": "cg", "solver.maxiter": 500, "solver.tol": 1e-8})
    x, info = solve(rhs)
    assert info.resid < 1e-8


def test_runtime_nested_precond():
    """precond.class=nested: a full inner Krylov (with its own nested
    preconditioner config) used as the outer preconditioner (reference:
    amgcl/preconditioner/runtime.hpp:147-158). The outer solver must be
    flexible since the inner solve is nonstationary."""
    from amgcl_tpu.models.runtime import make_solver_from_config
    A, rhs = poisson3d(10)
    s = make_solver_from_config(A, {
        "precond.class": "nested",
        "precond.solver.type": "cg",
        "precond.solver.maxiter": 4,
        "precond.solver.tol": 1e-2,
        "precond.precond.class": "amg",
        "precond.precond.dtype": "float64",
        "precond.precond.coarse_enough": 200,
        "solver.type": "fgmres",
        "solver.tol": 1e-8, "solver.maxiter": 100})
    x, info = s(rhs)
    r = np.linalg.norm(rhs - A.spmv(np.asarray(x))) / np.linalg.norm(rhs)
    assert r < 1e-7
    assert "nested" in repr(s)


def test_runtime_doubly_nested_precond():
    """nested-inside-nested exercises the recursion."""
    from amgcl_tpu.models.runtime import make_solver_from_config
    A, rhs = poisson3d(8)
    s = make_solver_from_config(A, {
        "precond.class": "nested",
        "precond.solver.type": "preonly",
        "precond.precond.class": "nested",
        "precond.precond.solver.type": "cg",
        "precond.precond.solver.maxiter": 3,
        "precond.precond.precond.class": "relaxation",
        "precond.precond.precond.relax.type": "spai0",
        "precond.precond.precond.dtype": "float64",
        "solver.type": "fgmres", "solver.tol": 1e-8})
    x, info = s(rhs)
    r = np.linalg.norm(rhs - A.spmv(np.asarray(x))) / np.linalg.norm(rhs)
    assert r < 1e-7


def test_runtime_schur_stokes():
    """Runtime-config Stokes solve: schur pressure correction whose U/P
    stages are themselves runtime-configured (the VERDICT round-1 ask)."""
    from amgcl_tpu.models.runtime import make_solver_from_config
    from tests.test_coupled import stokes_like
    A, pmask = stokes_like(10)
    rhs = np.ones(A.nrows)
    s = make_solver_from_config(A, {
        "precond.class": "schur",
        "precond.dtype": "float64",
        "precond.pmask": pmask,
        "precond.usolver.precond.dtype": "float64",
        "precond.usolver.precond.coarse_enough": 200,
        "precond.psolver.precond.dtype": "float64",
        "precond.psolver.solver.type": "cg",
        "precond.psolver.solver.maxiter": 4,
        "precond.psolver.solver.tol": 1e-2,
        "solver.type": "fgmres", "solver.tol": 1e-8,
        "solver.maxiter": 200})
    x, info = s(rhs)
    r = np.linalg.norm(rhs - A.spmv(np.asarray(x))) / np.linalg.norm(rhs)
    assert r < 1e-6


def test_runtime_schur_pmask_pattern():
    """Reference pmask_pattern strings: %start:stride / <m / >m."""
    from amgcl_tpu.models.runtime import _parse_pmask
    m = _parse_pmask({"pmask_pattern": "%3:4"}, 8)
    assert list(np.flatnonzero(m)) == [3, 7]
    m = _parse_pmask({"pmask_pattern": ">5"}, 8)
    assert list(np.flatnonzero(m)) == [5, 6, 7]
    m = _parse_pmask({"pmask_pattern": "<2"}, 8)
    assert list(np.flatnonzero(m)) == [0, 1]


def test_runtime_cpr_class():
    """Serial CPR selectable from config (precond.class=cpr)."""
    from amgcl_tpu.models.runtime import make_solver_from_config
    from tests.test_coupled import reservoir_like
    A, rhs = reservoir_like(6, 3)
    s = make_solver_from_config(A, {
        "precond.class": "cpr", "precond.dtype": "float64",
        "precond.pressure.dtype": "float64",
        "precond.pressure.coarse_enough": 100,
        "solver.type": "bicgstab", "solver.tol": 1e-8,
        "solver.maxiter": 200})
    x, info = s(rhs)
    assert info.resid < 1e-8


def test_runtime_cpr_drs_weighting():
    """precond.weighting=drs selects CPRDRS in the SERIAL runtime path
    (the distributed path honors the same keys)."""
    from amgcl_tpu.models.runtime import make_solver_from_config
    from tests.test_coupled import reservoir_like
    A, rhs = reservoir_like(6, 3)
    s = make_solver_from_config(A, {
        "precond.class": "cpr", "precond.dtype": "float64",
        "precond.weighting": "drs", "precond.eps_dd": 0.3,
        "precond.pressure.dtype": "float64",
        "solver.type": "bicgstab", "solver.tol": 1e-8,
        "solver.maxiter": 200})
    assert "drs" in repr(s)
    x, info = s(rhs)
    assert info.resid < 1e-8


def test_runtime_unknown_key_warns():
    A, _ = poisson3d(6)
    with pytest.warns(UserWarning, match="unknown parameter"):
        make_solver_from_config(A, {"solver.typo_field": 1,
                                    "precond.dtype": "float64"})


def test_runtime_unknown_type_raises():
    A, _ = poisson3d(6)
    with pytest.raises(ValueError, match="unknown solver"):
        make_solver_from_config(A, {"solver.type": "does_not_exist"})


def test_mm_roundtrip(tmp_path):
    A, _ = poisson3d(6)
    p = str(tmp_path / "a.mtx")
    aio.mm_write(p, A)
    B = aio.mm_read(p)
    assert np.allclose(B.to_dense(), A.to_dense())
    v = np.linspace(0, 1, 10)
    pv = str(tmp_path / "v.mtx")
    aio.mm_write(pv, v)
    assert np.allclose(np.asarray(aio.mm_read(pv)).ravel(), v)


def test_binary_roundtrip(tmp_path):
    A, rhs = poisson3d(6)
    p = str(tmp_path / "a.bin")
    aio.write_binary(p, A)
    B = aio.read_binary(p)
    assert np.allclose(B.to_dense(), A.to_dense())
    pv = str(tmp_path / "v.bin")
    aio.write_binary(pv, rhs)
    assert np.allclose(aio.read_binary(pv), rhs)


def test_reorder_adapter():
    from amgcl_tpu.utils.adapters import Reordered
    A, rhs = poisson3d(10)
    solve = Reordered(A, lambda M: make_solver(
        M, AMGParams(dtype=jnp.float64), CG(tol=1e-8)))
    x, info = solve(rhs)
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-7


def test_scaled_adapter():
    from amgcl_tpu.utils.adapters import Scaled
    A, rhs = poisson3d(10)
    solve = Scaled(A, lambda M: make_solver(
        M, AMGParams(dtype=jnp.float64), CG(tol=1e-8)))
    x, info = solve(rhs)
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-7


def test_complex_adapter():
    from amgcl_tpu.utils.adapters import complex_to_real, real_to_complex
    A, rhs = poisson3d_complex(8)
    Ar, rr = complex_to_real(A, rhs)
    solve = make_solver(Ar, AMGParams(dtype=jnp.float64),
                        CG(maxiter=300, tol=1e-10))
    y, info = solve(rr)
    x = real_to_complex(np.asarray(y))
    r = rhs - A.spmv(x)
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-7


def test_block_solver_scalar_io():
    A, rhs = poisson3d(8)
    solve = make_block_solver(A, 2, AMGParams(dtype=jnp.float64),
                              CG(maxiter=200, tol=1e-8))
    x, info = solve(rhs)
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-6


def test_deflated_solver():
    A, rhs = poisson3d(12)
    Z = np.ones((A.nrows, 1))
    solve = deflated_solver(A, Z, AMGParams(dtype=jnp.float64),
                            CG(maxiter=100, tol=1e-8))
    x, info = solve(rhs)
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-7


def test_as_preconditioner_and_dummy_repr():
    A, rhs = poisson3d(8)
    p1 = AsPreconditioner(A, Chebyshev(), jnp.float64)
    assert "chebyshev" in repr(p1).lower()
    p2 = DummyPreconditioner(A, jnp.float64)
    assert repr(p2) == "dummy"
    solve = make_solver(A, p1, CG(maxiter=300, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8


def test_binary_reference_raw_crs(tmp_path):
    """.bin files in the reference toolchain's headerless layout
    (amgcl/io/binary.hpp:70-122) load through read_binary."""
    import struct
    from amgcl_tpu.utils.io import read_binary
    A, _ = poisson3d(6)
    p = tmp_path / "ref.bin"
    with open(p, "wb") as f:
        f.write(struct.pack("<Q", A.nrows))
        f.write(A.ptr.astype(np.int64).tobytes())
        f.write(A.col.astype(np.int64).tobytes())
        f.write(A.val.astype(np.float64).tobytes())
    B = read_binary(str(p))
    assert B.nrows == A.nrows and B.nnz == A.nnz
    assert np.array_equal(B.col, A.col) and np.allclose(B.val, A.val)
    # garbage is still rejected with a clear error
    bad = tmp_path / "junk.bin"
    bad.write_bytes(b"\x01\x02\x03\x04" * 10)
    with pytest.raises(ValueError, match="neither"):
        read_binary(str(bad))


def test_cg_ns_search():
    """ns_search keeps iterating on a zero rhs from a nonzero x0 — the
    iterate approaches a null-space vector (reference cg.hpp:90,163)."""
    import scipy.sparse as sp
    from amgcl_tpu.ops.csr import CSR
    from amgcl_tpu.models.make_solver import make_solver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.cg import CG
    # singular: 1-D Neumann Laplacian (nullspace = constants)
    n = 64
    T = sp.diags([-np.ones(n - 1), 2 * np.ones(n), -np.ones(n - 1)],
                 [-1, 0, 1]).tolil()
    T[0, 0] = 1.0
    T[-1, -1] = 1.0
    A = CSR.from_scipy(T.tocsr())
    s = make_solver(A, AMGParams(dtype=jnp.float64, coarse_enough=32),
                    CG(maxiter=200, tol=1e-10, ns_search=True))
    x0 = np.random.RandomState(0).rand(n)
    x, info = s(np.zeros(n), x0=x0)
    x = np.asarray(x)
    assert np.linalg.norm(x) > 1e-8            # did NOT collapse to zero
    # normalized iterate is (close to) the constant null-space vector
    v = x / np.linalg.norm(x)
    assert np.std(v) < 1e-4 * np.abs(v).mean() + 1e-6


def test_gmres_right_side():
    """pside='right' converges and reports the UNpreconditioned residual
    (right preconditioning does not change the residual norm)."""
    from amgcl_tpu.models.make_solver import make_solver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.gmres import GMRES
    A, rhs = poisson3d(10)
    s = make_solver(A, AMGParams(dtype=jnp.float64),
                    GMRES(M=20, maxiter=100, tol=1e-8, pside="right"))
    x, info = s(rhs)
    r = np.linalg.norm(rhs - A.spmv(np.asarray(x))) / np.linalg.norm(rhs)
    assert r < 1e-7
    with pytest.raises(ValueError, match="pside"):
        GMRES(pside="middle").solve(None, None, jnp.zeros(4))


def test_profiler_aggregate():
    """mpi_aggregator equivalent: min/avg/max of scope totals across
    profilers (amgcl/perf_counter/mpi_aggregator.hpp:43-123)."""
    import time as _time
    from amgcl_tpu.utils.profiler import Profiler, aggregate, \
        format_aggregate
    ps = []
    for d in (0.001, 0.003):
        p = Profiler()
        with p.scope("setup"):
            _time.sleep(d)
            with p.scope("inner"):
                _time.sleep(d)
        ps.append(p)
    agg = aggregate(ps)
    mn, av, mx = agg["setup"]
    assert mn <= av <= mx and mn > 0
    assert "setup/inner" in agg
    out = format_aggregate(agg)
    assert "min" in out and "setup" in out


def test_profiler_tree():
    from amgcl_tpu.utils.profiler import Profiler
    prof = Profiler()
    with prof.scope("a"):
        with prof.scope("b"):
            pass
    with pytest.raises(RuntimeError):
        prof.tic("x")
        prof.toc("y")
    s = str(Profiler())
    assert "[total]" in s


def test_cli_poisson(capsys, tmp_path):
    from amgcl_tpu.cli import main
    out = str(tmp_path / "x.mtx")
    rc = main(["-n", "10", "-p", "precond.dtype=float64",
               "-p", "solver.type=cg", "-p", "solver.tol=1e-8",
               "-o", out, "--reorder"])
    assert rc == 0
    cap = capsys.readouterr().out
    assert "Iterations:" in cap and "Error:" in cap
    x = np.asarray(aio.mm_read(out)).ravel()
    A, rhs = poisson3d(10)
    r = rhs - A.spmv(x)
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-7


def test_binary_block_roundtrip(tmp_path):
    """Regression: block val arrays used to be flattened on write."""
    A, _ = poisson3d(6)
    B = A.to_block(2)
    p = str(tmp_path / "b.bin")
    aio.write_binary(p, B)
    C = aio.read_binary(p)
    assert C.is_block and C.block_size == (2, 2)
    assert np.allclose(C.unblock().to_dense(), A.to_dense())


def test_deflated_does_not_mutate_precond():
    """Regression: deflated_solver used to rebind the caller's hierarchy."""
    from amgcl_tpu.models.amg import AMG
    A, rhs = poisson3d(10)
    amg = AMG(A, AMGParams(dtype=jnp.float64))
    h0 = amg.hierarchy
    d1 = deflated_solver(A, np.ones((A.nrows, 1)), amg, CG(tol=1e-8))
    assert amg.hierarchy is h0
    x, info = d1(rhs)
    assert info.resid < 1e-8


def test_cli_block_size_respects_params(capsys, tmp_path):
    from amgcl_tpu.cli import main
    rc = main(["-n", "8", "-b", "2", "-p", "precond.dtype=float64",
               "-p", "solver.type=cg", "-p", "solver.tol=1e-10"])
    assert rc == 0
    cap = capsys.readouterr().out
    assert "CG" in cap
    err = float(cap.split("Error:")[1].split()[0])
    assert err < 1e-10


def test_pyamgcl_compat_surface():
    """Drop-in pyamgcl-style usage with the REFERENCE calling shapes
    (reference: pyamgcl/__init__.py + tests/test_pyamgcl.py): solver takes
    a prebuilt amgcl preconditioner and flat solver params; solve(rhs) and
    solve(A_new, rhs) both work."""
    import amgcl_tpu.pyamgcl_compat as pyamgcl
    import scipy.sparse.linalg as spla
    A, rhs = poisson3d(10)
    P = pyamgcl.amgcl(A.to_scipy(), {"dtype": "float64"})
    assert P.shape == (A.nrows, A.nrows)
    s = pyamgcl.solver(P, {"type": "cg", "tol": 1e-8})
    x = s(rhs)
    assert s.iterations > 0 and s.error < 1e-8
    r = rhs - A.spmv(x)
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-7
    # two-arg form: new matrix, same preconditioner
    A2 = CSR(A.ptr.copy(), A.col.copy(), 1.1 * A.val, A.ncols)
    x2 = s(A2, rhs)
    r2 = rhs - A2.spmv(x2)
    assert np.linalg.norm(r2) / np.linalg.norm(rhs) < 1e-7
    # preconditioner alone, as a scipy LinearOperator inside scipy's CG
    M = pyamgcl.amgcl(A.to_scipy(), {"dtype": "float64"})
    xs, ok = spla.cg(A.to_scipy(), rhs, M=M.aslinearoperator(),
                     rtol=1e-8, maxiter=100)
    assert ok == 0
