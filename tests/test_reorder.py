"""Executed reorder seam (ISSUE 20): the advisor's RCM/CM permutation is
APPLIED at build time — hierarchy + transfers absorb it, rhs/x0 are
permuted in and x un-permuted out — and must be semantically invisible:
solution parity in f64, batched (n, B) pass-through, rebuild/farm plan
reuse through the fingerprint cache, ledger-driven format winners
flipping on the permuted-banded fixture, and gather-SpMV agreement with
its XLA fallback."""

import json
import os

import numpy as np
import jax.numpy as jnp
import pytest

from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.models.make_solver import make_solver
from amgcl_tpu.ops import device as dev
from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.ops import pallas_gather as pg
from amgcl_tpu.ops.unstructured import csr_to_windowed_ell
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.telemetry import structure as st


def _fixture(n=512, bw=4, seed=0):
    A, A0, perm = st.permuted_banded(n, bw=bw, seed=seed)
    rng = np.random.RandomState(seed + 1)
    return A, A0, rng.rand(n)


# -- the plan and its fingerprint cache --------------------------------------

def test_reorder_plan_shape_and_cache(monkeypatch):
    monkeypatch.setenv("AMGCL_TPU_REORDER", "rcm")
    A, _, _ = _fixture()
    p1 = st.reorder_plan(A)
    assert p1 is not None
    n = A.nrows
    assert sorted(p1["perm"].tolist()) == list(range(n))
    np.testing.assert_array_equal(p1["iperm"][p1["perm"]], np.arange(n))
    assert p1["variant"] == "rcm"
    assert p1["fingerprint"] == st.fingerprint(A)
    assert p1["val_perm"].shape == (A.val.size,)
    # same pattern, fresh object -> SAME plan object (fingerprint keyed)
    B = CSR(A.ptr, A.col, A.val * 3.0, A.ncols)
    assert st.reorder_plan(B) is p1


def test_reorder_off_and_identity_decline(monkeypatch):
    monkeypatch.setenv("AMGCL_TPU_REORDER", "0")
    A, A0, _ = _fixture()
    assert st.reorder_plan(A) is None
    # auto declines the already-banded matrix: no predicted gain
    monkeypatch.setenv("AMGCL_TPU_REORDER", "auto")
    assert st.reorder_plan(A0) is None
    # ...but takes the scrambled one
    plan = st.reorder_plan(A)
    assert plan is not None and plan["predicted_gain"] >= st.GAIN_FLOOR


# -- solution parity through the solver seam ---------------------------------

def _solve(A, rhs, mode, monkeypatch, **kw):
    monkeypatch.setenv("AMGCL_TPU_REORDER", mode)
    s = make_solver(A, AMGParams(dtype=jnp.float64),
                    CG(maxiter=200, tol=1e-12), **kw)
    x, info = s(rhs)
    return s, np.asarray(x, np.float64), info


def test_solution_parity_f64(monkeypatch):
    A, _, rhs = _fixture()
    s_id, x_id, i_id = _solve(A, rhs, "0", monkeypatch)
    s_r, x_r, i_r = _solve(A, rhs, "rcm", monkeypatch)
    assert s_id.precond._reorder is None
    assert s_r.precond._reorder is not None
    # permutation changes reduction orders, so parity is to machine
    # precision (documented in DESIGN §21), not bit-for-bit
    np.testing.assert_allclose(x_r, x_id, rtol=1e-9, atol=1e-12)
    assert abs(int(i_r.iters) - int(i_id.iters)) <= 2
    # the residual reported is for the ORIGINAL-order system
    r = rhs - A.spmv(x_r)
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-10


def test_batched_rhs_passthrough(monkeypatch):
    A, _, _ = _fixture()
    rng = np.random.RandomState(9)
    Rhs = rng.rand(A.nrows, 3)
    s_id, X_id, _ = _solve(A, Rhs, "0", monkeypatch)
    s_r, X_r, _ = _solve(A, Rhs, "rcm", monkeypatch)
    assert X_r.shape == Rhs.shape
    np.testing.assert_allclose(X_r, X_id, rtol=1e-9, atol=1e-12)


# -- rebuild / farm reuse ----------------------------------------------------

def test_rebuild_reuses_plan_values_only(monkeypatch):
    """AMG-level values-only rebuild: callers hand back values in the
    ORIGINAL ordering (time-dependent loops never learn about the
    permutation); val_perm maps them into the permuted frame the
    hierarchy lives in, and the cached plan survives the refresh."""
    monkeypatch.setenv("AMGCL_TPU_REORDER", "rcm")
    A, _, _ = _fixture()
    amg = AMG(A, AMGParams(dtype=jnp.float64))
    plan = amg._reorder
    assert plan is not None
    amg.rebuild(A.val * 2.0)
    assert amg._reorder is plan                # no recompute
    hl0 = amg.host_levels[0][0]
    np.testing.assert_array_equal(
        np.asarray(hl0.val),
        np.asarray(A.val)[plan["val_perm"]] * 2.0)


def test_rebuild_accepts_original_order_csr(monkeypatch):
    A, _, rhs = _fixture()
    s, x1, _ = _solve(A, rhs, "rcm", monkeypatch)
    plan = s.precond._reorder
    A2 = CSR(A.ptr, A.col, A.val * 2.0, A.ncols)
    s.rebuild(A2)
    assert s.precond._reorder is plan
    x2, _ = s(rhs)
    np.testing.assert_allclose(np.asarray(x2), x1 / 2.0,
                               rtol=1e-9, atol=1e-12)


def test_same_pattern_builds_share_plan(monkeypatch):
    """The farm/registry reuse path: a re-registration of a same-pattern
    operator finds the permutation already computed (module cache keyed
    by the SAME fingerprint serve/registry.py uses)."""
    monkeypatch.setenv("AMGCL_TPU_REORDER", "rcm")
    A, _, _ = _fixture()
    B = CSR(A.ptr, A.col, A.val * 5.0, A.ncols)
    amg1 = AMG(A, AMGParams(dtype=jnp.float64))
    amg2 = AMG(B, AMGParams(dtype=jnp.float64))
    assert amg1._reorder is not None
    assert amg2._reorder is amg1._reorder


def test_release_readmit_roundtrip(monkeypatch):
    A, _, rhs = _fixture()
    s, x1, _ = _solve(A, rhs, "rcm", monkeypatch)
    s.release_device()
    s.readmit()
    x2, _ = s(rhs)
    np.testing.assert_allclose(np.asarray(x2), x1, rtol=1e-9,
                               atol=1e-12)


# -- ledger-driven auto-format ----------------------------------------------

def test_decision_winner_flips_on_reorder(monkeypatch):
    """On the permuted-banded fixture the identity layout cannot pack
    diagonals (thousands of them) while the reordered one is a clean
    band: the ledger-ranked auto pick flips format and the chosen
    layout's predicted bytes drop."""
    from amgcl_tpu.utils.adapters import permute
    A, _, _ = st.permuted_banded(4096, bw=4, seed=0)
    plan = st.reorder_plan(A, mode="rcm")
    Ar = permute(A, plan["perm"])
    M_id = dev.to_device(A, "auto", jnp.float64)
    M_r = dev.to_device(Ar, "auto", jnp.float64)
    d_id, d_r = M_id._format_decision, M_r._format_decision
    assert d_r["fmt"] != d_id["fmt"]

    def _pred(dec):
        row = [c for c in dec["candidates"]
               if c["format"] == dec["fmt"]][0]
        return row["predicted"]["bytes"]

    assert _pred(d_r) < _pred(d_id)


def test_decision_records_reorder_provenance(monkeypatch):
    monkeypatch.setenv("AMGCL_TPU_REORDER", "rcm")
    A, _, _ = _fixture(n=1024)
    amg = AMG(A, AMGParams(dtype=jnp.float64))
    decs = amg._format_decisions
    assert decs, "level decisions missing"
    prov = decs[0].get("reorder")
    assert prov and prov["variant"] == "rcm"
    assert prov["fingerprint"] == st.fingerprint(A)


# -- gather-SpMV kernel vs XLA fallback --------------------------------------

@pytest.mark.parametrize("dtype", [jnp.float32, jnp.float64])
def test_gather_spmv_agreement_interpret(dtype):
    _, A0, _ = _fixture(n=2048)
    W = csr_to_windowed_ell(A0, dtype)
    assert W is not None and W.block == (1, 1)
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.rand(A0.ncols), dtype)
    y_ref = np.asarray(pg.gather_spmv_xla(
        W.window_starts, W.cols_local, W.vals, x, W.shape[0]))
    y = np.asarray(pg.gather_spmv(
        W.window_starts, W.cols_local, W.vals, x, W.win, W.shape[0],
        interpret=True))
    tol = 1e-12 if dtype == jnp.float64 else 1e-5
    np.testing.assert_allclose(y, y_ref, rtol=tol,
                               atol=tol * np.abs(y_ref).max())
    # and both against the host truth
    y_host = A0.spmv(np.asarray(x, np.float64))
    np.testing.assert_allclose(
        y_ref, y_host, rtol=1e-4 if dtype == jnp.float32 else 1e-12)


def test_gather_dispatch_and_kill_switch(monkeypatch):
    _, A0, _ = _fixture(n=2048)
    W = csr_to_windowed_ell(A0, jnp.float32)
    x = jnp.asarray(np.random.RandomState(3).rand(A0.ncols), jnp.float32)
    monkeypatch.setenv("AMGCL_TPU_GATHER_KERNEL", "0")
    assert pg.maybe_gather_spmv(W, x) is None
    monkeypatch.setenv("AMGCL_TPU_GATHER_KERNEL", "auto")
    monkeypatch.setenv("AMGCL_TPU_PALLAS_INTERPRET", "1")
    y = pg.maybe_gather_spmv(W, x)
    assert y is not None
    y_ref = np.asarray(pg.gather_spmv_xla(
        W.window_starts, W.cols_local, W.vals, x, W.shape[0]))
    np.testing.assert_allclose(np.asarray(y), y_ref, rtol=1e-5,
                               atol=1e-5 * np.abs(y_ref).max())
    # mv() rides the same seam end to end
    y_mv = np.asarray(W.mv(x))
    np.testing.assert_allclose(y_mv, y_ref, rtol=1e-5,
                               atol=1e-5 * np.abs(y_ref).max())


@pytest.mark.skipif(
    __import__("jax").default_backend() != "tpu",
    reason="compiled gather kernel needs a real TPU")
def test_gather_spmv_agreement_compiled():
    _, A0, _ = _fixture(n=4096)
    W = csr_to_windowed_ell(A0, jnp.float32)
    assert pg.gather_kernel_supported(W.win, W.cols_local.shape[2],
                                      W.dtype)
    x = jnp.asarray(np.random.RandomState(4).rand(A0.ncols), jnp.float32)
    y = np.asarray(pg.gather_spmv(
        W.window_starts, W.cols_local, W.vals, x, W.win, W.shape[0],
        interpret=False))
    y_ref = np.asarray(pg.gather_spmv_xla(
        W.window_starts, W.cols_local, W.vals, x, W.shape[0]))
    np.testing.assert_allclose(y, y_ref, rtol=1e-5,
                               atol=1e-5 * np.abs(y_ref).max())


# -- flight-recorder replay parity under reorder -----------------------------

def test_replay_parity_reordered(monkeypatch, tmp_path):
    """A bundle dumped from a reordered solve replays with identical
    layout: provenance (fingerprint + advisor variant) is in the
    manifest and parity holds on the same platform."""
    from amgcl_tpu.telemetry import flight
    flight._reset_for_tests()
    monkeypatch.setenv("AMGCL_TPU_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("AMGCL_TPU_REORDER", "rcm")
    A, _, rhs = _fixture()
    s = make_solver(A, AMGParams(dtype=jnp.float64),
                    CG(maxiter=200, tol=1e-12))
    x, info = s(rhs)
    assert s.precond._reorder is not None
    path = flight.dump("reorder_parity", bundle=s, rhs=rhs,
                       report=info)
    assert path
    with open(os.path.join(path, "manifest.json")) as f:
        man = json.load(f)
    prov = man.get("reorder")
    assert prov and prov["variant"] == "rcm"
    assert prov["fingerprint"] == st.fingerprint(A)
    result = flight.run_replay(path)
    assert result["ok"], result
    rows = {c["check"]: c for c in result["parity"]["checks"]}
    assert rows["iters"]["status"] == "ok"
    assert rows["resid"]["status"] == "ok"
    flight._reset_for_tests()
