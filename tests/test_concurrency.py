"""Concurrency contract analyzer + runtime lock witness (ISSUE 15):
the four negative-injection fixtures (each rule must FIRE on an
injected bug and stay quiet on the disciplined variant), the repo's
own clean bill against the committed baseline, the witnessed-⊆-static
validation loop, and targeted regressions for the true positives the
analyzer surfaced in-tree (stranded futures resolved under _mem_lock,
timeout/failed-batch stats committed after the futures resolved, the
flight-ring append outside its lock)."""

import json
import os
import shutil
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from amgcl_tpu.analysis import concurrency
from amgcl_tpu.analysis import lockwitness as lw
from amgcl_tpu.analysis.lint import format_findings

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fixture(tmp_path, src, name="mod.py"):
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    (pkg / name).write_text(textwrap.dedent(src))
    return concurrency.run_concurrency(root=str(pkg), modules=(name,))


def _rules(findings):
    return sorted({f["rule"] for f in findings})


# ===========================================================================
# negative-injection fixtures — one per analysis
# ===========================================================================

def test_lock_order_inversion_fires(tmp_path):
    """An acquisition order inverted against the declared LOCK_ORDER
    is a finding (and the union graph reports the cycle)."""
    fs = _fixture(tmp_path, """
        import threading

        LOCK_ORDER = (("_a", "_b"),)

        class S:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def good(self):
                with self._a:
                    with self._b:
                        pass

            def bad(self):
                with self._b:
                    with self._a:
                        pass
    """)
    hits = [f for f in fs if f["rule"] == "lock-order"]
    assert any(f["symbol"] == "mod._b->mod._a" for f in hits), \
        format_findings(fs)
    # the declared direction stays quiet
    assert not any(f["symbol"] == "mod._a->mod._b" for f in hits)
    # both directions observed = a reachable deadlock cycle
    assert any("cycle" in f["message"] for f in fs)


def test_guarded_by_unguarded_thread_write_fires(tmp_path):
    """A field dominantly written under a lock, written lock-free from
    a Thread-target call tree — the PR-8/PR-13 race shape."""
    fs = _fixture(tmp_path, """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._count = 0

            def start(self):
                threading.Thread(target=self._work).start()

            def bump(self):
                with self._lock:
                    self._count += 1

            def drain(self):
                with self._lock:
                    self._count = 0

            def _work(self):
                self._count += 1
    """)
    hits = [f for f in fs if f["rule"] == "guarded-by"]
    assert len(hits) == 1 and hits[0]["symbol"] == "S._count", \
        format_findings(fs)
    assert "mod._lock" in hits[0]["message"]


def test_guarded_by_respects_declared_allowlist(tmp_path):
    """The same bug with the field declared UNGUARDED_OK (with a
    reason) is accepted — the allowlist is the contract seam."""
    fs = _fixture(tmp_path, """
        import threading

        UNGUARDED_OK = {"_count": "single-writer probe counter"}

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._count = 0

            def start(self):
                threading.Thread(target=self._work).start()

            def bump(self):
                with self._lock:
                    self._count += 1

            def drain(self):
                with self._lock:
                    self._count = 0

            def _work(self):
                self._count += 1
    """)
    assert [f for f in fs if f["rule"] == "guarded-by"] == []


def test_cv_wait_without_predicate_loop_fires(tmp_path):
    fs = _fixture(tmp_path, """
        import threading

        class S:
            def __init__(self):
                self._cond = threading.Condition()
                self._ready = False

            def consume_bad(self):
                with self._cond:
                    self._cond.wait(timeout=1.0)
                    return self._ready

            def consume_good(self):
                with self._cond:
                    while not self._ready:
                        self._cond.wait(timeout=1.0)
                    return self._ready

            def consume_wait_for(self):
                with self._cond:
                    self._cond.wait_for(lambda: self._ready)
                    return self._ready
    """)
    hits = [f for f in fs if f["rule"] == "cv-discipline"]
    assert {f["symbol"] for f in hits} == {"S.consume_bad"}, \
        format_findings(fs)


def test_cv_notify_on_lock_free_path_fires(tmp_path):
    fs = _fixture(tmp_path, """
        import threading

        class S:
            def __init__(self):
                self._cond = threading.Condition()

            def wake_bad(self):
                self._cond.notify_all()

            def wake_good(self):
                with self._cond:
                    self._cond.notify_all()

            def _wake_locked(self):
                # lexically lock-free but only ever CALLED under the
                # lock — the propagated held-set accepts it
                self._cond.notify_all()

            def wake_via_helper(self):
                with self._cond:
                    self._wake_locked()
    """)
    hits = [f for f in fs if f["rule"] == "cv-discipline"]
    assert {f["symbol"] for f in hits} == {"S.wake_bad"}, \
        format_findings(fs)


def test_handoff_set_result_under_lock_fires(tmp_path):
    fs = _fixture(tmp_path, """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def finish_bad(self, fut, value):
                with self._lock:
                    fut.set_result(value)

            def finish_good(self, fut, value):
                with self._lock:
                    pass
                fut.set_result(value)
    """)
    hits = [f for f in fs if f["rule"] == "handoff-discipline"]
    assert {f["symbol"] for f in hits} == {"S.finish_bad"}, \
        format_findings(fs)


def test_handoff_resolve_before_locked_commit_fires(tmp_path):
    """The resolve-last discipline: a future resolved before a later
    locked stats commit in the same function is the PR-11 bug shape."""
    fs = _fixture(tmp_path, """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def complete_bad(self, fut, value):
                fut.set_result(value)
                with self._lock:
                    self._n += 1

            def complete_good(self, fut, value):
                with self._lock:
                    self._n += 1
                fut.set_result(value)
    """)
    hits = [f for f in fs if f["rule"] == "handoff-discipline"]
    assert {f["symbol"] for f in hits} == {"S.complete_bad"}, \
        format_findings(fs)


def test_blocking_call_under_lock_fires(tmp_path):
    """Rule 4's blocking leg: a sleep / timeout-less queue get inside
    a lock-held region (Condition.wait stays exempt)."""
    fs = _fixture(tmp_path, """
        import queue
        import threading
        import time

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.queue = queue.Queue()

            def bad_sleep(self):
                with self._lock:
                    time.sleep(0.5)

            def bad_get(self):
                with self._lock:
                    return self.queue.get()

            def good_get(self):
                with self._lock:
                    return self.queue.get(timeout=0.1)
    """)
    hits = [f for f in fs if f["rule"] == "handoff-discipline"]
    assert {f["symbol"] for f in hits} == {"S.bad_sleep", "S.bad_get"}, \
        format_findings(fs)


def test_reacquire_plain_lock_is_self_deadlock(tmp_path):
    fs = _fixture(tmp_path, """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._rlock = threading.RLock()

            def bad(self):
                with self._lock:
                    with self._lock:
                        pass

            def fine(self):
                with self._rlock:
                    with self._rlock:
                        pass
    """)
    hits = [f for f in fs if f["rule"] == "lock-order"]
    assert len(hits) == 1 and "self-deadlock" in hits[0]["message"], \
        format_findings(fs)
    assert hits[0]["symbol"] == "S.bad"


def test_flight_ring_regression_shape_fires(tmp_path):
    """The exact in-tree bug the analyzer surfaced (flight.py ring
    append outside the module lock) stays detectable on module-global
    state."""
    fs = _fixture(tmp_path, """
        import threading
        from collections import deque

        _lock = threading.Lock()
        _ring: deque = deque(maxlen=8)

        def record(item):
            _ring.append(item)

        def reset():
            with _lock:
                _ring.clear()
    """)
    hits = [f for f in fs if f["rule"] == "guarded-by"]
    assert len(hits) == 1 and hits[0]["symbol"] == "<module>._ring", \
        format_findings(fs)


# ===========================================================================
# the repo's own clean bill + the exit-1 flip
# ===========================================================================

def test_repo_concurrency_clean_against_committed_baseline():
    """Acceptance: the analyzer runs over the declared module set with
    zero NEW findings against ANALYSIS_BASELINE.json, and every
    suppression carries a non-empty reason."""
    from amgcl_tpu import analysis
    split = analysis.apply_baseline(
        analysis.run_lint() + concurrency.run_concurrency(),
        analysis.load_baseline())
    assert split["new"] == [], format_findings(split["new"])
    assert split["stale"] == [], split["stale"]
    for s in (analysis.load_baseline() or {}).get("suppressions", []):
        assert s.get("reason", "").strip(), \
            "unexplained suppression: %r" % (s,)


def test_declared_contracts_live_next_to_the_code():
    """LOCK_ORDER / UNGUARDED_OK are declared in serve/farm.py and
    serve/service.py and the analyzer parses them."""
    from amgcl_tpu.serve import farm, service
    assert ("_mem_lock", "_cond") in farm.LOCK_ORDER
    assert service.LOCK_ORDER == ()
    assert "_thread" in service.UNGUARDED_OK
    assert all(v.strip() for v in farm.UNGUARDED_OK.values())
    assert all(v.strip() for v in service.UNGUARDED_OK.values())
    graph = concurrency.static_lock_graph()
    assert ["farm._mem_lock", "farm._cond"] in \
        [list(e) for e in graph["allowed"]]
    # every utility lock the witness can see must derive as a leaf —
    # losing one (e.g. a seam-wrapped constructor the discovery stops
    # recognizing) turns legal runtime edges into violations
    for leaf in ("live._lock", "sink._lock", "tracing._lock",
                 "flight._lock", "recovery._lock", "inject._lock",
                 "service._lock"):
        assert leaf in graph["leaves"], (leaf, graph["leaves"])


def test_negative_injections_flip_gate_to_exit_1(tmp_path):
    """Acceptance: each of the four negative injections — a lock-order
    inversion, an unguarded field write, a bare wait() outside a
    predicate loop, a set_result under a lock — planted in a copy of
    the tree flips `python -m amgcl_tpu.analysis` to exit 1 with the
    expected (rule, file, symbol) finding."""
    dst = tmp_path / "amgcl_tpu"
    shutil.copytree(os.path.join(_REPO, "amgcl_tpu"), dst,
                    ignore=shutil.ignore_patterns("__pycache__"))
    farm = dst / "serve" / "farm.py"
    farm.write_text(farm.read_text() + textwrap.dedent("""

    def _injected_inversion(self):
        with self._cond:
            with self._mem_lock:
                pass


    def _injected_bare_wait(self):
        with self._mem_lock:
            self._mem_cond.wait(timeout=0.1)


    def _injected_resolve_under_lock(self, fut):
        with self._mem_lock:
            fut.set_result(None)
    """))
    service = dst / "serve" / "service.py"
    service.write_text(service.read_text() + textwrap.dedent("""

    def _injected_unguarded_write(self):
        self._n_timeouts += 1
    """))
    r = subprocess.run(
        [sys.executable, "-m", "amgcl_tpu.analysis", "--no-audit",
         "--json", "--root", str(dst)],
        capture_output=True, text=True, timeout=300, cwd=_REPO,
        env=dict(os.environ))
    assert r.returncode == 1, r.stdout + r.stderr
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    keys = {(f["rule"], f["file"], f["symbol"])
            for f in rec["concurrency"]["new"]}
    farm_rel = "amgcl_tpu/serve/farm.py"
    service_rel = "amgcl_tpu/serve/service.py"
    assert ("lock-order", farm_rel,
            "farm._cond->farm._mem_lock") in keys, keys
    assert ("cv-discipline", farm_rel, "_injected_bare_wait") in keys
    assert ("handoff-discipline", farm_rel,
            "_injected_resolve_under_lock") in keys
    assert ("guarded-by", service_rel,
            "SolverService._n_timeouts") in keys


# ===========================================================================
# runtime lock witness
# ===========================================================================

@pytest.fixture
def witness(monkeypatch):
    monkeypatch.setenv("AMGCL_TPU_LOCK_WITNESS", "1")
    lw._reset_for_tests()
    yield lw
    lw._reset_for_tests()


def test_witness_off_is_identity(monkeypatch):
    monkeypatch.delenv("AMGCL_TPU_LOCK_WITNESS", raising=False)
    raw = threading.Lock()
    assert lw.maybe_wrap("x._l", raw) is raw


def test_witness_records_edges_and_checks_subset(witness):
    a = lw.maybe_wrap("wt._a", threading.Lock())
    b = lw.maybe_wrap("wt._b", threading.Lock())
    with a:
        with b:
            pass
    with a:        # second visit: count bumps, edge set stays 1
        with b:
            pass
    snap = lw.report()
    assert snap["edges"] == [
        {"src": "wt._a", "dst": "wt._b", "count": 2}]
    assert snap["holds"]["wt._a"]["count"] == 2
    ok = lw.check_witness(
        graph={"allowed": [("wt._a", "wt._b")], "leaves": []},
        snapshot=snap)
    assert ok["ok"] and ok["violations"] == []
    bad = lw.check_witness(graph={"allowed": [], "leaves": []},
                           snapshot=snap)
    assert not bad["ok"]
    assert bad["violations"][0]["src"] == "wt._a"
    # the cross-module leaf allowance (but not same-module)
    leafy = lw.check_witness(
        graph={"allowed": [], "leaves": ["wt._b"]}, snapshot=snap)
    assert not leafy["ok"]        # same module: leaf does not excuse
    cross = lw.check_witness(
        graph={"allowed": [], "leaves": ["other._b"]},
        snapshot={"edges": [{"src": "wt._a", "dst": "other._b",
                             "count": 1}],
                  "edges_total": 1, "watchdog_trips": 0,
                  "max_hold_ms": 0.0})
    assert cross["ok"]


def test_witness_condition_canonicalizes_onto_its_lock(witness):
    class Obj:
        pass

    o = Obj()
    o._mem_lock = threading.RLock()
    o._mem_cond = threading.Condition(o._mem_lock)
    o._cond = threading.Condition()
    lw.maybe_instrument(o, "fx")
    assert o._mem_cond.name == "fx._mem_lock"
    with o._mem_lock:
        with o._mem_cond:            # re-entry, not an edge
            o._mem_cond.wait(timeout=0.01)
        with o._cond:
            pass
    snap = lw.report()
    edges = {(e["src"], e["dst"]) for e in snap["edges"]}
    assert ("fx._mem_lock", "fx._cond") in edges
    assert all(src != dst for src, dst in edges)
    # wait released the lock: the recorded hold must be far below the
    # wall the wait would have added had it been counted
    assert "fx._mem_lock" in snap["holds"]


def test_witness_watchdog_trips_on_starved_acquire(witness,
                                                   monkeypatch):
    monkeypatch.setenv("AMGCL_TPU_LOCK_WITNESS_TIMEOUT_S", "0.1")
    lock = lw.maybe_wrap("wt._wd", threading.Lock())
    lock.acquire()
    landed = []

    def worker():
        lock.acquire()
        landed.append(True)
        lock.release()

    th = threading.Thread(target=worker, daemon=True)
    th.start()
    time.sleep(0.35)
    lock.release()
    th.join(5)
    assert landed, "starved acquire never landed after release"
    snap = lw.report()
    assert snap["watchdog_trips"] >= 1
    assert snap["trips"][0]["lock"] == "wt._wd"
    # trips fail the verdict even when every edge is legal
    out = lw.check_witness(graph={"allowed": [], "leaves": []},
                           snapshot=snap)
    assert not out["ok"]


def test_witness_gauges_ride_the_declared_metric_table(witness):
    from amgcl_tpu.telemetry.live import LiveRegistry
    a = lw.maybe_wrap("wt._a", threading.Lock())
    b = lw.maybe_wrap("wt._b", threading.Lock())
    with a:
        with b:
            pass
    reg = LiveRegistry()
    lw.publish_gauges(reg)
    assert reg.get("lock_witness_edges") == 1
    assert reg.get("lock_witness_watchdog_trips") == 0
    assert reg.get("lock_witness_max_hold_ms") is not None


def test_witness_instruments_real_service_and_farm(witness):
    """The constructor seams wrap the real classes' locks when the
    knob is on (no solve needed — construction is enough)."""
    from amgcl_tpu.serve.registry import OperatorRegistry
    from amgcl_tpu.telemetry.live import LiveRegistry
    from amgcl_tpu.telemetry.tracing import RequestSpans
    reg = OperatorRegistry()
    assert isinstance(reg._lock, lw._WitnessLock)
    assert reg._lock.name == "registry._lock"
    live = LiveRegistry()
    assert isinstance(live._lock, lw._WitnessLock)
    spans = RequestSpans()
    assert isinstance(spans._lock, lw._WitnessLock)
    spans.add(1, [("queue", 0.0, 1.0)])        # still functional
    assert spans.events


# ===========================================================================
# chaos matrix under the witness (witnessed ⊆ static, zero trips)
# ===========================================================================

def test_chaos_subset_under_lock_witness():
    """Acceptance: a chaos run with AMGCL_TPU_LOCK_WITNESS=1 passes
    with witnessed edges ⊆ the static graph and zero watchdog trips
    (two concurrency-heavy scenarios keep the tier-1 cost bounded;
    the full matrix rides bench.py --check)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               AMGCL_TPU_LOCK_WITNESS="1")
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8"
                        ).strip()
    env.pop("AMGCL_TPU_FAULT_PLAN", None)
    r = subprocess.run(
        [sys.executable, "-m", "amgcl_tpu.faults", "--selftest",
         "serve_worker_death", "farm_admission_retry"],
        capture_output=True, text=True, timeout=420, cwd=_REPO,
        env=env)
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["ok"] and rec["hangs"] == 0
    witness = rec.get("lock_witness")
    assert witness and witness["ok"], witness
    assert witness["watchdog_trips"] == 0
    assert witness["edges_total"] >= 1          # real nesting observed
    assert witness["violations"] == []


# ===========================================================================
# regressions for the true positives fixed in-tree
# ===========================================================================

@pytest.fixture(scope="module")
def small_bundle():
    import jax.numpy as jnp
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.models.make_solver import make_solver
    from amgcl_tpu.solver.cg import CG
    from amgcl_tpu.utils.sample_problem import poisson3d
    A, rhs = poisson3d(6)
    bundle = make_solver(A, AMGParams(dtype=jnp.float32,
                                      coarse_enough=200),
                         CG(maxiter=50, tol=1e-6))
    return A, bundle, rhs.astype(np.float32)


def test_serve_timeout_stats_commit_before_future_resolves(
        small_bundle):
    """Regression (handoff-discipline): a queue-expired request's
    done-callback must already see the timeout in stats() — the
    resolve-last ordering _run_batch previously violated."""
    from amgcl_tpu.serve.service import SolverService
    _A, bundle, rhs = small_bundle
    svc = SolverService(bundle, batch=2, flush_ms=10, metrics_port=-1)
    try:
        seen = []
        done = threading.Event()
        fut = svc.submit(rhs, timeout_s=0.0)
        fut.add_done_callback(
            lambda f: (seen.append(svc.stats()["timeouts"]),
                       done.set()))
        assert done.wait(60), "timeout future never resolved"
        assert isinstance(fut.exception(), TimeoutError)
        assert seen and seen[0] >= 1, \
            "future resolved before its timeout was booked"
    finally:
        svc.close()


def test_serve_failed_batch_stats_commit_before_future_resolves(
        small_bundle, monkeypatch):
    """Regression (handoff-discipline): a failed batch's done-callback
    must already see the failure in stats()["unhealthy"]."""
    from amgcl_tpu.faults import inject
    from amgcl_tpu.serve.service import SolverService
    _A, bundle, rhs = small_bundle
    monkeypatch.setenv("AMGCL_TPU_FAULT_PLAN", json.dumps(
        {"site": "serve.poison", "rid": 1, "count": -1}))
    inject._reset_for_tests()
    svc = SolverService(bundle, batch=2, flush_ms=10, metrics_port=-1)
    try:
        seen = []
        done = threading.Event()
        fut = svc.submit(rhs)
        fut.add_done_callback(
            lambda f: (seen.append(svc.stats()["unhealthy"]),
                       done.set()))
        assert done.wait(60), "poisoned future never resolved"
        assert fut.exception() is not None
        assert seen and seen[0] >= 1, \
            "future resolved before its failure was booked"
    finally:
        svc.close()
        inject._reset_for_tests()


def test_farm_stranded_future_resolves_outside_mem_lock(small_bundle):
    """Regression (handoff-discipline): a request stranded by a
    different-size re-register resolves AFTER _mem_lock drops — its
    done-callback can coordinate with a thread that needs the farm's
    control plane (the old in-lock resolution deadlocked this)."""
    from amgcl_tpu.serve.farm import SolverFarm, _FarmRequest
    from amgcl_tpu.utils.sample_problem import poisson3d
    A1, _bundle, rhs1 = small_bundle
    A2, _rhs2 = poisson3d(7)
    farm = SolverFarm(max_bytes=0, metrics_port=-1)
    try:
        farm.register("t", A1)
        req = _FarmRequest(rhs1, 30.0, rid=77, tenant="t")
        with farm._cond:
            farm.tenants["t"].q.append(req)
        lock_free = []
        cb_done = threading.Event()

        def cb(_fut):
            probe = threading.Event()
            res = []

            def helper():
                got = farm._mem_lock.acquire(timeout=2.0)
                res.append(got)
                if got:
                    farm._mem_lock.release()
                probe.set()

            threading.Thread(target=helper, daemon=True).start()
            probe.wait(5.0)
            lock_free.append(bool(res and res[0]))
            cb_done.set()

        req.public.add_done_callback(cb)
        farm.register("t", A2)            # different n: strands req
        assert cb_done.wait(10.0), "stranded future never resolved"
        assert isinstance(req.public.exception(), RuntimeError)
        assert "system size" in str(req.public.exception())
        assert lock_free == [True], \
            "public future resolved while _mem_lock was held"
    finally:
        farm.close()


def test_flight_record_solve_is_lock_guarded(tmp_path, monkeypatch):
    """Regression (guarded-by): concurrent record_solve against
    _reset_for_tests keeps the ring consistent (the append now runs
    under the module lock, like every other ring access)."""
    from amgcl_tpu.telemetry import flight
    monkeypatch.setenv("AMGCL_TPU_FLIGHT_DIR", str(tmp_path))
    flight._reset_for_tests()
    errs = []

    def writer():
        try:
            for i in range(200):
                flight.record_solve(None, np.zeros(3), None, None)
        except Exception as e:           # noqa: BLE001
            errs.append(e)

    def resetter():
        try:
            for _ in range(50):
                flight._reset_for_tests()
        except Exception as e:           # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=writer) for _ in range(4)] \
        + [threading.Thread(target=resetter)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errs
    assert len(flight._ring) <= flight.RING_CAPACITY
    flight._reset_for_tests()
