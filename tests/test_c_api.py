"""C API: build the shared library + C test program and run it end-to-end
(reference parity: lib/amgcl.cpp + examples/call_lib). Skipped when the
toolchain or Python embedding config is unavailable."""

import os
import shutil
import subprocess
import sys
import sysconfig

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _embed_flags():
    cfg = subprocess.run(
        [sys.executable + "-config" if shutil.which(sys.executable + "-config")
         else "python3-config", "--includes", "--ldflags", "--embed"],
        capture_output=True, text=True)
    if cfg.returncode != 0:
        # derive from sysconfig (python3-config may be absent)
        inc = "-I" + sysconfig.get_path("include")
        libdir = sysconfig.get_config_var("LIBDIR")
        ver = sysconfig.get_config_var("LDVERSION")
        return [inc, "-L" + libdir, "-lpython" + ver]
    return cfg.stdout.split()


@pytest.fixture(scope="module")
def c_binary(tmp_path_factory):
    if shutil.which("g++") is None or shutil.which("gcc") is None:
        pytest.skip("no C toolchain")
    tmp = tmp_path_factory.mktemp("capi")
    exe = str(tmp / "test_c_api")
    flags = _embed_flags()
    cmd = (["g++", "-O1", "-std=c++17",
            os.path.join(REPO, "csrc", "c_api.cpp"),
            os.path.join(REPO, "csrc", "test_c_api.c"),
            "-o", exe] + flags + ["-lm"])
    got = subprocess.run(cmd, capture_output=True, text=True)
    if got.returncode != 0:
        pytest.skip("cannot build C test: %s" % got.stderr[-800:])
    return exe


def test_c_api_end_to_end(c_binary):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # keep the embedded interpreter off the axon plugin and on CPU
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    got = subprocess.run([c_binary], capture_output=True, text=True,
                         env=env, timeout=600)
    assert got.returncode == 0, got.stdout + got.stderr
    assert "C API smoke test OK" in got.stdout


def test_capi_python_surface():
    """The marshalling layer itself (no embedding needed): create params,
    build a solver from raw addresses, solve, destroy."""
    import ctypes
    from amgcl_tpu import capi
    from amgcl_tpu.utils.sample_problem import poisson3d

    A, rhs = poisson3d(10)
    ptr32 = A.ptr.astype(np.int32)
    col32 = A.col.astype(np.int32)
    val = A.val.astype(np.float64)
    x = np.zeros(A.nrows)

    h = capi.params_create()
    capi.params_set(h, "solver.type", "cg")
    capi.params_set(h, "solver.tol", 1e-8)
    capi.params_set(h, "precond.dtype", "float64")
    s = capi.solver_create(
        A.nrows, ptr32.ctypes.data, col32.ctypes.data, val.ctypes.data, h)
    assert capi.handle_n(s) == A.nrows
    rhs64 = np.asarray(rhs, dtype=np.float64)
    iters, resid = capi.solver_solve(
        s, rhs64.ctypes.data, x.ctypes.data, A.nrows)
    assert resid < 1e-8 and iters > 0
    r = np.linalg.norm(rhs64 - A.spmv(x)) / np.linalg.norm(rhs64)
    assert r < 1e-7
    assert "make_solver" in capi.report(s)
    capi.handle_destroy(s)
    capi.handle_destroy(h)
