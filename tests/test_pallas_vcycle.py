"""Fused down-sweep kernel (ops/pallas_vcycle.py) in interpret mode.

Eligibility needs f0 % 128 == 0, so the fixtures use a thin 4x8x128
grid — small enough for interpret mode, wide enough for the lane gate.
"""

import numpy as np
import scipy.sparse as sp
import jax.numpy as jnp
import pytest

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.models.make_solver import make_solver
from amgcl_tpu.solver.cg import CG


def grid_laplacian(d2, d1, d0):
    """7-point Laplacian on a (d2, d1, d0) C-order grid."""
    def T(n):
        e = np.ones(n)
        return sp.diags([-e[:-1], 2 * e, -e[:-1]], [-1, 0, 1], format="csr")
    I = sp.identity
    A = (sp.kron(I(d2), sp.kron(I(d1), T(d0)))
         + sp.kron(I(d2), sp.kron(T(d1), I(d0)))
         + sp.kron(T(d2), sp.kron(I(d1), I(d0)))).tocsr()
    A.sort_indices()
    rhs = np.ones(d2 * d1 * d0)
    return CSR.from_scipy(A), rhs


@pytest.fixture()
def interpret_hook(monkeypatch):
    monkeypatch.setenv("AMGCL_TPU_PALLAS_INTERPRET", "1")


def test_fused_down_attached_and_exact(interpret_hook):
    """The level-0 fused handle exists under the hook and matches the
    composed residual -> filter -> restrict chain elementwise."""
    A, rhs = grid_laplacian(4, 8, 128)
    amg = AMG(A, AMGParams(dtype=jnp.float32, coarse_enough=200))
    lv = amg.hierarchy.levels[0]
    assert lv.down is not None, "eligible level built without fused down"

    rng = np.random.RandomState(0)
    f = jnp.asarray(rng.rand(A.nrows), dtype=jnp.float32)
    u = jnp.asarray(rng.rand(A.nrows), dtype=jnp.float32)
    fused = np.asarray(lv.down(f, u))
    from amgcl_tpu.ops import device as dev
    composed = np.asarray(dev.spmv(lv.R, dev.residual(f, lv.A, u)))
    assert fused.shape == composed.shape
    np.testing.assert_allclose(fused, composed, rtol=2e-5, atol=2e-5)


def test_fused_down_zero_guess_and_solve(interpret_hook):
    """Solve parity: the fused path must not change CG iteration counts
    vs the composed path (down handle stripped)."""
    A, rhs = grid_laplacian(4, 8, 128)
    prm = AMGParams(dtype=jnp.float32, coarse_enough=200)
    s1 = make_solver(A, prm, CG(tol=1e-6, maxiter=40))
    assert s1.precond.hierarchy.levels[0].down is not None
    x1, i1 = s1(rhs)

    s2 = make_solver(A, prm, CG(tol=1e-6, maxiter=40))
    for lv in s2.precond.hierarchy.levels:
        lv.down = None                      # force the composed path
    x2, i2 = s2(rhs)

    assert i1.iters == i2.iters
    r = rhs - A.spmv(np.asarray(x1, dtype=np.float64))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-5


def test_fused_down_ineligible_grids(interpret_hook):
    """Grids violating the lane/evenness gates must fall back (down is
    None) and still solve correctly."""
    A, rhs = grid_laplacian(4, 6, 96)      # f0 % 128 != 0
    amg = AMG(A, AMGParams(dtype=jnp.float32, coarse_enough=200))
    assert all(lv.down is None for lv in amg.hierarchy.levels)


def test_fused_down_odd_z(interpret_hook):
    """Odd f2: the last coarse plane covers one fine plane (zero pad)."""
    A, rhs = grid_laplacian(5, 8, 128)
    amg = AMG(A, AMGParams(dtype=jnp.float32, coarse_enough=200))
    lv = amg.hierarchy.levels[0]
    if lv.down is None:
        pytest.skip("grid path not taken for odd-z fixture")
    rng = np.random.RandomState(1)
    f = jnp.asarray(rng.rand(A.nrows), dtype=jnp.float32)
    u = jnp.asarray(rng.rand(A.nrows), dtype=jnp.float32)
    from amgcl_tpu.ops import device as dev
    fused = np.asarray(lv.down(f, u))
    composed = np.asarray(dev.spmv(lv.R, dev.residual(f, lv.A, u)))
    np.testing.assert_allclose(fused, composed, rtol=2e-5, atol=2e-5)


def test_fused_up_attached_and_exact(interpret_hook):
    """The fused up-sweep matches prolong + correct + one post-smooth
    sweep elementwise."""
    A, rhs = grid_laplacian(4, 8, 128)
    amg = AMG(A, AMGParams(dtype=jnp.float32, coarse_enough=200))
    lv = amg.hierarchy.levels[0]
    assert lv.up is not None, "eligible level built without fused up"

    nc = lv.R.shape[0]
    rng = np.random.RandomState(3)
    f = jnp.asarray(rng.rand(A.nrows), dtype=jnp.float32)
    u = jnp.asarray(rng.rand(A.nrows), dtype=jnp.float32)
    uc = jnp.asarray(rng.rand(nc), dtype=jnp.float32)
    fused = np.asarray(lv.up(f, u, uc))
    from amgcl_tpu.ops import device as dev
    u1 = u + dev.spmv(lv.P, uc)
    composed = np.asarray(lv.relax.apply_post(lv.A, f, u1))
    np.testing.assert_allclose(fused, composed, rtol=2e-5, atol=2e-5)


def test_fused_cycle_solve_parity(interpret_hook):
    """Both fused handles active: CG iteration parity vs the composed
    cycle (handles stripped)."""
    A, rhs = grid_laplacian(4, 8, 128)
    prm = AMGParams(dtype=jnp.float32, coarse_enough=200)
    s1 = make_solver(A, prm, CG(tol=1e-6, maxiter=40))
    lv0 = s1.precond.hierarchy.levels[0]
    assert lv0.down is not None and lv0.up is not None
    x1, i1 = s1(rhs)

    s2 = make_solver(A, prm, CG(tol=1e-6, maxiter=40))
    for lv in s2.precond.hierarchy.levels:
        lv.down = None
        lv.up = None
    x2, i2 = s2(rhs)
    assert i1.iters == i2.iters
    r = rhs - A.spmv(np.asarray(x1, dtype=np.float64))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-5


def test_fused_down_zero_guess_exact(interpret_hook):
    """zero(f) must match pre-smooth-from-zero + composed down chain."""
    A, rhs = grid_laplacian(4, 8, 128)
    amg = AMG(A, AMGParams(dtype=jnp.float32, coarse_enough=200))
    lv = amg.hierarchy.levels[0]
    assert lv.down is not None and lv.down.w is not None

    rng = np.random.RandomState(4)
    f = jnp.asarray(rng.rand(A.nrows), dtype=jnp.float32)
    u_z, fc_z = lv.down.zero(f)
    from amgcl_tpu.ops import device as dev
    u_ref = lv.relax.apply(lv.A, f)
    fc_ref = dev.spmv(lv.R, dev.residual(f, lv.A, u_ref))
    np.testing.assert_allclose(np.asarray(u_z), np.asarray(u_ref),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(fc_z), np.asarray(fc_ref),
                               rtol=2e-5, atol=2e-5)


def test_fused_kernels_bf16(interpret_hook):
    """bf16 hierarchy (precond_dtype seam) through both fused kernels:
    eligibility holds (itemsize 2) and parity vs the composed bf16 path."""
    A, rhs = grid_laplacian(4, 8, 128)
    amg = AMG(A, AMGParams(dtype=jnp.bfloat16, coarse_enough=200))
    lv = amg.hierarchy.levels[0]
    if lv.down is None:
        pytest.skip("bf16 level fell off the stencil path")
    rng = np.random.RandomState(5)
    f = jnp.asarray(rng.rand(A.nrows), dtype=jnp.bfloat16)
    u = jnp.asarray(rng.rand(A.nrows), dtype=jnp.bfloat16)
    from amgcl_tpu.ops import device as dev
    fused = np.asarray(lv.down(f, u), dtype=np.float32)
    composed = np.asarray(dev.spmv(lv.R, dev.residual(f, lv.A, u)),
                          dtype=np.float32)
    # bf16 accumulation orders differ; tolerance matches the format
    scale = max(1.0, np.abs(composed).max())
    assert np.max(np.abs(fused - composed)) / scale < 0.05
    if lv.up is not None:
        uc = jnp.asarray(rng.rand(lv.R.shape[0]), dtype=jnp.bfloat16)
        fu = np.asarray(lv.up(f, u, uc), dtype=np.float32)
        cu = np.asarray(lv.relax.apply_post(
            lv.A, f, u + dev.spmv(lv.P, uc)), dtype=np.float32)
        scale = max(1.0, np.abs(cu).max())
        assert np.max(np.abs(fu - cu)) / scale < 0.05


@pytest.mark.parametrize("dims", [(4, 8, 64), (4, 32, 32)])
def test_fused_packed_lanes(interpret_hook, dims):
    """f0 < 128 levels pack k = 128//f0 y-rows per lane row; both fused
    directions must stay exact under the packed reductions."""
    A, rhs = grid_laplacian(*dims)
    amg = AMG(A, AMGParams(dtype=jnp.float32, coarse_enough=100))
    lv = amg.hierarchy.levels[0]
    assert lv.down is not None, "packed grid %s not eligible" % (dims,)
    rng = np.random.RandomState(6)
    f = jnp.asarray(rng.rand(A.nrows), dtype=jnp.float32)
    u = jnp.asarray(rng.rand(A.nrows), dtype=jnp.float32)
    from amgcl_tpu.ops import device as dev
    fused = np.asarray(lv.down(f, u))
    composed = np.asarray(dev.spmv(lv.R, dev.residual(f, lv.A, u)))
    np.testing.assert_allclose(fused, composed, rtol=2e-5, atol=2e-5)
    u_z, fc_z = lv.down.zero(f)
    u_ref = lv.relax.apply(lv.A, f)
    np.testing.assert_allclose(np.asarray(u_z), np.asarray(u_ref),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(
        np.asarray(fc_z),
        np.asarray(dev.spmv(lv.R, dev.residual(f, lv.A, u_ref))),
        rtol=2e-5, atol=2e-5)
    if lv.up is not None:
        uc = jnp.asarray(rng.rand(lv.R.shape[0]), dtype=jnp.float32)
        fu = np.asarray(lv.up(f, u, uc))
        cu = np.asarray(lv.relax.apply_post(
            lv.A, f, u + dev.spmv(lv.P, uc)))
        np.testing.assert_allclose(fu, cu, rtol=2e-5, atol=2e-5)


def test_fused_up_two_plane_halo(interpret_hook):
    """27-point coarse operators whose halo exceeds one plane take the
    hp=2 frame; parity on a level-1 handle."""
    A, rhs = grid_laplacian(8, 32, 64)
    amg = AMG(A, AMGParams(dtype=jnp.float32, coarse_enough=100))
    lv = amg.hierarchy.levels[1]
    if lv.up is None:
        pytest.skip("level-1 up handle not built for this fixture")
    assert lv.up.halo_planes == 2
    n1 = lv.A.shape[0]
    rng = np.random.RandomState(7)
    f = jnp.asarray(rng.rand(n1), dtype=jnp.float32)
    u = jnp.asarray(rng.rand(n1), dtype=jnp.float32)
    uc = jnp.asarray(rng.rand(lv.R.shape[0]), dtype=jnp.float32)
    from amgcl_tpu.ops import device as dev
    fused = np.asarray(lv.up(f, u, uc))
    composed = np.asarray(lv.relax.apply_post(
        lv.A, f, u + dev.spmv(lv.P, uc)))
    np.testing.assert_allclose(fused, composed, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("offs_a,offs_m", [
    ((-1024, -128, -1, 0), (-1024, 0, 1, 128)),       # one-sided reach
    ((0, 1, 128, 1024), (-1024, -128, -1, 0, 1)),     # opposite skews
    ((-2048, 0, 2048), (-1024, 0, 1024)),             # |dz| = 2 coupling
])
def test_fused_down_asymmetric_offsets(offs_a, offs_m):
    """Direct kernel-vs-numpy parity on ASYMMETRIC diagonal sets — the
    frame arithmetic (base/Hr) distinguishes forward/backward reach,
    which the symmetric Laplacian fixtures never stress."""
    from amgcl_tpu.ops.pallas_vcycle import (fused_down_sweep, _pair_sum,
                                             down_geometry)
    dims, coarse = (4, 8, 128), (2, 4, 64)
    f2, f1, f0 = dims
    c2, c1, c0 = coarse
    s = f1 * f0
    n = f2 * s
    H, _, _ = down_geometry(offs_a, offs_m, dims)
    L = 2 * c2 * s + 2 * H
    rng = np.random.RandomState(11)
    Ad = rng.rand(len(offs_a), n).astype(np.float32)
    Md = rng.rand(len(offs_m), n).astype(np.float32)
    af = jnp.asarray(np.concatenate(
        [np.pad(Ad[k], (H, L - H - n)) for k in range(len(offs_a))]))
    mf = jnp.asarray(np.concatenate(
        [np.pad(Md[k], (H, L - H - n)) for k in range(len(offs_m))]))
    sy = _pair_sum(c1, f1, jnp.float32)
    sx = _pair_sum(c0, f0, jnp.float32).T
    f = jnp.asarray(rng.rand(n).astype(np.float32))
    u = jnp.asarray(rng.rand(n).astype(np.float32))
    out = np.asarray(fused_down_sweep(
        af, mf, sy, sx, f, u, tuple(offs_a), tuple(offs_m), dims, coarse,
        H, interpret=True))

    def shift_mv(data, offs, x):
        y = np.zeros(len(x))
        for k, d in enumerate(offs):
            lo, hi = max(0, -d), min(len(x), len(x) - d)
            y[lo:hi] += data[k, lo:hi] * x[lo + d:hi + d]
        return y

    r = np.asarray(f, np.float64) - shift_mv(Ad, offs_a,
                                             np.asarray(u, np.float64))
    t = r - shift_mv(Md, offs_m, r)
    rc = t.reshape(c2, 2, c1, 2, c0, 2).sum(axis=(1, 3, 5))
    np.testing.assert_allclose(out.ravel(), rc.ravel(),
                               rtol=1e-4, atol=1e-4)


def test_fused_handles_survive_rebuild(interpret_hook):
    """AMG.rebuild (time-dependent path) must reconstruct the fused
    handles against the NEW values, not keep stale padded copies."""
    A, rhs = grid_laplacian(4, 8, 128)
    amg = AMG(A, AMGParams(dtype=jnp.float32, coarse_enough=200))
    assert amg.hierarchy.levels[0].down is not None
    from amgcl_tpu.ops.csr import CSR as _CSR
    A2 = _CSR(A.ptr.copy(), A.col.copy(), A.val * 2.0, A.ncols)
    amg.rebuild(A2)
    lv = amg.hierarchy.levels[0]
    assert lv.down is not None, "rebuild dropped the fused handle"
    rng = np.random.RandomState(9)
    f = jnp.asarray(rng.rand(A.nrows), dtype=jnp.float32)
    u = jnp.asarray(rng.rand(A.nrows), dtype=jnp.float32)
    from amgcl_tpu.ops import device as dev
    fused = np.asarray(lv.down(f, u))
    composed = np.asarray(dev.spmv(lv.R, dev.residual(f, lv.A, u)))
    np.testing.assert_allclose(fused, composed, rtol=2e-5, atol=2e-5)


def _shift_mv(data, offs, x):
    y = np.zeros(len(x))
    for k, d in enumerate(offs):
        lo, hi = max(0, -d), min(len(x), len(x) - d)
        y[lo:hi] += data[k, lo:hi] * x[lo + d:hi + d]
    return y


def test_fused_down_fuzz_fixed_seed():
    """Randomized (fixed-seed) shape x offset-set sweep of the down
    kernel vs a numpy reference — regression net for the frame
    arithmetic beyond the hand-picked cases."""
    from amgcl_tpu.ops.pallas_vcycle import (fused_down_sweep, _pair_sum,
                                             _packed_reduce, _pack_shape,
                                             down_geometry)
    rng = np.random.RandomState(42)
    for dims in [(2, 8, 64), (3, 8, 128), (4, 16, 32)]:
        f2, f1, f0 = dims
        k = 128 // f0
        s = f1 * f0
        n = f2 * s
        c2, c1, c0 = (f2 + 1) // 2, f1 // 2, f0 // 2
        na, nm = rng.randint(3, 8), rng.randint(3, 8)
        pool = [-s, -f0, -1, 0, 1, f0, s, -2 * f0, 2 * f0, -s - f0, s + 1]
        offs_a = tuple(sorted(rng.choice(pool, na, replace=False).tolist()))
        offs_m = tuple(sorted(rng.choice(pool, nm, replace=False).tolist()))
        H, _, _ = down_geometry(offs_a, offs_m, dims)
        L = 2 * c2 * s + 2 * H
        Ad = rng.rand(na, n).astype(np.float32)
        Md = rng.rand(nm, n).astype(np.float32)
        af = jnp.asarray(np.concatenate(
            [np.pad(Ad[i], (H, L - H - n)) for i in range(na)]))
        mf = jnp.asarray(np.concatenate(
            [np.pad(Md[i], (H, L - H - n)) for i in range(nm)]))
        _, fv, _ = _pack_shape(f1, f0, c1, c0)
        if k == 1:
            sy = _pair_sum(c1, f1, jnp.float32)
            sx = _pair_sum(c0, f0, jnp.float32).T
        else:
            sy = jnp.eye(fv[0], dtype=jnp.float32)
            sx = _packed_reduce(f0, k, c0, jnp.float32)
        f = jnp.asarray(rng.rand(n).astype(np.float32))
        u = jnp.asarray(rng.rand(n).astype(np.float32))
        out = np.asarray(fused_down_sweep(
            af, mf, sy, sx, f, u, offs_a, offs_m, dims,
            (c2, c1, c0), H, interpret=True))
        r = np.asarray(f, np.float64) - _shift_mv(Ad, offs_a,
                                                  np.asarray(u, np.float64))
        t = r - _shift_mv(Md, offs_m, r)
        rc = np.pad(t, (0, 2 * c2 * s - n)).reshape(
            c2, 2, c1, 2, c0, 2).sum(axis=(1, 3, 5))
        np.testing.assert_allclose(out.ravel(), rc.ravel(),
                                   rtol=2e-4, atol=2e-4,
                                   err_msg=str((dims, offs_a, offs_m)))


def test_vcycle_fusion_kill_switch(interpret_hook, monkeypatch):
    """AMGCL_TPU_FUSED_VCYCLE=0 disables the sweep-kernel tier only."""
    monkeypatch.setenv("AMGCL_TPU_FUSED_VCYCLE", "0")
    A, rhs = grid_laplacian(4, 8, 128)
    amg = AMG(A, AMGParams(dtype=jnp.float32, coarse_enough=200))
    assert all(lv.down is None and lv.up is None
               for lv in amg.hierarchy.levels)
