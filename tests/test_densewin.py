"""Dense-window format (ops/densewin.py): packing, XLA path, Pallas
interpret path, fused kernels, budget gates, and the device-seam
dispatch (reference capability: general-sparsity device SpMV,
amgcl/backend/cuda.hpp:60-843 — re-designed gather-free for the TPU)."""

import numpy as np
import jax.numpy as jnp

from amgcl_tpu.ops import device as dev
from amgcl_tpu.ops.densewin import (
    DenseWindowMatrix, csr_to_dense_window, dense_window_spmv,
    dense_window_residual, dense_window_scaled_correction, _WIN_ALIGN)
from amgcl_tpu.ops.unstructured import fe_like_problem
from amgcl_tpu.utils.adapters import cuthill_mckee, permute


def _small_fe(n=2500, seed=2):
    A, rhs = fe_like_problem(n=n, nnz_target=n * 18, seed=seed)
    perm = cuthill_mckee(A)
    return permute(A, perm), rhs


def test_build_and_xla_matches_host():
    Ap, _ = _small_fe()
    D = csr_to_dense_window(Ap, jnp.float64)
    assert D is not None
    assert D.win % _WIN_ALIGN == 0
    assert int(D.window_starts.min()) >= 0
    assert all(int(s) % _WIN_ALIGN == 0 for s in np.asarray(
        D.window_starts))
    x = np.random.RandomState(0).rand(Ap.nrows)
    np.testing.assert_allclose(np.asarray(D._mv_xla(jnp.asarray(x))),
                               Ap.spmv(x), rtol=1e-12)


def test_interpret_kernels_match():
    Ap, _ = _small_fe(n=2000, seed=3)
    D = csr_to_dense_window(Ap, jnp.float32)
    assert D is not None
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.rand(Ap.nrows), jnp.float32)
    f = jnp.asarray(rng.rand(Ap.nrows), jnp.float32)
    w = jnp.asarray(rng.rand(Ap.nrows), jnp.float32)
    y_ref = Ap.spmv(np.asarray(x, np.float64))
    tol = dict(rtol=2e-4, atol=1e-4 * np.abs(y_ref).max())
    y = np.asarray(dense_window_spmv(
        D.window_starts, D.blocks, x, D.win, D.shape[0], interpret=True))
    np.testing.assert_allclose(y, y_ref, **tol)
    r = np.asarray(dense_window_residual(
        D.window_starts, D.blocks, f, x, D.win, D.shape[0],
        interpret=True))
    np.testing.assert_allclose(r, np.asarray(f, np.float64) - y_ref,
                               **tol)
    c = np.asarray(dense_window_scaled_correction(
        D.window_starts, D.blocks, w, f, x, D.win, D.shape[0],
        interpret=True))
    want = (np.asarray(x, np.float64)
            + np.asarray(w, np.float64)
            * (np.asarray(f, np.float64) - y_ref))
    np.testing.assert_allclose(c, want, **tol)


def test_device_seams_dispatch_interpret(monkeypatch):
    monkeypatch.setenv("AMGCL_TPU_PALLAS_INTERPRET", "1")
    Ap, _ = _small_fe(n=1500, seed=4)
    D = csr_to_dense_window(Ap, jnp.float32)
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.rand(Ap.nrows), jnp.float32)
    f = jnp.asarray(rng.rand(Ap.nrows), jnp.float32)
    w = jnp.asarray(rng.rand(Ap.nrows), jnp.float32)
    y_ref = Ap.spmv(np.asarray(x, np.float64))
    tol = dict(rtol=2e-4, atol=1e-4 * np.abs(y_ref).max())
    np.testing.assert_allclose(np.asarray(D.mv(x)), y_ref, **tol)
    np.testing.assert_allclose(np.asarray(dev.residual(f, D, x)),
                               np.asarray(f, np.float64) - y_ref, **tol)
    got = dev.scaled_correction(D, w, f, x)
    assert got is not None
    want = (np.asarray(x, np.float64)
            + np.asarray(w, np.float64)
            * (np.asarray(f, np.float64) - y_ref))
    np.testing.assert_allclose(np.asarray(got), want, **tol)


def test_budget_gates():
    Ap, _ = _small_fe(n=1200, seed=5)
    assert csr_to_dense_window(Ap, jnp.float32, max_bytes=1024) is None
    # block and complex matrices are out of scope for v1
    from amgcl_tpu.ops.csr import CSR
    Ab = CSR(np.array([0, 1]), np.array([0]),
             np.ones((1, 2, 2)), 1)
    assert csr_to_dense_window(Ab, jnp.float32) is None
    assert csr_to_dense_window(Ap, jnp.complex64) is None


def test_amg_solve_on_dense_window_hierarchy(monkeypatch):
    """End-to-end AMG+BiCGStab with dense-window level operators driven
    through the Pallas kernels in interpret mode — the closest possible
    rehearsal of the TPU auto-selected path, which no CPU CI reaches
    through to_device's backend gate."""
    import jax.numpy as jnp
    from amgcl_tpu.ops.densewin import DenseWindowMatrix
    monkeypatch.setenv("AMGCL_TPU_PALLAS_INTERPRET", "1")
    real_to_device = dev.to_device

    def dwin_to_device(A, fmt="auto", dtype=jnp.float32, **kw):
        if fmt == "auto" and not A.is_block:
            D = csr_to_dense_window(A, dtype)
            if D is not None:
                return D
        return real_to_device(A, fmt, dtype, **kw)

    monkeypatch.setattr(dev, "to_device", dwin_to_device)
    from amgcl_tpu.models.make_solver import make_solver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.bicgstab import BiCGStab
    Ap, rhs = _small_fe(n=1500, seed=6)
    # coarse_enough forces a real multilevel hierarchy at this size so
    # the dwin transfers/smoother seams all engage
    s = make_solver(Ap, AMGParams(dtype=jnp.float32, coarse_enough=200),
                    BiCGStab(maxiter=200, tol=1e-7))
    assert isinstance(s.A_dev, DenseWindowMatrix)
    assert any(isinstance(lv.A, DenseWindowMatrix)
               for lv in s.precond.hierarchy.levels)
    x, info = s(rhs)
    tr = np.linalg.norm(rhs - Ap.spmv(np.asarray(x, np.float64))) \
        / np.linalg.norm(rhs)
    # the 1/h² fixture floors an UNREFINED f32 solve around 2e-4; the
    # reference-format run measures the same (1.7e-4) — the assertion
    # is format-equivalence, not refined accuracy
    assert tr < 1e-3, (tr, int(info.iters))


def test_empty_tile_rows():
    # a matrix whose second 64-row tile is entirely empty
    from amgcl_tpu.ops.csr import CSR
    import scipy.sparse as sp
    n = 130
    rows = np.arange(64)
    M = sp.csr_matrix((np.ones(64), (rows, rows)), shape=(n, n))
    D = csr_to_dense_window(CSR.from_scipy(M), jnp.float32)
    assert D is not None
    x = np.random.RandomState(3).rand(n).astype(np.float32)
    y = np.asarray(D._mv_xla(jnp.asarray(x)))
    want = np.zeros(n)
    want[:64] = x[:64]
    np.testing.assert_allclose(y, want, rtol=1e-6)
