"""Coupled-physics preconditioners: Schur pressure correction and CPR."""

import numpy as np
import scipy.sparse as sp
import jax.numpy as jnp
import pytest

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.models.make_solver import make_solver
from amgcl_tpu.models.amg import AMGParams
from amgcl_tpu.models.schur import SchurPressureCorrection
from amgcl_tpu.models.cpr import CPR, CPRDRS
from amgcl_tpu.solver.gmres import FGMRES
from amgcl_tpu.solver.bicgstab import BiCGStab
from amgcl_tpu.utils.sample_problem import poisson3d, stokes_like


def test_schur_pressure_correction():
    A, pmask = stokes_like(12)
    rhs = np.ones(A.nrows)
    pre = SchurPressureCorrection(
        A, pmask,
        usolver_prm=AMGParams(dtype=jnp.float64, coarse_enough=100),
        psolver_prm=AMGParams(dtype=jnp.float64, coarse_enough=100),
        dtype=jnp.float64)
    solve = make_solver(A, pre, FGMRES(maxiter=300, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-6
    assert "schur" in repr(pre)


def reservoir_like(n, b=3):
    """Block system with a Poisson-ish pressure coupling plus local
    saturation equations per cell."""
    Ap, _ = poisson3d(n)
    m = Ap.to_scipy()
    nc = m.shape[0]
    K = sp.kron(m, np.eye(b)).tocsr()
    # couple saturations to pressure inside each cell and make the
    # saturation equations strongly diagonal
    rows = np.concatenate([np.arange(nc) * b + k for k in range(1, b)])
    extra = sp.csr_matrix(
        (np.full(len(rows), 0.3), (rows, (rows // b) * b)),
        shape=K.shape)
    diag = sp.csr_matrix(
        (np.full(len(rows), float(nc)), (rows, rows)), shape=K.shape)
    M = (K + extra + diag).tocsr()
    return CSR.from_scipy(M).to_block(b), np.ones(nc * b)


@pytest.mark.parametrize("cls", [CPR, CPRDRS])
def test_cpr(cls):
    A, rhs = reservoir_like(8, 3)
    pre = cls(A, pressure_prm=AMGParams(dtype=jnp.float64,
                                        coarse_enough=100),
              dtype=jnp.float64)
    solve = make_solver(A, pre, BiCGStab(maxiter=200, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-6


def test_cpr_rejects_scalar():
    A, _ = poisson3d(6)
    with pytest.raises(ValueError, match="block"):
        CPR(A)


@pytest.mark.parametrize("approx_schur,adjust_p", [
    (True, 0), (True, 1), (True, 2), (False, 1), (False, 2)])
def test_schur_param_variants(approx_schur, adjust_p):
    """approx_schur / adjust_p parity (reference:
    schur_pressure_correction.hpp:106-130, 258-283, 443-496)."""
    A, pmask = stokes_like(10)
    rhs = np.ones(A.nrows)
    pre = SchurPressureCorrection(
        A, pmask,
        usolver_prm=AMGParams(dtype=jnp.float64, coarse_enough=100),
        psolver_prm=AMGParams(dtype=jnp.float64, coarse_enough=100),
        # an actual inner p-Krylov so the matrix-free S operator (and thus
        # approx_schur) is exercised, not just the build matrix
        psolver=FGMRES(maxiter=8, tol=1e-2),
        approx_schur=approx_schur, adjust_p=adjust_p,
        dtype=jnp.float64)
    solve = make_solver(A, pre, FGMRES(maxiter=300, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-6


def test_schur_runtime_params():
    from amgcl_tpu.models.runtime import make_solver_from_config
    A, pmask = stokes_like(8)
    rhs = np.ones(A.nrows)
    solve = make_solver_from_config(A, {
        "precond.class": "schur",
        "precond.approx_schur": "true",
        "precond.adjust_p": "0",
        "precond.simplec_dia": "false",
        "precond.dtype": "float64",
        "precond.pmask_pattern": ">%d" % int((~pmask).sum()),
        "solver.type": "fgmres", "solver.maxiter": "300",
        "solver.tol": "1e-8"})
    x, info = solve(rhs)
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-6
