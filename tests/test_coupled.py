"""Coupled-physics preconditioners: Schur pressure correction and CPR."""

import numpy as np
import scipy.sparse as sp
import jax.numpy as jnp
import pytest

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.models.make_solver import make_solver
from amgcl_tpu.models.amg import AMGParams
from amgcl_tpu.models.schur import SchurPressureCorrection
from amgcl_tpu.models.cpr import CPR, CPRDRS
from amgcl_tpu.solver.gmres import FGMRES
from amgcl_tpu.solver.bicgstab import BiCGStab
from amgcl_tpu.utils.sample_problem import poisson3d, stokes_like


def test_schur_pressure_correction():
    A, pmask = stokes_like(12)
    rhs = np.ones(A.nrows)
    pre = SchurPressureCorrection(
        A, pmask,
        usolver_prm=AMGParams(dtype=jnp.float64, coarse_enough=100),
        psolver_prm=AMGParams(dtype=jnp.float64, coarse_enough=100),
        dtype=jnp.float64)
    solve = make_solver(A, pre, FGMRES(maxiter=300, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-6
    assert "schur" in repr(pre)


def reservoir_like(n, b=3):
    """Block system with a Poisson-ish pressure coupling plus local
    saturation equations per cell."""
    Ap, _ = poisson3d(n)
    m = Ap.to_scipy()
    nc = m.shape[0]
    K = sp.kron(m, np.eye(b)).tocsr()
    # couple saturations to pressure inside each cell and make the
    # saturation equations strongly diagonal
    rows = np.concatenate([np.arange(nc) * b + k for k in range(1, b)])
    extra = sp.csr_matrix(
        (np.full(len(rows), 0.3), (rows, (rows // b) * b)),
        shape=K.shape)
    diag = sp.csr_matrix(
        (np.full(len(rows), float(nc)), (rows, rows)), shape=K.shape)
    M = (K + extra + diag).tocsr()
    return CSR.from_scipy(M).to_block(b), np.ones(nc * b)


@pytest.mark.parametrize("cls", [CPR, CPRDRS])
def test_cpr(cls):
    A, rhs = reservoir_like(8, 3)
    pre = cls(A, pressure_prm=AMGParams(dtype=jnp.float64,
                                        coarse_enough=100),
              dtype=jnp.float64)
    solve = make_solver(A, pre, BiCGStab(maxiter=200, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-6


def test_cpr_rejects_scalar():
    A, _ = poisson3d(6)
    with pytest.raises(ValueError, match="block"):
        CPR(A)


@pytest.mark.parametrize("approx_schur,adjust_p", [
    (True, 0), (True, 1), (True, 2), (False, 1), (False, 2)])
def test_schur_param_variants(approx_schur, adjust_p):
    """approx_schur / adjust_p parity (reference:
    schur_pressure_correction.hpp:106-130, 258-283, 443-496)."""
    A, pmask = stokes_like(10)
    rhs = np.ones(A.nrows)
    pre = SchurPressureCorrection(
        A, pmask,
        usolver_prm=AMGParams(dtype=jnp.float64, coarse_enough=100),
        psolver_prm=AMGParams(dtype=jnp.float64, coarse_enough=100),
        # an actual inner p-Krylov so the matrix-free S operator (and thus
        # approx_schur) is exercised, not just the build matrix
        psolver=FGMRES(maxiter=8, tol=1e-2),
        approx_schur=approx_schur, adjust_p=adjust_p,
        dtype=jnp.float64)
    solve = make_solver(A, pre, FGMRES(maxiter=300, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-6


def test_schur_runtime_params():
    from amgcl_tpu.models.runtime import make_solver_from_config
    A, pmask = stokes_like(8)
    rhs = np.ones(A.nrows)
    solve = make_solver_from_config(A, {
        "precond.class": "schur",
        "precond.approx_schur": "true",
        "precond.adjust_p": "0",
        "precond.simplec_dia": "false",
        "precond.dtype": "float64",
        "precond.pmask_pattern": ">%d" % int((~pmask).sum()),
        "solver.type": "fgmres", "solver.maxiter": "300",
        "solver.tol": "1e-8"})
    x, info = solve(rhs)
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-6


def drs_hard_reservoir(n, ps=5.0, sp_own=-1.0, sp_nbr=6.0, sdiag=10.0):
    """Block system engineered to break quasi-IMPES weighting: each cell's
    saturation equation has a NEGATIVE own-cell pressure coupling and
    large oscillating-sign neighbor pressure couplings, so the
    diagonal-block-inverse weights mix the saturation equation into the
    pressure system and pollute its M-matrix structure. The reference's
    DRS test a_dia[i] < eps_dd * a_off[i] (cpr_drs.hpp:305-320, signed)
    zeroes that equation's delta and recovers the clean Laplacian."""
    Ap, _ = poisson3d(n)
    m = Ap.to_scipy().tocoo()
    nc = m.shape[0]
    rows, cols, vals = [], [], []
    for r, c, v in zip(m.row, m.col, m.data):
        blk = np.zeros((2, 2))
        if r == c:
            blk[0, 0] = v
            blk[0, 1] = ps
            blk[1, 0] = sp_own
            blk[1, 1] = sdiag
        else:
            blk[0, 0] = v
            blk[1, 0] = sp_nbr * (1 if (r + c) % 2 else -1)
        rows.append(r)
        cols.append(c)
        vals.append(blk)
    order = np.lexsort((cols, rows))
    vals = np.asarray(vals)[order]
    rows = np.asarray(rows)[order]
    cols = np.asarray(cols)[order]
    ptr = np.concatenate([[0], np.cumsum(np.bincount(rows, minlength=nc))])
    A = CSR(ptr.astype(np.int64), cols.astype(np.int32), vals, nc)
    return A, np.ones(nc * 2)


def test_drs_beats_quasi_impes():
    """The point of DRS (VERDICT r3 item 5): on a non-diagonally-dominant
    fixture the dynamic row-sum weights must win in iterations."""
    A, rhs = drs_hard_reservoir(10)
    iters = {}
    for cls in (CPR, CPRDRS):
        pre = cls(A, pressure_prm=AMGParams(dtype=jnp.float64,
                                            coarse_enough=100),
                  dtype=jnp.float64)
        solve = make_solver(A, pre, BiCGStab(maxiter=400, tol=1e-8))
        x, info = solve(rhs)
        r = rhs - A.spmv(np.asarray(x))
        assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-6
        iters[cls.weighting] = info.iters
    assert iters["drs"] < iters["quasi_impes"], iters


def test_drs_weight_semantics():
    """Unit checks of the reference delta rules (cpr_drs.hpp:305-320):
    signed eps_dd test, eps_ps pressure-sum test, user weights scaling."""
    A, _ = drs_hard_reservoir(4)
    n = A.nrows
    W = CPRDRS._weights(A)
    # saturation equations: a_dia[1] = -1 < eps_dd * a_off[1] -> delta 0
    assert np.all(W[:, 1] == 0.0)
    assert np.all(W[:, 0] == 1.0)
    # eps_ps: a_top[1] = |ps| per cell; huge eps_ps kills equation 1 even
    # when diagonally dominant; here it is already 0 — check it triggers
    # on a dominance-passing fixture instead
    A2, _ = drs_hard_reservoir(4, sp_own=50.0, sp_nbr=0.1)
    W2 = CPRDRS._weights(A2)          # dominance test passes
    assert np.all(W2[:, 1] == 1.0)
    W2b = CPRDRS._weights(A2, eps_ps=2.0)   # a_top[1]=5 < 2*6 -> dropped
    assert np.all(W2b[:, 1] == 0.0)
    # user weights scale every delta, including the pressure equation's
    w = np.full(n * 2, 0.5)
    W3 = CPRDRS._weights(A2, weights=w)
    assert np.allclose(W3, 0.5)
    with pytest.raises(ValueError, match="weights"):
        CPRDRS._weights(A2, weights=np.ones(3))


def wells_reservoir(n, b=3, n_wells=2):
    """Reservoir block system with appended well cells: trailing cells
    whose equations are NOT reservoir equations (strong diagonal, sparse
    coupling into cell 0's pressure) — the active_rows use case
    (cpr.hpp:85-106)."""
    A, rhs = reservoir_like(n, b)
    m = A.unblock().to_scipy().tolil()
    nc = A.nrows
    N = nc * b
    Nw = N + n_wells * b
    M = sp.lil_matrix((Nw, Nw))
    M[:N, :N] = m
    for w in range(n_wells):
        for i in range(b):
            j = N + w * b + i
            M[j, j] = 100.0
            M[j, w * b] = 1.0          # couple to an early cell's pressure
            M[w * b, j] = 1.0
    A_full = CSR.from_scipy(sp.csr_matrix(M)).to_block(b)
    return A_full, np.ones(Nw), N


@pytest.mark.parametrize("cls", [CPR, CPRDRS])
def test_cpr_active_rows(cls):
    A, rhs, N = wells_reservoir(6, 3)
    pre = cls(A, pressure_prm=AMGParams(dtype=jnp.float64,
                                        coarse_enough=50),
              dtype=jnp.float64, active_rows=N)
    # the pressure hierarchy covers only the leading reservoir cells
    assert pre.p_amg.host_levels[0][0].nrows == N // 3
    solve = make_solver(A, pre, BiCGStab(maxiter=300, tol=1e-8))
    x, info = solve(rhs)
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-6


def test_cpr_active_rows_validation():
    A, rhs, N = wells_reservoir(6, 3)
    with pytest.raises(ValueError, match="multiple"):
        CPR(A, active_rows=N + 1)


def test_cpr_runtime_drs_keys():
    from amgcl_tpu.models.runtime import make_solver_from_config
    A, rhs = drs_hard_reservoir(6)
    solve = make_solver_from_config(A, {
        "precond.class": "cpr",
        "precond.weighting": "drs",
        "precond.eps_dd": "0.2",
        "precond.eps_ps": "0.02",
        "precond.dtype": "float64",
        "precond.pressure.coarse_enough": "100",
        "solver.type": "bicgstab", "solver.maxiter": "400",
        "solver.tol": "1e-8"})
    x, info = solve(rhs)
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-6


def test_cpr_active_rows_singular_well_block():
    """active_rows must never invert the INACTIVE trailing blocks — the
    reference only forms weights over the active rows (cpr.hpp:194), and
    well/constraint blocks are routinely singular."""
    A, rhs, N = wells_reservoir(6, 3)
    # make every trailing well block singular (duplicate an in-block row)
    b = 3
    dia_mask = A.expanded_rows() == A.col
    vals = A.val.copy()
    rows = A.expanded_rows()
    sel = dia_mask & (rows >= N // b)
    blocks = vals[sel]
    blocks[:, 2, :] = blocks[:, 1, :]      # rank-deficient
    vals[sel] = blocks
    A2 = CSR(A.ptr.copy(), A.col.copy(), vals, A.ncols)
    pre = CPR(A2, pressure_prm=AMGParams(dtype=jnp.float64,
                                         coarse_enough=50),
              dtype=jnp.float64, active_rows=N)
    assert pre.p_amg.host_levels[0][0].nrows == N // b


@pytest.mark.parametrize("update_transfer", [True, False])
def test_cpr_partial_update(update_transfer):
    """cpr.hpp:159-186 partial_update: values change, structure reused.
    The updated preconditioner must converge like a freshly built one."""
    A, rhs = reservoir_like(8, 3)
    pre = CPRDRS(A, pressure_prm=AMGParams(dtype=jnp.float64,
                                           coarse_enough=100),
                 dtype=jnp.float64)
    # NON-uniform perturbation on the same structure: a symmetric diagonal
    # congruence D·A·D with per-row factors in [0.6, 1.4] (keeps the system
    # well posed, but changes weights/smoother non-trivially — a uniform
    # scaling would be invisible to DRS and BiCGStab)
    b = A.block_size[0]
    d = 1.0 + 0.4 * np.cos(np.arange(A.nrows * b))
    rows = A.expanded_rows()
    val2 = A.val * np.einsum(
        "ei,ej->eij", d.reshape(-1, b)[rows], d.reshape(-1, b)[A.col])
    A2 = CSR(A.ptr.copy(), A.col.copy(), val2, A.ncols)
    pre.partial_update(A2, update_transfer_ops=update_transfer)
    solve = make_solver(A2, pre, BiCGStab(maxiter=200, tol=1e-8))
    x, info = solve(rhs)
    r = rhs - A2.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-6
    fresh = CPRDRS(A2, pressure_prm=AMGParams(dtype=jnp.float64,
                                              coarse_enough=100),
                   dtype=jnp.float64)
    sf = make_solver(A2, fresh, BiCGStab(maxiter=200, tol=1e-8))
    _, i2 = sf(rhs)
    slack = 2 if update_transfer else 8   # stale/reused ops cost a little
    assert info.iters <= i2.iters + slack


def test_cpr_partial_update_rejects_new_structure():
    A, _ = reservoir_like(6, 3)
    pre = CPR(A, dtype=jnp.float64,
              pressure_prm=AMGParams(dtype=jnp.float64, coarse_enough=100))
    B, _ = reservoir_like(7, 3)
    with pytest.raises(ValueError):
        pre.partial_update(B)


def test_cpr_rebuild_via_make_solver():
    """make_solver.rebuild must reach CPR.partial_update and refresh the
    solver-side operators too (otherwise the Krylov loop runs on the old
    device matrix)."""
    A, rhs = reservoir_like(8, 3)
    pre = CPR(A, dtype=jnp.float64,
              pressure_prm=AMGParams(dtype=jnp.float64, coarse_enough=100))
    solve = make_solver(A, pre, BiCGStab(maxiter=200, tol=1e-8))
    solve(rhs)
    A2 = CSR(A.ptr.copy(), A.col.copy(), A.val * 2.0, A.ncols)
    solve.rebuild(A2)
    x, info = solve(rhs)
    r = rhs - A2.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-6
