"""Solve-as-a-service (ISSUE 7): batched multi-RHS parity, per-RHS
guard independence, the resident SolverService, the donation contract,
and the serving throughput gate.

The parity contract: a B=1 stacked solve matches the unbatched solver
per method (same iteration count, same solution to float tolerance),
and B>1 columns match B independent solves — per-column convergence
masking means a converged column's iterate is frozen while the loop
serves the stragglers, so iteration counts are per-column exact.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import amgcl_tpu.solver as S
from amgcl_tpu.ops import device as dev
from amgcl_tpu.ops import fused_vec as fv
from amgcl_tpu.serve import BlockCG, SolverService
from amgcl_tpu.utils.sample_problem import poisson3d

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_B = 3


def _problem(m=6, dtype=jnp.float64):
    A, rhs = poisson3d(m)
    Ad = dev.to_device(A, "dia", dtype)
    dinv = jnp.asarray(1.0 / A.diagonal(), dtype)

    def precond(r):
        return dinv[:, None] * r if r.ndim == 2 else dinv * r

    rng = np.random.RandomState(7)
    Rh = jnp.asarray(rng.rand(A.nrows, _B), dtype)
    return A, Ad, precond, Rh


_SOLVERS = [
    ("CG", dict(maxiter=200, tol=1e-8)),
    ("BiCGStab", dict(maxiter=200, tol=1e-8)),
    ("BiCGStabL", dict(maxiter=200, tol=1e-8)),
    ("GMRES", dict(maxiter=200, tol=1e-8)),
    ("FGMRES", dict(maxiter=200, tol=1e-8)),
    ("LGMRES", dict(maxiter=200, tol=1e-8)),
    ("IDRs", dict(maxiter=200, tol=1e-8)),
    ("Richardson", dict(maxiter=500, tol=1e-8)),
    ("PreOnly", dict()),
]


@pytest.mark.parametrize("name,kw", _SOLVERS,
                         ids=[n for n, _ in _SOLVERS])
def test_batched_parity_per_method(name, kw):
    """B=1 matches the unbatched solver; B>1 columns match independent
    solves (solution AND per-column iteration count)."""
    A, Ad, precond, Rh = _problem()
    sl = getattr(S, name)(**kw)
    got = sl.solve(Ad, precond, Rh)
    x, iters, resid = got[:3]
    assert x.shape == Rh.shape
    assert iters.shape == (_B,) and resid.shape == (_B,)
    # B=1 stacked vs plain 1-D entry
    g1 = sl.solve(Ad, precond, Rh[:, :1])
    g0 = sl.solve(Ad, precond, Rh[:, 0])
    assert int(g1[1][0]) == int(g0[1])
    np.testing.assert_allclose(np.asarray(g1[0][:, 0]),
                               np.asarray(g0[0]),
                               rtol=1e-9, atol=1e-12)
    # B>1 columns vs independent solves
    for b in range(_B):
        gb = sl.solve(Ad, precond, Rh[:, b])
        assert int(iters[b]) == int(gb[1]), \
            "per-column iteration count drifted (column %d)" % b
        np.testing.assert_allclose(np.asarray(x[:, b]),
                                   np.asarray(gb[0]),
                                   rtol=1e-7, atol=1e-10)
    # per-column guard states ride along, all clean here
    hs = got[-1]
    assert np.asarray(hs.flags).shape == (_B,)
    assert not np.asarray(hs.flags).any()


def test_batched_guard_trips_are_independent():
    """A poisoned column (an x0 so large its first iteration overflows
    to NaN) trips ITS guard and freezes ITS iterate at iteration 0; the
    healthy columns converge untouched."""
    A, Ad, precond, Rh = _problem()
    x0 = np.zeros(Rh.shape)
    x0[:, 1] = 1e200          # first body step overflows -> NaN guard
    sl = S.CG(maxiter=100, tol=1e-8)
    x, iters, resid, hs = sl.solve(Ad, precond, Rh, jnp.asarray(x0))
    flags = np.asarray(hs.flags)
    from amgcl_tpu.telemetry import health as H
    assert flags[1] & H.NAN
    assert flags[0] == 0 and flags[2] == 0
    assert int(iters[1]) == 0     # no committed iteration on the trip
    for b in (0, 2):
        gb = sl.solve(Ad, precond, Rh[:, b])
        assert int(iters[b]) == int(gb[1])
        np.testing.assert_allclose(np.asarray(x[:, b]),
                                   np.asarray(gb[0]),
                                   rtol=1e-7, atol=1e-10)
    # decode: headline reflects the union, per_rhs isolates the column
    from amgcl_tpu.serve import decode_batched_health
    dec = decode_batched_health(flags, np.asarray(hs.first_it))
    assert not dec["ok"] and dec["nan"]
    assert dec["unhealthy_rhs"] == [1]
    assert dec["per_rhs"][0]["ok"] and not dec["per_rhs"][1]["ok"]


def test_blockcg_shared_subspace():
    """Block CG converges every column and needs no more iterations
    than the worst independent CG column (the shared subspace can only
    add information)."""
    A, Ad, precond, Rh = _problem()
    bcg = BlockCG(maxiter=200, tol=1e-8)
    x, iters, resid = bcg.solve(Ad, precond, Rh)[:3]
    cg_iters = []
    for b in range(_B):
        g = S.CG(maxiter=200, tol=1e-8).solve(Ad, precond, Rh[:, b])
        cg_iters.append(int(g[1]))
        rb = np.asarray(Rh[:, b], np.float64)
        xr = np.asarray(x[:, b], np.float64)
        rel = np.linalg.norm(rb - A.spmv(xr)) / np.linalg.norm(rb)
        assert rel < 1e-7, rel
    assert int(np.max(np.asarray(iters))) <= max(cg_iters)
    # 1-D rhs runs as B=1 and returns the plain shapes
    g1 = bcg.solve(Ad, precond, Rh[:, 0])
    assert g1[0].ndim == 1 and np.ndim(g1[1]) == 0
    # registered in the runtime registry as solver.type=blockcg
    from amgcl_tpu.models.runtime import SOLVERS
    assert SOLVERS["blockcg"] is BlockCG


def test_fused_vec_stacked_primitives():
    """The (n, B) tier of ops/fused_vec.py matches the per-column
    composition exactly (same XLA arithmetic, one pass)."""
    rng = np.random.RandomState(11)
    p, q, x, r = (jnp.asarray(rng.rand(64, 4)) for _ in range(4))
    al = jnp.asarray(rng.rand(4))
    xn, rn, rr = fv.xr_update(al, p, q, x, r)
    for b in range(4):
        xb, rb, rrb = fv.xr_update(al[b], p[:, b], q[:, b],
                                   x[:, b], r[:, b])
        np.testing.assert_allclose(np.asarray(xn[:, b]), np.asarray(xb),
                                   rtol=1e-12)
        np.testing.assert_allclose(float(rr[b]), float(rrb), rtol=1e-12)
    z, zz = fv.axpby_dot(al, p, 0.5, x)
    xn2, rn2, rr2, rhr2 = fv.bicgstab_tail(al, p, 0.3, q, x, r,
                                           p * 0, q)
    assert z.shape == (64, 4) and zz.shape == (4,)
    assert rr2.shape == (4,) and rhr2.shape == (4,)
    A, rhs = poisson3d(5)
    Ad = dev.to_device(A, "dia", jnp.float64)
    F = jnp.asarray(rng.rand(A.nrows, 4))
    X = jnp.asarray(rng.rand(A.nrows, 4))
    rres, rrv = fv.residual_dot(F, Ad, X)
    ref = np.asarray(F) - np.stack(
        [A.spmv(np.asarray(X[:, b])) for b in range(4)], axis=1)
    np.testing.assert_allclose(np.asarray(rres), ref, rtol=1e-10,
                               atol=1e-12)
    assert rrv.shape == (4,)


def test_make_solver_batched_end_to_end():
    """make_solver(batch=B) + AMG V-cycle accept stacked vectors end to
    end; the report carries per-RHS detail, solves_per_sec and the
    batched per-iteration byte model."""
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.models.make_solver import make_solver
    A, rhs = poisson3d(8)
    ms = make_solver(A, AMGParams(dtype=jnp.float32, coarse_enough=50),
                     solver=S.CG(maxiter=50, tol=1e-6), batch=4)
    assert ms.batch == 4
    x1, info1 = ms(rhs)
    R = np.stack([rhs, 2 * rhs, 0.5 * rhs, -rhs], axis=1)
    xb, infob = ms(R)
    assert xb.shape == (len(rhs), 4)
    per = infob.extra["per_rhs"]
    assert len(per["iters"]) == 4 and infob.extra["batch"] == 4
    assert infob.iters == max(per["iters"]) == info1.iters
    assert infob.solves_per_sec and infob.solves_per_sec > 0
    assert "solves_per_sec" in infob.to_dict()
    # scaled rhs: same system, scaled solution
    np.testing.assert_allclose(np.asarray(xb[:, 1]),
                               2 * np.asarray(x1), rtol=1e-4,
                               atol=1e-5)
    assert infob.health is not None and infob.health["ok"]
    assert len(infob.health["per_rhs"]) == 4
    pi = (infob.resources or {}).get("per_iteration") or {}
    assert pi.get("batch") == 4
    # x0 must match the stacked shape
    with pytest.raises(ValueError):
        ms(R, x0=rhs)
    # refinement is gated off for stacked solves
    ms_ref = make_solver(A, AMGParams(dtype=jnp.float32,
                                      coarse_enough=50),
                         solver=S.CG(maxiter=50, tol=1e-6), refine=2)
    with pytest.raises(ValueError):
        ms_ref(R)


def test_krylov_iteration_model_batch_amortizes_operator():
    """Satellite: the batch axis scales FLOPs by B but amortizes the
    operator's stored bytes — bytes(B) < B * bytes(1)."""
    from amgcl_tpu.telemetry.ledger import krylov_iteration_model
    A, _ = poisson3d(8)
    Ad = dev.to_device(A, "dia", jnp.float32)
    m1 = krylov_iteration_model("CG", Ad)
    m8 = krylov_iteration_model("CG", Ad, batch=8)
    assert m8["batch"] == 8
    assert m8["flops"] == 8 * m1["flops"]
    assert m8["bytes"] < 8 * m1["bytes"]
    assert m8["bytes"] > m1["bytes"]


def test_service_queue_and_stats(tmp_path):
    """SolverService: async submits resolve to per-request results that
    match direct solves; stats carry solves/sec + p50/p99 latency; the
    per-batch 'serve' JSONL events land in the sink."""
    from amgcl_tpu import telemetry
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.models.make_solver import make_solver
    out = tmp_path / "serve.jsonl"
    telemetry.set_default_sink(telemetry.JsonlSink(str(out)))
    try:
        A, rhs = poisson3d(8)
        ms = make_solver(A, AMGParams(dtype=jnp.float32,
                                      coarse_enough=50),
                         solver=S.CG(maxiter=50, tol=1e-6))
        x_direct, _ = ms(rhs)
        with SolverService(ms, batch=4, flush_ms=25) as svc:
            futs = [svc.submit(rhs * (1.0 + k)) for k in range(6)]
            results = [f.result(timeout=120) for f in futs]
            stats = svc.stats()
        for k, (xk, rep) in enumerate(results):
            np.testing.assert_allclose(
                xk, (1.0 + k) * np.asarray(x_direct),
                rtol=1e-4, atol=1e-5)
            assert rep.iters > 0 and rep.extra["batch"] >= 1
        assert stats["requests"] == 6
        assert stats["batches"] >= 2          # bucket 4 forces a split
        assert stats["latency_s"]["p50"] <= stats["latency_s"]["p99"]
        assert stats["solves_per_sec"] > 0
    finally:
        telemetry.set_default_sink(telemetry.NullSink())
    recs = [json.loads(ln) for ln in open(out)]
    serve = [r for r in recs if r.get("event") == "serve"]
    assert serve, "no 'serve' events emitted"
    assert any(r.get("final") for r in serve)
    assert any(r.get("solves_per_sec") for r in serve)


def test_service_request_timeout_and_refine_gate():
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.models.make_solver import make_solver
    A, rhs = poisson3d(6)
    ms = make_solver(A, AMGParams(dtype=jnp.float32, coarse_enough=50),
                     solver=S.CG(maxiter=50, tol=1e-6))
    with SolverService(ms, batch=2, flush_ms=5) as svc:
        fut = svc.submit(rhs, timeout_s=-1.0)    # already expired
        with pytest.raises(TimeoutError):
            fut.result(timeout=60)
    ms_ref = make_solver(A, AMGParams(dtype=jnp.float32,
                                      coarse_enough=50),
                         solver=S.CG(maxiter=50, tol=1e-6), refine=1)
    with pytest.raises(ValueError):
        SolverService(ms_ref)


def test_serve_donation_contract():
    """The resident loop's lowered program aliases exactly the donated
    iterate buffer — the static contract the analysis gate enforces."""
    from amgcl_tpu.analysis import jaxpr_audit as ja
    from amgcl_tpu.telemetry.ledger import DONATION_CONTRACTS
    assert DONATION_CONTRACTS["serve.solve_step"] == 1
    rec = ja.audit_serve()
    assert rec["donation"]["aliasing_present"]
    assert rec["donation"]["donated_args"] == 1
    assert ja.check_serve(rec) == []
    # a drifted contract is an error finding, not a silent pass
    bad = dict(rec, donation={"donated_args": 0,
                              "aliasing_present": False})
    finds = ja.check_serve(bad)
    assert finds and finds[0]["severity"] == "error"


def test_gate_throughput_check():
    """bench.py --gate: the B=32 solves/sec floor trips on a drop below
    the tolerance fraction and skips across device platforms."""
    sys.path.insert(0, _REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    base = {"iters": 10, "value": 1.0, "device_platform": "cpu",
            "throughput": {"b32_sps": 100.0}}
    good = {"iters": 10, "value": 1.0, "device_platform": "cpu",
            "throughput": {"b32_sps": 90.0}}
    bad = {"iters": 10, "value": 1.0, "device_platform": "cpu",
           "throughput": {"b32_sps": 50.0}}
    other = {"iters": 10, "value": 1.0, "device_platform": "tpu",
             "throughput": {"b32_sps": 1.0}}
    ok, checks = bench.run_gate(good, base)
    row = [c for c in checks if c["check"] == "throughput_b32"][0]
    assert ok and row["status"] == "ok"
    ok, checks = bench.run_gate(bad, base)
    row = [c for c in checks if c["check"] == "throughput_b32"][0]
    assert not ok and row["status"] == "regression"
    ok, checks = bench.run_gate(other, base)
    row = [c for c in checks if c["check"] == "throughput_b32"][0]
    assert row["status"] == "skipped" and "platform_mismatch" \
        in row["reason"]
    # records predating the metric skip, never regress
    ok, checks = bench.run_gate({"iters": 10, "value": 1.0,
                                 "device_platform": "cpu"}, base)
    row = [c for c in checks if c["check"] == "throughput_b32"][0]
    assert ok and row["status"] == "skipped"


@pytest.mark.serial
def test_cli_serve_smoke(tmp_path):
    """`python -m amgcl_tpu.cli --serve N` end to end on the 8-virtual-
    device CPU topology: resident service, per-request iterations,
    throughput/latency lines, 'serve' events in the telemetry sink."""
    out = tmp_path / "serve_cli.jsonl"
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=8")
               .strip())
    r = subprocess.run(
        [sys.executable, "-m", "amgcl_tpu.cli", "-n", "8",
         "-p", "solver.type=cg", "--serve", "5", "--serve-batch", "2",
         "--telemetry", str(out)],
        capture_output=True, text=True, timeout=600, cwd=_REPO, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "serve: 5 request(s), batch bucket 2" in r.stdout
    assert "iters per request:" in r.stdout
    assert "throughput:" in r.stdout
    recs = [json.loads(ln) for ln in open(out)]
    serve = [x for x in recs if x.get("event") == "serve"]
    assert serve and any(x.get("final") for x in serve)
