"""Fortran binding drift check (VERDICT r3 item 9).

Two layers:
1. a symbol-level consistency check that runs EVERYWHERE: every
   ``bind(c)`` interface declared in fortran/amgcl_tpu.f90 must name an
   ``extern "C"`` function that actually exists in csrc/c_api.cpp with
   the same argument count, so signature drift is caught without a
   Fortran compiler;
2. an actual gfortran compile smoke test, skipped when no Fortran
   compiler is present in the image (none is baked in today).
"""

import os
import re
import shutil
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
F90 = os.path.join(REPO, "fortran", "amgcl_tpu.f90")
CAPI = os.path.join(REPO, "csrc", "c_api.cpp")


def _fortran_interfaces():
    """{name: n_args} for every bind(c) function/subroutine interface."""
    src = open(F90).read().lower()
    # join continuation lines (trailing &)
    src = re.sub(r"&\s*\n\s*", " ", src)
    out = {}
    for m in re.finditer(
            r"(?:function|subroutine)\s+(amgcl_tpu_\w+)\s*\(([^)]*)\)"
            r"\s*bind\(c\)", src):
        name = m.group(1)
        args = [a for a in m.group(2).split(",") if a.strip()]
        out[name] = len(args)
    return out


def _c_functions():
    """{name: n_args} for every amgcl_tpu_* C function definition."""
    src = open(CAPI).read()
    src = re.sub(r"\s+", " ", src)
    out = {}
    for m in re.finditer(
            r"[\w* ]+?\b(amgcl_tpu_\w+)\s*\(([^)]*)\)\s*\{", src):
        name = m.group(1)
        args = [a for a in m.group(2).split(",") if a.strip()
                and a.strip() != "void"]
        out[name] = len(args)
    return out


def test_fortran_symbols_match_c_api():
    fns = _fortran_interfaces()
    cs = _c_functions()
    assert fns, "no bind(c) interfaces parsed from the .f90"
    missing = sorted(set(fns) - set(cs))
    assert not missing, (
        "Fortran declares symbols absent from csrc/c_api.cpp: %s" % missing)
    mismatched = {k: (fns[k], cs[k]) for k in fns if fns[k] != cs[k]}
    assert not mismatched, (
        "argument-count drift between fortran/amgcl_tpu.f90 and "
        "csrc/c_api.cpp: {name: (fortran, c)} = %r" % mismatched)


def test_fortran_compiles():
    fc = shutil.which("gfortran") or shutil.which("flang")
    if fc is None:
        pytest.skip("no Fortran compiler in the image")
    r = subprocess.run(
        [fc, "-c", F90, "-o", "/tmp/amgcl_tpu_mod_test.o",
         "-J", "/tmp"], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
