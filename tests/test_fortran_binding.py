"""Fortran binding drift check (VERDICT r3 item 9).

Two layers:
1. a symbol-level consistency check that runs EVERYWHERE: every
   ``bind(c)`` interface declared in fortran/amgcl_tpu.f90 must name an
   ``extern "C"`` function that actually exists in csrc/c_api.cpp with
   the same argument count, so signature drift is caught without a
   Fortran compiler;
2. an actual gfortran compile smoke test, skipped when no Fortran
   compiler is present in the image (none is baked in today).
"""

import os
import re
import shutil
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
F90 = os.path.join(REPO, "fortran", "amgcl_tpu.f90")
CAPI = os.path.join(REPO, "csrc", "c_api.cpp")


def _fortran_interfaces():
    """{name: n_args} for every bind(c) function/subroutine interface."""
    src = open(F90).read().lower()
    # join continuation lines (trailing &)
    src = re.sub(r"&\s*\n\s*", " ", src)
    out = {}
    for m in re.finditer(
            r"(?:function|subroutine)\s+(amgcl_tpu_\w+)\s*\(([^)]*)\)"
            r"\s*bind\(c\)", src):
        name = m.group(1)
        args = [a for a in m.group(2).split(",") if a.strip()]
        out[name] = len(args)
    return out


def _c_functions():
    """{name: n_args} for every amgcl_tpu_* C function definition."""
    src = open(CAPI).read()
    src = re.sub(r"\s+", " ", src)
    out = {}
    for m in re.finditer(
            r"[\w* ]+?\b(amgcl_tpu_\w+)\s*\(([^)]*)\)\s*\{", src):
        name = m.group(1)
        args = [a for a in m.group(2).split(",") if a.strip()
                and a.strip() != "void"]
        out[name] = len(args)
    return out


def test_fortran_symbols_match_c_api():
    fns = _fortran_interfaces()
    cs = _c_functions()
    assert fns, "no bind(c) interfaces parsed from the .f90"
    missing = sorted(set(fns) - set(cs))
    assert not missing, (
        "Fortran declares symbols absent from csrc/c_api.cpp: %s" % missing)
    mismatched = {k: (fns[k], cs[k]) for k in fns if fns[k] != cs[k]}
    assert not mismatched, (
        "argument-count drift between fortran/amgcl_tpu.f90 and "
        "csrc/c_api.cpp: {name: (fortran, c)} = %r" % mismatched)


HDR = os.path.join(REPO, "include", "amgcl_tpu.h")

# iso_c_binding declaration -> the C parameter shapes it interoperates
# with. (kind, is_value, is_array): value scalars must match non-pointer
# C params of the same base type; by-ref / assumed-size args must match a
# pointer to that base type; c_ptr value args match handle/pointer params.
# Derived bind(c) types (e.g. type(conv_info)) interoperate with a struct
# pointer by-ref / a struct by value.
_F2C = {
    ("c_int", True): {"int"},
    ("c_int", False): {"int*"},
    ("c_double", True): {"double"},
    ("c_double", False): {"double*"},
    ("c_char", False): {"char*"},
    ("c_ptr", True): {"ptr"},
    ("c_ptr", False): {"ptr*"},
}


def _f2c_expected(kind, is_value):
    got = _F2C.get((kind, is_value))
    if got is not None:
        return got
    if not kind.startswith("c_"):        # derived bind(c) type
        return {"ptr"} if is_value else {"ptr*"}
    return None


def _fortran_arg_types():
    """{name: [(kind, is_value)] in declaration order} per interface."""
    src = open(F90).read().lower()
    src = re.sub(r"&\s*\n\s*", " ", src)
    out = {}
    blocks = re.split(r"\bend (?:function|subroutine)\b", src)
    for blk in blocks:
        m = re.search(
            r"(?:function|subroutine)\s+(amgcl_tpu_\w+)\s*\(([^)]*)\)"
            r"\s*bind\(c\)", blk)
        if not m:
            continue
        name = m.group(1)
        argnames = [a.strip() for a in m.group(2).split(",") if a.strip()]
        decls = {}
        for d in re.finditer(
                r"(integer|real|character|type)\s*\((\w+)\)\s*"
                r"([^:\n]*)::[ \t]*([\w (),*]+)", blk):
            kind = d.group(2)
            attrs = d.group(3)
            is_value = "value" in attrs
            for nm in d.group(4).split(","):
                nm = nm.strip().split("(")[0].strip()
                if nm:
                    decls[nm] = (kind, is_value)
        if all(a in decls for a in argnames):
            out[name] = [decls[a] for a in argnames]
    return out


def _c_prototype_types():
    """{name: [normalized param types]} from the public header; 'ptr' =
    any handle/pointer-to-struct, 'T*' = pointer to base type T."""
    src = open(HDR).read()
    src = re.sub(r"/\*.*?\*/", " ", src, flags=re.S)
    src = re.sub(r"//[^\n]*", " ", src)
    src = re.sub(r"\s+", " ", src)
    out = {}
    for m in re.finditer(r"[\w* ]+?\b(amgcl_tpu_\w+)\s*\(([^)]*)\)\s*;",
                         src):
        name = m.group(1)
        params = []
        for a in m.group(2).split(","):
            a = a.strip()
            if not a or a == "void":
                continue
            a = a.replace("const ", "").strip()
            ptr = "*" in a
            base = a.replace("*", " ").split()[0]
            if base in ("amgclHandle",) or base.startswith("struct"):
                base = "ptr"
            params.append(base + ("*" if ptr else ""))
        out[name] = params
    return out


def test_fortran_argument_types_interoperate():
    """Beyond symbol/arity drift: every Fortran argument's iso_c_binding
    kind + value attribute must interoperate with the C prototype's
    parameter type at the same position (the check a Fortran compiler
    would do against the header — VERDICT r4 missing item 4, runnable
    without gfortran)."""
    ftypes = _fortran_arg_types()
    ctypes = _c_prototype_types()
    assert ftypes, "no typed interfaces parsed from the .f90"
    # every bind(c) interface must be fully typed-parsed: a silently
    # skipped interface would make this test vacuous for exactly the
    # declaration that drifted
    skipped = sorted(set(_fortran_interfaces()) - set(ftypes))
    assert not skipped, ("interfaces with unparsed argument "
                         "declarations: %s" % skipped)
    problems = []
    for name, fargs in ftypes.items():
        if name not in ctypes:
            continue                    # covered by the symbol test
        cargs = ctypes[name]
        if len(cargs) != len(fargs):
            continue                    # covered by the arity test
        for i, ((kind, is_value), ct) in enumerate(zip(fargs, cargs)):
            ok = _f2c_expected(kind, is_value)
            if ok is None or ct not in ok:
                problems.append("%s arg %d: fortran %s%s vs C %s"
                                % (name, i, kind,
                                   "" if is_value else " (by-ref)", ct))
    assert not problems, "\n".join(problems)


def test_fortran_compiles():
    fc = shutil.which("gfortran") or shutil.which("flang")
    if fc is None:
        pytest.skip("no Fortran compiler in the image")
    r = subprocess.run(
        [fc, "-c", F90, "-o", "/tmp/amgcl_tpu_mod_test.o",
         "-J", "/tmp"], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
