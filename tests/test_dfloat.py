"""Double-float outer residual (ops/dfloat.py) + refine_dtype='df32'
(reference capability: mixed-precision refinement, mixing.hpp's spirit
— re-designed f64-free for the TPU, where float64 is software-emulated)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from amgcl_tpu.ops import device as dev
from amgcl_tpu.ops.dfloat import (two_sum, two_prod, df_decompose,
                                  df_add_vec, dia_residual_df)
from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.utils.sample_problem import poisson3d


def test_two_sum_exact():
    rng = np.random.RandomState(0)
    a = jnp.asarray(rng.randn(1000), jnp.float32)
    b = jnp.asarray(rng.randn(1000) * 1e-6, jnp.float32)
    s, e = two_sum(a, b)
    got = np.asarray(s, np.float64) + np.asarray(e, np.float64)
    want = np.asarray(a, np.float64) + np.asarray(b, np.float64)
    np.testing.assert_array_equal(got, want)


def test_two_prod_exact():
    rng = np.random.RandomState(1)
    a = jnp.asarray(rng.randn(1000), jnp.float32)
    b = jnp.asarray(rng.randn(1000), jnp.float32)
    p, e = two_prod(a, b)
    got = np.asarray(p, np.float64) + np.asarray(e, np.float64)
    want = np.asarray(a, np.float64) * np.asarray(b, np.float64)
    np.testing.assert_array_equal(got, want)


def test_df_residual_beats_f32_floor():
    """The compensated residual of a near-solution must match the f64
    residual to far below the plain-f32 evaluation floor."""
    A, rhs = poisson3d(16)
    Ad = dev.to_device(A, "dia", jnp.float32)
    A_lo = dev.csr_to_dia_remainder(A, Ad)
    # a high-quality solution: f64 solve on the host
    import scipy.sparse as sp
    import scipy.sparse.linalg as spla
    As = sp.csr_matrix((A.val, A.col, A.ptr), shape=A.shape)
    x64 = spla.spsolve(As.tocsc(), rhs)
    xh, xl = df_decompose(x64)
    r64 = rhs - As @ x64
    b_hi = jnp.asarray(rhs, jnp.float32)
    r_df = np.asarray(dia_residual_df(
        Ad.offsets, Ad.data, A_lo.data, b_hi,
        jnp.zeros_like(b_hi), jnp.asarray(xh), jnp.asarray(xl)),
        np.float64)
    # plain f32 residual for comparison
    r_f32 = np.asarray(
        dev.residual(b_hi, Ad, jnp.asarray(xh)), np.float64)
    err_df = np.linalg.norm(r_df - r64)
    err_f32 = np.linalg.norm(r_f32 - r64)
    # b rounded to f32 shifts both by the same ~eps32*||b||; the df
    # evaluation must recover the A x part to ~eps32^2 while plain f32
    # is floored at ~eps32*||A||*||x||
    assert err_df < 1e-3 * err_f32 + 1e-10, (err_df, err_f32)


def test_df_add_vec_carries_low_part():
    xh = jnp.asarray([1.0], jnp.float32)
    xl = jnp.asarray([0.0], jnp.float32)
    d = jnp.asarray([1e-9], jnp.float32)
    nh, nl = df_add_vec(xh, xl, d)
    got = float((np.asarray(nh, np.float64)
                 + np.asarray(nl, np.float64))[0])
    assert abs(got - (1.0 + 1e-9)) < 1e-14


def test_refine_df32_end_to_end():
    """df32 refinement reaches the same true-residual class as float64
    refinement on the structured Poisson system."""
    from amgcl_tpu.models.make_solver import make_solver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.cg import CG
    A, rhs = poisson3d(20)
    s_df = make_solver(A, AMGParams(dtype=jnp.float32),
                       CG(maxiter=100, tol=1e-7), refine=3,
                       refine_dtype="df32")
    assert s_df.refine_mode == "df32"
    x, info = s_df(rhs)
    x = np.asarray(x, np.float64)
    tr = np.linalg.norm(rhs - A.spmv(x)) / np.linalg.norm(rhs)
    assert tr < 2e-7, tr
    # and beats the no-refinement f32 floor
    s0 = make_solver(A, AMGParams(dtype=jnp.float32),
                     CG(maxiter=100, tol=1e-7), refine=0)
    x0, _ = s0(rhs)
    tr0 = np.linalg.norm(rhs - A.spmv(np.asarray(x0, np.float64))) \
        / np.linalg.norm(rhs)
    assert tr < tr0 or tr < 1e-7


def test_refine_df32_bicgstab():
    """df32 refinement through a solver WITHOUT the abstol kwarg (the
    has_abstol=False leg of the shared loop)."""
    from amgcl_tpu.models.make_solver import make_solver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.bicgstab import BiCGStab
    A, rhs = poisson3d(16)
    s = make_solver(A, AMGParams(dtype=jnp.float32),
                    BiCGStab(maxiter=100, tol=1e-7), refine=3,
                    refine_dtype="df32")
    assert s.refine_mode == "df32"
    x, info = s(rhs)
    tr = np.linalg.norm(rhs - A.spmv(np.asarray(x, np.float64))) \
        / np.linalg.norm(rhs)
    assert tr < 2e-7, tr


def test_refine_df32_needs_dia():
    from amgcl_tpu.models.make_solver import make_solver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.cg import CG
    from amgcl_tpu.ops.unstructured import fe_like_problem
    A, _ = fe_like_problem(n=800, nnz_target=8000, seed=1)
    with pytest.raises(ValueError, match="df32"):
        make_solver(A, AMGParams(dtype=jnp.float32), CG(), refine=2,
                    refine_dtype="df32", matrix_format="ell")
