"""Multi-tenant solver farm (ISSUE 11): the operator registry's
hit/rebuild/miss paths (rebuild bit-identity preserved through the
registry), LRU eviction + readmission determinism under a tiny byte
budget, cross-tenant isolation of health/SLO state and metric labels,
the fair-share starvation bound, concurrent submit stress, the capi
roundtrip, the farm gate, and the serial CLI ``--farm`` smoke."""

import gc
import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest
import jax.numpy as jnp

from amgcl_tpu.models.amg import AMGParams
from amgcl_tpu.models.make_solver import make_solver
from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.serve import SolverFarm, SolverService
from amgcl_tpu.serve.registry import (OperatorRegistry,
                                      sparsity_fingerprint,
                                      stable_config_key)
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.utils.sample_problem import poisson3d

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _prm():
    return AMGParams(dtype=jnp.float32, coarse_enough=50)


def _bundle_builder():
    return lambda Ah: make_solver(Ah, _prm(), CG(maxiter=80, tol=1e-7))


def _farm(**kw):
    kw.setdefault("batch", 4)
    kw.setdefault("flush_ms", 10)
    kw.setdefault("metrics_port", -9)
    return SolverFarm(**kw)


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_sparsity_fingerprint_pattern_keyed():
    """The fingerprint keys the PATTERN: value changes keep it, pattern
    changes move it — and it is cached on the matrix object."""
    A, _ = poisson3d(6)
    B, _ = poisson3d(7)
    fp = sparsity_fingerprint(A)
    assert fp == sparsity_fingerprint(CSR(A.ptr, A.col, 2.0 * A.val,
                                          A.ncols))
    assert fp != sparsity_fingerprint(B)
    assert A._sparsity_fp == fp          # cached


def test_registry_hit_rebuild_miss_paths():
    """The three acquire outcomes, their counters, and the acceptance
    invariant: the rebuild path is measurably cheaper than the fresh
    setup it replaces, and the rebuilt hierarchy is bit-identical to a
    fresh build (PR-9 contract preserved through the registry)."""
    A, rhs = poisson3d(8)
    reg = OperatorRegistry()
    key = stable_config_key(CG(maxiter=80, tol=1e-7), _prm())
    e1, o1 = reg.acquire("owner", A, _bundle_builder(), config_key=key)
    assert o1 == "miss" and reg.misses == 1
    # bit-identical matrix: shared as-is
    A_same = CSR(A.ptr, A.col, A.val.copy(), A.ncols)
    e2, o2 = reg.acquire("other", A_same, _bundle_builder(),
                         config_key=key)
    assert o2 == "hit" and e2 is e1 and reg.hits == 1
    # same pattern, new values, sole/orphaned ownership: rebuild
    reg.release("other")
    A2 = CSR(A.ptr, A.col, 2.0 * A.val, A.ncols)
    e3, o3 = reg.acquire("owner", A2, _bundle_builder(),
                         config_key=key)
    assert o3 == "rebuild" and e3 is e1 and reg.rebuilds == 1
    assert e3.rebuild_s is not None and e3.rebuild_s < e3.setup_s
    # bit-identity through the registry: the rebuilt bundle solves
    # exactly like a fresh build of the new matrix
    x_reg, _ = e3.obj(rhs)
    fresh = make_solver(A2, _prm(), CG(maxiter=80, tol=1e-7))
    x_fresh, _ = fresh(rhs)
    assert np.array_equal(np.asarray(x_reg), np.asarray(x_fresh))
    # a different config key is a different operator
    key2 = stable_config_key(CG(maxiter=50, tol=1e-5), _prm())
    _e4, o4 = reg.acquire("owner", A2, _bundle_builder(),
                          config_key=key2)
    assert o4 == "miss"


def test_registry_snapshot_defeats_inplace_mutation():
    """Mutating the value array IN PLACE and re-registering (the
    pyamgcl time-stepping idiom) must take the rebuild path, not 'hit'
    a hierarchy built from the stale values — the entry compares
    against a snapshot of what was built, never the caller's live
    buffer."""
    A, rhs = poisson3d(6)
    reg = OperatorRegistry()
    e1, o1 = reg.acquire("o", A, _bundle_builder())
    assert o1 == "miss"
    x_old, _ = e1.obj(rhs)
    A.val *= 2.0                    # in place: same array object
    A2 = CSR(A.ptr, A.col, A.val, A.ncols)
    e2, o2 = reg.acquire("o", A2, _bundle_builder())
    assert o2 == "rebuild" and e2 is e1
    x_new, _ = e2.obj(rhs)
    np.testing.assert_allclose(np.asarray(x_new),
                               np.asarray(x_old) / 2.0,
                               rtol=1e-4, atol=1e-6)


def test_config_key_sees_nested_policy_fields():
    """Two same-typed coarsening policies with different thresholds are
    different operators — the config key recurses into nested config
    objects' scalar fields."""
    from amgcl_tpu.coarsening.smoothed_aggregation import \
        SmoothedAggregation
    k1 = stable_config_key(AMGParams(
        coarsening=SmoothedAggregation(eps_strong=0.08)))
    k2 = stable_config_key(AMGParams(
        coarsening=SmoothedAggregation(eps_strong=0.25)))
    assert k1 != k2
    k3 = stable_config_key(AMGParams(
        coarsening=SmoothedAggregation(eps_strong=0.08)))
    assert k1 == k3                  # deterministic across instances


def test_registry_never_rebuilds_a_live_co_owner():
    """Same sparsity + new values while ANOTHER owner is live on the
    entry must NOT clobber it — fresh build (miss), both operators keep
    their own values."""
    A, rhs = poisson3d(6)
    reg = OperatorRegistry()
    e1, _ = reg.acquire("a", A, _bundle_builder())
    A2 = CSR(A.ptr, A.col, 3.0 * A.val, A.ncols)
    e2, o2 = reg.acquire("b", A2, _bundle_builder())
    assert o2 == "miss" and e2 is not e1
    x1, _ = e1.obj(rhs)
    x2, _ = e2.obj(rhs)
    # 3A x = b  =>  x = (1/3) A^{-1} b — the two entries really carry
    # different operators
    np.testing.assert_allclose(np.asarray(x2), np.asarray(x1) / 3.0,
                               rtol=1e-4, atol=1e-6)


def test_registry_orphan_cap_and_probe():
    """max_orphans bounds ownerless entries (oldest dropped first —
    the pre-registry free-on-drop behavior, bounded); probe() predicts
    acquire's outcome without building."""
    reg = OperatorRegistry(max_orphans=1)
    mats = [poisson3d(m)[0] for m in (5, 6, 7)]
    for k, A in enumerate(mats):
        assert reg.probe("o%d" % k, A) == "miss"
        reg.acquire("o%d" % k, A, _bundle_builder())
    assert reg.probe("o0", mats[0]) == "hit"
    for k in range(3):
        reg.release("o%d" % k)      # orphan one at a time; cap = 1
    assert len(reg.entries()) == 1  # only the newest orphan survives
    assert reg.entries()[0].fingerprint == \
        sparsity_fingerprint(mats[2])
    # an orphaned entry is a rebuild target for a returning registrant
    A2 = CSR(mats[2].ptr, mats[2].col, 2.0 * mats[2].val,
             mats[2].ncols)
    assert reg.probe("new", A2) == "rebuild"
    _e, o = reg.acquire("new", A2, _bundle_builder())
    assert o == "rebuild"


def test_registry_release_keep_preserves_new_entry():
    """release(keep=) drops the owner from every OTHER entry in one
    atomic call — the re-registration idiom that never leaves the
    previous entry ownerless while the tenant is still live on it (an
    ownerless entry is a legal rebuild target for any concurrent
    same-pattern registrant)."""
    A, _ = poisson3d(6)
    B, _ = poisson3d(7)
    reg = OperatorRegistry()
    e1, _ = reg.acquire("o", A, _bundle_builder())
    e2, _ = reg.acquire("o", B, _bundle_builder())
    assert e1.owners == {"o"} and e2.owners == {"o"}
    reg.release("o", keep=e2)
    assert not e1.owners and e2.owners == {"o"}


def test_registry_rebuild_ok_guard_vetoes_rebuild():
    """A rebuild_ok guard turns the rebuild path into a miss (and
    probe() predicts it) — the hook the farm uses to keep the registry
    from rebuilding an entry pinned by an in-flight batch or still
    referenced by a live tenant."""
    A, _ = poisson3d(6)
    reg = OperatorRegistry()
    e1, _ = reg.acquire("o", A, _bundle_builder())
    reg.release("o")                 # orphan: normally a rebuild target
    A2 = CSR(A.ptr, A.col, 2.0 * A.val, A.ncols)
    veto = lambda _e: False          # noqa: E731
    assert reg.probe("p", A2, rebuild_ok=veto) == "miss"
    e2, o2 = reg.acquire("p", A2, _bundle_builder(), rebuild_ok=veto)
    assert o2 == "miss" and e2 is not e1
    # without the veto the orphan is still the rebuild target
    A3 = CSR(A.ptr, A.col, 3.0 * A.val, A.ncols)
    assert reg.probe("q", A3) == "rebuild"


def test_registry_uid_mint_is_atomic_across_threads():
    """Concurrent entry construction (two registries, no shared lock)
    never mints duplicate uids — the sequence is an atomic counter,
    not a bare class-attribute read-modify-write."""
    from amgcl_tpu.serve.registry import RegistryEntry
    uids = []

    def mint():
        got = [RegistryEntry("fp", "", object(), np.zeros(1), 0.0).uid
               for _ in range(200)]
        uids.extend(got)

    threads = [threading.Thread(target=mint) for _ in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=60)
    assert len(set(uids)) == len(uids) == 800


def test_submit_waiting_on_full_queue_survives_reregister():
    """A submit() blocked on a full queue re-resolves the tenant after
    every wait: a size-changing re-registration fails it with a clear
    error instead of appending to the replaced tenant's abandoned
    deque (which would hang the caller forever)."""
    A6, rhs6 = poisson3d(6)
    A7, rhs7 = poisson3d(7)
    farm = _farm()
    try:
        farm.register("t", A6, solver=CG(maxiter=40, tol=1e-7),
                      precond=_prm(), queue_max=1)
        # park the dispatch loop so the queue stays deterministically
        # full (instance attribute shadows the method; del restores)
        farm._pick_tenant_locked = lambda: None
        f1 = farm.submit("t", rhs6, block=False)
        errs = []

        def waiter():
            try:
                farm.submit("t", rhs6, block=True, timeout_s=120)
            except RuntimeError as e:   # noqa: BLE001 — asserted below
                errs.append(e)

        th = threading.Thread(target=waiter)
        th.start()
        time.sleep(0.3)                # waiter parked on the full queue
        farm.register("t", A7, solver=CG(maxiter=40, tol=1e-7),
                      precond=_prm(), queue_max=1)
        th.join(timeout=60)
        assert not th.is_alive()       # the caller did NOT hang
        assert errs and "different system size" in str(errs[0])
        with pytest.raises(RuntimeError):
            f1.result(timeout=60)      # the queued head was stranded
        del farm._pick_tenant_locked   # un-park the dispatch loop
        x, rep = farm.solve("t", rhs7)
        assert rep.resid < 1e-6
    finally:
        farm.close()


def test_reregister_waits_out_inflight_pin_keeps_rebuild_path():
    """Re-registering new values while the tenant's own batch is
    in flight must WAIT for the unpin and then take the numeric
    rebuild path — not degrade to a fresh setup (miss) because the
    pin guard vetoed the entry mid-batch."""
    A, rhs = poisson3d(6)
    farm = _farm()
    release = threading.Event()
    try:
        farm.register("t", A, solver=CG(maxiter=40, tol=1e-7),
                      precond=_prm())
        e = farm.tenants["t"].entry
        svc = e.payload["service"]
        entered = threading.Event()
        orig = svc._run_batch

        def slow(batch):
            entered.set()              # the dispatch pin is held now
            release.wait(timeout=120)
            return orig(batch)

        svc._run_batch = slow
        fut = farm.submit("t", rhs)
        assert entered.wait(timeout=120)
        A2 = CSR(A.ptr, A.col, 2.0 * A.val, A.ncols)
        out = {}
        th = threading.Thread(target=lambda: out.update(
            farm.register("t", A2, solver=CG(maxiter=40, tol=1e-7),
                          precond=_prm())))
        th.start()
        time.sleep(0.3)
        assert not out                 # parked on the pin, not missed
        release.set()
        th.join(timeout=300)
        svc._run_batch = orig
        assert out.get("outcome") == "rebuild", out
        assert out["uid"] == e.uid     # same entry, refreshed in place
        fut.result(timeout=300)        # the in-flight batch completed
        x, rep = farm.solve("t", rhs)
        assert rep.resid < 1e-6
    finally:
        release.set()
        farm.close()


def test_readmission_preevicts_before_materializing():
    """Readmission makes room FIRST, sized by the entry's last charged
    footprint: at every readmit() the pool already fits the incoming
    bytes, so a tight budget's peak is never victims-plus-new at
    once."""
    farm = _farm()
    try:
        rhs_by = {}
        for k, m in enumerate((6, 7, 8)):
            A, rhs = poisson3d(m)
            farm.register("t%d" % k, A,
                          solver=CG(maxiter=40, tol=1e-7),
                          precond=_prm())
            rhs_by["t%d" % k] = rhs
        total = farm.stats()["pool"]["used_bytes"]
        farm.set_max_bytes(int(total * 0.75))
        overshoots = []
        for e in farm.registry.entries():
            svc = e.payload["service"]

            def wrapped(e=e, orig=svc.readmit):
                hint = farm._bytes_hint.get(e.uid, 0)
                if farm.pool.used + hint > farm.pool.total:
                    overshoots.append(
                        (e.uid, farm.pool.used, hint, farm.pool.total))
                return orig()

            svc.readmit = wrapped
        for _rnd in range(2):
            for t, rhs in rhs_by.items():
                _x, rep = farm.solve(t, rhs)
                assert rep.resid < 1e-6
        assert farm.stats()["readmissions"] >= 1
        assert not overshoots, overshoots
    finally:
        farm.close()


def test_farm_reregister_different_size_fails_stale_queue():
    """Queued requests were validated against the OLD operator size; a
    size-changing re-registration must fail them instead of poisoning
    the new operator's batches."""
    A6, rhs6 = poisson3d(6)
    A7, rhs7 = poisson3d(7)
    farm = _farm()
    try:
        farm.register("t", A6, solver=CG(maxiter=40, tol=1e-7),
                      precond=_prm())
        # hold the dispatch lock (RLock — register on this thread
        # re-enters it) so the re-registration lands while requests
        # are still queued, deterministically
        with farm._mem_lock:
            futs = [farm.submit("t", rhs6 * (1 + k), block=True)
                    for k in range(6)]
            farm.register("t", A7, solver=CG(maxiter=40, tol=1e-7),
                          precond=_prm())
        outcomes = []
        for f in futs:
            try:
                f.result(timeout=300)
                outcomes.append("ok")
            except RuntimeError as e:
                assert "re-registered with a different" in str(e)
                outcomes.append("stranded")
        assert "stranded" in outcomes    # the still-queued tail failed
        # the re-registered tenant serves its NEW size cleanly
        x, rep = farm.solve("t", rhs7)
        assert rep.resid < 1e-6
    finally:
        farm.close()


def test_pyamgcl_compat_routes_through_registry():
    """Repeated same-sparsity constructions take the registry: identical
    matrix = hit, a dropped predecessor's pattern with new values =
    rebuild (the reference's time-stepping workflow)."""
    import amgcl_tpu.pyamgcl_compat as pyamgcl
    A, rhs = poisson3d(7)
    prm = {"coarse_enough": 50}
    before = pyamgcl.registry_stats()
    P1 = pyamgcl.amgcl(A, prm)
    assert P1.registry_outcome == "miss"
    P2 = pyamgcl.amgcl(A, prm)
    assert P2.registry_outcome == "hit"
    solve = pyamgcl.solver(P2, {"type": "cg", "tol": 1e-8})
    x = solve(rhs)
    rel = np.linalg.norm(rhs - A.spmv(np.asarray(x, np.float64))) \
        / np.linalg.norm(rhs)
    assert rel < 1e-5
    del P1, P2, solve
    gc.collect()                       # finalizers release ownership
    P3 = pyamgcl.amgcl(CSR(A.ptr, A.col, 2.0 * A.val, A.ncols), prm)
    assert P3.registry_outcome == "rebuild"
    after = pyamgcl.registry_stats()
    assert after["misses"] == before["misses"] + 1
    assert after["rebuilds"] == before["rebuilds"] + 1


# ---------------------------------------------------------------------------
# eviction / readmission
# ---------------------------------------------------------------------------

def test_lru_pool_semantics():
    from amgcl_tpu.telemetry.ledger import LruMemoryPool
    pool = LruMemoryPool(100)
    assert pool.charge("a", 40) and pool.charge("b", 40)
    assert not pool.charge("c", 40)          # does not fit
    assert pool.coldest() == "a"
    pool.touch("a")                          # b is now coldest
    assert pool.coldest() == "b"
    assert pool.coldest(exclude=("b",)) == "a"
    assert pool.release("b") == 40 and pool.used == 40
    assert pool.charge("c", 40)
    assert sorted(pool.resident()) == ["a", "c"]
    pool.resize(0)                           # unlimited
    assert pool.unlimited and pool.charge("d", 10 ** 12)
    unl = LruMemoryPool(0)
    assert unl.unlimited and unl.to_dict()["total_bytes"] == 0


def test_service_release_device_returns_bytes():
    """The satellite fix: close() alone left the donated iterate buffer
    and bucket executables resident — release_device() drops them, the
    ledger bytes drop to zero, and readmission restores bit-identical
    solves."""
    A, rhs = poisson3d(8)
    ms = make_solver(A, _prm(), CG(maxiter=80, tol=1e-7))
    svc = SolverService(ms, batch=2, flush_ms=5, metrics_port=-9)
    x1, _ = svc.solve_batch(rhs)
    b0 = ms.precond.bytes()
    assert b0 > 0
    with pytest.raises(RuntimeError):
        # a running worker may own in-flight device buffers
        svc.start().release_device()
    svc.close()
    svc.release_device()
    assert ms.precond.bytes() == 0           # the ledger assertion
    assert ms.A_dev is None and svc._entry is None
    svc.readmit()
    assert ms.precond.bytes() == b0
    x2, _ = svc.solve_batch(rhs)
    np.testing.assert_array_equal(np.asarray(x1), np.asarray(x2))


def test_farm_eviction_readmission_determinism():
    """Three tenants under a budget that holds only two hierarchies:
    round-robin traffic forces eviction + readmission every round, the
    readmissions ride rebuild() (registry misses stay == tenants), and
    every tenant's solution is bit-identical across the cycles."""
    farm = _farm()
    rhs_by = {}
    try:
        for k, m in enumerate((6, 7, 8)):
            A, rhs = poisson3d(m)
            rep = farm.register("t%d" % k, A,
                                solver=CG(maxiter=80, tol=1e-7),
                                precond=_prm())
            assert rep["outcome"] == "miss"
            rhs_by["t%d" % k] = rhs
        total = farm.stats()["pool"]["used_bytes"]
        farm.set_max_bytes(int(total * 0.75))
        assert len(farm.pool.resident()) < 3   # something was evicted
        first = {}
        for rnd in range(2):
            futs = [(t, farm.submit(t, rhs))
                    for t, rhs in rhs_by.items()]
            for t, fut in futs:
                x, rep = fut.result(timeout=300)
                assert rep.resid < 1e-6 and rep.iters > 0
                if rnd == 0:
                    first[t] = np.asarray(x)
                else:
                    np.testing.assert_array_equal(first[t],
                                                  np.asarray(x))
        st = farm.stats()
        assert st["evictions"] >= 1 and st["readmissions"] >= 1
        # the acceptance counter check: every readmission was a
        # rebuild, never a fresh setup
        assert st["registry"]["misses"] == 3
        assert st["registry"]["rebuilds"] >= st["readmissions"]
        assert all(r["requests"] == 2 for r in st["tenants"])
        # pool stayed within budget and an under-budget operator is
        # still resident
        assert st["pool"]["used_bytes"] <= st["pool"]["total_bytes"]
    finally:
        farm.close()


def test_farm_budget_too_small_for_one_operator():
    A, _ = poisson3d(6)
    farm = _farm(max_bytes=1024)     # smaller than any hierarchy
    try:
        with pytest.raises(RuntimeError, match="FARM_MAX_BYTES"):
            farm.register("t0", A, solver=CG(maxiter=10, tol=1e-5),
                          precond=_prm())
        # the failed admission rolled back: the fresh entry is an
        # orphan (prunable / a rebuild target) and its device buffers
        # were dropped — no unevictable owned hierarchy leaks
        ents = farm.registry.entries()
        assert ents and all(not e.owners for e in ents)
        assert all(e.obj.A_dev is None for e in ents)
        assert farm.pool.used == 0
    finally:
        farm.close()


# ---------------------------------------------------------------------------
# isolation / fairness / stress
# ---------------------------------------------------------------------------

def test_failed_admission_rolls_back_inplace_rebuild():
    """A register() that fails admission must leave the tenant on its
    ORIGINAL operator: the in-place rebuild acquire performed is
    reverted (and the re-materialized device state dropped when the
    entry was evicted going in) — never silently serving the new
    values after reporting failure."""
    A, rhs = poisson3d(6)
    farm = _farm()
    try:
        farm.register("t", A, solver=CG(maxiter=80, tol=1e-7),
                      precond=_prm())
        x1, _ = farm.solve("t", rhs)
        farm.set_max_bytes(1024)         # evicts; too small to readmit
        A2 = CSR(A.ptr, A.col, 2.0 * A.val, A.ncols)
        with pytest.raises(RuntimeError, match="FARM_MAX_BYTES"):
            farm.register("t", A2, solver=CG(maxiter=80, tol=1e-7),
                          precond=_prm())
        # the bit-equal HIT path rolls back the same way: the
        # readmitted device state is dropped, not leaked uncharged
        with pytest.raises(RuntimeError, match="FARM_MAX_BYTES"):
            farm.register("t", CSR(A.ptr, A.col, A.val.copy(),
                                   A.ncols),
                          solver=CG(maxiter=80, tol=1e-7),
                          precond=_prm())
        assert farm.tenants["t"].entry.obj.A_dev is None
        assert farm.stats()["pool"]["used_bytes"] == 0
        farm.set_max_bytes(0)            # unlimited again
        x2, rep = farm.solve("t", rhs)
        assert rep.resid < 1e-6
        np.testing.assert_array_equal(np.asarray(x1), np.asarray(x2))
    finally:
        farm.close()


def test_failed_admission_rollback_inplace_mutation_idiom():
    """The rollback revert must come from the ENTRY's value snapshot,
    not the caller's matrix object: under the supported in-place
    mutation idiom the caller's object already carries the new values,
    and a revert from it would be a no-op — the tenant would silently
    serve the new operator after register() reported failure."""
    A, rhs = poisson3d(6)
    farm = _farm()
    try:
        farm.register("t", A, solver=CG(maxiter=80, tol=1e-7),
                      precond=_prm())
        x1, _ = farm.solve("t", rhs)
        farm.set_max_bytes(1024)         # evicts; too small to readmit
        A.val *= 2.0                     # in place: A_host IS this A
        with pytest.raises(RuntimeError, match="FARM_MAX_BYTES"):
            farm.register("t", A, solver=CG(maxiter=80, tol=1e-7),
                          precond=_prm())
        farm.set_max_bytes(0)            # unlimited again
        x2, rep = farm.solve("t", rhs)
        assert rep.resid < 1e-6
        np.testing.assert_array_equal(np.asarray(x1), np.asarray(x2))
    finally:
        farm.close()


def test_cross_tenant_isolation():
    """One tenant's guard trips + SLO breach stay on ITS labels and
    windows: the co-tenant's health, counters and trip state remain
    clean, and diagnose(farm=...) names the offender."""
    A, rhs = poisson3d(6)
    farm = _farm()
    try:
        tight = {"unhealthy_rate": 0.0}   # any unhealthy solve trips
        farm.register("bad", A, solver=CG(maxiter=40, tol=1e-7),
                      precond=_prm(), slo=tight)
        farm.register("good", A, solver=CG(maxiter=40, tol=1e-7),
                      precond=_prm(), slo=tight)
        # an x0 so large the first iteration overflows to NaN — trips
        # the NAN guard at iteration 0 (the test_serve poisoning idiom,
        # scaled to stay finite in the float32 cast)
        xb, rb = farm.solve("bad", rhs, x0=np.full(rhs.shape, 1e30))
        xg, rg = farm.solve("good", rhs)
        assert rb.health is not None and not rb.health["ok"]
        assert rg.health is not None and rg.health["ok"]
        st = farm.stats()
        rows = {r["tenant"]: r for r in st["tenants"]}
        assert rows["bad"]["unhealthy"] == 1
        assert rows["bad"]["slo_trips"] >= 1
        assert rows["good"]["unhealthy"] == 0
        assert rows["good"]["slo_trips"] == 0
        assert "unhealthy_rate" not in \
            rows["good"]["slo_summary"]["trips"]
        # labeled metrics: the bad tenant's counter exists, the good
        # tenant's was never created
        assert farm.live.get("farm_tenant_unhealthy_total",
                             tenant="bad") == 1
        assert farm.live.get("farm_tenant_unhealthy_total",
                             tenant="good") is None
        # the doctor names the tenant
        from amgcl_tpu.telemetry.health import diagnose, farm_findings
        finds = farm_findings(st)
        assert any(f.get("tenant") == "bad"
                   and f["code"] == "slo_unhealthy_rate"
                   for f in finds)
        assert not any(f.get("tenant") == "good" for f in finds)
        dfinds = diagnose(rg, farm=st)
        assert any(f.get("tenant") == "bad" for f in dfinds)
    finally:
        farm.close()


def test_fair_share_starvation_bound():
    """A flooding tenant cannot starve a peer: with the round-robin
    cursor advancing past every pick, the late tenant's single request
    completes before the flooder's tail."""
    A6, rhs6 = poisson3d(6)
    A7, rhs7 = poisson3d(7)
    order = []
    farm = _farm(batch=2, flush_ms=1)
    try:
        farm.register("flood", A6, solver=CG(maxiter=40, tol=1e-7),
                      precond=_prm())
        farm.register("late", A7, solver=CG(maxiter=40, tol=1e-7),
                      precond=_prm())
        floods = [farm.submit("flood", rhs6 * (1.0 + k), block=True)
                  for k in range(10)]
        late = farm.submit("late", rhs7, block=True)
        for tag, fut in [("flood%d" % k, f)
                         for k, f in enumerate(floods)] \
                + [("late", late)]:
            fut.add_done_callback(
                lambda _f, tag=tag: order.append(tag))
        for f in floods + [late]:
            f.result(timeout=300)
        assert order.index("late") < order.index("flood9"), order
    finally:
        farm.close()


def test_concurrent_submit_stress():
    """>= 3 tenants submitting from concurrent threads: every result
    matches the tenant's direct solve (no cross-tenant leakage under
    batching), no request is lost."""
    farm = _farm(batch=4, flush_ms=5)
    tenants = {}
    try:
        for k, m in enumerate((6, 7, 8)):
            A, rhs = poisson3d(m)
            name = "t%d" % k
            farm.register(name, A, solver=CG(maxiter=80, tol=1e-7),
                          precond=_prm())
            direct = make_solver(A, _prm(), CG(maxiter=80, tol=1e-7))
            xd, _ = direct(rhs)
            tenants[name] = (rhs, np.asarray(xd))
        reqs = 6
        results = {}
        errs = []

        def feeder(name):
            rhs, _xd = tenants[name]
            try:
                futs = [farm.submit(name, rhs * (1.0 + 0.5 * k),
                                    block=True) for k in range(reqs)]
                results[name] = [f.result(timeout=300) for f in futs]
            except Exception as e:      # noqa: BLE001 — surfaced below
                errs.append((name, e))

        threads = [threading.Thread(target=feeder, args=(n,))
                   for n in tenants]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=600)
        assert not errs, errs
        for name, (rhs, xd) in tenants.items():
            assert len(results[name]) == reqs
            for k, (x, rep) in enumerate(results[name]):
                np.testing.assert_allclose(
                    np.asarray(x), (1.0 + 0.5 * k) * xd,
                    rtol=1e-4, atol=1e-5)
                assert rep.health is None or rep.health["ok"]
        st = farm.stats()
        assert st["requests"] == 3 * reqs
    finally:
        farm.close()


# ---------------------------------------------------------------------------
# observability surface
# ---------------------------------------------------------------------------

def test_farm_metrics_endpoint_tenant_labels():
    """/metrics serves per-tenant labeled gauges while the farm runs —
    the acceptance criterion — plus /healthz liveness."""
    A, rhs = poisson3d(6)
    farm = _farm(metrics_port=0)
    try:
        farm.register("acct-a", A, solver=CG(maxiter=40, tol=1e-7),
                      precond=_prm())
        farm.solve("acct-a", rhs)
        farm.start()
        url = farm.metrics_url
        assert url
        text = urllib.request.urlopen(url, timeout=10).read().decode()
        assert 'amgcl_tpu_farm_tenant_requests_total'  \
            '{tenant="acct-a"} 1' in text
        assert 'amgcl_tpu_farm_tenant_resident{tenant="acct-a"} 1.0' \
            in text
        assert 'amgcl_tpu_farm_tenant_bytes{tenant="acct-a"}' in text
        assert "amgcl_tpu_farm_hbm_used_bytes" in text
        assert "amgcl_tpu_farm_registry_misses_total 1" in text
        h = json.loads(urllib.request.urlopen(
            url.replace("/metrics", "/healthz"), timeout=10).read())
        assert h["ok"] and h["tenants"] == 1
        assert farm.stats()["metrics_port"] == \
            farm.metrics_server.port
    finally:
        farm.close()


def test_labeled_gauges_declared_and_linted():
    """The METRIC_LABELS contract is enforced at both ends: the runtime
    registry rejects undeclared label keys, and the lint rule sees the
    same table (plus flags undeclared label keys at call sites)."""
    from amgcl_tpu.analysis import lint
    from amgcl_tpu.telemetry.live import (LiveRegistry, METRIC_LABELS,
                                          METRICS)
    assert METRIC_LABELS["farm_tenant_p99_ms"] == ("tenant",)
    assert set(METRIC_LABELS) <= set(METRICS)
    assert lint.declared_metric_labels() == METRIC_LABELS
    reg = LiveRegistry()
    with pytest.raises(KeyError):
        reg.inc("farm_tenant_requests_total", shard="x")
    with pytest.raises(KeyError):
        reg.set_gauge("farm_hbm_used_bytes", 1, tenant="a")
    # no new metric-name/label findings anywhere in the package
    finds = lint.run_lint(rules=["metric-name-literal"])
    assert finds == [], finds


# ---------------------------------------------------------------------------
# capi / gate / CLI
# ---------------------------------------------------------------------------

def test_capi_farm_roundtrip():
    """farm_create / farm_register / farm_solve / farm_evict /
    farm_stats through the ctypes marshalling layer; handle_destroy
    closes the farm."""
    import ctypes
    from amgcl_tpu import capi
    A, rhs = poisson3d(6)
    n = A.nrows
    ptr = np.ascontiguousarray(A.ptr, np.int32)
    col = np.ascontiguousarray(A.col, np.int32)
    val = np.ascontiguousarray(A.val, np.float64)
    prm_h = capi.params_create()
    capi.params_set(prm_h, "solver.type", "cg")
    capi.params_set(prm_h, "solver.tol", 1e-7)
    capi.params_set(prm_h, "precond.dtype", "float32")
    capi.params_set(prm_h, "precond.coarse_enough", 50)
    h = capi.farm_create(batch=2)
    rep = json.loads(capi.farm_register(
        h, "acct", n, ptr.ctypes.data, col.ctypes.data,
        val.ctypes.data, prm_h))
    assert rep["outcome"] == "miss" and rep["bytes"] > 0
    nrhs = 2
    rhs2 = np.concatenate([rhs, 2.0 * rhs]).astype(np.float64)
    x = np.zeros(n * nrhs)
    it, res = capi.farm_solve(h, "acct", rhs2.ctypes.data,
                              x.ctypes.data, n, nrhs)
    assert it > 0 and res < 1e-6
    rel = np.linalg.norm(rhs - A.spmv(x[:n])) / np.linalg.norm(rhs)
    assert rel < 1e-5
    np.testing.assert_allclose(x[n:], 2.0 * x[:n], rtol=1e-5,
                               atol=1e-7)
    # initial guesses are honored (solver_solve_batch contract): a
    # warm restart from the exact solution converges immediately
    x_warm = x.copy()
    it_w, _ = capi.farm_solve(h, "acct", rhs2.ctypes.data,
                              x_warm.ctypes.data, n, nrhs)
    assert it_w <= 1, it_w
    assert capi.farm_evict(h, "acct") == 1
    assert capi.farm_evict(h, "acct") == 0     # already evicted
    # readmission through the queue still works after an explicit evict
    # (x zeroed: all-zero guesses = cold start, same iters as before)
    x[:] = 0.0
    it2, _res2 = capi.farm_solve(h, "acct", rhs2.ctypes.data,
                                 x.ctypes.data, n, nrhs)
    assert it2 == it
    stats = json.loads(capi.farm_stats(h))
    assert stats["requests"] == 6 and stats["evictions"] == 1
    assert stats["registry"]["misses"] == 1
    assert stats["readmissions"] == 1
    capi.handle_destroy(h)
    capi.handle_destroy(prm_h)


def test_gate_farm_check():
    """bench.py --gate: the farm agg_sps floor trips on a drop below
    the AMGCL_TPU_GATE_FARM fraction, skips across platforms and on
    pre-metric records, and fails a candidate whose readmissions left
    the rebuild path regardless of speed."""
    sys.path.insert(0, _REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    base = {"iters": 10, "value": 1.0, "device_platform": "cpu",
            "farm": {"agg_sps": 10.0}}
    good = {"iters": 10, "value": 1.0, "device_platform": "cpu",
            "farm": {"agg_sps": 9.0, "rebuild_only_readmission": True}}
    bad = {"iters": 10, "value": 1.0, "device_platform": "cpu",
           "farm": {"agg_sps": 3.0}}
    fake = {"iters": 10, "value": 1.0, "device_platform": "cpu",
            "farm": {"agg_sps": 50.0,
                     "rebuild_only_readmission": False}}
    other = {"iters": 10, "value": 1.0, "device_platform": "tpu",
             "farm": {"agg_sps": 1.0}}

    def row(cand, lg=base):
        _ok, checks = bench.run_gate(cand, lg)
        return [c for c in checks if c["check"] == "farm_sps"][0]

    assert row(good)["status"] == "ok"
    assert row(bad)["status"] == "regression"
    r = row(fake)
    assert r["status"] == "regression" and "rebuild" in r["reason"]
    assert row(other)["status"] == "skipped"
    assert row({"iters": 10, "value": 1.0,
                "device_platform": "cpu"})["status"] == "skipped"
    # neither side carries the metric: no check row at all
    _ok, checks = bench.run_gate({"iters": 10, "value": 1.0},
                                 {"iters": 10, "value": 1.0})
    assert not [c for c in checks if c["check"] == "farm_sps"]


@pytest.mark.serial
def test_cli_farm_smoke(tmp_path):
    """`python -m amgcl_tpu.cli --farm 3` end to end: >= 3 tenants with
    distinct operators under a byte budget forcing >= 1 eviction and
    readmission, converged per-tenant reports, rebuild-path
    readmission asserted via the registry counters (the CLI exits
    nonzero otherwise), farm events in the telemetry sink."""
    out = tmp_path / "farm_cli.jsonl"
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=8")
               .strip())
    r = subprocess.run(
        [sys.executable, "-m", "amgcl_tpu.cli", "-n", "6", "--farm",
         "3", "--farm-requests", "2", "-p", "solver.type=cg",
         "--telemetry", str(out)],
        capture_output=True, text=True, timeout=600, cwd=_REPO, env=env)
    assert r.returncode == 0, r.stderr[-2000:] + r.stdout[-2000:]
    assert "farm: 3 tenant(s) x 2 round(s)" in r.stdout
    assert "registry:" in r.stdout and "eviction(s)" in r.stdout
    assert "acceptance: OK" in r.stdout
    recs = [json.loads(ln) for ln in open(out)]
    events = {x.get("event") for x in recs}
    assert {"farm_register", "farm_evict", "farm",
            "farm_demo"} <= events
    demo = [x for x in recs if x.get("event") == "farm_demo"][0]
    assert demo["ok"] and demo["evictions"] >= 1 \
        and demo["readmissions"] >= 1
