"""Strip-parallel hierarchy construction (parallel/dist_setup.py).

Validates the distributed-setup redesign of the reference's mpi::amg
step_down (amgcl/mpi/amg.hpp:163-330): distributed transpose + SpGEMM by
remote-row fetch / triple routing (distributed_matrix.hpp:559-716,
856-1066), mesh-sharded MIS aggregation, and strip-local smoother builds —
with iteration parity against the serial-build DistAMGSolver and a
per-strip peak-memory bound of ~nnz/nd."""

import numpy as np
import scipy.sparse as sp
import jax.numpy as jnp
import pytest

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.models.amg import AMGParams
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.solver.bicgstab import BiCGStab
from amgcl_tpu.coarsening.smoothed_aggregation import SmoothedAggregation
from amgcl_tpu.parallel.mesh import make_mesh
from amgcl_tpu.parallel.dist_amg import DistAMGSolver
from amgcl_tpu.parallel.dist_setup import (
    LocalComm, split_strips, strip_transpose, strip_spgemm,
    StripAMGSolver)
from amgcl_tpu.utils.sample_problem import poisson3d


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(8)


@pytest.fixture(scope="module")
def fe_problem():
    from amgcl_tpu.ops.unstructured import fe_like_problem
    A, rhs = fe_like_problem(n=8000, nnz_target=200_000, seed=3)
    return A, rhs


def _rand_csr(rng, n, m, density=0.01):
    M = sp.random(n, m, density=density, random_state=rng,
                  format="csr")
    M.sort_indices()
    return M


def test_strip_transpose_matches_scipy(mesh8):
    rng = np.random.RandomState(0)
    A = _rand_csr(rng, 100, 57, 0.05)
    comm = LocalComm(8)
    strips, nloc = split_strips(A, 8)
    nloc_out = -(-57 // 8)
    T = strip_transpose(strips, nloc, nloc_out, (57, 100), comm)
    got = sp.vstack(T, format="csr")
    np.testing.assert_allclose(got.toarray(), A.T.toarray())


def test_strip_spgemm_matches_scipy(mesh8):
    rng = np.random.RandomState(1)
    A = _rand_csr(rng, 90, 70, 0.06)
    B = _rand_csr(rng, 70, 40, 0.08)
    comm = LocalComm(8)
    A_s, nloc_a = split_strips(A, 8)
    B_s, nloc_b = split_strips(B, 8)
    C_s = strip_spgemm(A_s, B_s, nloc_b, comm)
    got = sp.vstack(C_s, format="csr")
    np.testing.assert_allclose(got.toarray(), (A @ B).toarray(),
                               rtol=1e-12, atol=1e-12)


def test_iteration_parity_vs_serial_build(mesh8):
    """The strip-built hierarchy must match the serial device_mis build
    exactly (same strength filter, same MIS, same Gershgorin omega —
    coarse unknowns differ only by a permutation)."""
    A, rhs = poisson3d(24)
    prm_serial = AMGParams(
        dtype=jnp.float32,
        coarsening=SmoothedAggregation(
            structured=False, stencil_setup=False,
            implicit_transfers=False))
    s0 = DistAMGSolver(A, mesh8, prm_serial, CG(tol=1e-6, maxiter=100),
                       replicate_below=1000, device_mis=True)
    x0, i0 = s0(rhs)
    s1 = StripAMGSolver(A, mesh8, AMGParams(dtype=jnp.float32),
                        CG(tol=1e-6, maxiter=100), replicate_below=1000)
    x1, i1 = s1(rhs)
    assert i1.iters == i0.iters
    r = np.linalg.norm(rhs - A.to_scipy() @ x1) / np.linalg.norm(rhs)
    assert r < 1e-5


def test_fe_unstructured_strip_build(mesh8, fe_problem):
    """General (non-stencil) matrix: builds sharded levels, solves, and the
    per-strip working set stays ~nnz/nd (the whole point — VERDICT r3
    item 2). eps_strong is lowered for the kNN-Laplacian profile (uniform
    ~25-neighbor couplings sit at |a_ij|^2/|a_ii a_jj| ~ 1/625)."""
    A, rhs = fe_problem
    prm = AMGParams(dtype=jnp.float32,
                    coarsening=SmoothedAggregation(eps_strong=0.02))
    s = StripAMGSolver(A, mesh8, prm,
                       CG(tol=1e-6, maxiter=200), replicate_below=2000)
    assert len(s.hier.levels) >= 1          # sharded level(s) exist
    x, info = s(rhs)
    r = np.linalg.norm(rhs - A.to_scipy() @ x) / np.linalg.norm(rhs)
    # f32 true-residual floor on this fixture is ~2.5e-4 (the serial-build
    # DistAMGSolver lands at the same level — conditioning, not setup)
    assert r < 1e-3
    # strip peak ~ total/nd: no step concentrated the matrix on one strip
    total_nnz = A.nnz
    assert s.stats["peak_strip_nnz"] < 3 * total_nnz / 8


def test_fe_parity_vs_serial_build(mesh8, fe_problem):
    A, rhs = fe_problem
    prm_serial = AMGParams(
        dtype=jnp.float32,
        coarsening=SmoothedAggregation(
            eps_strong=0.02, structured=False, stencil_setup=False,
            implicit_transfers=False))
    s0 = DistAMGSolver(A, mesh8, prm_serial, CG(tol=1e-6, maxiter=200),
                       replicate_below=2000, device_mis=True)
    _, i0 = s0(rhs)
    s1 = StripAMGSolver(
        A, mesh8,
        AMGParams(dtype=jnp.float32,
                  coarsening=SmoothedAggregation(eps_strong=0.02)),
        CG(tol=1e-6, maxiter=200), replicate_below=2000)
    _, i1 = s1(rhs)
    # same algorithm up to coarse-unknown permutation; f32 rounding in the
    # replicated tail may shift the count by 1
    assert abs(i1.iters - i0.iters) <= 1


def test_strips_ingestion_no_global_matrix(mesh8):
    """Multi-host ingestion pattern (mpi_solver.cpp:190-238): the solver
    accepts pre-split strips + n and never needs the assembled matrix."""
    A, rhs = poisson3d(16)
    strips, _ = split_strips(A, 8)
    s = StripAMGSolver(strips, mesh8, AMGParams(dtype=jnp.float32),
                       CG(tol=1e-6, maxiter=100), n=A.nrows,
                       replicate_below=1000)
    x, info = s(rhs)
    r = np.linalg.norm(rhs - A.to_scipy() @ x) / np.linalg.norm(rhs)
    assert r < 1e-5


@pytest.mark.parametrize("relax_name", ["spai0", "jacobi", "chebyshev",
                                        "spai1"])
def test_strip_smoothers(mesh8, relax_name):
    from amgcl_tpu.relaxation.spai0 import Spai0
    from amgcl_tpu.relaxation.jacobi import DampedJacobi
    from amgcl_tpu.relaxation.chebyshev import Chebyshev
    from amgcl_tpu.relaxation.spai1 import Spai1
    relax = {"spai0": Spai0(), "jacobi": DampedJacobi(),
             "chebyshev": Chebyshev(degree=3), "spai1": Spai1()}[relax_name]
    A, rhs = poisson3d(16)
    s = StripAMGSolver(A, mesh8, AMGParams(dtype=jnp.float32, relax=relax),
                       BiCGStab(tol=1e-6, maxiter=100),
                       replicate_below=600)
    x, info = s(rhs)
    r = np.linalg.norm(rhs - A.to_scipy() @ x) / np.linalg.norm(rhs)
    assert r < 1e-5


def test_unsupported_smoother_raises(mesh8):
    from amgcl_tpu.relaxation.ilu0 import ILU0
    A, _ = poisson3d(16)
    with pytest.raises(ValueError, match="strip-parallel"):
        StripAMGSolver(A, mesh8, AMGParams(dtype=jnp.float32,
                                           relax=ILU0()),
                       CG(), replicate_below=600)


def test_multihost_comm_chunked_alltoall(mesh8, monkeypatch):
    """MultihostComm's exchange primitives work in-process too; force a
    tiny chunk cap so large messages stream over multiple all_to_all
    rounds and reassemble exactly."""
    from amgcl_tpu.parallel.dist_setup import MultihostComm
    comm = MultihostComm(mesh8)
    monkeypatch.setattr(MultihostComm, "_CHUNK_CAP", 8)
    rng = np.random.default_rng(1)
    nd = 8
    buckets = []
    for s in range(nd):
        bk = []
        for d in range(nd):
            k = int(rng.integers(0, 40))      # many messages exceed cap=8
            bk.append((rng.integers(0, 1000, k),
                       rng.integers(0, 1000, k),
                       rng.standard_normal(k)))
        buckets.append(bk)
    recv = comm.alltoall(buckets)
    for d in range(nd):
        for s in range(nd):
            r0, c0, v0 = buckets[s][d]
            r1, c1, v1 = recv[d][s]
            np.testing.assert_array_equal(np.asarray(r1), np.asarray(r0))
            np.testing.assert_array_equal(np.asarray(c1), np.asarray(c0))
            np.testing.assert_allclose(np.asarray(v1), np.asarray(v0))


def test_strip_plain_aggregation(mesh8):
    """Plain (unsmoothed) aggregation on strips: P = P_tent, Galerkin
    scaled by 1/over_interp (aggregation.hpp:71-160)."""
    from amgcl_tpu.coarsening.aggregation import Aggregation
    A, rhs = poisson3d(16)
    s = StripAMGSolver(
        A, mesh8,
        AMGParams(dtype=jnp.float32, coarsening=Aggregation()),
        CG(tol=1e-6, maxiter=200), replicate_below=600)
    assert len(s.hier.levels) >= 1
    x, info = s(rhs)
    r = np.linalg.norm(rhs - A.to_scipy() @ x) / np.linalg.norm(rhs)
    assert r < 1e-4


def test_strip_amg_runtime_and_cli(mesh8, tmp_path, capsys):
    """precond.class=strip_amg through the distributed runtime config and
    the CLI --mesh --strip-setup flag (the mpi_solver surface)."""
    from amgcl_tpu.models.runtime import make_dist_solver_from_config
    A, rhs = poisson3d(16)
    s = make_dist_solver_from_config(A, mesh8, {
        "precond.class": "strip_amg",
        "precond.dtype": "float32",
        "precond.replicate_below": "600",
        "solver.type": "cg", "solver.maxiter": "100",
        "solver.tol": "1e-6"})
    x, info = s(rhs)
    r = np.linalg.norm(rhs - A.to_scipy() @ x) / np.linalg.norm(rhs)
    assert r < 1e-5

    from amgcl_tpu.cli import main as cli_main
    out = str(tmp_path / "x.mtx")
    cli_main(["-n", "16", "--mesh", "8", "--strip-setup",
              "-p", "solver.tol=1e-6", "-o", out])
    assert "iterations" in capsys.readouterr().out.lower()


def test_comm_empty_shards_safe():
    """A process (or view) that owns no shards must participate in the
    reductions instead of crashing (advisor r4: max_scalar over all-None,
    fetch_vals dereferencing my_shards[0])."""
    comm = LocalComm(4)
    # all-None reduction: the allreduce identity, not a ValueError
    assert comm.max_scalar([None] * 4) == float("-inf")
    # zero-owned-shards view: _vals_meta must not index my_shards[0]
    empty = LocalComm(4)
    empty.my_shards = []
    assert empty._vals_meta([None] * 4) == (False, False)
    # mixed ownership: flags come from owned non-None entries only
    comm2 = LocalComm(4)
    comm2.my_shards = [1, 3]
    vals = [None, np.arange(3, dtype=np.int64), None,
            np.ones(2, dtype=np.float64)]
    assert comm2._vals_meta(vals) == (False, True)
    vals_c = [None, np.ones(2, dtype=np.complex128), None, None]
    assert comm2._vals_meta(vals_c) == (True, False)


def test_coarsening_stall_is_distinct_exception():
    """strip_sa_hierarchy catches exactly CoarseningStall; an unrelated
    ValueError from deep inside a level build must PROPAGATE instead of
    silently truncating the hierarchy (advisor r4)."""
    from amgcl_tpu.parallel.dist_setup import CoarseningStall
    assert issubclass(CoarseningStall, ValueError)
    import amgcl_tpu.parallel.dist_setup as ds
    mesh = make_mesh(8)
    A, _ = poisson3d(12)
    orig = ds._strip_sa_level

    def boom(*a, **k):
        raise ValueError("unrelated numpy failure")

    ds._strip_sa_level = boom
    try:
        with pytest.raises(ValueError, match="unrelated"):
            ds.strip_sa_hierarchy(
                split_strips(A, 8)[0], A.nrows, mesh,
                AMGParams(dtype=jnp.float32, coarse_enough=100),
                replicate_below=200)
    finally:
        ds._strip_sa_level = orig
