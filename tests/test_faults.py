"""Fault injection + recovery (ISSUE 13): the deterministic injector,
the numeric guard-seam faults, the recovery policy ladder and host-side
checkpoints, serve-level retry/bisection and the worker supervisor
(future-stranding regression), farm admission faults under concurrent
register/evict/solve, load shedding, the swallowed-worker-exception
lint rule, the doctor's recovery findings, and a chaos-matrix smoke."""

import json
import os
import queue as _queue
import sys
import threading

import numpy as np
import pytest

import jax.numpy as jnp

from amgcl_tpu.faults import (AdmissionError, DeviceLostError,
                              LoadShedError, PoisonRequestError,
                              RecoveryExhausted, WorkerDiedError)
from amgcl_tpu.faults import inject, recovery
from amgcl_tpu.models.amg import AMGParams
from amgcl_tpu.models.make_solver import make_solver
from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.utils.sample_problem import poisson3d

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

KNOBS = ("AMGCL_TPU_FAULT_PLAN", "AMGCL_TPU_RETRY_MAX",
         "AMGCL_TPU_RETRY_BACKOFF_MS", "AMGCL_TPU_CKPT_EVERY",
         "AMGCL_TPU_SHED_BREACHES", "AMGCL_TPU_SHED_COOLDOWN_S",
         "AMGCL_TPU_RECOVERY")


@pytest.fixture(autouse=True)
def _clean_faults():
    saved = {k: os.environ.get(k) for k in KNOBS}
    inject._reset_for_tests()
    recovery._reset_for_tests()
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    inject._reset_for_tests()


def _arm(*rules, **env):
    os.environ["AMGCL_TPU_FAULT_PLAN"] = json.dumps(
        list(rules) if len(rules) != 1 else rules[0])
    for k, v in env.items():
        os.environ[k] = str(v)
    inject._reset_for_tests()


@pytest.fixture(scope="module")
def problem():
    A, rhs = poisson3d(8)
    return A, rhs.astype(np.float32)


def _mk(A, **kw):
    return make_solver(A, AMGParams(dtype=jnp.float32,
                                    coarse_enough=200),
                       CG(maxiter=100, tol=1e-6), **kw)


@pytest.fixture(scope="module")
def baseline(problem):
    A, rhs = problem
    os.environ.pop("AMGCL_TPU_FAULT_PLAN", None)
    inject._reset_for_tests()
    x, rep = _mk(A)(rhs)
    return np.asarray(x, np.float64), rep


# ---------------------------------------------------------------------------
# injector units
# ---------------------------------------------------------------------------

def test_plan_parsing_and_errors():
    _arm({"site": "numeric.nan", "at": 3, "count": 2})
    assert inject.enabled()
    assert inject.plan_errors() == []
    spec = inject.armed("numeric.nan")
    assert spec["at"] == 3 and spec["count"] == 2
    os.environ["AMGCL_TPU_FAULT_PLAN"] = "not json"
    assert inject.armed("numeric.nan") is None
    assert any("valid JSON" in e for e in inject.plan_errors())
    os.environ["AMGCL_TPU_FAULT_PLAN"] = json.dumps(
        [{"site": "no.such.site"}, {"nosite": 1}])
    assert len(inject.plan_errors()) == 2


def test_count_after_and_determinism():
    _arm({"site": "device.loss", "count": 2, "after": 1})
    assert inject.should_fire("device.loss") is None      # skipped: after
    assert inject.should_fire("device.loss") is not None  # fire 1
    assert inject.should_fire("device.loss") is not None  # fire 2
    assert inject.should_fire("device.loss") is None      # budget spent
    assert inject.injected_total() == 2
    # seeded probability: the firing pattern is identical across
    # re-arms of the same plan (fresh counters each _reset)
    _arm({"site": "device.loss", "count": -1, "p": 0.5, "seed": 9})
    pat1 = [inject.should_fire("device.loss") is not None
            for _ in range(16)]
    inject._reset_for_tests()
    pat2 = [inject.should_fire("device.loss") is not None
            for _ in range(16)]
    assert pat1 == pat2 and any(pat1) and not all(pat1)


def test_armed_does_not_consume():
    _arm({"site": "numeric.nan", "count": 1})
    for _ in range(5):
        assert inject.armed_numeric() is not None
    assert inject.injected_total() == 0
    inject.consume(inject.armed_numeric())
    assert inject.injected_total() == 1
    assert inject.armed_numeric() is None


def test_numeric_dispatch_window():
    """The guard seam only sees a numeric rule INSIDE the begin/end
    dispatch window (any other trace in the process sees None), and
    the window applies the full after/count trigger logic — one check
    per dispatch."""
    _arm({"site": "numeric.nan", "at": 2, "after": 1, "count": 1})
    assert inject.pending_numeric() is None   # armed but not pending
    assert inject.begin_numeric_dispatch() is None   # after=1: skip
    inject.end_numeric_dispatch()
    spec = inject.begin_numeric_dispatch()           # second: fires
    assert spec is not None and inject.pending_numeric() == spec
    inject.end_numeric_dispatch()
    assert inject.pending_numeric() is None
    assert inject.begin_numeric_dispatch() is None   # budget spent
    assert inject.injected_total() == 1


def test_numeric_fault_respects_after(problem, baseline):
    """`after` on a numeric rule skips whole dispatches: the first
    solve is clean, the second faults (the reviewer-found gap)."""
    A, rhs = problem
    _arm({"site": "numeric.nan", "at": 2, "after": 1, "count": 1})
    b = _mk(A)
    _x, rep1 = b(rhs)
    assert rep1.health["ok"], rep1.health
    _x, rep2 = b(rhs)
    assert rep2.health["nan"] and rep2.health["first_trip"]["nan"] == 2


def test_serve_trace_not_poisoned_by_armed_numeric(problem):
    """A serve bucket compiled while a numeric plan is ARMED must stay
    clean — the pending window belongs to make_solver dispatches only
    (a poisoned cached program would fault every later batch)."""
    A, rhs = problem
    _arm({"site": "numeric.nan", "at": 1, "count": 1})
    svc = _svc(A, batch=2)
    try:
        _x, rep = svc.submit(rhs).result(timeout=60)
        assert rep.health["ok"], rep.health
        _x, rep2 = svc.submit(rhs).result(timeout=60)
        assert rep2.health["ok"], rep2.health
        assert inject.injected_total() == 0
    finally:
        svc.close()


def test_unchanged_plan_keeps_consumed_budget():
    """Re-reading an identical plan string is not re-arming: the
    counters survive env round-trips (a new experiment needs a new
    plan value or an explicit reset)."""
    plan = json.dumps({"site": "device.loss", "count": 1})
    _arm({"site": "device.loss", "count": 1})
    assert inject.should_fire("device.loss") is not None
    os.environ["AMGCL_TPU_FAULT_PLAN"] = plan    # same value
    assert inject.should_fire("device.loss") is None


def test_alloc_fault_refuses_charges():
    from amgcl_tpu.telemetry.ledger import (DeviceMemoryBudget,
                                            LruMemoryPool)
    _arm({"site": "alloc.dwin", "count": 1})
    b = DeviceMemoryBudget(1000, name="dense_window")
    assert not b.try_charge(10, "t")     # injected refusal
    assert b.try_charge(10, "t")         # budget honest again
    assert b.used == 10
    _arm({"site": "alloc.farm", "count": 1})
    pool = LruMemoryPool(0, name="farm_hbm")
    assert not pool.charge("k", 5)
    assert pool.charge("k", 5)
    assert pool.used == 5 and pool.release("k") == 5 and pool.used == 0


def test_dist_delay_seam_fires():
    from amgcl_tpu.parallel import dist_matrix
    _arm({"site": "dist.delay", "delay_ms": 1, "count": 1})
    dist_matrix._maybe_stall_exchange()
    assert inject.injected_total() == 1
    assert inject.fired()[0]["site"] == "dist.delay"


# ---------------------------------------------------------------------------
# numeric guard-seam faults
# ---------------------------------------------------------------------------

def test_numeric_nan_trips_guard_then_clears(problem, baseline):
    A, rhs = problem
    x_ref, rep_ref = baseline
    _arm({"site": "numeric.nan", "at": 2, "count": 1})
    b = _mk(A)
    x, rep = b(rhs)
    h = rep.health
    assert h["nan"] and h["first_trip"]["nan"] == 2
    assert rep.iters == 2                      # frozen at the trip
    assert np.all(np.isfinite(np.asarray(x)))  # guard-commit freeze
    # count consumed: the next dispatch rides the clean cached trace
    x2, rep2 = b(rhs)
    assert rep2.health["ok"] and rep2.iters == rep_ref.iters


def test_numeric_breakdown_injection(problem):
    A, rhs = problem
    _arm({"site": "numeric.breakdown", "at": 1, "count": 1})
    _x, rep = _mk(A)(rhs)
    assert rep.health["breakdown"] == "breakdown_rho"
    assert rep.health["breakdown_iteration"] == 1


# ---------------------------------------------------------------------------
# recovery ladder + checkpoints
# ---------------------------------------------------------------------------

def test_ladder_recovers_from_transient_nan(problem, baseline):
    A, rhs = problem
    x_ref, _ = baseline
    _arm({"site": "numeric.nan", "at": 2, "count": 1})
    x, rep = _mk(A, recovery=True)(rhs)
    rec = rep.recovery
    assert rec["recovered"] and rec["final_rung"] == "last_good"
    assert [a["rung"] for a in rec["attempts"]] == ["initial",
                                                    "last_good"]
    assert rec["attempts"][0]["flags"] == ["nan"]
    assert float(rep.resid) <= 1e-6
    xa = np.asarray(x, np.float64)
    assert np.linalg.norm(xa - x_ref) <= 1e-3 * np.linalg.norm(x_ref)
    # the trail rides to_dict (and therefore the JSONL solve events)
    assert rep.to_dict()["recovery"]["final_rung"] == "last_good"


def test_ladder_precision_rung(problem):
    """Two faulted attempts exhaust initial+last_good; the f64 rung
    (x64 is live under conftest) lands the solve."""
    A, rhs = problem
    _arm({"site": "numeric.nan", "at": 1, "count": 2})
    x, rep = _mk(A, recovery=True)(rhs)
    rec = rep.recovery
    assert rec["recovered"] and rec["final_rung"] == "precision"
    assert rec["attempts"][-1].get("dtype") == "float64"
    assert float(rep.resid) <= 1e-6


def test_ladder_exhausts_typed_with_flight_bundle(problem, tmp_path,
                                                  monkeypatch):
    A, rhs = problem
    monkeypatch.setenv("AMGCL_TPU_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("AMGCL_TPU_FLIGHT_MAX_DUMPS", "0")
    _arm({"site": "numeric.nan", "at": 1, "count": -1})
    with pytest.raises(RecoveryExhausted) as ei:
        _mk(A, recovery=True)(rhs)
    rungs = [a["rung"] for a in ei.value.attempts]
    assert rungs[0] == "initial" and "smoother" in rungs
    assert any("recovery_exhausted" in d for d in os.listdir(tmp_path))


def test_checkpointed_solve_and_device_loss_resume(problem, baseline):
    A, rhs = problem
    x_ref, _ = baseline
    # clean checkpointed run: segments, no resumes
    os.environ["AMGCL_TPU_CKPT_EVERY"] = "4"
    os.environ.pop("AMGCL_TPU_FAULT_PLAN", None)
    inject._reset_for_tests()
    b = _mk(A, recovery=True)
    x, rep = b(rhs)
    ck = rep.extra["checkpoints"]
    assert ck["every"] == 4 and ck["segments"] >= 2 \
        and ck["resumes"] == 0
    assert float(rep.resid) <= 1e-6
    assert recovery.last_checkpoint_age_s() is not None
    # device loss after the first segment: resume from the snapshot
    _arm({"site": "device.loss", "count": 1, "after": 1,
          "target": "solve"})
    x2, rep2 = b(rhs)
    assert rep2.extra["checkpoints"]["resumes"] == 1
    assert float(rep2.resid) <= 1e-6
    xa = np.asarray(x2, np.float64)
    assert np.linalg.norm(xa - x_ref) <= 1e-3 * np.linalg.norm(x_ref)


def test_recovery_env_opt_in(problem):
    """recovery=None follows AMGCL_TPU_RECOVERY; the default stays the
    historical single-dispatch path (no .recovery on the report)."""
    A, rhs = problem
    _x, rep = _mk(A)(rhs)
    assert rep.recovery is None
    os.environ["AMGCL_TPU_RECOVERY"] = "1"
    _x, rep2 = _mk(A)(rhs)
    assert rep2.recovery is not None and not rep2.recovery["recovered"]


# ---------------------------------------------------------------------------
# serve: supervisor (stranding regression), retry, bisection
# ---------------------------------------------------------------------------

def _svc(A, **kw):
    from amgcl_tpu.serve.service import SolverService
    kw.setdefault("metrics_port", -1)
    kw.setdefault("flush_ms", 20)
    return SolverService(_mk(A), **kw)


def test_worker_death_never_strands_futures(problem):
    """Satellite regression: ANY unexpected worker exception (not just
    a failed batch) must fail every pending/queued future through the
    supervisor — before this PR those futures hung forever."""
    A, rhs = problem
    svc = _svc(A, batch=2)

    def boom(*a, **k):
        raise ValueError("synthetic worker bug outside the batch path")

    svc._run_batch = boom
    svc._handle_batch_failure = boom     # the handler itself is broken
    futs = [svc.submit(rhs) for _ in range(3)]
    for f in futs:
        with pytest.raises(WorkerDiedError):
            f.result(timeout=60)         # formerly: hangs forever
    # a submit racing past one death can trigger another on the
    # restarted (still-broken) worker, and the supervisor bumps the
    # restart counter AFTER the futures fail — the CONTRACT is "every
    # future failed, supervisor engaged", not exact counts at an exact
    # instant, so poll briefly for the restart
    import time as _time
    deadline = _time.monotonic() + 30
    st = {}
    while _time.monotonic() < deadline:
        st = svc.stats().get("recovery") or {}
        if st.get("worker_restarts", 0) >= 1:
            break
        _time.sleep(0.05)
    assert st.get("worker_deaths", 0) >= 1 \
        and st.get("worker_restarts", 0) >= 1, st
    svc.close()


def test_injected_worker_death_restarts_and_serves(problem):
    A, rhs = problem
    _arm({"site": "serve.worker", "count": 1, "target": "serve"})
    svc = _svc(A, batch=2)
    futs = [svc.submit(rhs) for _ in range(2)]
    failed = 0
    for f in futs:
        try:
            f.result(timeout=60)
        except WorkerDiedError:
            failed += 1
    assert failed >= 1
    # the supervisor restarted the worker: traffic flows again
    _x, rep = svc.submit(rhs).result(timeout=60)
    assert rep.health["ok"]
    assert svc.live.get("serve_worker_deaths_total") == 1
    assert svc.live.get("serve_worker_restarts_total") == 1
    assert svc.live.get("faults_injected_total",
                        site="serve.worker") == 1
    svc.close()


def test_device_loss_retry_with_backoff(problem):
    A, rhs = problem
    _arm({"site": "device.loss", "count": 1, "target": "serve"},
         AMGCL_TPU_RETRY_MAX=2, AMGCL_TPU_RETRY_BACKOFF_MS=10)
    svc = _svc(A, batch=2)
    _x, rep = svc.submit(rhs).result(timeout=60)
    assert rep.health["ok"]
    st = svc.stats()["recovery"]
    assert st["retries"] == 1 and st["recovered"] == 1
    assert svc.live.get("recovery_retries_total") == 1
    assert svc.live.get("recoveries_total") == 1
    svc.close()


def test_retries_off_fails_batch_typed(problem):
    """With AMGCL_TPU_RETRY_MAX unset the historical behavior holds:
    a failed batch fails its futures (typed), no retries."""
    A, rhs = problem
    _arm({"site": "device.loss", "count": 1, "target": "serve"})
    svc = _svc(A, batch=2)
    with pytest.raises(DeviceLostError):
        svc.submit(rhs).result(timeout=60)
    assert "recovery" not in svc.stats()
    svc.close()


def test_poison_bisection_isolates(problem):
    A, rhs = problem
    _arm({"site": "serve.poison", "rid": 2, "count": -1},
         AMGCL_TPU_RETRY_MAX=1, AMGCL_TPU_RETRY_BACKOFF_MS=10)
    svc = _svc(A, batch=4, flush_ms=60)
    futs = [svc.submit(rhs) for _ in range(4)]
    outcomes = []
    for f in futs:
        try:
            _x, rep = f.result(timeout=120)
            assert rep.health["ok"]
            outcomes.append("ok")
        except PoisonRequestError:
            outcomes.append("poison")
    assert outcomes == ["ok", "poison", "ok", "ok"]
    svc.close()


def test_cancelled_expired_future_does_not_poison_batch(problem):
    """A caller-cancelled PENDING future whose request then expires
    must not blow up the timeout path (set_exception on a CANCELLED
    future raises InvalidStateError) — batch-mates still get served."""
    A, rhs = problem
    svc = _svc(A, batch=2, flush_ms=40)
    # stall the worker so the cancel lands while the request is queued
    svc.start()
    import time as _time
    gate = threading.Event()
    orig = svc._run_batch

    def gated(batch):
        gate.wait(timeout=30)
        return orig(batch)

    svc._run_batch = gated
    f_dead = svc.submit(rhs, timeout_s=0.01)
    assert f_dead.cancel()               # PENDING -> CANCELLED
    f_live = svc.submit(rhs)
    _time.sleep(0.05)                    # let f_dead expire
    gate.set()
    _x, rep = f_live.result(timeout=60)  # innocent batch-mate served
    assert rep.health["ok"]
    svc.close()


def test_guard_off_solver_never_books_numeric_fault(problem):
    """guard=False solvers never reach the numeric seam — the rule
    must stay armed and unbooked (no vacuous fault telemetry)."""
    A, rhs = problem
    _arm({"site": "numeric.nan", "at": 1, "count": 1})
    b = make_solver(A, AMGParams(dtype=jnp.float32, coarse_enough=200),
                    CG(maxiter=100, tol=1e-6, guard=False))
    _x, rep = b(rhs)
    assert rep.health is None            # guard off: no decode
    assert inject.injected_total() == 0  # nothing booked
    assert inject.armed_numeric() is not None   # still armed


def test_rid_string_coerced():
    _arm({"site": "serve.poison", "rid": "2", "count": 1})
    assert inject.plan_errors() == []
    assert inject.should_fire("serve.poison", rids=(2,)) is not None
    _arm({"site": "serve.poison", "rid": "x"})
    assert any("bad field" in e for e in inject.plan_errors())


def test_timeout_storm_and_reject(problem):
    A, rhs = problem
    _arm([{"site": "serve.timeout", "count": 1},
          {"site": "serve.reject", "count": 1, "after": 1}])
    svc = _svc(A, batch=2)
    f1 = svc.submit(rhs)                 # injected timeout victim
    with pytest.raises(TimeoutError):
        f1.result(timeout=60)
    with pytest.raises(_queue.Full):     # injected saturation
        svc.submit(rhs)
    _x, rep = svc.submit(rhs).result(timeout=60)
    assert rep.health["ok"]
    svc.close()


# ---------------------------------------------------------------------------
# farm: admission faults under concurrency, load shedding
# ---------------------------------------------------------------------------

def _farm(**kw):
    from amgcl_tpu.serve.farm import SolverFarm
    kw.setdefault("metrics_port", -1)
    return SolverFarm(**kw)


def _scaled(A, f):
    return CSR(A.ptr, A.col, np.asarray(A.val) * f, A.ncols)


def test_farm_eviction_under_admission_faults(problem):
    """Satellite: concurrent register/evict/solve while the injector
    forces admission failures — the budget balances to zero leaked
    charges and no tenant deadlocks (bounded joins)."""
    A, rhs = problem
    _arm({"site": "alloc.farm", "count": -1, "p": 0.4, "seed": 3},
         AMGCL_TPU_RETRY_MAX=1, AMGCL_TPU_RETRY_BACKOFF_MS=5)
    farm = _farm(max_bytes=0)
    names = ["t0", "t1", "t2"]
    mats = {n: _scaled(A, 1.0 + i) for i, n in enumerate(names)}
    errors = []

    def worker(name):
        for k in range(6):
            try:
                farm.register(name, mats[name])
                farm.solve(name, rhs, timeout_s=60)
                if k % 2:
                    farm.evict(name)
            except (AdmissionError, KeyError,
                    RuntimeError, _queue.Full):
                continue                  # typed/expected under chaos
            except Exception as e:        # noqa: BLE001 — anything
                errors.append(e)          # else is a real bug
                return

    threads = [threading.Thread(target=worker, args=(n,), daemon=True)
               for n in names]
    for t in threads:
        t.start()
    for t in threads:
        t.join(180)
        assert not t.is_alive(), "tenant worker deadlocked"
    assert not errors, errors
    # drain: evict every tenant, then the pool must balance to ZERO —
    # no charge leaked through the failed/rolled-back admissions
    os.environ.pop("AMGCL_TPU_FAULT_PLAN", None)
    inject._reset_for_tests()
    for n in names:
        try:
            farm.evict(n)
        except KeyError:
            pass
    assert farm.pool.used == 0, farm.pool.resident()
    farm.close()


def test_farm_admission_exhausted_typed(problem):
    A, _rhs = problem
    _arm({"site": "alloc.farm", "count": -1},
         AMGCL_TPU_RETRY_MAX=1, AMGCL_TPU_RETRY_BACKOFF_MS=5)
    farm = _farm(max_bytes=0)
    with pytest.raises(AdmissionError, match="FARM_MAX_BYTES"):
        farm.register("t0", A)
    assert farm.pool.used == 0
    farm.close()


def test_farm_load_shedding_and_cooldown(problem):
    A, rhs = problem
    os.environ["AMGCL_TPU_SHED_BREACHES"] = "1"
    os.environ["AMGCL_TPU_SHED_COOLDOWN_S"] = "0.3"
    farm = _farm(max_bytes=0)
    farm.register("hot", A, slo={"p99_ms": 1e-3}, slo_window=4)
    farm.solve("hot", rhs, timeout_s=60)
    import time as _time
    shed = False
    deadline = _time.monotonic() + 60
    while _time.monotonic() < deadline:
        try:
            farm.solve("hot", rhs, timeout_s=60)
        except LoadShedError:
            shed = True
            break
    assert shed
    assert farm.stats()["tenants"][0]["shedding"] is True
    assert farm.stats()["recovery"]["shed"] >= 1
    assert farm.live.get("farm_load_shed_total", tenant="hot") >= 1
    # the cooldown re-admits a probe (shedding is bounded, not sticky)
    _time.sleep(0.4)
    farm.solve("hot", rhs, timeout_s=60)
    farm.close()


def test_farm_injected_worker_death(problem):
    A, rhs = problem
    _arm({"site": "serve.worker", "count": 1, "target": "farm"})
    farm = _farm(max_bytes=0)
    farm.register("t", A)
    fut = farm.submit("t", rhs)
    with pytest.raises(WorkerDiedError):
        fut.result(timeout=60)
    # supervisor restarted the dispatch thread: traffic flows again
    _x, rep = farm.solve("t", rhs, timeout_s=60)
    assert rep.health["ok"]
    assert farm.stats()["recovery"]["worker_deaths"] == 1
    farm.close()


# ---------------------------------------------------------------------------
# lint rule 8 + doctor findings + chaos smoke
# ---------------------------------------------------------------------------

def test_lint_swallowed_worker_rule(tmp_path):
    from amgcl_tpu.analysis import lint
    bad = tmp_path / "workers"
    bad.mkdir()
    (bad / "w.py").write_text(
        "import threading\n"
        "class W:\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._loop).start()\n"
        "    def _loop(self):\n"
        "        while True:\n"
        "            try:\n"
        "                self._step()\n"
        "            except Exception:\n"
        "                pass\n"
        "    def _step(self):\n"
        "        try:\n"
        "            print('x')\n"
        "        except Exception:\n"
        "            pass\n"
        "    def not_a_worker(self):\n"
        "        try:\n"
        "            print('y')\n"
        "        except Exception:\n"
        "            pass\n")
    fs = lint.run_lint(root=str(bad),
                       rules=["swallowed-worker-exception"])
    symbols = sorted(f["symbol"] for f in fs)
    # _loop directly, _step through the same-module call closure;
    # not_a_worker is lexically outside every thread-target tree
    assert symbols == ["W._loop", "W._step"]
    # routed errors are clean
    good = tmp_path / "ok"
    good.mkdir()
    (good / "w.py").write_text(
        "import threading\n"
        "def start(fn):\n"
        "    threading.Thread(target=loop).start()\n"
        "def loop():\n"
        "    try:\n"
        "        pass\n"
        "    except Exception as e:\n"
        "        report(e)\n"
        "def report(e):\n"
        "    pass\n")
    assert lint.run_lint(root=str(good),
                         rules=["swallowed-worker-exception"]) == []


def test_lint_repo_clean_vs_baseline():
    from amgcl_tpu.analysis import lint
    with open(os.path.join(REPO, "ANALYSIS_BASELINE.json")) as f:
        base = json.load(f)
    fs = lint.run_lint(rules=["swallowed-worker-exception"])
    split = lint.apply_baseline(fs, base)
    assert split["new"] == [], split["new"]
    assert all(s["reason"] for s in split["suppressed"])


def test_diagnose_recovery_findings():
    from amgcl_tpu.telemetry import health as H
    from amgcl_tpu.telemetry.report import SolveReport
    rec = {"recovered": True, "final_rung": "solver", "runs": 3,
           "attempts": [
               {"rung": "initial", "ok": False,
                "flags": ["breakdown_rho"]},
               {"rung": "solver", "ok": True, "flags": []}]}
    rep = SolveReport(5, 1e-8, recovery=rec)
    codes = [f["code"] for f in H.diagnose(rep)]
    assert "recovered" in codes and "recovery_thrash" in codes
    lost = {"recovered": False, "runs": 1,
            "attempts": [{"rung": "initial", "ok": False}]}
    codes = [f["code"] for f in H.diagnose(
        SolveReport(5, 1e-8), recovery=lost)]
    assert "recovery_exhausted" in codes
    sev = {f["code"]: f["severity"] for f in H.diagnose(
        SolveReport(5, 1e-8), recovery=lost)}
    assert sev["recovery_exhausted"] == "critical"
    # the clean recovery-enabled solve (one ok attempt, no ladder)
    # must NOT read as an exhaustion (reviewer-found false critical)
    clean = {"recovered": False, "final_rung": "initial", "runs": 0,
             "attempts": [{"rung": "initial", "ok": True,
                           "flags": []}]}
    codes = [f["code"] for f in H.recovery_findings(clean)]
    assert "recovery_exhausted" not in codes and "recovered" not in codes


def test_chaos_single_scenario_smoke():
    from amgcl_tpu.faults import chaos
    out = chaos.run_chaos(names=["numeric_nan"])
    assert out["ok"], out
    assert out["scenarios"][0]["outcome"] == "recovered"
    assert out["hangs"] == 0


def test_chaos_cli_contract():
    """The `python -m amgcl_tpu.faults --selftest [names]` entry the
    bench.py --check recovery gate consumes: one JSON line on stdout,
    exit 0 when green (a narrowed two-scenario run keeps it fast)."""
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8"
                        ).strip()
    env.pop("AMGCL_TPU_FAULT_PLAN", None)
    r = subprocess.run(
        [sys.executable, "-m", "amgcl_tpu.faults", "--selftest",
         "numeric_nan", "serve_timeout_storm"],
        capture_output=True, text=True, timeout=420, cwd=REPO, env=env)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["ok"] and rec["total"] == 2 and rec["hangs"] == 0
    assert {s["name"] for s in rec["scenarios"]} \
        == {"numeric_nan", "serve_timeout_storm"}


def test_fault_event_emitted(problem, tmp_path, monkeypatch):
    """Every firing emits a ``fault`` JSONL event (and the recovery
    path's solve event carries the trail)."""
    from amgcl_tpu.telemetry import sink
    A, rhs = problem
    out = tmp_path / "faults.jsonl"
    monkeypatch.setenv("AMGCL_TPU_TELEMETRY", str(out))
    sink.set_default_sink(sink.JsonlSink(str(out)))
    try:
        _arm({"site": "numeric.nan", "at": 2, "count": 1})
        _mk(A, recovery=True)(rhs)
    finally:
        sink.set_default_sink(sink.NullSink())
    events = [json.loads(ln) for ln in open(out)]
    fault = [e for e in events if e.get("event") == "fault"]
    assert fault and fault[0]["site"] == "numeric.nan"
    recs = [e for e in events if e.get("event") == "recovery"]
    assert recs and recs[-1]["recovered"] is True
    assert recs[-1]["final_rung"] == "last_good"
