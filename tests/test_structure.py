"""Operator X-ray tests (ISSUE 14): structure analytics on known
matrices, the to_device('auto') format-decision ledger (winner + reason
incl. budget-starved picks), the predict-only reorder-gain advisor, the
host-purity contract (no jax, compile_watch delta 0), and the
surfacing seams (hierarchy_stats fold, doctor fold, rollup specs,
cli/bench --xray)."""

import json

import numpy as np
import pytest

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.telemetry import structure as st
from amgcl_tpu.utils.sample_problem import poisson3d


def _amg(A, coarse_enough=50):
    from amgcl_tpu.models.amg import AMG, AMGParams
    return AMG(A, AMGParams(coarse_enough=coarse_enough))


# ---------------------------------------------------------------------------
# window-tiling parity with the real packer
# ---------------------------------------------------------------------------

def test_tile_windows_host_matches_packer():
    """The X-ray's O(n) window mirror must agree exactly with
    ops.unstructured.tile_windows (the packer the predictions price)."""
    from amgcl_tpu.ops.unstructured import tile_windows
    mats = [poisson3d(8)[0], st.permuted_banded(2048, bw=4, seed=1)[0]]
    # a matrix with empty rows (ptr[i] == ptr[i+1])
    ptr = np.array([0, 2, 2, 3], np.int64)
    mats.append(CSR(ptr, np.array([0, 2, 1], np.int32),
                    np.ones(3), 3))
    for A in mats:
        for tile in (1024, 64):      # windowed-ELL and dense-window
            a = tile_windows(A, tile)
            b = st.tile_windows_host(A, tile)
            assert a[0] == b[0] and a[4] == b[4]
            np.testing.assert_array_equal(a[3], b[3])


def test_fingerprint_matches_registry_scheme():
    A1 = poisson3d(6)[0]
    A2 = CSR(A1.ptr.copy(), A1.col.copy(), A1.val.copy(), A1.ncols)
    from amgcl_tpu.serve.registry import sparsity_fingerprint
    assert st.fingerprint(A1) == sparsity_fingerprint(A2)


# ---------------------------------------------------------------------------
# structure metrics on known matrices
# ---------------------------------------------------------------------------

def test_seven_point_stencil_metrics():
    """7-point stencil: exactly 7 occupied diagonals, near-zero ELL
    padding (boundary rows only), and the advisor reports no gain —
    the structure is already as banded as it gets."""
    A, _ = poisson3d(8)
    met = st.structure_metrics(A)
    assert met["diagonals"]["ndiags"] == 7
    # occupied offsets are exactly {0, ±1, ±8, ±64}
    offs = sorted(o for o, _, _ in met["diagonals"]["occupancy_top"])
    assert offs == [-64, -8, -1, 0, 1, 8, 64]
    # the main diagonal is fully occupied
    top = {o: c for o, c, _ in met["diagonals"]["occupancy_top"]}
    assert top[0] == A.nrows
    assert met["ell"]["k"] == 7 and met["ell"]["k_padded"] == 8
    # padding vs the raw max row length is only the Dirichlet boundary
    assert met["ell"]["pad_frac"] == pytest.approx(
        1.0 - A.nnz / (A.nrows * 7), abs=1e-4)
    assert met["ell"]["pad_frac"] < 0.15
    assert met["bandwidth"]["max"] == 64
    adv = st.advise(A, variants=("rcm",))
    best = adv.get("best")
    assert best is None or best["gain"] <= 1.02, \
        "advisor must report no gain on an already-banded stencil"
    # and no reorder_gain finding fires
    xray = {"levels": [{"level": 0, "metrics": met, "advisor": adv}],
            "summary": {}}
    codes = [f["code"] for f in st.structure_findings(xray)]
    assert "reorder_gain" not in codes


def test_permuted_banded_rcm_recovers_band():
    """Randomly-permuted banded matrix: RCM recovers the band, and the
    predicted ndiags / window densification is asserted."""
    A, A0, _perm = st.permuted_banded(4096, bw=4, seed=0)
    met = st.structure_metrics(A)
    assert met["diagonals"]["ndiags"] > 500          # scrambled
    adv = st.advise(A, variants=("rcm",))
    best = adv["best"]
    assert best["gain"] > 1.5
    nd_id, nd_rcm = best["densify"]["ndiags"]
    assert nd_id > 500
    assert nd_rcm <= 4 * (2 * 4 + 1)                 # band recovered
    # window span shrinks from full width toward the aligned band
    # (starts floor to the 1024 DMA alignment, so the recovered band
    # still pays up to two alignment quanta)
    win_id, win_rcm = best["densify"]["window_win"]
    assert win_id == 4096 and win_rcm < win_id
    wf_id, wf_rcm = best["densify"]["window_fill"]
    assert wf_rcm > wf_id
    bw_id, bw_rcm = best["densify"]["bandwidth_max"]
    assert bw_rcm < bw_id / 10


def test_block_structured_density_curve():
    """Block-structured CSR: the (8, 128) tile-granularity density
    curve pins exactly — dense 8x128 blocks on a block diagonal give
    128 occupied granules out of 1024, each completely full."""
    n = 1024
    rows_l, cols_l = [], []
    for band in range(n // 8):                  # 8-row bands
        c0 = 128 * (band % 8)                   # one 8x128 block each
        r = np.repeat(np.arange(band * 8, band * 8 + 8), 128)
        c = np.tile(np.arange(c0, c0 + 128), 8)
        rows_l.append(r)
        cols_l.append(c)
    rows = np.concatenate(rows_l)
    cols = np.concatenate(cols_l)
    order = np.lexsort((cols, rows))
    rows, cols = rows[order], cols[order]
    ptr = np.zeros(n + 1, np.int64)
    np.add.at(ptr, rows + 1, 1)
    A = CSR(np.cumsum(ptr), cols.astype(np.int32),
            np.ones(len(cols), np.float32), n)
    met = st.structure_metrics(A)
    curve = {c["granule"]: c for c in met["window"]["density_curve"]}
    # one tile (n=1024), win = 1024: 128x8 = 1024 granules of (8, 128)
    assert met["window"]["tiles"] == 1 and met["window"]["win"] == 1024
    assert curve["8x128"]["occupied_frac"] == pytest.approx(
        128 / 1024.0)
    assert curve["8x128"]["fill_in_occupied"] == pytest.approx(1.0)
    assert curve["1x1"]["occupied_frac"] == pytest.approx(
        A.nnz / (1024.0 * 1024.0))


# ---------------------------------------------------------------------------
# the format-decision ledger
# ---------------------------------------------------------------------------

def test_decision_recorded_on_auto_conversion():
    from amgcl_tpu.ops import device as dev
    A, _ = poisson3d(8)
    M = dev.to_device(A, "auto")
    dec = M._format_decision
    assert dec["fmt"] == "dia" and dec["reason"] == "cost"
    fmts = [c["format"] for c in dec["candidates"]]
    assert fmts == ["dense", "dia", "dwin", "well", "ell"]
    assert dec["margin"] is not None and dec["margin"] > 1.0
    # the DIA byte model is exact: predicted stored == built stored
    assert dec["built_bytes"] == dec["stored_bytes"]
    # every ineligible candidate names its reason
    for c in dec["candidates"]:
        assert c["eligible"] or c.get("why")


def test_decision_forced_reason():
    from amgcl_tpu.ops import device as dev
    A, _ = poisson3d(6)
    M = dev.to_device(A, "dia")
    assert M._format_decision["reason"] == "forced"
    M = dev.to_device(A, "dense")
    assert M._format_decision["reason"] == "forced"


def test_hierarchy_collects_decisions():
    amg = _amg(poisson3d(8)[0])
    decs = amg._format_decisions
    assert len(decs) == len(amg.host_levels)
    assert decs[0] is not None and decs[0]["fmt"] == "dia"
    assert all(d is None or d["reason"] in ("cost", "budget", "forced")
               for d in decs)


def test_rebuild_carries_decisions_over():
    A, _ = poisson3d(8)
    amg = _amg(A)
    before = [d and d["fmt"] for d in amg._format_decisions]
    amg.structure_report()
    assert amg._structure_cache is not None
    amg.rebuild(A.val.copy())
    # cache invalidated, decisions carried (refresh_values path)
    assert amg._structure_cache is None
    assert [d and d["fmt"] for d in amg._format_decisions] == before


def test_dense_window_budget_vs_window_reason():
    """The satellite fix: a dense-window decline distinguishes 'budget'
    (starved by earlier draws on the shared pool) from 'window'
    (structurally too wide for any budget)."""
    from amgcl_tpu.ops.densewin import csr_to_dense_window
    from amgcl_tpu.telemetry.ledger import DeviceMemoryBudget
    A, _ = poisson3d(8)
    # learn this matrix's dense-window footprint from a free dry run
    probe = {}
    assert csr_to_dense_window(
        A, budget=DeviceMemoryBudget(0), why=probe) is None
    need = probe["need_bytes"]
    assert need > 0
    # pool large enough in total, but drained by an earlier charge
    budget = DeviceMemoryBudget(2 * need)
    assert budget.try_charge(2 * need - 1024, "earlier_level")
    why = {}
    assert csr_to_dense_window(A, budget=budget, why=why) is None
    assert why["why"] == "budget"
    assert why["need_bytes"] == need
    # pool too small in total: structural, not budget starvation
    why = {}
    assert csr_to_dense_window(
        A, budget=DeviceMemoryBudget(1024), why=why) is None
    assert why["why"] == "window"


def test_candidate_table_budget_reason_and_decision():
    A, _ = poisson3d(8)
    need = st.fast_facts(A)["dwin_bytes"]
    cands = st.candidate_table(A, on_tpu=True,
                               budget_remaining=need // 2,
                               budget_total=10 * need)
    dwin = next(c for c in cands if c["format"] == "dwin")
    assert not dwin["eligible"] and dwin["why"] == "budget"
    # the realistic starved shape: auto fell THROUGH dwin (which it
    # prefers for gather-freedom, whatever the byte ranking) to a
    # later format — the pick is budget-starved, not a cost win
    for fallback in ("well", "ell"):
        assert st.decision_record(cands, fallback)["reason"] == "budget"
    # a winner auto prefers OVER dwin (dia wins before the budget is
    # even consulted) stays a cost win
    assert st.decision_record(cands, "dia")["reason"] == "cost"
    assert st.decision_record(cands, "ell",
                              forced=True)["reason"] == "forced"


# ---------------------------------------------------------------------------
# host-purity contract (STRUCTURE_CONTRACTS)
# ---------------------------------------------------------------------------

def test_structure_audit_contract():
    from amgcl_tpu.analysis import jaxpr_audit as ja
    rec = ja.audit_structure(m=6)
    assert rec["jax_imports"] == 0, rec.get("jax_import_names")
    assert not rec.get("skipped"), rec
    assert rec["new_traces"] == 0
    assert rec["new_backend_compiles"] == 0
    assert ja.check_structure(rec) == []


def test_structure_report_compile_watch_delta_zero():
    from amgcl_tpu.telemetry import compile_watch as cw
    amg = _amg(poisson3d(8)[0])
    before = cw.snapshot()["totals"]
    xray = amg.structure_report(advise=True)
    st.structure_findings(xray)
    st.format_xray(xray)
    after = cw.snapshot()["totals"]
    assert after["traces"] == before["traces"]
    assert after["backend_compiles"] == before["backend_compiles"]


# ---------------------------------------------------------------------------
# surfacing: hierarchy_stats fold, doctor fold, gauges, rollups, diff
# ---------------------------------------------------------------------------

def test_hierarchy_stats_folds_structure():
    amg = _amg(poisson3d(8)[0])
    assert "structure" not in amg.hierarchy_stats()["levels"][0]
    amg.structure_report()
    stats = amg.hierarchy_stats()
    srow = stats["levels"][0]["structure"]
    assert srow["ndiags"] == 7
    assert srow["decision"]["fmt"] == "dia"
    assert stats["structure"]["formats"].startswith("dia")
    # JSON-clean (rides the 'hierarchy' telemetry event)
    json.dumps(stats)


def test_diagnose_folds_structure_findings():
    from amgcl_tpu.telemetry.health import diagnose
    A, _, _ = st.permuted_banded(2048, bw=4, seed=0)
    amg = _amg(A, coarse_enough=40)
    xray = amg.structure_report(advise=True)
    findings = diagnose(None, structure=xray)
    codes = [f.get("code") for f in findings]
    assert "reorder_gain" in codes
    f = next(f for f in findings if f["code"] == "reorder_gain")
    assert f["predicted_gain"] > 1.15
    assert "reorder" in f["suggestion"].lower() or \
        "Reordered" in f["suggestion"]


def test_publish_xray_gauges():
    from amgcl_tpu.telemetry.live import LiveRegistry, \
        publish_xray_gauges
    reg = LiveRegistry()
    publish_xray_gauges(reg, {"padding_waste_frac": 0.25,
                              "predicted_reorder_gain": 2.5,
                              "dia_fill": 1.1})
    text = reg.prometheus()
    assert "xray_padding_waste_frac 0.25" in text
    assert "xray_predicted_reorder_gain 2.5" in text
    assert "xray_dia_fill 1.1" in text


def test_rollup_specs_pick_up_new_events():
    from amgcl_tpu.telemetry import metrics
    recs = [
        {"event": "structure",
         "summary": {"padding_waste_frac": 0.2, "dia_fill": 1.1,
                     "predicted_reorder_gain": 2.0,
                     "window_fill": 0.5, "bandwidth_max": 10}},
        {"event": "bench_xray",
         "join": {"predicted_gain": 2.0, "measured_gain": 1.8,
                  "ratio": 0.9}},
    ]
    out = metrics.rollup_events(recs)
    assert out["structure.padding_waste_frac"]["last"] == 0.2
    assert out["bench_xray.measured_gain"]["last"] == 1.8
    assert out["bench_xray.gain_ratio"]["last"] == 0.9


def test_diff_names_format_decision_changes():
    from amgcl_tpu.telemetry import diff as dmod
    a = {"metric": "solve", "value": 1.0, "iters": 5,
         "device_platform": "cpu",
         "structure": {"formats": "ell/dense", "reasons": "cost/cost"}}
    b = {"metric": "solve", "value": 1.0, "iters": 5,
         "device_platform": "cpu",
         "structure": {"formats": "dia/dense",
                       "reasons": "budget/cost"}}
    d = dmod.diff(a, b)
    assert d["structure"]["changed"]
    codes = [f["code"] for f in dmod.findings(d)]
    assert "cross_run_format" in codes
    assert "format decisions" in dmod.format_diff(d)
    # identical summaries produce no call-out
    assert "structure" not in dmod.diff(a, dict(a))


# ---------------------------------------------------------------------------
# cli / bench surfaces
# ---------------------------------------------------------------------------

def test_cli_xray_smoke(capsys):
    from amgcl_tpu import cli
    rc = cli.main(["-n", "8", "--xray", "--doctor",
                   "-p", "precond.coarse_enough=50"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "Operator X-ray:" in out
    assert "Format-decision ledger" in out
    assert "Convergence doctor" in out


def test_bench_xray_smoke(monkeypatch):
    import bench
    emitted = []
    monkeypatch.setattr(bench._stdout_sink, "emit",
                        lambda rec, **kw: emitted.append(dict(rec)))
    monkeypatch.setenv("AMGCL_TPU_XRAY_N", "1024")
    monkeypatch.setenv("AMGCL_TPU_XRAY_BW", "3")
    rc = bench.main_xray()
    rec = emitted[-1]
    json.dumps(rec)
    assert rc == 0
    assert rec["event"] == "bench_xray"
    assert rec["join"]["predicted_gain"] > 1.0
    assert rec["join"]["measured_gain"] is not None
    assert rec["provenance"]["platform_tag"] in ("ici", "cpu-fallback")
    # per-format rows: ELL always measures on both sides
    ell = next(r for r in rec["formats"] if r["format"] == "ell")
    assert ell["t_identity_s"] and ell["t_rcm_s"]


def test_bench_worker_summary_shape():
    """The compact summary bench.py embeds on every worker record is
    JSON-clean and carries the attribution fields the trend reads."""
    amg = _amg(poisson3d(8)[0])
    summ = st.xray_summary(amg.structure_report(advise=False))
    json.dumps(summ)
    assert summ["formats"].startswith("dia")
    assert summ["reasons"].startswith("cost")
    assert summ["padding_waste_frac"] is not None
    assert summ["fingerprint"]
