"""Mesh-sharded stencil setup (parallel/dist_stencil.py): the hierarchy is
CONSTRUCTED on the mesh — per-shard slabs, halo-exchange shifts, psum/pmax
reductions — and the solve runs as one shard_map program. Parity against
the serial build is the contract."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.models.amg import AMGParams
from amgcl_tpu.models.make_solver import make_solver
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.relaxation.jacobi import DampedJacobi
from amgcl_tpu.utils.sample_problem import poisson3d
from amgcl_tpu.parallel.mesh import make_mesh
from amgcl_tpu.parallel.dist_stencil import (
    DistStencilSolver, dist_stencil_build)


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(8)


def _serial_iters(A, rhs, prm_kw, tol=1e-6):
    import os
    os.environ["AMGCL_TPU_DEVICE_SETUP"] = "1"
    try:
        s = make_solver(A, AMGParams(**prm_kw), CG(maxiter=100, tol=tol))
        x, info = s(jnp.asarray(rhs, jnp.float32))
    finally:
        del os.environ["AMGCL_TPU_DEVICE_SETUP"]
    return info.iters


def test_sharded_setup_iteration_parity(mesh8):
    A, rhs = poisson3d(32)
    kw = dict(dtype=jnp.float32, coarse_enough=600)
    s = DistStencilSolver(A, mesh8, AMGParams(**kw),
                          CG(maxiter=100, tol=1e-6), rep_coarse_enough=600)
    assert len(s.hier.levels) >= 2          # >= 2 levels built ON the mesh
    x, info = s(rhs)
    true = np.linalg.norm(rhs - A.spmv(np.asarray(x, np.float64))) \
        / np.linalg.norm(rhs)
    assert true < 1e-4
    assert info.iters == _serial_iters(A, rhs, kw)


def test_per_shard_memory_is_divided(mesh8):
    A, rhs = poisson3d(48)
    got = dist_stencil_build(A, mesh8, AMGParams(dtype=jnp.float32), 3000)
    assert got is not None
    hier, meta = got
    lv0 = hier.levels[0]
    shards = lv0.adata.addressable_shards
    assert len(shards) == 8
    # each shard holds exactly 1/8 of the level operator
    assert shards[0].data.size == lv0.adata.size // 8


def test_sharded_jacobi_variant(mesh8):
    A, rhs = poisson3d(32)
    kw = dict(dtype=jnp.float32, relax=DampedJacobi(), coarse_enough=600)
    s = DistStencilSolver(A, mesh8, AMGParams(**kw),
                          CG(maxiter=200, tol=1e-6), rep_coarse_enough=600)
    x, info = s(rhs)
    true = np.linalg.norm(rhs - A.spmv(np.asarray(x, np.float64))) \
        / np.linalg.norm(rhs)
    assert true < 1e-4
    assert info.iters == _serial_iters(A, rhs, kw)


def test_warm_start(mesh8):
    A, rhs = poisson3d(32)
    s = DistStencilSolver(A, mesh8, AMGParams(dtype=jnp.float32),
                          CG(maxiter=100, tol=1e-6))
    x, info = s(rhs)
    x2, info2 = s(rhs, x0=x)
    # f32 recursive-vs-recomputed residual drift can cost an iteration or
    # two at the tolerance boundary; the warm start must still be ~free
    assert info2.iters <= 2 < info.iters


def test_indivisible_grid_rejected(mesh8):
    A, rhs = poisson3d(12)      # 12 % 16 != 0
    with pytest.raises(ValueError):
        DistStencilSolver(A, mesh8, AMGParams(dtype=jnp.float32))


def test_unstructured_outside_fast_path(mesh8):
    # a non-stencil matrix has no grid -> build declines (callers use
    # DistAMGSolver / StripAMGSolver instead). Anisotropy no longer
    # declines — the semicoarsening rerun handles it (test below).
    from amgcl_tpu.ops.unstructured import fe_like_problem
    A, _ = fe_like_problem(n=2048, nnz_target=30_000, seed=7)
    got = dist_stencil_build(A, mesh8, AMGParams(dtype=jnp.float32), 600)
    assert got is None


def test_sharded_setup_anisotropic_semicoarsening(mesh8):
    """Anisotropy stays on the MESH-BUILT path: the speculation check
    reruns the level with the measured strong axes instead of breaking
    out (mirrors ops/stencil_device.py's device-path behavior)."""
    A, rhs = poisson3d(16, anisotropy=1e-3)
    s = DistStencilSolver(A, mesh8,
                          AMGParams(dtype=jnp.float32, coarse_enough=300),
                          CG(maxiter=100, tol=1e-6),
                          rep_coarse_enough=300)
    assert len(s.hier.levels) >= 1       # mesh-built despite anisotropy
    # semicoarsening: first coarse level halves only the strong axes
    assert s.meta[1] > s.meta[0] // 8    # not full 2x2x2 coarsening
    x, info = s(rhs)
    r = rhs - A.spmv(np.asarray(x, dtype=np.float64))
    rel = float(np.linalg.norm(r) / np.linalg.norm(rhs))
    assert rel < 1e-3


def test_dist_stencil_fused_slab_parity(mesh8, monkeypatch):
    """Fused slab kernels (interpret hook) vs the composed slab chain:
    the same sharded problem must converge with identical iterations."""
    import scipy.sparse as sp
    from amgcl_tpu.ops.csr import CSR

    def T(n):
        e = np.ones(n)
        return sp.diags([-e[:-1], 2 * e, -e[:-1]], [-1, 0, 1],
                        format="csr")
    I = sp.identity
    A = (sp.kron(I(16), sp.kron(I(8), T(64)))
         + sp.kron(I(16), sp.kron(T(8), I(64)))
         + sp.kron(T(16), sp.kron(I(8), I(64)))).tocsr()
    A.sort_indices()
    A = CSR.from_scipy(A)
    rhs = np.ones(A.nrows)

    s0 = DistStencilSolver(A, mesh8,
                           AMGParams(dtype=jnp.float32, coarse_enough=64),
                           CG(maxiter=40, tol=1e-5))
    assert all(lv.fused is None for lv in s0.hier.levels)
    x0, i0 = s0(rhs)

    monkeypatch.setenv("AMGCL_TPU_PALLAS_INTERPRET", "1")
    s1 = DistStencilSolver(A, mesh8,
                           AMGParams(dtype=jnp.float32, coarse_enough=64),
                           CG(maxiter=40, tol=1e-5))
    assert s1.hier.levels[0].fused is not None, \
        "eligible slab level built without fused kernels"
    assert s1.hier.levels[0].fused.up_ok
    x1, i1 = s1(rhs)

    assert i1.iters == i0.iters
    r = rhs - A.spmv(np.asarray(x1, dtype=np.float64))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-4
