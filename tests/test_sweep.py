"""Convergence cross-sweep — the reference's test matrix shape
(tests/test_solver.hpp:120-248): {Krylov solvers} x {smoothers} x
{coarsenings} on the Poisson fixture, each asserting the final relative
residual like the reference's < 1e-4 criterion (tighter here: 1e-6, f64).
Unsupported combinations must raise, not silently misbehave
(test_solver.hpp:166 skips on std::logic_error)."""

import numpy as np
import jax.numpy as jnp
import pytest

from amgcl_tpu.models.make_solver import make_solver
from amgcl_tpu.models.amg import AMGParams
from amgcl_tpu.models.runtime import SOLVERS, RELAXATION, COARSENING
from amgcl_tpu.utils.sample_problem import poisson3d

SOLVER_NAMES = ["cg", "bicgstab", "bicgstabl", "gmres", "lgmres", "fgmres",
                "idrs", "richardson"]
RELAX_NAMES = ["damped_jacobi", "spai0", "spai1", "chebyshev",
               "gauss_seidel", "ilu0", "ilut"]
COARSE_NAMES = ["smoothed_aggregation", "aggregation", "ruge_stuben",
                "smoothed_aggr_emin"]


@pytest.fixture(scope="module")
def problem():
    return poisson3d(10)


@pytest.mark.parametrize("solver_name", SOLVER_NAMES)
@pytest.mark.parametrize("relax_name", ["spai0", "ilu0"])
def test_solver_x_relax(problem, solver_name, relax_name):
    A, rhs = problem
    solver = SOLVERS[solver_name](maxiter=300, tol=1e-6)
    solve = make_solver(
        A, AMGParams(relax=RELAXATION[relax_name](), dtype=jnp.float64,
                     coarse_enough=200), solver)
    x, info = solve(rhs)
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-4, \
        (solver_name, relax_name, info.iters)


def test_unsupported_combo_raises():
    """ruge_stuben is scalar-only; block input must raise, not misbehave
    (the reference skips unsupported combos via thrown logic_error)."""
    from amgcl_tpu.utils.sample_problem import poisson3d_block
    A, _ = poisson3d_block(6, 2)
    with pytest.raises(NotImplementedError):
        COARSENING["ruge_stuben"]().transfer_operators(A)


@pytest.mark.parametrize("relax_name", RELAX_NAMES)
@pytest.mark.parametrize("coarse_name", COARSE_NAMES)
def test_relax_x_coarsening(problem, relax_name, coarse_name):
    A, rhs = problem
    solve = make_solver(
        A, AMGParams(coarsening=COARSENING[coarse_name](),
                     relax=RELAXATION[relax_name](), dtype=jnp.float64,
                     coarse_enough=200),
        SOLVERS["cg"](maxiter=300, tol=1e-6))
    x, info = solve(rhs)
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-4, \
        (relax_name, coarse_name, info.iters)


# ---------------------------------------------------------------------------
# value-type cross product (reference: test_solver.hpp instantiates the
# sweep per value type — double / complex / static_matrix / nullspace)
# ---------------------------------------------------------------------------

def _value_problem(vtype):
    from amgcl_tpu.utils.sample_problem import (poisson3d_block,
                                                poisson3d_complex)
    if vtype == "block2":
        return poisson3d_block(8, 2)
    if vtype == "complex":
        return poisson3d_complex(8)
    if vtype == "nullspace":
        n = 8
        A, rhs = poisson3d(n)
        g = np.arange(n)
        X, _, _ = np.meshgrid(g, g, g, indexing="ij")
        B = np.stack([np.ones(n ** 3), X.ravel() / n], axis=1)
        return (A, B), rhs
    raise AssertionError(vtype)


def _value_params(relax_name="spai0"):
    return dict(relax=RELAXATION[relax_name](), dtype=jnp.float64,
                coarse_enough=150)


@pytest.mark.parametrize("solver_name", SOLVER_NAMES)
@pytest.mark.parametrize("vtype", ["block2", "complex"])
def test_solver_x_valuetype(solver_name, vtype):
    """Every Krylov solver against block and complex value types — the
    interaction coverage the per-feature tests could not give."""
    A, rhs = _value_problem(vtype)
    if vtype == "complex" and solver_name == "idrs":
        pytest.skip("IDR(s) shadow space is real-valued by construction")
    solver = SOLVERS[solver_name](maxiter=400, tol=1e-6)
    solve = make_solver(A, AMGParams(**_value_params()), solver)
    x, info = solve(rhs)
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-4, \
        (solver_name, vtype, info.iters)


@pytest.mark.parametrize("relax_name", RELAX_NAMES)
@pytest.mark.parametrize("vtype", ["block2", "complex", "nullspace"])
def test_relax_x_valuetype(relax_name, vtype):
    """Every smoother family against block / complex / near-nullspace
    fixtures, CG outer loop. Combinations the framework rejects must
    raise loudly (reference convention: thrown logic_error == skip)."""
    from amgcl_tpu.coarsening.smoothed_aggregation import \
        SmoothedAggregation
    prob, rhs = _value_problem(vtype)
    kw = _value_params(relax_name)
    if vtype == "nullspace":
        A, B = prob
        kw["coarsening"] = SmoothedAggregation(nullspace=B)
    else:
        A = prob
    solver = SOLVERS["cg"](maxiter=400, tol=1e-6)
    try:
        solve = make_solver(A, AMGParams(**kw), solver)
    except (NotImplementedError, ValueError) as e:
        pytest.skip("combination rejected loudly: %s" % e)
    x, info = solve(rhs)
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-4, \
        (relax_name, vtype, info.iters)
