"""Numerical-health layer: in-loop guard detection (breakdown / NaN /
stagnation / divergence with early exit), per-level convergence probes,
the convergence doctor, the Perfetto trace export, and the bench gate's
health check."""

import json
import os
import subprocess
import sys

import numpy as np
import scipy.sparse as sp
import jax.numpy as jnp
import pytest

from amgcl_tpu.models.make_solver import make_solver
from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.models.preconditioner import DummyPreconditioner
from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.ops import device as dev
from amgcl_tpu.solver import (CG, BiCGStab, BiCGStabL, GMRES, FGMRES,
                              LGMRES, IDRs, Richardson, PreOnly)
from amgcl_tpu.telemetry import (JsonlSink, diagnose, format_findings,
                                 health)
from amgcl_tpu.utils.sample_problem import poisson3d

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def neumann_laplacian(n):
    """Singular 1-D Neumann Laplacian: null space = span(ones). The ones
    rhs lies entirely in the null space (A @ ones == 0), so every Krylov
    method breaks down at the first search direction."""
    main = 2.0 * np.ones(n)
    main[0] = main[-1] = 1.0
    L = sp.diags([-np.ones(n - 1), main, -np.ones(n - 1)],
                 [-1, 0, 1]).tocsr()
    return dev.to_device(CSR.from_scipy(L), "ell", jnp.float64)


# -- breakdown paths (ISSUE 3 satellite: singular/indefinite systems) -------

@pytest.mark.parametrize("solver,kind", [
    (CG(maxiter=50, tol=1e-8, record_history=True), "breakdown_alpha"),
    (BiCGStab(maxiter=50, tol=1e-8, record_history=True), None),
    (IDRs(s=2, maxiter=50, tol=1e-8, record_history=True),
     "breakdown_rho"),
], ids=lambda v: v if isinstance(v, str) else type(v).__name__)
def test_breakdown_on_singular_system(solver, kind):
    """A singular system with a null-space rhs must set the breakdown
    flag (with its first-trip iteration) and return FINITE history and
    iterate — not NaN-filled arrays."""
    A = neumann_laplacian(8)
    b = jnp.ones(8, jnp.float64)
    x, it, res, hist, hs = solver.solve(A, lambda r: r, b)
    d = health.decode(hs.flags, hs.first_it)
    assert d["breakdown"] is not None
    if kind is not None:
        assert d["breakdown"] == kind
    assert "breakdown_iteration" in d
    assert bool(jnp.all(jnp.isfinite(x)))
    assert np.isfinite(float(res))
    h = np.asarray(hist)[:int(it)]
    assert np.all(np.isfinite(h)), type(solver).__name__
    # the loop exited at the trip instead of burning maxiter
    assert int(it) < solver.maxiter


@pytest.mark.parametrize("solver", [
    GMRES(M=10, maxiter=50, tol=1e-8, record_history=True),
    LGMRES(M=10, maxiter=50, tol=1e-8, record_history=True),
], ids=lambda s: type(s).__name__)
def test_hessenberg_breakdown_on_singular_system(solver):
    """GMRES/LGMRES on the null-space rhs: the zero-column Givens
    rotation annihilates the projected residual, so without the guard
    the solve reports res=0 'converged' while the singular triangular
    solve fills x with NaN. The Hessenberg trip (rjj ≈ 0 with the
    pre-step residual above eps) must fire instead, leaving a finite
    iterate and an honest residual."""
    A = neumann_laplacian(8)
    b = jnp.ones(8, jnp.float64)
    x, it, res, hist, hs = solver.solve(A, lambda r: r, b)
    d = health.decode(hs.flags, hs.first_it)
    assert d["breakdown"] == "breakdown_hessenberg"
    assert bool(jnp.all(jnp.isfinite(x)))
    assert np.isfinite(float(res)) and float(res) > 1e-8  # not 'converged'
    assert np.all(np.isfinite(np.asarray(hist)[:int(it)]))


def test_cg_guard_off_keeps_nan_exit():
    """guard=False restores the historical failure signal on a singular
    direction: the raw alpha division poisons the state and the loop
    NaN-exits immediately instead of burning maxiter on a
    finite-looking garbage iterate."""
    A = neumann_laplacian(8)
    b = jnp.ones(8, jnp.float64)
    x, it, res = CG(maxiter=50, tol=1e-8, guard=False).solve(
        A, lambda r: r, b)
    assert int(it) < 50                      # exited at the breakdown
    assert not np.isfinite(float(res))       # the honest NaN signal


def test_cg_indefinite_flags():
    """CG on a symmetric indefinite diagonal: p·Ap == 0 on the ones rhs
    — alpha-breakdown at iteration 0, iterate untouched and finite."""
    D = sp.diags([np.array([1., 1., 1., 1., -1., -1., -1., -1.])],
                 [0]).tocsr()
    A = dev.to_device(CSR.from_scipy(D), "ell", jnp.float64)
    x, it, res, hist, hs = CG(maxiter=50, tol=1e-10,
                              record_history=True).solve(
        A, lambda r: r, jnp.ones(8, jnp.float64))
    d = health.decode(hs.flags, hs.first_it)
    assert d["breakdown"] == "breakdown_alpha"
    assert d["breakdown_iteration"] == 0
    assert int(it) == 0 and np.isfinite(float(res))


def test_refine_merges_correction_health():
    """With refine>0 the correction solves' guard flags must reach
    SolveReport.health — a breakdown inside a correction cannot vanish
    into the [:2] slice (clean refined solves stay clean)."""
    A, rhs = poisson3d(10)
    s = make_solver(A, AMGParams(dtype=jnp.float64, coarse_enough=200),
                    CG(maxiter=100, tol=1e-8), refine=2,
                    refine_dtype="float64")
    x, info = s(rhs)
    assert info.health["ok"], info.health
    # singular operator: the initial solve breaks down AND the refine
    # restarts rediscover it — either way the flag must be in the report
    n = 8
    main = 2.0 * np.ones(n)
    main[0] = main[-1] = 1.0
    L = sp.diags([-np.ones(n - 1), main, -np.ones(n - 1)],
                 [-1, 0, 1]).tocsr()
    s = make_solver(L, DummyPreconditioner(L, dtype=jnp.float64),
                    CG(maxiter=50, tol=1e-10), refine=2,
                    refine_dtype="float64")
    x, info = s(np.ones(n))
    assert info.health["breakdown"] == "breakdown_alpha"


def test_clean_solves_report_ok():
    """Guards must stay silent on healthy solves — every solver, AMG-
    preconditioned Poisson."""
    A, rhs = poisson3d(10)
    for solver in [CG(maxiter=100, tol=1e-8),
                   BiCGStab(maxiter=100, tol=1e-8),
                   BiCGStabL(L=2, maxiter=100, tol=1e-8),
                   GMRES(maxiter=100, tol=1e-8),
                   FGMRES(maxiter=100, tol=1e-8),
                   LGMRES(maxiter=100, tol=1e-8),
                   IDRs(s=2, maxiter=100, tol=1e-8),
                   PreOnly()]:
        solve = make_solver(A, AMGParams(dtype=jnp.float64,
                                         coarse_enough=200), solver)
        x, info = solve(rhs)
        assert info.health is not None, type(solver).__name__
        assert info.health["ok"], (type(solver).__name__, info.health)
        assert info.health["flags"] == []


def test_divergence_breaks_early_and_reported():
    """An explicitly diverging iteration (Richardson, damping 2 on an SPD
    diagonal: error factor 3 per sweep) trips the divergence guard and
    terminates the while_loop early instead of burning maxiter; the
    report marks health.diverged (ISSUE 3 satellite)."""
    D = sp.diags([2.0 * np.ones(16)], [0]).tocsr()
    solve = make_solver(D, DummyPreconditioner(D, dtype=jnp.float64),
                        Richardson(maxiter=200, tol=1e-12, damping=2.0))
    x, info = solve(np.ones(16))
    assert info.health["diverged"] is True
    assert "divergence" in info.health["flags"]
    assert info.iters < 200          # early exit, not maxiter
    assert np.isfinite(info.resid)


def test_divergence_break_env_off(monkeypatch):
    """AMGCL_TPU_DIVERGENCE_BREAK=0: the flag still trips but the loop
    runs to maxiter (the historical behavior)."""
    monkeypatch.setenv("AMGCL_TPU_DIVERGENCE_BREAK", "0")
    D = sp.diags([2.0 * np.ones(4)], [0]).tocsr()
    A = dev.to_device(CSR.from_scipy(D), "ell", jnp.float64)
    x, it, res, hs = Richardson(maxiter=30, tol=1e-12, damping=2.0).solve(
        A, lambda r: r, jnp.ones(4, jnp.float64))
    d = health.decode(hs.flags, hs.first_it)
    assert d["diverged"] and int(it) == 30


def test_stagnation_flag():
    """Near-unit residual reduction over the window trips the (non-fatal)
    stagnation flag; the loop keeps going."""
    I = sp.identity(4, format="csr")
    A = dev.to_device(CSR.from_scipy(I), "ell", jnp.float64)
    x, it, res, hs = Richardson(maxiter=40, tol=1e-12,
                                damping=0.005).solve(
        A, lambda r: r, jnp.ones(4, jnp.float64))
    d = health.decode(hs.flags, hs.first_it)
    assert d["stagnated"] and not d["diverged"]
    assert int(it) == 40             # informational: no early exit


def test_divergence_tolerates_oscillation():
    """The divergence counter anchors on the best residual seen
    (AMGCL_TPU_DIV_RTOL): oscillation near the current floor — the
    normal life of BiCGStab/IDR(s) — must not trip, while sustained
    growth far off the floor must."""
    import jax.numpy as jnp_
    hs = health.init_state(jnp_.asarray(1.0))
    # grows every other step but never leaves 10x of the floor: clean
    for it, r in enumerate([0.5, 0.9, 0.4, 0.8, 0.3, 0.7, 0.2, 0.6,
                            0.15, 0.5, 0.1, 0.4]):
        ok, hs = health.step(hs, it, jnp_.asarray(r))
        assert bool(ok)
    assert int(hs.flags) == 0
    # now a genuine runaway: strictly growing, far above the floor
    r = 2.0
    for it in range(12, 25):
        ok, hs = health.step(hs, it, jnp_.asarray(r))
        r *= 3.0
    d = health.decode(hs.flags, hs.first_it)
    assert d["diverged"]


def test_guard_off_restores_bare_tuple():
    """guard=False drops the trailing HealthState — the historical
    (x, iters, resid[, hist]) contract, for callers that unpack."""
    A, rhs = poisson3d(8)
    Ad = dev.to_device(A, "ell", jnp.float64)
    got = CG(maxiter=50, tol=1e-8, guard=False).solve(
        Ad, lambda r: r, jnp.asarray(rhs))
    assert len(got) == 3
    got = CG(maxiter=50, tol=1e-8, record_history=True, guard=False).solve(
        Ad, lambda r: r, jnp.asarray(rhs))
    assert len(got) == 4


def test_breakdown_through_make_solver_and_sink(tmp_path):
    """SolveReport.health names the breakdown kind and iteration on a
    deliberately singular system, and the sink receives a dedicated
    'health' event (ISSUE 3 acceptance)."""
    from amgcl_tpu import telemetry
    n = 8
    main = 2.0 * np.ones(n)
    main[0] = main[-1] = 1.0
    L = sp.diags([-np.ones(n - 1), main, -np.ones(n - 1)],
                 [-1, 0, 1]).tocsr()
    path = str(tmp_path / "health.jsonl")
    telemetry.set_default_sink(JsonlSink(path))
    try:
        solve = make_solver(L, DummyPreconditioner(L, dtype=jnp.float64),
                            CG(maxiter=50, tol=1e-8,
                               record_history=True))
        x, info = solve(np.ones(n))
    finally:
        telemetry.set_default_sink(None)
    assert info.health["breakdown"] == "breakdown_alpha"
    assert info.health["breakdown_iteration"] >= 0
    assert len(info.history) == info.iters
    assert np.all(np.isfinite(np.asarray(info.history)))
    recs = [json.loads(ln) for ln in open(path)]
    events = {r["event"] for r in recs}
    assert "health" in events and "solve" in events
    hrec = [r for r in recs if r["event"] == "health"][-1]
    assert hrec["breakdown"] == "breakdown_alpha"
    # the solve record carries the same decode
    srec = [r for r in recs if r["event"] == "solve"][-1]
    assert srec["health"]["ok"] is False


# -- per-level convergence probes -------------------------------------------

def test_probe_convergence_poisson():
    """Measured per-level cycle factors on Poisson SA: healthy factors
    well below 1 on every level, smoother spectral radius in (0, 1),
    and the probe rows fold into hierarchy_stats()."""
    A, _ = poisson3d(12)
    amg = AMG(A, AMGParams(dtype=jnp.float64, coarse_enough=200))
    probe = amg.probe_convergence()
    assert len(probe) == len(amg.hierarchy.levels)
    for row in probe[:-1]:
        assert 0 < row["conv_factor"] < 0.9, row
        assert 0 < row["smoother_rho"] < 1, row
    # coarsest level is direct-solved: factor at the eps level
    assert probe[-1]["conv_factor"] < 1e-6
    # cached + folded into the structured stats
    assert amg.probe_convergence() is probe
    st = amg.hierarchy_stats()
    for i, lv in enumerate(st["levels"]):
        assert lv["conv_factor"] == pytest.approx(
            probe[i]["conv_factor"], rel=1e-12, abs=1e-30)
    json.dumps(st)

    # the level-0 factor bounds the cycle's error reduction: a
    # Richardson iteration preconditioned by one cycle must converge at
    # ~ that rate, so the probe is a genuine prediction, not a printout
    solve = make_solver(A, AMGParams(dtype=jnp.float64,
                                     coarse_enough=200),
                        Richardson(maxiter=100, tol=1e-10))
    x, info = solve(np.ones(A.nrows))
    assert info.convergence_rate < probe[0]["conv_factor"] + 0.1


def test_two_grid_factor_single_level():
    from amgcl_tpu.telemetry.health import two_grid_factor
    A, _ = poisson3d(10)
    amg = AMG(A, AMGParams(dtype=jnp.float64, coarse_enough=200))
    row = two_grid_factor(amg.hierarchy, level=0, n_iters=10)
    assert row["level"] == 0 and len(row["factors"]) == 10
    assert 0 < row["conv_factor"] < 0.9


# -- the doctor -------------------------------------------------------------

def test_diagnose_rules():
    from amgcl_tpu.telemetry import SolveReport
    # diverged health -> critical divergence finding, ranked first
    rep = SolveReport(20, 1e3, solver="CG",
                      health={"ok": False, "flags": ["divergence"],
                              "first_trip": {"divergence": 4},
                              "nan": False, "diverged": True,
                              "stagnated": False, "indefinite": False,
                              "breakdown": None})
    fins = diagnose(rep, tol=1e-8, maxiter=20)
    codes = [f["code"] for f in fins]
    assert codes[0] in ("divergence", "not_converged")
    assert "divergence" in codes and "not_converged" in codes
    assert all(f["severity"] == "critical" for f in fins[:2])
    # breakdown names the kind and the suggestion mentions an alternative
    rep = SolveReport(3, 1.0, solver="BiCGStab",
                      health={"ok": False, "flags": ["breakdown_omega"],
                              "first_trip": {"breakdown_omega": 3},
                              "nan": False, "diverged": False,
                              "stagnated": False, "indefinite": False,
                              "breakdown": "breakdown_omega",
                              "breakdown_iteration": 3})
    fins = diagnose(rep)
    assert fins[0]["code"] == "breakdown_omega"
    assert "iteration 3" in fins[0]["message"]
    assert "bicgstabl" in fins[0]["suggestion"]
    # probe: a bad level names the level and suggests npre/npost
    rep = SolveReport(80, 1e-7, solver="CG")
    fins = diagnose(rep, probe=[{"level": 0, "conv_factor": 0.5},
                                {"level": 2, "conv_factor": 0.94}])
    bad = [f for f in fins if f["code"] == "level_conv_factor"]
    assert len(bad) == 1 and "level 2" in bad[0]["message"]
    assert "npre" in bad[0]["suggestion"]
    # healthy report -> single info finding; text renderer runs
    rep = SolveReport(10, 1e-9, solver="CG",
                      health={"ok": True, "flags": []})
    fins = diagnose(rep, tol=1e-8)
    assert [f["code"] for f in fins] == ["healthy"]
    text = format_findings(fins)
    assert "Convergence doctor" in text and "[INFO]" in text


def test_cli_doctor_and_trace(tmp_path):
    """cli.py --doctor prints the per-level probe factors + ranked
    findings, and --trace writes Perfetto-loadable trace-event JSON
    (ISSUE 3 acceptance / satellite)."""
    trace = tmp_path / "trace.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "amgcl_tpu.cli", "-n", "16",
         "-p", "solver.type=cg", "-p", "precond.coarse_enough=200",
         "--doctor", "--trace", str(trace)],
        capture_output=True, text=True, timeout=600, cwd=_REPO, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "Per-level convergence probe" in r.stdout
    assert "Convergence doctor" in r.stdout
    # the factors the doctor prints ARE probe_convergence()'s numbers:
    # re-run the probe in-process and compare within 10%
    A, _ = poisson3d(16)
    amg = AMG(A, AMGParams(dtype=jnp.float64, coarse_enough=200))
    probe = amg.probe_convergence()
    printed = []
    seen = False
    for line in r.stdout.splitlines():
        if line.startswith("Per-level convergence probe"):
            seen = True
        parts = line.split()
        if seen and parts and parts[0].isdigit():
            printed.append(float(parts[2]))
    assert len(printed) == len(probe)
    for got, row in zip(printed, probe):
        assert got == pytest.approx(row["conv_factor"],
                                    rel=0.1, abs=1e-3)
    # the trace opens as Chrome trace-event JSON
    t = json.load(open(trace))
    evs = [e for e in t["traceEvents"] if e.get("ph") == "X"]
    names = {e["name"] for e in evs}
    assert {"setup", "solve", "probe"} <= names
    assert all(e["dur"] >= 0 and e["ts"] >= 0 for e in evs)
    # the AMG setup profile rides along as its own track, on the SAME
    # timeline (shared epoch): its events land inside the CLI's 'setup'
    # span, where the build actually ran
    tids = {e["tid"] for e in evs}
    assert len(tids) >= 2
    cli_setup = [e for e in evs if e["tid"] == 0
                 and e["name"] == "setup"][0]
    setup_track = [e for e in evs if e["tid"] != 0]
    assert setup_track
    slop = 1e4    # 10 ms of scope-boundary overhead
    for e in setup_track:
        assert e["ts"] >= cli_setup["ts"] - slop
        assert e["ts"] + e["dur"] <= cli_setup["ts"] + cli_setup["dur"] \
            + slop


def test_profiler_chrome_trace_export():
    """Profiler.to_chrome_trace(): complete events with microsecond
    ts/dur, nesting contained in the parent span, JSON-serializable."""
    import time as _time
    from amgcl_tpu.utils.profiler import Profiler
    p = Profiler()
    with p.scope("outer"):
        with p.scope("inner"):
            _time.sleep(0.002)
        with p.scope("inner"):
            _time.sleep(0.001)
    t = p.to_chrome_trace(tid=3, tid_name="test")
    json.dumps(t)
    meta = [e for e in t["traceEvents"] if e["ph"] == "M"]
    assert meta and meta[0]["args"]["name"] == "test"
    evs = [e for e in t["traceEvents"] if e["ph"] == "X"]
    assert len(evs) == 3
    assert all(e["tid"] == 3 for e in evs)
    inner = [e for e in evs if e["name"] == "inner"]
    outer = [e for e in evs if e["name"] == "outer"][0]
    assert len(inner) == 2
    for e in inner:
        assert e["args"]["path"] == "outer/inner"
        assert e["ts"] >= outer["ts"] - 1e-6
        assert e["ts"] + e["dur"] <= outer["ts"] + outer["dur"] + 1e-6


# -- bench gate -------------------------------------------------------------

def test_gate_health_check(monkeypatch):
    """bench.py --gate: a previously-clean record that now trips any
    guard is a regression; pre-health records are skipped, and
    AMGCL_TPU_GATE_HEALTH=0 opts out (ISSUE 3 satellite)."""
    sys.path.insert(0, _REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    lg = {"iters": 10, "value": 1.0,
          "health": {"ok": True, "flags": []}}
    bad = {"iters": 10, "value": 1.0,
           "health": {"ok": False, "flags": ["divergence"]}}
    ok, checks = bench.run_gate(bad, lg)
    row = [c for c in checks if c["check"] == "health_flags"][0]
    assert not ok and row["status"] == "regression"
    assert row["new_flags"] == ["divergence"]
    ok, _ = bench.run_gate(lg, lg)
    assert ok
    # flag IDENTITIES, not counts: swapping a warning-level stagnation
    # for a fatal breakdown is a regression even at equal counts
    stag = {"iters": 10, "value": 1.0,
            "health": {"ok": False, "flags": ["stagnation"]}}
    nan = {"iters": 10, "value": 1.0,
           "health": {"ok": False, "flags": ["nan"]}}
    ok, checks = bench.run_gate(nan, stag)
    row = [c for c in checks if c["check"] == "health_flags"][0]
    assert not ok and row["new_flags"] == ["nan"]
    # a baseline that already trips the same flag tolerates it
    ok, _ = bench.run_gate(stag, stag)
    assert ok
    # records predating health telemetry: skipped, not failed
    ok, checks = bench.run_gate({"iters": 10, "value": 1.0},
                                {"iters": 10, "value": 1.0})
    row = [c for c in checks if c["check"] == "health_flags"][0]
    assert ok and row["status"] == "skipped"
    # opt-out
    monkeypatch.setenv("AMGCL_TPU_GATE_HEALTH", "0")
    ok, checks = bench.run_gate(bad, lg)
    assert ok and not any(c["check"] == "health_flags" for c in checks)


def test_dist_cg_health_report():
    """Distributed CG carries the same guard decode in its report
    (replicated across shards — the dots are psum'd)."""
    from amgcl_tpu.parallel.mesh import make_mesh
    from amgcl_tpu.parallel.dist_matrix import DistDiaMatrix
    from amgcl_tpu.parallel.dist_solver import dist_cg
    mesh = make_mesh(4)
    A, rhs = poisson3d(8)
    M = DistDiaMatrix.from_csr(A, mesh, jnp.float64)
    out = dist_cg(M, mesh, jnp.asarray(rhs), maxiter=50, tol=1e-8)
    assert out.report.health is not None
    assert out.report.health["ok"] and out.report.health["flags"] == []
