"""Comm/compute overlap structure of the sharded SpMVs (round-2 review
item 8; reference: amgcl/mpi/distributed_matrix.hpp:520-534).

XLA overlaps a collective with compute only when some compute does NOT
consume the collective's result. These tests assert that property on the
compiled HLO: the bulk (interior/local) product must not transitively
depend on the halo exchange."""

import re

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from amgcl_tpu.parallel.compat import shard_map
from jax.sharding import PartitionSpec as P

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.parallel.mesh import make_mesh, ROWS_AXIS
from amgcl_tpu.parallel.dist_matrix import DistDiaMatrix, dia_halo_mv
from amgcl_tpu.utils.sample_problem import poisson3d


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(8)


def _hlo_collective_independent_flops(txt, collective_ops):
    """Parse optimized HLO; return (n_heavy_total, n_heavy_independent):
    heavy instructions (fusion/dot/reduce/multiply) and how many of them
    do NOT transitively depend on any collective."""
    deps = {}
    kinds = {}
    order = []
    for m in re.finditer(
            r"^\s*(?:ROOT\s+)?%?([\w\.\-]+)\s*=\s*[\w\[\],{}\s]*?"
            r"([\w\-]+)\((.*)$", txt, re.M):
        name, op, rest = m.group(1), m.group(2), m.group(3)
        operands = re.findall(r"%([\w\.\-]+)", rest)
        deps[name] = operands
        kinds[name] = op
        order.append(name)
    tainted = set()
    for name in order:
        k = kinds[name]
        if any(c in k for c in collective_ops) \
                or any(d in tainted for d in deps[name]):
            tainted.add(name)
    heavy = [n for n in order
             if kinds[n] in ("fusion", "dot", "reduce", "multiply")]
    indep = [n for n in heavy if n not in tainted]
    return len(heavy), len(indep)


def test_dia_halo_mv_interior_independent_of_ppermute(mesh8):
    A, _ = poisson3d(16)
    M = DistDiaMatrix.from_csr(A, mesh8, jnp.float32)

    fn = shard_map(
        lambda d, x: dia_halo_mv(d, M.offsets, x),
        mesh=mesh8, in_specs=(P(None, ROWS_AXIS), P(ROWS_AXIS)),
        out_specs=P(ROWS_AXIS), check_vma=False)
    x = jnp.ones(A.nrows, jnp.float32)
    txt = jax.jit(fn).lower(M.data, x).compile().as_text()
    assert "collective-permute" in txt
    heavy, indep = _hlo_collective_independent_flops(
        txt, ("collective-permute",))
    assert heavy > 0
    # the interior product (the bulk of the FLOPs) must be schedulable
    # concurrently with the exchange
    assert indep > 0, "every compute op consumes the collective: no overlap"


def test_dist_ell_local_product_independent_of_all_to_all(mesh8):
    from amgcl_tpu.parallel.dist_ell import build_dist_ell
    A, _ = poisson3d(16)
    dA = build_dist_ell(A, mesh8, jnp.float32)

    def body(lc, lv, rc, rv, si, x):
        from amgcl_tpu.parallel.dist_ell import DistEllMatrix
        m = DistEllMatrix(lc, lv, rc, rv, si, dA.shape, dA.nloc, dA.ncloc)
        return m.shard_mv(x)

    sp = P(ROWS_AXIS, None, None)
    fn = shard_map(body, mesh=mesh8,
                   in_specs=(sp, sp, sp, sp, sp, P(ROWS_AXIS)),
                   out_specs=P(ROWS_AXIS), check_vma=False)
    x = jnp.ones(dA.shape[1], jnp.float32)
    txt = jax.jit(fn).lower(dA.loc_cols, dA.loc_vals, dA.rem_cols,
                            dA.rem_vals, dA.send_idx, x).compile().as_text()
    assert "all-to-all" in txt
    heavy, indep = _hlo_collective_independent_flops(txt, ("all-to-all",))
    assert indep > 0, "local ELL product consumes the collective"


def test_overlapped_dia_mv_matches_reference_product(mesh8):
    """Numerics: the interior/edge split must be exact."""
    A, _ = poisson3d(16)
    M = DistDiaMatrix.from_csr(A, mesh8, jnp.float64)
    x = np.random.RandomState(0).rand(A.nrows)

    fn = shard_map(
        lambda d, v: dia_halo_mv(d, M.offsets, v),
        mesh=mesh8, in_specs=(P(None, ROWS_AXIS), P(ROWS_AXIS)),
        out_specs=P(ROWS_AXIS), check_vma=False)
    y = np.asarray(jax.jit(fn)(M.data, jnp.asarray(x)))
    np.testing.assert_allclose(y, A.spmv(x), rtol=1e-12)


def test_dia_halo_mv_reach_beyond_neighbour(mesh8):
    """w > nl: a diagonal reaching past the immediate neighbour slab must
    fall back to the gather path, not silently clamp (round-3 advice)."""
    rng = np.random.default_rng(0)
    nd, nl = 8, 4
    n = nd * nl
    offs = (0, 6)            # reach 6 > nl=4: crosses TWO shards
    data = rng.standard_normal((len(offs), n)).astype(np.float32)
    x = rng.standard_normal(n).astype(np.float32)
    # dense reference with zero-filled shift semantics
    want = np.zeros(n, np.float32)
    for k, s in enumerate(offs):
        src = np.zeros(n, np.float32)
        if s >= 0:
            src[: n - s] = x[s:]
        else:
            src[-s:] = x[: n + s]
        want += data[k] * src

    fn = shard_map(
        lambda d, v: dia_halo_mv(d, offs, v),
        mesh=mesh8, in_specs=(P(None, ROWS_AXIS), P(ROWS_AXIS)),
        out_specs=P(ROWS_AXIS), check_vma=False)
    got = jax.jit(fn)(jnp.asarray(data), jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)
