"""Device-resident stencil setup (ops/stencil_device.py): parity with the
host build, hybrid continuation, rebuild, smoother variants."""

import numpy as np
import jax.numpy as jnp
import pytest

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.models.make_solver import make_solver
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.relaxation.jacobi import DampedJacobi
from amgcl_tpu.utils.sample_problem import poisson3d
from amgcl_tpu.ops import stencil_device as sdev


@pytest.fixture
def force_device_setup(monkeypatch):
    monkeypatch.setenv("AMGCL_TPU_DEVICE_SETUP", "1")


def _hierarchies(n=20, prm_kw=None):
    import os
    A, rhs = poisson3d(n)
    kw = dict(dtype=jnp.float32)
    kw.update(prm_kw or {})
    dev = AMG(A, AMGParams(**kw))
    os.environ["AMGCL_TPU_DEVICE_SETUP"] = "0"
    try:
        host = AMG(A, AMGParams(**kw))
    finally:
        os.environ["AMGCL_TPU_DEVICE_SETUP"] = "1"
    return A, rhs, dev, host


def test_device_build_matches_host(force_device_setup):
    A, rhs, dev, host = _hierarchies(20)
    assert dev._device_built
    # consumers (pyamgcl_compat) read host_levels[0][0] as the system CSR
    assert hasattr(dev.host_levels[0][0], "val")
    assert len(dev.hierarchy.levels) == len(host.hierarchy.levels)
    for i, (ld, lh) in enumerate(zip(dev.hierarchy.levels,
                                     host.hierarchy.levels)):
        assert ld.A.shape == lh.A.shape
        x = np.random.RandomState(i).rand(ld.A.shape[1]).astype(np.float32)
        yd = np.asarray(ld.A.mv(jnp.asarray(x)))
        yh = np.asarray(lh.A.mv(jnp.asarray(x)))
        scale = max(np.abs(yh).max(), 1e-30)
        np.testing.assert_allclose(yd / scale, yh / scale, atol=2e-5)


def test_device_solve_iteration_parity(force_device_setup):
    import os
    A, rhs = poisson3d(24)
    s_dev = make_solver(A, AMGParams(dtype=jnp.float32),
                        CG(maxiter=100, tol=1e-6))
    assert s_dev.precond._device_built
    x, info_d = s_dev(jnp.asarray(rhs, jnp.float32))
    os.environ["AMGCL_TPU_DEVICE_SETUP"] = "0"
    try:
        s_host = make_solver(A, AMGParams(dtype=jnp.float32),
                             CG(maxiter=100, tol=1e-6))
        x2, info_h = s_host(jnp.asarray(rhs, jnp.float32))
    finally:
        os.environ["AMGCL_TPU_DEVICE_SETUP"] = "1"
    assert not s_host.precond._device_built
    assert info_d.iters == info_h.iters
    r = rhs - A.spmv(np.asarray(x, np.float64))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-3


def test_hybrid_continuation_kicks_in(force_device_setup):
    # 40^3 coarsens 40->20->10->5: the level-2 operator has >34 candidate
    # diagonals, forcing the device prefix + host continuation path
    A, rhs, dev, host = _hierarchies(40, {"coarse_enough": 50})
    assert dev._device_built
    assert 0 < len(dev._dev_prefix) < len(dev.hierarchy.levels)
    assert [l[0].nrows for l in dev.host_levels] \
        == [l[0].nrows for l in host.host_levels]


def test_device_rebuild(force_device_setup):
    A, rhs = poisson3d(16)
    solve = make_solver(A, AMGParams(dtype=jnp.float32), CG(tol=1e-6))
    assert solve.precond._device_built
    x1, _ = solve(rhs.astype(np.float32))
    A2 = CSR(A.ptr.copy(), A.col.copy(), 2.0 * A.val, A.ncols)
    solve.rebuild(A2)
    x2, info = solve(rhs.astype(np.float32))
    r = rhs - A2.spmv(np.asarray(x2, np.float64))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-3
    np.testing.assert_allclose(np.asarray(x2), np.asarray(x1) / 2.0,
                               atol=1e-4)


def test_device_jacobi_smoother(force_device_setup):
    A, rhs = poisson3d(16)
    solve = make_solver(
        A, AMGParams(dtype=jnp.float32, relax=DampedJacobi()),
        CG(maxiter=200, tol=1e-6))
    assert solve.precond._device_built
    x, info = solve(rhs.astype(np.float32))
    r = rhs - A.spmv(np.asarray(x, np.float64))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-3


def test_device_no_direct_coarse(force_device_setup):
    A, rhs = poisson3d(16)
    solve = make_solver(
        A, AMGParams(dtype=jnp.float32, direct_coarse=False),
        CG(maxiter=300, tol=1e-5))
    assert solve.precond._device_built
    x, info = solve(rhs.astype(np.float32))
    r = rhs - A.spmv(np.asarray(x, np.float64))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-2


@pytest.mark.parametrize("aniso", [0.1, 1e-3])
def test_anisotropic_device_semicoarsening(force_device_setup, aniso):
    """Anisotropy stays ON the device path (VERDICT r3 item 8): the
    speculation check reruns the level with the measured strong axes
    (semicoarsening) instead of bailing to the host. Hierarchy shape and
    iteration count must match the host build."""
    A, rhs = poisson3d(16, anisotropy=aniso)
    dev = AMG(A, AMGParams(dtype=jnp.float32))
    assert dev._device_built                     # no host fallback
    import os
    os.environ["AMGCL_TPU_DEVICE_SETUP"] = "0"
    try:
        host = AMG(A, AMGParams(dtype=jnp.float32))
    finally:
        os.environ["AMGCL_TPU_DEVICE_SETUP"] = "1"
    # semicoarsened level sizes agree with the host build
    assert [m[0].nrows for m in dev.host_levels] == \
        [h[0].nrows for h in host.host_levels]
    solve = make_solver(A, AMGParams(dtype=jnp.float32),
                        CG(maxiter=100, tol=1e-6))
    x, info = solve(rhs.astype(np.float32))
    assert info.iters < 60
    r = rhs - A.spmv(np.asarray(x, np.float64))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-3


def test_f64_declines_device_path(force_device_setup):
    A, _ = poisson3d(12)
    amg = AMG(A, AMGParams(dtype=jnp.float64))
    assert not amg._device_built
