"""Windowed-ELL unstructured SpMV: packing, XLA path, Pallas interpret
path, and an end-to-end AMG solve on an FE-style irregular matrix
(reference capability: general-sparsity device SpMV,
amgcl/backend/cuda.hpp:60-843)."""

import numpy as np
import jax.numpy as jnp
import pytest

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.ops import device as dev
from amgcl_tpu.ops.unstructured import (
    WindowedEllMatrix, csr_to_windowed_ell, windowed_ell_spmv,
    windowed_ell_residual, windowed_ell_scaled_correction,
    windowed_ell_spmv_dots, fe_like_problem, _TILE, _WIN_ALIGN)
from amgcl_tpu.utils.adapters import cuthill_mckee, permute


def _small_fe(n=3000, seed=1):
    A, rhs = fe_like_problem(n=n, nnz_target=n * 18, seed=seed)
    return A, rhs


def test_windowed_ell_matches_host_spmv():
    A, _ = _small_fe()
    perm = cuthill_mckee(A)
    Ap = permute(A, perm)
    W = csr_to_windowed_ell(Ap, jnp.float64)
    assert W is not None
    x = np.random.RandomState(0).rand(A.nrows)
    y_ref = Ap.spmv(x)
    y = np.asarray(W._mv_xla(jnp.asarray(x)))
    np.testing.assert_allclose(y, y_ref, rtol=1e-12)


def test_windowed_ell_pallas_interpret_matches():
    A, _ = _small_fe(n=2500, seed=2)
    perm = cuthill_mckee(A)
    Ap = permute(A, perm)
    W = csr_to_windowed_ell(Ap, jnp.float32)
    x = np.random.RandomState(1).rand(A.nrows).astype(np.float32)
    y_ref = Ap.spmv(x.astype(np.float64))
    y = np.asarray(windowed_ell_spmv(
        W.window_starts, W.cols_local, W.vals, jnp.asarray(x),
        W.win, W.shape[0], interpret=True))
    # scale-aware atol: the 1/h² fixture weights span ~3 orders, so rows
    # with catastrophic cancellation bound the f32 error absolutely (by
    # ~max|y|·eps·√k), not relatively
    np.testing.assert_allclose(y, y_ref, rtol=2e-4,
                               atol=1e-4 * np.abs(y_ref).max())


def test_rcm_shrinks_windows():
    A, _ = _small_fe(n=4000, seed=3)
    W_raw = csr_to_windowed_ell(A, jnp.float32)
    perm = cuthill_mckee(A)
    W_rcm = csr_to_windowed_ell(permute(A, perm), jnp.float32)
    assert W_rcm is not None
    # RCM must genuinely shrink the per-tile column span on a kNN graph
    # (review r3: the pre-fix window computation made this vacuous)
    if W_raw is not None:
        assert W_rcm.win < W_raw.win
    assert W_rcm.win < 4000 // _TILE * _WIN_ALIGN + 2 * _WIN_ALIGN


def test_to_device_auto_picks_windowed_for_banded_irregular():
    A, _ = _small_fe(n=4096, seed=4)
    Ap = permute(A, cuthill_mckee(A))
    M = dev.to_device(Ap, "auto", jnp.float32, dense_cutoff=256)
    # irregular (not DIA-eligible at CPU thresholds) but banded -> windowed
    assert isinstance(M, WindowedEllMatrix)
    x = np.random.RandomState(2).rand(A.nrows)
    want = Ap.spmv(x)
    np.testing.assert_allclose(
        np.asarray(M.mv(jnp.asarray(x, dtype=jnp.float32))),
        want, rtol=2e-4, atol=1e-4 * np.abs(want).max())


def _windowed_fixture(n=2500, seed=7):
    A, _ = _small_fe(n=n, seed=seed)
    Ap = permute(A, cuthill_mckee(A))
    W = csr_to_windowed_ell(Ap, jnp.float32)
    rng = np.random.RandomState(seed)
    x = rng.rand(Ap.nrows).astype(np.float32)
    f = rng.rand(Ap.nrows).astype(np.float32)
    w = rng.rand(Ap.nrows).astype(np.float32)
    return Ap, W, x, f, w


def test_windowed_fused_residual_interpret_matches():
    Ap, W, x, f, _ = _windowed_fixture()
    r_ref = f - Ap.spmv(x.astype(np.float64))
    r = np.asarray(windowed_ell_residual(
        W.window_starts, W.cols_local, W.vals, jnp.asarray(f),
        jnp.asarray(x), W.win, W.shape[0], interpret=True))
    np.testing.assert_allclose(r, r_ref, rtol=5e-4, atol=5e-4)


def test_windowed_fused_correction_interpret_matches():
    Ap, W, x, f, w = _windowed_fixture(seed=8)
    ref = x + w * (f - Ap.spmv(x.astype(np.float64)))
    got = np.asarray(windowed_ell_scaled_correction(
        W.window_starts, W.cols_local, W.vals, jnp.asarray(w),
        jnp.asarray(f), jnp.asarray(x), W.win, W.shape[0],
        interpret=True))
    np.testing.assert_allclose(got, ref, rtol=5e-4, atol=5e-4)


def test_windowed_fused_spmv_dots_interpret_matches():
    Ap, W, x, _, w = _windowed_fixture(seed=9)
    y_ref = Ap.spmv(x.astype(np.float64))
    y, yy, yx, yw = windowed_ell_spmv_dots(
        W.window_starts, W.cols_local, W.vals, jnp.asarray(x),
        jnp.asarray(w), win=W.win, n_out=W.shape[0], interpret=True)
    np.testing.assert_allclose(np.asarray(y), y_ref, rtol=5e-4, atol=5e-4)
    np.testing.assert_allclose(float(yy), y_ref @ y_ref, rtol=1e-3)
    np.testing.assert_allclose(float(yx), y_ref @ x, rtol=1e-3)
    np.testing.assert_allclose(float(yw), y_ref @ w, rtol=1e-3)
    # w=None leg returns yw=None and the same pairs
    y2, yy2, yx2, yw2 = windowed_ell_spmv_dots(
        W.window_starts, W.cols_local, W.vals, jnp.asarray(x),
        None, win=W.win, n_out=W.shape[0], interpret=True)
    assert yw2 is None
    np.testing.assert_allclose(float(yx2), float(yx), rtol=1e-6)


def test_windowed_fused_wiring_through_seams(monkeypatch):
    """The production seams (dev.residual / dev.spmv_dots / smoother
    apply_pre) must route WindowedEllMatrix through the fused kernels
    under the CI interpret hook — same wiring discipline as the DIA
    tiers (tests/test_sweep.py)."""
    monkeypatch.setenv("AMGCL_TPU_PALLAS_INTERPRET", "1")
    Ap, W, x, f, w = _windowed_fixture(seed=10)
    assert W._pallas_mode(jnp.asarray(x)) is True
    r = np.asarray(dev.residual(jnp.asarray(f), W, jnp.asarray(x)))
    np.testing.assert_allclose(
        r, f - Ap.spmv(x.astype(np.float64)), rtol=5e-4, atol=5e-4)
    y, yy, yx, yw = dev.spmv_dots(W, jnp.asarray(x), jnp.asarray(w))
    y_ref = Ap.spmv(x.astype(np.float64))
    np.testing.assert_allclose(float(yx), y_ref @ x, rtol=1e-3)
    from amgcl_tpu.relaxation.base import ScaledResidualSmoother
    sm = ScaledResidualSmoother(jnp.asarray(w))
    got = np.asarray(sm.apply_pre(W, jnp.asarray(f), jnp.asarray(x)))
    ref = x + w * (f - Ap.spmv(x.astype(np.float64)))
    np.testing.assert_allclose(got, ref, rtol=5e-4, atol=5e-4)


def _block_fixture(n_pt=1500, b=3, seed=12):
    """Block-valued FE-style fixture: scalar kNN Laplacian re-blocked."""
    A, _ = _small_fe(n=n_pt * b, seed=seed)
    Ap = permute(A, cuthill_mckee(A))
    Ab = Ap.to_block(b)
    W = csr_to_windowed_ell(Ab, jnp.float32)
    assert W is not None and W.block == (b, b)
    rng = np.random.RandomState(seed)
    x = rng.rand(n_pt * b).astype(np.float32)
    f = rng.rand(n_pt * b).astype(np.float32)
    S = rng.rand(n_pt, b, b).astype(np.float32) * 0.1
    return Ab, W, x, f, S


def test_windowed_block_spmv_interpret_matches():
    from amgcl_tpu.ops.unstructured import windowed_ell_block_spmv
    Ab, W, x, _, _ = _block_fixture()
    y_ref = Ab.unblock().spmv(x.astype(np.float64))
    y = np.asarray(windowed_ell_block_spmv(
        W.window_starts, W.cols_local, W.vals, jnp.asarray(x),
        W.win, W.shape[0], interpret=True))
    np.testing.assert_allclose(y, y_ref, rtol=5e-4, atol=5e-4)
    # XLA fallback agrees too
    np.testing.assert_allclose(np.asarray(W._mv_xla(jnp.asarray(x))),
                               y_ref, rtol=5e-4, atol=5e-4)


def test_windowed_block_fused_interpret_matches():
    from amgcl_tpu.ops.unstructured import (
        windowed_ell_block_residual, windowed_ell_block_scaled_correction)
    Ab, W, x, f, S = _block_fixture(seed=13)
    ax = Ab.unblock().spmv(x.astype(np.float64))
    r_ref = f - ax
    r = np.asarray(windowed_ell_block_residual(
        W.window_starts, W.cols_local, W.vals, jnp.asarray(f),
        jnp.asarray(x), W.win, W.shape[0], interpret=True))
    np.testing.assert_allclose(r, r_ref, rtol=5e-4, atol=5e-4)
    b = W.block[0]
    corr_ref = x + np.einsum(
        "nij,nj->ni", S, r_ref.reshape(-1, b)).reshape(-1)
    got = np.asarray(windowed_ell_block_scaled_correction(
        W.window_starts, W.cols_local, W.vals, jnp.asarray(S),
        jnp.asarray(f), jnp.asarray(x), W.win, W.shape[0],
        interpret=True))
    np.testing.assert_allclose(got, corr_ref, rtol=5e-4, atol=5e-4)


def test_windowed_block_spmv_dots_interpret_matches(monkeypatch):
    import amgcl_tpu.ops.unstructured as unstruct
    Ab, W, x, _, _ = _block_fixture(seed=16)
    rng = np.random.RandomState(16)
    w = rng.rand(x.shape[0]).astype(np.float32)
    y_ref = Ab.unblock().spmv(x.astype(np.float64))
    y, yy, yx, yw = unstruct.windowed_ell_block_spmv_dots(
        W.window_starts, W.cols_local, W.vals, jnp.asarray(x),
        jnp.asarray(w), win=W.win, n_out=W.shape[0], interpret=True)
    np.testing.assert_allclose(np.asarray(y), y_ref, rtol=5e-4, atol=5e-4)
    np.testing.assert_allclose(float(yy), y_ref @ y_ref, rtol=1e-3)
    np.testing.assert_allclose(float(yx), y_ref @ x, rtol=1e-3)
    np.testing.assert_allclose(float(yw), y_ref @ w, rtol=1e-3)
    # the seam must actually REACH the block kernel under the interpret
    # hook (numeric equality alone also holds on the mv fallback)
    monkeypatch.setenv("AMGCL_TPU_PALLAS_INTERPRET", "1")
    calls = []
    real = unstruct.windowed_ell_block_spmv_dots
    monkeypatch.setattr(
        unstruct, "windowed_ell_block_spmv_dots",
        lambda *a, **k: calls.append(1) or real(*a, **k))
    y2, yy2, yx2, yw2 = dev.spmv_dots(W, jnp.asarray(x), jnp.asarray(w))
    assert calls, "seam did not dispatch the block dots kernel"
    np.testing.assert_allclose(float(yx2), float(yx), rtol=1e-5)


def test_windowed_block_wiring_through_seams(monkeypatch):
    monkeypatch.setenv("AMGCL_TPU_PALLAS_INTERPRET", "1")
    Ab, W, x, f, S = _block_fixture(seed=14)
    assert W._pallas_mode(jnp.asarray(x)) is True
    r = np.asarray(dev.residual(jnp.asarray(f), W, jnp.asarray(x)))
    ax = Ab.unblock().spmv(x.astype(np.float64))
    np.testing.assert_allclose(r, f - ax, rtol=5e-4, atol=5e-4)
    from amgcl_tpu.relaxation.base import ScaledResidualSmoother
    sm = ScaledResidualSmoother(jnp.asarray(S), block=W.block[0])
    got = np.asarray(sm.apply_pre(W, jnp.asarray(f), jnp.asarray(x)))
    b = W.block[0]
    ref = x + np.einsum("nij,nj->ni", S,
                        (f - ax).reshape(-1, b)).reshape(-1)
    np.testing.assert_allclose(got, ref, rtol=5e-4, atol=5e-4)


def test_block_solver_windowed_end_to_end(monkeypatch):
    """make_block_solver on an RCM-banded problem: the block windowed-ELL
    device format carries the whole solve under the interpret hook."""
    monkeypatch.setenv("AMGCL_TPU_PALLAS_INTERPRET", "1")
    from amgcl_tpu.models.make_solver import make_solver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.bicgstab import BiCGStab
    b = 2
    A, rhs = _small_fe(n=2000 * b, seed=15)
    Ap = permute(A, cuthill_mckee(A))
    rhs_p = rhs[cuthill_mckee(A)]
    Ab = Ap.to_block(b)
    M = dev.to_device(Ab, "auto", jnp.float32)
    assert isinstance(M, WindowedEllMatrix) and M.block == (b, b)
    solve = make_solver(Ab, AMGParams(dtype=jnp.float64),
                        BiCGStab(tol=1e-8))
    x, info = solve(rhs_p)
    r = rhs_p - Ap.spmv(np.asarray(x, np.float64))
    assert np.linalg.norm(r) / np.linalg.norm(rhs_p) < 1e-6


def test_windowed_bf16_values_interpret():
    """bfloat16 operator values through the windowed kernels (the HBM-
    halving hierarchy option): packing, SpMV, and fused residual stay
    within bf16 accuracy of the f64 reference."""
    Ap, _, x, f, _ = _windowed_fixture(seed=17)
    Wb = csr_to_windowed_ell(Ap, jnp.bfloat16)
    assert Wb is not None and Wb.dtype == jnp.bfloat16
    y_ref = Ap.spmv(x.astype(np.float64))
    y = np.asarray(windowed_ell_spmv(
        Wb.window_starts, Wb.cols_local, Wb.vals, jnp.asarray(x),
        Wb.win, Wb.shape[0], interpret=True), np.float64)
    denom = np.abs(y_ref).max()
    assert np.abs(y - y_ref).max() / denom < 3e-2      # bf16 epsilon
    r = np.asarray(windowed_ell_residual(
        Wb.window_starts, Wb.cols_local, Wb.vals, jnp.asarray(f),
        jnp.asarray(x), Wb.win, Wb.shape[0], interpret=True), np.float64)
    assert np.abs(r - (f - y_ref)).max() / denom < 3e-2


def test_transfers_take_windowed_format():
    """Hierarchy P/R go through auto format selection: on an RCM-banded
    problem with explicit transfers (Ruge-Stuben) they must pick the
    windowed-ELL device format, riding the same Pallas SpMV as the level
    operators."""
    from amgcl_tpu.models.amg import AMG, AMGParams
    from amgcl_tpu.coarsening.ruge_stuben import RugeStuben
    A, _ = _small_fe(n=6000, seed=18)
    Ap = permute(A, cuthill_mckee(A))
    amg = AMG(Ap, AMGParams(coarsening=RugeStuben()))
    lv0 = amg.hierarchy.levels[0]
    assert isinstance(lv0.P, WindowedEllMatrix), type(lv0.P).__name__
    assert isinstance(lv0.R, WindowedEllMatrix), type(lv0.R).__name__


def test_amg_solve_fe_like():
    from amgcl_tpu.models.make_solver import make_solver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.cg import CG
    A, rhs = _small_fe(n=5000, seed=5)
    Ap = permute(A, cuthill_mckee(A))
    rhs_p = rhs[cuthill_mckee(A)]
    solve = make_solver(Ap, AMGParams(dtype=jnp.float64), CG(tol=1e-8))
    x, info = solve(rhs_p)
    r = rhs_p - Ap.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs_p) < 1e-6
