"""Windowed-ELL unstructured SpMV: packing, XLA path, Pallas interpret
path, and an end-to-end AMG solve on an FE-style irregular matrix
(reference capability: general-sparsity device SpMV,
amgcl/backend/cuda.hpp:60-843)."""

import numpy as np
import jax.numpy as jnp
import pytest

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.ops import device as dev
from amgcl_tpu.ops.unstructured import (
    WindowedEllMatrix, csr_to_windowed_ell, windowed_ell_spmv,
    fe_like_problem, _TILE, _WIN_ALIGN)
from amgcl_tpu.utils.adapters import cuthill_mckee, permute


def _small_fe(n=3000, seed=1):
    A, rhs = fe_like_problem(n=n, nnz_target=n * 18, seed=seed)
    return A, rhs


def test_windowed_ell_matches_host_spmv():
    A, _ = _small_fe()
    perm = cuthill_mckee(A)
    Ap = permute(A, perm)
    W = csr_to_windowed_ell(Ap, jnp.float64)
    assert W is not None
    x = np.random.RandomState(0).rand(A.nrows)
    y_ref = Ap.spmv(x)
    y = np.asarray(W._mv_xla(jnp.asarray(x)))
    np.testing.assert_allclose(y, y_ref, rtol=1e-12)


def test_windowed_ell_pallas_interpret_matches():
    A, _ = _small_fe(n=2500, seed=2)
    perm = cuthill_mckee(A)
    Ap = permute(A, perm)
    W = csr_to_windowed_ell(Ap, jnp.float32)
    x = np.random.RandomState(1).rand(A.nrows).astype(np.float32)
    y_ref = Ap.spmv(x.astype(np.float64))
    y = np.asarray(windowed_ell_spmv(
        W.window_starts, W.cols_local, W.vals, jnp.asarray(x),
        W.win, W.shape[0], interpret=True))
    np.testing.assert_allclose(y, y_ref, rtol=2e-4)


def test_rcm_shrinks_windows():
    A, _ = _small_fe(n=4000, seed=3)
    W_raw = csr_to_windowed_ell(A, jnp.float32)
    perm = cuthill_mckee(A)
    W_rcm = csr_to_windowed_ell(permute(A, perm), jnp.float32)
    assert W_rcm is not None
    # RCM must genuinely shrink the per-tile column span on a kNN graph
    # (review r3: the pre-fix window computation made this vacuous)
    if W_raw is not None:
        assert W_rcm.win < W_raw.win
    assert W_rcm.win < 4000 // _TILE * _WIN_ALIGN + 2 * _WIN_ALIGN


def test_to_device_auto_picks_windowed_for_banded_irregular():
    A, _ = _small_fe(n=4096, seed=4)
    Ap = permute(A, cuthill_mckee(A))
    M = dev.to_device(Ap, "auto", jnp.float32, dense_cutoff=256)
    # irregular (not DIA-eligible at CPU thresholds) but banded -> windowed
    assert isinstance(M, WindowedEllMatrix)
    x = np.random.RandomState(2).rand(A.nrows)
    np.testing.assert_allclose(
        np.asarray(M.mv(jnp.asarray(x, dtype=jnp.float32))),
        Ap.spmv(x), rtol=2e-4)


def test_amg_solve_fe_like():
    from amgcl_tpu.models.make_solver import make_solver
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.solver.cg import CG
    A, rhs = _small_fe(n=5000, seed=5)
    Ap = permute(A, cuthill_mckee(A))
    rhs_p = rhs[cuthill_mckee(A)]
    solve = make_solver(Ap, AMGParams(dtype=jnp.float64), CG(tol=1e-8))
    x, info = solve(rhs_p)
    r = rhs_p - Ap.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs_p) < 1e-6
