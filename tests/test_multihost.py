"""REAL multi-process distributed execution: two controller processes,
Gloo CPU collectives, one global 4-device mesh — the jax.distributed
rendition of the reference's MPI scale-out (SURVEY.md §5.8). The worker
script builds a DistAMGSolver over the global mesh and solves the Poisson
fixture; the test asserts convergence AND iteration parity with a
single-process mesh of the same size (multi-controller must not change
the math)."""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = r"""
import os, sys
pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
sys.path.insert(0, @REPO@)
from amgcl_tpu.parallel import multihost
multihost.initialize("127.0.0.1:" + port, nproc, pid)
import jax
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
import numpy as np
from amgcl_tpu.utils.sample_problem import poisson3d
from amgcl_tpu.parallel.dist_amg import DistAMGSolver
from amgcl_tpu.models.amg import AMGParams
from amgcl_tpu.solver.cg import CG

assert jax.process_count() == nproc
mesh = multihost.global_mesh()
assert mesh.devices.size == 2 * nproc
A, rhs = poisson3d(12)
s = DistAMGSolver(A, mesh, AMGParams(dtype=jnp.float64, coarse_enough=300),
                  CG(maxiter=100, tol=1e-8))
x, info = s(rhs)
r = np.linalg.norm(rhs - A.spmv(x)) / np.linalg.norm(rhs)
assert r < 1e-7, r
print("RESULT %d iters=%d resid=%.3e" % (pid, info.iters, r), flush=True)
""".replace("@REPO@", repr(REPO))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_dist_amg():
    port = str(_free_port())
    env = {k: v for k, v in os.environ.items()
           if k not in ("PALLAS_AXON_POOL_IPS", "JAX_PLATFORMS",
                        "XLA_FLAGS")}
    procs = [subprocess.Popen(
        [sys.executable, "-c", _WORKER, str(pid), "2", port],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in range(2)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process run timed out")
        outs.append(out)
    for pid, out in enumerate(outs):
        assert procs[pid].returncode == 0, out[-2000:]
        assert "RESULT %d" % pid in out, out[-2000:]
    # iteration parity: both processes agree, and match a single-process
    # 4-device mesh of the same problem
    iters = sorted(int(o.split("iters=")[1].split()[0]) for o in outs)
    assert iters[0] == iters[1]

    probe = subprocess.run(
        [sys.executable, "-c", r"""
import os, sys
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, @REPO@)
import jax
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp, numpy as np
from amgcl_tpu.utils.sample_problem import poisson3d
from amgcl_tpu.parallel.mesh import make_mesh
from amgcl_tpu.parallel.dist_amg import DistAMGSolver
from amgcl_tpu.models.amg import AMGParams
from amgcl_tpu.solver.cg import CG
A, rhs = poisson3d(12)
s = DistAMGSolver(A, make_mesh(4), AMGParams(dtype=jnp.float64,
                                             coarse_enough=300),
                  CG(maxiter=100, tol=1e-8))
x, info = s(rhs)
print("ITERS", info.iters)
""".replace("@REPO@", repr(REPO))], capture_output=True, text=True, env=env,
        timeout=420)
    assert probe.returncode == 0, probe.stdout + probe.stderr
    single = int(probe.stdout.split("ITERS")[1].split()[0])
    assert iters[0] == single
