"""REAL multi-process distributed execution: two controller processes,
Gloo CPU collectives, one global mesh — the jax.distributed rendition of
the reference's MPI scale-out (SURVEY.md §5.8). Worker scripts build
distributed solvers over the global mesh and solve the Poisson fixture;
the tests assert convergence AND iteration parity with a single-process
mesh of the same size (multi-controller must not change the math).

Both tests are ``@pytest.mark.serial``: they spawn controller
subprocesses that bind ports and race the Gloo init timeout, which is
known to fail under concurrent host load. The launcher now retries a
timed-out or init-crashed attempt on a fresh port (up to 3 attempts),
so load flakes self-heal; a failure that survives every attempt is a
real signal (the README re-run-alone protocol remains the final
arbiter: ``pytest tests/test_multihost.py -m serial``)."""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# common per-worker bootstrap: env scrubbing, virtual devices, jax.distributed
_BOOT = r"""
import os, sys
pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=@NDEV@"
sys.path.insert(0, @REPO@)
from amgcl_tpu.parallel import multihost
multihost.initialize("127.0.0.1:" + port, nproc, pid)
import jax
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
import numpy as np
assert jax.process_count() == nproc
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _scrub_env():
    return {k: v for k, v in os.environ.items()
            if k not in ("PALLAS_AXON_POOL_IPS", "JAX_PLATFORMS",
                         "XLA_FLAGS")}


def _run_workers(body, nproc=2, devices_per_proc=2, timeout=420,
                 attempts=3):
    """Launch ``nproc`` workers running _BOOT + body; return their stdout
    and the parsed iters= values (body must print 'RESULT <pid> iters=N').

    Load-tolerant by construction (the README re-run-alone protocol,
    internalized): the Gloo init handshake and the port bind race the
    host load, so a timed-out or crashed attempt is retried up to
    ``attempts`` times on a FRESH port before the test fails — a real
    regression fails every attempt, a loaded host passes a later one."""
    src = (_BOOT.replace("@REPO@", repr(REPO))
           .replace("@NDEV@", str(devices_per_proc)) + body)
    last = None
    for attempt in range(attempts):
        port = str(_free_port())
        procs = [subprocess.Popen(
            [sys.executable, "-c", src, str(pid), str(nproc), port],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=_scrub_env()) for pid in range(nproc)]
        outs = []
        timed_out = False
        for p in procs:
            try:
                out, _ = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                for q in procs:       # reap so nothing leaks across
                    try:              # attempts
                        q.communicate(timeout=10)
                    except Exception:          # noqa: BLE001
                        pass
                timed_out = True
                break
            outs.append(out)
        if timed_out:
            last = "attempt %d timed out after %ss" % (attempt + 1,
                                                       timeout)
            continue
        bad = [pid for pid in range(nproc)
               if procs[pid].returncode != 0
               or "RESULT %d" % pid not in outs[pid]]
        if bad:
            last = outs[bad[0]][-3000:]
            if "Multiprocess computations aren't implemented" in last:
                # capability failure, not a regression: this jax build's
                # CPU backend cannot execute cross-process collectives
                # at all — no retry (or code change) can make the test
                # meaningful here, so say so instead of failing
                pytest.skip("jax CPU backend lacks multiprocess "
                            "collective support in this environment")
            continue
        iters = sorted(int(o.split("iters=")[1].split()[0])
                       for o in outs)
        return outs, iters
    pytest.fail("multi-process run failed after %d attempt(s): %s"
                % (attempts, last))


def _single_process_iters(body, n_devices, timeout=420):
    """Run ``body`` on one process with an ``n_devices`` virtual mesh;
    body must print 'ITERS <n>'."""
    src = r"""
import os, sys
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=@NDEV@"
sys.path.insert(0, @REPO@)
import jax
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp, numpy as np
""".replace("@REPO@", repr(REPO)).replace("@NDEV@", str(n_devices)) + body
    try:
        probe = subprocess.run([sys.executable, "-c", src],
                               capture_output=True, text=True,
                               env=_scrub_env(), timeout=timeout)
    except subprocess.TimeoutExpired:
        # one load-tolerant retry with a doubled budget (compiles on a
        # saturated host legitimately take longer); a second timeout is
        # a real failure
        probe = subprocess.run([sys.executable, "-c", src],
                               capture_output=True, text=True,
                               env=_scrub_env(), timeout=2 * timeout)
    assert probe.returncode == 0, probe.stdout + probe.stderr
    return int(probe.stdout.split("ITERS")[1].split()[0])


@pytest.mark.serial
def test_two_process_dist_amg():
    outs, iters = _run_workers(r"""
from amgcl_tpu.utils.sample_problem import poisson3d
from amgcl_tpu.parallel.dist_amg import DistAMGSolver
from amgcl_tpu.models.amg import AMGParams
from amgcl_tpu.solver.cg import CG

mesh = multihost.global_mesh()
assert mesh.devices.size == 2 * nproc
A, rhs = poisson3d(12)
s = DistAMGSolver(A, mesh, AMGParams(dtype=jnp.float64, coarse_enough=300),
                  CG(maxiter=100, tol=1e-8))
x, info = s(rhs)
r = np.linalg.norm(rhs - A.spmv(x)) / np.linalg.norm(rhs)
assert r < 1e-7, r
print("RESULT %d iters=%d resid=%.3e" % (pid, info.iters, r), flush=True)
""", nproc=2, devices_per_proc=2)
    assert iters[0] == iters[1]

    single = _single_process_iters(r"""
from amgcl_tpu.utils.sample_problem import poisson3d
from amgcl_tpu.parallel.mesh import make_mesh
from amgcl_tpu.parallel.dist_amg import DistAMGSolver
from amgcl_tpu.models.amg import AMGParams
from amgcl_tpu.solver.cg import CG
A, rhs = poisson3d(12)
s = DistAMGSolver(A, make_mesh(4), AMGParams(dtype=jnp.float64,
                                             coarse_enough=300),
                  CG(maxiter=100, tol=1e-8))
x, info = s(rhs)
print("ITERS", info.iters)
""", n_devices=4)
    assert iters[0] == single


@pytest.mark.serial
def test_two_process_strip_ingestion():
    """VERDICT r3 item 3: each controller holds only its row strips; the
    hierarchy is built with real cross-process exchanges (strip-parallel
    setup, parallel/dist_setup.py) and matches the single-process strip
    build's iterations."""
    outs, iters = _run_workers(r"""
from amgcl_tpu.utils.sample_problem import poisson3d
from amgcl_tpu.parallel.dist_setup import (StripAMGSolver, MultihostComm,
                                           split_strips)
from amgcl_tpu.models.amg import AMGParams
from amgcl_tpu.solver.cg import CG

mesh = multihost.global_mesh()
nd = mesh.devices.size
assert nd == 4 * nproc
A, rhs = poisson3d(12)
# strip ingestion: this process keeps ONLY its own shards' row strips
# (the full A exists here only to generate the fixture; the solver never
# sees it and non-owned slots are None)
comm = MultihostComm(mesh)
full_strips, nloc = split_strips(A, nd)
mine = set(comm.my_shards)
strips = [full_strips[s] if s in mine else None for s in range(nd)]
del full_strips
s = StripAMGSolver(strips, mesh,
                   AMGParams(dtype=jnp.float64, coarse_enough=200),
                   CG(maxiter=100, tol=1e-8), n=A.nrows,
                   replicate_below=400, comm=comm)
x, info = s(rhs)
r = np.linalg.norm(rhs - A.spmv(np.asarray(x))) / np.linalg.norm(rhs)
assert r < 1e-7, r
print("RESULT %d iters=%d resid=%.3e sizes=%s" % (pid, info.iters, r,
                                                  s.sizes), flush=True)
""", nproc=2, devices_per_proc=4)
    assert iters[0] == iters[1]

    single = _single_process_iters(r"""
from amgcl_tpu.utils.sample_problem import poisson3d
from amgcl_tpu.parallel.mesh import make_mesh
from amgcl_tpu.parallel.dist_setup import StripAMGSolver
from amgcl_tpu.models.amg import AMGParams
from amgcl_tpu.solver.cg import CG
A, rhs = poisson3d(12)
s = StripAMGSolver(A, make_mesh(8),
                   AMGParams(dtype=jnp.float64, coarse_enough=200),
                   CG(maxiter=100, tol=1e-8), replicate_below=400)
x, info = s(rhs)
print("ITERS", info.iters)
""", n_devices=8)
    assert iters[0] == single
