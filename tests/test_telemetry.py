"""Telemetry layer: per-iteration history inside the device loop for every
Krylov solver, structured hierarchy stats, the JSONL sink, named-scope
device tracing of the V-cycle, and the profiler's exception safety."""

import json
import os
import subprocess
import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from amgcl_tpu.models.make_solver import make_solver, SolverInfo
from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.solver import (CG, BiCGStab, BiCGStabL, GMRES, FGMRES,
                              LGMRES, IDRs, Richardson, PreOnly)
from amgcl_tpu.telemetry import SolveReport, JsonlSink
from amgcl_tpu.utils.sample_problem import poisson3d

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.parametrize("solver", [
    CG(maxiter=100, tol=1e-8, record_history=True),
    BiCGStab(maxiter=100, tol=1e-8, record_history=True),
    BiCGStabL(L=2, maxiter=100, tol=1e-8, record_history=True),
    GMRES(maxiter=100, tol=1e-8, record_history=True),
    FGMRES(maxiter=100, tol=1e-8, record_history=True),
    LGMRES(maxiter=100, tol=1e-8, record_history=True),
    IDRs(s=2, maxiter=100, tol=1e-8, record_history=True),
    Richardson(maxiter=200, tol=1e-8, record_history=True),
    PreOnly(record_history=True),
], ids=lambda s: type(s).__name__)
def test_history_length_matches_iters(solver):
    """Every Krylov solver records one history entry per counted iteration
    (inside the lax.while_loop — no host syncs), ending at the returned
    residual."""
    A, rhs = poisson3d(12)
    solve = make_solver(A, AMGParams(dtype=jnp.float64, coarse_enough=200),
                        solver)
    x, info = solve(rhs)
    h = np.asarray(info.history)
    name = type(solver).__name__
    assert len(h) == info.iters, name
    assert not np.any(np.isnan(h)), name
    assert abs(h[-1] - info.resid) <= 1e-12 + 1e-6 * abs(info.resid), name


def test_lgmres_history_small_restart_large_k():
    """K >= M: a restart cycle runs mk + K > M steps — the history buffer
    must still hold one slot per counted iteration (regression: overshoot
    was sized M, clamping the final cycle's writes)."""
    A, rhs = poisson3d(12)
    solve = make_solver(A, AMGParams(dtype=jnp.float64, coarse_enough=200),
                        LGMRES(M=2, K=3, maxiter=39, tol=1e-30,
                               record_history=True))
    x, info = solve(rhs)
    assert len(info.history) == info.iters


def test_emit_never_raises(tmp_path):
    """A broken sink path must not discard a converged solve — module-level
    emit warns once and drops instead of raising."""
    from amgcl_tpu import telemetry
    from amgcl_tpu.telemetry import sink as sink_mod
    telemetry.set_default_sink(
        JsonlSink(str(tmp_path / "no-such-dir" / "out.jsonl")))
    old = sink_mod._emit_warned
    sink_mod._emit_warned = False
    try:
        with pytest.warns(UserWarning, match="telemetry sink emit failed"):
            rec = telemetry.emit(event="x", value=1)
        assert rec["value"] == 1          # record still returned
        telemetry.emit(event="y")         # second drop is silent
    finally:
        telemetry.set_default_sink(None)
        sink_mod._emit_warned = old


def test_explicit_nullsink_beats_env(tmp_path, monkeypatch):
    """An explicit set_default_sink(NullSink()) opt-out must stick even
    when AMGCL_TPU_TELEMETRY is exported — only env-derived NullSinks are
    re-resolved against the env var."""
    from amgcl_tpu import telemetry
    from amgcl_tpu.telemetry import NullSink
    path = tmp_path / "env.jsonl"
    monkeypatch.setenv("AMGCL_TPU_TELEMETRY", str(path))
    try:
        telemetry.set_default_sink(NullSink())
        telemetry.emit(event="silenced")
        assert not path.exists()                 # opt-out honored
        telemetry.set_default_sink(None)         # back to env-driven
        telemetry.emit(event="audible")
        assert path.exists()
    finally:
        telemetry.set_default_sink(None)


def test_cg_history_monotone_ish():
    """AMG-preconditioned CG on Poisson: broadly decreasing residuals (no
    order-of-magnitude regressions between consecutive iterations)."""
    A, rhs = poisson3d(12)
    solve = make_solver(A, AMGParams(dtype=jnp.float64, coarse_enough=200),
                        CG(maxiter=100, tol=1e-10, record_history=True))
    x, info = solve(rhs)
    vals = np.asarray(info.history)
    assert len(vals) >= 3
    assert np.all(np.diff(np.log10(vals)) < 1)


def test_solve_report_fields_and_compat():
    A, rhs = poisson3d(10)
    solve = make_solver(A, AMGParams(dtype=jnp.float64, coarse_enough=200),
                        CG(maxiter=100, tol=1e-8, record_history=True))
    x, info = solve(rhs)
    # report is the SolverInfo (historical alias) and unpacks like pyamgcl
    assert isinstance(info, SolveReport) and SolverInfo is SolveReport
    it, err = info
    assert (it, err) == (info.iters, info.resid)
    assert info.solver == "CG"
    assert info.wall_time_s is not None and info.wall_time_s > 0
    assert 0 < info.convergence_rate < 1
    assert info.hierarchy is not None and info.hierarchy["n_levels"] >= 2
    # the whole report serializes to JSON
    rec = json.loads(info.to_json())
    assert rec["iters"] == info.iters
    assert len(rec["history"]) == info.iters


def test_hierarchy_stats_match_repr():
    A, _ = poisson3d(12)
    amg = AMG(A, AMGParams(dtype=jnp.float64, coarse_enough=200))
    st = amg.hierarchy_stats()
    text = repr(amg)
    assert ("Number of levels:    %d" % st["n_levels"]) in text
    assert ("Operator complexity: %.2f" % st["operator_complexity"]) in text
    assert ("Grid complexity:     %.2f" % st["grid_complexity"]) in text
    for lv in st["levels"]:
        assert ("%5d %12d %14d" % (lv["level"], lv["rows"], lv["nnz"])) \
            in text
    # complexity identities against the host levels
    nnz = [l["nnz"] for l in st["levels"]]
    assert st["operator_complexity"] == pytest.approx(sum(nnz) / nnz[0])
    json.dumps(st)     # structured path must be JSON-clean


def test_vcycle_named_phases_in_trace():
    """A lowered V-cycle carries the five named phases as jax.named_scope
    paths (what a jax.profiler trace groups device time by)."""
    A, rhs = poisson3d(12)
    amg = AMG(A, AMGParams(dtype=jnp.float64, coarse_enough=200))
    low = jax.jit(lambda h, r: h.apply(r)).lower(
        amg.hierarchy, jnp.asarray(rhs))
    asm = low.compiler_ir(dialect="stablehlo").operation.get_asm(
        enable_debug_info=True)
    for name in ("pre_smooth", "restrict", "coarse_solve", "prolong",
                 "post_smooth"):
        assert "amgcl/level" in asm and name in asm, name


def test_jsonl_sink_roundtrip(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    sink = JsonlSink(path)
    sink.emit({"event": "a", "value": 1.5})
    sink.emit(event="b", nested={"x": [1, 2, 3]},
              npval=np.float32(2.5), nparr=np.arange(3))
    lines = open(path).read().splitlines()
    assert len(lines) == 2
    recs = [json.loads(ln) for ln in lines]       # every line valid JSON
    assert recs[0]["event"] == "a" and "ts" in recs[0] \
        and "ts_iso" in recs[0]
    assert recs[1]["npval"] == 2.5 and recs[1]["nparr"] == [0, 1, 2]
    # breakdown records stay STRICT JSON: non-finite floats become their
    # string names instead of bare NaN/Infinity tokens
    sink.emit(event="breakdown", resid=float("nan"),
              history=[1.0, float("inf")])
    last = open(path).read().splitlines()[-1]
    assert "NaN" not in last and "Infinity" not in last
    rec = json.loads(last, parse_constant=lambda c: pytest.fail(c))
    assert rec["resid"] == "nan" and rec["history"] == [1.0, "inf"]


def test_default_sink_captures_solve_events(tmp_path):
    from amgcl_tpu import telemetry
    path = str(tmp_path / "solves.jsonl")
    telemetry.set_default_sink(JsonlSink(path))
    try:
        A, rhs = poisson3d(10)
        solve = make_solver(A, AMGParams(dtype=jnp.float64,
                                         coarse_enough=200),
                            CG(maxiter=100, tol=1e-8))
        solve(rhs)
        solve(rhs)
    finally:
        telemetry.set_default_sink(None)
    recs = [json.loads(ln) for ln in open(path)]
    assert len(recs) == 2
    assert all(r["event"] == "solve" and r["iters"] > 0 for r in recs)


def test_profiler_survives_exception_in_scope():
    """An exception inside a scope (even with an unbalanced inner tic) must
    not corrupt subsequent tic/toc pairing (ISSUE 1 satellite)."""
    from amgcl_tpu.utils.profiler import Profiler
    p = Profiler()
    with pytest.raises(ValueError):
        with p.scope("outer"):
            p.tic("inner")                 # never toc'd: the exception
            raise ValueError("boom")       # escapes before the toc
    assert p._stack == [p.root]            # stack fully restored
    with p.scope("after"):
        pass                               # pairing still works
    d = p.to_dict()
    assert "outer" in d["scopes"] and "after" in d["scopes"]
    assert "inner" in d["scopes"]["outer"]["children"]
    # a toc with no matching open scope is still a hard error
    p.tic("a")
    with pytest.raises(RuntimeError):
        p.toc("b")
    p.toc("a")
    # strict pairing on the CLEAN path too: a forgotten inner toc is
    # surfaced, not silently absorbed by the scope's exit
    p2 = Profiler()
    with pytest.raises(RuntimeError):
        with p2.scope("outer"):
            p2.tic("inner")


def test_profiler_device_mode_and_dict():
    from amgcl_tpu.utils.profiler import Profiler
    p = Profiler.device()                  # sync-aware scopes
    with p.scope("compute"):
        jnp.ones(16).sum()
    d = p.to_dict()
    assert d["scopes"]["compute"]["count"] == 1
    assert d["scopes"]["compute"]["total_s"] >= 0
    json.dumps(d)


def test_dist_cg_report(tmp_path):
    """Distributed CG: mesh-reduced iters/residual land in a SolveReport
    and the record goes through the process-global sink."""
    from amgcl_tpu import telemetry
    from amgcl_tpu.parallel.mesh import make_mesh
    from amgcl_tpu.parallel.dist_matrix import DistDiaMatrix
    from amgcl_tpu.parallel.dist_solver import dist_cg
    path = str(tmp_path / "dist.jsonl")
    telemetry.set_default_sink(JsonlSink(path))
    try:
        mesh = make_mesh(4)
        A, rhs = poisson3d(8)
        M = DistDiaMatrix.from_csr(A, mesh, jnp.float64)
        out = dist_cg(M, mesh, jnp.asarray(rhs), maxiter=50, tol=1e-8)
        x, it, res = out
    finally:
        telemetry.set_default_sink(None)
    assert out.report.iters == it and out.report.resid == res
    assert out.report.extra["devices"] == 4
    recs = [json.loads(ln) for ln in open(path)]
    assert recs and recs[-1]["event"] == "dist_solve" \
        and recs[-1]["solver"] == "dist_cg"


def test_pyamgcl_compat_report_shape():
    import amgcl_tpu.pyamgcl_compat as pyamgcl
    A, rhs = poisson3d(10)
    P = pyamgcl.amgcl(A, {"dtype": "float64", "coarse_enough": "200"})
    solve = pyamgcl.solver(P, {"type": "cg", "tol": 1e-8})
    x = solve(rhs)
    assert solve.iterations > 0 and solve.error < 1e-8
    # the pyamgcl-style (x, (iters, error)) shape via the report
    it, err = solve.last_report
    assert (it, err) == (solve.iterations, solve.error)


@pytest.mark.serial
@pytest.mark.parametrize("mesh", [0, 4], ids=["serial", "mesh4"])
def test_cli_telemetry_smoke(tmp_path, mesh):
    """`python -m amgcl_tpu.cli --telemetry out.jsonl` end to end on CPU
    with 8 virtual devices (ISSUE 1 satellite)."""
    out = tmp_path / "cli.jsonl"
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=8")
               .strip())
    cmd = [sys.executable, "-m", "amgcl_tpu.cli", "-n", "10",
           "-p", "solver.type=cg", "-p", "solver.record_history=true",
           "--telemetry", str(out)]
    if mesh:
        cmd += ["--mesh", str(mesh)]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=600, cwd=_REPO, env=env)
    except subprocess.TimeoutExpired:
        # load-tolerant retry (the README re-run-alone protocol,
        # internalized): CLI compile time on a saturated host can
        # exceed the budget without anything being wrong — one retry
        # with a doubled budget; a second timeout is a real failure
        out.unlink(missing_ok=True)
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=1200, cwd=_REPO, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "Iterations:" in r.stdout and "Profile:" in r.stdout
    recs = [json.loads(ln) for ln in open(out)]
    events = {r_["event"] for r_ in recs}
    assert {"cli", "profile"} <= events, events
    assert "solve" in events or "dist_solve" in events, events
    solve_rec = [r_ for r_ in recs
                 if r_["event"] in ("solve", "dist_solve")][-1]
    assert solve_rec["iters"] > 0 and solve_rec["resid"] < 1e-6


def test_bench_check_emits_dots():
    """bench.py --check runs the tier-1 pytest line (here narrowed to one
    fast file) and emits a JSONL record carrying DOTS_PASSED."""
    # the chaos-matrix recovery gate is exercised by tests/test_faults,
    # the storm smoke by tests/test_storm and the memwatch leak cycle
    # by tests/test_memwatch (all run in the real --check); skipping
    # them here keeps this smoke inside its load-tolerant timeout
    # envelope
    env = dict(os.environ, AMGCL_TPU_CHECK_TIMEOUT="480",
               AMGCL_TPU_GATE_RECOVERY="0",
               AMGCL_TPU_STORM_IN_CHECK="0",
               AMGCL_TPU_MEMWATCH_IN_CHECK="0")
    r = subprocess.run(
        [sys.executable, "bench.py", "--check",
         "tests/test_telemetry.py::test_jsonl_sink_roundtrip",
         "tests/test_telemetry.py::test_profiler_survives_exception_in_scope"],
        capture_output=True, text=True, timeout=540, cwd=_REPO, env=env)
    line = [ln for ln in r.stdout.splitlines() if ln.startswith("{")][-1]
    rec = json.loads(line)
    assert rec["event"] == "tier1_check"
    assert rec["metric"] == "tier1_dots_passed"
    assert rec["value"] == 2, rec
    assert rec["rc"] == 0 and r.returncode == 0
    # ISSUE 6: --check embeds the static-analysis gate as an `analysis`
    # record (new lint findings or audit contract errors fail the check)
    an = rec["analysis"]
    assert an["ok"] is True, an
    assert an["lint_new"] == 0 and an["audit_errors"] == 0
    assert an["audit_records"] > 0
    assert "bare-jit" in an["rules"]


def test_bench_count_dots():
    """The DOTS_PASSED parser matches the ROADMAP grep contract."""
    sys.path.insert(0, _REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    text = "collected 5 items\n....F      [100%]\nsome log line\n..\n"
    assert bench.count_dots(text) == 6
    assert bench.count_dots("no dots here\n") == 0
