"""Test configuration: run on a virtual 8-device CPU mesh with x64 enabled.

Mirrors the survey's test-strategy note (SURVEY.md §4): distributed behavior
is validated on `xla_force_host_platform_device_count=8` virtual devices so
multi-chip code paths are exercised in CI without TPU pod hardware. Real-TPU
benchmarking lives in bench.py, not in the test suite.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

from amgcl_tpu.utils.axon_guard import force_cpu_backend

force_cpu_backend()

jax.config.update("jax_enable_x64", True)
