"""Test configuration: run on a virtual 8-device CPU mesh with x64 enabled.

Mirrors the survey's test-strategy note (SURVEY.md §4): distributed behavior
is validated on `xla_force_host_platform_device_count=8` virtual devices so
multi-chip code paths are exercised in CI without TPU pod hardware. Real-TPU
benchmarking lives in bench.py, not in the test suite.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

# The axon TPU plugin (sitecustomize in this image) force-registers itself
# and hooks backend lookup; when its tunnel is wedged, ANY backend init
# hangs forever — even with JAX_PLATFORMS=cpu. Tests must never touch the
# TPU, so drop the factory before the first backend init.
try:
    from jax._src import xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
except Exception:
    pass
# the plugin also overrides the jax_platforms config at registration time
# (which beats the env var) — force it back
jax.config.update("jax_platforms", "cpu")

jax.config.update("jax_enable_x64", True)
