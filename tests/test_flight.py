"""Flight recorder, deterministic solve replay, and cross-run
regression attribution (ISSUE 12): capsule ring + incident dumps,
replay parity on a health-trip bundle, the crash excepthook, the
stdlib diff engine (exact wall split, stage join, platform skip),
gate-failure attribution with measured pairs, the --trend why column,
and the serial CLI --replay smoke."""

import json
import os
import sys
import subprocess

import numpy as np
import jax.numpy as jnp
import pytest

from amgcl_tpu.models.make_solver import make_solver
from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.models.preconditioner import DummyPreconditioner
from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.telemetry import JsonlSink, set_default_sink
from amgcl_tpu.telemetry import diff as diffmod
from amgcl_tpu.telemetry import flight
from amgcl_tpu.telemetry.health import diagnose
from amgcl_tpu.telemetry.report import SolveReport
from amgcl_tpu.utils.sample_problem import poisson3d

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bench():
    sys.path.insert(0, _REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    return bench


def _singular_system(n=12):
    """Singular 1-D Neumann Laplacian as a host CSR + the null-space
    rhs — every Krylov method breaks down on it (test_health's
    fixture, kept on the host so the flight dump carries the CSR)."""
    import scipy.sparse as sp
    main = 2.0 * np.ones(n)
    main[0] = main[-1] = 1.0
    L = sp.diags([-np.ones(n - 1), main, -np.ones(n - 1)],
                 [-1, 0, 1]).tocsr()
    return CSR.from_scipy(L), np.ones(n)


@pytest.fixture(autouse=True)
def _fresh_recorder():
    flight._reset_for_tests()
    yield
    flight._reset_for_tests()


# -- capsules, dumps, knobs --------------------------------------------------

def test_dump_disabled_without_dir(monkeypatch, tmp_path):
    """AMGCL_TPU_FLIGHT_DIR unset = nothing on disk AND nothing ringed
    (every ring consumer writes into that directory, so ringing
    without it would only pin buffers); AMGCL_TPU_FLIGHT=0 kills the
    recorder outright."""
    monkeypatch.delenv("AMGCL_TPU_FLIGHT_DIR", raising=False)
    A, rhs = _singular_system()
    s = make_solver(A, DummyPreconditioner(A, dtype=jnp.float64),
                    CG(maxiter=30, tol=1e-8))
    s(rhs)
    assert flight.last_capsule() is None           # no dir, no ring
    assert flight.dumps_total() == 0               # nothing written
    monkeypatch.setenv("AMGCL_TPU_FLIGHT_DIR", str(tmp_path))
    s(rhs)
    assert flight.last_capsule() is not None       # dir set -> ringed
    monkeypatch.setenv("AMGCL_TPU_FLIGHT", "0")
    assert not flight.enabled()
    assert flight.dump("x", bundle=s, rhs=rhs) is None


def test_failed_dump_leaves_no_partial_bundle(monkeypatch, tmp_path):
    """A dump that fails mid-write removes its half-written directory —
    a partial bundle would both crash a later replay and permanently
    consume a MAX_DUMPS slot."""
    monkeypatch.setenv("AMGCL_TPU_FLIGHT_DIR", str(tmp_path))
    A, rhs = poisson3d(6)
    s = make_solver(A, AMGParams(dtype=jnp.float32, coarse_enough=100),
                    CG(maxiter=50, tol=1e-6))

    def boom(*a, **k):
        raise OSError("disk full")

    monkeypatch.setattr(flight.np, "savez_compressed", boom)
    assert flight.dump("t", bundle=s, rhs=rhs) is None
    assert flight._existing_bundles(str(tmp_path)) == []


def test_health_trip_dumps_bundle_and_event(monkeypatch, tmp_path):
    """A fatal guard trip during a make_solver solve dumps a
    self-contained bundle: manifest with fingerprint/config/env/
    provenance/report summaries + the npz system, and a flight_dump
    JSONL event rides the sink."""
    monkeypatch.setenv("AMGCL_TPU_FLIGHT_DIR", str(tmp_path / "fd"))
    sink_path = tmp_path / "t.jsonl"
    set_default_sink(JsonlSink(str(sink_path)))
    try:
        A, rhs = _singular_system()
        s = make_solver(A, DummyPreconditioner(A, dtype=jnp.float64),
                        CG(maxiter=30, tol=1e-8))
        _x, rep = s(rhs)
        assert rep.health is not None and not rep.health["ok"]
        assert flight.fatal_health(rep.health)
    finally:
        set_default_sink(None)
    bundles = flight._existing_bundles(str(tmp_path / "fd"))
    assert len(bundles) == 1 and "health_trip" in bundles[0]
    manifest, arrays = flight.load_bundle(
        os.path.join(str(tmp_path / "fd"), bundles[0]))
    assert manifest["schema"] == flight.BUNDLE_SCHEMA
    assert manifest["reason"] == "health_trip"
    assert manifest["config"]["replayable"] is True
    assert manifest["config"]["precond"]["class"] == "dummy"
    assert manifest["fingerprint"]
    assert manifest["rhs_hash"]
    assert manifest["hw_provenance"]["device_platform"] == "cpu"
    assert manifest["report"]["health"]["flags"]
    assert isinstance(manifest["env"], dict)
    assert arrays["rhs"].shape == (A.nrows,)
    assert arrays["val"].shape[0] == A.nnz
    events = [json.loads(line) for line in
              open(sink_path).read().splitlines()]
    fd = [e for e in events if e.get("event") == "flight_dump"]
    assert fd and fd[0]["reason"] == "health_trip" \
        and fd[0]["dumps_total"] == 1
    assert fd[0]["flags"]


def test_max_dumps_bound(monkeypatch, tmp_path):
    """The per-directory bundle count is bounded: at the bound new
    incidents write nothing (counted via the skipped event)."""
    monkeypatch.setenv("AMGCL_TPU_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("AMGCL_TPU_FLIGHT_MAX_DUMPS", "2")
    A, rhs = _singular_system()
    s = make_solver(A, DummyPreconditioner(A, dtype=jnp.float64),
                    CG(maxiter=30, tol=1e-8))
    paths = [flight.dump("t%d" % k, bundle=s, rhs=rhs)
             for k in range(4)]
    assert [p is not None for p in paths] == [True, True, False, False]
    assert len(flight._existing_bundles(str(tmp_path))) == 2


# -- replay parity -----------------------------------------------------------

def test_replay_parity_on_health_trip_bundle(monkeypatch, tmp_path):
    """The acceptance contract: a health-trip bundle replays with
    IDENTICAL iteration count and health-flag identity on the same
    platform, residual within tolerance (singular system through
    cg)."""
    monkeypatch.setenv("AMGCL_TPU_FLIGHT_DIR", str(tmp_path))
    A, rhs = _singular_system()
    s = make_solver(A, DummyPreconditioner(A, dtype=jnp.float64),
                    CG(maxiter=30, tol=1e-8))
    _x, rep = s(rhs)
    assert not rep.health["ok"]
    bundles = flight._existing_bundles(str(tmp_path))
    assert bundles
    path = os.path.join(str(tmp_path), bundles[0])
    result = flight.run_replay(path)
    assert result["ok"], result
    parity = result["parity"]
    assert not parity["platform_skip"]
    rows = {c["check"]: c for c in parity["checks"]}
    assert rows["iters"]["status"] == "ok" \
        and rows["iters"]["recorded"] == rep.iters
    assert rows["health_flags"]["status"] == "ok" \
        and rows["health_flags"]["replayed"] == sorted(
            rep.health["flags"])
    assert rows["resid"]["status"] == "ok"
    # the recorded-vs-replayed diff rides the result for the doctor
    assert result["diff"]["kind"] == "solve"


def test_replay_does_not_recursively_dump(monkeypatch, tmp_path):
    """Replaying a health-trip bundle re-trips the same fatal guard —
    the recorder must stay OFF during the replayed solve, or every
    replay burns one MAX_DUMPS slot until real incidents are silently
    skipped (the review-confirmed recursion)."""
    monkeypatch.setenv("AMGCL_TPU_FLIGHT_DIR", str(tmp_path))
    A, rhs = _singular_system()
    s = make_solver(A, DummyPreconditioner(A, dtype=jnp.float64),
                    CG(maxiter=30, tol=1e-8))
    s(rhs)
    assert len(flight._existing_bundles(str(tmp_path))) == 1
    path = os.path.join(str(tmp_path),
                        flight._existing_bundles(str(tmp_path))[0])
    result = flight.run_replay(path)
    assert result["ok"]
    assert len(flight._existing_bundles(str(tmp_path))) == 1
    # and the live kill switch is restored afterwards
    assert flight.enabled()


def test_replay_refuses_tampered_x0(monkeypatch, tmp_path):
    """The x0 hash is verified like the rhs hash — a modified initial
    guess must refuse, not misdiagnose as solver nondeterminism."""
    monkeypatch.setenv("AMGCL_TPU_FLIGHT_DIR", str(tmp_path))
    A, rhs = _singular_system()
    s = make_solver(A, DummyPreconditioner(A, dtype=jnp.float64),
                    CG(maxiter=30, tol=1e-8))
    s(rhs, np.full(A.nrows, 0.5))
    path = os.path.join(str(tmp_path),
                        flight._existing_bundles(str(tmp_path))[0])
    npz = os.path.join(path, "system.npz")
    with np.load(npz) as z:
        arrays = {k: z[k] for k in z.files}
    arrays["x0"] = arrays["x0"] + 1.0
    np.savez_compressed(npz, **arrays)
    result = flight.run_replay(path)
    assert result["ok"] is False and "x0" in result["error"]


def test_reportless_bundle_parity_is_not_vacuous_ok(monkeypatch,
                                                    tmp_path):
    """A bundle dumped with no report (the failed-batch incidents)
    compares nothing — the parity verdict must say NOT APPLICABLE
    instead of a vacuous green OK."""
    monkeypatch.setenv("AMGCL_TPU_FLIGHT_DIR", str(tmp_path))
    A, rhs = poisson3d(6)
    s = make_solver(A, AMGParams(dtype=jnp.float32, coarse_enough=100),
                    CG(maxiter=50, tol=1e-6))
    path = flight.dump("serve_batch_failed", bundle=s,
                       rhs=rhs.astype(np.float32),
                       tags={"request_ids": [1, 2]})
    result = flight.run_replay(path)
    assert result["parity"]["vacuous"] is True
    assert all(c["status"] == "skipped"
               for c in result["parity"]["checks"])
    assert "NOT APPLICABLE" in flight.format_replay(result)


def test_replay_refuses_tampered_rhs(monkeypatch, tmp_path):
    """The content hash is verified on load — a modified bundle does
    not silently replay a different solve."""
    monkeypatch.setenv("AMGCL_TPU_FLIGHT_DIR", str(tmp_path))
    A, rhs = _singular_system()
    s = make_solver(A, DummyPreconditioner(A, dtype=jnp.float64),
                    CG(maxiter=30, tol=1e-8))
    s(rhs)
    path = os.path.join(str(tmp_path),
                        flight._existing_bundles(str(tmp_path))[0])
    npz = os.path.join(path, "system.npz")
    with np.load(npz) as z:
        arrays = {k: z[k] for k in z.files}
    arrays["rhs"] = arrays["rhs"] * 2.0
    np.savez_compressed(npz, **arrays)
    result = flight.run_replay(path)
    assert result["ok"] is False and "hash" in result["error"]


def test_selftest_roundtrip(tmp_path):
    """flight.selftest (the bench.py --check determinism gate): dump →
    replay → parity on a small headline-config solve."""
    result = flight.selftest(n=6, workdir=str(tmp_path))
    assert result["ok"], result
    assert result["parity"]["checks"][0]["status"] == "ok"
    assert flight._existing_bundles(str(tmp_path))


def test_crash_excepthook_dumps_last_capsule(monkeypatch, tmp_path,
                                             capsys):
    """An unhandled exception dumps the newest capsule (reason crash,
    exception repr tagged) and still chains to the previous hook."""
    monkeypatch.setenv("AMGCL_TPU_FLIGHT_DIR", str(tmp_path))
    A, rhs = poisson3d(6)
    s = make_solver(A, AMGParams(dtype=jnp.float32, coarse_enough=100),
                    CG(maxiter=50, tol=1e-6))
    s(rhs.astype(np.float32))
    assert flight.last_capsule() is not None
    seen = []
    # earlier in-process CLI runs (test_dist_setup's smoke) leave the
    # chained hook installed — reset so THIS install wraps the collector
    flight.uninstall_excepthook()
    monkeypatch.setattr(sys, "excepthook",
                        lambda *a: seen.append(a))
    try:
        assert flight.install_excepthook()
        try:
            raise ValueError("boom for the recorder")
        except ValueError:
            sys.excepthook(*sys.exc_info())
    finally:
        flight.uninstall_excepthook()
    assert seen, "previous hook must still run"
    bundles = flight._existing_bundles(str(tmp_path))
    assert len(bundles) == 1 and "crash" in bundles[0]
    manifest, arrays = flight.load_bundle(
        os.path.join(str(tmp_path), bundles[0]))
    assert "boom for the recorder" in manifest["tags"]["exception"]
    assert manifest["config"]["replayable"] is True
    assert "rhs" in arrays


# -- report schema / provenance stamp ---------------------------------------

def test_report_schema_and_provenance_stamp():
    """SolveReport.to_dict() carries the schema version and the
    hw_provenance stamp (the diff platform gate's solve-level source —
    bench records already had provenance, solve events did not)."""
    rec = SolveReport(5, 1e-8).to_dict()
    assert rec["schema"] == 1
    assert rec["hw_provenance"]["device_platform"] == "cpu"
    assert diffmod.platform_of(rec) == "cpu"


# -- diff engine (stdlib) ----------------------------------------------------

def test_diff_exact_wall_split():
    """The two-term identity Δwall = Δiters·t_B + iters_A·Δt is exact:
    the contributions sum to the headline wall delta."""
    a = {"iters": 10, "resid": 1e-8, "wall_time_s": 1.0,
         "hw_provenance": {"device_platform": "cpu"}}
    b = {"iters": 14, "resid": 1e-8, "wall_time_s": 2.1,
         "hw_provenance": {"device_platform": "cpu"}}
    d = diffmod.diff(a, b)
    assert d["kind"] == "solve" and not d["platform"]["skip"]
    split = {c["key"]: c["delta_s"] for c in d["contributions"]}
    assert split["iterations"] + split["per_iteration"] == \
        pytest.approx(2.1 - 1.0, rel=1e-12)
    assert d["headline"]["wall_s"]["regressed"]
    # findings name the regression with its top contributor
    folds = diagnose(None, diff=d)
    assert any(f["code"] == "cross_run_regression" for f in folds)


def test_diff_platform_skip():
    """Cross-platform pairs skip every timed row (the
    _record_platform rule) — iters stay compared."""
    a = {"metric": "m", "value": 0.07, "iters": 25,
         "device_platform": "tpu"}
    b = {"metric": "m", "value": 2.1, "iters": 25,
         "device_platform": "cpu"}
    d = diffmod.diff(a, b)
    assert d["platform"]["skip"]
    assert "wall_s" not in d["headline"]
    assert d["headline"]["iters"]["delta"] == 0
    assert d["contributions"] == []
    assert diffmod.why(a, b) is None


def test_diff_kind_mismatch_and_gaps():
    d = diffmod.diff({"iters": 3, "resid": 1e-9},
                     {"metric": "m", "value": 1.0})
    assert "error" in d
    # no per-stage rows on either side -> a gap note, never an error
    d = diffmod.diff({"metric": "m", "value": 1.0, "iters": 5,
                      "device_platform": "cpu"},
                     {"metric": "m", "value": 2.0, "iters": 5,
                      "device_platform": "cpu"})
    assert any("per-stage" in g for g in d["gaps"])
    assert diffmod.format_diff(d)


def test_diff_multichip_records():
    """Multichip diffs join per-(solver, mode, devices) cells and call
    out the comm-fraction movement."""
    def rec(eff, cf, t8):
        return {"event": "multichip_scaling", "schema": 2,
                "device_platform": "cpu",
                "headline": {"devices": 8, "weak_efficiency": eff,
                             "comm_fraction": cf, "iters": 20},
                "solvers": {"dist_cg": {
                    "weak": {"cells": [
                        {"devices": 1, "t_iter_s": 1e-4},
                        {"devices": 8, "t_iter_s": t8}]},
                    "strong": {"cells": []}}}}
    d = diffmod.diff(rec(0.5, 0.2, 2e-4), rec(0.25, 0.4, 4e-4))
    assert d["kind"] == "multichip"
    assert d["headline"]["weak_efficiency"]["regressed"]
    assert d["headline"]["comm_fraction"]["regressed"]
    assert d["top"] == "comm_fraction"
    assert d["contributions"][0]["key"] == "dist_cg/weak/nd8"


# -- injected regression: the acceptance scenario ---------------------------

@pytest.mark.serial
def test_injected_regression_attributes_perturbed_stage(tmp_path,
                                                        monkeypatch):
    """Force one V-cycle stage slower — npre 1 -> 8 multiplies exactly
    the pre-smooth work — measure real per-stage roofline rows for
    both builds, and assert diff.py attributes the majority (>=50%)
    of the per-stage delta to pre_smooth; then drive the same pair
    through `bench.py --why` as gate-failure-style bench records and
    check the printed attribution names the stage. (serial: the stages
    are µs-scale timed measurements — concurrent host load swamps the
    injected delta with jitter, the documented re-run-alone
    protocol.)"""
    monkeypatch.setenv("AMGCL_TPU_ROOFLINE_REPS", "7")
    A, _rhs = poisson3d(12)

    def record(npre):
        amg = AMG(A, AMGParams(dtype=jnp.float64, coarse_enough=100,
                               npre=npre))
        roof = amg.roofline()
        stages = [{"level": r["level"], "stage": r["stage"],
                   "visits": r.get("visits", 1), "t_s": r["t_s"],
                   "model_bytes": r.get("model_bytes")}
                  for r in roof["stages"]]
        iters = 30
        wall = iters * sum(r["t_s"] * r.get("visits", 1)
                           for r in roof["stages"])
        return {"metric": "inj", "value": wall, "iters": iters,
                "device_platform": "cpu", "roofline_stages": stages}

    a, b = record(1), record(8)
    d = diffmod.diff(a, b)
    assert d["stages"], d["gaps"]
    by = d["by_stage"]
    assert "pre_smooth" in by
    assert by["pre_smooth"]["share"] >= 0.5, by
    assert d["top"] == "per_iteration:pre_smooth"
    # the same pair through the bench surface (stdlib supervisor path)
    pa, pb = tmp_path / "a.json", tmp_path / "b.json"
    pa.write_text(json.dumps(a))
    pb.write_text(json.dumps(b))
    r = subprocess.run([sys.executable,
                        os.path.join(_REPO, "bench.py"), "--why",
                        str(pa), str(pb)],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    assert "pre_smooth" in r.stdout
    assert "top contributor: per_iteration:pre_smooth" in r.stdout


# -- gate failure attribution + measured pairs ------------------------------

def test_gate_failure_carries_measured_pairs_and_attribution():
    """run_gate failures surface the measured candidate/baseline pair
    per failed check (gate_failures) and the attribution section
    (gate_attribution) — the post-hoc --why answer rides the failure
    record itself."""
    bench = _bench()
    lg = {"iters": 10, "value": 1.0, "device_platform": "cpu"}
    bad = {"iters": 16, "value": 2.0, "device_platform": "cpu"}
    ok, checks = bench.run_gate(bad, lg)
    assert not ok
    failed = bench.gate_failures(checks)
    assert {f["check"] for f in failed} == {"iters", "solve_time"}
    row = [f for f in failed if f["check"] == "solve_time"][0]
    assert row["candidate"] == 2.0 and row["baseline"] == 1.0 \
        and row["limit"] is not None
    attr = bench.gate_attribution(bad, lg)
    assert attr.get("error") is None
    assert attr["headline"]["wall_s"]["regressed"]
    assert attr["contributions"]


def test_trend_why_column():
    """--trend's why annotation: only rounds beyond the gate's time
    tolerance get a label; the label names the top attributed
    contributor (gap '-' rendered for None)."""
    bench = _bench()
    hist = [
        {"round": 1, "value": 1.0, "iters": 10,
         "device_platform": "cpu"},
        {"round": 2, "value": 1.02, "iters": 10,
         "device_platform": "cpu"},                 # within tolerance
        {"round": 3, "value": 2.0, "iters": 20,
         "device_platform": "cpu"},                 # regression
    ]
    rows = [{"round": r["round"], "solve_s": r["value"]} for r in hist]
    bench._annotate_trend_why(rows, hist)
    assert rows[0]["why"] is None and rows[1]["why"] is None
    assert rows[2]["why"] in ("iterations", "per_iteration")
    m = bench._load_metrics()
    table = m.format_trend(rows, [("solve_s", "value"),
                                  ("why", "why")])
    assert "why" in table.splitlines()[0]


# -- live counter declaration ------------------------------------------------

def test_flight_dumps_total_declared():
    """The live-metric name is declared in live.METRICS (the
    metric-name-literal lint enforces the call sites against the same
    table) and a registry accepts it."""
    from amgcl_tpu.telemetry.live import METRICS, LiveRegistry
    assert METRICS["flight_dumps_total"][0] == "counter"
    reg = LiveRegistry()
    reg.inc("flight_dumps_total")
    assert reg.get("flight_dumps_total") == 1


# -- serve trigger -----------------------------------------------------------

def test_serve_slo_trip_dumps_bundle(monkeypatch, tmp_path):
    """An SLO trip inside a SolverService dumps a replay bundle of the
    most recent dispatched request, tagged with the trip kinds + a
    request id, and bumps flight_dumps_total."""
    from amgcl_tpu.serve import SolverService
    monkeypatch.setenv("AMGCL_TPU_FLIGHT_DIR", str(tmp_path))
    A, rhs = poisson3d(6)
    s = make_solver(A, AMGParams(dtype=jnp.float32, coarse_enough=100),
                    CG(maxiter=50, tol=1e-6))
    x0 = np.full(A.nrows, 0.25, np.float32)
    with SolverService(s, batch=2, slo_p99_ms=1e-6) as svc:
        fut = svc.submit(rhs.astype(np.float32), x0=x0, block=True)
        fut.result(timeout=300)
        # any finished request breaches the absurd 1ns p99 target
        assert svc.stats()["slo_trips"] >= 1
        assert svc.live.get("flight_dumps_total") >= 1
    bundles = flight._existing_bundles(str(tmp_path))
    assert bundles and "serve_slo_trip" in bundles[0]
    manifest, arrays = flight.load_bundle(
        os.path.join(str(tmp_path), bundles[0]))
    assert manifest["tags"]["trips"] == ["p99"]
    assert manifest["tags"]["request_id"] is not None
    assert "rhs" in arrays and manifest["config"]["replayable"]
    # the probe carries the request's WARM START — a bundle replayed
    # from zeros would fail parity on a deterministic solve
    assert np.array_equal(arrays["x0"], x0)


# -- CLI surface -------------------------------------------------------------

@pytest.mark.serial
def test_cli_replay_smoke(monkeypatch, tmp_path, capsys):
    """cli --replay on a health-trip bundle: exit 0, parity table +
    attribution printed, doctor fold runs (serial: CLI smokes are
    load-sensitive on shared hosts)."""
    from amgcl_tpu import cli
    monkeypatch.setenv("AMGCL_TPU_FLIGHT_DIR", str(tmp_path))
    A, rhs = _singular_system()
    s = make_solver(A, DummyPreconditioner(A, dtype=jnp.float64),
                    CG(maxiter=30, tol=1e-8))
    s(rhs)
    path = os.path.join(str(tmp_path),
                        flight._existing_bundles(str(tmp_path))[0])
    try:
        rc = cli.main(["--replay", path, "--doctor"])
    finally:
        flight.uninstall_excepthook()
    out = capsys.readouterr().out
    assert rc == 0
    assert "parity: OK" in out
    assert "Cross-run attribution" in out
    assert "Convergence doctor" in out
