"""Stencil (host-DIA) setup algebra: equivalence with the generic CSR
setup path (ops/stencil.py vs coarsening/smoothed_aggregation.py's
SpGEMM route)."""

import numpy as np
import pytest

import jax.numpy as jnp

from amgcl_tpu.utils.sample_problem import poisson3d, convection_diffusion_2d
from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.ops.structured import detect_grid_csr
from amgcl_tpu.ops import stencil as st
from amgcl_tpu.coarsening.smoothed_aggregation import (
    SmoothedAggregation, _filtered)
from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.models.make_solver import make_solver
from amgcl_tpu.solver.cg import CG


def _host_dia(n=8, **kw):
    A, _ = poisson3d(n, **kw)
    grid = detect_grid_csr(A)
    assert grid is not None
    return A, st.host_dia_from_csr(A, grid)


def test_pack_roundtrip():
    A, Ad = _host_dia()
    d = abs(st.HostDia(list(Ad.offsets3), Ad.data, Ad.dims).to_csr()
            .to_scipy() - A.to_scipy())
    assert d.nnz == 0 or d.max() == 0.0


def test_transpose_matches_scipy():
    A, Ad = _host_dia()
    d = abs(Ad.transpose().to_csr().to_scipy() - A.to_scipy().T)
    assert d.nnz == 0 or d.max() == 0.0


def test_dia_matmul_matches_scipy():
    A, Ad = _host_dia()
    d = abs(st.dia_matmul(Ad, Ad).to_csr().to_scipy()
            - A.to_scipy() @ A.to_scipy())
    assert d.nnz == 0 or d.max() < 1e-12


def test_filtered_matches_csr_filter():
    A, Ad = _host_dia(n=8, anisotropy=1e-3)
    Af_c, Dinv_c = _filtered(A, 0.08)
    Af_d, Dinv_d = st.filtered_dia(Ad, 0.08)
    d = abs(Af_d.to_csr().to_scipy() - Af_c.to_scipy())
    assert d.nnz == 0 or d.max() < 1e-14
    np.testing.assert_allclose(Dinv_d, Dinv_c, rtol=1e-14)
    rho_d = st.gershgorin_scaled(Af_d, Dinv_d)
    from amgcl_tpu.ops.csr import spectral_radius
    assert abs(rho_d - spectral_radius(Af_c, 0, scale=True)) < 1e-12


@pytest.mark.parametrize("gen,kw", [
    (poisson3d, {}),                       # 8^3, grid-aligned 2x2x2
    (poisson3d, {"anisotropy": 1e-3}),     # semicoarsening blocks
    (convection_diffusion_2d, {}),         # 2-D, nonsymmetric
])
def test_coarse_operator_matches_csr_path(gen, kw):
    A, _ = gen(12, **kw)
    sa_csr = SmoothedAggregation(stencil_setup=False)
    P1, R1 = sa_csr.transfer_operators(A)
    Ac1 = sa_csr.coarse_operator(A, P1, R1)
    sa_st = SmoothedAggregation()
    P2, R2 = sa_st.transfer_operators(A)
    assert isinstance(P2, st.StencilTransfer)
    Ac2 = sa_st.coarse_operator(A, P2, R2)
    assert Ac1.nnz == Ac2.nnz
    d = abs(Ac1.to_scipy() - Ac2.to_scipy())
    scale = max(abs(Ac1.val).max(), 1)
    assert d.nnz == 0 or d.max() < 1e-11 * scale


def test_odd_dims_partial_blocks():
    A, _ = poisson3d(9)        # 9 = 2*4+1: ragged edge blocks in collapse
    sa_csr = SmoothedAggregation(stencil_setup=False)
    Ac1 = sa_csr.coarse_operator(A, *sa_csr.transfer_operators(A))
    sa_st = SmoothedAggregation()
    Ac2 = sa_st.coarse_operator(A, *sa_st.transfer_operators(A))
    d = abs(Ac1.to_scipy() - Ac2.to_scipy())
    assert d.nnz == 0 or d.max() < 1e-11


def test_numpy_fallback_matches_native(monkeypatch):
    A, _ = poisson3d(10)
    sa = SmoothedAggregation()
    Ac_native = sa.coarse_operator(A, *sa.transfer_operators(A))
    import amgcl_tpu.native as native
    monkeypatch.setattr(native, "native_dia_fnma_batch",
                        lambda *a, **k: False)
    A2, _ = poisson3d(10)
    sa2 = SmoothedAggregation()
    Ac_np = sa2.coarse_operator(A2, *sa2.transfer_operators(A2))
    d = abs(Ac_native.to_scipy() - Ac_np.to_scipy())
    assert d.nnz == 0 or d.max() < 1e-12


def test_solve_iteration_parity():
    A, rhs = poisson3d(16)
    iters = []
    for stencil in (False, True):
        prm = AMGParams(dtype=jnp.float64,
                        coarsening=SmoothedAggregation(
                            stencil_setup=stencil))
        solve = make_solver(A, prm, CG(maxiter=100, tol=1e-8))
        x, info = solve(np.asarray(rhs))
        tr = float(np.linalg.norm(rhs - A.spmv(np.asarray(x)))
                   / np.linalg.norm(rhs))
        assert tr < 1e-7
        iters.append(int(info.iters))
    assert iters[0] == iters[1]


def test_rebuild_reuses_stencil_transfers():
    A, rhs = poisson3d(16)
    amg = AMG(A, AMGParams(dtype=jnp.float64))
    assert isinstance(amg.host_levels[0][1], st.StencilTransfer)
    A2, _ = poisson3d(16)
    A2 = CSR(A2.ptr, A2.col, A2.val * 2.0, A2.ncols)
    amg.rebuild(A2)
    # rebuilt coarse operator reflects the new values (Galerkin is linear
    # in A for fixed P): Ac_new = 2 * Ac_old
    ref = AMG(poisson3d(16)[0], AMGParams(dtype=jnp.float64)) \
        .host_levels[1][0]
    d = abs(amg.host_levels[1][0].to_scipy() - 2.0 * ref.to_scipy())
    assert d.nnz == 0 or d.max() < 1e-11


def test_f32_setup_dtype_convergence():
    A, rhs = poisson3d(16)
    solve = make_solver(A, AMGParams(dtype=jnp.float32),
                        CG(maxiter=100, tol=1e-6), refine=2)
    # the f32 hierarchy was built with float32 stencil algebra
    lvl1 = solve.precond if hasattr(solve, "precond") else None
    x, info = solve(jnp.asarray(rhs, jnp.float32))
    tr = float(np.linalg.norm(rhs - A.spmv(np.asarray(x, np.float64)))
               / np.linalg.norm(rhs))
    assert tr < 1e-5


def test_wide_stencils_fall_back_to_csr_route():
    # a radius-2 1-D operator on a 3-D grid index space exceeds the
    # 13-diagonal gate only when offsets decompose; here just assert the
    # coarse (27-diagonal) second level takes the generic CSR route
    A, _ = poisson3d(16)
    sa = SmoothedAggregation()
    ctx = {}   # per-build state (eps_strong decay) lives in the context
    P, R = sa.transfer_operators(A, ctx)
    Ac = sa.coarse_operator(A, P, R, ctx)
    # level-1 operator is a 27-point stencil -> generic path (explicit CSR)
    P2, R2 = sa.transfer_operators(Ac, ctx)
    assert not isinstance(P2, st.StencilTransfer)
    assert hasattr(P2, "val")


def test_plain_aggregation_stencil_matches_explicit():
    from amgcl_tpu.coarsening.aggregation import Aggregation
    from amgcl_tpu.coarsening.tentative import tentative_prolongation
    from amgcl_tpu.coarsening.galerkin import scaled_galerkin
    from amgcl_tpu.ops.structured import grid_aggregates

    A, _ = poisson3d(12)
    ag = Aggregation()
    P, R = ag.transfer_operators(A)
    assert isinstance(P, st.StencilTransfer)
    Ac = ag.coarse_operator(A, P, R)
    grid = detect_grid_csr(A)
    agg, n_agg, _, _ = grid_aggregates(grid, P._implicit_spec["block"])
    Pe, _ = tentative_prolongation(A.nrows, agg, n_agg, None, 1)
    Ace = scaled_galerkin(A, Pe, Pe.transpose(), 1 / 1.5)
    d = abs(Ac.to_scipy() - Ace.to_scipy())
    assert d.nnz == 0 or d.max() < 1e-12


def test_plain_aggregation_stencil_converges():
    from amgcl_tpu.coarsening.aggregation import Aggregation
    A, rhs = poisson3d(16)
    solve = make_solver(A, AMGParams(dtype=jnp.float64,
                                     coarsening=Aggregation()),
                        CG(maxiter=200, tol=1e-8))
    x, info = solve(np.asarray(rhs))
    tr = float(np.linalg.norm(rhs - A.spmv(np.asarray(x)))
               / np.linalg.norm(rhs))
    assert tr < 1e-7
    # device transfers are the tentative-only implicit pair
    lv = solve.precond.hierarchy.levels[0]
    assert type(lv.P).__name__ == "TentativeP"
    assert type(lv.R).__name__ == "TentativeR"
