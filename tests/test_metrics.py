"""Fleet metric rollups (ISSUE 4): percentile math, dotted-path
extraction over heterogeneous records, the cross-round bench trend
against the checked-in BENCH_r01..r05.json history (missing-field
tolerance for pre-ledger rounds), Prometheus export, the JSONL sink
size-capped rotation satellite, and the bench.py --trend surface."""

import json
import os
import subprocess
import sys

import pytest

from amgcl_tpu.telemetry import metrics as m
from amgcl_tpu.telemetry.sink import JsonlSink

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# percentiles / rollups / extraction
# ---------------------------------------------------------------------------

def test_percentile_interpolates():
    vals = [1.0, 2.0, 3.0, 4.0]
    assert m.percentile(vals, 50) == 2.5
    assert m.percentile(vals, 0) == 1.0
    assert m.percentile(vals, 100) == 4.0
    assert m.percentile([7.0], 99) == 7.0
    assert m.percentile([], 50) is None
    assert m.percentile([float("nan"), 5.0], 50) == 5.0


def test_rollup_summary():
    r = m.rollup([3, 1, 2, None, float("inf"), "x"])
    assert r["count"] == 3 and r["min"] == 1 and r["max"] == 3
    assert r["p50"] == 2 and r["last"] == 2.0
    assert m.rollup(["a", None]) is None
    assert m.rollup([True, True]) is None    # bools are not metrics


def test_extract_dotted_paths():
    rec = {"a": {"b": {"c": 7}}, "x": 1}
    assert m.extract(rec, "a.b.c") == 7
    assert m.extract(rec, "a.b.missing") is None
    assert m.extract(rec, "x.y") is None
    assert m.extract({}, "a") is None


# ---------------------------------------------------------------------------
# bench history trend (the committed BENCH_r*.json rounds)
# ---------------------------------------------------------------------------

def test_bench_history_loads_all_rounds():
    hist = m.bench_history(_REPO)
    rounds = [h["round"] for h in hist]
    assert rounds == sorted(rounds)
    assert set(rounds) >= {1, 2, 3, 4, 5}


def test_trend_tolerates_pre_ledger_records():
    """r01/r02 never produced a value (tunnel down) and r03..r05 predate
    the ledger/compile/roofline fields — every round still renders, with
    gaps instead of errors."""
    rows = m.trend(m.bench_history(_REPO))
    by_round = {r["round"]: r for r in rows}
    assert by_round[1]["solve_s"] is None and "error" in by_round[1]
    for rnd in (3, 4, 5):
        assert by_round[rnd]["solve_s"] > 0
        assert by_round[rnd]["iters"] == 13       # monotone across rounds
        assert by_round[rnd]["ledger_bytes"] is None   # pre-ledger
        assert by_round[rnd]["compile_s"] is None      # pre-watch
    txt = m.format_trend(rows)
    assert "round" in txt and "-" in txt
    for rnd in (1, 2, 3, 4, 5):
        assert str(rnd) in txt


def test_trend_rollups_and_prometheus():
    rows = m.trend(m.bench_history(_REPO))
    roll = m.trend_rollups(rows)
    assert roll["solve_s"]["count"] >= 3
    assert roll["iters"]["p50"] == 13
    text = m.prometheus_text(roll)
    assert '# TYPE amgcl_tpu_solve_s summary' in text
    assert 'amgcl_tpu_solve_s{quantile="0.5"}' in text
    assert text.endswith("\n")
    # names sanitize to the prometheus charset
    bad = m.prometheus_text({"a.b/c": {"count": 1, "min": 0, "max": 1,
                                       "p50": 0.5, "p90": 1, "p99": 1,
                                       "mean": 0.5, "last": 1}})
    assert "amgcl_tpu_a_b_c" in bad


def test_rollup_events_groups_by_event():
    recs = [{"event": "solve", "iters": 10, "wall_time_s": 0.5},
            {"event": "solve", "iters": 20, "wall_time_s": 1.5},
            {"event": "doctor"},
            {"event": "solve", "iters": 30, "wall_time_s": 2.5,
             "resources": {"roofline": {"gbps": 7.0}}}]
    out = m.rollup_events(recs)
    assert out["solve.iters"]["count"] == 3
    assert out["solve.iters"]["p50"] == 20
    assert out["solve.solve_time_s"]["max"] == 2.5
    assert out["solve.achieved_gbps"]["count"] == 1


def test_iter_jsonl_merges_rotation_and_skips_torn(tmp_path):
    base = str(tmp_path / "out.jsonl")
    with open(base + ".1", "w") as f:
        f.write('{"i": 1}\n{"i": 2}\n')
    with open(base, "w") as f:
        f.write('{"i": 3}\n{"i": 4, "torn...\n')
    recs = m.iter_jsonl(base)
    assert [r["i"] for r in recs] == [1, 2, 3]
    assert m.iter_jsonl(str(tmp_path / "missing.jsonl")) == []


# ---------------------------------------------------------------------------
# sink rotation satellite (AMGCL_TPU_TELEMETRY_MAX_BYTES)
# ---------------------------------------------------------------------------

def test_sink_rotates_at_cap(tmp_path):
    path = str(tmp_path / "out.jsonl")
    sink = JsonlSink(path, max_bytes=300)
    for i in range(20):
        sink.emit(event="t", i=i)
    assert os.path.exists(path + ".1")
    # base file restarted below the cap + one record's slack
    assert os.path.getsize(path) < 300 + 200
    # no record was split across the rotation: both files parse line-wise
    seen = []
    for p in (path + ".1", path):
        with open(p) as f:
            for line in f:
                seen.append(json.loads(line)["i"])
    assert seen == sorted(seen)           # order preserved across files
    assert seen[-1] == 19


def test_sink_rotation_env_default(tmp_path, monkeypatch):
    monkeypatch.setenv("AMGCL_TPU_TELEMETRY_MAX_BYTES", "250")
    path = str(tmp_path / "env.jsonl")
    sink = JsonlSink(path)                # picks the env cap up
    assert sink.max_bytes == 250
    for i in range(20):
        sink.emit(event="t", i=i)
    assert os.path.exists(path + ".1")
    monkeypatch.setenv("AMGCL_TPU_TELEMETRY_MAX_BYTES", "nonsense")
    assert JsonlSink(str(tmp_path / "e2.jsonl")).max_bytes == 0


def test_sink_unbounded_without_cap(tmp_path):
    path = str(tmp_path / "u.jsonl")
    sink = JsonlSink(path)
    for i in range(10):
        sink.emit(event="t", i=i)
    assert not os.path.exists(path + ".1")


# ---------------------------------------------------------------------------
# bench.py --trend surface
# ---------------------------------------------------------------------------

def test_bench_trend_cli(tmp_path):
    prom = str(tmp_path / "prom.txt")
    r = subprocess.run(
        [sys.executable, "bench.py", "--trend", "--prom", prom],
        capture_output=True, text=True, timeout=120, cwd=_REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "round" in r.stdout
    last = [ln for ln in r.stdout.splitlines() if ln.startswith("{")][-1]
    rec = json.loads(last)
    assert rec["event"] == "bench_trend"
    assert len(rec["rows"]) >= 5
    assert rec["rollups"]["solve_s"]["count"] >= 3
    with open(prom) as f:
        assert "amgcl_tpu_solve_s" in f.read()


def test_bench_trend_summary_importable():
    """trend_summary (what --check attaches to the CI record) works when
    bench.py is loaded the supervisor way — no jax in sight."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_bench_t", os.path.join(_REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    summ = bench.trend_summary()
    assert summ["rollups"]["solve_s"]["count"] >= 3
    assert {r["round"] for r in summ["rows"]} >= {1, 2, 3, 4, 5}
