"""Convergence sweep across Krylov solvers — the reference's test matrix
(tests/test_solver.hpp:120-248): {solvers} × {preconditioner configs},
asserting the final relative residual (there: < 1e-4; here tighter since we
run f64 on CPU)."""

import numpy as np
import jax.numpy as jnp
import pytest

from amgcl_tpu.models.make_solver import make_solver
from amgcl_tpu.models.amg import AMGParams
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.solver.bicgstab import BiCGStab
from amgcl_tpu.solver.gmres import GMRES, FGMRES
from amgcl_tpu.solver.richardson import Richardson
from amgcl_tpu.solver.preonly import PreOnly
from amgcl_tpu.utils.sample_problem import poisson3d, convection_diffusion_2d


@pytest.mark.parametrize("solver", [
    CG(maxiter=100, tol=1e-8),
    BiCGStab(maxiter=100, tol=1e-8),
    GMRES(maxiter=100, tol=1e-8),
    FGMRES(maxiter=100, tol=1e-8),
    Richardson(maxiter=200, tol=1e-8),
])
def test_solvers_poisson_amg(solver):
    A, rhs = poisson3d(12)
    solve = make_solver(A, AMGParams(dtype=jnp.float64), solver)
    x, info = solve(rhs)
    assert info.resid < 1e-8, type(solver).__name__
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-6


@pytest.mark.parametrize("solver", [
    BiCGStab(maxiter=200, tol=1e-8),
    GMRES(maxiter=300, tol=1e-8),
    FGMRES(maxiter=300, tol=1e-8),
])
def test_nonsymmetric_convection_diffusion(solver):
    A, rhs = convection_diffusion_2d(24, eps=0.1)
    solve = make_solver(A, AMGParams(dtype=jnp.float64), solver)
    x, info = solve(rhs)
    assert info.resid < 1e-8, type(solver).__name__
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-5


def test_preonly_is_single_application():
    A, rhs = poisson3d(10)
    solve = make_solver(A, AMGParams(dtype=jnp.float64), PreOnly())
    x, info = solve(rhs)
    assert info.iters == 1
    # one AMG application on a single-level (direct) hierarchy is exact
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-10


def test_gmres_restart_cycles():
    """Force restarts: tiny M on a problem needing more than M steps."""
    A, rhs = convection_diffusion_2d(20, eps=0.05)
    solve = make_solver(A, AMGParams(dtype=jnp.float64),
                        GMRES(M=5, maxiter=400, tol=1e-8))
    x, info = solve(rhs)
    assert info.resid < 1e-8
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-5


def test_gmres_complex_system():
    """Complex-safe Givens rotations (regression: real-only rotation left a
    non-triangular R for complex systems)."""
    from amgcl_tpu.utils.sample_problem import poisson3d_complex
    from amgcl_tpu.ops import device as dev
    A, rhs = poisson3d_complex(8)
    Ad = dev.to_device(A, "ell", jnp.complex128)
    g = GMRES(maxiter=300, tol=1e-8, M=30)
    x, it, res = g.solve(Ad, lambda r: r, jnp.asarray(rhs))[:3]
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-7


def test_dist_cg_compile_cache():
    import jax
    from amgcl_tpu.parallel.mesh import make_mesh
    from amgcl_tpu.parallel.dist_matrix import DistDiaMatrix
    from amgcl_tpu.parallel.dist_solver import dist_cg, _compiled_dist_cg
    from amgcl_tpu.utils.sample_problem import poisson3d
    mesh = make_mesh(4)
    A, rhs = poisson3d(8)
    M = DistDiaMatrix.from_csr(A, mesh, jnp.float64)
    before = _compiled_dist_cg.cache_info().misses
    for _ in range(3):
        dist_cg(M, mesh, jnp.asarray(rhs), maxiter=5, tol=1e-12)
    after = _compiled_dist_cg.cache_info()
    assert after.misses == before + 1 and after.hits >= 2


def test_bicgstabl_right_side():
    """pside='right': true-residual tracking, converges to the same
    quality as left (reference default side, bicgstabl.hpp:137)."""
    from amgcl_tpu.solver.bicgstabl import BiCGStabL
    from amgcl_tpu.models.make_solver import make_solver
    from amgcl_tpu.models.amg import AMGParams
    A, rhs = poisson3d(10)
    s = make_solver(A, AMGParams(dtype=jnp.float64),
                    BiCGStabL(L=2, maxiter=200, tol=1e-8, pside="right"))
    x, info = s(rhs)
    r = np.linalg.norm(rhs - A.spmv(np.asarray(x))) / np.linalg.norm(rhs)
    assert r < 1e-7
    # warm start must also work in correction form
    x2, info2 = s(rhs, x0=np.asarray(x))
    assert info2.iters <= 2


def test_lgmres_bicgstabl_idrs():
    from amgcl_tpu.solver.lgmres import LGMRES
    from amgcl_tpu.solver.bicgstabl import BiCGStabL
    from amgcl_tpu.solver.idrs import IDRs
    A, rhs = convection_diffusion_2d(24, eps=0.05)
    for s in [LGMRES(maxiter=300, tol=1e-8),
              BiCGStabL(L=2, maxiter=200, tol=1e-8),
              IDRs(s=4, maxiter=300, tol=1e-8)]:
        solve = make_solver(A, AMGParams(dtype=jnp.float64,
                                         coarse_enough=200), s)
        x, info = solve(rhs)
        assert info.resid < 1e-8, type(s).__name__
        r = rhs - A.spmv(np.asarray(x))
        assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-5, \
            type(s).__name__


def test_lgmres_right_side():
    """pside='right' (the reference default, lgmres.hpp params): true
    residuals tracked, preconditioner applied once per cycle to the
    assembled correction — converges to the same quality as left."""
    from amgcl_tpu.solver.lgmres import LGMRES
    A, rhs = convection_diffusion_2d(24, eps=0.05)
    solve = make_solver(A, AMGParams(dtype=jnp.float64, coarse_enough=200),
                        LGMRES(maxiter=300, tol=1e-8, pside="right"))
    x, info = solve(rhs)
    assert info.resid < 1e-8
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-5
    # warm start in correction form
    x2, info2 = solve(rhs, x0=np.asarray(x))
    assert info2.iters <= 2
    with pytest.raises(ValueError):
        LGMRES(pside="middle").solve(None, None, jnp.zeros(4))


@pytest.mark.parametrize("pside", ["left", "right"])
def test_bicgstabl_delta_reliable_updates(pside):
    """delta > 0 enables the reliable-update scheme
    (bicgstabl.hpp:386-409): convergence quality must match delta=0, and
    the knob must be reachable from the runtime config."""
    from amgcl_tpu.solver.bicgstabl import BiCGStabL
    A, rhs = convection_diffusion_2d(24, eps=0.05)
    prm = AMGParams(dtype=jnp.float64, coarse_enough=200)
    s = make_solver(A, prm, BiCGStabL(L=2, maxiter=200, tol=1e-8,
                                      pside=pside, delta=1e-2))
    x, info = s(rhs)
    assert info.resid < 1e-8
    r = rhs - A.spmv(np.asarray(x))
    assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-5
    # warm start still correct with the flush machinery
    x2, info2 = s(rhs, x0=np.asarray(x))
    assert info2.iters <= 2


def test_runtime_config_reaches_new_knobs():
    """lgmres.pside and bicgstabl.delta are expressible in the dotted
    runtime config (VERDICT r4 item 6)."""
    from amgcl_tpu.models.runtime import make_solver_from_config
    A, rhs = poisson3d(8)
    for cfg in (
        {"solver": {"type": "lgmres", "pside": "right", "tol": 1e-8,
                    "maxiter": 300},
         "precond": {"dtype": "float64"}},
        {"solver": {"type": "bicgstabl", "delta": "1e-2", "tol": 1e-8,
                    "maxiter": 200},
         "precond": {"dtype": "float64"}},
    ):
        import warnings as _w
        with _w.catch_warnings():
            _w.simplefilter("error")       # unknown keys would warn
            solve = make_solver_from_config(A, cfg)
        x, info = solve(rhs)
        r = rhs - A.spmv(np.asarray(x))
        assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-6


def test_lgmres_small_restart_beats_gmres_stall():
    """Augmentation should not be slower than plain GMRES at equal M."""
    from amgcl_tpu.solver.lgmres import LGMRES
    A, rhs = convection_diffusion_2d(24, eps=0.02)
    prm = dict(dtype=jnp.float64, coarse_enough=100)
    _, ig = make_solver(A, AMGParams(**prm), GMRES(M=8, maxiter=600,
                                                   tol=1e-8))(rhs)
    _, il = make_solver(A, AMGParams(**prm), LGMRES(M=8, K=2, maxiter=600,
                                                    tol=1e-8))(rhs)
    assert il.resid < 1e-8
    assert il.iters <= ig.iters + 8


def test_cg_convergence_history():
    from amgcl_tpu.utils.sample_problem import poisson3d
    A, rhs = poisson3d(12)
    cg = CG(maxiter=100, tol=1e-10, record_history=True)
    solve = make_solver(A, AMGParams(dtype=jnp.float64, coarse_enough=200),
                        cg)
    x, info = solve(rhs)
    vals = np.asarray(info.history)
    assert len(vals) == info.iters
    assert np.all(np.diff(np.log10(vals[1:])) < 1)   # broadly decreasing
    assert abs(vals[-1] - info.resid) < 1e-12


def test_history_with_refinement_contract():
    """Under refinement, history covers the initial solve and its length
    matches the recorded count (not the accumulated iters)."""
    from amgcl_tpu.utils.sample_problem import poisson3d
    A, rhs = poisson3d(16)
    cg = CG(maxiter=100, tol=1e-6, record_history=True)
    solve = make_solver(A, AMGParams(dtype=jnp.float32, coarse_enough=300),
                        cg, refine=2)
    x, info = solve(rhs)
    assert info.history is not None
    assert len(info.history) <= info.iters
    assert not np.any(np.isnan(info.history))


def test_bicgstab_precond_side():
    A, rhs = convection_diffusion_2d(20, eps=0.05)
    for side in ("right", "left"):
        solve = make_solver(
            A, AMGParams(dtype=jnp.float64, coarse_enough=150),
            BiCGStab(maxiter=200, tol=1e-8, precond_side=side))
        x, info = solve(rhs)
        r = rhs - A.spmv(np.asarray(x))
        assert np.linalg.norm(r) / np.linalg.norm(rhs) < 1e-5, side
