"""Coarse-level repartitioning (parallel/repartition.py) — the
mpi::partition::parmetis/ptscotch analogue (parmetis.hpp:105-199):
permutation-based re-distribution of coarse levels that cuts halo volume
without changing the math."""

import numpy as np
import jax.numpy as jnp
import pytest

from amgcl_tpu.models.amg import AMGParams
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.parallel.mesh import make_mesh
from amgcl_tpu.parallel.dist_amg import DistAMGSolver
from amgcl_tpu.parallel.repartition import halo_fraction


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(8)


@pytest.fixture(scope="module")
def scrambled_poisson():
    """24^3 Poisson with SCRAMBLED row order: every shard couples with
    every other, and the coarse levels inherit the scrambling — the case
    the repartitioner exists for."""
    from amgcl_tpu.utils.sample_problem import poisson3d
    from amgcl_tpu.utils.adapters import permute
    A, rhs = poisson3d(24)
    rng = np.random.RandomState(0)
    perm = rng.permutation(A.nrows)
    return permute(A, perm), np.asarray(rhs)[perm]


def test_halo_fraction_measures_locality():
    from amgcl_tpu.utils.sample_problem import poisson3d
    from amgcl_tpu.utils.adapters import permute
    A, _ = poisson3d(24)
    ordered = halo_fraction(A, 8)        # banded: slab-boundary planes
    rng = np.random.RandomState(1)
    scrambled = halo_fraction(permute(A, rng.permutation(A.nrows)), 8)
    assert ordered < 1.0
    assert scrambled > 2 * ordered       # random: near-total halo


def test_repartition_cuts_halo_keeps_iterations(mesh8, scrambled_poisson):
    A, rhs = scrambled_poisson
    prm = lambda: AMGParams(dtype=jnp.float32, coarse_enough=300)
    s0 = DistAMGSolver(A, mesh8, prm(), CG(maxiter=200, tol=1e-6),
                       replicate_below=500)
    s1 = DistAMGSolver(A, mesh8, prm(), CG(maxiter=200, tol=1e-6),
                       replicate_below=500, repartition=0.2)
    assert s1.repartition_report, "no level was repartitioned"
    for (k, before, after) in s1.repartition_report:
        assert after < before
    x0, i0 = s0(rhs)
    x1, i1 = s1(rhs)
    # permutation-invariant math; f32 summation-order drift at the tol
    # boundary may cost/save one iteration
    assert abs(i1.iters - i0.iters) <= 1
    r = np.linalg.norm(rhs - A.to_scipy() @ x1) / np.linalg.norm(rhs)
    assert r < 1e-3


def test_repartition_off_by_default(mesh8):
    from amgcl_tpu.utils.sample_problem import poisson3d
    A, rhs = poisson3d(16)
    s = DistAMGSolver(A, mesh8, AMGParams(dtype=jnp.float32),
                      CG(maxiter=100, tol=1e-6))
    assert s.repartition_report == []
