"""Coarse-level repartitioning (parallel/repartition.py) — the
mpi::partition::parmetis/ptscotch analogue (parmetis.hpp:105-199):
permutation-based re-distribution of coarse levels that cuts halo volume
without changing the math."""

import numpy as np
import jax.numpy as jnp
import pytest

from amgcl_tpu.models.amg import AMGParams
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.parallel.mesh import make_mesh
from amgcl_tpu.parallel.dist_amg import DistAMGSolver
from amgcl_tpu.parallel.repartition import halo_fraction


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(8)


@pytest.fixture(scope="module")
def scrambled_poisson():
    """24^3 Poisson with SCRAMBLED row order: every shard couples with
    every other, and the coarse levels inherit the scrambling — the case
    the repartitioner exists for."""
    from amgcl_tpu.utils.sample_problem import poisson3d
    from amgcl_tpu.utils.adapters import permute
    A, rhs = poisson3d(24)
    rng = np.random.RandomState(0)
    perm = rng.permutation(A.nrows)
    return permute(A, perm), np.asarray(rhs)[perm]


def test_halo_fraction_measures_locality():
    from amgcl_tpu.utils.sample_problem import poisson3d
    from amgcl_tpu.utils.adapters import permute
    A, _ = poisson3d(24)
    ordered = halo_fraction(A, 8)        # banded: slab-boundary planes
    rng = np.random.RandomState(1)
    scrambled = halo_fraction(permute(A, rng.permutation(A.nrows)), 8)
    assert ordered < 1.0
    assert scrambled > 2 * ordered       # random: near-total halo


def test_repartition_cuts_halo_keeps_iterations(mesh8, scrambled_poisson):
    A, rhs = scrambled_poisson
    prm = lambda: AMGParams(dtype=jnp.float32, coarse_enough=300)
    s0 = DistAMGSolver(A, mesh8, prm(), CG(maxiter=200, tol=1e-6),
                       replicate_below=500)
    s1 = DistAMGSolver(A, mesh8, prm(), CG(maxiter=200, tol=1e-6),
                       replicate_below=500, repartition=0.2)
    assert s1.repartition_report, "no level was repartitioned"
    for (k, before, after) in s1.repartition_report:
        assert after < before
    x0, i0 = s0(rhs)
    x1, i1 = s1(rhs)
    # permutation-invariant math; f32 summation-order drift at the tol
    # boundary may cost/save one iteration
    assert abs(i1.iters - i0.iters) <= 1
    r = np.linalg.norm(rhs - A.to_scipy() @ x1) / np.linalg.norm(rhs)
    assert r < 1e-3


def test_repartition_off_by_default(mesh8):
    from amgcl_tpu.utils.sample_problem import poisson3d
    A, rhs = poisson3d(16)
    s = DistAMGSolver(A, mesh8, AMGParams(dtype=jnp.float32),
                      CG(maxiter=100, tol=1e-6))
    assert s.repartition_report == []


@pytest.fixture(scope="module")
def community_graph():
    """Irregular fixture the k-way partitioner exists for: 8 dense
    communities + sparse random cross-links, rows randomly scrambled.
    RCM's bandwidth objective cannot make the communities contiguous;
    graph bisection recovers them."""
    import scipy.sparse as sp
    from amgcl_tpu.ops.csr import CSR
    from amgcl_tpu.utils.adapters import permute
    rng = np.random.RandomState(7)
    k, m = 8, 256                    # 8 communities of 256 nodes
    n = k * m
    blocks = []
    for b in range(k):
        # ring + chords inside the community: sparse but well-connected
        i = np.arange(m)
        rows = np.concatenate([i, i, i])
        cols = np.concatenate([(i + 1) % m, (i + 7) % m, (i + 31) % m])
        blocks.append(sp.coo_matrix(
            (np.ones(3 * m), (rows, cols)), shape=(m, m)))
    G = sp.block_diag(blocks).tolil()
    # sparse cross-community links (~2% of edges)
    for _ in range(n // 8):
        u, v = rng.randint(0, n, 2)
        G[u, v] = 1.0
    G = G.tocsr()
    G = G + G.T
    L = sp.diags(np.asarray(G.sum(axis=1)).ravel() + 0.1) - G
    A = CSR.from_scipy(L.tocsr())
    perm = rng.permutation(n)
    return permute(A, perm)


def test_kway_beats_rcm_on_communities(community_graph):
    """On a scrambled community graph the multilevel k-way partitioner
    must cut the halo where RCM cannot (VERDICT r4 item 5)."""
    from amgcl_tpu.parallel.partition import partition_permutation
    from amgcl_tpu.parallel.repartition import locality_permutation
    from amgcl_tpu.utils.adapters import permute
    A = community_graph
    nd = 8
    before = halo_fraction(A, nd)
    rcm = halo_fraction(permute(A, locality_permutation(A)), nd)
    kway = halo_fraction(permute(A, partition_permutation(A, nd)), nd)
    assert kway < before
    assert kway < 0.5 * rcm, (before, rcm, kway)


def test_kway_partition_exact_blocks_and_determinism(community_graph):
    """The mesh layout needs exact row-block sizes; the permutation must
    be a permutation and reproducible run to run."""
    from amgcl_tpu.parallel.partition import partition_permutation
    A = community_graph
    p1 = partition_permutation(A, 8)
    p2 = partition_permutation(A, 8)
    np.testing.assert_array_equal(p1, p2)
    assert len(np.unique(p1)) == A.nrows
    # odd shard counts and non-dividing sizes still yield exact blocks
    p3 = partition_permutation(A, 3)
    assert len(np.unique(p3)) == A.nrows


def test_repartition_uses_kway_when_it_wins(mesh8, community_graph):
    """DistAMGSolver(repartition=...) must pick up the k-way win through
    best_permutation and keep the solve correct."""
    A = community_graph
    rhs = np.ones(A.nrows)
    # coarse level (~170 rows) must stay SHARDED to be repartition-
    # eligible, so the replicate threshold sits below it
    s = DistAMGSolver(A, mesh8,
                      AMGParams(dtype=jnp.float32, coarse_enough=50),
                      CG(maxiter=300, tol=1e-6),
                      replicate_below=100, repartition=0.05)
    assert s.repartition_report, "no level was repartitioned"
    for (k, before, after) in s.repartition_report:
        assert after < before
    x, info = s(rhs)
    r = np.linalg.norm(rhs - A.to_scipy() @ x) / np.linalg.norm(rhs)
    assert r < 1e-3
