"""Memory observatory (ISSUE 18): measured-vs-ledger joins per device
format, ownership attribution through eviction, the leak-cycle
selftest and its negative injection, RESOURCE_EXHAUSTED classification
into the typed AllocationError taxonomy, OOM flight forensics
(timeline + top-owner table in the bundle manifest), the doctor
``memory=`` fold, measured farm headroom, and the live gauges."""

import glob
import json
import os

import numpy as np
import jax.numpy as jnp
import pytest

from amgcl_tpu import faults
from amgcl_tpu.faults import inject
from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.models.make_solver import make_solver
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.telemetry import memwatch
from amgcl_tpu.telemetry import flight
from amgcl_tpu.telemetry.health import diagnose
from amgcl_tpu.utils.sample_problem import poisson3d

KNOBS = ("AMGCL_TPU_MEMWATCH", "AMGCL_TPU_MEMWATCH_INTERVAL_MS",
         "AMGCL_TPU_MEMWATCH_TIMELINE", "AMGCL_TPU_MEMWATCH_TOL",
         "AMGCL_TPU_MEMWATCH_LEAK_BYTES", "AMGCL_TPU_FARM_HEADROOM",
         "AMGCL_TPU_FAULT_PLAN", "AMGCL_TPU_FLIGHT_DIR")


@pytest.fixture(autouse=True)
def _fresh_memwatch():
    saved = {k: os.environ.get(k) for k in KNOBS}
    memwatch._reset_for_tests()
    flight._reset_for_tests()
    inject._reset_for_tests()
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    memwatch._reset_for_tests()
    flight._reset_for_tests()
    inject._reset_for_tests()


def _amg(fmt="auto", n=8, **kw):
    A, _ = poisson3d(n)
    kw.setdefault("coarse_enough", 20)
    kw.setdefault("max_levels", 3)
    return AMG(A, AMGParams(dtype=jnp.float32, matrix_format=fmt, **kw))


# ---------------------------------------------------------------------------
# measured-vs-ledger join, per device format
# ---------------------------------------------------------------------------

_EXPECT = {"dia": "DiaMatrix", "ell": "EllMatrix",
           "dense": "DenseMatrix", "well": "WindowedEllMatrix"}


@pytest.mark.parametrize("fmt", sorted(_EXPECT))
def test_join_within_tolerance_per_format(fmt):
    """AMG.bytes() (the analytic ledger) vs the live-array measurement
    agrees within the declared tolerance for every device format —
    the number every admission/eviction decision trusts."""
    amg = _amg(fmt)
    assert type(amg.hierarchy.levels[0].A).__name__ == _EXPECT[fmt]
    tol = memwatch.declared_tolerance()
    measured = memwatch.measured_tree_bytes(amg.hierarchy)
    assert measured > 0
    assert abs(measured - amg.bytes()) <= tol * amg.bytes()
    rep = amg.memory_report()
    assert rep["provenance"] == "measured" and rep["resident"]
    assert len(rep["levels"]) >= 2
    assert abs(rep["drift_ratio"] - 1.0) <= tol
    for row in rep["levels"]:
        assert abs(row["drift_ratio"] - 1.0) <= tol, row
        assert row["slots"].get("A", 0) > 0
    # a clean join raises no doctor findings (just the healthy row)
    assert [f for f in diagnose(None, memory=rep)
            if f["code"] != "healthy"] == []


def test_release_device_zeroes_measured_owner():
    amg = _amg("dia")
    name = memwatch.register_owner("hierarchy", amg)
    assert name is not None
    row = next(r for r in memwatch.owner_table() if r["owner"] == name)
    assert row["bytes_measured"] > 0 and row["drift_ratio"] == 1.0
    amg.release_device()
    assert memwatch.measured_tree_bytes(amg.hierarchy) == 0
    row = next(r for r in memwatch.owner_table() if r["owner"] == name)
    assert row["bytes_measured"] == 0
    rep = amg.memory_report()
    assert rep["resident"] is False and rep["total_measured"] == 0
    # the owner row dies with its object (weakref registry)
    del amg, row
    assert all(r["owner"] != name for r in memwatch.owner_table())


def test_owner_table_census_remainder():
    """On the CPU census the table closes: attributed rows plus the
    ``unattributed`` remainder account for every live byte."""
    amg = _amg("dia")
    memwatch.register_owner("hierarchy", amg)
    sample = memwatch.device_sample()
    assert sample["source"] == "census"
    rows = memwatch.owner_table(sample)
    un = next(r for r in rows if r["owner"] == "unattributed")
    attributed = sum(r["bytes_measured"] for r in rows
                     if r["owner"] != "unattributed")
    assert attributed + un["bytes_measured"] >= sample["bytes_in_use"]


# ---------------------------------------------------------------------------
# timeline, kill switch, Perfetto export
# ---------------------------------------------------------------------------

def test_timeline_bounded_and_kill_switch(monkeypatch):
    monkeypatch.setenv("AMGCL_TPU_MEMWATCH_TIMELINE", "16")
    for i in range(40):
        assert memwatch.snapshot("unit.test", i=i) is not None
    rows = memwatch.timeline()
    assert len(rows) == 16 and rows[-1]["i"] == 39
    trace = memwatch.to_chrome_trace()
    phases = {e["ph"] for e in trace["traceEvents"]}
    assert {"C", "i", "M"} <= phases
    monkeypatch.setenv("AMGCL_TPU_MEMWATCH", "0")
    assert memwatch.snapshot("unit.test") is None
    assert memwatch.register_owner("hierarchy", object()) is None


def test_sampler_thread_fills_timeline():
    assert memwatch.start_sampler(0.005)
    try:
        import time
        deadline = time.perf_counter() + 2.0
        while time.perf_counter() < deadline:
            if any(r["phase"] == "sampler" for r in memwatch.timeline()):
                break
            time.sleep(0.01)
    finally:
        memwatch.stop_sampler()
    ticks = [r for r in memwatch.timeline() if r["phase"] == "sampler"]
    assert ticks and ticks[0]["bytes_in_use"] is not None


# ---------------------------------------------------------------------------
# doctor findings (telemetry.diagnose(memory=...))
# ---------------------------------------------------------------------------

def test_memory_findings_drift_leak_unattributed():
    codes = [f["code"] for f in memwatch.memory_findings(
        {"drift_ratio": 2.0, "tolerance": 0.25, "leaked_bytes": 4096,
         "owners": [{"owner": "unattributed", "bytes_measured": 900},
                    {"owner": "hierarchy:1", "bytes_measured": 100}]})]
    assert codes == ["mem_drift", "mem_leak", "mem_unattributed"]
    assert memwatch.memory_findings({"drift_ratio": 1.01,
                                     "leaked_bytes": 0}) == []
    sev = {f["code"]: f["severity"]
           for f in diagnose(None, memory={"drift_ratio": 1.0,
                                           "leaked_bytes": 1})}
    assert sev["mem_leak"] == "critical"


# ---------------------------------------------------------------------------
# RESOURCE_EXHAUSTED classification -> typed AllocationError
# ---------------------------------------------------------------------------

def test_is_resource_exhausted_classification():
    class XlaRuntimeError(Exception):
        pass

    assert faults.is_resource_exhausted(
        XlaRuntimeError("RESOURCE_EXHAUSTED: Out of memory ..."))
    assert faults.is_resource_exhausted(
        XlaRuntimeError("Failed to allocate 12884901888 bytes"))
    assert faults.is_resource_exhausted(
        RuntimeError("RESOURCE_EXHAUSTED while compiling"))
    assert not faults.is_resource_exhausted(
        XlaRuntimeError("INVALID_ARGUMENT: shape mismatch"))
    assert not faults.is_resource_exhausted(ValueError("nope"))
    assert not faults.is_resource_exhausted(None)
    # typed faults never re-classify (no double wrapping)
    assert not faults.is_resource_exhausted(
        faults.AllocationError("RESOURCE_EXHAUSTED"))
    # the taxonomy: admission refusals ARE allocation errors
    assert issubclass(faults.AdmissionError, faults.AllocationError)
    assert issubclass(faults.AllocationError, faults.FaultError)


def test_dispatch_oom_raises_typed_with_forensics(tmp_path, monkeypatch):
    """A backend RESOURCE_EXHAUSTED escaping the compiled entry comes
    back as faults.AllocationError, and the flight bundle embeds the
    memory timeline + top-owner table."""
    monkeypatch.setenv("AMGCL_TPU_FLIGHT_DIR", str(tmp_path))
    flight._reset_for_tests()
    A, rhs = poisson3d(8)
    b = make_solver(A, AMGParams(dtype=jnp.float32, coarse_enough=200),
                    CG(maxiter=50, tol=1e-6))
    b(rhs.astype(np.float32))        # warm: populates b._compiled

    class XlaRuntimeError(Exception):
        pass

    def boom(*a, **kw):
        raise XlaRuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory allocating 8589934592 "
            "bytes")

    monkeypatch.setattr(b, "_compiled", boom)
    with pytest.raises(faults.AllocationError) as ei:
        b(rhs.astype(np.float32))
    assert "measured bytes" in str(ei.value)
    assert isinstance(ei.value.__cause__, XlaRuntimeError)
    mans = glob.glob(str(tmp_path / "*" / "manifest.json"))
    assert mans, "no flight bundle dumped"
    man = json.load(open(mans[0]))
    assert man["reason"] == "allocation_failure"
    tags = man["tags"]
    assert tags["seam"] == "solve.dispatch"
    assert tags["memory_owners"] and tags["memory_timeline"]
    assert tags["memory_timeline"][-1]["phase"] == "allocation_failure"
    # a non-OOM failure still raises untyped (no blanket rewrap)
    monkeypatch.setattr(
        b, "_compiled",
        lambda *a, **kw: (_ for _ in ()).throw(ValueError("bad")))
    with pytest.raises(ValueError):
        b(rhs.astype(np.float32))


def test_farm_admission_refusal_typed_with_forensics(tmp_path,
                                                     monkeypatch):
    """The injected ``alloc.farm`` refusal surfaces as the typed
    AllocationError (AdmissionError leg) and trips the same OOM
    forensics bundle."""
    from amgcl_tpu.serve.farm import SolverFarm
    monkeypatch.setenv("AMGCL_TPU_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("AMGCL_TPU_FAULT_PLAN", json.dumps(
        [{"site": "alloc.farm", "count": 1}]))
    flight._reset_for_tests()
    inject._reset_for_tests()
    A, _ = poisson3d(8)
    farm = SolverFarm(max_bytes=1, metrics_port=-1)
    try:
        with pytest.raises(faults.AllocationError):
            farm.register("t0", A,
                          precond=AMGParams(dtype=jnp.float32,
                                            coarse_enough=200))
    finally:
        farm.close()
    mans = [m for m in glob.glob(str(tmp_path / "*" / "manifest.json"))
            if json.load(open(m))["reason"] == "allocation_failure"]
    assert mans, "no allocation_failure bundle dumped"
    tags = json.load(open(mans[0]))["tags"]
    assert tags["seam"] == "farm.register" and tags["tenant"] == "t0"
    assert "pool_used" in tags and "pool_total" in tags
    assert isinstance(tags["memory_timeline"], list)
    assert isinstance(tags["memory_owners"], list)


# ---------------------------------------------------------------------------
# per-solve measured resources + measured farm headroom
# ---------------------------------------------------------------------------

def test_solve_report_carries_measured_bytes():
    A, rhs = poisson3d(8)
    b = make_solver(A, AMGParams(dtype=jnp.float32, coarse_enough=200),
                    CG(maxiter=50, tol=1e-6))
    _, rep = b(rhs.astype(np.float32))
    bm = rep.resources["bytes_measured"]
    assert bm["provenance"] == "measured"
    assert bm["hierarchy"] > 0 and bm["total"] >= bm["hierarchy"]
    assert bm["device"]["source"] == "census"
    assert any(r["phase"] == "solve" for r in memwatch.timeline())


def test_farm_headroom_measured_mode(monkeypatch):
    """AMGCL_TPU_FARM_HEADROOM=measured charges max(measured, model)
    so a drifting model can never silently over-admit."""
    from amgcl_tpu.serve.farm import SolverFarm
    monkeypatch.setenv("AMGCL_TPU_FARM_HEADROOM", "measured")
    A, _ = poisson3d(8)
    farm = SolverFarm(max_bytes=0, metrics_port=-1)
    try:
        farm.register("t0", A, precond=AMGParams(dtype=jnp.float32,
                                                 coarse_enough=20,
                                                 max_levels=3))
        assert farm._headroom_mode == "measured"
        ten = farm.tenants["t0"]
        hint = farm._bytes_hint[ten.entry.uid]
        measured = memwatch.measured_tree_bytes(
            ten.entry.obj.precond.hierarchy)
        model = ten.entry.obj.precond.bytes()
        assert hint >= measured and hint >= min(measured, model)
    finally:
        farm.close()


# ---------------------------------------------------------------------------
# the leak-cycle selftest (the bench --check record) + live gauges
# ---------------------------------------------------------------------------

def test_selftest_clean_and_leak_injection():
    rec = memwatch.selftest(cycles=1)
    assert rec["ok"], rec
    assert rec["leaked_bytes"] == 0
    assert abs(rec["drift_ratio"] - 1.0) <= rec["tolerance"]
    assert {c["check"] for c in rec["checks"]} == {
        "join_within_tolerance", "evict_zeroes_owner",
        "cycle_returns_to_baseline"}
    json.dumps(rec)                  # JSONL-sink clean
    # the negative injection: a deliberately pinned buffer per cycle
    # must flip the record (what proves the bench gate can trip)
    memwatch._reset_for_tests()
    bad = memwatch.selftest(cycles=1, leak_bytes=2_000_000)
    assert not bad["ok"] and bad["leaked_bytes"] >= 2_000_000
    assert any(f["code"] == "mem_leak" for f in bad["findings"])


def test_publish_memwatch_gauges():
    from amgcl_tpu.telemetry import live
    amg = _amg("dia")
    memwatch.register_owner("hierarchy", amg, name="hierarchy:test")
    reg = live.LiveRegistry()
    live.publish_memwatch_gauges(reg)
    assert reg.get("memwatch_bytes_in_use") > 0
    assert reg.get("memwatch_owner_bytes", owner="hierarchy:test") > 0
    assert reg.get("memwatch_unattributed_bytes") is not None
