"""Roofline attribution + compile watch (ISSUE 4): measured-vs-model
stage join, device-peak detection with the CPU measured fallback, the
per-stage XLA byte cross-check, the recompile counter (repeat shape = 0
new compiles, changed shape = exactly 1), retrace findings, the Perfetto
counter track, and the profiler truncation satellite."""

import json
import os
import subprocess
import sys

import numpy as np
import jax.numpy as jnp
import pytest

from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.models.make_solver import make_solver
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.telemetry import SolveReport
from amgcl_tpu.telemetry import roofline as rl
from amgcl_tpu.telemetry import compile_watch as cw
from amgcl_tpu.telemetry.health import diagnose
from amgcl_tpu.utils.profiler import Profiler
from amgcl_tpu.utils.sample_problem import poisson3d

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def amg():
    A, _ = poisson3d(12)
    return AMG(A, AMGParams(dtype=jnp.float32, coarse_enough=200))


# ---------------------------------------------------------------------------
# device peaks
# ---------------------------------------------------------------------------

def test_device_peaks_measured_fallback():
    """On CPU the peaks come from a real stream/matmul measurement, not a
    TPU table — roofline fractions in CI compare against this host."""
    pk = rl.device_peaks()
    assert pk["gbps"] and pk["gbps"] > 0
    assert pk["flops"] and pk["flops"] > 0
    if pk["platform"] == "cpu":
        assert pk["source"]["gbps"] in ("measured-stream", "env")
    json.dumps(pk)


def test_device_peaks_env_override(monkeypatch):
    monkeypatch.setenv("AMGCL_TPU_PEAK_GBPS", "123.5")
    monkeypatch.setenv("AMGCL_TPU_PEAK_FLOPS", "1e12")
    pk = rl.device_peaks(refresh=True)
    try:
        assert pk["gbps"] == 123.5 and pk["flops"] == 1e12
        assert pk["source"] == {"gbps": "env", "flops": "env"}
    finally:
        monkeypatch.delenv("AMGCL_TPU_PEAK_GBPS")
        monkeypatch.delenv("AMGCL_TPU_PEAK_FLOPS")
        rl.device_peaks(refresh=True)      # drop the override from cache


# ---------------------------------------------------------------------------
# measured-vs-model join
# ---------------------------------------------------------------------------

def test_roofline_join(amg):
    rf = amg.roofline(reps=1)
    stages = rf["stages"]
    assert stages, "no stages joined"
    names = {(r["level"], r["stage"]) for r in stages}
    assert (0, "pre_smooth") in names and (0, "restrict") in names
    assert any(r["stage"] == "coarse_solve" for r in stages)
    for r in stages:
        assert r["t_s"] > 0 and r["model_bytes"] > 0
        assert r["gbps"] > 0 and r["bound"] in ("memory", "compute")
        assert r["frac_peak"] is None or r["frac_peak"] > 0
    assert rf["total"]["gbps"] > 0 and rf["cycle_s"] > 0
    # cached per build, measurement profiler rides along
    assert amg.roofline() is rf and rf["_prof"] is not None
    json.dumps({k: v for k, v in rf.items() if not k.startswith("_")})


def test_roofline_counter_track(amg):
    """The achieved-GB/s Perfetto counter track: one pair of 'C' events
    per recorded stage occurrence."""
    rf = amg.roofline()
    trace = rf["_prof"].to_chrome_trace(counters=rl.counter_map(rf))
    counters = [e for e in trace["traceEvents"] if e["ph"] == "C"]
    assert counters and all(e["name"] == "achieved_gbps"
                            for e in counters)
    assert any(e["args"]["achieved_gbps"] > 0 for e in counters)


def test_xla_stage_check(amg):
    """Per-stage model bytes vs XLA cost analysis: the stage-accurate
    stages (zero-guess scaled-residual pre-smooth, dense levels, the
    dense coarse solve) agree within the ~5% ledger tolerance; gather/
    roll-paying DIA lowerings may exceed the streaming floor but are
    reported, not hidden."""
    rows = rl.xla_stage_check(amg.hierarchy)
    if not rows:
        pytest.skip("backend exposes no cost analysis")
    by = {(r["level"], r["stage"]): r for r in rows}
    assert by[(0, "pre_smooth")]["within_tol"]
    coarse = [r for r in rows if r["stage"] == "coarse_solve"]
    assert coarse and coarse[0]["within_tol"]
    assert all(r["ratio"] > 0 for r in rows)
    # the model is a floor: XLA never accesses fewer bytes than ~model
    assert all(r["ratio"] < 1.1 for r in rows)


def test_solve_roofline_classification():
    peaks = {"gbps": 10.0, "flops": 1e12}     # balance = 100 F/B
    mem = rl.solve_roofline({"flops": 10 ** 6, "bytes": 10 ** 6}, 10, 1.0,
                            peaks=peaks)
    assert mem["bound"] == "memory" and mem["frac_hbm_peak"] > 0
    comp = rl.solve_roofline({"flops": 10 ** 9, "bytes": 10 ** 3}, 10, 1.0,
                             peaks=peaks)
    assert comp["bound"] == "compute"
    assert rl.solve_roofline({"flops": 0, "bytes": 0}, 10, 1.0) is None


def test_report_carries_solve_roofline():
    A, rhs = poisson3d(10)
    s = make_solver(A, AMGParams(dtype=jnp.float32, coarse_enough=150),
                    CG(maxiter=60, tol=1e-6))
    _, r1 = s(rhs)
    _, r2 = s(rhs)
    rf = r2.resources["roofline"]
    assert rf["gbps"] > 0 and rf["bound"] in ("memory", "compute")
    assert "first_call" not in rf       # steady-state call overwrote it
    rec = json.loads(r2.to_json())
    assert rec["resources"]["roofline"]["gbps"] == rf["gbps"]


def test_format_roofline_renders(amg):
    rf = amg.roofline()
    txt = rl.format_roofline(rf, rl.xla_stage_check(amg.hierarchy))
    assert "Roofline" in txt and "pre_smooth" in txt
    assert "GB/s" in txt


# ---------------------------------------------------------------------------
# compile watch
# ---------------------------------------------------------------------------

def test_watched_jit_counts_and_retrace():
    @cw.watched_jit(name="t_roof.k", static_argnames=("n",))
    def k(x, n):
        return x * n

    k(jnp.ones(4), n=2)
    k(jnp.ones(4), n=2)
    k(jnp.ones(8), n=2)
    s = cw.snapshot("t_roof.k")
    assert s["calls"] == 3 and s["traces"] == 2
    assert s["cache_hits"] == 1 and s["retraces"] == 1
    assert s["signatures"] == 2
    # monitoring attribution (when the jax API exposes it)
    if s["backend_compiles"]:
        assert s["compile_s"] > 0
    fs = cw.findings(cw.snapshot())
    assert any(f["code"] == "retrace" and "t_roof.k" in f["message"]
               for f in fs)


def test_recompile_counter_same_and_changed_shape():
    """Acceptance contract: a repeated-shape solve reports ZERO new
    compiles, a changed-shape solve exactly ONE."""
    # the watch is process-global and other tests solve too — reset so
    # the retrace/warmup semantics here are deterministic
    cw.global_watch().reset()
    A, rhs = poisson3d(9)
    s = make_solver(A, AMGParams(dtype=jnp.float32, coarse_enough=150),
                    CG(maxiter=50, tol=1e-6))
    _, r1 = s(rhs)
    assert r1.compile["new_traces"] == 1
    _, r2 = s(rhs)                       # same shape: cache hit
    assert r2.compile["new_traces"] == 0
    assert r2.compile["new_backend_compiles"] == 0
    assert r2.compile["new_cache_hits"] == 1
    A2, rhs2 = poisson3d(10)             # changed shape: one new compile
    s2 = make_solver(A2, AMGParams(dtype=jnp.float32, coarse_enough=150),
                     CG(maxiter=50, tol=1e-6))
    _, r3 = s2(rhs2)
    assert r3.compile["new_traces"] == 1
    assert r3.compile["new_retraces"] == 1    # new sig after warmup
    json.dumps(r3.compile)


def test_compile_watch_disabled(monkeypatch):
    monkeypatch.setenv("AMGCL_TPU_COMPILE_WATCH", "0")
    f = cw.watched_jit(lambda x: x + 1, name="t_roof.off")
    assert not hasattr(f, "_watched_name")
    f(jnp.ones(3))
    assert cw.snapshot("t_roof.off")["calls"] == 0
    A, rhs = poisson3d(8)
    s = make_solver(A, AMGParams(dtype=jnp.float32, coarse_enough=100),
                    CG(maxiter=40, tol=1e-6))
    _, rep = s(rhs)
    assert rep.compile is None


def test_watched_jit_forwards_jit_surface():
    f = cw.watched_jit(lambda x: x * 2, name="t_roof.fw")
    f(jnp.ones(3))
    f.clear_cache()                       # the jit API tests rely on
    f(jnp.ones(3))
    assert cw.snapshot("t_roof.fw")["traces"] == 2


def test_diagnose_efficiency_findings(amg):
    rep = SolveReport(10, 1e-8, solver="CG",
                      wall_time_s=0.1, extra={})
    roof = {"bottlenecks": [{"severity": "warning",
                             "code": "roofline_stage",
                             "message": "level 2 restrict at 9% of HBM "
                                        "peak", "suggestion": "x"}]}
    comp = {"retrace_events": [{"fn": "f", "sig": "f32[8]",
                                "prior_sigs": 1}],
            "totals": {"compile_s": 0.09}}
    fs = diagnose(rep, roofline=roof, compile_stats=comp)
    codes = {f["code"] for f in fs}
    assert "roofline_stage" in codes and "retrace" in codes
    # PER-CALL compile time dominating a non-first call is a finding;
    # process-cumulative totals alone must NOT trip it (a warm solve
    # after one normal first-call compile is healthy)
    rep2 = SolveReport(10, 1e-8, solver="CG", wall_time_s=0.1)
    comp2 = {"retrace_events": [], "new_compile_s": 0.09}
    assert any(f["code"] == "compile_dominates"
               for f in diagnose(rep2, compile_stats=comp2))
    cumulative = {"retrace_events": [], "totals": {"compile_s": 9.0}}
    assert not any(f["code"] == "compile_dominates"
                   for f in diagnose(rep2, compile_stats=cumulative))


# ---------------------------------------------------------------------------
# profiler satellites: truncation visibility + counter support
# ---------------------------------------------------------------------------

def test_profiler_event_cap_is_loud():
    p = Profiler()
    p.MAX_EVENTS = 3                       # instance override
    with pytest.warns(UserWarning, match="event cap"):
        for _ in range(5):
            with p.scope("s"):
                pass
    assert p._events_dropped == 2
    trace = p.to_chrome_trace()
    assert trace["otherData"]["events_dropped"] == 2
    drop = [e for e in trace["traceEvents"] if e["ph"] == "i"]
    assert drop and drop[0]["args"]["dropped"] == 2
    # aggregate totals keep counting past the cap
    assert p.root.children["s"].count == 5


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------

def test_cli_roofline_smoke():
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               AMGCL_TPU_ROOFLINE_REPS="1")
    r = subprocess.run(
        [sys.executable, "-m", "amgcl_tpu.cli", "-n", "12",
         "-p", "solver.type=cg", "--roofline"],
        capture_output=True, text=True, timeout=420, cwd=_REPO, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "Roofline" in r.stdout
    assert "xla-check" in r.stdout        # per-stage model-vs-XLA bytes
    assert "GB/s" in r.stdout
